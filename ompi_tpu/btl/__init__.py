"""BTL — byte transfer layer (host transports).

Reference: opal/mca/btl/ (btl.h:1172-1240 module struct). Components here:
``self`` (loopback, reference btl/self), ``sm`` (shared-memory rings,
reference btl/sm FIFO + fast-box), ``tcp`` (reference btl/tcp). Each BTL
delivers framed active-message bytes to the PML callback, reliable and
ordered per (sender, receiver) direction.
"""

from ompi_tpu.btl.base import Btl, set_recv_callback, framework  # noqa: F401
