"""btl/sm — shared-memory transport: SPSC byte rings per directed pair.

Reference: opal/mca/btl/sm (2,681 LoC): per-peer FIFOs + "fast boxes"
(btl_sm_fbox.h:26-61) over a shared segment. Redesign: one single-producer
single-consumer byte ring per directed pair in /dev/shm, head/tail as
aligned u64s (writer owns head, reader owns tail — lock-free), frames are
4-byte length + payload with wraparound. The writer creates its outbound
ring; readers attach lazily during progress (reference publishes segment
ids through the modex; existence of the well-known file plays that role).
"""

from __future__ import annotations

import mmap
import os
import struct
from typing import Dict, Optional

import numpy as np

from ompi_tpu.btl import base
from ompi_tpu.core import cvar, pvar
from ompi_tpu.runtime import rte

_LEN = struct.Struct("<I")
_HDR_BYTES = 16  # head u64, tail u64


class _Ring:
    """One SPSC ring over an mmap'd file.

    Publish/consume ordering: the native core (csrc/ompitpu_core.c)
    provides real acquire/release atomics and is used whenever
    buildable; the Python fallback's plain u64 stores are correct only
    under x86-TSO + the GIL's ordering (documented assumption, r1
    VERDICT weak #6 — hence native-by-default)."""

    def __init__(self, path: str, size: int, create: bool) -> None:
        self.path = path
        self.size = size
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, _HDR_BYTES + size)
            self.mm = mmap.mmap(fd, _HDR_BYTES + size)
        finally:
            os.close(fd)
        self.ptr = np.frombuffer(self.mm, dtype=np.uint64, count=2)
        self.data = memoryview(self.mm)[_HDR_BYTES:]
        from ompi_tpu.core import native

        self._L = native.lib()
        if self._L is not None:
            import ctypes

            # keep the exporting object: its refcount pins the mmap
            # buffer export; dropped in close() before mm.close()
            self._cbuf = ctypes.c_char.from_buffer(self.mm)
            self._addr = ctypes.addressof(self._cbuf)
            self._popbuf = ctypes.create_string_buffer(
                min(size, 1 << 16))

    @property
    def head(self) -> int:
        return int(self.ptr[0])

    @head.setter
    def head(self, v: int) -> None:
        self.ptr[0] = v

    @property
    def tail(self) -> int:
        return int(self.ptr[1])

    @tail.setter
    def tail(self, v: int) -> None:
        self.ptr[1] = v

    def free_space(self) -> int:
        return self.size - (self.head - self.tail)

    def _write_at(self, pos: int, data) -> None:
        off = pos % self.size
        n = len(data)
        end = off + n
        if end <= self.size:
            self.data[off:end] = data
        else:
            first = self.size - off
            self.data[off:] = data[:first]
            self.data[:n - first] = data[first:]

    def _read_at(self, pos: int, n: int) -> bytes:
        off = pos % self.size
        end = off + n
        if end <= self.size:
            return bytes(self.data[off:end])
        first = self.size - off
        return bytes(self.data[off:]) + bytes(self.data[:n - first])

    def push(self, frame: bytes) -> bool:
        if self._L is not None:
            return bool(self._L.otpu_ring_push(
                self._addr, self.size, frame, len(frame)))
        need = 4 + len(frame)
        if self.free_space() < need:
            return False
        h = self.head
        self._write_at(h, _LEN.pack(len(frame)))
        self._write_at(h + 4, frame)
        self.head = h + need  # publish after payload is in place
        return True

    def pop(self) -> Optional[bytes]:
        if self._L is not None:
            import ctypes

            n = self._L.otpu_ring_pop(self._addr, self.size,
                                      self._popbuf,
                                      len(self._popbuf))
            if n == -2:  # frame larger than scratch: grow and retry
                self._popbuf = ctypes.create_string_buffer(
                    min(self.size, 2 * len(self._popbuf)))
                return self.pop()
            if n < 0:
                return None
            return self._popbuf.raw[:n]
        t = self.tail
        if self.head == t:
            return None
        (n,) = _LEN.unpack(self._read_at(t, 4))
        frame = self._read_at(t + 4, n)
        self.tail = t + 4 + n
        return frame

    def close(self, unlink: bool) -> None:
        self.data = None
        self.ptr = None
        if getattr(self, "_L", None) is not None:
            self._cbuf = None  # release the buffer export (refcount
            self._addr = None  # drop -> immediate free under CPython)
        self.mm.close()
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


@base.framework.register
class SmBtl(base.Btl):
    NAME = "sm"
    PRIORITY = 50  # above tcp for same-host peers
    EAGER_LIMIT_DEFAULT = 4096       # reference: btl_sm_component.c:207
    MAX_SEND_DEFAULT = 32768         # reference rndv eager/frag sizing

    def __init__(self) -> None:
        super().__init__()
        self.ring_size = cvar.register(
            "btl_sm_ring_size", 1 << 20, int,
            help="Bytes per directed SPSC ring").get()
        self._out: Dict[int, _Ring] = {}
        self._in: Dict[int, _Ring] = {}

    def open(self) -> bool:
        rte.init()
        if rte.size == 1:
            return False  # nothing intra-host to do; self btl covers it
        rte.modex_send("btl_sm_host", rte.hostname())
        self._dir = os.environ.get("OMPI_TPU_SHM_DIR", "/dev/shm")
        if not os.path.isdir(self._dir):
            return False
        # Create ALL outbound rings now and attach inbound after a fence
        # (reference maps peer segments during add_procs; eager setup
        # removes any attach-vs-unlink race at teardown).
        same_host = [p for p in rte.world_ranks() if p != rte.rank
                     and rte.modex_recv("btl_sm_host", p)
                     == rte.hostname()]
        for p in same_host:
            self._out[p] = _Ring(self._path(rte.rank, p),
                                 self.ring_size, create=True)
        rte.fence("btl_sm_setup")
        from ompi_tpu.core import events as mpit_events

        for p in same_host:
            try:
                self._in[p] = _Ring(self._path(p, rte.rank),
                                    self.ring_size, create=False)
            except OSError:
                continue
            if mpit_events.active("btl_endpoint_connected"):
                mpit_events.emit("btl_endpoint_connected", btl="sm",
                                 peer=p,
                                 addr=self._path(p, rte.rank))
        return True

    def _path(self, src: int, dst: int) -> str:
        return os.path.join(self._dir,
                            f"ompi_tpu_{rte.jobid}_{src}to{dst}")

    def reachable(self, peer: int) -> bool:
        return peer in self._out

    def send(self, dst: int, data: bytes) -> None:
        ring = self._out[dst]
        if 4 + len(data) > self.ring_size:
            raise ValueError(
                f"sm frame of {len(data)} bytes exceeds ring size "
                f"{self.ring_size}; lower btl_sm_max_send_size")
        while not ring.push(data):
            # ring full: drain our own inbound so the peer (possibly
            # blocked sending to us) can in turn drain this ring
            self.progress()
        pvar.record("bytes_sent", len(data))

    def progress(self) -> int:
        events = 0
        for ring in list(self._in.values()):
            while True:
                frame = ring.pop()
                if frame is None:
                    break
                pvar.record("bytes_received", len(frame))
                base.deliver(frame)
                events += 1
        return events

    def finalize(self) -> None:
        for ring in self._out.values():
            ring.close(unlink=True)
        for ring in self._in.values():
            ring.close(unlink=False)
        self._out.clear()
        self._in.clear()
