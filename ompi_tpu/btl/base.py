"""BTL base interface and the BML endpoint multiplexer.

Reference: opal/mca/btl/btl.h (module interface) + ompi/mca/bml/r2 (the
BTL multiplexer choosing, per peer, which BTL to use by exclusivity/
priority). The PML registers one receive callback
(mca_bml_base_register AM callbacks, pml_ob1.c:478-527).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ompi_tpu.core import cvar, progress, registry
from ompi_tpu.runtime import rte
from ompi_tpu.trace import recorder as _trace

framework = registry.framework("btl")

# the PML's AM callback: fn(data: bytes) — framing is PML-private
_recv_cb: Optional[Callable[[bytes], None]] = None


def set_recv_callback(cb: Callable[[bytes], None]) -> None:
    global _recv_cb
    _recv_cb = cb


def deliver(data: bytes) -> None:
    if _recv_cb is not None:
        _recv_cb(data)


class Btl(registry.Component):
    """One transport. Reliable ordered delivery per directed pair."""

    #: max payload the PML may push in one eager send (btl_eager_limit)
    EAGER_LIMIT_DEFAULT = 65536
    #: max bytes per rndv fragment (btl_max_send_size)
    MAX_SEND_DEFAULT = 131072

    def __init__(self) -> None:
        self.eager_limit = cvar.register(
            f"btl_{self.NAME}_eager_limit", self.EAGER_LIMIT_DEFAULT, int,
            help=f"Max eager message size for btl/{self.NAME} "
                 "(reference: btl_eager_limit)").get()
        self.max_send = cvar.register(
            f"btl_{self.NAME}_max_send_size", self.MAX_SEND_DEFAULT, int,
            help="Max rndv fragment size").get()

    def reachable(self, peer: int) -> bool:
        raise NotImplementedError

    def send(self, dst: int, data: bytes) -> None:
        """Reliable ordered AM send of one framed message."""
        raise NotImplementedError

    def progress(self) -> int:
        return 0

    def finalize(self) -> None:
        pass


class Bml:
    """Endpoint table: picks one BTL per peer (reference: bml/r2).

    Selection: highest-priority reachable BTL. btl/self for self, sm for
    same-host peers, tcp otherwise; OMPI_TPU_BTL can restrict the set.
    """

    def __init__(self) -> None:
        self.btls: List[Btl] = [c for c in framework.open_components()
                                if isinstance(c, Btl)]
        self.endpoints: Dict[int, Btl] = {}
        for btl in self.btls:
            progress.register(btl.progress)

    def endpoint(self, peer: int) -> Btl:
        ep = self.endpoints.get(peer)
        if ep is None:
            for btl in self.btls:  # already priority-sorted
                if btl.reachable(peer):
                    ep = btl
                    break
            if ep is None:
                raise RuntimeError(
                    f"rank {rte.rank}: no BTL reaches peer {peer}")
            self.endpoints[peer] = ep
        return ep

    def send(self, peer: int, data: bytes) -> None:
        """Endpoint lookup + send — the PML's framed-message exit
        point, so btl-layer spans cover every wire handoff."""
        ep = self.endpoint(peer)
        rec = _trace.RECORDER
        if rec is None:
            ep.send(peer, data)
            return
        t0 = _trace.now()
        ep.send(peer, data)
        rec.record("send", "btl", t0, _trace.now(),
                   {"peer": peer, "nbytes": len(data), "btl": ep.NAME})

    def finalize(self) -> None:
        for btl in self.btls:
            progress.unregister(btl.progress)
            btl.finalize()
        framework.close_components()
