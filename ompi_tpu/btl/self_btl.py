"""btl/self — loopback transport.

Reference: opal/mca/btl/self (690 LoC): sends to one's own rank complete by
invoking the receive callback directly. Delivery is deferred to the next
progress sweep (queued) so that matching never recurses inside a send call
from within the matching engine itself.
"""

from __future__ import annotations

from collections import deque

from ompi_tpu.btl import base
from ompi_tpu.runtime import rte


@base.framework.register
class SelfBtl(base.Btl):
    NAME = "self"
    PRIORITY = 100  # exclusively owns self-sends (reference exclusivity)
    EAGER_LIMIT_DEFAULT = 1 << 30  # loopback copies once either way

    def __init__(self) -> None:
        super().__init__()
        self._queue: deque = deque()

    def open(self) -> bool:
        return True

    def reachable(self, peer: int) -> bool:
        return peer == rte.rank

    def send(self, dst: int, data: bytes) -> None:
        assert dst == rte.rank
        self._queue.append(data)

    def progress(self) -> int:
        n = 0
        while self._queue:
            base.deliver(self._queue.popleft())
            n += 1
        return n
