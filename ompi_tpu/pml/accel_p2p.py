"""Device-buffer point-to-point: pipelined staging over the
accelerator's async-copy stream.

Reference: ompi/mca/pml/ob1/pml_ob1_accelerator.c:57-89 — ob1 moves
device buffers through host bounce buffers tracked by outstanding-copy
event arrays, so the D2H of fragment k overlaps the wire transfer of
fragment k-1. Same schedule here: the sender submits every chunk's D2H
to the accelerator's ordered stream up front, then sends each chunk as
its event fires — the stream worker is copying chunk k+1 off the
device while the main thread drives chunk k through the PML. The
receiver overlaps in the mirror direction: each received chunk's H2D
is dispatched asynchronously (PJRT) while the next chunk is on the
wire.

Both sides derive the chunking from ``pml_accel_chunk_bytes`` and the
buffer size, so no extra protocol rides the wire; the cvar must be
uniform across ranks (launcher-forwarded MCA values are).
"""

from __future__ import annotations

import numpy as np

from ompi_tpu.core import cvar, pvar

_chunk_var = cvar.register(
    "pml_accel_chunk_bytes", 4 << 20, int,
    help="Bounce-buffer fragment size for device-buffer p2p staging "
         "(the btl_accelerator_eager_limit/pipeline analog). Sender "
         "D2H of chunk k+1 overlaps the send of chunk k; must be "
         "uniform across ranks (chunk boundaries are derived, not "
         "negotiated).", level=6)


def _chunk_elems(dtype) -> int:
    return max(1, _chunk_var.get() // np.dtype(dtype).itemsize)


def send_dev(comm, buf, dest: int, tag: int) -> None:
    """Pipelined device->wire send of a jax array. A tiny header
    message carries the element count so the receiver's chunk
    schedule follows the SENDER's size (MPI semantics: recv count >=
    send count succeeds with Status reporting the actual amount)."""
    from ompi_tpu import accelerator

    acc = accelerator.current()
    pvar.record("accel_p2p_send")
    flat = buf.reshape(-1)
    n = flat.size
    comm.Send(np.array([n], np.int64), dest=dest, tag=tag)
    if n == 0:
        return
    step = _chunk_elems(flat.dtype)
    # submit ALL D2H copies to the ordered stream first: the worker
    # stays ahead of the wire (outstanding-copy events, ob1-style)
    events = [acc.copy_async(flat[a:a + step])
              for a in range(0, n, step)]
    for ev in events:
        comm.Send(ev.wait(), dest=dest, tag=tag)


def recv_dev(comm, like, source: int, tag: int):
    """Pipelined wire->device receive; returns (new device array,
    final Status). ``like`` supplies shape/dtype (jax arrays are
    immutable — in-place recv is impossible on PJRT buffers); the
    result is shaped by ``like`` with the sender's data in the leading
    elements when the message is shorter (host-recv semantics)."""
    import jax.numpy as jnp

    from ompi_tpu import errors
    from ompi_tpu import accelerator

    acc = accelerator.current()
    pvar.record("accel_p2p_recv")
    cap = int(np.prod(like.shape, dtype=np.int64))
    dtype = np.dtype(like.dtype)
    hdr = np.zeros(1, np.int64)
    st = comm.Recv(hdr, source=source, tag=tag)
    # chunks of one message must all come from the matched peer
    # (per-(src,tag) non-overtaking makes this deterministic)
    source, tag = st.source, st.tag
    n = int(hdr[0])
    if n > cap:
        raise errors.TruncateError(
            f"device recv truncation: message of {n} elements exceeds "
            f"template capacity {cap}")
    step = _chunk_elems(dtype)
    parts = []
    for a in range(0, n, step):
        host = np.empty(min(step, n - a), dtype)
        st = comm.Recv(host, source=source, tag=tag)
        parts.append(acc.to_device(host))  # async H2D overlaps next recv
    if n < cap:  # short message: zero-fill the tail, like-shaped
        parts.append(jnp.zeros(cap - n, like.dtype))
    out = parts[0] if len(parts) == 1 else jnp.concatenate(
        parts or [jnp.zeros(0, like.dtype)])
    st.count = n * dtype.itemsize  # total, not the last fragment
    return out.reshape(like.shape), st
