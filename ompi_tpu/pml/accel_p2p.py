"""Device-buffer point-to-point: pipelined staging over the
accelerator's async-copy stream.

Reference: ompi/mca/pml/ob1/pml_ob1_accelerator.c:57-89 — ob1 moves
device buffers through host bounce buffers tracked by outstanding-copy
event arrays, so the D2H of fragment k overlaps the wire transfer of
fragment k-1. Same schedule here: the sender submits every chunk's D2H
to the accelerator's ordered stream up front, then sends each chunk as
its event fires — the stream worker is copying chunk k+1 off the
device while the main thread drives chunk k through the PML. The
receiver overlaps in the mirror direction: each received chunk's H2D
is dispatched asynchronously (PJRT) while the next chunk is on the
wire.

Both sides derive the chunking from ``pml_accel_chunk_bytes`` and the
buffer size, so no extra protocol rides the wire; the cvar must be
uniform across ranks (launcher-forwarded MCA values are).
"""

from __future__ import annotations

import numpy as np

from ompi_tpu.core import cvar, pvar
from ompi_tpu.pml import request as rq

_chunk_var = cvar.register(
    "pml_accel_chunk_bytes", 4 << 20, int,
    help="Bounce-buffer fragment size for device-buffer p2p staging "
         "(the btl_accelerator_eager_limit/pipeline analog). Sender "
         "D2H of chunk k+1 overlaps the send of chunk k; must be "
         "uniform across ranks (chunk boundaries are derived, not "
         "negotiated). 0 = monolithic (whole message as one chunk, "
         "no overlap) — measured FASTER when ranks oversubscribe "
         "the cores, because the copy-stream worker competes with "
         "the ranks for CPU; the launcher forwards 0 automatically "
         "on oversubscribed single-host jobs (mpirun's "
         "mpi_yield_when_idle-style detection).", level=6)


def _chunk_elems(dtype) -> int:
    nbytes = _chunk_var.get()
    if nbytes <= 0:  # monolithic: one chunk regardless of size
        return 1 << 62
    return max(1, nbytes // np.dtype(dtype).itemsize)


class _DevP2PChannel:
    """Per-(comm, peer, tag) FIFO of in-flight nonblocking device
    transfers. The header+chunks wire protocol relies on one message
    occupying the (src, tag) matching channel at a time: a second
    Isend must not issue its header until the first has ISSUED all
    its chunk sends, and a second Irecv must not post its header
    until the first has POSTED all its chunk recvs — otherwise MPI's
    arrival-order matching interleaves the two messages' frames.
    Wildcard receives serialize on their literal (ANY, tag) key;
    mixing wildcard and specific receives that could match the same
    sender is the application-level race it is in host MPI."""

    _queues = {}

    @classmethod
    def join(cls, key, req) -> None:
        cls._queues.setdefault(key, []).append(req)

    @classmethod
    def is_head(cls, key, req) -> bool:
        q = cls._queues.get(key)
        return bool(q) and q[0] is req

    @classmethod
    def leave(cls, key, req) -> None:
        q = cls._queues.get(key)
        if q and req in q:
            q.remove(req)
        if not q:
            cls._queues.pop(key, None)


class _DevP2PRequest(rq.Request):
    """Progress-driven request for nonblocking device p2p: a state
    machine advanced by the progress engine (no helper threads — the
    same single-progress-loop discipline as ob1). Subclasses implement
    _step(); completion/Status/error semantics are the shared Request
    contract (wait raises on status.error, etc.)."""

    def __init__(self, key) -> None:
        super().__init__()
        self.array = None
        self._key = key
        self._busy = False
        _DevP2PChannel.join(key, self)
        from ompi_tpu.core import progress

        self._cb = self._advance
        progress.register(self._cb)

    def _advance(self) -> int:
        # re-entrancy guard: a pml isend issued from _step can spin
        # the progress engine (full transport), which re-enters this
        # callback — one state-machine step at a time keeps the
        # chunk bookkeeping consistent (ob1's seq reorder queue
        # absorbs any resulting frame reordering)
        if self._busy:
            return 0
        self._busy = True
        try:
            return self._step()
        finally:
            self._busy = False

    def _step(self) -> int:  # returns event count; StopIteration
        raise NotImplementedError  # unregisters (progress contract)

    def _finish(self, error: int = 0) -> None:
        _DevP2PChannel.leave(self._key, self)
        self.complete(error)
        raise StopIteration

    def retrieve_status(self):
        return self.status


class _DevISend(_DevP2PRequest):
    """Nonblocking device send. Construction only queues on the
    channel; the progress engine starts the transfer when this
    request reaches the channel head (header isend + all D2H copies
    submitted), then pushes each chunk to the PML as its copy event
    fires — D2H of chunk k+1 overlaps the wire of chunk k without
    ever blocking the caller."""

    def __init__(self, comm, buf, dest: int, tag: int) -> None:
        pvar.record("accel_p2p_send")
        self._comm, self._dest, self._tag = comm, dest, tag
        self._buf = buf  # pins the source until fully shipped
        self._events = None  # None = not started
        self._reqs = []
        self._issued = False
        super().__init__(("s", comm.cid, dest, tag))

    def _start(self) -> None:
        from collections import deque

        from ompi_tpu import accelerator, pml

        acc = accelerator.current()
        flat = self._buf.reshape(-1)
        step = _chunk_elems(flat.dtype)
        # header first, then ALL copies onto the ordered stream
        self._reqs.append(pml.current().isend(
            self._comm, np.array([flat.size], np.int64), 1, None,
            self._dest, self._tag))
        self._events = deque(
            acc.copy_async(flat[a:a + step])
            for a in range(0, flat.size, step))

    def _step(self) -> int:
        from ompi_tpu import pml

        if self._events is None:
            if not _DevP2PChannel.is_head(self._key, self):
                return 0
            self._start()
        events = 0
        while self._events and self._events[0].query():
            host = self._events.popleft().wait()
            self._reqs.append(pml.current().isend(
                self._comm, host, host.size, None, self._dest,
                self._tag))
            events += 1
        if not self._issued and not self._events:
            # every chunk handed to the PML in order: the next queued
            # send to this (dest, tag) may start
            self._issued = True
            _DevP2PChannel.leave(self._key, self)
        err = next((r.status.error for r in self._reqs
                    if r.status.error), 0)
        if err:
            self._buf = None
            self._finish(err)
        self._reqs = [r for r in self._reqs if not r.completed]
        if self._issued and not self._reqs:
            self._buf = None
            self._finish()
        return events


class _DevIRecv(_DevP2PRequest):
    """Nonblocking device receive. The header irecv posts when this
    request reaches its channel head; once the header lands, chunk
    irecvs post (to the matched peer) and the channel is released;
    each completed chunk dispatches its H2D asynchronously.
    ``.array`` holds the assembled device array after completion. An
    oversized message drains fully into scratch, then errors with
    ERR_TRUNCATE (the channel stays clean for the next match)."""

    def __init__(self, comm, like, source: int, tag: int,
                 transform=None) -> None:
        pvar.record("accel_p2p_recv")
        self._comm = comm
        self._like = like
        self._transform = transform  # e.g. the device convertor's
        # unpack (datatype scatter) applied to the assembled array
        self._want_src, self._want_tag = source, tag
        self._cap = int(np.prod(like.shape, dtype=np.int64))
        self._dtype = np.dtype(like.dtype)
        self._hdr = np.zeros(1, np.int64)
        self._hdr_req = None
        self._chunks = None  # deque of (host, req) once header lands
        self._parts = None
        self._n = 0
        self._truncated = False
        super().__init__(("r", comm.cid, source, tag))

    def _step(self) -> int:
        import jax.numpy as jnp

        from ompi_tpu import accelerator, errors, pml

        if self._hdr_req is None:
            if not _DevP2PChannel.is_head(self._key, self):
                return 0
            self._hdr_req = pml.current().irecv(
                self._comm, self._hdr, 1, None, self._want_src,
                self._want_tag)
        if self._chunks is None:
            if not self._hdr_req.completed:
                return 0
            st = self._hdr_req.status
            if st.error:
                self._finish(st.error)
            self._n = int(self._hdr[0])
            self._truncated = self._n > self._cap
            self.status.source, self.status.tag = st.source, st.tag
            self.status.count = self._n * self._dtype.itemsize
            from collections import deque

            step = _chunk_elems(self._dtype)
            self._chunks = deque()
            self._parts = []
            for a in range(0, self._n, step):
                host = np.empty(min(step, self._n - a), self._dtype)
                self._chunks.append(
                    (host, pml.current().irecv(
                        self._comm, host, host.size, None, st.source,
                        st.tag)))
            # chunk recvs posted in order: release the channel
            _DevP2PChannel.leave(self._key, self)
        events = 0
        acc = accelerator.current()
        while self._chunks and self._chunks[0][1].completed:
            host, req = self._chunks.popleft()
            if req.status.error:
                self._finish(req.status.error)
            if not self._truncated:
                self._parts.append(acc.to_device(host))  # async H2D
            events += 1
        if not self._chunks:
            if self._truncated:  # fully drained: channel stays clean
                self._finish(errors.ERR_TRUNCATE)
            if self._n < self._cap:
                self._parts.append(
                    jnp.zeros(self._cap - self._n, self._like.dtype))
            if len(self._parts) == 1:
                out = self._parts[0]
            elif self._parts:
                out = jnp.concatenate(self._parts)
            else:
                out = jnp.zeros(0, self._like.dtype)
            out = out.reshape(self._like.shape)
            self.array = out if self._transform is None \
                else self._transform(out)
            self._finish()
        return events


def isend_dev(comm, buf, dest: int, tag: int) -> _DevISend:
    return _DevISend(comm, buf, dest, tag)


def irecv_dev(comm, like, source: int, tag: int,
              transform=None) -> _DevIRecv:
    return _DevIRecv(comm, like, source, tag, transform)


def send_dev(comm, buf, dest: int, tag: int) -> None:
    """Pipelined device->wire send of a jax array. A tiny header
    message carries the element count so the receiver's chunk
    schedule follows the SENDER's size (MPI semantics: recv count >=
    send count succeeds with Status reporting the actual amount)."""
    from ompi_tpu import accelerator

    acc = accelerator.current()
    pvar.record("accel_p2p_send")
    flat = buf.reshape(-1)
    n = flat.size
    comm.Send(np.array([n], np.int64), dest=dest, tag=tag)
    if n == 0:
        return
    step = _chunk_elems(flat.dtype)
    # submit ALL D2H copies to the ordered stream first: the worker
    # stays ahead of the wire (outstanding-copy events, ob1-style)
    events = [acc.copy_async(flat[a:a + step])
              for a in range(0, n, step)]
    for ev in events:
        comm.Send(ev.wait(), dest=dest, tag=tag)


def recv_dev(comm, like, source: int, tag: int):
    """Pipelined wire->device receive; returns (new device array,
    final Status). ``like`` supplies shape/dtype (jax arrays are
    immutable — in-place recv is impossible on PJRT buffers); the
    result is shaped by ``like`` with the sender's data in the leading
    elements when the message is shorter (host-recv semantics)."""
    import jax.numpy as jnp

    from ompi_tpu import errors
    from ompi_tpu import accelerator

    acc = accelerator.current()
    pvar.record("accel_p2p_recv")
    cap = int(np.prod(like.shape, dtype=np.int64))
    dtype = np.dtype(like.dtype)
    hdr = np.zeros(1, np.int64)
    st = comm.Recv(hdr, source=source, tag=tag)
    # chunks of one message must all come from the matched peer
    # (per-(src,tag) non-overtaking makes this deterministic)
    source, tag = st.source, st.tag
    n = int(hdr[0])
    if n > cap:
        raise errors.TruncateError(
            f"device recv truncation: message of {n} elements exceeds "
            f"template capacity {cap}")
    step = _chunk_elems(dtype)
    parts = []
    for a in range(0, n, step):
        host = np.empty(min(step, n - a), dtype)
        st = comm.Recv(host, source=source, tag=tag)
        parts.append(acc.to_device(host))  # async H2D overlaps next recv
    if n < cap:  # short message: zero-fill the tail, like-shaped
        parts.append(jnp.zeros(cap - n, like.dtype))
    out = parts[0] if len(parts) == 1 else jnp.concatenate(
        parts or [jnp.zeros(0, like.dtype)])
    st.count = n * dtype.itemsize  # total, not the last fragment
    return out.reshape(like.shape), st
