"""Indexed matching engines — the ob1 custom-match analog.

Reference: ompi/mca/pml/ob1/custommatch/pml_ob1_custom_match.h —
compile-time-selectable matching structures (linked list, arrays,
SIMD fuzzy-512, vectors) that replace the linear posted/unexpected
queue walks. TPU-first redesign: the wildcard lattice is indexed
directly — posted receives bucket by their (want_src, want_tag)
pattern, so an incoming (src, tag) probes at most FOUR bucket heads
((src,tag), (src,ANY), (ANY,tag), (ANY,ANY)) and takes the oldest by
posting sequence; unexpected frags bucket by their concrete
(src, tag), so a specific receive probes one bucket and a wildcard
receive probes bucket heads. O(1)-ish instead of O(queue length),
with EXACTLY the posted-order semantics of the linear walk (MPI
matching is ordered by post time, not bucket).

Selection: cvar ``pml_ob1_matching`` = ``list`` (plain deques, the
default) or ``indexed``. Both containers expose the same deque-like
surface (append / remove / in / iter / len) so every slow-path site
(probes, cancels, fault sweeps) works unchanged; only the two hot
matching scans call the indexed fast paths.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Dict, Iterator, Optional

from ompi_tpu.core import cvar
from ompi_tpu.pml.request import ANY_SOURCE, ANY_TAG

_match_var = cvar.register(
    "pml_ob1_matching", "list", str,
    help="Matching engine for the posted/unexpected queues: 'list' "
         "walks deques linearly (reference ob1 default); 'indexed' "
         "buckets by (src, tag) pattern so matching probes O(1) "
         "bucket heads instead of the whole queue (the custommatch/ "
         "vector-engine analog, pml_ob1_custom_match.h) — wins when "
         "many receives are outstanding.",
    choices=["list", "indexed"], level=6)


def indexed_enabled() -> bool:
    return _match_var.get() == "indexed"


class _Bucketed:
    """Insertion-ordered container with per-key bucket deques.

    ``_order`` (a dict: Python dicts iterate in insertion order, and
    deletion is O(1)) carries the global posted order for the generic
    deque-compatible surface; buckets carry (seq, item) pairs with
    LAZY deletion — a removed item's pair stays in its bucket until
    it surfaces at the head (the tombstone trick every lock-free
    matching structure in the reference uses in some form)."""

    def __init__(self, key_fn: Callable) -> None:
        self._key_fn = key_fn
        self._order: Dict[int, object] = {}
        self._seq = 0
        self._pairs: Dict[int, list] = {}  # id -> [seq, item] cell
        self._buckets: Dict[tuple, deque] = {}

    # -- deque-compatible surface -----------------------------------------
    def append(self, item) -> None:
        self._seq += 1
        cell = [self._seq, item]
        self._order[id(item)] = item
        self._pairs[id(item)] = cell
        self._buckets.setdefault(self._key_fn(item),
                                 deque()).append(cell)

    def remove(self, item) -> None:
        cell = self._pairs.pop(id(item), None)
        if cell is None:
            raise ValueError("item not in queue")
        del self._order[id(item)]
        cell[1] = None  # null the cell NOW: the strong reference to
        # the request/payload drops immediately (the tombstone left
        # in the bucket deque is an empty [seq, None] shell)

    def __contains__(self, item) -> bool:
        return id(item) in self._order

    def __iter__(self) -> Iterator:
        return iter(list(self._order.values()))

    def __len__(self) -> int:
        return len(self._order)

    def __bool__(self) -> bool:
        return bool(self._order)

    # -- bucket plumbing ---------------------------------------------------
    def _head(self, key) -> Optional[list]:
        """[seq, item] at the live head of a bucket, dropping
        tombstone shells."""
        b = self._buckets.get(key)
        if not b:
            return None
        while b:
            if b[0][1] is not None:
                return b[0]
            b.popleft()
        self._buckets.pop(key, None)
        return None

    def _take(self, cell) -> object:
        item = cell[1]
        self.remove(item)
        return item


class PostedIndex(_Bucketed):
    """Posted-receive queue bucketed by (want_src, want_tag)."""

    def __init__(self) -> None:
        super().__init__(lambda req: (req.want_src, req.want_tag))

    def match_incoming(self, src: int, tag: int):
        """Oldest posted receive matching a concrete (src, tag) —
        probes the four wildcard-pattern buckets. Internal (negative)
        tags never match ANY_TAG, as in the linear walk; an incoming
        tag equal to the ANY_TAG sentinel itself (-1) matches nothing
        — its "exact" bucket IS the wildcard bucket, which the linear
        engine's tag<0 rule rejects."""
        if tag == ANY_TAG:
            return None
        cands = [self._head((src, tag)),
                 self._head((ANY_SOURCE, tag))]
        if tag >= 0:
            cands.append(self._head((src, ANY_TAG)))
            cands.append(self._head((ANY_SOURCE, ANY_TAG)))
        best = None
        for c in cands:
            if c is not None and (best is None or c[0] < best[0]):
                best = c
        return None if best is None else self._take(best)


class UnexpectedIndex(_Bucketed):
    """Unexpected-frag queue bucketed by the frag's concrete
    (src, tag) (hdr fields)."""

    def __init__(self) -> None:
        super().__init__(lambda ux: (ux.hdr[2], ux.hdr[3]))

    def _candidate_keys(self, want_src: int, want_tag: int):
        if want_src != ANY_SOURCE and want_tag != ANY_TAG:
            yield (want_src, want_tag)
            return
        for key in list(self._buckets):
            s, t = key
            if want_src != ANY_SOURCE and s != want_src:
                continue
            if want_tag != ANY_TAG and t != want_tag:
                continue
            if want_tag == ANY_TAG and t < 0:
                continue  # internal tags never match wildcards
            yield key

    def find(self, want_src: int, want_tag: int, take: bool):
        """Oldest unexpected frag matching the receive pattern;
        ``take`` removes it (match/mprobe) vs peeks it (iprobe)."""
        best = None
        for key in self._candidate_keys(want_src, want_tag):
            c = self._head(key)
            if c is not None and (best is None or c[0] < best[0]):
                best = c
        if best is None:
            return None
        return self._take(best) if take else best[1]


def make_posted():
    return PostedIndex() if indexed_enabled() else deque()


def make_unexpected():
    return UnexpectedIndex() if indexed_enabled() else deque()
