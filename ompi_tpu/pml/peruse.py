"""PERUSE — message-queue event callbacks for MPI tools.

Reference: ompi/peruse/ (729 LoC): a tool registers per-communicator
callbacks on the PML's internal queue events (PERUSE_COMM_REQ_INSERT_IN_
POSTED_Q, ..._REMOVE_FROM_POSTED_Q, ..._MSG_INSERT_IN_UNEX_Q,
..._MSG_REMOVE_FROM_UNEX_Q, ..._REQ_MATCH_UNEX, peruse.h event enum) and
observes matching behavior — the data MPI profilers use to attribute
late-sender/late-receiver time.

TPU-first shape: a process-wide subscription table fired from ob1's
matching engine. The hot path pays one module-attribute truth test when
no tool is attached (``active`` flips only on first subscription) — the
reference compiles to the same single branch via its event-handle
activation check.

Event payloads are keyword dicts rather than opaque handles: Python
tools want ``ev["tag"]`` not a descriptor query API.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

# -- event ids (reference: peruse.h PERUSE_COMM_* enum) --------------------
REQ_INSERT_IN_POSTED_Q = "req_insert_in_posted_q"
REQ_REMOVE_FROM_POSTED_Q = "req_remove_from_posted_q"
MSG_INSERT_IN_UNEX_Q = "msg_insert_in_unex_q"
MSG_REMOVE_FROM_UNEX_Q = "msg_remove_from_unex_q"
REQ_MATCH_UNEX = "req_match_unex"
REQ_COMPLETE = "req_complete"

EVENTS = (REQ_INSERT_IN_POSTED_Q, REQ_REMOVE_FROM_POSTED_Q,
          MSG_INSERT_IN_UNEX_Q, MSG_REMOVE_FROM_UNEX_Q,
          REQ_MATCH_UNEX, REQ_COMPLETE)

#: fast-path guard: ob1 tests this before building event payloads
active: bool = False

_lock = threading.Lock()
_subs: Dict[str, List[Callable[[dict], None]]] = {}


def subscribe(event: str, cb: Callable[[dict], None]) -> None:
    """Attach a tool callback; cb receives one dict per event with keys
    ``event, ctx, src, tag`` (+ ``size, msgid`` for message events)."""
    global active
    if event not in EVENTS:
        raise ValueError(f"unknown peruse event {event!r}")
    with _lock:
        _subs.setdefault(event, []).append(cb)
        active = True


def unsubscribe(event: str, cb: Callable[[dict], None]) -> None:
    global active
    with _lock:
        try:
            _subs.get(event, []).remove(cb)
        except ValueError:
            pass
        if not any(_subs.values()):
            active = False


def fire(event: str, **info) -> None:
    """Deliver an event (no-op without subscribers; ob1 additionally
    guards on :data:`active` so payload dicts aren't even built)."""
    cbs = _subs.get(event)
    if not cbs:
        return
    info["event"] = event
    for cb in tuple(cbs):
        cb(info)


def reset_for_testing() -> None:
    global active
    with _lock:
        _subs.clear()
        active = False
