"""PML — point-to-point messaging layer.

Reference: ompi/mca/pml/ (pml.h:157-515 interface; ob1 is the default
matching engine over BML/BTLs). Exactly one PML is selected per job
(ompi/instance/instance.c:535). Here the framework selects the ``ob1``
equivalent; the interposition pattern (pml/monitoring) is available via
``monitoring.install()``.
"""

from __future__ import annotations

from typing import Optional

_pml = None


def select():
    """Select and initialize the PML (mca_pml_base_select equivalent)."""
    global _pml
    if _pml is None:
        from ompi_tpu.pml.ob1 import Ob1

        _pml = Ob1()
        _pml.enable()
    return _pml


def current():
    if _pml is None:
        return select()
    return _pml


def instance() -> Optional[object]:
    """The selected PML, or None if none selected yet (no side effects)."""
    return _pml


def set_current(pml) -> None:
    """Install an interposition PML (reference: pml/monitoring, pml/v)."""
    global _pml
    _pml = pml


def finalize() -> None:
    global _pml
    if _pml is not None:
        _pml.disable()
        _pml = None
