"""Requests — completion objects for nonblocking operations.

Reference: ompi/request/ (request.h:451-470 wait via ompi_wait_sync_t;
req_test.c/req_wait.c for test/wait{,any,all,some}). Completion here is a
flag flipped by the progress engine; waits spin progress (SYNC_WAIT,
opal/threads/wait_sync.h:52).
"""

from __future__ import annotations

import itertools
from typing import Any, List, Optional, Sequence

from ompi_tpu.check import memchecker
from ompi_tpu.core import progress

ANY_SOURCE = -1
ANY_TAG = -1
PROC_NULL = -2

_req_ids = itertools.count(1)


class Status:
    """MPI_Status."""

    __slots__ = ("source", "tag", "error", "count", "cancelled")

    def __init__(self) -> None:
        self.source = ANY_SOURCE
        self.tag = ANY_TAG
        self.error = 0
        self.count = 0
        self.cancelled = False

    def get_count(self, datatype=None) -> int:
        if datatype is None or datatype.size == 0:
            return self.count
        return self.count // datatype.size

    def get_elements(self, datatype=None) -> int:
        """MPI_Get_elements (ompi/mpi/c/get_elements.c): the number of
        complete BASIC (predefined) elements received — unlike
        get_count, meaningful for a partial receive of a derived type
        (a truncated struct still reports the leading fields that DID
        arrive). Complex scalars count as ONE element and padding as
        zero (the typemap walk, via datatype.element_pattern). -1
        (MPI_UNDEFINED) when the type has no known basic-element
        decomposition."""
        nbytes = self.count
        if datatype is None or datatype.size == 0:
            return nbytes
        from ompi_tpu.datatype.datatype import element_pattern

        pat = element_pattern(datatype)
        if pat is None:
            return -1  # MPI_UNDEFINED
        # the pattern is ONE inner period (the packed stream repeats
        # it); counting in periods — not whole datatypes — handles
        # contiguous/vector/struct-of-uniform types correctly
        period = sum(nb for nb, _ in pat)
        per_period = sum(ne for _, ne in pat)
        full, rem = divmod(nbytes, period)
        elems = full * per_period
        for nb, ne in pat:  # rem < period: one partial walk suffices
            if rem <= 0:
                break
            take = min(nb, rem)
            if take == nb:
                elems += ne
            elif ne and nb % ne == 0:  # homogeneous segment: count
                elems += take // (nb // ne)  # complete sub-elements
            rem -= take
        return elems

    def set_elements(self, datatype, count: int) -> None:
        """MPI_Status_set_elements (ompi/mpi/c/status_set_elements.c):
        sets the opaque byte count so a later get_elements returns
        exactly ``count`` BASIC elements (generalized-request
        query_fns report their app-defined transfer this way). For
        derived types the byte total walks the element decomposition,
        so get_count floors to whole top-level elements consistently."""
        count = int(count)
        if datatype is None or datatype.size == 0:
            self.count = count
            return
        from ompi_tpu.datatype.datatype import element_pattern

        pat = element_pattern(datatype)
        if pat is None:  # no decomposition known: one element = one
            self.count = count * datatype.size  # datatype (best fit)
            return
        period = sum(nb for nb, _ in pat)
        per_period = sum(ne for _, ne in pat) or 1
        full, rem = divmod(count, per_period)
        nbytes = full * period
        for nb, ne in pat:
            if rem <= 0:
                break
            if ne == 0:  # padding crossed en route to more elements
                nbytes += nb
                continue
            take = min(ne, rem)
            nbytes += take * (nb // ne)
            rem -= take
        self.count = nbytes

    def set_cancelled(self, flag: bool) -> None:
        """MPI_Status_set_cancelled."""
        self.cancelled = bool(flag)

    def is_cancelled(self) -> bool:
        """MPI_Test_cancelled."""
        return self.cancelled

    # mpi4py-convention aliases (the capitalized binding names)
    Set_elements = set_elements
    Set_cancelled = set_cancelled
    Is_cancelled = is_cancelled

    def __repr__(self) -> str:
        return (f"Status(source={self.source}, tag={self.tag}, "
                f"count={self.count})")


class Request:
    """Base request; subclasses fill in _cancel/_free/start."""

    def __init__(self) -> None:
        self.id = next(_req_ids)
        self.completed = False
        self.status = Status()
        self.persistent = False
        self._obj: Any = None  # deserialized payload for object recvs

    # -- completion ------------------------------------------------------
    def complete(self, error: int = 0) -> None:
        self.status.error = error
        self.completed = True
        # memchecker: a completed receive's bytes become defined
        # (no-op unless shadow intervals exist — see check/memchecker)
        memchecker.mark_defined(self.id)

    def test(self) -> bool:
        if not self.completed:
            progress.progress()
        return self.completed

    def wait(self, timeout: Optional[float] = None) -> Status:
        progress.wait_until(lambda: self.completed, timeout=timeout)
        if not self.completed:
            raise TimeoutError(f"request {self.id} did not complete")
        if self.status.error:
            # nonblocking errors surface HERE, so the errhandler
            # dispatch happens here too (the reference invokes the
            # request's comm errhandler at completion). The API layer
            # stamps .comm on requests it hands out; a user callback
            # that returns makes wait() a recovery (status returned,
            # error field still set for inspection).
            from ompi_tpu import errors

            comm = getattr(self, "comm", None)
            if comm is not None and isinstance(
                    getattr(comm, "errhandler", None),
                    errors.Errhandler):
                errors.dispatch(comm, errors.make_mpi_error(
                    self.status.error))
                return self.status
            errors.raise_mpi_error(self.status.error)
        return self.status

    def cancel(self) -> None:
        self._cancel()

    def _cancel(self) -> None:  # best-effort; recv-only in practice
        pass

    def retrieve_status(self) -> Status:
        """The Status as handed to the caller at completion — the hook
        point generalized requests use to run query_fn before the
        status escapes (plural wait/test forms call this)."""
        return self.status

    def start(self) -> None:  # persistent requests override
        raise RuntimeError("not a persistent request")

    def free(self) -> None:
        pass


class GeneralizedRequest(Request):
    """MPI_Grequest_start (reference: ompi/request/grequest.c): an
    application-defined operation exposed as an MPI request. The app
    calls :meth:`complete` (MPI_Grequest_complete) when its operation
    finishes; query_fn fills the Status at wait/test success, free_fn
    runs at free, cancel_fn(completed) at cancel."""

    def __init__(self, query_fn=None, free_fn=None,
                 cancel_fn=None) -> None:
        super().__init__()
        self._query_fn = query_fn
        self._free_fn = free_fn
        self._cancel_fn = cancel_fn
        self._queried = False

    def _maybe_query(self) -> None:
        if self.completed and not self._queried:
            self._queried = True
            if self._query_fn is not None:
                self._query_fn(self.status)

    def retrieve_status(self) -> Status:
        self._maybe_query()
        return self.status

    def test(self) -> bool:
        done = super().test()
        if done:
            self._maybe_query()
        return done

    def wait(self, timeout=None):
        st = super().wait(timeout)
        self._maybe_query()
        return st

    def _cancel(self) -> None:
        """MPI grequest cancel: informs the app (cancel_fn) but does
        NOT complete the request — completion always comes from the
        app's Grequest_complete (the in-flight operation may be
        uncancelable and still own the buffers)."""
        if self._cancel_fn is not None:
            self._cancel_fn(self.completed)
        if not self.completed:
            self.status.cancelled = True

    def free(self) -> None:
        if self._free_fn is not None:
            fn, self._free_fn = self._free_fn, None
            fn()


class CompletedRequest(Request):
    """Immediately-complete request (e.g. PROC_NULL ops)."""

    def __init__(self) -> None:
        super().__init__()
        self.complete()


REQUEST_NULL = CompletedRequest()


# -- wait/test plural forms (MPI_Waitall etc.) ---------------------------

def wait_all(reqs: Sequence[Request],
             timeout: Optional[float] = None) -> List[Status]:
    progress.wait_until(lambda: all(r.completed for r in reqs),
                        timeout=timeout)
    if not all(r.completed for r in reqs):
        raise TimeoutError("waitall timed out")
    return [r.retrieve_status() for r in reqs]


def wait_any(reqs: Sequence[Request]) -> int:
    progress.wait_until(lambda: any(r.completed for r in reqs))
    for i, r in enumerate(reqs):
        if r.completed:
            r.retrieve_status()  # grequest query_fn before status use
            return i
    raise AssertionError


def wait_some(reqs: Sequence[Request]) -> List[int]:
    progress.wait_until(lambda: any(r.completed for r in reqs))
    done = [i for i, r in enumerate(reqs) if r.completed]
    for i in done:
        reqs[i].retrieve_status()
    return done


def test_all(reqs: Sequence[Request]) -> bool:
    progress.progress()
    if all(r.completed for r in reqs):
        for r in reqs:
            r.retrieve_status()
        return True
    return False


def test_any(reqs: Sequence[Request]) -> Optional[int]:
    progress.progress()
    for i, r in enumerate(reqs):
        if r.completed:
            r.retrieve_status()
            return i
    return None
