"""ob1 — the default matching PML over BML/BTLs.

Reference: ompi/mca/pml/ob1/ — protocols MATCH (eager), RNDV (ack-driven
pipelined frags), headers pml_ob1_hdr.h:43-52, protocol choice by size
(pml_ob1_sendreq.h:388-440), per-(comm,peer) sequence ordering + expected/
unexpected queues (pml_ob1_recvfrag.c:863-960). RGET/RDMA protocols have
no host-RDMA substrate here; the accelerator-aware path lives at the coll
level (coll/xla) per the TPU integration architecture (SURVEY.md §5).

Wire format (little-endian structs + raw convertor payload):
  MATCH/RNDV: <B type><I ctx><i src><i tag><Q seq><Q size><B flags><Q msgid>
              [payload (eager only)]
  ACK:        <B type><Q msgid><Q recv_id>
  FRAG:       <B type><Q recv_id><Q offset>[payload]
  FRAG_ACK:   <B type><Q msgid><Q bytes_received>
ctx = cid*2 + (0 p2p | 1 collective); src is the sender's ctx-comm rank.

RNDV flow control: the sender keeps at most ``pml_ob1_send_pipeline_depth``
fragments un-acknowledged (reference: mca_pml_ob1.send_pipeline_depth,
pml_ob1_component.c:207-208); the receiver FRAG_ACKs each fragment, which
both paces GB-scale messages (bounded userspace queueing on tcp, bounded
ring occupancy on sm) and overlaps the sender's pack with the receiver's
unpack.
"""

from __future__ import annotations

import itertools
import pickle
import struct
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu import errors
from ompi_tpu.btl import base as btl_base
from ompi_tpu.check import memchecker
from ompi_tpu.core import arch, events, mpool, output, pvar
from ompi_tpu.datatype import BYTE, Convertor
from ompi_tpu.datatype.convertor import dtype_of
from ompi_tpu.pml import custommatch, peruse
from ompi_tpu.pml import request as rq
from ompi_tpu.runtime import rte
from ompi_tpu.telemetry import flight as _flight
from ompi_tpu.trace import recorder as _trace

HDR_MATCH = 1
HDR_RNDV = 2
HDR_ACK = 3
HDR_FRAG = 4
HDR_FRAG_ACK = 5
HDR_RNDV_SC = 6   # rendezvous offering single-copy (smsc/cma): the
#                   match header + (pid, address) of the stable packed
#                   buffer — the RGET protocol with CMA as the RDMA
#                   (reference: pml_ob1_sendreq.c start_rdma)
HDR_SC_FIN = 7    # receiver finished the single-copy pull

FLAG_SYNC = 1  # ssend: sender wants a match ack
FLAG_OBJ = 2   # payload is a pickled python object

_MATCH = struct.Struct("<BIiiQQBQ")
_ACK = struct.Struct("<BQQ")
_FRAG = struct.Struct("<BQQ")
_FRAGACK = struct.Struct("<BQQ")
_SC = struct.Struct("<QQ")     # pid, remote address
_SCFIN = struct.Struct("<BQ")  # type, msgid

_out = output.stream("pml_ob1")
_msg_ids = itertools.count(1)

from ompi_tpu.core import cvar as _cvar  # noqa: E402

_pipeline_depth = _cvar.register(
    "pml_ob1_send_pipeline_depth", 4, int,
    help="min un-acknowledged RNDV fragments in flight per message "
         "(reference default 3-4); bounds transport queueing and "
         "overlaps sender pack with receiver unpack", level=4)

_send_window = _cvar.register(
    "pml_ob1_send_window_bytes", 1 << 20, int,
    help="RNDV un-acked window floor in bytes: our FRAG_ACKs are "
         "end-to-end (the reference's depth counts local BTL "
         "completions), so the window must cover the ack round-trip "
         "bandwidth-delay product or throughput collapses on "
         "small-fragment BTLs; effective window = "
         "max(depth * frag_size, this)", level=4)

#: "no object" sentinel — None is a perfectly valid object to send
NO_OBJ = object()


class SendRequest(rq.Request):
    __slots__ = ("conv", "dst_world", "ctx", "msgid", "recv_id",
                 "acked_bytes", "pumping", "sc_keep")

    def __init__(self) -> None:
        super().__init__()
        self.conv: Optional[Convertor] = None
        self.dst_world = -1
        self.ctx = 0
        self.msgid = 0
        self.recv_id = 0       # RNDV: receiver's stream id
        self.acked_bytes = 0   # RNDV: FRAG_ACK high-water mark
        self.pumping = False   # re-entrancy guard (see _pump)
        self.sc_keep = None    # single-copy: pins the exposed buffer
        #                        until the receiver's SC_FIN


class RecvRequest(rq.Request):
    __slots__ = ("ctx", "want_src", "want_tag", "buf", "count", "dtype",
                 "conv", "total", "is_obj", "recv_id", "matched",
                 "src_world", "src_msgid")

    def __init__(self, ctx: int, src: int, tag: int, buf, count, dtype,
                 is_obj: bool) -> None:
        super().__init__()
        self.ctx = ctx
        self.want_src = src
        self.want_tag = tag
        self.buf = buf
        self.count = count
        self.dtype = dtype
        self.conv: Optional[Convertor] = None
        self.total = 0
        self.is_obj = is_obj
        self.recv_id = 0
        self.matched = False
        self.src_world = -1   # RNDV: where FRAG_ACKs go
        self.src_msgid = 0    # RNDV: the sender request they address

    def _cancel(self) -> None:
        if not self.matched and not self.completed:
            self.status.cancelled = True
            self.complete()

    def complete(self, error: int = 0) -> None:
        # pooled obj scratch returns to the mpool on EVERY completion
        # path (success, truncation, cancel, FT sweep), and the
        # convertor reference is dropped with it so no completed
        # request aliases a recycled pool buffer
        if self.is_obj and self.buf is not None:
            mpool.pool.give(self.buf)
            self.buf = None
            self.conv = None
        super().complete(error)


class _Unexpected:
    """Parked arrival that found no posted recv."""

    __slots__ = ("hdr", "payload", "src_world")

    def __init__(self, hdr, payload, src_world) -> None:
        self.hdr = hdr       # parsed (type, ctx, src, tag, seq, size,
        self.payload = payload  # flags, msgid); eager payload bytes
        self.src_world = src_world


class Message:
    """MPI_Message (mprobe/mrecv handle)."""

    def __init__(self, pml, ctx, unexpected: _Unexpected) -> None:
        self._pml = pml
        self._ctx = ctx
        self._ux = unexpected


class Ob1:
    """The PML instance (one per process)."""

    def __init__(self) -> None:
        from ompi_tpu.btl import self_btl, sm, tcp  # register components
        from ompi_tpu.btl.base import Bml

        self.bml = Bml()
        # matching state, keyed by ctx (= cid*2 + collective bit);
        # containers come from the selected matching engine (plain
        # deques, or the indexed custom-match analog — see
        # pml/custommatch.py, pml_ob1_custom_match.h)
        self.posted: Dict[int, object] = {}
        self.unexpected: Dict[int, object] = {}
        # ordered delivery: per (ctx, src) sequence numbers
        self.send_seq: Dict[Tuple[int, int], int] = {}
        self.recv_seq: Dict[Tuple[int, int], int] = {}
        self.reorder: Dict[Tuple[int, int], Dict[int, Tuple]] = {}
        # in-flight protocol state
        self.pending_ack: Dict[int, SendRequest] = {}   # msgid -> req
        self.active_recv: Dict[int, RecvRequest] = {}   # recv_id -> req
        self.streaming: Dict[int, SendRequest] = {}     # msgid -> rndv tx
        self._recv_ids = itertools.count(1)
        # frames for communicators this rank has not constructed yet
        # (a peer can finish comm creation and send before we do —
        # reference ob1 queues "non-existing communicator" fragments)
        self.early_frames: Dict[int, list] = {}
        # ULFM: world ranks known to have failed (fed by ft.detector;
        # reference: ob1 request FT sweep, ompi/request/req_ft.c) and
        # failures the app acknowledged (MPIX_Comm_ack_failed) — acked
        # failures no longer poison wildcard receives
        self.failed: set = set()
        self.acked: set = set()

    # -- lifecycle --------------------------------------------------------
    def enable(self) -> None:
        # architecture modex (reference: opal/util/arch.c descriptor
        # exchange) — consulted per peer for heterogeneous conversion
        rte.init()
        rte.modex_send("arch", arch.advertised())
        self._arch_cache: Dict[int, str] = {}
        btl_base.set_recv_callback(self._on_frame)

    def _peer_arch(self, world_rank: int) -> str:
        a = self._arch_cache.get(world_rank)
        if a is None:
            a = self._arch_cache[world_rank] = rte.modex_recv(
                "arch", world_rank)
        return a

    def disable(self) -> None:
        btl_base.set_recv_callback(None)
        self.bml.finalize()

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def _ctx(comm, collective: bool = False) -> int:
        return comm.cid * 2 + (1 if collective else 0)

    def _next_seq(self, ctx: int, dst_commrank: int) -> int:
        key = (ctx, dst_commrank)
        seq = self.send_seq.get(key, 0)
        self.send_seq[key] = seq + 1
        return seq

    def _eager_limit(self, dst_world: int) -> int:
        return self.bml.endpoint(dst_world).eager_limit

    def _frag_size(self, dst_world: int) -> int:
        return self.bml.endpoint(dst_world).max_send

    # -- send path (reference: pml_ob1_sendreq.h:388-440) -----------------
    def isend(self, comm, buf, count, dtype, dst: int, tag: int,
              sync: bool = False, obj=NO_OBJ,
              collective: bool = False) -> SendRequest:
        rec = _trace.RECORDER
        t_send = _trace.now() if rec is not None else 0
        req = SendRequest()
        if dst == rq.PROC_NULL:
            req.complete()
            return req
        ctx = self._ctx(comm, collective)
        flags = 0
        if obj is not NO_OBJ:
            payload_all = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
            conv = Convertor(payload_all, BYTE, len(payload_all))
            flags |= FLAG_OBJ
        else:
            if dtype is None:
                dtype = dtype_of(buf)
            conv = Convertor(buf, dtype, count)
            if memchecker.enabled() and count:
                # reference: MEMCHECKER annotation on every send entry
                # (ompi/mpi/c/send.c) — flag sends of undefined bytes,
                # bounded to the count*extent span actually packed
                # (zero-count sends read nothing: skipped above, since
                # nbytes=0 means "whole buffer" to the interval map)
                memchecker.check_defined(buf, "send",
                                         count * dtype.extent)
        if sync:
            flags |= FLAG_SYNC
        dst_world = comm.world_rank(dst)
        if dst_world in self.failed:
            req.complete(errors.ERR_PROC_FAILED)
            return req
        if obj is NO_OBJ:
            # heterogeneous wire: order on the wire is MY advertised
            # arch; materialize it (swap) whenever the advertisement
            # differs from the machine's real order — even when the
            # peer advertises the SAME forced order, since the peer
            # converts based on my advertisement being true. Round
            # pack windows to whole elements so the converting
            # receiver never sees a split element (pickle obj traffic
            # is arch-independent).
            mine = arch.advertised()
            if (self._peer_arch(dst_world) != mine
                    or mine != arch.native()):
                conv.set_hetero(swap=mine != arch.native())
        src_commrank = comm.rank
        seq = self._next_seq(ctx, dst)
        fl = _flight.FLIGHT
        if fl is not None and collective:
            # dump-only detail: the hang dump shows the last pml seq
            # that moved on each collective context (host-staged
            # collectives progressing vs truly wedged)
            fl.mark_pml(ctx, seq)
        size = conv.packed_size
        msgid = next(_msg_ids)
        req.conv = conv
        req.dst_world = dst_world
        req.ctx = ctx
        req.msgid = msgid
        eager = self._eager_limit(dst_world)
        pvar.record("isend")
        if size <= eager:
            payload = conv.pack()
            hdr = _MATCH.pack(HDR_MATCH, ctx, src_commrank, tag, seq,
                              size, flags, msgid)
            pvar.record("eager")
            if sync:
                self.pending_ack[msgid] = req
                self.bml.send(dst_world, hdr + payload)
            else:
                self.bml.send(dst_world, hdr + payload)
                req.complete()
        else:
            sc = self._expose_single_copy(req, dst_world)
            if sc is not None:
                hdr = _MATCH.pack(HDR_RNDV_SC, ctx, src_commrank, tag,
                                  seq, size, flags, msgid) + sc
                pvar.record("rndv_sc")
            else:
                hdr = _MATCH.pack(HDR_RNDV, ctx, src_commrank, tag, seq,
                                  size, flags, msgid)
                pvar.record("rndv")
            self.pending_ack[msgid] = req
            self.bml.send(dst_world, hdr)
        if rec is not None:
            # span covers pack + protocol selection + first fragment
            # handoff to the BTL (an RNDV transfer continues under
            # progress after this returns)
            rec.record("isend", "pml", t_send, _trace.now(),
                       {"dst": dst_world, "tag": tag, "size": size,
                        "path": "eager" if size <= eager else "rndv"})
        return req

    def _expose_single_copy(self, req: SendRequest,
                            dst_world: int) -> Optional[bytes]:
        """Offer smsc/cma single-copy for a same-host RNDV: pin a
        stable contiguous byte image of the message and return the
        (pid, addr) trailer. Contiguous user buffers are exposed
        in place (a true zero-copy offer); non-contiguous layouts are
        packed once. Returns None when the peer is remote or cma is
        off (reference: the smsc qualification in sm add_procs)."""
        import os

        from ompi_tpu import smsc

        if not smsc.available():
            return None
        if self.bml.endpoint(dst_world).NAME != "sm":
            return None
        if (arch.advertised() != arch.native()
                or self._peer_arch(dst_world) != arch.advertised()):
            # cross-arch pairs stream through the convertor (raw
            # memory pulls would skip the byte-order conversion) —
            # the reference disqualifies single-copy the same way
            return None
        conv = req.conv
        flat = conv._flat(False)
        if conv.is_contig_layout and flat.flags["C_CONTIGUOUS"]:
            req.sc_keep = flat
            addr = flat.ctypes.data
        else:
            data = conv.pack()
            conv.set_position(0)  # keep the frag path viable: the
            # receiver falls back to a plain ACK + streaming if its
            # cma read is denied at runtime
            view = np.frombuffer(data, dtype=np.uint8)
            req.sc_keep = (data, view)
            addr = view.ctypes.data
        return _SC.pack(os.getpid(), addr)

    def send(self, comm, buf, count, dtype, dst: int, tag: int,
             sync: bool = False, collective: bool = False) -> None:
        self.isend(comm, buf, count, dtype, dst, tag, sync=sync,
                   collective=collective).wait()

    def send_obj(self, comm, obj, dst: int, tag: int,
                 collective: bool = False) -> None:
        self.isend(comm, None, 0, None, dst, tag, obj=obj,
                   collective=collective).wait()

    def isend_obj(self, comm, obj, dst: int, tag: int,
                  collective: bool = False) -> SendRequest:
        return self.isend(comm, None, 0, None, dst, tag, obj=obj,
                          collective=collective)

    # -- recv path --------------------------------------------------------
    def irecv(self, comm, buf, count, dtype, src: int, tag: int,
              collective: bool = False) -> RecvRequest:
        if src == rq.PROC_NULL:
            req = RecvRequest(0, src, tag, buf, count, dtype, False)
            req.status.source = rq.PROC_NULL
            req.status.tag = rq.ANY_TAG
            req.complete()
            return req
        ctx = self._ctx(comm, collective)
        if dtype is None and buf is not None:
            dtype = dtype_of(buf)
        req = RecvRequest(ctx, src, tag, buf, count, dtype, False)
        pvar.record("irecv")
        if buf is not None and memchecker.enabled() and count:
            # contents undefined until completion; also flags a second
            # receive racing into the same bytes. Shadow only the
            # count*extent bytes the receive can write — a recv into a
            # larger array must not poison the untouched tail, and a
            # zero-count recv writes nothing at all (skipped above).
            span = count * dtype.extent if dtype is not None else 0
            memchecker.mark_undefined(req.id, buf, span)
        err = self._recv_src_failed(comm, src)
        if err:
            req.complete(err)
            return req
        self._post(req)
        rec = _trace.RECORDER
        if rec is not None:
            rec.instant("irecv_post", "pml", {"src": src, "tag": tag})
        return req

    def irecv_obj(self, comm, src: int, tag: int,
                  collective: bool = False) -> RecvRequest:
        ctx = self._ctx(comm, collective)
        req = RecvRequest(ctx, src, tag, None, 0, None, True)
        pvar.record("irecv")
        err = self._recv_src_failed(comm, src)
        if err:
            req.complete(err)
            return req
        self._post(req)
        return req

    def _recv_src_failed(self, comm, src: int) -> int:
        """Error class for a recv that can/should not be posted: a named
        recv towards a failed sender can never match (PROC_FAILED); a
        wildcard recv while unacknowledged failures exist in the comm
        must fail PENDING (ULFM ANY_SOURCE semantics)."""
        if not self.failed:
            return 0
        g = comm.remote_group if getattr(comm, "is_inter", False) \
            else comm.group
        if src == rq.ANY_SOURCE:
            unacked = self.failed - self.acked
            if any(r in unacked for r in g.ranks):
                return errors.ERR_PROC_FAILED_PENDING
            return 0
        if g.ranks[src] in self.failed:
            return errors.ERR_PROC_FAILED
        return 0

    def recv(self, comm, buf, count, dtype, src: int, tag: int,
             collective: bool = False) -> rq.Status:
        return self.irecv(comm, buf, count, dtype, src, tag,
                          collective=collective).wait()

    def recv_obj(self, comm, src: int, tag: int, collective: bool = False):
        req = self.irecv_obj(comm, src, tag, collective=collective)
        req.wait()
        return req._obj

    def _find_unexpected(self, ctx: int, want_src: int, want_tag: int,
                         take: bool):
        """Oldest unexpected frag matching the receive pattern, via
        the selected matching engine (the ONE dispatch point — post,
        iprobe and improbe all route here so the engines can never
        drift)."""
        q = self.unexpected.get(ctx)
        if q is None:
            return None
        if isinstance(q, custommatch.UnexpectedIndex):
            return q.find(want_src, want_tag, take)
        probe = RecvRequest(ctx, want_src, want_tag, None, 0, None,
                            False)
        for cand in q:
            if self._hdr_matches(probe, cand.hdr):
                if take:
                    q.remove(cand)
                return cand
        return None

    def _post(self, req: RecvRequest) -> None:
        """Try the unexpected queue, else append to posted."""
        ux = self._find_unexpected(req.ctx, req.want_src,
                                   req.want_tag, take=True)
        if ux is not None:
            if peruse.active:
                peruse.fire(peruse.MSG_REMOVE_FROM_UNEX_Q,
                            ctx=req.ctx, src=ux.hdr[2],
                            tag=ux.hdr[3], size=ux.hdr[5],
                            msgid=ux.hdr[7])
                peruse.fire(peruse.REQ_MATCH_UNEX, ctx=req.ctx,
                            src=ux.hdr[2], tag=ux.hdr[3],
                            size=ux.hdr[5], msgid=ux.hdr[7])
            if events.active("pml_message_matched"):
                events.emit("pml_message_matched", ctx=req.ctx,
                            src=ux.hdr[2], tag=ux.hdr[3],
                            size=ux.hdr[5], from_unexpected=True)
            self._match(req, ux.hdr, ux.payload, ux.src_world)
            return
        # get-or-create (NOT setdefault: make_posted() costs a cvar
        # lookup + container alloc, too much for the per-post path)
        q = self.posted.get(req.ctx)
        if q is None:
            q = self.posted[req.ctx] = custommatch.make_posted()
        q.append(req)
        if peruse.active:
            peruse.fire(peruse.REQ_INSERT_IN_POSTED_Q, ctx=req.ctx,
                        src=req.want_src, tag=req.want_tag)

    @staticmethod
    def _hdr_matches(req: RecvRequest, hdr) -> bool:
        _, _, src, tag, _, _, _, _ = hdr
        if req.want_src != rq.ANY_SOURCE and req.want_src != src:
            return False
        if req.want_tag != rq.ANY_TAG and req.want_tag != tag:
            return False
        # negative tags are framework-internal: never match ANY_TAG
        if req.want_tag == rq.ANY_TAG and tag < 0:
            return False
        return True

    # -- probe family -----------------------------------------------------
    def iprobe(self, comm, src: int, tag: int) -> Optional[rq.Status]:
        from ompi_tpu.core import progress

        progress.progress()
        ctx = self._ctx(comm)
        ux = self._find_unexpected(ctx, src, tag, take=False)
        if ux is not None:
            st = rq.Status()
            _, _, s, t, _, size, _, _ = ux.hdr
            st.source, st.tag, st.count = s, t, size
            pvar.record("matched_probes")
            return st
        return None

    def probe(self, comm, src: int, tag: int) -> rq.Status:
        from ompi_tpu.core import progress

        result: List[rq.Status] = []

        def check() -> bool:
            st = self.iprobe(comm, src, tag)
            if st is not None:
                result.append(st)
                return True
            return False

        progress.wait_until(check)
        return result[0]

    def improbe(self, comm, src: int,
                tag: int) -> Optional[Tuple[Message, rq.Status]]:
        from ompi_tpu.core import progress

        progress.progress()
        ctx = self._ctx(comm)
        ux = self._find_unexpected(ctx, src, tag, take=True)
        if ux is not None:
            st = rq.Status()
            _, _, s, t, _, size, _, _ = ux.hdr
            st.source, st.tag, st.count = s, t, size
            return Message(self, ctx, ux), st
        return None

    def mprobe(self, comm, src: int, tag: int) -> Tuple[Message, rq.Status]:
        from ompi_tpu.core import progress

        out: List = []

        def check() -> bool:
            got = self.improbe(comm, src, tag)
            if got is not None:
                out.append(got)
                return True
            return False

        progress.wait_until(check)
        return out[0]

    def mrecv(self, msg: Message, buf, count, dtype) -> rq.Status:
        ux = msg._ux
        req = RecvRequest(msg._ctx, ux.hdr[2], ux.hdr[3], buf, count,
                          dtype, buf is None)
        self._match(req, ux.hdr, ux.payload, ux.src_world)
        req.wait()
        return req.status

    # -- matching & protocol (receiver side) ------------------------------
    def _on_frame(self, data: bytes) -> None:
        t = data[0]
        if t in (HDR_MATCH, HDR_RNDV, HDR_RNDV_SC):
            hdr = _MATCH.unpack_from(data, 0)
            payload = data[_MATCH.size:]
            self._on_match_frame(hdr, payload)
        elif t == HDR_ACK:
            _, msgid, recv_id = _ACK.unpack_from(data, 0)
            self._on_ack(msgid, recv_id)
        elif t == HDR_FRAG:
            _, recv_id, offset = _FRAG.unpack_from(data, 0)
            self._on_frag(recv_id, offset, data[_FRAG.size:])
        elif t == HDR_FRAG_ACK:
            _, msgid, nbytes = _FRAGACK.unpack_from(data, 0)
            self._on_frag_ack(msgid, nbytes)
        elif t == HDR_SC_FIN:
            _, msgid = _SCFIN.unpack_from(data, 0)
            self._on_sc_fin(msgid)
        else:
            _out.error("unknown frame type %d", t)

    def _on_match_frame(self, hdr, payload) -> None:
        """Sequence-ordered delivery per (ctx, src)
        (reference: match_incomming, pml_ob1_recvfrag.c:863-960)."""
        _, ctx, src, _, seq, _, _, _ = hdr
        from ompi_tpu import comm as comm_mod

        if comm_mod.lookup_cid(ctx // 2) is None:
            self.early_frames.setdefault(ctx // 2, []).append(
                (hdr, payload))
            return
        key = (ctx, src)
        expected = self.recv_seq.get(key, 0)
        if seq != expected:
            pvar.record("out_of_sequence")
            self.reorder.setdefault(key, {})[seq] = (hdr, payload)
            return
        self._deliver_match(hdr, payload)
        self.recv_seq[key] = expected + 1
        # drain any parked successors
        parked = self.reorder.get(key)
        while parked:
            nxt = self.recv_seq[key]
            item = parked.pop(nxt, None)
            if item is None:
                break
            self._deliver_match(*item)
            self.recv_seq[key] = nxt + 1

    def _deliver_match(self, hdr, payload) -> None:
        _, ctx, src, tag, _, size, flags, msgid = hdr
        q = self.posted.get(ctx)
        if q is None:
            q = self.posted[ctx] = custommatch.make_posted()
        if isinstance(q, custommatch.PostedIndex):
            req = q.match_incoming(src, tag)  # four bucket heads
        else:
            req = None
            for cand in q:
                if self._hdr_matches(cand, hdr):
                    q.remove(cand)
                    req = cand
                    break
        if req is not None:
            if peruse.active:
                peruse.fire(peruse.REQ_REMOVE_FROM_POSTED_Q,
                            ctx=ctx, src=src, tag=tag, size=size,
                            msgid=msgid)
            if events.active("pml_message_matched"):
                events.emit("pml_message_matched", ctx=ctx,
                            src=src, tag=tag, size=size,
                            from_unexpected=False)
            self._match(req, hdr, payload, self._src_world(ctx, src))
            return
        pvar.record("unexpected")
        uq = self.unexpected.get(ctx)
        if uq is None:
            uq = self.unexpected[ctx] = custommatch.make_unexpected()
        uq.append(_Unexpected(hdr, payload, self._src_world(ctx, src)))
        if peruse.active:
            peruse.fire(peruse.MSG_INSERT_IN_UNEX_Q, ctx=ctx, src=src,
                        tag=tag, size=size, msgid=msgid)
        if events.active("pml_unexpected_queued"):
            events.emit("pml_unexpected_queued", ctx=ctx, src=src,
                        tag=tag, size=size, depth=len(uq))

    @staticmethod
    def _src_world(ctx: int, src_commrank: int) -> int:
        from ompi_tpu import comm as comm_mod

        c = comm_mod.lookup_cid(ctx // 2)
        if c is None:
            raise errors.MPIError(errors.ERR_COMM,
                                  f"message for unknown cid {ctx // 2}")
        # intercomm: inbound src ranks are the sender's LOCAL ranks,
        # which index OUR remote group
        g = c.remote_group if getattr(c, "is_inter", False) else c.group
        return g.ranks[src_commrank]

    def _match(self, req: RecvRequest, hdr, payload, src_world: int) -> None:
        typ, ctx, src, tag, _, size, flags, msgid = hdr
        req.matched = True
        req.status.source = src
        req.status.tag = tag
        req.total = size
        # build the receive convertor
        if req.is_obj or (flags & FLAG_OBJ and req.buf is None):
            # pooled scratch (mpool): object payloads arrive at a high
            # rate from the lowercase API; the pool's size classes may
            # hand back a larger bytearray — the convertor only touches
            # [0, size) and _finish_recv slices before unpickling
            req.buf = mpool.pool.take(size)
            req.is_obj = True
            req.conv = Convertor(req.buf, BYTE, size)
        else:
            req.conv = Convertor(req.buf, req.dtype, req.count)
            if self._peer_arch(src_world) != arch.native():
                # wire order is the sender's advertised arch: convert
                # incoming elements to native on unpack. A layout the
                # convertor cannot convert (mixed struct) errors the
                # REQUEST — raising here would unwind the progress
                # callback with the message half-processed and hang
                # the (ctx, src) ordering channel
                try:
                    req.conv.set_hetero(swap=True)
                except ValueError:
                    req.status.error = errors.ERR_TYPE
            if size > req.conv.packed_size:
                # truncation: still must drain the protocol
                req.status.error = errors.ERR_TRUNCATE
        if typ == HDR_MATCH:
            take = min(size, req.conv.packed_size)
            req.conv.unpack(payload[:take])
            req.status.count = take
            if flags & FLAG_SYNC:
                ack = _ACK.pack(HDR_ACK, msgid, 0)
                self.bml.send(src_world, ack)
            self._finish_recv(req)
            return
        if typ == HDR_RNDV_SC and self._try_single_copy(
                req, payload, size, msgid, src_world):
            return
        # RNDV: allocate recv id, ack, wait for frags
        req.recv_id = next(self._recv_ids)
        req.src_world = src_world
        req.src_msgid = msgid
        self.active_recv[req.recv_id] = req
        ack = _ACK.pack(HDR_ACK, msgid, req.recv_id)
        self.bml.send(src_world, ack)

    def _try_single_copy(self, req: RecvRequest, payload: bytes,
                         size: int, msgid: int,
                         src_world: int) -> bool:
        """Pull the message straight from the sender's address space
        (smsc/cma); on any denial fall back to streaming by returning
        False (the plain ACK then triggers the sender's frag pump —
        its convertor was left rewound for exactly this)."""
        from ompi_tpu import smsc

        if not smsc.available():
            return False
        if req.conv.wire_round or req.conv.wire_swap:
            # heterogeneous peer: a raw memory pull would skip the
            # byte-order conversion on the contiguous fast path
            # (unpack() converts; smsc.read does not) — stream instead
            return False
        pid, addr = _SC.unpack_from(payload, 0)
        take = min(size, req.conv.packed_size)
        try:
            flat = req.conv._flat(True)
            if req.conv.is_contig_layout and flat.flags["C_CONTIGUOUS"]:
                # contiguous receiver: pull straight into the user
                # buffer — the actual single copy
                smsc.read(pid, addr, memoryview(flat)[:take])
                req.conv.set_position(take)
            else:
                wire = bytearray(take)
                smsc.read(pid, addr, memoryview(wire))
                req.conv.unpack(wire)
        except OSError as exc:
            # e.g. yama ptrace restrictions between sibling ranks that
            # the self-read probe cannot detect
            smsc.disqualify(f"runtime read from pid {pid}: {exc}")
            return False
        req.status.count = take
        self.bml.send(src_world, _SCFIN.pack(HDR_SC_FIN, msgid))
        self._finish_recv(req)
        return True

    def _on_sc_fin(self, msgid: int) -> None:
        """Receiver completed its single-copy pull: release the pinned
        buffer and complete (RGET FIN, pml_ob1_recvreq.c fin)."""
        req = self.pending_ack.pop(msgid, None)
        if req is None:
            _out.error("SC_FIN for unknown msgid %d", msgid)
            return
        req.sc_keep = None
        req.complete()

    def _finish_recv(self, req: RecvRequest) -> None:
        if req.is_obj and req.status.error == 0:
            req._obj = pickle.loads(
                bytes(memoryview(req.buf)[:req.total]))
        req.complete(req.status.error)  # releases pooled obj scratch
        if peruse.active:
            peruse.fire(peruse.REQ_COMPLETE, ctx=req.ctx,
                        src=req.status.source, tag=req.status.tag,
                        size=req.status.count)

    # -- sender: ack/frag streaming (reference: mca_pml_ob1_send_request_
    #    schedule pipeline, depth pml_ob1_component.c:207) ----------------
    def _on_ack(self, msgid: int, recv_id: int) -> None:
        req = self.pending_ack.pop(msgid, None)
        if req is None:
            _out.error("ACK for unknown msgid %d", msgid)
            return
        if recv_id == 0:  # eager ssend ack
            req.complete()
            return
        # the receiver declined any single-copy offer: release the
        # pinned image (the frag pump re-packs from the user buffer)
        req.sc_keep = None
        req.recv_id = recv_id
        self.streaming[msgid] = req
        self._pump(req)

    def _pump(self, req: SendRequest) -> None:
        """Send fragments while the un-acked window has room
        (reference: mca_pml_ob1_send_request_schedule with
        send_pipeline_depth). Completion = all bytes handed to the BTL
        (the send buffer is then reusable — MPI completion semantics);
        FRAG_ACKs only pace the stream."""
        # re-entrancy guard: ep.send can spin the progress engine when a
        # transport is full, delivering a FRAG_ACK that re-enters _pump
        # for this very request — the nested pump would enqueue a LATER
        # fragment before the outer one, reordering the stream. The
        # nested call just updates acked_bytes (in _on_frag_ack) and
        # returns; the outer loop re-reads the window each iteration.
        if req.pumping:
            return
        req.pumping = True
        try:
            conv = req.conv
            frag_size = self._frag_size(req.dst_world)
            window = max(max(1, _pipeline_depth.get()) * frag_size,
                         _send_window.get())
            ep = self.bml.endpoint(req.dst_world)
            while not conv.done \
                    and conv.position - req.acked_bytes < window:
                offset = conv.position
                data = conv.pack(max_bytes=frag_size)
                pvar.record("rndv_frag")
                frame = _FRAG.pack(HDR_FRAG, req.recv_id, offset) + data
                rec = _trace.RECORDER
                if rec is None:
                    ep.send(req.dst_world, frame)
                else:
                    t0 = _trace.now()
                    ep.send(req.dst_world, frame)
                    rec.record("send", "btl", t0, _trace.now(),
                               {"peer": req.dst_world,
                                "nbytes": len(frame), "btl": ep.NAME})
        finally:
            req.pumping = False
        if conv.done and not req.completed:
            self.streaming.pop(req.msgid, None)
            req.complete()

    def _on_frag_ack(self, msgid: int, nbytes: int) -> None:
        req = self.streaming.get(msgid)
        if req is None:
            return  # stream already fully sent — stale ack, fine
        if nbytes > req.acked_bytes:
            req.acked_bytes = nbytes
        self._pump(req)

    def _on_frag(self, recv_id: int, offset: int, data: bytes) -> None:
        req = self.active_recv.get(recv_id)
        if req is None:
            _out.error("FRAG for unknown recv_id %d", recv_id)
            return
        if req.status.error == errors.ERR_TRUNCATE:
            # drain the stream but drop bytes beyond capacity
            room = req.conv.packed_size - req.conv.position
            if room > 0:
                req.conv.unpack(data[:room])
        else:
            assert offset == req.conv.position, \
                f"frag offset {offset} != conv position {req.conv.position}"
            req.conv.unpack(data)
        # credit the sender's window (every fragment: the ack is tiny
        # relative to frag_size and keeps the pipe full)
        end = offset + len(data)
        fack = _FRAGACK.pack(HDR_FRAG_ACK, req.src_msgid, end)
        self.bml.send(req.src_world, fack)
        # completion when the sender's full size has streamed past us
        if end >= req.total:
            req.status.count = min(req.total, req.conv.packed_size)
            del self.active_recv[recv_id]
            self._finish_recv(req)

    def comm_registered(self, cid: int) -> None:
        """Replay frames that arrived before this comm existed locally."""
        frames = self.early_frames.pop(cid, None)
        if frames:
            for hdr, payload in frames:
                self._on_match_frame(hdr, payload)

    # -- cancel / cleanup -------------------------------------------------
    def cancel_recv(self, req: RecvRequest) -> None:
        q = self.posted.get(req.ctx)
        if q is not None and req in q:
            q.remove(req)
        req._cancel()

    # -- ULFM fault sweep (reference: ompi/request/req_ft.c) --------------
    def on_fault(self, dead_world: set) -> int:
        """Error every in-flight request that involves a failed rank.
        Called from the progress sweep by ft.detector."""
        from ompi_tpu import comm as comm_mod

        self.failed |= dead_world
        events = 0
        # posted (unmatched) recvs: named sources towards the dead fail;
        # wildcards fail PENDING once any group member is gone (ULFM
        # MPI_ERR_PROC_FAILED_PENDING — the app may ack and repost)
        for ctx, q in list(self.posted.items()):
            c = comm_mod.lookup_cid(ctx // 2)
            if c is None:
                continue
            g = c.remote_group if getattr(c, "is_inter", False) else c.group
            dead_in_comm = [r for r in g.ranks if r in dead_world]
            if not dead_in_comm:
                continue
            for req in list(q):
                if req.want_src == rq.ANY_SOURCE:
                    q.remove(req)
                    req.complete(errors.ERR_PROC_FAILED_PENDING)
                    events += 1
                elif g.ranks[req.want_src] in dead_world:
                    q.remove(req)
                    req.complete(errors.ERR_PROC_FAILED)
                    events += 1
        # matched RNDV recvs streaming from a dead sender
        for recv_id, req in list(self.active_recv.items()):
            if req.src_world in dead_world:
                del self.active_recv[recv_id]
                req.complete(errors.ERR_PROC_FAILED)
                events += 1
        # sends awaiting ACK / streaming frags towards a dead receiver
        for table in (self.pending_ack, self.streaming):
            for msgid, req in list(table.items()):
                if req.dst_world in dead_world:
                    del table[msgid]
                    if not req.completed:
                        req.complete(errors.ERR_PROC_FAILED)
                        events += 1
        return events

    def on_revoke(self, cid: int) -> int:
        """Error every in-flight request on a revoked communicator
        (reference: ompi/communicator/ft/comm_ft_revoke.c drains the
        match queues)."""
        events = 0
        for ctx in (cid * 2, cid * 2 + 1):
            q = self.posted.get(ctx)
            for req in list(q or ()):
                q.remove(req)
                req.complete(errors.ERR_REVOKED)
                events += 1
            for recv_id, req in list(self.active_recv.items()):
                if req.ctx == ctx:
                    del self.active_recv[recv_id]
                    req.complete(errors.ERR_REVOKED)
                    events += 1
            for table in (self.pending_ack, self.streaming):
                for msgid, req in list(table.items()):
                    if req.ctx == ctx and not req.completed:
                        del table[msgid]
                        req.complete(errors.ERR_REVOKED)
                        events += 1
        return events
