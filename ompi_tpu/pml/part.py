"""Compat shim — partitioned point-to-point moved to the dedicated
MPI-4 subsystem :mod:`ompi_tpu.part` (host path: ``part.host``; the
device-path partitioned fused allreduce lives in coll/xla as
``Pallreduce_init``). Importing this module keeps attaching
``Comm.Psend_init`` / ``Precv_init`` exactly as before."""

from ompi_tpu.part.host import (  # noqa: F401
    MAX_PARTITIONS, MAX_TAG, PartitionedRecvRequest,
    PartitionedSendRequest, _Precv_init, _Psend_init, attach,
)
