"""pml/monitoring — interposition PML feeding the monitoring plane.

Reference: ompi/mca/pml/monitoring (512 LoC) + common/monitoring: a
PML that wraps the selected one and counts messages/bytes per
destination peer. Since the monitoring plane landed this module is a
thin shim: the matrices themselves live in
:mod:`ompi_tpu.monitoring.matrix` (per-context, per-(src,dst), with
link attribution at level 2), and this layer only provides the
send-path interposition plus the historical module API.

Peer translation goes through the (remote, for inter-communicators)
group's rank table; a peer outside the group raises
``MPIError(ERR_RANK)`` at the call — the old silent ``world = dst``
fallback misattributed inter-communicator traffic.

Usage:
    from ompi_tpu.pml import monitoring
    monitoring.install()           # or --mca monitoring_level 1
    ... run ...
    matrix = monitoring.matrix()   # {peer: (msgs, bytes)}
    monitoring.dump()              # human-readable to the output stream

``--mca pml_monitoring 1`` still works (deprecated): it compat-maps
to ``monitoring_level 1`` and now gets the full plane, including the
Finalize-time matrix dump and telemetry-rollup inclusion it never
had.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from ompi_tpu.core import cvar, output
from ompi_tpu.monitoring import matrix as _matrix

_out = output.stream("pml_monitoring")

# ompi_tpu.osc._SERVICE_TAG — resolved here (not imported) because
# osc imports the pml package; window service traffic is counted by
# the osc epoch path with its real payload bytes, not as p2p obj sends
_OSC_SERVICE_TAG = -64

# ompi_tpu.part.host._PART_BASE — every partitioned-chunk isend rides
# a tag at or below this ceiling, which classifies it as ctx="part"
# here instead of a second counting site in Pready (same not-imported
# rationale: part imports the pml package)
_PART_TAG_CEIL = -(1 << 24)

_enable_var = cvar.register(
    "pml_monitoring", False, bool,
    help="DEPRECATED compat alias for --mca monitoring_level 1 "
         "(reference: pml/monitoring). The monitoring plane replaces "
         "this cvar; it keeps working via the compat mapping.",
    level=7)


class MonitoringPml:
    """Wraps the real PML; counts sends per destination world rank
    into the plane's TRAFFIC matrix (send side only — every message
    counted exactly once, by its sender; the merge transposes for the
    receive view)."""

    def __init__(self, inner) -> None:
        self._inner = inner

    # -- counting helpers -------------------------------------------------
    @staticmethod
    def _count(comm, dst: int, nbytes: int, collective: bool,
               ns: int = 0, tag: int = 0) -> None:
        tm = _matrix.TRAFFIC
        if tm is None:
            return
        if tag <= _PART_TAG_CEIL:
            ctx = "part"
        else:
            ctx = "coll" if collective else "p2p"
        tm.count(ctx, _matrix.world_rank(comm, dst), nbytes, ns=ns)

    @staticmethod
    def _nbytes(buf, count, dtype) -> int:
        if dtype is not None and count:
            return count * dtype.size
        nb = getattr(buf, "nbytes", None)
        return nb if nb is not None else 0

    # -- intercepted send-side entries ------------------------------------
    def isend(self, comm, buf, count, dtype, dst, tag, **kw):
        self._count(comm, dst, self._nbytes(buf, count, dtype),
                    kw.get("collective", False), tag=tag)
        return self._inner.isend(comm, buf, count, dtype, dst, tag, **kw)

    def send(self, comm, buf, count, dtype, dst, tag, **kw):
        t0 = time.monotonic_ns()
        out = self._inner.send(comm, buf, count, dtype, dst, tag, **kw)
        self._count(comm, dst, self._nbytes(buf, count, dtype),
                    kw.get("collective", False),
                    ns=time.monotonic_ns() - t0, tag=tag)
        return out

    def isend_obj(self, comm, obj, dst, tag, **kw):
        if tag != _OSC_SERVICE_TAG:
            self._count(comm, dst, 0, kw.get("collective", False))
        return self._inner.isend_obj(comm, obj, dst, tag, **kw)

    def send_obj(self, comm, obj, dst, tag, **kw):
        # osc window service messages are counted at the epoch path
        # (ctx="osc", with their actual payload bytes) — counting them
        # here too would double-book every put/get/ack
        if tag != _OSC_SERVICE_TAG:
            self._count(comm, dst, 0, kw.get("collective", False))
        return self._inner.send_obj(comm, obj, dst, tag, **kw)

    # -- everything else passes through -----------------------------------
    def __getattr__(self, name):
        return getattr(self._inner, name)


def install() -> MonitoringPml:
    """Wrap the currently-selected PML (idempotent). Enables the
    matrix core at level 1 if the plane isn't up yet, so the direct
    ``monitoring.install()`` API keeps working without the runtime."""
    from ompi_tpu import pml

    if _matrix.TRAFFIC is None:
        from ompi_tpu.runtime import rte

        _matrix.enable(rank=rte.rank, level=1,
                       nranks=max(rte.size, 1))
    cur = pml.current()
    if isinstance(cur, MonitoringPml):
        return cur
    mon = MonitoringPml(cur)
    pml.set_current(mon)
    return mon


def installed() -> Optional[MonitoringPml]:
    """Find the monitoring layer anywhere in the interposition stack."""
    from ompi_tpu import pml

    cur = pml.instance()
    while cur is not None:
        if isinstance(cur, MonitoringPml):
            return cur
        cur = getattr(cur, "_inner", None)
    return None


def uninstall() -> None:
    from ompi_tpu import pml

    cur = pml.instance()
    if isinstance(cur, MonitoringPml):
        pml.set_current(cur._inner)


def matrix(collective: bool = False) -> Dict[int, Tuple[int, int]]:
    """Send-side traffic matrix {peer_world_rank: (msgs, bytes)} —
    the plane's p2p (or coll) context table."""
    tm = _matrix.TRAFFIC
    if tm is None:
        return {}
    return dict(sorted(
        tm.peer_totals("coll" if collective else "p2p").items()))


def dump() -> None:
    """common/monitoring-style matrix dump to the output stream."""
    tm = _matrix.TRAFFIC
    if tm is None:
        _out.verbose(0, "monitoring not installed")
        return
    for label in ("p2p", "coll"):
        for peer, (msgs, nbytes) in sorted(
                tm.peer_totals(label).items()):
            _out.verbose(
                0, "rank %d -> %d [%s]: %d msgs, %d bytes",
                tm.rank, peer, label, msgs, nbytes)
