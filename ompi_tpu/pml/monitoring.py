"""pml/monitoring — interposition PML recording per-peer traffic.

Reference: ompi/mca/pml/monitoring (512 LoC) + common/monitoring: a
PML that wraps the selected one, counts messages and bytes per
destination peer (split by point-to-point vs collective context), and
dumps a traffic matrix at finalize or on demand. The same pattern
carries pml/v (message logging) — any interposition layer installs via
``pml.set_current``.

Usage:
    from ompi_tpu.pml import monitoring
    monitoring.install()           # or --mca pml_monitoring 1
    ... run ...
    matrix = monitoring.matrix()   # {peer: (msgs, bytes)}
    monitoring.dump()              # human-readable to the output stream
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ompi_tpu.core import cvar, output, pvar

_out = output.stream("pml_monitoring")

_enable_var = cvar.register(
    "pml_monitoring", False, bool,
    help="Install the monitoring interposition PML at init "
         "(reference: pml/monitoring).", level=7)


class MonitoringPml:
    """Wraps the real PML; counts sends per destination world rank.

    The reference monitors the send side (every message is counted
    exactly once, by its sender); receive totals are available as the
    transpose after an allgather of matrices."""

    def __init__(self, inner) -> None:
        self._inner = inner
        # world rank -> [messages, bytes], split by context
        self.p2p: Dict[int, list] = {}
        self.coll: Dict[int, list] = {}

    # -- counting helpers -------------------------------------------------
    def _count(self, comm, dst: int, nbytes: int,
               collective: bool) -> None:
        if dst < 0:  # PROC_NULL
            return
        try:
            g = comm.remote_group if getattr(comm, "is_inter", False) \
                else comm.group
            world = g.ranks[dst]
        except (IndexError, AttributeError):
            world = dst
        table = self.coll if collective else self.p2p
        cell = table.setdefault(world, [0, 0])
        cell[0] += 1
        cell[1] += nbytes
        pvar.record("monitoring_msgs")
        pvar.record("monitoring_bytes", nbytes)
        # per-context counters (reference common/monitoring splits its
        # counting by p2p vs collective the same way); the combined
        # pair above stays for compatibility
        kind = "coll" if collective else "p2p"
        pvar.record(f"monitoring_{kind}_msgs")
        pvar.record(f"monitoring_{kind}_bytes", nbytes)

    @staticmethod
    def _nbytes(buf, count, dtype) -> int:
        if dtype is not None and count:
            return count * dtype.size
        nb = getattr(buf, "nbytes", None)
        return nb if nb is not None else 0

    # -- intercepted send-side entries ------------------------------------
    def isend(self, comm, buf, count, dtype, dst, tag, **kw):
        self._count(comm, dst, self._nbytes(buf, count, dtype),
                    kw.get("collective", False))
        return self._inner.isend(comm, buf, count, dtype, dst, tag, **kw)

    def send(self, comm, buf, count, dtype, dst, tag, **kw):
        self._count(comm, dst, self._nbytes(buf, count, dtype),
                    kw.get("collective", False))
        return self._inner.send(comm, buf, count, dtype, dst, tag, **kw)

    def isend_obj(self, comm, obj, dst, tag, **kw):
        self._count(comm, dst, 0, kw.get("collective", False))
        return self._inner.isend_obj(comm, obj, dst, tag, **kw)

    def send_obj(self, comm, obj, dst, tag, **kw):
        self._count(comm, dst, 0, kw.get("collective", False))
        return self._inner.send_obj(comm, obj, dst, tag, **kw)

    # -- everything else passes through -----------------------------------
    def __getattr__(self, name):
        return getattr(self._inner, name)


def install() -> MonitoringPml:
    """Wrap the currently-selected PML (idempotent)."""
    from ompi_tpu import pml

    cur = pml.current()
    if isinstance(cur, MonitoringPml):
        return cur
    mon = MonitoringPml(cur)
    pml.set_current(mon)
    return mon


def installed() -> Optional[MonitoringPml]:
    """Find the monitoring layer anywhere in the interposition stack."""
    from ompi_tpu import pml

    cur = pml.instance()
    while cur is not None:
        if isinstance(cur, MonitoringPml):
            return cur
        cur = getattr(cur, "_inner", None)
    return None


def uninstall() -> None:
    from ompi_tpu import pml

    cur = pml.instance()
    if isinstance(cur, MonitoringPml):
        pml.set_current(cur._inner)


def matrix(collective: bool = False) -> Dict[int, Tuple[int, int]]:
    """Send-side traffic matrix {peer_world_rank: (msgs, bytes)}."""
    mon = installed()
    if mon is None:
        return {}
    table = mon.coll if collective else mon.p2p
    return {peer: tuple(cell) for peer, cell in sorted(table.items())}


def dump() -> None:
    """common/monitoring-style matrix dump to the output stream."""
    mon = installed()
    if mon is None:
        _out.verbose(0, "monitoring not installed")
        return
    from ompi_tpu.runtime import rte

    for label, table in (("p2p", mon.p2p), ("coll", mon.coll)):
        for peer, (msgs, nbytes) in sorted(table.items()):
            _out.verbose(
                0, "rank %d -> %d [%s]: %d msgs, %d bytes",
                rte.rank, peer, label, msgs, nbytes)
