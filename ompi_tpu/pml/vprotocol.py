"""pml/v + vprotocol/pessimist — sender-based message logging.

Reference: ompi/mca/pml/v (469 LoC) + vprotocol/pessimist (3,224 LoC):
an interposition PML that (a) keeps a copy of every sent message in the
sender's volatile memory (sender-based logging), and (b) logs every
nondeterministic event outcome — which source/tag a receive actually
matched, in completion order (the "determinants") — to stable storage.
After a failure, a restarted process replays: peers re-send from their
send logs and the process consumes them in the recorded determinant
order, reconstructing its pre-crash state without coordinated
checkpoints (uncoordinated recovery).

Scope here: the logging planes and the replay channel — install(),
per-peer send logs with resend(), determinant capture with optional
disk persistence, and truncation on acknowledged progress. Process
re-spawn itself rides the ULFM + connect/accept machinery
(ompi_tpu.ft, ompi_tpu.comm.intercomm); the recovery *protocol* is the
application/runtime policy layered on these, as in the reference where
pml/v supplies mechanism and the fault-tolerance runtime drives it.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional, Tuple

from ompi_tpu.core import cvar, output, pvar

_out = output.stream("vprotocol")

_enable_var = cvar.register(
    "pml_v", False, bool,
    help="Install the message-logging interposition PML at init "
         "(reference: pml/v + vprotocol/pessimist).", level=7)
_dir_var = cvar.register(
    "vprotocol_log_dir", "", str,
    help="Directory for determinant logs (stable storage). Empty = "
         "memory only (volatile, like the reference's sender log; "
         "determinants then survive only with the process).", level=7)


class VprotocolPml:
    """Wraps the selected PML; logs sends + recv determinants."""

    def __init__(self, inner) -> None:
        self._inner = inner
        self._lock = threading.Lock()
        # sender-based log: dst world rank -> [(kind, comm_cid, tag,
        # payload)] in send order; kind 'buf' payload = (bytes, dtype
        # str, count) | kind 'obj' payload = object
        self.send_log: Dict[int, List[Tuple]] = {}
        # determinants: completion-order (source, tag, count) of every
        # receive — the nondeterministic outcomes
        self.determinants: List[Tuple[int, int, int]] = []
        self._det_fh = None
        d = _dir_var.get()
        if d:
            from ompi_tpu.runtime import rte

            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"det_{rte.jobid}_{rte.rank}.log")
            self._det_fh = open(path, "ab")

    # -- send side: log a copy (sender-based logging) ---------------------
    def _world(self, comm, dst: int) -> int:
        g = comm.remote_group if getattr(comm, "is_inter", False) \
            else comm.group
        try:
            return g.ranks[dst]
        except (IndexError, TypeError):
            return dst

    def _log_send(self, comm, dst: int, entry: Tuple) -> None:
        if dst < 0:
            return
        with self._lock:
            self.send_log.setdefault(
                self._world(comm, dst), []).append(entry)
        pvar.record("vprotocol_logged_sends")

    def isend(self, comm, buf, count, dtype, dst, tag, **kw):
        import numpy as np

        if kw.get("collective"):
            # collective-internal rounds are deterministically
            # re-executed on recovery, never replayed (the reference
            # logs application messages only)
            return self._inner.isend(comm, buf, count, dtype, dst,
                                     tag, **kw)
        arr = np.ascontiguousarray(buf) if buf is not None else None
        if arr is not None:
            self._log_send(comm, dst, (
                "buf", comm.cid, tag,
                (arr.tobytes(), arr.dtype.str, count)))
        return self._inner.isend(comm, buf, count, dtype, dst, tag, **kw)

    def send(self, comm, buf, count, dtype, dst, tag, **kw):
        req = self.isend(comm, buf, count, dtype, dst, tag, **kw)
        return req.wait()

    def isend_obj(self, comm, obj, dst, tag, **kw):
        if not kw.get("collective"):
            self._log_send(comm, dst, ("obj", comm.cid, tag, obj))
        return self._inner.isend_obj(comm, obj, dst, tag, **kw)

    def send_obj(self, comm, obj, dst, tag, **kw):
        return self.isend_obj(comm, obj, dst, tag, **kw).wait()

    # -- recv side: determinant capture -----------------------------------
    def _record_det(self, req) -> None:
        det = (req.status.source, req.status.tag, req.status.count)
        with self._lock:
            self.determinants.append(det)
            if self._det_fh is not None:
                pickle.dump(det, self._det_fh)
                self._det_fh.flush()

    def _capture(self, req):
        if req.completed:
            # matched synchronously from the unexpected queue inside
            # the inner irecv — the outcome is already determined
            self._record_det(req)
            return req
        orig_complete = req.complete

        def complete(error: int = 0):
            orig_complete(error)
            self._record_det(req)

        req.complete = complete
        return req

    def irecv(self, comm, buf, count, dtype, src, tag, **kw):
        req = self._inner.irecv(comm, buf, count, dtype, src, tag, **kw)
        return req if kw.get("collective") else self._capture(req)

    def irecv_obj(self, comm, src, tag, **kw):
        req = self._inner.irecv_obj(comm, src, tag, **kw)
        return req if kw.get("collective") else self._capture(req)

    def recv(self, comm, buf, count, dtype, src, tag, **kw):
        return self.irecv(comm, buf, count, dtype, src, tag, **kw).wait()

    def recv_obj(self, comm, src, tag, **kw):
        req = self.irecv_obj(comm, src, tag, **kw)
        req.wait()
        return req._obj

    # -- replay channel ----------------------------------------------------
    def resend(self, peer_world: int, comm) -> int:
        """Re-transmit every logged message for a recovering peer, in
        original order (the pessimist replay: the peer consumes them
        guided by its determinant log). Returns messages resent."""
        import numpy as np

        with self._lock:
            entries = list(self.send_log.get(peer_world, ()))
        g = comm.remote_group if getattr(comm, "is_inter", False) \
            else comm.group
        dst = g.ranks.index(peer_world)
        n = 0
        for kind, cid, tag, payload in entries:
            if cid != comm.cid:
                continue
            if kind == "buf":
                raw, dtstr, count = payload
                arr = np.frombuffer(raw, dtype=np.dtype(dtstr))
                self._inner.send(comm, arr, count, None, dst, tag)
            else:
                self._inner.send_obj(comm, payload, dst, tag)
            n += 1
        pvar.record("vprotocol_resends", n)
        return n

    def truncate(self, peer_world: int,
                 keep_last: int = 0) -> None:
        """Garbage-collect the send log for a peer once its progress
        is known stable (the reference truncates on checkpoint/ack)."""
        with self._lock:
            log = self.send_log.get(peer_world)
            if log is not None:
                del log[:len(log) - keep_last]

    # -- passthrough -------------------------------------------------------
    def __getattr__(self, name):
        return getattr(self._inner, name)


def install() -> VprotocolPml:
    from ompi_tpu import pml

    cur = pml.current()
    if isinstance(cur, VprotocolPml):
        return cur
    v = VprotocolPml(cur)
    pml.set_current(v)
    return v


def installed() -> Optional[VprotocolPml]:
    """Find the vprotocol layer anywhere in the interposition stack
    (other layers, e.g. pml/monitoring, may wrap it)."""
    from ompi_tpu import pml

    cur = pml.instance()
    while cur is not None:
        if isinstance(cur, VprotocolPml):
            return cur
        cur = getattr(cur, "_inner", None)
    return None


def load_determinants(jobid: str, rank: int) -> List[Tuple]:
    """Read a (possibly dead) rank's persisted determinant log."""
    d = _dir_var.get()
    if not d:
        return []
    path = os.path.join(d, f"det_{jobid}_{rank}.log")
    out: List[Tuple] = []
    try:
        with open(path, "rb") as fh:
            while True:
                out.append(pickle.load(fh))
    except (FileNotFoundError, EOFError):
        pass
    return out
