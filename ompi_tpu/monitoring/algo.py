"""Algorithmic byte accounting for device collectives.

``coll/xla`` never moves bytes through the pml, so the matrix core
cannot observe collective traffic by interposition the way
``common/monitoring`` does on the host path. Instead each collective
launch *declares* the bytes its algorithm moves per peer, given the
(op, rank, comm size, payload size). The models below follow the
lowering the XLA TPU compiler actually uses on an ICI torus (and the
classic algorithms the reference's ``coll/tuned`` tables assume):

- ring **reduce_scatter** / **allgather**: n-1 steps, each rank sends
  1/n of the payload to its ring successor per step -> (n-1)/n * B
  to peer (rank+1) % n.
- **allreduce** = reduce_scatter + allgather -> 2 * (n-1)/n * B on
  the same ring edge (the bandwidth-optimal rotated-pincer/ring
  family).
- **bcast** / **reduce** / **scan**: pipelined ring/chain -> each
  interior rank forwards the full payload B one hop.
- **alltoall(v)**: direct pairwise exchange, *actual* splits — the v
  variant records the exact per-destination row bytes, which is what
  makes the EP expert-imbalance matrix honest under skew.
- **barrier**: modeled as a 4-byte allreduce.

All models count SEND-side bytes only (the merge transposes for the
receive view), and return {} for size-1 comms and unknown ops — an
unknown op under-counts rather than guesses.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

# Ops whose ring lowering sends (n-1)/n of the payload one hop.
_RING_FRACTION = frozenset((
    "allgather", "allgatherv", "allgather_multi",
    "reduce_scatter", "reduce_scatter_block", "reduce_scatter_multi",
))

# Bandwidth-optimal allreduce = reduce_scatter + allgather.
_RS_AG = frozenset(("allreduce", "allreduce_multi"))

# Pipelined chain ops: forward the full payload one hop.
_PIPELINE = frozenset(("bcast", "reduce", "scan", "exscan"))

BARRIER_BYTES = 4


def log2_bucket(nbytes: int) -> int:
    """log2 size bucket for the (op, bucket, dtype, mesh) record key
    — the granularity coll/tuned switchpoint tables select on."""
    b = 0
    n = int(nbytes)
    while n > 1:
        n >>= 1
        b += 1
    return b


def pallas_per_peer(op: str, algorithm: str, rank: int, n: int,
                    nbytes: int) -> Dict[int, float]:
    """Bytes `rank` SENDS per peer for one coll/pallas launch — the
    explicit hand-rolled schedules, not the XLA-lowering model above:

    - ``ring``: every step sends 1/n of the payload to the clockwise
      successor -> (n-1)/n * B to (rank+1) % n (doubled for allreduce
      = reduce_scatter + allgather, exactly like the ring model).
    - ``bidir``: half the rows travel each ring direction -> the same
      total split evenly between (rank+1) % n and (rank-1) % n.
    - ``linear``: the rank-order fold gathers every contribution, so
      this rank ships its full block n-1 times along the ring edge
      (the ``lax.all_gather`` transport coll/xla's fold uses too).

    coll/pallas passes the result as ``TrafficMatrix.coll``'s
    ``per_peer=`` override so level-2 ICI link attribution stays
    exact for the new backend instead of falling back to the
    XLA-lowering guess."""
    if n <= 1:
        return {}
    nxt, prv = (rank + 1) % n, (rank - 1) % n
    if algorithm == "linear":
        return {nxt: float(nbytes) * (n - 1)}
    mult = 2.0 if op in _RS_AG else 1.0
    total = mult * nbytes * (n - 1) / n
    if algorithm == "bidir":
        return {nxt: total / 2.0, prv: total / 2.0}
    return {nxt: total}


def rma_per_peer(rank: int, edges, itemsize: int) -> Dict[int, float]:
    """Bytes `rank` SENDS per peer for one osc/pallas fence flush.

    ``edges`` are (sender, receiver, nelems) wire descriptors over
    comm-local ranks — puts flow origin->target, gets target->origin,
    so the caller hands BOTH directions pre-oriented. Only this
    rank's outgoing edges count (send-side accounting, like every
    model here), self-edges never touch a link, and the result feeds
    ``TrafficMatrix.count`` so level-2 ICI link attribution walks the
    CartTopo routes for RMA exactly as it does for collectives."""
    out: Dict[int, float] = {}
    for s, d, n in edges:
        if s == rank and d != rank:
            out[d] = out.get(d, 0.0) + float(n) * float(itemsize)
    return out


def hier_level_bytes(op: str, n_dcn: int, n_ici: int,
                     nbytes: int, linear: bool = False):
    """(ici_bytes, dcn_bytes) one rank moves for a coll/hier launch —
    the two-level schedules' send-side transport models:

    - split-level **allreduce**: ICI ring reduce_scatter + allgather
      on the full payload (2 * (n_ici-1)/n_ici * B); the DCN phase
      allreduces the 1/n_ici chunk (2 * (B/n_ici) * (n_dcn-1)/n_dcn)
      — the whole point of the composition: DCN carries <= B/n_ici.
    - **reduce_scatter** family: one scatter per level, same chunk
      shrink; **allgather** family inverts it (DCN gathers the shard,
      ICI replicates the n_dcn-fold row).
    - **alltoall**: each byte crosses each level at most once.
    - **bcast**: one DCN column hop + the full ICI fanout row.
    - ``linear`` (the rank-order fold): gather transport — DCN ships
      the block to n_dcn-1 group peers, ICI replicates the gathered
      n_dcn-stack to n_ici-1 row peers.

    Unknown ops return (0, 0) — under-count rather than guess, like
    :func:`per_peer`."""
    b = float(nbytes)
    if n_dcn <= 1 or n_ici <= 1:
        return (0.0, 0.0)
    if linear:
        return (b * n_dcn * (n_ici - 1), b * (n_dcn - 1))
    if op in _RS_AG:
        return (2.0 * b * (n_ici - 1) / n_ici,
                2.0 * (b / n_ici) * (n_dcn - 1) / n_dcn)
    if op in ("reduce_scatter", "reduce_scatter_block",
              "reduce_scatter_multi"):
        return (b * (n_ici - 1) / n_ici,
                (b / n_ici) * (n_dcn - 1) / n_dcn)
    if op in ("allgather", "allgatherv", "allgather_multi"):
        return (b * n_dcn * (n_ici - 1) / n_ici,
                b * (n_dcn - 1) / n_dcn)
    if op == "alltoall":
        return (b * (n_ici - 1) / n_ici, b * (n_dcn - 1) / n_dcn)
    if op == "bcast":
        return (b, b * (n_dcn - 1) / n_dcn)
    return (0.0, 0.0)


#: bytes/element of the compressed-DCN wire formats — a literal copy
#: of the util.jaxcompat table, kept here so this accounting module
#: stays import-free (no jax/ml_dtypes just to model bytes)
WIRE_ITEMSIZE = {"bf16": 2.0, "fp8_e4m3": 1.0, "fp8_e5m2": 1.0}

#: scale-factor exchange cost of one fp8 launch (a 4-byte pmax over
#: the DCN axis inside the same program)
_FP8_SCALE_BYTES = 4.0

#: ops whose compressed-DCN transport the hier plane implements
_WIRE_OPS = _RS_AG | frozenset((
    "reduce_scatter", "reduce_scatter_block", "reduce_scatter_multi"))


def hier_wire_bytes(op: str, n_dcn: int, n_ici: int, nbytes: int,
                    wire: Optional[str] = None,
                    itemsize: int = 0, linear: bool = False) -> float:
    """ACTUAL DCN bytes one rank moves for a coll/hier launch — the
    figure ``hier_dcn_wire_bytes`` records next to the nominal model
    of :func:`hier_level_bytes`. Equal to the nominal DCN bytes for an
    exact launch (``wire`` None/unknown, linear fold, or unknown
    ``itemsize``); compressed launches transmit the ICI shard once in
    the wire dtype (gather + local upcast-sum replaces the exact
    phase's reduce_scatter+allgather pair), so:

    - allreduce family: ``(B·f/n_ici)·(n_dcn-1)/n_dcn`` with
      ``f = wire_itemsize/itemsize`` — nominal × f/2 (bf16 ¼, fp8 ⅛).
    - reduce_scatter family: nominal × f (bf16 ½, fp8 ¼).
    - fp8 adds the 4-byte scale-factor pmax.
    """
    _ici, dcn = hier_level_bytes(op, n_dcn, n_ici, nbytes,
                                 linear=linear)
    w = WIRE_ITEMSIZE.get(wire or "")
    if w is None or linear or itemsize <= 0 or op not in _WIRE_OPS:
        return dcn
    f = w / float(itemsize)
    wired = dcn * f / 2.0 if op in _RS_AG else dcn * f
    if str(wire).startswith("fp8"):
        wired += _FP8_SCALE_BYTES
    return wired


def hier_per_peer(op: str, rank: int, n_dcn: int, n_ici: int,
                  nbytes: int, linear: bool = False,
                  wire: Optional[str] = None,
                  itemsize: int = 0) -> Dict[int, float]:
    """Bytes `rank` SENDS per comm-local peer for one coll/hier
    launch, split by level: the ICI share rides the intra-slice ring
    edge (rank's row successor), the DCN share the inter-slice edge
    (same column, next slice) — so the link map separates fast-axis
    from slow-axis load instead of smearing both onto one flat ring
    edge. ``wire``/``itemsize`` charge the DCN edge the ACTUAL
    (compressed) transmit bytes of :func:`hier_wire_bytes`."""
    ici_b, _nom = hier_level_bytes(op, n_dcn, n_ici, nbytes,
                                   linear=linear)
    dcn_b = hier_wire_bytes(op, n_dcn, n_ici, nbytes, wire=wire,
                            itemsize=itemsize, linear=linear)
    if not ici_b and not dcn_b:
        return {}
    s, j = divmod(rank, n_ici)
    out: Dict[int, float] = {}
    if ici_b:
        out[s * n_ici + (j + 1) % n_ici] = float(ici_b)
    if dcn_b:
        peer = ((s + 1) % n_dcn) * n_ici + j
        out[peer] = out.get(peer, 0.0) + float(dcn_b)
    return out


def per_peer(op: str, rank: int, n: int, nbytes: int,
             root: int = 0,
             counts: Optional[Sequence[int]] = None,
             row_bytes: float = 0.0) -> Dict[int, float]:
    """Bytes `rank` SENDS per peer (comm-local ranks) for one launch
    of `op` over an n-rank comm moving `nbytes` of payload.

    `counts`/`row_bytes` give alltoallv its actual splits: bytes to
    peer r = counts[r] * row_bytes. `root` shapes the rooted ops.
    """
    if n <= 1:
        return {}
    nxt = (rank + 1) % n
    if op in _RING_FRACTION:
        return {nxt: nbytes * (n - 1) / n}
    if op in _RS_AG:
        return {nxt: 2.0 * nbytes * (n - 1) / n}
    if op == "barrier":
        return {nxt: 2.0 * BARRIER_BYTES * (n - 1) / n}
    if op in _PIPELINE:
        if op in ("scan", "exscan"):
            # Chain, not ring: the last rank has no successor.
            return {rank + 1: float(nbytes)} if rank < n - 1 else {}
        if op == "bcast":
            # Ring pipeline rooted at `root`; the rank whose successor
            # is the root closes the ring without sending.
            return {} if nxt == root else {nxt: float(nbytes)}
        # reduce: chain toward the root; model the common
        # one-hop-forward cost for every non-root rank.
        return {} if rank == root else {nxt: float(nbytes)}
    if op in ("gather", "gatherv"):
        return {} if rank == root else {root: float(nbytes)}
    if op in ("scatter", "scatterv"):
        if rank != root:
            return {}
        if counts is not None:
            return {r: counts[r] * row_bytes
                    for r in range(n) if r != rank and counts[r]}
        chunk = nbytes / n
        return {r: chunk for r in range(n) if r != rank}
    if op == "alltoall":
        chunk = nbytes / n
        return {r: chunk for r in range(n) if r != rank}
    if op == "alltoallv":
        # Explicit splits required (the skew-honest path). Neighbor
        # collectives bypass this table entirely: their graph edges
        # come from the comm topology, so the instrumentation sites
        # hand the matrix explicit per-peer dicts.
        if counts is None:
            return {}
        return {r: counts[r] * row_bytes
                for r in range(n) if r != rank and counts[r]}
    return {}
