"""CLI: merge per-rank matrix dumps into heatmap + hotspot reports.

    python -m ompi_tpu.monitoring report mon_r0.json mon_r1.json
    python -m ompi_tpu.monitoring report --json merged.json --top 10 \
        mon_r*.json

Inputs are the Finalize-time dumps ``--mca monitoring_dump
'/tmp/mon_r{rank}.json'`` writes (schema
``ompi_tpu.monitoring.matrix/1``). Missing or corrupt input: one
line on stderr, exit 1 — same contract as the trace merge CLI.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ompi_tpu.monitoring import merge, report


def _cmd_report(args) -> int:
    docs = []
    try:
        for path in args.inputs:
            with open(path) as fh:
                docs.append(json.load(fh))
        merged = merge.merge(docs)
    except OSError as exc:
        print(f"monitoring report: {exc}", file=sys.stderr)
        return 1
    except (json.JSONDecodeError, KeyError, TypeError,
            ValueError) as exc:
        print("monitoring report: corrupt matrix input: "
              f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return 1
    print(report.render(merged, top=args.top))
    if args.json:
        try:
            with open(args.json, "w") as fh:
                json.dump(merged, fh, indent=1)
        except OSError as exc:
            print(f"monitoring report: {exc}", file=sys.stderr)
            return 1
        print(f"merged matrix written: {args.json}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.monitoring",
        description="merge/report ompi_tpu traffic matrices")
    sub = ap.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser(
        "report", help="rank-by-rank + per-link heatmaps with top-N "
                       "hotspot ranking from per-rank matrix dumps")
    r.add_argument("inputs", nargs="+",
                   help="per-rank monitoring_dump JSON files")
    r.add_argument("--json", default="",
                   help="also write the merged matrix JSON artifact")
    r.add_argument("--top", type=int, default=5,
                   help="hotspot rows to print (default 5)")
    r.set_defaults(fn=_cmd_report)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
