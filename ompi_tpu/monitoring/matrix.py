"""The traffic-matrix core (guarded global: ``TRAFFIC``).

One ``TrafficMatrix`` per rank, live only while the plane is enabled
(``monitoring_level >= 1``). Every instrumented site follows the
repo's one-branch guard discipline:

    tm = _matrix.TRAFFIC
    if tm is not None:
        tm.count("p2p", world_dst, nbytes)

so a disabled plane costs exactly one attribute load + one branch
(the same contract FLIGHT / RECORDER / SANITIZER keep, enforced by
the ``unguarded-observability`` lint rule).

Counting is SEND-side only, per the reference ``common/monitoring``
design: each rank records what *it* transmits, and the cross-rank
merge recovers the receive view as the transpose (and checks the two
agree — see :mod:`merge`). Cells are per-(dst, ctx) with ctx one of
``p2p`` (pml host sends), ``coll`` (algorithmic device-collective
accounting, :mod:`algo`), ``osc`` (one-sided service traffic), and
``part`` (partitioned chunk sends).

Everything lands on the pvar plane twice: per-context totals under
literal names (``monitoring_p2p_bytes`` ...) and per-cell dynamic
families (``monitoring_tx_bytes_s0_d1_p2p`` ...) that
``telemetry.openmetrics`` decodes into labeled OpenMetrics series —
which also makes kvstore rollup inclusion automatic.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ompi_tpu.core import pvar
from ompi_tpu.errors import ERR_RANK, MPIError
from ompi_tpu.monitoring import algo
from ompi_tpu.monitoring.links import Link, LinkMap, link_name
from ompi_tpu.pml.request import ANY_SOURCE, PROC_NULL

CTXS = ("p2p", "coll", "osc", "part")

# Bounded per-link time series for the Perfetto counter tracks: the
# plane is an accountant, not a tracer — cap the memory it can hold.
SERIES_CAP = 4096

TRAFFIC: Optional["TrafficMatrix"] = None


def world_rank(comm, peer: int) -> int:
    """Translate a comm-local peer to its world rank through the
    (remote, for inter-communicators) group — MPI_Group_translate_
    ranks against WORLD, as the reference monitoring_translate does.
    Raises MPIError(ERR_RANK) on a genuinely invalid peer instead of
    silently misattributing the traffic."""
    if peer in (PROC_NULL, ANY_SOURCE):
        return peer
    g = comm.remote_group if getattr(comm, "is_inter", False) \
        else comm.group
    ranks = getattr(g, "ranks", None)
    if ranks is None:  # groupless comm stub: local rank IS world rank
        return peer
    if not 0 <= peer < len(ranks):
        raise MPIError(
            ERR_RANK,
            f"invalid peer {peer} for monitoring translation "
            f"(group size {len(ranks)})")
    return ranks[peer]


class TrafficMatrix:
    """Per-rank send-side traffic matrix + link attribution state."""

    def __init__(self, rank: int, level: int, nranks: int):
        self.rank = int(rank)
        self.level = int(level)
        self.nranks = max(int(nranks), 1)
        self.lock = threading.Lock()
        # ctx -> dst(world) -> [msgs, bytes, latency_ns]
        self.tables: Dict[str, Dict[int, List[float]]] = \
            {c: {} for c in CTXS}
        # (op, log2 size bucket, dtype, mesh shape) -> [launches, bytes]
        self.coll_records: Dict[Tuple[str, int, str, Tuple[int, ...]],
                                List[float]] = {}
        # coll/hier per-level totals:
        # op -> [launches, ici_b, dcn_b, dcn_wire_b] — dcn_b is the
        # nominal (accumulate-dtype) model, dcn_wire_b what the wire
        # actually carried (equal unless the DCN phase is compressed)
        self.hier_levels: Dict[str, List[float]] = {}
        # serve/ plane per-policy accounting: policy -> counters +
        # log2(ns) latency histogram ({bucket: requests}) — the
        # [serve] report section's feed. Doc state only: the serve
        # plane records its pvars at the dispatch/loop sites, so this
        # table never double-counts.
        self.serve: Dict[str, Dict[str, object]] = {}
        self.link_bytes: Dict[Link, float] = {}
        self.expert: Dict[int, int] = {}
        self.series: List[Tuple[int, str, float]] = []
        self.linkmap: Optional[LinkMap] = \
            LinkMap.for_world(self.nranks) if level >= 2 else None

    # -- core cell update --------------------------------------------------

    def count(self, ctx: str, dst: int, nbytes: float,
              msgs: int = 1, ns: int = 0) -> None:
        """Record `msgs` sends totalling `nbytes` to world rank `dst`
        in context `ctx` (dst may be PROC_NULL: dropped here so call
        sites stay branch-free)."""
        if dst < 0:
            return
        nbytes = float(nbytes)
        with self.lock:
            cell = self.tables[ctx].get(dst)
            if cell is None:
                cell = self.tables[ctx][dst] = [0, 0.0, 0]
            cell[0] += msgs
            cell[1] += nbytes
            cell[2] += ns
        b = int(nbytes)
        pvar.record(f"monitoring_{ctx}_msgs", msgs)
        pvar.record(f"monitoring_{ctx}_bytes", b)
        pvar.record("monitoring_msgs", msgs)
        pvar.record("monitoring_bytes", b)
        pvar.record(f"monitoring_tx_msgs_s{self.rank}_d{dst}_{ctx}",
                    msgs)
        pvar.record(f"monitoring_tx_bytes_s{self.rank}_d{dst}_{ctx}",
                    b)
        if self.linkmap is not None:
            self._attribute({dst: nbytes})

    # -- collective launches (algorithmic accounting) ----------------------

    def coll(self, op: str, comm, nbytes: float, dtype: str = "",
             root: int = 0,
             per_peer: Optional[Dict[int, float]] = None,
             counts: Optional[Sequence[int]] = None,
             row_bytes: float = 0.0, ctx: str = "coll") -> None:
        """Account one collective launch: bytes this rank's share of
        the algorithm sends per peer (either the explicit `per_peer`
        comm-local dict, or the :mod:`algo` model for `op`), recorded
        into the `ctx` table after world-rank translation, plus the
        (op, size-bucket, dtype, mesh) record switchpoint tables
        derive from."""
        n = comm.size
        me = comm.rank
        if per_peer is None:
            per_peer = algo.per_peer(op, me, n, nbytes, root=root,
                                     counts=counts,
                                     row_bytes=row_bytes)
        mesh = self._mesh_shape(comm)
        key = (op, algo.log2_bucket(int(nbytes)), str(dtype), mesh)
        with self.lock:
            rec = self.coll_records.get(key)
            if rec is None:
                rec = self.coll_records[key] = [0, 0.0]
            rec[0] += 1
            rec[1] += float(nbytes)
        pvar.record("monitoring_coll_launches", 1)
        for peer, b in per_peer.items():
            self.count(ctx, world_rank(comm, peer), b)

    def hier(self, op: str, ici_bytes: float, dcn_bytes: float,
             dcn_wire_bytes: Optional[float] = None) -> None:
        """Account one coll/hier launch's per-level byte split — the
        table that lets the report answer "which level is the
        bottleneck" (the per-peer spatial view goes through
        :meth:`coll` separately). ``dcn_wire_bytes`` is the actual
        transmitted DCN figure (defaults to nominal = exact launch);
        the report recomputes its verdict from it."""
        if dcn_wire_bytes is None:
            dcn_wire_bytes = dcn_bytes
        with self.lock:
            rec = self.hier_levels.get(op)
            if rec is None:
                rec = self.hier_levels[op] = [0, 0.0, 0.0, 0.0]
            rec[0] += 1
            rec[1] += float(ici_bytes)
            rec[2] += float(dcn_bytes)
            rec[3] += float(dcn_wire_bytes)

    def serve_event(self, policy: str, *, requests: int = 0,
                    tokens: int = 0, kept: int = 0, rerouted: int = 0,
                    dropped: int = 0, dcn_tokens: int = 0,
                    dcn_bytes: int = 0, lat_ns: int = 0) -> None:
        """Accumulate one serve-plane event under its dispatch
        policy: the Dispatcher reports token accounting per dispatch,
        the decode loop reports request count + wall latency (log2-ns
        histogram bucket). Both call sites, one table — the report's
        ``[serve]`` section reads it whole."""
        with self.lock:
            rec = self.serve.get(policy)
            if rec is None:
                rec = self.serve[policy] = {
                    "requests": 0, "tokens": 0, "kept": 0,
                    "rerouted": 0, "dropped": 0, "dcn_tokens": 0,
                    "dcn_bytes": 0, "lat_ns": {}}
            rec["requests"] += int(requests)
            rec["tokens"] += int(tokens)
            rec["kept"] += int(kept)
            rec["rerouted"] += int(rerouted)
            rec["dropped"] += int(dropped)
            rec["dcn_tokens"] += int(dcn_tokens)
            rec["dcn_bytes"] += int(dcn_bytes)
            if lat_ns > 0:
                b = int(lat_ns).bit_length()
                hist = rec["lat_ns"]
                hist[b] = hist.get(b, 0) + 1

    @staticmethod
    def _mesh_shape(comm) -> Tuple[int, ...]:
        dc = getattr(comm, "_device_comm", None)
        mesh = getattr(dc, "mesh", None)
        if mesh is not None:
            try:
                return tuple(int(d) for d in mesh.devices.shape)
            except Exception:  # noqa: BLE001 — shape is best-effort
                pass
        return (int(comm.size),)

    # -- link attribution (level 2) ----------------------------------------

    def _attribute(self, world_bytes: Dict[int, float]) -> None:
        lm = self.linkmap
        if lm is None:
            return
        with self.lock:
            for dst, b in world_bytes.items():
                lm.charge(self.link_bytes, self.rank, dst, b)
            loads = dict(self.link_bytes)
        for link, total in loads.items():
            d, a, bb = link
            pvar.record_hwm(
                f"monitoring_link_bytes_d{d}_r{a}_r{bb}", int(total))
        pvar.record_hwm("monitoring_link_imbalance_permille",
                        int(LinkMap.imbalance(loads) * 1000))
        hot = LinkMap.hottest(loads)
        if hot:
            from ompi_tpu.trace import recorder as _rec

            with self.lock:
                self.series.append(
                    (_rec.now(), link_name(hot[0][0]), hot[0][1]))
                if len(self.series) > SERIES_CAP:
                    del self.series[:len(self.series) - SERIES_CAP]

    # -- expert load (EP alltoall path; ROADMAP item 5 feed) ---------------

    def expert_tokens(self, counts: Sequence[int]) -> None:
        """Per-expert routed-token counts from one EP dispatch; expert
        identity is the destination shard index."""
        total = 0
        with self.lock:
            for e, c in enumerate(counts):
                c = int(c)
                if c <= 0:
                    continue
                self.expert[e] = self.expert.get(e, 0) + c
                total += c
        for e, c in enumerate(counts):
            if int(c) > 0:
                pvar.record(f"monitoring_expert_tokens_e{e}", int(c))
        if total:
            pvar.record("monitoring_expert_tokens", total)

    # -- views --------------------------------------------------------------

    def peer_totals(self, ctx: Optional[str] = None
                    ) -> Dict[int, Tuple[int, int]]:
        """{world dst: (msgs, bytes)} for one ctx, or all ctxs summed
        — the shape pml/monitoring.matrix() has always returned."""
        out: Dict[int, List[float]] = {}
        with self.lock:
            tables = [self.tables[ctx]] if ctx else \
                list(self.tables.values())
            for t in tables:
                for dst, (m, b, _ns) in t.items():
                    cell = out.setdefault(dst, [0, 0.0])
                    cell[0] += m
                    cell[1] += b
        return {d: (int(m), int(b)) for d, (m, b) in out.items()}

    def hotspot(self) -> Optional[Dict[str, object]]:
        """Hottest-link summary for the watchdog hang dump: the link,
        its load, this rank's ICI neighbors, and the heaviest peer."""
        with self.lock:
            loads = dict(self.link_bytes)
        lm = self.linkmap
        doc: Dict[str, object] = {}
        peers = self.peer_totals()
        if peers:
            top = max(peers.items(), key=lambda kv: kv[1][1])
            doc["top_peer"] = {"rank": top[0], "bytes": top[1][1],
                               "msgs": top[1][0]}
        if lm is not None:
            doc["neighbors"] = lm.neighbors(self.rank)
            hot = LinkMap.hottest(loads)
            if hot:
                doc["hottest_link"] = {
                    "name": link_name(hot[0][0]),
                    "dim": hot[0][0][0],
                    "ranks": [hot[0][0][1], hot[0][0][2]],
                    "bytes": int(hot[0][1]),
                }
        return doc or None

    def link_series(self) -> List[Tuple[int, str, float]]:
        with self.lock:
            return list(self.series)


def enable(rank: int, level: int, nranks: int) -> "TrafficMatrix":
    global TRAFFIC
    if TRAFFIC is None:
        TRAFFIC = TrafficMatrix(rank, level, nranks)
    return TRAFFIC


def disable() -> None:
    global TRAFFIC
    TRAFFIC = None
