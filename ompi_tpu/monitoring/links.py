"""ICI link attribution — matrix cells onto torus links.

Level 2 of the monitoring plane: every (src, dst) byte cell is walked
along its dimension-ordered minimal-hop route on the job's torus
(``topo.CartTopo.route``), and each traversed hop charges its bytes
to the undirected physical link it rides. The mesh shape comes from
``parallel.mesh.mesh_shape_for`` — the same near-square factorization
the device plane builds its meshes with — so host-side attribution
names the links the XLA collectives actually occupy.

Link identity is ``(dim, lo_rank, hi_rank)`` (undirected: both
directions of a bidirectional ICI link aggregate onto one counter,
which is how hotspots present — a saturated link hurts both ways).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

Link = Tuple[int, int, int]  # (dim, lo_rank, hi_rank)


def link_name(link: Link) -> str:
    d, a, b = link
    return f"d{d}:r{a}-r{b}"


class LinkMap:
    """Routing + per-link aggregation over one torus shape."""

    def __init__(self, dims: Sequence[int],
                 periods: Optional[Sequence[bool]] = None):
        from ompi_tpu.topo import CartTopo

        dims = [int(d) for d in dims if int(d) > 1] or [1]
        if periods is None:
            periods = [True] * len(dims)  # ICI axes are rings
        self.topo = CartTopo(dims, periods)
        self.dims = self.topo.dims
        self.n = self.topo.size
        self._routes: Dict[Tuple[int, int], List[Link]] = {}

    @classmethod
    def for_world(cls, n: int) -> "LinkMap":
        """The LinkMap of an n-rank job: same near-square 2D torus
        factorization the device plane uses (1D ring below 4)."""
        from ompi_tpu.parallel.mesh import mesh_shape_for

        return cls(mesh_shape_for(n, 2 if n >= 4 else 1))

    def route(self, src: int, dst: int) -> List[Link]:
        """The undirected links the src->dst route traverses
        (memoized — the route table is static for the job)."""
        key = (src, dst)
        got = self._routes.get(key)
        if got is None:
            got = [(d, min(a, b), max(a, b))
                   for a, b, d, _step in self.topo.route(src, dst)]
            self._routes[key] = got
        return got

    def neighbors(self, rank: int) -> List[int]:
        """Distinct ICI neighbors of `rank` (the watchdog names these
        next to the hottest link in a hang dump)."""
        out: List[int] = []
        for p in self.topo.neighbors(rank):
            if p >= 0 and p != rank and p not in out:
                out.append(p)
        return out

    def charge(self, loads: Dict[Link, float], src: int, dst: int,
               nbytes: float) -> None:
        """Charge `nbytes` of src->dst traffic onto every link of its
        route."""
        if src == dst or not 0 <= dst < self.n or not 0 <= src < self.n:
            return
        for link in self.route(src, dst):
            loads[link] = loads.get(link, 0.0) + nbytes

    @staticmethod
    def imbalance(loads: Dict[Link, float]) -> float:
        """max/mean link load — 1.0 is perfectly balanced; the gauge
        the plane exports as monitoring_link_imbalance_permille."""
        if not loads:
            return 0.0
        vals = list(loads.values())
        mean = sum(vals) / len(vals)
        return max(vals) / mean if mean > 0 else 0.0

    @staticmethod
    def hottest(loads: Dict[Link, float],
                top: int = 1) -> List[Tuple[Link, float]]:
        return sorted(loads.items(), key=lambda kv: (-kv[1], kv[0]))[:top]


def sum_links(parts: Iterable[Dict[Link, float]]) -> Dict[Link, float]:
    """Merge per-rank link loads (send-side charging means each rank
    contributes its own outbound routes; summing gives the job view)."""
    out: Dict[Link, float] = {}
    for p in parts:
        for link, v in p.items():
            out[link] = out.get(link, 0.0) + v
    return out
