"""Terminal + JSON reporting over merged traffic matrices.

Renders the rank×rank heatmap per context, the per-link load table
with the hottest ICI links ranked, top-N (src, dst, ctx) hotspot
cells, collective-launch records, and expert-token imbalance — the
human face of ``python -m ompi_tpu.monitoring report``.
"""

from __future__ import annotations

from typing import Dict, List

# Shade ramp for the terminal heatmap: cell byte count relative to
# the matrix max.
_RAMP = " .:-=+*#%@"


def _fmt_bytes(b: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(b) < 1024 or unit == "GiB":
            return (f"{b:.0f}{unit}" if unit == "B"
                    else f"{b:.1f}{unit}")
        b /= 1024
    return f"{b:.1f}GiB"


def heatmap_lines(rows: Dict[int, Dict[int, List[float]]],
                  nranks: int, ctx: str) -> List[str]:
    """rank×rank byte heatmap for one context: shaded cells plus the
    per-row send totals (send-side counting means row r is exactly
    what rank r transmitted)."""
    peak = max((cell[1] for row in rows.values()
                for cell in row.values()), default=0.0)
    out = [f"[{ctx}] send-side bytes, {nranks}x{nranks} "
           f"(peak cell {_fmt_bytes(peak)})"]
    hdr = "      " + "".join(f"{d:>4d}" for d in range(nranks))
    out.append(hdr + "   tx_total")
    for src in range(nranks):
        row = rows.get(src, {})
        cells = []
        total = 0.0
        for dst in range(nranks):
            b = row.get(dst, [0, 0.0])[1]
            total += b
            if src == dst:
                cells.append("   -")
            elif b <= 0:
                cells.append("   .")
            else:
                shade = _RAMP[min(len(_RAMP) - 1,
                                  int(b / peak * (len(_RAMP) - 1)))] \
                    if peak > 0 else "."
                cells.append(f"   {shade}")
        out.append(f"  r{src:<3d}" + "".join(cells) +
                   f"   {_fmt_bytes(total)}")
    return out


def link_lines(links: List[Dict[str, object]],
               imbalance: float, top: int) -> List[str]:
    if not links:
        return ["[links] no link attribution recorded "
                "(needs monitoring_level 2)"]
    peak = float(links[0]["bytes"]) or 1.0
    out = [f"[links] {len(links)} ICI links, "
           f"imbalance max/mean = {imbalance:.2f}; "
           f"hottest: {links[0]['name']} "
           f"({_fmt_bytes(float(links[0]['bytes']))})"]
    for row in links[:top]:
        b = float(row["bytes"])
        bar = "#" * max(1, int(b / peak * 40))
        out.append(f"  {row['name']:>12s} {_fmt_bytes(b):>10s} {bar}")
    return out


def hotspot_lines(merged: Dict[str, object], top: int) -> List[str]:
    cells = []
    for ctx, rows in merged.get("matrices", {}).items():
        for src, row in rows.items():
            for dst, (msgs, b) in row.items():
                cells.append((float(b), int(msgs), int(src),
                              int(dst), ctx))
    cells.sort(key=lambda c: (-c[0], c[2], c[3]))
    out = [f"[hotspots] top {min(top, len(cells))} of "
           f"{len(cells)} cells"]
    for b, msgs, src, dst, ctx in cells[:top]:
        out.append(f"  r{src} -> r{dst} [{ctx}]: "
                   f"{_fmt_bytes(b)} in {msgs} msgs")
    return out


def _hist_percentile(hist: Dict[int, int], q: float) -> float:
    """Approximate percentile in ms from a log2(ns)-bucket histogram
    (bucket upper bound — the same conservative read the trace
    plane's exporter uses)."""
    if not hist:
        return 0.0
    items = sorted((int(b), int(c)) for b, c in hist.items())
    total = sum(c for _, c in items)
    target = q / 100.0 * total
    run = 0
    for b, c in items:
        run += c
        if run >= target:
            return float(2 ** b) / 1e6
    return float(2 ** items[-1][0]) / 1e6


def serve_lines(serve: Dict[str, Dict[str, object]],
                experts: Dict[object, int], top: int) -> List[str]:
    """The serving-plane section: per-policy token accounting + tail
    latency, the per-expert load heatmap, and the hot-expert verdict
    (expert NAMED with its load share — the smoke lane greps for
    it)."""
    out: List[str] = []
    for pol, rec in sorted(serve.items()):
        toks = max(int(rec.get("tokens", 0)), 1)
        out.append(
            f"[serve] policy {pol}: {rec.get('requests', 0)} requests,"
            f" {rec.get('tokens', 0)} tokens; "
            f"kept {rec.get('kept', 0)} "
            f"({100.0 * int(rec.get('kept', 0)) / toks:.1f}%), "
            f"dropped {rec.get('dropped', 0)} "
            f"({100.0 * int(rec.get('dropped', 0)) / toks:.1f}%), "
            f"rerouted {rec.get('rerouted', 0)}, "
            f"DCN {rec.get('dcn_tokens', 0)} tokens / "
            f"{_fmt_bytes(float(rec.get('dcn_bytes', 0)))}")
        hist = rec.get("lat_ns", {})
        if hist:
            out.append(
                f"  latency ~p50 {_hist_percentile(hist, 50):.2f}ms"
                f"  ~p95 {_hist_percentile(hist, 95):.2f}ms"
                f"  ~p99 {_hist_percentile(hist, 99):.2f}ms"
                " (log2-bin upper bounds)")
    if serve and experts:
        counts = {int(e): int(c) for e, c in experts.items()}
        peak = max(counts.values())
        total = sum(counts.values()) or 1
        out.append(f"  expert load ({len(counts)} experts, "
                   f"{total} routed tokens):")
        for e in sorted(counts):
            c = counts[e]
            bar = "#" * max(1, int(c / peak * 40)) if c else ""
            out.append(f"    e{e:<3d} {c:>8d} {bar}")
        hot_e, hot_c = max(counts.items(), key=lambda kv: kv[1])
        share = hot_c / total
        fair = 1.0 / max(len(counts), 1)
        verdict = "HOT" if share >= 2.0 * fair else "balanced"
        out.append(f"  hot expert: e{hot_e} — {100.0 * share:.1f}% "
                   f"of routed tokens ({share / fair:.1f}x fair "
                   f"share, {verdict})")
    return out


def render(merged: Dict[str, object], top: int = 5) -> str:
    nranks = int(merged["nranks"])
    out: List[str] = [
        f"traffic report: {nranks} ranks, "
        f"tx {_fmt_bytes(sum(merged['tx_bytes']))} total"]
    for ctx in sorted(merged.get("matrices", {})):
        out.extend(heatmap_lines(merged["matrices"][ctx], nranks,
                                 ctx))
        skew = merged.get("transpose_skew", {}).get(ctx)
        if skew is not None:
            out.append(f"  transpose skew: {skew:.3f} "
                       "(0.0 = send/recv views agree)")
    out.extend(link_lines(merged.get("links", []),
                          float(merged.get("link_imbalance", 0.0)),
                          top))
    out.extend(hotspot_lines(merged, top))
    recs = merged.get("coll_records", [])
    if recs:
        out.append(f"[collectives] {len(recs)} (op, size-bucket, "
                   "dtype, mesh) records")
        for rec in recs[:top]:
            out.append(
                f"  {rec['op']:<22s} 2^{rec['bucket']:<2d}B "
                f"{rec['dtype'] or '?':<10s} "
                f"mesh{tuple(rec['mesh'])!r:<10} "
                f"{rec['launches']:.0f} launches "
                f"{_fmt_bytes(float(rec['bytes']))}")
    hier = merged.get("hier_levels", {})
    if hier:
        tot_ici = sum(rec[1] for rec in hier.values())
        tot_dcn = sum(rec[2] for rec in hier.values())
        # actual transmitted DCN bytes (compressed wire formats);
        # 3-element records predate compression — wire == nominal
        tot_wire = sum(rec[3] if len(rec) > 3 else rec[2]
                       for rec in hier.values())
        # which level is the bottleneck: weight the slow axis by the
        # nominal ICI/DCN bandwidth gap (order of magnitude) before
        # comparing byte loads — against what the wire ACTUALLY
        # carried, else a compressed job would keep reading DCN-bound
        if tot_dcn > 0:
            verdict = "DCN-bound" if tot_wire * 10.0 >= tot_ici \
                else "ICI-bound"
            line = (f"[hier] two-level collectives: "
                    f"ICI {_fmt_bytes(tot_ici)} / "
                    f"DCN {_fmt_bytes(tot_wire)} on the wire")
            if tot_wire < tot_dcn:
                line += (f" ({_fmt_bytes(tot_dcn)} nominal, "
                         f"{tot_dcn / max(tot_wire, 1e-9):.1f}x "
                         "compressed)")
            line += (f" (ratio {tot_ici / max(tot_wire, 1e-9):.1f}:1;"
                     f" {verdict} at a nominal 10x slower DCN)")
            out.append(line)
        else:
            out.append(f"[hier] two-level collectives: "
                       f"ICI {_fmt_bytes(tot_ici)} / DCN 0B")
        for op, rec in list(hier.items())[:top]:
            wire = float(rec[3] if len(rec) > 3 else rec[2])
            line = (f"  {op:<22s} {rec[0]:.0f} launches  "
                    f"ICI {_fmt_bytes(float(rec[1])):>10s}  "
                    f"DCN {_fmt_bytes(wire):>10s}")
            if wire < float(rec[2]):
                line += (f" (nominal "
                         f"{_fmt_bytes(float(rec[2]))})")
            out.append(line)
    experts = merged.get("expert_tokens", {})
    serve = merged.get("serve", {})
    if serve:
        out.extend(serve_lines(serve, experts, top))
    if experts:
        total = sum(experts.values()) or 1
        hot = max(experts.items(), key=lambda kv: kv[1])
        out.append(f"[experts] {len(experts)} experts, "
                   f"{total} tokens; hottest expert {hot[0]} "
                   f"({hot[1]} tokens, "
                   f"{hot[1] * len(experts) / total:.2f}x fair "
                   "share)")
    return "\n".join(out)
