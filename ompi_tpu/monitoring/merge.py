"""Cross-rank matrix merge — kvstore exchange + transpose check.

Counting is send-side (each rank records only what it transmits), so
the job-wide matrix assembles by stacking per-rank rows; the receive
view is its transpose. On a clean run the p2p/coll contexts must be
transpose-consistent for symmetric traffic patterns — the merge
computes the worst relative |M[i][j] - M[j][i]| skew per context and
reports it, which catches both lost counts and misattributed peers
(the bug class the old inter-communicator fallback hid).

Two transports: ranks publish JSON snapshot docs to the kvstore under
``mon:mat:{jobid}:{rank}`` (the telemetry rollup pattern), or dump
them as files at Finalize (``--mca monitoring_dump``) for the report
CLI to merge offline. Schema ``ompi_tpu.monitoring.matrix/1``.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ompi_tpu.monitoring.links import Link, LinkMap, link_name, sum_links

SCHEMA = "ompi_tpu.monitoring.matrix/1"


def snapshot_doc(tm) -> Dict[str, object]:
    """One rank's JSON-able matrix snapshot (keys stringified for
    JSON round-tripping; parse back with int())."""
    with tm.lock:
        tables = {ctx: {str(d): list(cell) for d, cell in t.items()}
                  for ctx, t in tm.tables.items() if t}
        coll_records = [
            {"op": op, "bucket": bucket, "dtype": dt,
             "mesh": list(mesh), "launches": rec[0],
             "bytes": rec[1]}
            for (op, bucket, dt, mesh), rec in
            sorted(tm.coll_records.items())]
        link_bytes = {link_name(k): v
                      for k, v in tm.link_bytes.items()}
        expert = {str(e): c for e, c in tm.expert.items()}
        hier = {op: list(rec)
                for op, rec in sorted(tm.hier_levels.items())}
        serve = {
            pol: {**{k: v for k, v in rec.items() if k != "lat_ns"},
                  "lat_ns": {str(b): c
                             for b, c in sorted(rec["lat_ns"].items())}}
            for pol, rec in sorted(tm.serve.items())}
    return {
        "schema": SCHEMA,
        "rank": tm.rank,
        "nranks": tm.nranks,
        "level": tm.level,
        "tables": tables,
        "coll_records": coll_records,
        "link_bytes": link_bytes,
        "expert_tokens": expert,
        "hier_levels": hier,
        "serve": serve,
    }


def _key(jobid: str, rank: int) -> str:
    return f"mon:mat:{jobid}:{rank}"


def publish(client, jobid: str, rank: int,
            doc: Dict[str, object]) -> None:
    client.put(_key(jobid, rank), json.dumps(doc))


def collect(client, jobid: str, nranks: int,
            timeout: float = 10.0) -> List[Dict[str, object]]:
    """Gather every rank's published snapshot (blocking get per rank,
    kvstore-side wait)."""
    docs = []
    for r in range(nranks):
        raw = client.get(_key(jobid, r), wait=timeout)
        docs.append(json.loads(raw))
    return docs


def _parse_link(name: str) -> Link:
    # inverse of links.link_name: "d0:r1-r3"
    d, rest = name.split(":", 1)
    a, b = rest.split("-")
    return (int(d[1:]), int(a[1:]), int(b[1:]))


def merge(docs: List[Dict[str, object]]) -> Dict[str, object]:
    """Assemble per-rank snapshots into the job view.

    Returns {ctx: {src: {dst: [msgs, bytes]}}} matrices, per-rank
    send/recv byte totals, the per-context transpose skew, summed
    link loads + imbalance + hottest link, merged collective records,
    and merged expert-token counts.
    """
    for doc in docs:
        if doc.get("schema") != SCHEMA:
            raise ValueError(
                f"not a monitoring matrix dump (schema="
                f"{doc.get('schema')!r}, want {SCHEMA!r})")
    nranks = max([int(d.get("nranks", 0)) for d in docs] +
                 [int(d["rank"]) + 1 for d in docs])
    mats: Dict[str, Dict[int, Dict[int, List[float]]]] = {}
    for doc in docs:
        src = int(doc["rank"])
        for ctx, table in doc.get("tables", {}).items():
            row = mats.setdefault(ctx, {}).setdefault(src, {})
            for dst, cell in table.items():
                got = row.setdefault(int(dst), [0, 0.0])
                got[0] += cell[0]
                got[1] += cell[1]

    tx = [0.0] * nranks
    rx = [0.0] * nranks
    for rows in mats.values():
        for src, row in rows.items():
            for dst, (_m, b) in row.items():
                tx[src] += b
                if 0 <= dst < nranks:
                    rx[dst] += b

    skew = {ctx: transpose_skew(rows) for ctx, rows in mats.items()}

    link_loads = sum_links(
        [{_parse_link(k): v
          for k, v in doc.get("link_bytes", {}).items()}
         for doc in docs])
    hot = LinkMap.hottest(link_loads, top=len(link_loads))

    coll_records: Dict[Tuple[str, int, str, Tuple[int, ...]],
                       List[float]] = {}
    for doc in docs:
        for rec in doc.get("coll_records", []):
            key = (rec["op"], int(rec["bucket"]), rec["dtype"],
                   tuple(rec["mesh"]))
            got = coll_records.setdefault(key, [0, 0.0])
            got[0] += rec["launches"]
            got[1] += rec["bytes"]

    expert: Dict[int, int] = {}
    for doc in docs:
        for e, c in doc.get("expert_tokens", {}).items():
            expert[int(e)] = expert.get(int(e), 0) + int(c)

    hier_levels: Dict[str, List[float]] = {}
    for doc in docs:
        for op, rec in doc.get("hier_levels", {}).items():
            got = hier_levels.setdefault(op, [0, 0.0, 0.0, 0.0])
            got[0] += rec[0]
            got[1] += rec[1]
            got[2] += rec[2]
            # pre-compression dumps carry 3 elements: the wire figure
            # IS the nominal one (every launch was exact)
            got[3] += rec[3] if len(rec) > 3 else rec[2]

    serve: Dict[str, Dict[str, object]] = {}
    for doc in docs:
        for pol, rec in doc.get("serve", {}).items():
            got = serve.setdefault(pol, {
                "requests": 0, "tokens": 0, "kept": 0, "rerouted": 0,
                "dropped": 0, "dcn_tokens": 0, "dcn_bytes": 0,
                "lat_ns": {}})
            for k in ("requests", "tokens", "kept", "rerouted",
                      "dropped", "dcn_tokens", "dcn_bytes"):
                got[k] += int(rec.get(k, 0))
            for b, c in rec.get("lat_ns", {}).items():
                got["lat_ns"][int(b)] = (got["lat_ns"].get(int(b), 0)
                                         + int(c))

    return {
        "schema": SCHEMA + "+merged",
        "nranks": nranks,
        "matrices": mats,
        "tx_bytes": tx,
        "rx_bytes": rx,
        "transpose_skew": skew,
        "links": [{"name": link_name(k), "bytes": v}
                  for k, v in hot],
        "link_imbalance": LinkMap.imbalance(link_loads),
        "coll_records": [
            {"op": op, "bucket": bucket, "dtype": dt,
             "mesh": list(mesh), "launches": rec[0],
             "bytes": rec[1]}
            for (op, bucket, dt, mesh), rec in
            sorted(coll_records.items())],
        "expert_tokens": expert,
        "hier_levels": {op: list(rec)
                        for op, rec in sorted(hier_levels.items())},
        "serve": {pol: dict(rec)
                  for pol, rec in sorted(serve.items())},
    }


def transpose_skew(rows: Dict[int, Dict[int, List[float]]]) -> float:
    """Worst relative |M[i][j] - M[j][i]| over byte cells — 0.0 for
    transpose-consistent (symmetric-pattern) traffic; send-side
    counting makes asymmetry here mean lost or misattributed counts
    when the pattern itself is symmetric."""
    worst = 0.0
    seen = set()
    for i, row in rows.items():
        for j in row:
            if (j, i) in seen:
                continue
            seen.add((i, j))
            a = row.get(j, [0, 0.0])[1]
            b = rows.get(j, {}).get(i, [0, 0.0])[1]
            hi = max(a, b)
            if hi > 0:
                worst = max(worst, abs(a - b) / hi)
    return worst


def exchange(tm, client, jobid: str, nranks: int,
             timeout: float = 10.0) -> Optional[Dict[str, object]]:
    """All ranks publish; rank 0 collects and merges (the telemetry
    rollup shape). Non-zero ranks return None."""
    publish(client, jobid, tm.rank, snapshot_doc(tm))
    if tm.rank != 0:
        return None
    return merge(collect(client, jobid, nranks, timeout))
