"""Monitoring plane — topology-aware traffic matrices + link loads.

Reference: ompi/mca/common/monitoring (the MPI_T traffic-matrix
plane the pml/osc/coll monitoring components all feed) — generalized
here into the eighth observability plane, because on a TPU the
traffic that matters never touches the host p2p path the old
``pml/monitoring`` stub watched.

Three cooperating pieces, all opt-in via ``monitoring_level`` (or the
short ``OMPI_TPU_MONITORING`` env knob):

- :mod:`matrix` — the matrix core: per-(src, dst) message/byte/
  latency cells split by context (p2p / coll / osc / part), fed by
  interposition on the pml send path (:mod:`ompi_tpu.pml.monitoring`,
  now a thin shim over this plane), the osc service-send funnel, the
  partitioned Pready path, and **algorithmic byte accounting** on the
  ``coll/xla`` device slots: each collective launch records the bytes
  its algorithm moves per peer (:mod:`algo` — ring RS/AG, allreduce =
  RS+AG, alltoall(v) actual splits), keyed by ``(op, log2-size-bucket,
  dtype, mesh-shape)`` so ``coll/tuned``-style switchpoint tables can
  be derived later.
- :mod:`links` — topology attribution (level 2): matrix cells map
  onto ICI links via ``topo.CartTopo`` coordinates and minimal-hop
  torus routing (``CartTopo.route``), producing per-link load
  estimates, a link-imbalance gauge
  (``monitoring_link_imbalance_permille``), and hottest-link naming.
- :mod:`merge` + the ``python -m ompi_tpu.monitoring report`` CLI —
  cross-rank merge (kvstore or JSON artifacts; send-side counting
  with a transpose check on merge) and rank×rank / per-link heatmap
  reports with top-N hotspot ranking.

Level semantics: 0 = off (every instrumented site pays one attribute
load + one branch — the ``TRAFFIC is None`` guard, same discipline as
``FLIGHT``/``RECORDER``/``SANITIZER``); 1 = matrices + per-cell
pvars; 2 = + per-link attribution and Perfetto link counter tracks.
The deprecated ``pml_monitoring`` cvar compat-maps to level 1.
"""

from __future__ import annotations

import os

from ompi_tpu.core import cvar, output

_out = output.stream("monitoring")

_level_var = cvar.register(
    "monitoring_level", 0, int,
    help="Traffic-monitoring plane level: 0 off (one branch per "
         "instrumented site), 1 per-(src,dst,ctx) traffic matrices + "
         "pvars, 2 adds per-ICI-link attribution (CartTopo minimal-"
         "hop routing) and Perfetto link counter tracks. "
         "Equivalently: OMPI_TPU_MONITORING=<level>. Supersedes the "
         "deprecated pml_monitoring cvar (compat: level 1).", level=5)

_dump_var = cvar.register(
    "monitoring_dump", "", str,
    help="Finalize-time per-rank matrix dump path; '{rank}' expands "
         "to the world rank (e.g. /tmp/mon_r{rank}.json). Feed the "
         "files to `python -m ompi_tpu.monitoring report`. Empty "
         "with pml_monitoring/monitoring_level set still logs the "
         "matrix through the output stream.", level=6)


def level() -> int:
    """Requested plane level: max of the cvar, the short
    OMPI_TPU_MONITORING env knob, and the deprecated pml_monitoring
    compat mapping (truthy -> level 1)."""
    lvl = int(_level_var.get())
    raw = os.environ.get("OMPI_TPU_MONITORING", "").strip().lower()
    if raw and raw not in ("0", "false", "no", "off"):
        try:
            lvl = max(lvl, int(raw))
        except ValueError:
            lvl = max(lvl, 1)  # any other truthy value: level 1
    from ompi_tpu.pml import monitoring as _pml_mon

    if _pml_mon._enable_var.get():
        lvl = max(lvl, 1)
    return lvl


def requested() -> bool:
    return level() > 0


def start(rank: int = 0, nranks: int = 0) -> None:
    """Bring the plane up (idempotent): enable the TRAFFIC matrix at
    the requested level and install the pml interposition shim so the
    host send path is counted too."""
    from ompi_tpu.monitoring import matrix as _matrix
    from ompi_tpu.pml import monitoring as _pml_mon

    lvl = level()
    if lvl <= 0:
        return
    if _pml_mon._enable_var.get() and not int(_level_var.get()):
        _out.verbose(1, "pml_monitoring is deprecated; it now maps "
                        "to monitoring_level 1 (use --mca "
                        "monitoring_level N)")
    if nranks <= 0:
        from ompi_tpu.runtime import rte

        nranks = rte.size
    _matrix.enable(rank=rank, level=lvl, nranks=nranks)
    _pml_mon.install()


def stop() -> None:
    """Tear the plane down: Finalize-time matrix dump (the
    common/monitoring dump-at-finalize contract for --mca
    pml_monitoring / monitoring_dump), then drop the guard."""
    from ompi_tpu.monitoring import matrix as _matrix

    tm = _matrix.TRAFFIC
    if tm is None:
        return
    try:
        finalize_dump()
    except Exception as exc:  # noqa: BLE001 — dumps must not sink
        _out.verbose(0, "monitoring dump failed: %r", exc)  # Finalize
    _matrix.disable()


def finalize_dump() -> str:
    """Write this rank's matrix snapshot: JSON artifact when
    ``monitoring_dump`` names a path (returned), and the
    human-readable per-peer lines through the output stream either
    way (the reference's MPI_Finalize flush)."""
    import json

    from ompi_tpu.monitoring import matrix as _matrix
    from ompi_tpu.monitoring import merge as _merge

    tm = _matrix.TRAFFIC
    if tm is None:
        return ""
    doc = _merge.snapshot_doc(tm)
    for ctx, table in sorted(doc["tables"].items()):
        for dst, (msgs, nbytes, _ns) in sorted(table.items(),
                                               key=lambda kv:
                                               int(kv[0])):
            _out.verbose(1, "rank %d -> %s [%s]: %d msgs, %d bytes",
                         tm.rank, dst, ctx, msgs, nbytes)
    path = _dump_var.get()
    if not path:
        return ""
    path = path.replace("{rank}", str(tm.rank))
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
    os.replace(tmp, path)
    _out.verbose(1, "matrix dump written: %s", path)
    return path


def expert_load(counts) -> None:
    """Record per-expert token counts on the plane
    (``monitoring_expert_tokens{expert=...}`` OpenMetrics family) —
    the EP/MoE serving feed of ROADMAP item 5. One branch when off."""
    from ompi_tpu.monitoring import matrix as _matrix

    tm = _matrix.TRAFFIC
    if tm is not None:
        tm.expert_tokens(counts)
