"""ompi_info equivalent — dump frameworks, components, cvars, pvars.

Reference: opal/runtime/opal_info_support.c + ompi/tools/ompi_info —
enumerates every framework's components and every registered MCA
variable with type/default/current/source, gated by verbosity level
(ompi_info -a / --level).

Usage:
    python -m ompi_tpu.tools.info              # components + level<=3 vars
    python -m ompi_tpu.tools.info -a           # everything incl. pvars
    python -m ompi_tpu.tools.info --level 9
    python -m ompi_tpu.tools.info --param coll # one framework's vars
    python -m ompi_tpu.tools.info --json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from ompi_tpu.core import cvar, pvar, registry

_SOURCES = {0: "default", 1: "file", 2: "env", 3: "set"}


#: modules never imported by the dump: heavy (models pull jax and
#: compile), side-effectful (launcher forks, __main__ runs CLIs), or
#: meaningless without a live job
_DISCOVERY_DENYLIST = (
    "ompi_tpu.models", "ompi_tpu.ops", "ompi_tpu.parallel",
    "ompi_tpu.runtime.launcher", "ompi_tpu.tools",
)


def _import_component_universe() -> None:
    """Import every ompi_tpu module so each component/cvar
    registration runs and the dump is complete, without bringing up
    the runtime (no rte/store init — like ompi_info, which opens
    frameworks without calling MPI_Init). Auto-discovered via
    pkgutil.iter_modules with *manual* recursion: walk_packages would
    itself import every package — including denylisted ones — just to
    recurse into it; iter_modules only reads directory listings, so
    denylisted subtrees are pruned before any import runs. Per-module
    failures warn and continue."""
    import importlib
    import pkgutil

    import ompi_tpu

    stack = [("ompi_tpu.", list(ompi_tpu.__path__))]
    while stack:
        prefix, paths = stack.pop()
        for info in pkgutil.iter_modules(paths, prefix):
            mod = info.name
            if mod.startswith(_DISCOVERY_DENYLIST):
                continue
            try:
                imported = importlib.import_module(mod)
            except Exception as exc:  # noqa: BLE001 — a broken module
                print(f"# warning: {mod} failed to import: {exc}",
                      file=sys.stderr)  # must not hide the whole dump
                continue
            if info.ispkg:
                stack.append((mod + ".", list(imported.__path__)))


def collect(level: int = 3,
            param: Optional[str] = None,
            include_pvars: bool = False) -> Dict:
    """Build the info tree (frameworks/components, cvars, pvars)."""
    _import_component_universe()
    out: Dict = {"frameworks": {}, "cvars": {}, "pvars": {}}
    for fw_name, fw in sorted(registry.all_frameworks().items()):
        out["frameworks"][fw_name] = fw.names()
    for name, var in sorted(cvar.all_vars().items()):
        if var.level > level:
            continue
        if param is not None and not name.startswith(param):
            continue
        out["cvars"][name] = {
            "value": var.get(),
            "default": var.default,
            "type": var.typ.__name__,
            "source": _SOURCES.get(var._source, "?"),
            "level": var.level,
            "help": var.help,
        }
        if var.choices is not None:
            out["cvars"][name]["choices"] = list(var.choices)
    if include_pvars:
        # seed with the well-known set so never-recorded counters
        # (e.g. the telemetry plane's, in a process that ran no job)
        # still list at 0 — ompi_info shows every pvar, not just the
        # ones that already ticked
        pvars = {k: 0 for k in pvar.WELL_KNOWN}
        pvars.update(pvar.snapshot())
        out["pvars"] = pvars
    from ompi_tpu.core import events

    out["events"] = [events.get_info(i)
                     for i in range(events.get_num())]
    return out


def render(info: Dict, verbose_help: bool = False) -> List[str]:
    lines: List[str] = []
    lines.append("ompi_tpu info")
    lines.append("=" * 60)
    lines.append("")
    lines.append("Frameworks and components:")
    for fw, comps in info["frameworks"].items():
        lines.append(f"  {fw:<14} {', '.join(comps) if comps else '(none)'}")
    lines.append("")
    lines.append(f"Control variables ({len(info['cvars'])}):")
    for name, v in info["cvars"].items():
        val = v["value"]
        mark = "" if v["source"] == "default" else f"  [{v['source']}]"
        lines.append(f"  {name:<34} {val!r:<14} "
                     f"(type {v['type']}, level {v['level']}){mark}")
        if verbose_help and v["help"]:
            lines.append(f"      {v['help']}")
    if info["pvars"]:
        lines.append("")
        lines.append(f"Performance variables ({len(info['pvars'])}):")
        for name, val in sorted(info["pvars"].items()):
            lines.append(f"  {name:<34} {val}")
    if info.get("events"):
        lines.append("")
        lines.append(f"Event types ({len(info['events'])}):")
        for ev in info["events"]:
            lines.append(f"  {ev['name']:<34} "
                         f"({', '.join(ev['fields'])})")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="ompi_tpu.tools.info",
                                 description=__doc__)
    ap.add_argument("-a", "--all", action="store_true",
                    help="everything: level 9 + pvars + help text")
    ap.add_argument("--level", type=int, default=None,
                    help="max cvar verbosity level (1..9)")
    ap.add_argument("--param", default=None, metavar="PREFIX",
                    help="only cvars with this prefix (e.g. 'coll')")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ns = ap.parse_args(argv)
    level = ns.level if ns.level is not None else (9 if ns.all else 3)
    info = collect(level=level, param=ns.param, include_pvars=ns.all)
    if ns.as_json:
        print(json.dumps(info, indent=2, default=repr))
    else:
        print("\n".join(render(info, verbose_help=ns.all)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
