"""Tools — introspection and operator utilities.

Reference: ompi/tools/ (ompi_info, mpirun wrapper, wrapper compilers).
The launcher (tpurun) lives in ompi_tpu.runtime.launcher; this package
holds ompi_info's equivalent (``python -m ompi_tpu.tools.info``).
"""
