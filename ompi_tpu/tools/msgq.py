"""Message-queue introspection — the parallel-debugger (MPIR) analog.

Reference: ompi/debuggers/ (5,654 LoC): the MPIR interface plus
TotalView-style DLLs that walk a live rank's match queues
(ompi_msgq_dll.c: posted receives, unexpected messages, pending sends)
and handle tables (ompi_mpihandles_dll.c) from *outside* the process.

TPU-first redesign: the queues live in one Python object (the ob1
instance), so introspection is a first-party API instead of a debugger
plug-in that re-implements struct layouts:

- :func:`snapshot` — structured dump of posted/unexpected/in-flight
  queues plus live communicator handles (the msgq + mpihandles DLL
  payloads in one dict).
- :func:`render` — human-readable lines, what a debugger would show.
- :func:`install_signal_dump` — SIGUSR1 dumps the queues of a live
  (possibly hung) rank to stderr: the practical equivalent of
  attaching TotalView to inspect why a recv never matched. Installed
  at init when the ``mpir_dump_on_signal`` cvar is on; ``tpurun``
  users can then ``kill -USR1`` a stuck rank.
"""

from __future__ import annotations

import signal
import sys
from typing import Dict, List

from ompi_tpu.core import cvar

dump_on_signal = cvar.register(
    "mpir_dump_on_signal", "off", str,
    help="Install a SIGUSR1 handler that dumps PML match queues and "
         "communicator handles to stderr — the debugger-attach "
         "(MPIR/ompi_msgq_dll) equivalent for hung-rank triage. "
         "Opt-in: installing it changes the process-wide SIGUSR1 "
         "disposition (default action is terminate) and the dump runs "
         "Python printing inside a signal handler, which a production "
         "job should not do silently.",
    choices=["on", "off"], level=5)


def _tag_str(tag: int) -> str:
    return "ANY_TAG" if tag == -1 else str(tag)


def _src_str(src: int) -> str:
    return "ANY_SOURCE" if src == -1 else str(src)


def snapshot() -> Dict:
    """Queue + handle state of this rank (empty when no PML yet)."""
    from ompi_tpu import comm as comm_mod, pml

    inst = pml.instance()
    out: Dict = {"posted": [], "unexpected": [], "pending_sends": [],
                 "communicators": []}
    # live communicator handles (mpihandles DLL payload); copy under
    # the registry lock — snapshot() may run from a watchdog thread
    # while the main thread creates/frees communicators. Non-blocking:
    # the SIGUSR1 handler runs on the main thread between bytecodes,
    # and blocking on a lock that same (suspended) thread holds would
    # deadlock the rank — fall back to a lockless dict copy (atomic
    # enough under the GIL for a diagnostic).
    got = comm_mod._comms_lock.acquire(blocking=False)
    try:
        comms = sorted(dict(comm_mod._comms).items())
    finally:
        if got:
            comm_mod._comms_lock.release()
    for cid, c in comms:
        if c is None:
            continue
        out["communicators"].append({
            "cid": cid, "size": c.size, "rank": c.rank,
            "name": getattr(c, "name", f"cid{cid}"),
            "revoked": bool(getattr(c, "revoked", False)),
            "inter": bool(getattr(c, "is_inter", False)),
        })
    if inst is None:
        return out
    for ctx, q in inst.posted.items():
        for req in q:
            out["posted"].append({
                "cid": ctx // 2, "collective": bool(ctx & 1),
                "src": req.want_src, "tag": req.want_tag,
                "count": req.count,
            })
    for ctx, q in inst.unexpected.items():
        for ux in q:
            _, _, src, tag, seq, size, _, msgid = ux.hdr
            out["unexpected"].append({
                "cid": ctx // 2, "collective": bool(ctx & 1),
                "src": src, "tag": tag, "seq": seq, "bytes": size,
                "msgid": msgid,
            })
    for msgid, req in list(inst.pending_ack.items()):
        out["pending_sends"].append({
            "msgid": msgid, "dst_world": req.dst_world,
            "state": "awaiting_ack",
        })
    for msgid, req in list(inst.streaming.items()):
        out["pending_sends"].append({
            "msgid": msgid, "dst_world": req.dst_world,
            "state": "streaming", "acked_bytes": req.acked_bytes,
            "total": req.conv.packed_size if req.conv else 0,
        })
    return out


def decode_type(dt) -> Dict:
    """Decode a derived datatype's constructor tree via
    Get_envelope/Get_contents — what a debugger's handle-introspection
    DLL shows for a type handle (reference: ompi_mpihandles_dll.c
    datatype decoding over MPI_Type_get_envelope/_contents)."""
    ni, na, nd, combiner = dt.Get_envelope()
    node: Dict = {"combiner": combiner, "name": dt.name,
                  "size": dt.size, "extent": dt.extent}
    if combiner == "named":
        return node
    ints, addrs, types = dt.Get_contents()
    node["integers"] = ints
    node["addresses"] = addrs
    node["types"] = [decode_type(t) for t in types]
    return node


def render_type(dt, indent: int = 0) -> List[str]:
    """Human-readable lines for a derived-type tree — one
    envelope/contents walk per node."""
    _, _, _, combiner = dt.Get_envelope()
    pad = "  " * indent
    line = (f"{pad}{combiner} '{dt.name}' "
            f"size={dt.size} extent={dt.extent}")
    if combiner == "named":
        return [line]
    ints, addrs, types = dt.Get_contents()
    if ints or addrs:
        line += f" args={ints + addrs}"
    lines = [line]
    for t in types:
        lines.extend(render_type(t, indent + 1))
    return lines


def render(snap: Dict = None) -> List[str]:
    snap = snapshot() if snap is None else snap
    lines = ["MPI message queues:"]
    lines.append(f"  communicators ({len(snap['communicators'])}):")
    for c in snap["communicators"]:
        flags = "".join(f for f, on in (("R", c["revoked"]),
                                        ("I", c["inter"])) if on)
        lines.append(f"    cid {c['cid']:>3} {c['name']}: rank "
                     f"{c['rank']}/{c['size']} {flags}")
    lines.append(f"  posted receives ({len(snap['posted'])}):")
    for p in snap["posted"]:
        coll = " coll" if p["collective"] else ""
        lines.append(f"    cid {p['cid']}{coll}: src "
                     f"{_src_str(p['src'])} tag {_tag_str(p['tag'])} "
                     f"count {p['count']}")
    lines.append(f"  unexpected messages ({len(snap['unexpected'])}):")
    for u in snap["unexpected"]:
        coll = " coll" if u["collective"] else ""
        lines.append(f"    cid {u['cid']}{coll}: src {u['src']} tag "
                     f"{_tag_str(u['tag'])} seq {u['seq']} "
                     f"{u['bytes']}B")
    lines.append(f"  pending sends ({len(snap['pending_sends'])}):")
    for s in snap["pending_sends"]:
        extra = (f" {s['acked_bytes']}/{s['total']}B"
                 if s["state"] == "streaming" else "")
        lines.append(f"    msgid {s['msgid']} -> world "
                     f"{s['dst_world']}: {s['state']}{extra}")
    return lines


def dump(file=None) -> None:
    print("\n".join(render()), file=file or sys.stderr, flush=True)


_installed = False


def install_signal_dump() -> None:
    """Idempotent; main-thread only (signal module restriction). An
    application handler registered before Init is *chained*, not
    clobbered — SIGUSR1 has conventional uses (reload, log rotation)
    that MPI must not silently eat."""
    global _installed
    if _installed or dump_on_signal.get() != "on":
        return
    try:
        prior = signal.getsignal(signal.SIGUSR1)

        def _handler(signum, frame):
            dump()
            if callable(prior):
                prior(signum, frame)

        signal.signal(signal.SIGUSR1, _handler)
        _installed = True
    except ValueError:
        pass  # not the main thread: debugger dump stays manual
