"""PMPI-style profiling interposition on the MPI API surface.

Reference: every C binding is a weak symbol so tools can interpose
(ompi/mpi/c/allreduce.c:37-41 PMPI_Allreduce alias; Fortran and SHMEM
likewise) and SPC_RECORD instruments each entry.

Pythonic redesign: the API methods live in one dispatch table
(ompi_tpu.mpi._API) attached to Communicator; a tool attaches pre/post
hooks and every MPI call on every communicator flows through them.
Attach twice and the wrappers nest — the PMPI chaining behavior.

    from ompi_tpu import profile
    handle = profile.attach_tool(
        pre=lambda name, comm, args, kwargs: ...,
        post=lambda name, comm, result, error: ...)
    ...
    profile.detach_tool(handle)

A ready-made timing tool is included: ``with profile.timing() as t``
collects per-call counts and wall time (the SPC/MPI_T overhead-harness
pattern, test/monitoring/test_overhead.c).
"""

from __future__ import annotations

import functools
import itertools
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional

_handles = itertools.count(1)
_active: Dict[int, Dict[str, Callable]] = {}  # handle -> {name: prev_fn}


def _wrap(name: str, fn: Callable, pre, post) -> Callable:
    @functools.wraps(fn)
    def wrapper(comm, *args, **kwargs):
        if pre is not None:
            pre(name, comm, args, kwargs)
        error = None
        result = None
        try:
            result = fn(comm, *args, **kwargs)
            return result
        except BaseException as exc:
            error = exc
            raise
        finally:
            if post is not None:
                post(name, comm, result, error)
    wrapper.__profiled__ = True
    return wrapper


def attach_tool(pre: Optional[Callable] = None,
                post: Optional[Callable] = None,
                names: Optional[list] = None) -> int:
    """Interpose pre/post hooks on the MPI API; returns a handle for
    detach_tool. `names` limits interposition to specific calls."""
    from ompi_tpu import mpi
    from ompi_tpu.comm import Communicator

    targets = names if names is not None else list(mpi._API)
    saved: Dict[str, Callable] = {}
    for name in targets:
        cur = getattr(Communicator, name, None)
        if cur is None:
            continue
        saved[name] = cur  # what this tool wrapped (maybe a wrapper)
        setattr(Communicator, name, _wrap(name, cur, pre, post))
    handle = next(_handles)
    _active[handle] = saved
    return handle


def detach_tool(handle: int) -> None:
    """Remove a tool by restoring the methods it wrapped. Tools nest
    like PMPI layers: detach in LIFO order (detaching an inner tool
    out of order drops any tool attached after it on those names)."""
    from ompi_tpu.comm import Communicator

    saved = _active.pop(handle, None)
    if saved is None:
        return
    for name, prev in saved.items():
        setattr(Communicator, name, prev)


@contextmanager
def timing(names: Optional[list] = None):
    """Collect per-call counts and wall-clock seconds.

    Also publishes each call into the pvar plane as
    ``profile_<op>_calls`` / ``profile_<op>_ns``, so an MPI_T session
    can read tool overhead without holding the stats dict (the
    reference's test/monitoring/test_overhead.c harness pattern)."""
    from ompi_tpu.core import pvar

    stats: Dict[str, list] = {}
    stack: Dict[int, float] = {}

    def pre(name, comm, args, kwargs):
        stack[id(comm), name] = time.perf_counter()

    def post(name, comm, result, error):
        t0 = stack.pop((id(comm), name), None)
        if t0 is None:
            return
        dt = time.perf_counter() - t0
        cell = stats.setdefault(name, [0, 0.0])
        cell[0] += 1
        cell[1] += dt
        pvar.record(f"profile_{name}_calls")
        pvar.record(f"profile_{name}_ns", int(dt * 1e9))

    handle = attach_tool(pre, post, names)
    try:
        yield stats
    finally:
        detach_tool(handle)
