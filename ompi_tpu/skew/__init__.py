"""skew/ — cross-rank straggler attribution + critical-path plane.

Every other observability plane answers "how long did MY rank
spend"; this one answers the distributed-training question — **which
rank made everyone else wait, and in which collective**. It rides
the flight recorder's entry instrumentation (coll/xla, partitioned,
hier, and API-level blocking collectives all already register
``(seq, op, cid, nbytes, t_enter)``) and adds the exit side:
completed collectives land in a bounded per-rank ring
(:mod:`record`), rings merge through the kvstore at Finalize
(:mod:`merge`, the ``monitoring/merge`` shape), and the
decomposition engine (:mod:`decompose`) splits each rank's wall time
into ``arrival_skew`` (waiting for stragglers) vs ``transfer``
(actually moving data), walks the per-step critical path, and names
persistent stragglers — rendered by :mod:`report` and
``python -m ompi_tpu.skew report``.

Level semantics: 0 = off (the flight exit path pays one attribute
load + one branch — the ``SKEW is None`` guard, same discipline as
``FLIGHT``/``RECORDER``/``TRAFFIC``/``OBSERVER``); 1 = post-hoc
(ring + Finalize merge + verdicts); 2 = + live sampling through the
heartbeat payload's last-arrival stamp, so the watchdog can name a
*slow* rank before it becomes a *hung* rank
(``skew_live_lag_ns``, hang-dump ``skew`` context).

Clocks: arrival comparisons ride ``telemetry/clock.py`` — each rank
samples a bracketed wall-vs-monotonic offset at start and syncs rank
0's base through the store, and every report states the resulting
timestamp error bar.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from ompi_tpu.core import cvar, output

_out = output.stream("skew")

_level_var = cvar.register(
    "skew_level", 0, int,
    help="Cross-rank skew attribution plane: 0 off (the flight exit "
         "path pays one attribute load + one branch — the SKEW "
         "guard), 1 completed-collective ring + Finalize kvstore "
         "merge + arrival-skew/transfer decomposition + persistent-"
         "straggler verdicts, 2 adds live lag sampling through the "
         "heartbeat payload (watchdog names slow ranks before they "
         "hang). Equivalently: OMPI_TPU_SKEW=<level>.", level=5)

_dump_var = cvar.register(
    "skew_dump", "", str,
    help="Finalize-time per-rank skew-ring dump path; '{rank}' "
         "expands to the world rank (e.g. /tmp/skew_r{rank}.json). "
         "Feed the files to `python -m ompi_tpu.skew report`.",
    level=6)


def level() -> int:
    """Requested plane level: max of the cvar and the short
    OMPI_TPU_SKEW env knob (monitoring-style truthy parse)."""
    lvl = int(_level_var.get())
    raw = os.environ.get("OMPI_TPU_SKEW", "").strip().lower()
    if raw and raw not in ("0", "false", "no", "off"):
        try:
            lvl = max(lvl, int(raw))
        except ValueError:
            lvl = max(lvl, 1)  # any other truthy value: level 1
    return lvl


def requested() -> bool:
    return level() > 0


def start(rank: int = 0, nranks: int = 0) -> None:
    """Bring the plane up (idempotent): enable the flight recorder
    (the entry/exit instrumentation the ring rides), sync the clock
    bracket through the store, raise the SKEW guard."""
    from ompi_tpu.runtime import rte
    from ompi_tpu.skew import record as _record
    from ompi_tpu.telemetry import clock as _clock
    from ompi_tpu.telemetry import flight as _flight

    lvl = level()
    if lvl <= 0:
        return
    if nranks <= 0:
        nranks = rte.size
    fl = _flight.enable(rank=rank)
    sk = _record.enable(rank=rank, nranks=nranks, level=lvl)
    sk.clock_offset_ns = fl.clock_offset_ns
    sk.clock_err_ns = fl.clock_err_ns
    if nranks > 1:
        sk.clock_base_ns, sk.clock_base_err_ns = \
            _clock.sync_via_store("skew_clock", sk.clock_offset_ns,
                                  sk.clock_err_ns)
    else:
        sk.clock_base_ns = sk.clock_offset_ns
        sk.clock_base_err_ns = sk.clock_err_ns


def stop() -> None:
    """Tear the plane down: per-rank ring dump, kvstore merge, rank-0
    decomposition + named verdicts, pvar fold-in on every rank. Every
    step is failure-proof — teardown must not sink Finalize."""
    import json

    from ompi_tpu.skew import record as _record

    sk = _record.SKEW
    if sk is None:
        return
    from ompi_tpu.runtime import rte
    from ompi_tpu.skew import decompose as _decompose
    from ompi_tpu.skew import merge as _merge
    from ompi_tpu.skew import report as _report

    # 1. per-rank artifact dump ({rank} expansion, atomic write) —
    # lands even if the merge below fails
    path = _dump_var.get()
    if path:
        try:
            path = path.replace("{rank}", str(sk.rank))
            tmp = "%s.tmp.%d" % (path, os.getpid())
            with open(tmp, "w") as fh:
                json.dump(_merge.snapshot_doc(sk), fh, indent=1)
            os.replace(tmp, path)
            _out.verbose(1, "skew ring dump written: %s", path)
        except Exception as exc:  # noqa: BLE001 — dumps must not sink
            _out.verbose(0, "skew dump failed: %r", exc)

    # 2. cross-rank merge; rank 0 decomposes and publishes the
    # analysis back so every rank folds its own exposed-wait figures
    # into the pvar plane
    analysis: Optional[Dict[str, Any]] = None
    ana_key = "skew:ana:%s" % rte.jobid
    try:
        if rte.size > 1:
            merged = _merge.exchange(sk, rte.client(), rte.jobid,
                                     rte.size)
            if merged is not None:  # rank 0
                analysis = _decompose.analyze(
                    merged["records"],
                    clock_err_ns=merged["clock_err_ns"])
                rte.client().put(ana_key, json.dumps(analysis))
            else:
                raw = rte.client().get(ana_key, wait=15.0)
                analysis = json.loads(raw)
        else:
            merged = _merge.merge([_merge.snapshot_doc(sk)])
            analysis = _decompose.analyze(
                merged["records"],
                clock_err_ns=merged["clock_err_ns"])
    except Exception as exc:  # noqa: BLE001 — teardown must not sink
        _out.verbose(0, "skew merge failed: %r", exc)

    if analysis is not None:
        try:
            sk.set_arrivals({(g["cid"], g["seq"]): g["last_arrival_ns"]
                             for g in analysis["groups"]})
            _decompose.record_pvars(analysis, sk.rank)
            if sk.rank == 0:
                for v in analysis["stragglers"]:
                    _out.verbose(0, "%s", _report.verdict_line(v))
                _out.verbose(1, "skew: %d collectives decomposed, "
                             "error bar ±%.1f us",
                             analysis["collectives"],
                             analysis["clock_err_ns"] / 1e3)
        except Exception as exc:  # noqa: BLE001
            _out.verbose(0, "skew verdict failed: %r", exc)
    _record.disable()


def skew_info() -> Optional[Dict[str, Any]]:
    """Current worst-skew context for the watchdog hang dump (None
    while the plane is off) — a hang on a rank the live view already
    saw falling behind should say so next to the verdict."""
    from ompi_tpu.core import pvar
    from ompi_tpu.skew import record as _record

    sk = _record.SKEW
    if sk is None:
        return None
    info: Dict[str, Any] = {
        "level": sk.level,
        "records": pvar.read("skew_records"),
        "dropped": pvar.read("skew_dropped"),
    }
    if sk.live_worst is not None:
        info["live_worst"] = dict(sk.live_worst)
    return info
