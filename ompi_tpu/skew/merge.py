"""Cross-rank skew-ring merge — kvstore exchange + timebase rebase.

The ``monitoring/merge`` shape: ranks publish JSON snapshot docs to
the kvstore under ``skew:rec:{jobid}:{rank}`` (or dump them as files
at Finalize via ``--mca skew_dump`` for the offline CLI), rank 0
collects and merges. Schema ``ompi_tpu.skew/1``.

Records are published in LOCAL monotonic ns alongside the rank's
synced clock numbers; :func:`merge` rebases every rank's ring into
the shared (rank 0 monotonic) timebase via ``telemetry/clock.py``
and carries the worst pairwise comparison error so the analysis can
state its error bar.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ompi_tpu.telemetry import clock as _clock

SCHEMA = "ompi_tpu.skew/1"


def snapshot_doc(sk) -> Dict[str, Any]:
    """One rank's JSON-able skew-ring snapshot."""
    return {
        "schema": SCHEMA,
        "rank": sk.rank,
        "nranks": sk.nranks,
        "level": sk.level,
        "clock_offset_ns": sk.clock_offset_ns,
        "clock_err_ns": sk.clock_err_ns,
        "clock_base_ns": sk.clock_base_ns,
        "clock_base_err_ns": sk.clock_base_err_ns,
        "records": [
            {"seq": s, "op": op, "cid": cid, "nbytes": nb,
             "t0": t0, "t1": t1}
            for s, op, cid, nb, t0, t1 in sk.records()],
    }


def _key(jobid: str, rank: int) -> str:
    return f"skew:rec:{jobid}:{rank}"


def publish(client, jobid: str, rank: int,
            doc: Dict[str, Any]) -> None:
    client.put(_key(jobid, rank), json.dumps(doc))


def collect(client, jobid: str, nranks: int,
            timeout: float = 10.0) -> List[Dict[str, Any]]:
    """Gather every rank's published snapshot (blocking get per rank,
    kvstore-side wait)."""
    docs = []
    for r in range(nranks):
        raw = client.get(_key(jobid, r), wait=timeout)
        docs.append(json.loads(raw))
    return docs


def merge(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-rank snapshots -> one shared-timebase record map.

    Every doc's records shift by ``clock.shift_ns(offset, base)``
    (= 0 for the base rank and for unsynced single-rank docs).
    Returns ``{"records": {rank: [...]}, "clock_err_ns": worst
    pairwise comparison error, ...}`` — the input
    ``decompose.analyze`` wants."""
    for doc in docs:
        if doc.get("schema") != SCHEMA:
            raise ValueError(
                f"not a skew ring dump (schema="
                f"{doc.get('schema')!r}, want {SCHEMA!r})")
    per_rank: Dict[int, List[Dict[str, Any]]] = {}
    errs: List[int] = []
    level = 0
    for doc in docs:
        rank = int(doc["rank"])
        shift = _clock.shift_ns(doc.get("clock_offset_ns"),
                                doc.get("clock_base_ns"))
        errs.append(int(doc.get("clock_err_ns", 0))
                    + int(doc.get("clock_base_err_ns", 0)))
        level = max(level, int(doc.get("level", 0)))
        out = per_rank.setdefault(rank, [])
        for rec in doc.get("records", ()):
            rec = dict(rec)
            rec["t0"] = int(rec["t0"]) + shift
            rec["t1"] = int(rec["t1"]) + shift
            out.append(rec)
    worst_pair = 0
    top = sorted(errs, reverse=True)[:2]
    if len(top) == 2:
        worst_pair = _clock.pair_err_ns(top[0], top[1])
    elif top:
        worst_pair = top[0]
    return {
        "schema": SCHEMA + "+merged",
        "nranks": max([len(per_rank)]
                      + [int(d.get("nranks", 0)) for d in docs]),
        "level": level,
        "clock_err_ns": worst_pair,
        "records": per_rank,
    }


def exchange(sk, client, jobid: str, nranks: int,
             timeout: float = 10.0) -> Optional[Dict[str, Any]]:
    """All ranks publish; rank 0 collects and merges (the
    monitoring/merge rollup shape). Non-zero ranks return None."""
    publish(client, jobid, sk.rank, snapshot_doc(sk))
    if sk.rank != 0:
        return None
    return merge(collect(client, jobid, nranks, timeout))
