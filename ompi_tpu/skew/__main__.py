"""CLI: merge per-rank skew-ring dumps into the straggler report.

    python -m ompi_tpu.skew report skew_r0.json skew_r1.json
    python -m ompi_tpu.skew report --json analysis.json --pct 60 \
        skew_r*.json

Inputs are the Finalize-time dumps ``--mca skew_dump
'/tmp/skew_r{rank}.json'`` writes (schema ``ompi_tpu.skew/1``).
Missing or corrupt input: one line on stderr, exit 1 — same contract
as the monitoring/trace merge CLIs.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ompi_tpu.skew import decompose, merge, report


def _cmd_report(args) -> int:
    docs = []
    try:
        for path in args.inputs:
            with open(path) as fh:
                docs.append(json.load(fh))
        merged = merge.merge(docs)
        analysis = decompose.analyze(
            merged["records"], clock_err_ns=merged["clock_err_ns"],
            pct=args.pct, win=args.window)
    except OSError as exc:
        print(f"skew report: {exc}", file=sys.stderr)
        return 1
    except (json.JSONDecodeError, KeyError, TypeError,
            ValueError) as exc:
        print("skew report: corrupt skew ring input: "
              f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return 1
    print(report.render(analysis, top=args.top))
    if args.json:
        try:
            with open(args.json, "w") as fh:
                json.dump(analysis, fh, indent=1)
        except OSError as exc:
            print(f"skew report: {exc}", file=sys.stderr)
            return 1
        print(f"skew analysis written: {args.json}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.skew",
        description="merge/report ompi_tpu cross-rank skew rings")
    sub = ap.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser(
        "report", help="exposed-wait ranking, per-op skew table, "
                       "critical path, and persistent-straggler "
                       "verdicts from per-rank skew_dump files")
    r.add_argument("inputs", nargs="+",
                   help="per-rank skew_dump JSON files")
    r.add_argument("--json", default="",
                   help="also write the analysis JSON artifact")
    r.add_argument("--top", type=int, default=8,
                   help="exposed-wait rows to print (default 8)")
    r.add_argument("--pct", type=float, default=None,
                   help="persistent-straggler share bar in percent "
                        "(default: the skew_straggler_pct cvar)")
    r.add_argument("--window", type=int, default=None,
                   help="most recent N collectives for the verdict "
                        "(default: the skew_window cvar; 0 = all)")
    r.set_defaults(fn=_cmd_report)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
