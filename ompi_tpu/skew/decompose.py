"""Skew decomposition engine — wall time into wait vs transfer.

Given the clock-synced records of all ranks for a ``(cid, seq)``,
each rank's wall time inside the collective splits exactly:

- ``arrival_skew`` (aka exposed wait): ``latest_arrival - my_arrival``
  — time I spent waiting for stragglers, the part no algorithm or
  wire tuning can recover;
- ``transfer``: ``my_exit - latest_arrival`` — the collective
  actually moving data once everyone showed up (clamped at 0: a rank
  can observe its exit before the recorded last arrival by up to the
  clock error).

Each group's straggler (the last-arriving rank) has its lateness
attributed to compute vs comm by the gap since its previous
collective exit: a straggler whose time OUTSIDE collectives covers
at least half its lateness was doing compute (or injected delay —
the smoke lane's case); one that left its previous collective late
was dragged by communication upstream. The half bar (not 1.0×)
keeps the call stable when the outside gap and the lateness are the
same quantity measured on two clocks — the sleep-injected-straggler
shape, where scheduler jitter would otherwise flip it per step.

The per-step critical path chains the last-arriving rank of each
collective in seq order — the bounding rank sequence a pipeline
bubble analysis would walk (ROADMAP item 2). The persistent-straggler
verdict names any rank last into ≥ ``skew_straggler_pct`` of the
window's collectives, the monitoring hot-expert verdict shape.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ompi_tpu.core import cvar, pvar

_pct_var = cvar.register(
    "skew_straggler_pct", 50.0, float,
    help="Persistent-straggler bar: a rank arriving last into at "
         "least this percentage of the window's collectives gets a "
         "named verdict (skew report, Finalize log line, "
         "skew_stragglers pvar).", level=7)

_window_var = cvar.register(
    "skew_window", 0, int,
    help="Collectives considered by the persistent-straggler verdict "
         "(most recent N groups; 0 = the whole merged window).",
    level=7)


def straggler_pct() -> float:
    return float(_pct_var.get())


def window() -> int:
    return int(_window_var.get())


def groups_of(per_rank: Dict[int, List[Dict[str, Any]]]
              ) -> List[Dict[str, Any]]:
    """Group shared-timebase records by ``(cid, seq)`` and decompose.

    ``per_rank`` maps rank -> record dicts (``seq/op/cid/nbytes/
    t0/t1`` in ns, already rebased into one timebase). Groups seen by
    fewer than two ranks carry no cross-rank information (ring drops,
    rank-local collectives) and are skipped. Returns seq-ordered
    group dicts."""
    by_key: Dict[Tuple[int, int], Dict[int, Dict[str, Any]]] = {}
    for rank, recs in per_rank.items():
        for rec in recs:
            by_key.setdefault(
                (int(rec["cid"]), int(rec["seq"])), {})[int(rank)] = rec
    # previous-exit lookup per rank (seq order) for cause attribution
    prev_exit: Dict[Tuple[int, int, int], int] = {}
    for rank, recs in per_rank.items():
        by_cid: Dict[int, List[Dict[str, Any]]] = {}
        for rec in recs:
            by_cid.setdefault(int(rec["cid"]), []).append(rec)
        for cid, rs in by_cid.items():
            rs.sort(key=lambda r: int(r["seq"]))
            for prev, cur in zip(rs, rs[1:]):
                prev_exit[(int(rank), cid, int(cur["seq"]))] = \
                    int(prev["t1"])
    groups: List[Dict[str, Any]] = []
    for (cid, seq), members in sorted(by_key.items(),
                                      key=lambda kv: (kv[0][1],
                                                      kv[0][0])):
        if len(members) < 2:
            continue
        last_rank = max(members, key=lambda r: int(members[r]["t0"]))
        last_arr = int(members[last_rank]["t0"])
        first_arr = min(int(m["t0"]) for m in members.values())
        ranks: Dict[int, Dict[str, int]] = {}
        for r, m in sorted(members.items()):
            t0, t1 = int(m["t0"]), int(m["t1"])
            ranks[r] = {
                "wall_ns": t1 - t0,
                "wait_ns": last_arr - t0,
                "transfer_ns": max(0, t1 - last_arr),
            }
        lateness = last_arr - first_arr
        gap = prev_exit.get((last_rank, cid, seq))
        if gap is None:
            cause = "unknown"
        else:
            cause = ("compute" if last_arr - gap >= lateness / 2
                     else "comm")
        groups.append({
            "cid": cid, "seq": seq,
            "op": members[last_rank].get("op", "?"),
            "nbytes": int(members[last_rank].get("nbytes", 0)),
            "last_rank": last_rank,
            "last_arrival_ns": last_arr,
            "arrival_skew_ns": lateness,
            "cause": cause,
            "ranks": ranks,
        })
    return groups


def critical_path(groups: List[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """The step's bounding rank sequence: the last-arriving rank of
    each collective, chained in seq order."""
    return [{"seq": g["seq"], "cid": g["cid"], "op": g["op"],
             "rank": g["last_rank"],
             "arrival_skew_ns": g["arrival_skew_ns"],
             "cause": g["cause"]}
            for g in sorted(groups, key=lambda g: (g["seq"],
                                                   g["cid"]))]


def verdict(groups: List[Dict[str, Any]],
            pct: Optional[float] = None,
            win: Optional[int] = None) -> List[Dict[str, Any]]:
    """Persistent stragglers over the (most recent) window: ranks
    last into >= pct% of the window's collectives, worst first. Each
    entry carries the rank's last-share, its dominant lateness cause
    (weighted by arrival skew, so a handful of big compute stalls
    outvotes many sub-ms barrier hops), and its summed arrival skew
    — everything the named verdict line renders."""
    pct = straggler_pct() if pct is None else float(pct)
    win = window() if win is None else int(win)
    ordered = sorted(groups, key=lambda g: (g["seq"], g["cid"]))
    if win > 0:
        ordered = ordered[-win:]
    if not ordered:
        return []
    last_counts: Dict[int, int] = {}
    causes: Dict[int, Dict[str, int]] = {}
    skew_sum: Dict[int, int] = {}
    for g in ordered:
        r = g["last_rank"]
        last_counts[r] = last_counts.get(r, 0) + 1
        c = causes.setdefault(r, {})
        # skew-weighted (+1 so zero-skew ties still count the cause)
        c[g["cause"]] = (c.get(g["cause"], 0) + 1
                         + g["arrival_skew_ns"])
        skew_sum[r] = skew_sum.get(r, 0) + g["arrival_skew_ns"]
    n = len(ordered)
    out = []
    for r, cnt in sorted(last_counts.items(),
                         key=lambda kv: -kv[1]):
        share = 100.0 * cnt / n
        if share < pct:
            continue
        cause = max(causes[r], key=causes[r].get)
        out.append({"rank": r, "last": cnt, "of": n,
                    "share_pct": round(share, 1),
                    "cause": cause,
                    "arrival_skew_ns": skew_sum[r]})
    return out


def exposed_wait(groups: List[Dict[str, Any]]) -> Dict[int, int]:
    """Per-rank summed exposed wait (ns) — the straggler tax each
    rank paid, the report's headline ranking."""
    out: Dict[int, int] = {}
    for g in groups:
        for r, cell in g["ranks"].items():
            out[int(r)] = out.get(int(r), 0) + int(cell["wait_ns"])
    return out


def per_op(groups: List[Dict[str, Any]]
           ) -> List[Dict[str, Any]]:
    """Per-op skew table: group count, mean/max arrival skew, summed
    exposed wait across all ranks."""
    accum: Dict[str, List[int]] = {}
    for g in groups:
        row = accum.setdefault(g["op"], [0, 0, 0, 0])
        row[0] += 1
        row[1] += g["arrival_skew_ns"]
        row[2] = max(row[2], g["arrival_skew_ns"])
        row[3] += sum(int(c["wait_ns"]) for c in g["ranks"].values())
    return [{"op": op, "n": row[0],
             "mean_skew_ns": row[1] // max(1, row[0]),
             "max_skew_ns": row[2], "wait_ns": row[3]}
            for op, row in sorted(accum.items())]


def analyze(per_rank: Dict[int, List[Dict[str, Any]]],
            clock_err_ns: int = 0,
            pct: Optional[float] = None,
            win: Optional[int] = None) -> Dict[str, Any]:
    """Full analysis doc over shared-timebase per-rank records: the
    decomposed groups, per-rank exposed-wait ranking, per-op table,
    critical path, persistent-straggler verdicts, and the timestamp
    error bar every one of those figures inherits."""
    groups = groups_of(per_rank)
    return {
        "schema": "ompi_tpu.skew/1+analysis",
        "nranks": len(per_rank),
        "collectives": len(groups),
        "clock_err_ns": int(clock_err_ns),
        "groups": groups,
        "exposed_wait_ns": {str(r): v for r, v in
                            sorted(exposed_wait(groups).items())},
        "per_op": per_op(groups),
        "critical_path": critical_path(groups),
        "stragglers": verdict(groups, pct=pct, win=win),
    }


def record_pvars(analysis: Dict[str, Any], rank: int) -> None:
    """Fold one rank's view of an analysis into the pvar plane:
    summed exposed wait for THIS rank, per-op wait (dynamic
    ``skew_op_wait_ns_<op>`` family — OpenMetrics folds it into a
    labelled family), the worst arrival skew seen (hwm), and the
    persistent-straggler count."""
    mine = int(analysis.get("exposed_wait_ns", {}).get(str(rank), 0))
    if mine:
        pvar.record("skew_exposed_wait_ns", mine)
    for row in analysis.get("per_op", ()):
        if row.get("wait_ns"):
            pvar.record("skew_op_wait_ns_%s" % row["op"],
                        int(row["wait_ns"]))
    worst = max((g["arrival_skew_ns"]
                 for g in analysis.get("groups", ())), default=0)
    pvar.record_hwm("skew_arrival_skew_ns", worst)
    n = len(analysis.get("stragglers", ()))
    if n:
        pvar.record("skew_stragglers", n)
