"""Human-readable skew reports — rankings, tables, critical path.

Renders the analysis doc ``decompose.analyze`` produces: the
per-rank exposed-wait ranking (who paid the straggler tax), the
per-op skew table, the step's critical path (last-arriving rank per
collective with its compute-vs-comm cause), and the persistent-
straggler verdicts — each figure qualified by the merged clock error
bar, because a wait smaller than the error bar is noise.
"""

from __future__ import annotations

from typing import Any, Dict, List


def _ms(ns: int) -> str:
    return "%.3f ms" % (ns / 1e6)


def verdict_line(v: Dict[str, Any]) -> str:
    """The named persistent-straggler line (Finalize log + report +
    smoke-lane grep target)."""
    return ("PERSISTENT STRAGGLER: rank %d last into %d%% of %d "
            "collectives (%s, +%s skew)"
            % (v["rank"], round(v["share_pct"]), v["of"],
               v["cause"], _ms(v["arrival_skew_ns"])))


def render(analysis: Dict[str, Any], top: int = 8,
           path_rows: int = 16) -> str:
    lines: List[str] = []
    err = int(analysis.get("clock_err_ns", 0))
    lines.append(
        "skew report: %d collectives across %d ranks "
        "(timestamp error bar ±%.1f us)"
        % (analysis.get("collectives", 0),
           analysis.get("nranks", 0), err / 1e3))

    waits = sorted(analysis.get("exposed_wait_ns", {}).items(),
                   key=lambda kv: -int(kv[1]))
    if waits:
        lines.append("")
        lines.append("exposed wait by rank (time spent waiting for "
                     "stragglers):")
        for r, w in waits[:top]:
            lines.append("  rank %-4s %12s" % (r, _ms(int(w))))

    ops = analysis.get("per_op", ())
    if ops:
        lines.append("")
        lines.append("per-op arrival skew:")
        lines.append("  %-24s %5s %14s %14s %14s"
                     % ("op", "n", "mean skew", "max skew",
                        "total wait"))
        for row in ops:
            lines.append("  %-24s %5d %14s %14s %14s"
                         % (row["op"], row["n"],
                            _ms(row["mean_skew_ns"]),
                            _ms(row["max_skew_ns"]),
                            _ms(row["wait_ns"])))

    path = analysis.get("critical_path", ())
    if path:
        lines.append("")
        lines.append("critical path (last-arriving rank per "
                     "collective, seq order):")
        shown = list(path)[-path_rows:]
        if len(shown) < len(path):
            lines.append("  ... %d earlier collectives elided"
                         % (len(path) - len(shown)))
        for hop in shown:
            lines.append(
                "  seq %-5d %-24s rank %-4d +%s (%s)"
                % (hop["seq"], hop["op"], hop["rank"],
                   _ms(hop["arrival_skew_ns"]), hop["cause"]))

    lines.append("")
    stragglers = analysis.get("stragglers", ())
    if stragglers:
        for v in stragglers:
            lines.append(verdict_line(v))
    else:
        lines.append("no persistent straggler (no rank was last "
                     "often enough to name)")
    return "\n".join(lines)
