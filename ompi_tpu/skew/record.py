"""Skew plane core — the completed-collective ring behind ``SKEW``.

The flight recorder owns the entry side (``(seq, op, cid, nbytes,
t_enter)`` in the in-flight table); this module owns the exit side:
``FlightRecorder.exit`` feeds each *completed* collective here, so
every rank accumulates a bounded ring of ``(seq, op, cid, nbytes,
t_enter_ns, t_exit_ns)`` records — the raw material the decomposition
engine turns into arrival-skew vs transfer time once all ranks'
rings meet (kvstore merge at Finalize, or per-rank dumps offline).

Hot-path contract (the ``FLIGHT``/``RECORDER``/``TRAFFIC``/
``OBSERVER`` discipline, lint-enforced): while the plane is off —
the default — the one instrumented site (flight exit) pays ONE
module-attribute load + ONE ``is None`` branch and constructs
nothing. Ring overflow overwrites the oldest record and counts in
``skew_dropped`` (the trace-recorder drop-accounting shape).

Timestamps are local ``time.monotonic()`` converted to ns; the
recorder carries the rank's clock offset/error and rank 0's base
(``telemetry/clock.py``) so merges rebase every ring into one
timebase and the report can state its error bar.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ompi_tpu.core import cvar, pvar

#: THE disabled guard. The instrumented site does
#: ``sk = record.SKEW`` / ``if sk is not None: sk.complete(...)`` —
#: module attribute load plus one branch, nothing constructed on the
#: None path.
SKEW: Optional["SkewRecorder"] = None

_ring_var = cvar.register(
    "skew_ring", 8192, int,
    help="Completed-collective ring capacity per rank for the skew "
         "plane; overflow overwrites the oldest record and counts in "
         "the skew_dropped pvar.", level=6)

#: one completed collective:
#: (seq, op, comm_cid, nbytes, t_enter_ns, t_exit_ns) — both stamps
#: local monotonic ns
Record = Tuple[int, str, int, int, int, int]


class SkewRecorder:
    """Thread-safe bounded ring of completed collectives + the live
    cross-rank lag view (level 2)."""

    def __init__(self, rank: int = 0, nranks: int = 0,
                 level: int = 1,
                 capacity: Optional[int] = None) -> None:
        cap = int(capacity if capacity is not None
                  else _ring_var.get())
        self.capacity = max(1, cap)
        self.rank = rank
        self.nranks = nranks
        self.level = level
        self._buf: List[Optional[Record]] = [None] * self.capacity
        self._head = 0
        self._n = 0
        self._lock = threading.Lock()
        # this rank's clock bracket + rank 0's (telemetry/clock.py);
        # start() fills them in after the store sync
        self.clock_offset_ns = 0
        self.clock_err_ns = 0
        self.clock_base_ns = 0
        self.clock_base_err_ns = 0
        #: resolved arrival map {(cid, seq): last_arrival_ns in the
        #: SHARED timebase} — set after a merge so the trace export
        #: can split each record into wait + transfer spans
        self.arrivals: Dict[Tuple[int, int], int] = {}
        #: level-2 live view: the rank whose last collective arrival
        #: lags the job's freshest arrival the most (watchdog context)
        self.live_worst: Optional[Dict[str, Any]] = None

    # -- hot path (enabled only; fed by FlightRecorder.exit) -------------
    def complete(self, seq: int, op: str, cid: int, nbytes: int,
                 t0_s: float, t1_s: float) -> None:
        rec = (seq, op, cid, int(nbytes),
               int(t0_s * 1e9), int(t1_s * 1e9))
        with self._lock:
            if self._n == self.capacity:
                pvar.record("skew_dropped")
            else:
                self._n += 1
            depth = self._n
            self._buf[self._head] = rec
            self._head = (self._head + 1) % self.capacity
        pvar.record("skew_records")
        pvar.record_hwm("skew_ring_depth", depth)

    # -- merge/export side -----------------------------------------------
    def records(self) -> List[Record]:
        """Chronological (completion-order) snapshot."""
        with self._lock:
            if self._n < self.capacity:
                out = self._buf[:self._n]
            else:
                out = self._buf[self._head:] + self._buf[:self._head]
            return list(out)

    def shift_ns(self) -> int:
        """Local-monotonic -> shared-timebase rebase (clock.shift_ns
        over this recorder's synced offsets)."""
        from ompi_tpu.telemetry import clock as _clock

        return _clock.shift_ns(self.clock_offset_ns,
                               self.clock_base_ns)

    def set_arrivals(self,
                     arrivals: Dict[Tuple[int, int], int]) -> None:
        """Install the merged last-arrival map (shared timebase) so
        this rank's records can be split into wait/transfer locally
        (trace export's skew lane, pvar accounting)."""
        with self._lock:
            self.arrivals = dict(arrivals)

    def observe_live(self, peers: Dict[Any, Any], my_rank: int,
                     my_arr_ns: int,
                     my_seq: int) -> Optional[Dict[str, Any]]:
        """Level-2 live sampling (one watchdog sweep): compare the
        ``arr`` wall-ns stamps riding the heartbeat payloads and name
        the rank whose last collective arrival lags the freshest
        arrival the most — the slow rank, named BEFORE it becomes a
        hung rank. Returns (and stashes) the worst-lag context."""
        arrs: Dict[int, Tuple[int, int]] = {}
        for r, p in peers.items():
            if isinstance(p, dict) and int(p.get("arr", 0)):
                arrs[int(r)] = (int(p.get("seq", 0)), int(p["arr"]))
        if my_arr_ns:
            arrs[my_rank] = (my_seq, my_arr_ns)
        if len(arrs) < 2:
            return None
        newest = max(a for _s, a in arrs.values())
        worst_r = min(arrs, key=lambda r: arrs[r][1])
        ws, wa = arrs[worst_r]
        lag = max(0, newest - wa)
        pvar.record_hwm("skew_live_lag_ns", lag)
        self.live_worst = {"rank": worst_r, "seq": ws,
                           "behind_s": round(lag / 1e9, 3)}
        return self.live_worst


def enable(rank: int = 0, nranks: int = 0, level: int = 1,
           capacity: Optional[int] = None) -> SkewRecorder:
    """Raise the SKEW guard (idempotent)."""
    global SKEW
    if SKEW is None:
        SKEW = SkewRecorder(rank=rank, nranks=nranks, level=level,
                            capacity=capacity)
    else:
        SKEW.rank = rank
        if nranks:
            SKEW.nranks = nranks
        SKEW.level = max(SKEW.level, level)
    return SKEW


def disable() -> Optional[SkewRecorder]:
    global SKEW
    sk, SKEW = SKEW, None
    return sk
