"""Reduction operations (MPI_Op) — host kernels + user-defined ops.

Reference: ompi/mca/op/ — `base` C loops for every op×type pair plus SIMD
components (op/avx, op/aarch64) picked per-op by priority (op.h:56-75).
TPU-first: host kernels are numpy ufuncs (which are themselves SIMD); the
device plane reduces inside XLA (coll/xla), where the op maps to a lax
primitive. reduce_local mirrors MPI_Reduce_local
(ompi/mpi/c/reduce_local.c).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ompi_tpu.datatype.datatype import Datatype


class Op:
    """An MPI reduction operator.

    ``np_fn(a, b) -> result`` elementwise over numpy arrays;
    ``lax_name`` names the XLA lowering used by coll/xla (e.g. 'add');
    ``commute`` as per MPI_Op_create's commutativity flag.
    """

    def __init__(self, name: str, np_fn: Callable, commute: bool = True,
                 lax_name: Optional[str] = None) -> None:
        self.name = name
        self.np_fn = np_fn
        self.commute = commute
        self.lax_name = lax_name
        self.is_builtin = lax_name is not None or name.startswith("MPI_")

    def __call__(self, a, b):
        return self.np_fn(a, b)

    def __repr__(self) -> str:
        return f"Op({self.name})"


def _minloc(a, b):
    """MINLOC over (val, loc) struct arrays — lower loc wins ties."""
    take_b = (b["val"] < a["val"]) | ((b["val"] == a["val"])
                                      & (b["loc"] < a["loc"]))
    return np.where(take_b, b, a)


def _maxloc(a, b):
    take_b = (b["val"] > a["val"]) | ((b["val"] == a["val"])
                                      & (b["loc"] < a["loc"]))
    return np.where(take_b, b, a)


SUM = Op("MPI_SUM", np.add, lax_name="add")
PROD = Op("MPI_PROD", np.multiply, lax_name="mul")
MIN = Op("MPI_MIN", np.minimum, lax_name="min")
MAX = Op("MPI_MAX", np.maximum, lax_name="max")
LAND = Op("MPI_LAND", np.logical_and, lax_name="and")
LOR = Op("MPI_LOR", np.logical_or, lax_name="or")
LXOR = Op("MPI_LXOR", np.logical_xor, lax_name="xor")
BAND = Op("MPI_BAND", np.bitwise_and, lax_name="and")
BOR = Op("MPI_BOR", np.bitwise_or, lax_name="or")
BXOR = Op("MPI_BXOR", np.bitwise_xor, lax_name="xor")
MINLOC = Op("MPI_MINLOC", _minloc)
MAXLOC = Op("MPI_MAXLOC", _maxloc)
REPLACE = Op("MPI_REPLACE", lambda a, b: b, commute=False)
NO_OP = Op("MPI_NO_OP", lambda a, b: a, commute=False)

BUILTIN = {op.name: op for op in (
    SUM, PROD, MIN, MAX, LAND, LOR, LXOR, BAND, BOR, BXOR,
    MINLOC, MAXLOC, REPLACE, NO_OP)}


def create(fn: Callable, commute: bool = True, name: str = "user") -> Op:
    """MPI_Op_create. fn(invec, inoutvec) -> result elementwise arrays."""
    return Op(name, fn, commute=commute)


def reduce_local(inbuf: np.ndarray, inoutbuf: np.ndarray, op: Op,
                 dtype: Optional[Datatype] = None) -> None:
    """MPI_Reduce_local: inoutbuf = op(inbuf, inoutbuf), in place.

    Argument order matters for non-commutative user ops: inbuf is the
    'left' operand, matching MPI's accumulate-order semantics.
    """
    if isinstance(op.np_fn, np.ufunc):
        op.np_fn(inbuf, inoutbuf, out=inoutbuf, casting="same_kind")
    else:
        result = op.np_fn(inbuf, inoutbuf)
        np.copyto(inoutbuf, result, casting="same_kind")


def apply_bytes(a: bytes, b: bytearray, np_dtype, op: Op) -> None:
    """Reduce packed byte buffers in place: b = op(a, b) (used by coll).

    ``b`` must be a mutable buffer (bytearray / writable memoryview).
    """
    ia = np.frombuffer(a, dtype=np_dtype)
    ib = np.frombuffer(b, dtype=np_dtype)
    ib[:] = op.np_fn(ia, ib)
