"""Datatype objects and constructors.

Reference: ompi/datatype/ompi_datatype_create*.c for each constructor;
opal_datatype_optimize.c for the span-merging "optimized description";
lb/ub/extent semantics per MPI-3.1 §4.1.

TPU-first representation: the compiled form of a datatype is an (N,2) int64
numpy span table of half-open (offset, length) byte ranges — construction,
tiling and merging are vectorized numpy ops, never per-element Python loops
(big-count types are this fork's specialty). ``extent`` is the stride
between consecutive elements; ``lb`` may be negative or positive per MPI.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ompi_tpu.attr import AttrHost
from ompi_tpu.core import mpool as _mpool

#: tiled span tables per (derived dtype, count) — rcache analog
_span_cache = _mpool.Rcache()

try:  # bfloat16 as a first-class predefined type (TPU-native)
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None
_FP16 = np.dtype(np.float16)


def _as_span_array(spans) -> np.ndarray:
    arr = np.asarray(spans, dtype=np.int64)
    if arr.size == 0:
        return np.empty((0, 2), dtype=np.int64)
    return arr.reshape(-1, 2)


def _merge(arr: np.ndarray) -> np.ndarray:
    """Merge adjacent spans, vectorized (opal_datatype_optimize.c)."""
    if len(arr) == 0:
        return arr
    arr = arr[arr[:, 1] > 0]
    if len(arr) <= 1:
        return arr
    adjacent = arr[1:, 0] == arr[:-1, 0] + arr[:-1, 1]
    group_start = np.concatenate([[True], ~adjacent])
    idx = np.nonzero(group_start)[0]
    offs = arr[idx, 0]
    lens = np.add.reduceat(arr[:, 1], idx)
    return np.stack([offs, lens], axis=1)


#: cap on a materialized type descriptor (default ~1 GB of span table).
#: Big-count transfers belong on the API count — Send(buf, count=huge,
#: dtype=small) streams through the convertor's windowed span
#: generation with O(window) memory (the reference encodes such types
#: as O(1) DT_LOOP descriptors; a span table cannot, so we bound it).
#: A cvar so big-memory hosts can raise it (the bound rejects some
#: huge derived-type constructions at Type_*create time that would
#: previously have been attempted).
from ompi_tpu.core import cvar as _cvar

_max_spans_var = _cvar.register(
    "datatype_max_descriptor_spans", 1 << 26, int,
    help="Maximum spans a materialized derived-type descriptor may "
         "hold (each span is 16 bytes; the default caps descriptor "
         "memory at ~1 GB). Constructions above the cap raise at "
         "type-creation time: put the repetition in the transfer "
         "count instead — Send(buf, count, small_dtype) streams any "
         "count with O(window) memory. Raise on big-memory hosts to "
         "allow larger materialized types.", level=6)


def _tile(spans: np.ndarray, n: int, stride: int) -> np.ndarray:
    """n copies of a span table at byte stride, merged. Vectorized."""
    if n == 1:
        return _merge(spans)
    spans = _merge(spans)
    if len(spans) == 1 and stride == spans[0, 1]:
        # contiguous tiling collapses to one span
        return np.array([[spans[0, 0], stride * n]], dtype=np.int64)
    cap = _max_spans_var.get()
    if n * len(spans) > cap:
        raise ValueError(
            f"type descriptor would need {n * len(spans):,} spans "
            f"(> {cap:,}; cvar datatype_max_descriptor_spans); move "
            "the repetition to the transfer count — Send(buf, count, "
            "small_dtype) streams any count with O(1) descriptor "
            "memory")
    reps = np.arange(n, dtype=np.int64) * stride
    offs = (spans[None, :, 0] + reps[:, None]).reshape(-1)
    lens = np.broadcast_to(spans[None, :, 1],
                           (n, len(spans))).reshape(-1)
    return _merge(np.stack([offs, lens], axis=1))


def _pattern_of_np(dt: np.dtype):
    """Wire pattern of one packed element of a numpy dtype: a list of
    (unit_bytes, nbytes) segments in offset order — the typemap the
    heterogeneous convertor swaps by
    (opal_copy_functions_heterogeneous.c converts per typemap entry).
    unit 1 = raw bytes (padding, no swap); complex swaps per
    component."""
    dt = np.dtype(dt)
    if dt.names is None:
        if dt.subdtype is not None:
            # subarray field (e.g. ('<f4', (3,))): kind is 'V' but the
            # payload is n copies of the base scalar — swap per element,
            # not raw (raw would skip the byteswap and corrupt)
            base, shape = dt.subdtype
            n = int(np.prod(shape))
            inner = _pattern_of_np(base)
            return _merge_pattern(inner * n)
        if dt.kind == "V":  # opaque raw bytes: NEVER swapped (the
            # uniform numpy-byteswap path is an identity on void too)
            return [(1, dt.itemsize)]
        unit = dt.itemsize // 2 if dt.kind == "c" else dt.itemsize
        return [(max(unit, 1), dt.itemsize)]
    segs = []
    pos = 0
    for name in sorted(dt.names, key=lambda k: dt.fields[k][1]):
        fld, off = dt.fields[name][0], dt.fields[name][1]
        if off > pos:
            segs.append((1, off - pos))  # padding: raw
        segs.extend(_pattern_of_np(fld))
        pos = off + fld.itemsize
    if pos < dt.itemsize:
        segs.append((1, dt.itemsize - pos))
    return _merge_pattern(segs)


def _merge_pattern(segs):
    out = []
    for unit, nbytes in segs:
        if nbytes <= 0:
            continue
        if out and out[-1][0] == unit:
            out[-1] = (unit, out[-1][1] + nbytes)
        else:
            out.append((unit, nbytes))
    return out


def wire_pattern(d: "Datatype"):
    """ONE PERIOD of the (unit, nbytes) swap pattern of `d`'s packed
    stream — the stream is a repetition of this period (the inner
    typemap element), so the convertor tiles it by reshaping, never
    by materializing O(count) patterns. None when unknown (a raw
    span table with no type info — the heterogeneous path must
    reject it rather than corrupt)."""
    if d.pattern is not None:
        return d.pattern
    if d.base is not None:
        # scalar, complex, void, subarray and structured bases all
        # derive through ONE function — duplicating the scalar logic
        # here once skipped the subarray case and shipped a no-swap
        # pattern for subarray bases
        return _pattern_of_np(d.base) if d.size else []
    return None


def _elems_of_np(dt):
    """ONE packed element of a numpy dtype as (nbytes, nelems)
    segments for MPI_Get_elements: a complex scalar is ONE basic
    element (unlike the wire pattern's per-component swap units) and
    interior/trailing padding is ZERO elements."""
    dt = np.dtype(dt)
    if dt.names is None:
        if dt.subdtype is not None:
            base, shape = dt.subdtype
            return _elems_of_np(base) * int(np.prod(shape))
        if dt.kind == "V":
            return [(dt.itemsize, 0)]
        return [(dt.itemsize, 1)]
    segs = []
    pos = 0
    for name in sorted(dt.names, key=lambda k: dt.fields[k][1]):
        fld, off = dt.fields[name][0], dt.fields[name][1]
        if off > pos:
            segs.append((off - pos, 0))
        segs.extend(_elems_of_np(fld))
        pos = off + fld.itemsize
    if pos < dt.itemsize:
        segs.append((dt.itemsize - pos, 0))
    return segs


def element_pattern(d: "Datatype"):
    """ONE period of (nbytes, nelems) segments of ``d``'s packed
    stream — the basic-element decomposition MPI_Get_elements counts
    by (get_elements.c walks the typemap the same way). Derived via
    the numpy base where one exists, else through the constructor
    provenance; ``None`` when no decomposition is known (the caller
    reports MPI_UNDEFINED)."""
    if d.base is not None:
        return _elems_of_np(d.base) if d.size else []
    if d.combiner == "struct":
        ints, _, types = d.cargs
        out = []
        for bl, t in zip(ints[1:], types):
            if bl <= 0 or t.size == 0:
                continue
            p = element_pattern(t)
            if p is None:
                return None
            period = sum(nb for nb, _ in p)
            out.extend(p * ((bl * t.size) // period))
        return out
    if d.combiner in ("contiguous", "vector", "hvector", "indexed",
                      "hindexed", "indexed_block", "subarray",
                      "resized", "dup", "darray"):
        # the packed stream repeats the old type's element
        types = d.cargs[2]
        return element_pattern(types[0]) if types else None
    return None


class Datatype(AttrHost):
    """An MPI datatype: a byte-layout description over an (N,2) span table.

    Attribute caching (Set/Get/Delete_attr) comes from AttrHost."""

    # __weakref__: the span cache's invalidate-on-death hook
    # (mpool.buffer_key) needs weakref support — without it a recycled
    # id() could alias a dead dtype's cached tables
    __slots__ = ("spans", "size", "extent", "lb", "name", "base",
                 "committed", "pattern", "attrs", "combiner", "cargs",
                 "__weakref__")
    _attr_kind = "type"

    def __init__(self, spans, extent: int, lb: int = 0,
                 base: Optional[np.dtype] = None,
                 name: str = "derived", pattern=None) -> None:
        self.spans = _merge(_as_span_array(spans))
        self.size = int(self.spans[:, 1].sum()) if len(self.spans) else 0
        self.extent = int(extent)
        self.lb = int(lb)
        self.base = base
        self.name = name
        self.pattern = pattern  # mixed-layout wire pattern (see
        # wire_pattern); uniform-base types derive theirs on demand
        self.committed = False
        self.attrs = {}  # keyval attribute cache (ompi_tpu.attr)
        # constructor provenance (MPI_Type_get_envelope/_contents,
        # ompi/mpi/c/type_get_envelope.c): predefined until a
        # constructor stamps itself via _prov
        self.combiner = "named"
        self.cargs = ((), (), ())

    # -- introspection (MPI_Type_size / get_extent) ----------------------
    def Get_size(self) -> int:
        """MPI_Type_size: significant (non-gap) bytes per element."""
        return self.size

    def Get_extent(self) -> Tuple[int, int]:
        """MPI_Type_get_extent -> (lb, extent)."""
        return self.lb, self.extent

    def Get_true_extent(self) -> Tuple[int, int]:
        """MPI_Type_get_true_extent -> (true_lb, true_extent): the
        span of bytes the type ACTUALLY touches, ignoring lb/ub
        markers and resizing (type_get_true_extent.c)."""
        if len(self.spans) == 0:
            return 0, 0
        lo = int(self.spans[:, 0].min())
        hi = int((self.spans[:, 0] + self.spans[:, 1]).max())
        return lo, hi - lo

    @property
    def ub(self) -> int:
        return self.lb + self.extent

    @property
    def is_contiguous(self) -> bool:
        return (len(self.spans) == 1 and self.spans[0, 0] == 0
                and self.spans[0, 1] == self.extent and self.lb == 0)

    @property
    def has_gaps(self) -> bool:
        return not self.is_contiguous

    def merged_spans(self):
        return [tuple(map(int, s)) for s in self.spans]

    def commit(self) -> "Datatype":
        """MPI_Type_commit (the span table is already optimized)."""
        self.committed = True
        return self

    # -- introspection (MPI_Type_get_envelope / get_contents) ------------
    def Get_envelope(self):
        """MPI_Type_get_envelope (ompi/mpi/c/type_get_envelope.c):
        (num_integers, num_addresses, num_datatypes, combiner)."""
        ints, addrs, types = self.cargs
        return len(ints), len(addrs), len(types), self.combiner

    def Get_contents(self):
        """MPI_Type_get_contents (ompi/mpi/c/type_get_contents.c):
        (integers, addresses, datatypes) exactly as passed to the
        constructor (MPI-3.1 §4.1.13 per-combiner layout). Erroneous
        on predefined types, as in the reference."""
        if self.combiner == "named":
            from ompi_tpu import errors

            raise errors.MPIError(
                errors.ERR_TYPE,
                f"{self.name}: get_contents on a predefined type")
        ints, addrs, types = self.cargs
        return list(ints), list(addrs), list(types)

    def free(self) -> None:
        """MPI_Type_free: handles are GC'd; the visible effect is the
        attribute delete callbacks (ompi_attr_delete_all)."""
        if self.attrs:
            from ompi_tpu import attr as _attr

            _attr.delete_attrs(self, "type")

    def dup(self) -> "Datatype":
        d = Datatype(self.spans, self.extent, self.lb, self.base,
                     self.name + "_dup", pattern=self.pattern)
        _prov(d, "dup", (), (), (self,))
        if self.attrs:
            from ompi_tpu import attr as _attr

            _attr.copy_attrs(self, d, "type")
        return d

    def spans_for_count(self, count: int) -> np.ndarray:
        """(N,2) span table covering ``count`` consecutive elements.

        Tiled tables are cached in the registration cache (rcache
        analog — the reference caches the compiled ddt description the
        same way, opal_datatype_optimize.c): repeated sends of the same
        (derived dtype, count) skip the O(spans*count) rebuild; LRU
        eviction bounds memory for adversarial count diversity."""
        key = _mpool.buffer_key(self, _span_cache)  # id + death hook
        if key is None:  # no weakref support: uncacheable (a recycled
            return _tile(self.spans, count, self.extent)  # id aliases)
        per_count = _span_cache.lookup(key)
        if per_count is not None and count in per_count:
            return per_count[count]
        table = _tile(self.spans, count, self.extent)
        if per_count is None:
            per_count = {}
        per_count[count] = table
        _span_cache.insert(
            key, per_count, sum(t.nbytes for t in per_count.values()))
        return table

    def __repr__(self) -> str:
        return (f"Datatype({self.name}, size={self.size}, "
                f"extent={self.extent}, lb={self.lb}, "
                f"spans={len(self.spans)})")


# -- predefined types -----------------------------------------------------

def _predef(np_dtype, name: str) -> Datatype:
    dt = np.dtype(np_dtype)
    d = Datatype([(0, dt.itemsize)], dt.itemsize, base=dt, name=name)
    d.commit()
    return d


BYTE = _predef(np.uint8, "MPI_BYTE")
PACKED = _predef(np.uint8, "MPI_PACKED")
CHAR = _predef(np.int8, "MPI_CHAR")
INT8 = _predef(np.int8, "MPI_INT8_T")
UINT8 = _predef(np.uint8, "MPI_UINT8_T")
INT16 = _predef(np.int16, "MPI_INT16_T")
UINT16 = _predef(np.uint16, "MPI_UINT16_T")
INT32 = _predef(np.int32, "MPI_INT32_T")
UINT32 = _predef(np.uint32, "MPI_UINT32_T")
INT64 = _predef(np.int64, "MPI_INT64_T")
UINT64 = _predef(np.uint64, "MPI_UINT64_T")
INT = INT32
LONG = INT64
FLOAT = _predef(np.float32, "MPI_FLOAT")
DOUBLE = _predef(np.float64, "MPI_DOUBLE")
FLOAT16 = _predef(_FP16, "MPI_FLOAT16")
BOOL = _predef(np.bool_, "MPI_C_BOOL")
COMPLEX64 = _predef(np.complex64, "MPI_C_FLOAT_COMPLEX")
COMPLEX128 = _predef(np.complex128, "MPI_C_DOUBLE_COMPLEX")
if _BF16 is not None:
    BFLOAT16 = _predef(_BF16, "MPI_BFLOAT16")  # TPU-native extension
else:  # pragma: no cover
    BFLOAT16 = FLOAT16

# MINLOC/MAXLOC pair types (MPI-3.1 §5.9.4) as numpy struct dtypes
_float_int = np.dtype([("val", np.float32), ("loc", np.int32)])
_double_int = np.dtype([("val", np.float64), ("loc", np.int32)])
_long_int = np.dtype([("val", np.int64), ("loc", np.int32)])
_2int = np.dtype([("val", np.int32), ("loc", np.int32)])
_short_int = np.dtype([("val", np.int16), ("loc", np.int32)])
FLOAT_INT = _predef(_float_int, "MPI_FLOAT_INT")
DOUBLE_INT = _predef(_double_int, "MPI_DOUBLE_INT")
LONG_INT = _predef(_long_int, "MPI_LONG_INT")
TWOINT = _predef(_2int, "MPI_2INT")
SHORT_INT = _predef(_short_int, "MPI_SHORT_INT")

PREDEFINED = {
    d.name: d for d in (
        BYTE, PACKED, CHAR, INT8, UINT8, INT16, UINT16, INT32, UINT32,
        INT64, UINT64, FLOAT, DOUBLE, FLOAT16, BFLOAT16, BOOL, COMPLEX64,
        COMPLEX128, FLOAT_INT, DOUBLE_INT, LONG_INT, TWOINT, SHORT_INT)
}

_NP_CACHE = {}


def from_numpy_dtype(dt) -> Datatype:
    """Map a numpy dtype to a (possibly cached) predefined Datatype."""
    dt = np.dtype(dt)
    key = dt.str if dt.names is None else str(dt)
    got = _NP_CACHE.get(key)
    if got is None:
        for d in PREDEFINED.values():
            if d.base == dt:
                got = d
                break
        else:
            got = _predef(dt, f"MPI_NP_{key}")
        _NP_CACHE[key] = got
    return got


# -- constructors (MPI_Type_*) -------------------------------------------

def _prov(d: Datatype, combiner: str, ints, addrs, types) -> Datatype:
    """Stamp constructor provenance (the MPI-3.1 §4.1.13 envelope/
    contents record): argument lists exactly as the user passed them."""
    d.combiner = combiner
    d.cargs = (tuple(ints), tuple(addrs), tuple(types))
    return d


def contiguous(count: int, old: Datatype) -> Datatype:
    """MPI_Type_contiguous (ompi_datatype_create_contiguous.c)."""
    spans = _tile(old.spans, count, old.extent)
    base = old.base if old.is_contiguous else None
    # the packed stream stays periodic in old's element: ONE period
    # suffices (never tile O(count) patterns at type creation)
    pat = wire_pattern(old) if base is None else None
    return _prov(Datatype(spans, count * old.extent, lb=old.lb,
                          base=base, name="contiguous", pattern=pat),
                 "contiguous", (count,), (), (old,))


def vector(count: int, blocklength: int, stride: int,
           old: Datatype) -> Datatype:
    """MPI_Type_vector — stride in elements of old."""
    return _prov(hvector(count, blocklength, stride * old.extent, old),
                 "vector", (count, blocklength, stride), (), (old,))


def hvector(count: int, blocklength: int, stride_bytes: int,
            old: Datatype) -> Datatype:
    """MPI_Type_create_hvector — stride in bytes.

    lb/ub derive from old's markers (MPI-3.1 §4.1.7), so resized inner
    types tile at their resized extent.
    """
    block = _tile(old.spans, blocklength, old.extent)
    spans = _tile(block, count, stride_bytes)
    # marker arithmetic over all placements org = i*stride + b*extent
    placements_lo = min(0, (count - 1) * stride_bytes)
    placements_hi = max(0, (count - 1) * stride_bytes) \
        + (blocklength - 1) * old.extent
    lb = placements_lo + old.lb
    ub = placements_hi + old.ub
    # a vector of a uniform element keeps that element as its typemap
    # base (external32 swaps by it); mixed elements carry ONE period
    # of their wire pattern (the packed stream repeats it)
    pat = None
    if old.base is None or old.base.names is not None:
        pat = wire_pattern(old)
    return _prov(Datatype(spans, ub - lb, lb=lb, base=old.base,
                          name="vector", pattern=pat),
                 "hvector", (count, blocklength), (stride_bytes,),
                 (old,))


def indexed(blocklengths: Sequence[int], displs: Sequence[int],
            old: Datatype) -> Datatype:
    """MPI_Type_indexed — displacements in elements of old."""
    bl = list(blocklengths)
    displs = list(displs)
    return _prov(hindexed(bl, [d * old.extent for d in displs], old),
                 "indexed", (len(bl), *bl, *displs), (), (old,))


def hindexed(blocklengths: Sequence[int], displs_bytes: Sequence[int],
             old: Datatype) -> Datatype:
    """MPI_Type_create_hindexed — displacements in bytes. Pack order
    follows the type map (declaration) order per MPI-3.1 §4.1, exactly
    like create_struct with a single repeated type."""
    bl = list(blocklengths)
    displs_bytes = list(displs_bytes)
    d = create_struct(bl, displs_bytes, [old] * len(bl))
    d.name = "indexed"
    return _prov(d, "hindexed", (len(bl), *bl), tuple(displs_bytes),
                 (old,))


def indexed_block(blocklength: int, displs: Sequence[int],
                  old: Datatype) -> Datatype:
    """MPI_Type_create_indexed_block."""
    displs = list(displs)
    return _prov(indexed([blocklength] * len(displs), displs, old),
                 "indexed_block", (len(displs), blocklength, *displs),
                 (), (old,))


def create_struct(blocklengths: Sequence[int], displs_bytes: Sequence[int],
                  types: Sequence[Datatype]) -> Datatype:
    """MPI_Type_create_struct."""
    # materialize once: callers may pass one-shot iterables, and the
    # provenance stamp below re-reads every argument list
    blocklengths = list(blocklengths)
    displs_bytes = list(displs_bytes)
    types = list(types)
    parts = []
    lb = None
    ub = None
    for bl, disp, t in zip(blocklengths, displs_bytes, types):
        if bl <= 0:
            continue
        block = _tile(t.spans, bl, t.extent).copy()
        block[:, 0] += disp
        parts.append(block)
        this_lb = disp + t.lb
        this_ub = disp + (bl - 1) * t.extent + t.ub
        lb = this_lb if lb is None else min(lb, this_lb)
        ub = this_ub if ub is None else max(ub, this_ub)
    if not parts:  # zero-count struct is still a DERIVED type with
        # a contents record (MPI_Type_create_struct with count 0)
        return _prov(Datatype([], 0, name="struct"),
                     "struct", (len(blocklengths), *blocklengths),
                     tuple(displs_bytes), tuple(types))
    spans = np.concatenate(parts)
    bases = {t.base for t in types if t.size}
    base = bases.pop() if len(bases) == 1 else None  # uniform only
    pat = None
    if base is None:  # mixed: compose the wire pattern in pack
        # (declaration) order so the hetero convertor can swap per
        # typemap entry (opal_copy_functions_heterogeneous.c). Each
        # field contributes bl*t.size bytes = its period tiled; a
        # pathological pattern (huge blocklengths of mixed fields)
        # degrades to None — the hetero path then rejects instead of
        # building an unbounded descriptor.
        pat = []
        for bl, t in zip(blocklengths, types):
            if bl <= 0 or t.size == 0:
                continue
            p = wire_pattern(t)
            if p is None:
                pat = None
                break
            period = sum(nb for _, nb in p)
            reps = (bl * t.size) // period
            if len(pat) + reps * len(p) > (1 << 16):
                pat = None
                break
            pat.extend(p * reps)
        pat = _merge_pattern(pat) if pat is not None else None
    # struct pack order follows declaration order (MPI pack traversal),
    # which for typical ascending-displacement structs is ascending
    return _prov(Datatype(spans, ub - lb, lb=lb, base=base,
                          name="struct", pattern=pat),
                 "struct", (len(list(blocklengths)),
                            *blocklengths), tuple(displs_bytes),
                 tuple(types))


def subarray(sizes: Sequence[int], subsizes: Sequence[int],
             starts: Sequence[int], old: Datatype,
             order: str = "C") -> Datatype:
    """MPI_Type_create_subarray — an ndim tile out of a larger array."""
    ndim = len(sizes)
    orig = (list(sizes), list(subsizes), list(starts))
    if order != "C":
        sizes = list(reversed(sizes))
        subsizes = list(reversed(subsizes))
        starts = list(reversed(starts))
    strides = [1] * ndim
    for i in range(ndim - 2, -1, -1):
        strides[i] = strides[i + 1] * sizes[i + 1]
    idx = np.indices(subsizes).reshape(ndim, -1)
    flat = np.zeros(idx.shape[1], dtype=np.int64)
    for d in range(ndim):
        flat += (idx[d] + starts[d]) * strides[d]
    flat.sort()
    if not old.is_contiguous:
        raise NotImplementedError(
            "subarray over non-contiguous base types")
    offs = flat * old.extent
    lens = np.full(len(offs), old.extent, dtype=np.int64)
    spans = np.stack([offs, lens], axis=1)
    total = 1
    for s in sizes:
        total *= s
    return _prov(Datatype(spans, total * old.extent, name="subarray"),
                 "subarray", (ndim, *orig[0], *orig[1], *orig[2],
                              order), (), (old,))


def resized(old: Datatype, lb: int, extent: int) -> Datatype:
    """MPI_Type_create_resized."""
    return _prov(Datatype(old.spans, extent, lb=lb, base=old.base,
                          name=old.name + "_resized",
                          pattern=old.pattern),
                 "resized", (), (lb, extent), (old,))


# -- darray (MPI_Type_create_darray, ompi/mpi/c/type_create_darray.c) -----

DISTRIBUTE_NONE = "none"
DISTRIBUTE_BLOCK = "block"
DISTRIBUTE_CYCLIC = "cyclic"
DISTRIBUTE_DFLT_DARG = -1


def _darray_dim_indices(gsize: int, distrib: str, darg: int,
                        psize: int, coord: int) -> np.ndarray:
    """Global indices along one dimension owned by process `coord` of
    `psize` (HPF block/cyclic rules, type_create_darray.c helpers)."""
    if distrib == DISTRIBUTE_NONE:
        if psize != 1:
            raise ValueError("DISTRIBUTE_NONE requires psize 1")
        return np.arange(gsize, dtype=np.int64)
    if distrib == DISTRIBUTE_BLOCK:
        bsize = (-(-gsize // psize) if darg == DISTRIBUTE_DFLT_DARG
                 else int(darg))
        if bsize * psize < gsize:
            raise ValueError(
                f"block darg {bsize} x {psize} procs < gsize {gsize}")
        lo = coord * bsize
        return np.arange(lo, min(lo + bsize, gsize), dtype=np.int64)
    if distrib == DISTRIBUTE_CYCLIC:
        k = 1 if darg == DISTRIBUTE_DFLT_DARG else int(darg)
        period = k * psize
        starts = np.arange(coord * k, gsize, period, dtype=np.int64)
        out = (starts[:, None] + np.arange(k, dtype=np.int64)[None, :])
        return out.reshape(-1)[out.reshape(-1) < gsize]
    raise ValueError(f"unknown distribution {distrib!r}")


def darray(size: int, rank: int, gsizes: Sequence[int],
           distribs: Sequence[str], dargs: Sequence[int],
           psizes: Sequence[int], old: Datatype,
           order: str = "C") -> Datatype:
    """MPI_Type_create_darray: the HPF block/cyclic decomposition of
    an ndim global array over a process grid — THE fileview type for
    distributed HPC-IO. Process grid ordering is always row-major
    (MPI-3.1 §4.1.3); ``order`` describes the array storage.

    Extent spans the whole global array so fileviews tile correctly.
    """
    gsizes, distribs, dargs, psizes = (list(gsizes), list(distribs),
                                       list(dargs), list(psizes))
    ndim = len(gsizes)
    if int(np.prod(psizes)) != size:
        raise ValueError(f"psizes {psizes} != size {size}")
    if not old.is_contiguous:
        raise NotImplementedError(
            "darray over non-contiguous base types")
    orig = (gsizes, distribs, dargs, psizes)
    # rank -> grid coords, row-major over psizes
    coords = []
    stride = size
    rem = rank
    for p in psizes:
        stride //= p
        coords.append(rem // stride)
        rem %= stride
    gs, ds, da, ps = (list(gsizes), list(distribs), list(dargs),
                      list(psizes))
    if order != "C":  # F storage: reverse dims, keep coords aligned
        gs, ds, da, ps = (list(reversed(gs)), list(reversed(ds)),
                          list(reversed(da)), list(reversed(ps)))
        coords = list(reversed(coords))
    owned = [_darray_dim_indices(gs[d], ds[d], da[d], ps[d], coords[d])
             for d in range(ndim)]
    strides = [1] * ndim
    for i in range(ndim - 2, -1, -1):
        strides[i] = strides[i + 1] * gs[i + 1]
    if any(len(o) == 0 for o in owned):
        flat = np.empty(0, dtype=np.int64)
    else:
        grids = np.meshgrid(*owned, indexing="ij")
        flat = sum(g.astype(np.int64) * strides[d]
                   for d, g in enumerate(grids)).reshape(-1)
        flat.sort()
    offs = flat * old.extent
    lens = np.full(len(offs), old.extent, dtype=np.int64)
    spans = (np.stack([offs, lens], axis=1) if len(offs)
             else np.empty((0, 2), dtype=np.int64))
    total = int(np.prod(gs)) if ndim else 0
    return _prov(Datatype(spans, total * old.extent, name="darray"),
                 "darray", (size, rank, ndim, *orig[0], *orig[1],
                            *orig[2], *orig[3], order), (), (old,))
