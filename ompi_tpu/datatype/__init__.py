"""Datatype engine — MPI derived datatypes + pack/unpack convertor.

Reference: opal/datatype/ (the convertor VM: datatypes compile to vectors of
ddt_elem_desc_t walked by pack/unpack iterators with partial-completion
state, opal_datatype_internal.h:115-133) and ompi/datatype/ (the MPI face).

TPU-first redesign: the "compiled" form here is a flat span table
(offset, length byte ranges per element) held in numpy arrays — packing is
vectorized gather/scatter over a byte view instead of an interpreter loop,
which is also the form a future C kernel or on-device gather consumes.
Partial (pipelined) pack/unpack keeps a byte position, like the reference
convertor's stack state.
"""

from ompi_tpu.datatype.datatype import (  # noqa: F401
    Datatype,
    PREDEFINED,
    BYTE,
    PACKED,
    CHAR,
    INT8,
    UINT8,
    INT16,
    UINT16,
    INT32,
    UINT32,
    INT64,
    UINT64,
    INT,
    LONG,
    FLOAT,
    DOUBLE,
    FLOAT16,
    BFLOAT16,
    BOOL,
    COMPLEX64,
    COMPLEX128,
    FLOAT_INT,
    DOUBLE_INT,
    LONG_INT,
    TWOINT,
    SHORT_INT,
    from_numpy_dtype,
    contiguous,
    vector,
    hvector,
    indexed,
    hindexed,
    indexed_block,
    create_struct,
    subarray,
    resized,
    darray,
    DISTRIBUTE_BLOCK,
    DISTRIBUTE_CYCLIC,
    DISTRIBUTE_NONE,
    DISTRIBUTE_DFLT_DARG,
)
from ompi_tpu.datatype.convertor import Convertor  # noqa: F401
