"""Convertor — pack/unpack engine with partial-completion state.

Reference: opal/datatype/opal_convertor.{h,c} — prepare_for_send/recv,
opal_convertor_pack/unpack (opal_convertor.h:136-142) with position state
for pipelined fragments, optional checksum (opal_convertor.h:113-130).

TPU-first: the hot path is numpy slicing over a byte view (vectorized via
the span table); a future native kernel can consume the same span table.
Device buffers (jax arrays) are handled by the accelerator framework at a
higher level (staged D2H/H2D), as the reference does via CONVERTOR_ACCELERATOR.
"""

from __future__ import annotations

import zlib
from typing import Optional, Union

import numpy as np

from ompi_tpu.datatype.datatype import Datatype, from_numpy_dtype

Buffer = Union[np.ndarray, bytearray, memoryview, bytes]

#: above this many total spans the convertor switches from a
#: materialized span table to windowed per-range generation (big-count)
_SPAN_WINDOW_LIMIT = 1 << 22


def _pattern_perm(pattern) -> np.ndarray:
    """Byte permutation applying a typemap wire pattern's byteswap to
    ONE packed element: each (unit, nbytes) segment reverses bytes
    within every unit (unit 1 = raw/padding, identity)."""
    parts = []
    pos = 0
    for unit, nbytes in pattern:
        if unit <= 1:
            parts.append(np.arange(pos, pos + nbytes, dtype=np.int64))
        else:
            k = nbytes // unit
            parts.append(
                (pos + np.arange(k * unit, dtype=np.int64)
                 .reshape(k, unit)[:, ::-1]).reshape(-1))
        pos += nbytes
    return (np.concatenate(parts) if parts
            else np.empty(0, np.int64))


def _writable_byte_view(buf: Buffer) -> np.ndarray:
    if isinstance(buf, np.ndarray):
        return buf.view(np.uint8).reshape(-1)
    mv = memoryview(buf)
    if mv.readonly:
        raise ValueError("buffer not writable")
    arr = np.frombuffer(mv, dtype=np.uint8)
    arr.flags.writeable = True
    return arr


class Convertor:
    """Pack/unpack iterator over (buffer, datatype, count).

    Supports full and partial (bounded-size) pack/unpack, tracking a byte
    position like the reference convertor stack. ``checksum=True`` keeps a
    running CRC32 of packed bytes (reference CONVERTOR_WITH_CHECKSUM).
    """

    def __init__(self, buf: Buffer, dtype: Datatype, count: int,
                 checksum: bool = False) -> None:
        self.dtype = dtype
        self.count = count
        self.packed_size = dtype.size * count
        self.position = 0
        self.checksum = 0 if checksum else None
        self._buf = buf
        # heterogeneous wire conversion (reference:
        # opal_copy_functions_heterogeneous.c): see set_hetero
        self.wire_swap = False
        self.wire_round = False
        self._swap_unit = 0
        self._swap_dtype = None  # uniform-base fast path; mixed
        self._swap_perm = None   # layouts use the pattern permutation
        if dtype.lb < 0:
            # MPI allows negative lb (bytes before the buffer pointer);
            # with array-backed buffers that memory does not exist. The
            # caller must shift the buffer origin (resized / MPI_BOTTOM
            # style) — fail loudly instead of wrapping numpy indices.
            raise ValueError(
                f"datatype {dtype.name} has negative lb={dtype.lb}; "
                "pass a buffer view that starts at lb or resize the type")
        self._windowed = False
        if dtype.is_contiguous:
            self._spans = None  # fast path: one contiguous range
        elif count * len(dtype.spans) > _SPAN_WINDOW_LIMIT:
            # big-count (the fork's defining feature,
            # ompi/util/count_disp_array.h:21-45 size_t count arrays):
            # a materialized span table would be O(count) memory, so
            # window-generate spans per pack/unpack range instead —
            # the reference's iterative pack stack never materializes
            # the full description either (opal_datatype_pack.c).
            self._windowed = True
            self._spans = None
        else:
            self._spans = dtype.spans_for_count(count)
            self._cum = np.concatenate(
                [[0], np.cumsum(self._spans[:, 1])])

    # -- helpers ----------------------------------------------------------
    def _flat(self, writable: bool) -> np.ndarray:
        if writable:
            return _writable_byte_view(self._buf)
        if isinstance(self._buf, np.ndarray):
            return self._buf.view(np.uint8).reshape(-1)
        return np.frombuffer(memoryview(self._buf), dtype=np.uint8)

    @property
    def done(self) -> bool:
        return self.position >= self.packed_size

    @property
    def is_contig_layout(self) -> bool:
        """True iff packed bytes == the buffer's own byte layout (the
        zero-copy precondition). NOTE: ``_spans is None`` alone does
        NOT mean contiguous — windowed big-count convertors also carry
        no materialized table while being non-contiguous."""
        return self._spans is None and not self._windowed

    def set_position(self, pos: int) -> None:
        """Reposition (pipelined restart). Restarting from 0 resets the
        running checksum; repositioning mid-stream with checksumming on
        would corrupt it, so that is rejected."""
        if self.checksum is not None:
            if pos == 0:
                self.checksum = 0
            elif pos != self.position:
                raise ValueError(
                    "cannot reposition a checksumming convertor "
                    "mid-stream (restart from 0)")
        self.position = pos

    # -- heterogeneous wire conversion ------------------------------------
    def set_hetero(self, swap: bool) -> None:
        """Cross-architecture peer (reference:
        opal_copy_functions_heterogeneous.c; the arch descriptor of
        opal/util/arch.c rides the modex). The packed wire format is
        element-dense, so conversion = per-typemap-entry byte reversal
        on the wire. ``swap=False`` still enables window ROUNDING to
        whole elements (a swapping peer must never see a split
        element); ``swap=True`` also reverses bytes.

        Uniform-base layouts swap with one vectorized byteswap; mixed
        layouts (MINLOC pairs, structs of different-size fields) swap
        through their wire pattern — a per-element byte permutation
        derived from the typemap (datatype.wire_pattern), with window
        rounding coarsened to whole packed elements so the pattern
        always applies at offset 0."""
        base = self.dtype.base
        if base is not None and base.names is None:
            self._swap_unit = int(base.itemsize)
            self._swap_dtype = base
            self.wire_round = True
            self.wire_swap = swap and self._swap_unit > 1
            return
        from ompi_tpu.datatype.datatype import wire_pattern

        pat = wire_pattern(self.dtype)
        if pat is None:
            raise ValueError(
                f"datatype {self.dtype.name!r} has no typemap wire "
                "pattern (raw span table); cross-architecture "
                "transfer of unknown layouts is unsupported "
                "(convert on the host first)")
        self._swap_dtype = None
        # the pattern is ONE PERIOD of the packed stream: windows
        # round to the period and the permutation applies by reshape
        self._swap_unit = int(sum(nb for _, nb in pat)) or 1
        self._swap_perm = _pattern_perm(pat)
        self.wire_round = True
        self.wire_swap = swap and any(u > 1 for u, _ in pat)

    def _swap_bytes(self, data: bytes) -> bytes:
        # per-COMPONENT byteswap (complex values swap each float
        # half; whole-element reversal would exchange re/im) — the
        # same numpy semantics the external32 _swap_wire path uses
        if self._swap_dtype is not None:
            return np.frombuffer(
                data, dtype=self._swap_dtype).byteswap().tobytes()
        # mixed layout: apply the per-element typemap permutation
        arr = np.frombuffer(data, np.uint8).reshape(-1,
                                                    self._swap_unit)
        return arr[:, self._swap_perm].tobytes()

    # -- pack -------------------------------------------------------------
    def pack(self, max_bytes: Optional[int] = None) -> bytes:
        """Pack up to max_bytes from the current position; advances it."""
        start = self.position
        end = self.packed_size if max_bytes is None else \
            min(self.packed_size, start + max_bytes)
        if self.wire_round and end < self.packed_size:
            # whole elements per window: the swapping side reverses
            # per element and must never see one split across frames
            end = start + (end - start) // self._swap_unit \
                * self._swap_unit
            if end <= start:
                raise ValueError(
                    f"pack window {max_bytes} smaller than the "
                    f"{self._swap_unit}-byte element of a "
                    "heterogeneous transfer")
        if end <= start:
            return b""
        src = self._flat(writable=False)
        if self._windowed:
            out = self._gather_win(src, start, end)
        elif self._spans is None:
            out = src[start:end].tobytes()
        elif start == 0 and end == self.packed_size:
            out = self._move_full(src, scatter=False)
        else:
            out = self._gather(src, start, end)
        self.position = end
        if self.wire_swap:
            out = self._swap_bytes(out)  # wire order = advertised arch
        if self.checksum is not None:  # checksums cover WIRE bytes
            self.checksum = zlib.crc32(out, self.checksum)
        return out

    def _move_full(self, flat: np.ndarray, scatter: bool,
                   wire: Optional[np.ndarray] = None):
        """Whole-layout byte movement: per-span memcpy in the native
        core when built (the opal_datatype_pack.c hot loop), else the
        vectorized fancy-index fallback. flat must be a contiguous
        uint8 view of the user buffer."""
        from ompi_tpu.core import native

        L = native.lib()
        n = self.packed_size
        if L is not None and flat.flags["C_CONTIGUOUS"]:
            spans = np.ascontiguousarray(self._spans, dtype=np.int64)
            if scatter:
                w = np.ascontiguousarray(wire)
                L.otpu_scatter_spans(w.ctypes.data, spans.ctypes.data,
                                     len(spans), flat.ctypes.data)
                return None
            out = np.empty(n, dtype=np.uint8)
            L.otpu_gather_spans(flat.ctypes.data, spans.ctypes.data,
                                len(spans), out.ctypes.data)
            return out.tobytes()
        if scatter:
            flat[self._gather_index()] = wire
            return None
        return flat[self._gather_index()].tobytes()

    def _gather_index(self) -> np.ndarray:
        """Flat byte-index vector for the whole layout — one vectorized
        fancy-index replaces the per-span interpreter loop (the compiled
        form a native/pallas gather kernel consumes as-is)."""
        idx = getattr(self, "_idx", None)
        if idx is None:
            spans, cum = self._spans, self._cum
            lens = spans[:, 1]
            idx = (np.repeat(spans[:, 0], lens)
                   + np.arange(int(cum[-1]), dtype=np.int64)
                   - np.repeat(cum[:-1], lens))
            self._idx = idx
        return idx

    def _gather(self, src: np.ndarray, start: int, end: int) -> bytes:
        return _gather_range(src, self._spans, self._cum, start,
                             end).tobytes()

    # -- big-count windowed movement --------------------------------------
    def _window_spans(self, e0: int, e1: int):
        """Span table + packed-byte cumsum for elements [e0, e1) —
        generated on demand so memory is O(window), not O(count)."""
        espans = self.dtype.spans
        base = np.arange(e0, e1, dtype=np.int64) * self.dtype.extent
        offs = (espans[:, 0][None, :] + base[:, None]).reshape(-1)
        lens = np.tile(espans[:, 1], e1 - e0)
        spans = np.stack([offs, lens], axis=1)
        return spans, np.concatenate(([0], np.cumsum(lens)))

    def _win_iter(self, start: int, end: int):
        """Yield (window spans, window cum, local start, local end,
        out position) chunks covering packed bytes [start, end)."""
        esize = self.dtype.size
        W = max(1, _SPAN_WINDOW_LIMIT //
                max(1, len(self.dtype.spans)))
        last = (end - 1) // esize + 1  # first element past the range:
        # never generate spans beyond what the fragment touches (a
        # 64KB fragment must cost O(fragment), not O(window limit))
        e = start // esize
        pos = 0
        while pos < end - start:
            we = min(self.count, e + W, last)
            spans, cum = self._window_spans(e, we)
            wb0 = e * esize
            s = max(start, wb0) - wb0
            t = min(end, we * esize) - wb0
            yield spans, cum, s, t, pos
            pos += t - s
            e = we

    def _gather_win(self, src: np.ndarray, start: int,
                    end: int) -> bytes:
        out = np.empty(end - start, np.uint8)
        for spans, cum, s, t, pos in self._win_iter(start, end):
            out[pos:pos + (t - s)] = _gather_range(src, spans, cum, s, t)
        return out.tobytes()

    def _scatter_win(self, dst: np.ndarray, src: np.ndarray,
                     start: int, end: int) -> None:
        for spans, cum, s, t, pos in self._win_iter(start, end):
            _scatter_range(dst, src[pos:pos + (t - s)], spans, cum, s, t)

    # -- unpack -----------------------------------------------------------
    def unpack(self, data: bytes) -> int:
        """Unpack bytes at the current position; returns bytes consumed."""
        if not data:
            return 0
        dst = self._flat(writable=True)
        start = self.position
        end = min(self.packed_size, start + len(data))
        n = end - start
        if self.wire_swap:
            if n % self._swap_unit:
                raise ValueError(
                    f"heterogeneous frame of {n} bytes splits a "
                    f"{self._swap_unit}-byte element (peer did not "
                    "round its windows)")
            src = np.frombuffer(self._swap_bytes(data[:n]),
                                dtype=np.uint8)
        else:
            src = np.frombuffer(data, dtype=np.uint8, count=n)
        if self._windowed:
            self._scatter_win(dst, src, start, end)
        elif self._spans is None:
            dst[start:end] = src
        elif start == 0 and end == self.packed_size:
            self._move_full(dst, scatter=True, wire=src)
        else:
            self._scatter(dst, src, start, end)
        self.position = end
        if self.checksum is not None:
            self.checksum = zlib.crc32(data[:n], self.checksum)
        return n

    def _scatter(self, dst: np.ndarray, src: np.ndarray,
                 start: int, end: int) -> None:
        _scatter_range(dst, src, self._spans, self._cum, start, end)


_SPAN_LOOP_MAX = 64  # below this a python loop beats index building


def _range_index(spans: np.ndarray, cum: np.ndarray, start: int,
                 end: int) -> np.ndarray:
    """Flat byte-index vector for packed range [start, end) — the
    vectorized movement the materialized path gets from _gather_index,
    built for just the touched spans (O(range), not O(layout))."""
    i0 = int(np.searchsorted(cum, start, side="right")) - 1
    i1 = int(np.searchsorted(cum, end, side="left"))
    offs = spans[i0:i1, 0].copy()
    lens = spans[i0:i1, 1].copy()
    head = start - int(cum[i0])
    if head > 0:
        offs[0] += head
        lens[0] -= head
    tail = int(cum[i1]) - end
    if tail > 0:
        lens[-1] -= tail
    n = int(lens.sum())
    starts = np.concatenate(([0], np.cumsum(lens[:-1])))
    return (np.repeat(offs, lens)
            + np.arange(n, dtype=np.int64)
            - np.repeat(starts, lens))


def _gather_range(src: np.ndarray, spans: np.ndarray, cum: np.ndarray,
                  start: int, end: int) -> np.ndarray:
    """Collect packed bytes [start, end) (cum coordinates) from src."""
    i0 = int(np.searchsorted(cum, start, side="right")) - 1
    i1 = int(np.searchsorted(cum, end, side="left"))
    if i1 - i0 > _SPAN_LOOP_MAX:
        return src[_range_index(spans, cum, start, end)]
    parts = []
    for i in range(i0, i1):
        off, ln = int(spans[i, 0]), int(spans[i, 1])
        s0 = max(0, start - int(cum[i]))
        s1 = min(ln, end - int(cum[i]))
        parts.append(src[off + s0:off + s1])
    return np.concatenate(parts) if parts else \
        np.empty(0, dtype=np.uint8)


def _scatter_range(dst: np.ndarray, src: np.ndarray, spans: np.ndarray,
                   cum: np.ndarray, start: int, end: int) -> None:
    """Place packed bytes [start, end) (cum coordinates) into dst."""
    i0 = int(np.searchsorted(cum, start, side="right")) - 1
    i1 = int(np.searchsorted(cum, end, side="left"))
    if i1 - i0 > _SPAN_LOOP_MAX:
        dst[_range_index(spans, cum, start, end)] = src[:end - start]
        return
    pos = 0
    for i in range(i0, i1):
        off, ln = int(spans[i, 0]), int(spans[i, 1])
        s0 = max(0, start - int(cum[i]))
        s1 = min(ln, end - int(cum[i]))
        take = s1 - s0
        dst[off + s0:off + s1] = src[pos:pos + take]
        pos += take


def pack_external(datarep: str, buf: Buffer, dtype: Datatype,
                  count: int) -> bytes:
    """MPI_Pack_external: canonical big-endian 'external32' wire form
    (reference: opal/datatype's external32 path +
    opal_copy_functions_heterogeneous.c). The element type is taken
    from the buffer — external32's fixed sizes coincide with the
    native numpy sizes, so only byte order changes."""
    if datarep != "external32":
        from ompi_tpu import errors

        raise errors.MPIError(errors.ERR_ARG,
                              f"unknown datarep {datarep!r}")
    wire = pack(buf, dtype, count)
    return _swap_wire(wire, _elem_dtype(buf, dtype))


def unpack_external(datarep: str, data: bytes, buf: Buffer,
                    dtype: Datatype, count: int) -> int:
    """MPI_Unpack_external (inverse of pack_external)."""
    if datarep != "external32":
        from ompi_tpu import errors

        raise errors.MPIError(errors.ERR_ARG,
                              f"unknown datarep {datarep!r}")
    return unpack(_swap_wire(bytes(data), _elem_dtype(buf, dtype)),
                  buf, dtype, count)


def _elem_dtype(buf, dtype: Datatype) -> np.dtype:
    """The element REPRESENTATION to swap by: a typed buffer's own
    dtype governs (an already-big-endian buffer needs no swap); a
    raw-byte buffer falls back to the Datatype's typemap base in
    native order (predefined/contiguous/vector/indexed propagate a
    uniform base). Raw bytes under a baseless datatype are rejected —
    guessing would silently skip the canonical swap."""
    from ompi_tpu import errors

    elem = np.asarray(buf).dtype
    raw = (elem.names is not None or elem.kind in ("V", "S")
           or elem.itemsize == 1)
    if not raw:
        return elem
    base = getattr(dtype, "base", None)
    if base is not None:
        # raw byte staging: the datatype's logical element governs,
        # in native representation
        return np.dtype(base)
    raise errors.MPIError(
        errors.ERR_NOT_SUPPORTED,
        "external32 needs a uniform element type: this datatype "
        "carries no base type and the buffer is raw bytes")


def _swap_wire(wire: bytes, elem: np.dtype) -> bytes:
    """Element representation <-> big-endian canonical swap of a
    packed stream (no-op when the representation is already BE —
    including native order on big-endian hosts)."""
    from ompi_tpu import errors

    if elem.names is not None:
        # a struct's packed stream strips inter-field padding, so it
        # cannot be re-viewed as the structured dtype for swapping
        raise errors.MPIError(
            errors.ERR_NOT_SUPPORTED,
            "external32 over structured element types")
    if elem.itemsize <= 1 or elem.byteorder == "|":
        return wire
    if elem.newbyteorder(">") == elem:
        return wire  # representation is already big-endian
    if len(wire) % elem.itemsize:
        raise errors.MPIError(
            errors.ERR_TYPE,
            "packed size is not a multiple of the element size")
    return np.frombuffer(wire, dtype=elem).byteswap().tobytes()


def pack(buf: Buffer, dtype: Datatype, count: int) -> bytes:
    """One-shot MPI_Pack."""
    return Convertor(buf, dtype, count).pack()


def unpack(data: bytes, buf: Buffer, dtype: Datatype, count: int) -> int:
    """One-shot MPI_Unpack."""
    return Convertor(buf, dtype, count).unpack(data)


def dtype_of(obj) -> Datatype:
    """Infer a Datatype for a numpy array (element type)."""
    arr = np.asarray(obj)
    return from_numpy_dtype(arr.dtype)
