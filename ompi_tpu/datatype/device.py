"""Device-side convertor route — derived datatypes over jax arrays.

Reference: the convertor is accelerator-aware
(opal/datatype/opal_datatype_copy.h — CONVERTOR_ACCELERATOR memcpy
selection, consumed at ompi/mca/pml/ob1/pml_ob1_sendreq.h:399): a
device buffer with a non-contiguous datatype packs THROUGH the device,
never via a host bounce of the whole extent.

TPU-first redesign: instead of a byte-walking pack VM, the span table
(datatype.py) compiles to an **element-index vector**; pack is one
on-device gather (``jnp.take``), unpack one on-device scatter
(``.at[idx].set``). XLA fuses these with the surrounding program.
The packed ELEMENT layout equals the host convertor's pack output;
note the device p2p framing differs (accel_p2p's header+chunks
protocol), so both endpoints of a transfer stay on one plane.

Constraints: spans must align to the array's element size (true for
contiguous/vector/hvector/indexed/subarray families over a uniform
base — mixed structs stay on the host route, stage with np.asarray).
"""

from __future__ import annotations

import numpy as np

from ompi_tpu.core import mpool as _mpool

#: element-index vectors per (datatype, count, itemsize) — same rcache
#: discipline as the span-table cache (datatype._span_cache)
_idx_cache = _mpool.Rcache()


def supports(dt, arr) -> bool:
    """True when `dt` has a device route over `arr` (element-aligned
    spans of arr's dtype)."""
    if dt is None or dt.is_contiguous:
        return True
    k = np.dtype(arr.dtype).itemsize
    spans = dt.spans
    return not ((spans[:, 0] % k).any() or (spans[:, 1] % k).any())


def element_indices(dt, count: int, itemsize: int) -> np.ndarray:
    """Flat element indices covering `count` elements of `dt` laid
    over an array of `itemsize`-byte elements, in typemap order —
    the compiled form of the datatype for the device convertor."""
    spans = dt.spans_for_count(count)
    if len(spans) == 0:
        return np.empty(0, np.int64)
    if (spans[:, 0] % itemsize).any() or (spans[:, 1] % itemsize).any():
        raise TypeError(
            f"datatype {dt.name}: spans are not aligned to the device "
            f"array's {itemsize}-byte elements — no device route; "
            "stage with np.asarray for byte-granular layouts")
    offs = spans[:, 0] // itemsize
    lens = spans[:, 1] // itemsize
    total = int(lens.sum())
    # vectorized [arange(o, o+l) for o, l in spans] concatenation
    starts = np.repeat(offs, lens)
    prefix = np.concatenate([[0], np.cumsum(lens[:-1])])
    inc = np.arange(total, dtype=np.int64) - np.repeat(prefix, lens)
    return starts + inc


def _bounds(idx: np.ndarray):
    if len(idx) == 0:
        return 0, -1
    return int(idx.min()), int(idx.max())


def _indices(dt, count: int, itemsize: int):
    """(index vector, (min, max)) — bounds are cached with the vector
    so the per-call check stays O(1) on the big-count hot path."""
    key = _mpool.buffer_key(dt, _idx_cache)
    if key is None:
        idx = element_indices(dt, count, itemsize)
        return idx, _bounds(idx)
    per = _idx_cache.lookup(key) or {}
    got = per.get((count, itemsize))
    if got is None:
        idx = element_indices(dt, count, itemsize)
        got = per[(count, itemsize)] = (idx, _bounds(idx))
        _idx_cache.insert(key, per,
                          sum(v[0].nbytes for v in per.values()))
    return got


def pack(arr, dt, count: int):
    """Device pack: gather `count` elements of `dt` out of the device
    array into a packed 1-D device array (the wire layout). Runs as
    one XLA gather — data never leaves the device."""
    import jax.numpy as jnp

    flat = arr.reshape(-1)
    k = np.dtype(arr.dtype).itemsize
    if dt is None:
        return flat if count is None else flat[:count]
    if dt.is_contiguous:
        return flat[:(dt.size * count) // k]
    idx, (lo, hi) = _indices(dt, count, k)
    # span tables preserve declaration order (descending displacements
    # are legal) — bound by max/min, not the last entry
    if len(idx) and (hi >= flat.size or lo < 0):
        raise ValueError(
            f"datatype {dt.name} x {count} spans element "
            f"{hi} but the device array has {flat.size}")
    return jnp.take(flat, jnp.asarray(idx), axis=0)


def unpack(packed, dt, count: int, template):
    """Device unpack: scatter a packed 1-D device array into a NEW
    array shaped like `template`, with the datatype's gaps holding
    `template`'s values (jax arrays are immutable — the host path's
    'gaps untouched' becomes 'gaps from the template')."""
    if dt is None or dt.is_contiguous:
        if packed.size == template.size:
            return packed.reshape(template.shape)
        flat = template.reshape(-1)
        return flat.at[:packed.size].set(
            packed.reshape(-1)).reshape(template.shape)
    import jax.numpy as jnp

    idx, (lo, hi) = _indices(dt, count,
                             np.dtype(template.dtype).itemsize)
    flat = template.reshape(-1)
    if len(idx) and (hi >= flat.size or lo < 0):
        raise ValueError(
            f"datatype {dt.name} x {count} spans element "
            f"{hi} but the template has {flat.size}")
    return flat.at[jnp.asarray(idx)].set(
        packed.reshape(-1)).reshape(template.shape)


def packed_elems(dt, count, itemsize: int) -> int:
    """Number of wire elements a (dt, count) pack produces."""
    if dt is None:
        return int(count)
    return (dt.size * int(count)) // itemsize
