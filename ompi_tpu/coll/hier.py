"""coll/hier — two-level ICI x DCN hierarchical collective backend.

The device-plane realization of coll/han's architecture (reference:
ompi/mca/coll/han/coll_han.h:22-33,62-63 — hierarchical subgrouping
with per-level algorithm selection): a communicator whose devices span
slices is split into an intra-slice (ICI) x inter-slice (DCN) 2-axis
mesh, and each collective lowers as a composition of per-level phases
with the bulk bytes pinned to the fast axis. Allreduce is the
canonical case: ICI reduce_scatter -> DCN allreduce over 1/ici_size of
the payload -> ICI allgather, so the slow wire carries
``2*(n_dcn-1)/n_dcn * payload/ici_size`` bytes instead of the flat
ring's ``~2*payload``.

Topology comes from ``parallel.hierarchical.parse_split``: 'auto'
groups the comm's devices by ``slice_index`` (real pods), while
``--mca coll_hier_split 2x2`` fakes a nested topology on the virtual
CPU mesh — the whole plane is testable in tier-1. A malformed or
indivisible split spec raises ``MPIError(ERR_ARG)`` at slot-call time
(never inside ``query``, where comm_select would silently swallow it).

Selection is two-dimensional:

- hierarchical-vs-flat per collective: ``coll_hier_force`` >
  ``coll_hier_switchpoints`` table entry (op, dtype, log2-size, mesh
  shape — the same key shape as coll/pallas's table) > default-hier;
  ``deterministic='ring'`` and sub-``coll_hier_min_bytes`` payloads
  always take the flat path.
- per-level inner algorithm: the ICI phase of the split-level
  allreduce may run the coll/pallas ring kernels
  (``coll_hier_inner`` ring|bidir, or 'auto' consulting the pallas
  switchpoint table keyed on the INNER mesh shape) instead of the
  traced XLA lowering.

``deterministic='linear'`` stays hierarchical but switches to the
rank-order compositions (``H.allreduce_rankorder`` and friends):
DCN-first gathers + a statically unrolled flat-rank-order fold,
bit-identical to coll/xla's linear mode by construction — the
bit-identity contract survives the topology change.

Staged fallthrough one priority level down: any unsupported case calls
the coll/pallas slot when pallas stacked for this comm, else coll/xla,
counted by ``hier_fallthrough``. Compiled programs and fused-bucket
plans live in the SAME per-comm ``_Ctx`` caches as coll/xla (distinct
key prefixes), so steady-state steps pay zero recompiles. Every launch
attributes per-level traffic: ``hier_ici_bytes`` / ``hier_dcn_bytes``
pvars, link-map bytes split across the ICI-axis and DCN-axis neighbor
edges (``monitoring.algo.hier_per_peer``), and the per-level table the
monitoring report renders to answer "which level is the bottleneck".
"""

from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as np

from ompi_tpu import errors, op as op_mod
from ompi_tpu.coll import CollModule, framework
from ompi_tpu.coll import pallas as _pallas
from ompi_tpu.coll import pallas_kernels as K
from ompi_tpu.coll import xla as _xla
from ompi_tpu.core import cvar, output, pvar
from ompi_tpu.monitoring import algo as _algo
from ompi_tpu.monitoring import matrix as _mon
from ompi_tpu.parallel import hierarchical as H
from ompi_tpu.telemetry import flight as _flight
from ompi_tpu.trace import recorder as _trace
from ompi_tpu.tune import observe as _tobs

_out = output.stream("coll_hier")

_enable_var = cvar.register(
    "coll_hier", "off", str,
    help="Enable the two-level ICI x DCN hierarchical collective "
         "backend (priority 70, above coll/pallas's 60): 'on' stacks "
         "it for every comm the device plane serves; 'off' [default] "
         "keeps the flat lowerings in charge. Opt-in because it "
         "re-routes every supported collective.",
    choices=["off", "on"], level=4)

_split_var = cvar.register(
    "coll_hier_split", "auto", str,
    help="How the comm's devices split into DCN groups: 'auto' "
         "[default] groups by device.slice_index (flat when ranks "
         "are not slice-contiguous or carry no slice info), 'DxI' "
         "forces a DCN x ICI grid (e.g. '2x4' — CPU topology "
         "faking), an integer N forces N equal slices, 'off' "
         "disables the split. A spec that does not divide the comm "
         "raises MPIError(ERR_ARG) at the first collective.", level=5)

_force_var = cvar.register(
    "coll_hier_force", "", str,
    help="Force the hierarchical-vs-flat decision: 'hier' always "
         "two-level (when a split exists), 'flat' always falls "
         "through (A/B validation, the coll_tuned forced-algorithm "
         "analog). Empty [default] consults the switchpoint table "
         "and built-in thresholds.",
    choices=["", "hier", "flat"], level=5)

_inner_var = cvar.register(
    "coll_hier_inner", "auto", str,
    help="ICI-phase algorithm for the split-level allreduce: 'xla' "
         "the traced lowering, 'ring'/'bidir' the coll/pallas DMA "
         "ring kernels over the inner axis, 'auto' [default] asks "
         "the coll_pallas switchpoint table (keyed on the INNER mesh "
         "shape) when coll_pallas is on, else xla. Unsupported "
         "dtype/op combinations always use xla.",
    choices=["auto", "xla", "ring", "bidir"], level=5)

_min_bytes_var = cvar.register(
    "coll_hier_min_bytes", 0, int,
    help="Payloads below this take the flat path (two phased "
         "programs lose to one latency-optimized flat program at "
         "tiny sizes). 0 [default] keeps every supported size "
         "hierarchical.", level=5)

_switch_var = cvar.register(
    "coll_hier_switchpoints", "", str,
    help="Path to a measured hierarchical-vs-flat switchpoint table: "
         "a JSON list of {op, dtype, mesh, log2, algorithm} rules "
         "with algorithm 'hier' or 'flat' and mesh the [n_dcn, "
         "n_ici] grid; for each (op, dtype, mesh) the rule with the "
         "largest log2 <= the payload's log2 bucket wins (the "
         "coll_pallas_switchpoints shape, one level up). Empty "
         "[default] = hierarchical whenever a split exists.", level=5)

# NOTE: the dcn_dtype cvars register WITHOUT choices= on purpose —
# choices validate at set() time, but this family's contract is the
# bad-split one: an unknown value must surface as MPIError(ERR_ARG)
# at the FIRST COLLECTIVE (uncached, never swallowed by query), so
# an operator typo in an mca file fails where the collectives run.
_dcn_dtype_var = cvar.register(
    "coll_hier_dcn_dtype", "off", str,
    help="Wire dtype for the hier plane's inter-slice (DCN) phase: "
         "'off' [default] transmits the accumulate dtype — bitwise "
         "identical to the uncompressed plane; 'bf16', 'fp8_e4m3', "
         "'fp8_e5m2' cast-compress the DCN payload (gather in the "
         "wire dtype + local upcast-sum; fp8 adds a per-launch scale "
         "factor agreed by pmax in the same program). Applies to SUM "
         "reductions of float payloads only; 'linear' determinism "
         "and non-float dtypes always run exact. fp8 degrades to "
         "bf16 on jax builds without fp8 lowerings. Unknown values "
         "raise MPIError(ERR_ARG) at the first collective.", level=5)

_dcn_dtype_op_vars = {
    kind: cvar.register(
        f"coll_hier_dcn_dtype_{kind}", "", str,
        help=f"Per-op override of coll_hier_dcn_dtype for {kind} "
             "launches ('off'/'bf16'/'fp8_e4m3'/'fp8_e5m2'; empty "
             "[default] inherits the global setting) — the per-level "
             "algorithm-choice shape coll/tuned tables use.", level=5)
    for kind in ("allreduce", "allreduce_multi",
                 "reduce_scatter_block")
}

#: wire-format spellings _wire_dtype accepts (resolution/probing in
#: util.jaxcompat; byte model in monitoring.algo.WIRE_ITEMSIZE)
_WIRE_NAMES = H.WIRE_DTYPES


def _wire_dtype(kind: str, dtype: str, det: Optional[str],
                opn) -> Optional[str]:
    """The DCN wire format for this launch, or None = exact.

    Resolution: per-op override > coll_hier_dcn_dtype > off. Unknown
    values raise MPIError(ERR_ARG) HERE — slot-call time, per call,
    the bad-split contract. Compression is declined silently (exact
    lowering, no error) whenever the result must be bit-stable or the
    cast cannot help: 'linear' determinism, non-SUM ops, non-float
    payloads, or a wire format no narrower than the input dtype.
    Unavailable fp8 degrades to bf16 (the jaxcompat capability probe)
    with a verbose note instead of failing."""
    v = _dcn_dtype_op_vars.get(kind)
    spec = v.get().strip().lower() if v is not None else ""
    if not spec:
        spec = _dcn_dtype_var.get().strip().lower()
    if not spec or spec == "off":
        return None
    if spec not in _WIRE_NAMES:
        raise errors.MPIError(
            errors.ERR_ARG,
            f"coll_hier_dcn_dtype={spec!r}: expected 'off', 'bf16', "
            "'fp8_e4m3' or 'fp8_e5m2'")
    if det == "linear" or opn.name != "MPI_SUM":
        return None
    from ompi_tpu.util import jaxcompat as _jc

    try:
        ndt = _jc.np_dtype(dtype)
    except TypeError:
        return None
    if ndt.kind != "f":
        return None
    wire = _jc.wire_degrade(spec)
    if wire != spec:
        _out.verbose(1, "coll_hier_dcn_dtype=%s unavailable on this "
                        "jax: degrading to %s", spec, wire)
    if _jc.wire_itemsize(wire) >= ndt.itemsize:
        return None  # the "compression" would not shrink the wire
    return wire


#: flat-path slots coll/pallas can serve (one priority level down)
_PALLAS_SLOTS = frozenset((
    "allreduce_dev", "allgather_dev", "reduce_scatter_block_dev"))

_PALLAS_COMP = _pallas.CollPallas()


# ---------------------------------------------------------------------------
# topology plan — per-comm, cached beside the _Ctx caches


class _Plan:
    """The comm's 2-level grid: a (n_dcn, n_ici) Mesh over the SAME
    devices (and device order) as the flat _Ctx mesh, so row-major
    (dcn, ici) position IS the comm rank, plus the matching dim-0
    input sharding."""

    __slots__ = ("n_dcn", "n_ici", "mesh", "sharding")

    def __init__(self, devs, n_dcn: int, n_ici: int) -> None:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        self.n_dcn = n_dcn
        self.n_ici = n_ici
        self.mesh = Mesh(np.array(devs).reshape(n_dcn, n_ici),
                         (H.DCN_AXIS, H.ICI_AXIS))
        self.sharding = NamedSharding(
            self.mesh, PartitionSpec((H.DCN_AXIS, H.ICI_AXIS)))


#: cached marker for a valid-but-trivial split (stay flat forever)
_NO_PLAN = object()


def _plan(comm) -> Optional[_Plan]:
    """The comm's grid plan, or None = flat. Cached on the comm
    (freed with it). A malformed/indivisible coll_hier_split raises
    MPIError(ERR_ARG) and is NOT cached — every collective keeps
    surfacing the config error instead of silently running flat."""
    cached = getattr(comm, "_coll_hier_plan", None)
    if cached is not None:
        return None if cached is _NO_PLAN else cached
    ctx = _xla._ctx(comm)
    devs = list(ctx.mesh.devices.reshape(-1))
    split = H.parse_split(_split_var.get(), len(devs), devices=devs)
    if split is None or split[0] < 2 or split[1] < 2:
        comm._coll_hier_plan = _NO_PLAN
        return None
    plan = comm._coll_hier_plan = _Plan(devs, split[0], split[1])
    _out.verbose(1, "comm cid=%s: %dx%d ICI x DCN grid",
                 getattr(comm, "cid", -1), plan.n_dcn, plan.n_ici)
    return plan


# ---------------------------------------------------------------------------
# selection


def _det_ok(deterministic: Optional[str]) -> Optional[str]:
    det = _xla._det(deterministic)
    if det not in (None, "ring", "linear"):
        raise errors.MPIError(
            errors.ERR_ARG,
            f"coll_hier: deterministic={det!r} (expected None, "
            "'ring' or 'linear' — silent fallthrough would void the "
            "fixed-reduction-order guarantee)")
    return det


_sw_cache: dict = {}


def _switchpoint(kind: str, nbytes: int, dtype: str,
                 mesh_shape) -> str:
    """'hier' | 'flat' | '' from the measured table (the coll/pallas
    rule shape: per (op, dtype, mesh) the largest log2 <= the
    payload's bucket wins)."""
    path = _switch_var.get().strip()
    if not path:
        return ""
    table = _sw_cache.get(path)
    if table is None:
        try:
            with open(path, encoding="utf-8") as f:
                entries = json.load(f)
        except (OSError, ValueError) as exc:
            # tune satellite: a fat-fingered table path is a silent
            # perf cliff — warn once per path, count every attempt
            _tobs.table_error("coll_hier_switchpoints", path, exc)
            entries = []
        table = {}
        for e in entries if isinstance(entries, list) else []:
            key = (str(e.get("op", "")), str(e.get("dtype", "")),
                   tuple(int(v) for v in e.get("mesh", ())))
            table.setdefault(key, []).append(
                (int(e.get("log2", 0)), str(e.get("algorithm", ""))))
        for rules in table.values():
            rules.sort()
        _sw_cache[path] = table
    rules = table.get((kind, dtype, tuple(mesh_shape)))
    if not rules:
        return ""
    bucket = _algo.log2_bucket(nbytes)
    best = ""
    for lg, alg in rules:
        if bucket >= lg:
            best = alg
        else:
            break
    return best


def _select(kind: str, comm, nbytes: int, dtype: str,
            det: Optional[str]) -> Optional[_Plan]:
    """The hierarchical-vs-flat decision: the plan, or None = fall
    through. 'ring' determinism is always flat (the two-level chunk
    order cannot reproduce the flat ring's); 'linear' stays
    hierarchical via the rank-order compositions."""
    plan = _plan(comm)  # may raise MPIError(ERR_ARG) on a bad spec
    if plan is None:
        return None
    if det == "ring":
        return None
    if nbytes == 0 or nbytes < _min_bytes_var.get():
        return None
    forced = _force_var.get()
    if forced == "flat":
        return None
    if forced == "hier":
        return plan
    if _switchpoint(kind, nbytes, dtype,
                    (plan.n_dcn, plan.n_ici)) == "flat":
        return None
    return plan


def _inner_algo(kind: str, nbytes: int, dtype: str, opn,
                plan: _Plan, chunk_rows: int) -> str:
    """ICI-phase algorithm for the split-level schedule — per-level
    selection: 'xla' = traced C.* lowering, 'ring'/'bidir' = the
    coll/pallas kernels over the inner axis. 'auto' consults the
    pallas switchpoint table keyed on the INNER mesh shape, only when
    the pallas backend is enabled."""
    mode = _inner_var.get()
    if mode == "xla":
        return "xla"
    if dtype not in _pallas._SUPPORTED_DTYPES \
            or opn.name not in _pallas._SUPPORTED_OPS:
        return "xla"
    if mode == "auto":
        if _pallas._enable_var.get() != "on":
            return "xla"
        sw = _pallas._switchpoint(kind, nbytes, dtype, (plan.n_ici,))
        if sw not in ("ring", "bidir"):
            return "xla"
        mode = sw
    if mode == "bidir" and chunk_rows < 2:
        mode = "ring"
    return mode


# ---------------------------------------------------------------------------
# dispatch plumbing


def _pallas_stacked(comm) -> bool:
    try:
        return _PALLAS_COMP.query(comm) >= 0
    except Exception:  # a query error means "not stacked", as in
        return False   # comm_select itself


def _flat_fn(comm, slot: str):
    """The slot one priority level down: coll/pallas when it stacked
    for this comm and serves the slot, else coll/xla — the same
    staged chain comm_select would have resolved without hier."""
    if slot in _PALLAS_SLOTS and _pallas_stacked(comm):
        return getattr(_pallas, slot)
    return getattr(_xla, slot)


def _fallthrough(comm, slot: str, *args, **kw):
    pvar.record("hier_fallthrough")
    return _flat_fn(comm, slot)(comm, *args, **kw)


def _smap(ctx, plan: _Plan, body, out_varying: bool):
    return ctx.smap(body, out_varying, mesh=plan.mesh,
                    spec=ctx.P((H.DCN_AXIS, H.ICI_AXIS)))


def _launch(launcher, op: str, plan: _Plan, comm=None, nbytes=0,
            dtype: str = ""):
    """Dispatch, with a coll_hier trace span naming the grid (the xla
    launch funnel inside adds its own span) and a tune-plane sample
    under provider 'hier', mesh (n_dcn, n_ici), when the observatory
    is up."""
    obs = _tobs.OBSERVER
    if obs is not None:
        launcher = obs.timed("hier", op, "hier", comm, nbytes, dtype,
                             launcher,
                             mesh=(plan.n_dcn, plan.n_ici))
    rec = _trace.RECORDER
    if rec is None:
        return launcher()
    t0 = _trace.now()
    out = launcher()
    rec.record("launch", "coll_hier", t0, _trace.now(),
               {"op": op, "grid": f"{plan.n_dcn}x{plan.n_ici}"})
    return out


def _itemsize(dtype: str) -> int:
    """Element bytes of a dtype string over the ml_dtypes-extended
    namespace (0 for unparseable — wire accounting then degrades to
    the nominal model)."""
    from ompi_tpu.util import jaxcompat as _jc

    try:
        return _jc.np_dtype(dtype).itemsize
    except TypeError:
        return 0


def _account(kind: str, comm, nbytes: int, dtype: str, plan: _Plan,
             linear: bool = False, wire: Optional[str] = None,
             parts=None) -> None:
    """Per-level attribution: the launch and per-level byte pvars
    (nominal DCN model + actual wire bytes), the link map split
    across the ICI-axis and DCN-axis neighbor edges, and the
    per-level totals the report renders. ``parts`` — a list of
    (nbytes, dtype, wire) — covers the fused multi path, whose
    dtype-segregated buckets can mix compressed float and exact int
    payloads in one launch; the models are linear in nbytes, so the
    per-part sums equal the whole."""
    if parts is None:
        parts = ((nbytes, dtype, wire),)
    ici_b = dcn_b = wire_b = 0.0
    peers: dict = {}
    for nb, dt, w in parts:
        isz = _itemsize(dt) if w else 0
        i_b, d_b = _algo.hier_level_bytes(
            kind, plan.n_dcn, plan.n_ici, nb, linear=linear)
        ici_b += i_b
        dcn_b += d_b
        wire_b += _algo.hier_wire_bytes(
            kind, plan.n_dcn, plan.n_ici, nb, wire=w, itemsize=isz,
            linear=linear)
        for peer, b in _algo.hier_per_peer(
                kind, comm.rank, plan.n_dcn, plan.n_ici, nb,
                linear=linear, wire=w, itemsize=isz).items():
            peers[peer] = peers.get(peer, 0.0) + b
    pvar.record("hier_launches")
    pvar.record("hier_ici_bytes", int(ici_b))
    pvar.record("hier_dcn_bytes", int(dcn_b))
    pvar.record("hier_dcn_wire_bytes", int(wire_b))
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll(kind, comm, nbytes, dtype=dtype, per_peer=peers)
        tm.hier(kind, ici_b, dcn_b, wire_b)


# ---------------------------------------------------------------------------
# lowerings — bodies run inside shard_map over the plan's 2-axis mesh


def _split_level(flat, opn, inner: str, interp: bool,
                 wire: Optional[str] = None):
    """The han split-level allreduce on a flat vector whose length is
    a multiple of n_ici: ICI reduce_scatter -> DCN allreduce of the
    1/n_ici chunk -> ICI allgather. ``inner`` picks the ICI-phase
    kernels; the RS/AG pair always matches so chunk placement
    round-trips. ``wire`` swaps the DCN step for the cast-compressed
    transport (``H.dcn_wire_allreduce``: gather in the wire dtype +
    local upcast-sum, fp8 scale agreed in the same traced body) —
    still one compiled program, the ICI phases untouched."""
    from ompi_tpu.parallel import collectives as C

    def dcn_step(part):
        if wire is not None:
            return H.dcn_wire_allreduce(part, wire, H.DCN_AXIS)
        return C.allreduce(part, H.DCN_AXIS, opn)

    if inner in ("ring", "bidir"):
        fnc = C.combine_fn(opn)
        if inner == "bidir":
            part = K.bidir_reduce_scatter(flat, H.ICI_AXIS, fnc,
                                          interpret=interp)
        else:
            part = K.ring_reduce_scatter(flat, H.ICI_AXIS, fnc,
                                         interpret=interp)
        part = dcn_step(part)
        if inner == "bidir":
            return K.bidir_allgather(part, H.ICI_AXIS,
                                     interpret=interp)
        return K.ring_allgather(part, H.ICI_AXIS, interpret=interp)
    part = C.reduce_scatter(flat, H.ICI_AXIS, opn, scatter_dim=0,
                            tiled=True)
    part = dcn_step(part)
    return C.allgather(part, H.ICI_AXIS, tiled=True, gather_dim=0)


def _allreduce_prep(comm, sendbuf, opn, det: Optional[str],
                    plan: _Plan, wire: Optional[str] = None):
    ctx = _xla._ctx(comm)
    if det == "linear":
        def build():
            return _smap(ctx, plan,
                         lambda a: H.allreduce_rankorder(a[0], op=opn),
                         out_varying=False)

        fn = ctx.compiled(
            _xla._key(sendbuf, "hier_allreduce", "linear", opn.name,
                      plan.n_dcn, plan.n_ici), build)
    else:
        size = int(sendbuf.size)
        pad = (-size) % plan.n_ici
        interp = _pallas._interpret()
        inner = _inner_algo("allreduce", int(sendbuf.nbytes),
                            str(sendbuf.dtype), opn, plan,
                            (size + pad) // plan.n_ici)
        shape = tuple(sendbuf.shape)

        def build():
            def body(a):
                import jax.numpy as jnp

                flat = a[0].reshape(-1)
                if pad:
                    flat = jnp.pad(flat, (0, pad))
                red = _split_level(flat, opn, inner, interp, wire)
                if pad:
                    red = red[:size]
                return red.reshape(shape)

            return _smap(ctx, plan, body, out_varying=False)

        # wire in the key: exact and compressed programs must never
        # collide (toggling coll_hier_dcn_dtype back and forth reuses
        # both cached executables, zero recompiles)
        fn = ctx.compiled(
            _xla._key(sendbuf, "hier_allreduce", "split", opn.name,
                      plan.n_dcn, plan.n_ici, inner, interp, wire),
            build)
    g = ctx.to_global(sendbuf, plan.sharding)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def allreduce_dev(comm, sendbuf, op=op_mod.SUM,
                  deterministic: Optional[str] = None):
    det = _det_ok(deterministic)
    if not _xla._op_ok(op) or comm.size == 1 \
            or not hasattr(sendbuf, "shape"):
        return _fallthrough(comm, "allreduce_dev", sendbuf, op,
                            deterministic)
    plan = _select("allreduce", comm, int(sendbuf.nbytes),
                   str(sendbuf.dtype), det)
    if plan is None:
        return _fallthrough(comm, "allreduce_dev", sendbuf, op,
                            deterministic)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    # resolve the wire format BEFORE accounting: an unknown
    # coll_hier_dcn_dtype raises here, per call, with nothing counted
    wire = _wire_dtype("allreduce", str(sendbuf.dtype), det, opn)
    _account("allreduce", comm, int(sendbuf.nbytes),
             str(sendbuf.dtype), plan, linear=det == "linear",
             wire=wire)
    launcher = _allreduce_prep(comm, sendbuf, opn, det, plan, wire)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "allreduce", plan, comm,
                       int(sendbuf.nbytes), str(sendbuf.dtype))
    tok = fl.enter("allreduce_dev", getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return _launch(launcher, "allreduce", plan, comm,
                       int(sendbuf.nbytes), str(sendbuf.dtype))
    finally:
        fl.exit(tok)


def _bcast_prep(comm, buf, root: int, plan: _Plan):
    ctx = _xla._ctx(comm)
    ici = plan.n_ici

    def build():
        return _smap(ctx, plan,
                     lambda a: H.bcast(a[0], root_dcn=root // ici,
                                       root_ici=root % ici),
                     out_varying=False)

    fn = ctx.compiled(_xla._key(buf, "hier_bcast", root, plan.n_dcn,
                                plan.n_ici), build)
    g = ctx.to_global(buf, plan.sharding)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def bcast_dev(comm, buf, root: int = 0):
    if comm.size == 1 or not hasattr(buf, "shape"):
        return _fallthrough(comm, "bcast_dev", buf, root)
    plan = _select("bcast", comm, int(buf.nbytes), str(buf.dtype),
                   None)
    if plan is None:
        return _fallthrough(comm, "bcast_dev", buf, root)
    _account("bcast", comm, int(buf.nbytes), str(buf.dtype), plan)
    launcher = _bcast_prep(comm, buf, root, plan)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "bcast", plan, comm,
                       int(buf.nbytes), str(buf.dtype))
    tok = fl.enter("bcast_dev", getattr(comm, "cid", -1),
                   getattr(buf, "nbytes", 0))
    try:
        return _launch(launcher, "bcast", plan, comm,
                       int(buf.nbytes), str(buf.dtype))
    finally:
        fl.exit(tok)


def _allgather_prep(comm, sendbuf, plan: _Plan):
    ctx = _xla._ctx(comm)

    def build():
        return _smap(ctx, plan, lambda a: H.gather_rankorder(a[0]),
                     out_varying=False)

    fn = ctx.compiled(_xla._key(sendbuf, "hier_allgather",
                                plan.n_dcn, plan.n_ici), build)
    g = ctx.to_global(sendbuf, plan.sharding)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def allgather_dev(comm, sendbuf):
    if comm.size == 1 or not hasattr(sendbuf, "shape"):
        return _fallthrough(comm, "allgather_dev", sendbuf)
    plan = _select("allgather", comm, int(sendbuf.nbytes),
                   str(sendbuf.dtype), None)
    if plan is None:
        return _fallthrough(comm, "allgather_dev", sendbuf)
    _account("allgather", comm, int(sendbuf.nbytes),
             str(sendbuf.dtype), plan)
    launcher = _allgather_prep(comm, sendbuf, plan)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "allgather", plan, comm,
                       int(sendbuf.nbytes), str(sendbuf.dtype))
    tok = fl.enter("allgather_dev", getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return _launch(launcher, "allgather", plan, comm,
                       int(sendbuf.nbytes), str(sendbuf.dtype))
    finally:
        fl.exit(tok)


def _alltoall_prep(comm, sendbuf, plan: _Plan):
    ctx = _xla._ctx(comm)

    def build():
        # two-phase: every byte crosses DCN exactly once; output is
        # source-rank-major, the MPI alltoall order
        return _smap(ctx, plan, lambda a: H.alltoall(a[0]),
                     out_varying=True)

    fn = ctx.compiled(_xla._key(sendbuf, "hier_alltoall",
                                plan.n_dcn, plan.n_ici), build)
    g = ctx.to_global(sendbuf, plan.sharding)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def alltoall_dev(comm, sendbuf):
    if comm.size == 1 or getattr(sendbuf, "ndim", 0) < 1 \
            or sendbuf.shape[0] % comm.size:
        # indivisible dim0 falls through: coll/xla raises the same
        # MPIError(ERR_COUNT) the flat contract specifies
        return _fallthrough(comm, "alltoall_dev", sendbuf)
    plan = _select("alltoall", comm, int(sendbuf.nbytes),
                   str(sendbuf.dtype), None)
    if plan is None:
        return _fallthrough(comm, "alltoall_dev", sendbuf)
    _account("alltoall", comm, int(sendbuf.nbytes),
             str(sendbuf.dtype), plan)
    launcher = _alltoall_prep(comm, sendbuf, plan)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "alltoall", plan, comm,
                       int(sendbuf.nbytes), str(sendbuf.dtype))
    tok = fl.enter("alltoall_dev", getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return _launch(launcher, "alltoall", plan, comm,
                       int(sendbuf.nbytes), str(sendbuf.dtype))
    finally:
        fl.exit(tok)


def _reduce_scatter_block_prep(comm, sendbuf, opn,
                               det: Optional[str], plan: _Plan,
                               wire: Optional[str] = None):
    ctx = _xla._ctx(comm)
    if det == "linear":
        body = lambda a: H.reduce_scatter_block_rankorder(  # noqa: E731
            a[0], op=opn)
    else:
        body = lambda a: H.reduce_scatter_rankmajor(  # noqa: E731
            a[0], op=opn, wire=wire)

    def build():
        return _smap(ctx, plan, body, out_varying=True)

    fn = ctx.compiled(_xla._key(sendbuf, "hier_rsb", opn.name, det,
                                plan.n_dcn, plan.n_ici, wire), build)
    g = ctx.to_global(sendbuf, plan.sharding)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def reduce_scatter_block_dev(comm, sendbuf, op=op_mod.SUM,
                             deterministic: Optional[str] = None):
    det = _det_ok(deterministic)
    if not _xla._op_ok(op) or comm.size == 1 \
            or getattr(sendbuf, "ndim", 0) < 1 \
            or sendbuf.shape[0] % comm.size:
        return _fallthrough(comm, "reduce_scatter_block_dev", sendbuf,
                            op, deterministic)
    plan = _select("reduce_scatter_block", comm, int(sendbuf.nbytes),
                   str(sendbuf.dtype), det)
    if plan is None:
        return _fallthrough(comm, "reduce_scatter_block_dev", sendbuf,
                            op, deterministic)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    wire = _wire_dtype("reduce_scatter_block", str(sendbuf.dtype),
                       det, opn)
    _account("reduce_scatter_block", comm, int(sendbuf.nbytes),
             str(sendbuf.dtype), plan, linear=det == "linear",
             wire=wire)
    launcher = _reduce_scatter_block_prep(comm, sendbuf, opn, det,
                                          plan, wire)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "reduce_scatter_block", plan, comm,
                       int(sendbuf.nbytes), str(sendbuf.dtype))
    tok = fl.enter("reduce_scatter_block_dev",
                   getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return _launch(launcher, "reduce_scatter_block", plan, comm,
                       int(sendbuf.nbytes), str(sendbuf.dtype))
    finally:
        fl.exit(tok)


# ---------------------------------------------------------------------------
# fused bucketed allreduce — ZeRO / GradientSync ride the two-level
# lowering transparently. Bucket plans come from the SAME
# _xla._fuse_plan cache (geometry is mode-independent); the compiled
# bucket programs get hier-prefixed keys in the same _Ctx.fns LRU.


def _hier_bucket_fn(ctx, metas, idxs, opn, det: Optional[str],
                    plan: _Plan, interp: bool,
                    wire: Optional[str] = None):
    """ONE compiled concat + two-level-allreduce + split program per
    bucket. Under 'linear' the body is the rank-order fold —
    concatenation never changes an element's per-rank fold order, so
    fused == per-buffer bit for bit (the same argument as the flat
    fused path, tested). ``wire`` (per bucket — buckets are
    dtype-segregated, so a float bucket can compress while its int
    sibling runs exact in the same multi launch) swaps the DCN step,
    and joins the cache key so exact/compressed never collide."""
    sig = tuple((metas[i][0], metas[i][1]) for i in idxs)
    elems = sum(int(np.prod(metas[i][0], dtype=np.int64))
                for i in idxs)
    pad = (-elems) % plan.n_ici
    if det == "linear":
        inner = "xla"
    else:
        inner = _inner_algo("allreduce",
                            sum(metas[i][2] for i in idxs),
                            metas[idxs[0]][1], opn, plan,
                            (elems + pad) // plan.n_ici)

    def build():
        def body(args):
            import jax.numpy as jnp

            flat = (jnp.concatenate(
                [a[0].reshape(-1) for a in args])
                if len(args) > 1 else args[0][0].reshape(-1))
            if det == "linear":
                red = H.allreduce_rankorder(flat, op=opn)
            else:
                if pad:
                    flat = jnp.pad(flat, (0, pad))
                red = _split_level(flat, opn, inner, interp, wire)
                if pad:
                    red = red[:elems]
            outs, off = [], 0
            for a in args:  # static split back to member shapes
                k = a[0].size
                outs.append(red[off:off + k].reshape(a.shape[1:]))
                off += k
            return tuple(outs)

        return _smap(ctx, plan, body, out_varying=False)

    return ctx.compiled(("hier_fused", sig, opn.name, det,
                         plan.n_dcn, plan.n_ici, inner, interp,
                         wire), build)


def _hier_fuse_prep(comm, leaves, treedef, opn, det: Optional[str],
                    plan: _Plan):
    import jax

    ctx = _xla._ctx(comm)
    metas = _xla._fuse_metas(leaves)
    fplan = _xla._fuse_plan(ctx, metas, treedef, opn, det)
    interp = _pallas._interpret()

    launches = []
    for idxs in fplan.buckets:
        wire = _wire_dtype("allreduce_multi", metas[idxs[0]][1], det,
                           opn)
        fn = _hier_bucket_fn(ctx, metas, idxs, opn, det, plan, interp,
                             wire)
        gs = tuple(ctx.to_global(leaves[i], plan.sharding)
                   for i in idxs)
        launches.append((fn, gs, idxs))

    def launch():
        outs = [None] * len(leaves)
        for fn, gs, idxs in launches:
            res = ctx.launch(fn, gs)
            pvar.record("hier_fused_launches")
            for j, i in enumerate(idxs):
                outs[i] = ctx.my_shard(res[j])
        pvar.record("coll_xla_fused_bytes", fplan.nbytes)
        return jax.tree.unflatten(treedef, outs)

    return launch


def _multi_parts(leaves, det, opn):
    """Dtype-grouped (nbytes, dtype, wire) accounting parts for a
    fused multi launch: the byte models are linear in nbytes, so
    grouped sums account exactly, and resolving every group's wire
    here (before ``_account``) keeps the unknown-cvar MPIError
    per-call with nothing counted."""
    groups: Dict[str, int] = {}
    for b in leaves:
        dt = str(getattr(b, "dtype", ""))
        groups[dt] = groups.get(dt, 0) + int(getattr(b, "nbytes", 0))
    return tuple(
        (nb, dt, _wire_dtype("allreduce_multi", dt, det, opn))
        for dt, nb in groups.items())


def allreduce_multi_dev(comm, bufs, op=op_mod.SUM,
                        deterministic: Optional[str] = None):
    det = _det_ok(deterministic)
    import jax

    leaves = jax.tree.leaves(bufs)
    if not _xla._op_ok(op) or comm.size == 1 or not leaves:
        return _fallthrough(comm, "allreduce_multi_dev", bufs, op,
                            deterministic)
    nb = sum(int(getattr(b, "nbytes", 0)) for b in leaves)
    dt = str(getattr(leaves[0], "dtype", ""))
    plan = _select("allreduce_multi", comm, nb, dt, det)
    if plan is None:
        return _fallthrough(comm, "allreduce_multi_dev", bufs, op,
                            deterministic)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    _, treedef = jax.tree.flatten(bufs)
    _account("allreduce_multi", comm, nb, dt, plan,
             linear=det == "linear",
             parts=_multi_parts(leaves, det, opn))
    launcher = _hier_fuse_prep(comm, leaves, treedef, opn, det, plan)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "allreduce_multi", plan, comm, nb, dt)
    tok = fl.enter("allreduce_multi_dev", getattr(comm, "cid", -1),
                   nb)
    try:
        return _launch(launcher, "allreduce_multi", plan, comm, nb, dt)
    finally:
        fl.exit(tok)


# ---------------------------------------------------------------------------
# persistent inits — the prep either wraps the hier launcher with
# per-start accounting or hands the whole init to coll/xla's prep
# (flat), so Start()+Wait() cycles pay zero re-planning either way.


def _allreduce_pprep(comm, sendbuf, op=op_mod.SUM,
                     deterministic: Optional[str] = None):
    det = _det_ok(deterministic)
    plan = _select("allreduce", comm,
                   int(getattr(sendbuf, "nbytes", 0)),
                   str(getattr(sendbuf, "dtype", "")), det)
    if plan is None:
        pvar.record("hier_fallthrough")
        return _xla._allreduce_prep(comm, sendbuf, op, deterministic)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    # wire format resolves at init time, like the plan: a persistent
    # handle keeps the schedule it was built with across Start() calls
    wire = _wire_dtype("allreduce", str(sendbuf.dtype), det, opn)
    raw = _allreduce_prep(comm, sendbuf, opn, det, plan, wire)
    nb, dt = int(sendbuf.nbytes), str(sendbuf.dtype)

    def run():
        _account("allreduce", comm, nb, dt, plan,
                 linear=det == "linear", wire=wire)
        return raw()

    return run


def _allreduce_multi_pprep(comm, bufs, op=op_mod.SUM,
                           deterministic: Optional[str] = None):
    det = _det_ok(deterministic)
    import jax

    leaves, treedef = jax.tree.flatten(bufs)
    nb = sum(int(getattr(b, "nbytes", 0)) for b in leaves)
    dt = str(getattr(leaves[0], "dtype", ""))
    plan = _select("allreduce_multi", comm, nb, dt, det)
    if plan is None:
        pvar.record("hier_fallthrough")
        return _xla._allreduce_multi_prep(comm, bufs, op,
                                          deterministic)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    # per-bucket wire resolves inside _hier_fuse_prep at init time;
    # the accounting parts are captured alongside so every Start()
    # reports what the frozen schedule actually transmits
    parts = _multi_parts(leaves, det, opn)
    raw = _hier_fuse_prep(comm, leaves, treedef, opn, det, plan)

    def run():
        _account("allreduce_multi", comm, nb, dt, plan,
                 linear=det == "linear", parts=parts)
        return raw()

    return run


allreduce_init_dev = _xla._pprep(
    _allreduce_pprep, allreduce_dev, "allreduce_init_dev",
    gates=(_xla._gate_op, _xla._gate_size1))
allreduce_multi_init_dev = _xla._pprep(
    _allreduce_multi_pprep, allreduce_multi_dev,
    "allreduce_multi_init_dev",
    gates=(_xla._gate_op, _xla._gate_size1, _xla._multi_empty))


# ---------------------------------------------------------------------------


@framework.register
class CollHier(CollModule):
    NAME = "hier"
    PRIORITY = 70  # above pallas(60): the two-level schedule decides
    # first and falls through the same staged chain (pallas, then
    # xla) for everything it declines

    def query(self, comm) -> int:
        if _enable_var.get() != "on":
            return -1
        if comm.size == 1:
            return -1  # no hierarchy in a singleton
        from ompi_tpu.runtime import device_plane

        if not device_plane.active():
            return -1
        if any(device_plane.device_for_world_rank(w) is None
               for w in comm.group.ranks):
            return -1
        # NOTE: no plan/split validation here — comm_select swallows
        # query exceptions, so a malformed coll_hier_split must
        # surface at the first collective call instead
        return self.PRIORITY

    def slots(self, comm):
        return {
            "allreduce_dev": allreduce_dev,
            "bcast_dev": bcast_dev,
            "allgather_dev": allgather_dev,
            "alltoall_dev": alltoall_dev,
            "reduce_scatter_block_dev": reduce_scatter_block_dev,
            "allreduce_multi_dev": allreduce_multi_dev,
            "allreduce_init_dev": allreduce_init_dev,
            "allreduce_multi_init_dev": allreduce_multi_init_dev,
        }
