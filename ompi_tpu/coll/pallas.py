"""coll/pallas — hand-rolled ICI DMA collective backend.

A peer to :mod:`ompi_tpu.coll.xla` one priority level up: ring and
bidirectional-ring reduce_scatter / allgather / allreduce implemented
as explicit Pallas kernels (:mod:`ompi_tpu.coll.pallas_kernels` —
``make_async_remote_copy`` double-buffered DMA rings on TPU, the same
schedule as interpret-mode kernels + ``ppermute`` hops on CPU), plus
the two fused compute+comm kernels the backend exists for:
reduce_scatter fused with the ZeRO stage-1/2 shard update
(``fused_rs_update_dev``) and matmul-overlapped allgather for tensor
parallelism (``allgather_matmul_dev``).

Selection (reference analog: coll/tuned's forced-algorithm params +
measured switchpoints, coll_tuned_decision_fixed.c):

- ``deterministic='linear'`` always runs the rank-order fold kernel —
  bit-identical to coll/xla's linear mode (the contract tier-1
  verifies on >= 3 mesh sizes); ``'ring'`` always the clockwise ring
  (bit-identical to coll/xla's ring mode).
- otherwise a forced ``coll_pallas_*_algorithm`` cvar wins, then a
  ``coll_pallas_switchpoints`` table entry keyed (op, log2-size,
  dtype, mesh-shape) — the same key the monitoring plane records and
  ``bench.py --pallas`` emits — then the built-in size threshold
  (bidirectional ring at/above ``coll_pallas_bidir_min_bytes``).

Staged fallthrough: any unsupported (dtype, op, shape, mesh) case —
and any forced/``'xla'`` switchpoint decision — calls the coll/xla
slot with identical arguments (one priority level down, exactly as
xla itself falls to accelerator/host), counted by the
``pallas_fallthrough`` pvar. The component is opt-in
(``--mca coll_pallas on``): stacking above xla re-routes every
supported collective, which existing provider-asserting tests must
not see by default.
"""

from __future__ import annotations

import json
from typing import Optional

from ompi_tpu import errors, op as op_mod
from ompi_tpu.coll import CollModule, framework
from ompi_tpu.coll import pallas_kernels as K
from ompi_tpu.coll import xla as _xla
from ompi_tpu.core import cvar, output, pvar
from ompi_tpu.monitoring import algo as _algo
from ompi_tpu.monitoring import matrix as _mon
from ompi_tpu.telemetry import flight as _flight
from ompi_tpu.trace import recorder as _trace
from ompi_tpu.tune import observe as _tobs
from ompi_tpu.util import jaxcompat

_out = output.stream("coll_pallas")

_enable_var = cvar.register(
    "coll_pallas", "off", str,
    help="Enable the hand-rolled Pallas ring collective backend "
         "(priority 60, above coll/xla's 50): 'on' stacks it for "
         "every comm the device plane serves; 'off' [default] leaves "
         "the XLA lowering in charge. Opt-in because it re-routes "
         "every supported collective.",
    choices=["off", "on"], level=4)

_interpret_var = cvar.register(
    "coll_pallas_interpret", "auto", str,
    help="Kernel transport: 'auto' [default] uses the monolithic "
         "make_async_remote_copy DMA kernels on real TPU and the "
         "interpret-mode schedule (pallas_call(interpret=True) "
         "compute kernels + ppermute hops, identical accumulation "
         "order) everywhere else; 'on' forces interpret even on TPU "
         "(debugging); 'off' forces the DMA kernels (fails off-TPU).",
    choices=["auto", "on", "off"], level=6)

_force_allreduce = cvar.register(
    "coll_pallas_allreduce_algorithm", "", str,
    help="Force the pallas allreduce variant: ring|bidir|linear, or "
         "'xla' to fall through to coll/xla (A/B validation, the "
         "coll_tuned_*_algorithm analog). Deterministic modes ignore "
         "a forced ring/bidir/linear — the bit-identity contract "
         "picks the kernel — but 'xla' always falls through.",
    choices=["", "ring", "bidir", "linear", "xla"], level=5)
_force_reduce_scatter = cvar.register(
    "coll_pallas_reduce_scatter_algorithm", "", str,
    help="Force the pallas reduce_scatter_block variant: "
         "ring|bidir|linear|xla (see coll_pallas_allreduce_algorithm).",
    choices=["", "ring", "bidir", "linear", "xla"], level=5)
_force_allgather = cvar.register(
    "coll_pallas_allgather_algorithm", "", str,
    help="Force the pallas allgather variant: ring|bidir|xla "
         "(allgather has no reduction, so no linear fold).",
    choices=["", "ring", "bidir", "xla"], level=5)

_min_bytes_var = cvar.register(
    "coll_pallas_min_bytes", 0, int,
    help="Payloads below this fall through to coll/xla (XLA's "
         "latency-optimized lowering wins at tiny sizes; this is the "
         "low switchpoint). 0 [default] keeps every supported size "
         "on the pallas path.", level=5)
_bidir_min_var = cvar.register(
    "coll_pallas_bidir_min_bytes", 1 << 20, int,
    help="Payloads at/above this use the bidirectional ring (both "
         "ICI link directions carry half the payload) when no "
         "deterministic mode, forced algorithm, or switchpoint-table "
         "entry overrides; below it the clockwise ring. -1 disables "
         "the bidirectional default.", level=5)
_dma_max_var = cvar.register(
    "coll_pallas_dma_max_bytes", 64 << 20, int,
    help="Payload bound for the monolithic DMA kernels (whole-buffer "
         "VMEM residency: payload + double-buffered chunk scratch "
         "must fit); larger payloads fall through to coll/xla. Only "
         "consulted on the TPU (non-interpret) path. 0 = unbounded.",
    level=6)
_switch_var = cvar.register(
    "coll_pallas_switchpoints", "", str,
    help="Path to a measured switchpoint table (the JSON emitted by "
         "`bench.py --pallas` under extra.pallas.switchpoints): a "
         "list of {op, dtype, mesh, log2, algorithm} rules; for each "
         "(op, dtype, mesh) the rule with the largest log2 <= the "
         "payload's log2 bucket wins ('xla' falls through). Empty "
         "[default] uses the built-in thresholds.", level=5)

#: support matrix — everything else falls through to coll/xla
_SUPPORTED_DTYPES = frozenset(("float32", "bfloat16", "int32"))
_SUPPORTED_OPS = frozenset(("MPI_SUM", "MPI_PROD", "MPI_MIN",
                            "MPI_MAX"))

_BYTES_PVAR = {"ring": "pallas_ring_bytes",
               "bidir": "pallas_bidir_bytes",
               "linear": "pallas_linear_bytes"}

_FORCE = {"allreduce": _force_allreduce,
          "reduce_scatter_block": _force_reduce_scatter,
          "allgather": _force_allgather}


def _interpret() -> bool:
    mode = _interpret_var.get()
    if mode == "on":
        return True
    if mode == "off":
        return False
    return not jaxcompat.pallas_remote_dma_ok()


def _det_ok(deterministic: Optional[str]) -> Optional[str]:
    """Normalize the deterministic mode (slot arg over cvar default)
    and reject unknown values on this public coll path."""
    det = _xla._det(deterministic)
    if det not in (None, "ring", "linear"):
        raise errors.MPIError(
            errors.ERR_ARG,
            f"coll_pallas: deterministic={det!r} (expected None, "
            "'ring' or 'linear' — silent fallthrough would void the "
            "fixed-reduction-order guarantee)")
    return det


def _opn(op) -> Optional[op_mod.Op]:
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN.get(op)
    if opn is None or opn.name not in _SUPPORTED_OPS:
        return None
    return opn


def _fallthrough(xla_fn, *args, **kw):
    pvar.record("pallas_fallthrough")
    return xla_fn(*args, **kw)


_sw_cache: dict = {}


def _switchpoint(kind: str, nbytes: int, dtype: str,
                 mesh_shape) -> str:
    path = _switch_var.get().strip()
    if not path:
        return ""
    table = _sw_cache.get(path)
    if table is None:
        try:
            with open(path, encoding="utf-8") as f:
                entries = json.load(f)
        except (OSError, ValueError) as exc:
            # tune satellite: a fat-fingered table path is a silent
            # perf cliff — warn once per path, count every attempt
            _tobs.table_error("coll_pallas_switchpoints", path, exc)
            entries = []
        table = {}
        for e in entries if isinstance(entries, list) else []:
            key = (str(e.get("op", "")), str(e.get("dtype", "")),
                   tuple(int(v) for v in e.get("mesh", ())))
            table.setdefault(key, []).append(
                (int(e.get("log2", 0)), str(e.get("algorithm", ""))))
        for rules in table.values():
            rules.sort()
        _sw_cache[path] = table
    rules = table.get((kind, dtype, tuple(mesh_shape)))
    if not rules:
        return ""
    bucket = _algo.log2_bucket(nbytes)
    best = ""
    for lg, alg in rules:
        if bucket >= lg:
            best = alg
        else:
            break
    return best


def _select(kind: str, comm, sendbuf, det: Optional[str],
            chunk_rows: int) -> Optional[str]:
    """The decision layer: algorithm name, or None = fall through to
    coll/xla. Deterministic modes pin the matching kernel (the
    bit-identity contract); otherwise forced cvar > switchpoint
    table > built-in bidir threshold > ring."""
    ctx = _xla._ctx(comm)
    if ctx.mesh2d is not None:
        return None  # ICI x DCN comms: xla's split-level schedule
    dt = str(getattr(sendbuf, "dtype", ""))
    if dt not in _SUPPORTED_DTYPES:
        return None
    nbytes = int(getattr(sendbuf, "nbytes", 0))
    if nbytes == 0 or nbytes < _min_bytes_var.get():
        return None
    dma_max = _dma_max_var.get()
    if not _interpret() and 0 < dma_max < nbytes:
        return None
    forced = _FORCE[kind].get()
    if forced == "xla":
        return None
    if det == "linear":
        return "linear" if kind != "allgather" else "ring"
    if det == "ring":
        return "ring"
    if forced:
        return forced if not (forced == "bidir" and chunk_rows < 2) \
            else "ring"
    sw = _switchpoint(kind, nbytes, dt,
                      tuple(int(d) for d in ctx.mesh.devices.shape))
    if sw == "xla":
        return None
    if sw:
        return sw if not (sw == "bidir" and chunk_rows < 2) else "ring"
    bmin = _bidir_min_var.get()
    if 0 <= bmin <= nbytes and chunk_rows >= 2:
        return "bidir"
    return "ring"


def _launch(launcher, op: str, algo: str, comm=None, buf=None,
            nbytes=None):
    """Dispatch, with a coll_pallas trace span naming the chosen
    algorithm (the xla launch funnel inside adds its own span) and a
    tune-plane sample under provider 'pallas' when the observatory
    is up (`nbytes` overrides `buf.nbytes` for multi-buffer ops)."""
    obs = _tobs.OBSERVER
    if obs is not None:
        launcher = obs.timed(
            "pallas", op, algo, comm,
            int(getattr(buf, "nbytes", 0) if nbytes is None
                else nbytes),
            str(getattr(buf, "dtype", "")), launcher)
    rec = _trace.RECORDER
    if rec is None:
        return launcher()
    t0 = _trace.now()
    out = launcher()
    rec.record("launch", "coll_pallas", t0, _trace.now(),
               {"op": op, "algorithm": algo})
    return out


def _account(kind: str, comm, sendbuf, algo: str) -> None:
    nbytes = int(getattr(sendbuf, "nbytes", 0))
    pvar.record("pallas_launches")
    pvar.record(_BYTES_PVAR[algo], nbytes)
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll(kind, comm, nbytes,
                dtype=str(getattr(sendbuf, "dtype", "")),
                per_peer=_algo.pallas_per_peer(
                    kind, algo, comm.rank, comm.size, nbytes))


# ---------------------------------------------------------------------------
# slots — signatures match coll/xla's (the fallthrough target)


def _allreduce_prep(comm, sendbuf, opn, algo: str):
    from ompi_tpu.parallel import collectives as C

    ctx = _xla._ctx(comm)
    fnc = C.combine_fn(opn)
    interp = _interpret()

    def build():
        if algo == "linear":
            body = lambda a: K.linear_allreduce(  # noqa: E731
                a[0], _xla.AXIS, fnc, interpret=interp)
        else:
            body = lambda a: K.ring_allreduce(  # noqa: E731
                a[0], _xla.AXIS, fnc, interpret=interp,
                bidir=algo == "bidir")
        return ctx.smap(body, out_varying=False)

    fn = ctx.compiled(
        _xla._key(sendbuf, "pallas_allreduce", algo, opn.name, interp),
        build)
    g = ctx.to_global(sendbuf)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def allreduce_dev(comm, sendbuf, op=op_mod.SUM,
                  deterministic: Optional[str] = None):
    det = _det_ok(deterministic)
    opn = _opn(op)
    if opn is None or comm.size == 1:
        return _fallthrough(_xla.allreduce_dev, comm, sendbuf, op,
                            deterministic)
    size = int(getattr(sendbuf, "size", 0))
    chunk_rows = -(-size // comm.size) if size else 0
    algo = _select("allreduce", comm, sendbuf, det, chunk_rows)
    if algo is None:
        return _fallthrough(_xla.allreduce_dev, comm, sendbuf, op,
                            deterministic)
    _account("allreduce", comm, sendbuf, algo)
    launcher = _allreduce_prep(comm, sendbuf, opn, algo)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "allreduce", algo, comm, sendbuf)
    tok = fl.enter("allreduce_dev", getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return _launch(launcher, "allreduce", algo, comm, sendbuf)
    finally:
        fl.exit(tok)


def _allgather_prep(comm, sendbuf, algo: str):
    ctx = _xla._ctx(comm)
    interp = _interpret()
    shape = tuple(sendbuf.shape)
    n = ctx.n

    def build():
        def body(a):
            flat = a[0].reshape(-1)
            if algo == "bidir":
                full = K.bidir_allgather(flat, _xla.AXIS,
                                         interpret=interp)
            else:
                full = K.ring_allgather(flat, _xla.AXIS,
                                        interpret=interp)
            return full.reshape((n,) + shape)

        return ctx.smap(body, out_varying=False)

    fn = ctx.compiled(_xla._key(sendbuf, "pallas_allgather", algo,
                                interp), build)
    g = ctx.to_global(sendbuf)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def allgather_dev(comm, sendbuf):
    if comm.size == 1 or not hasattr(sendbuf, "shape"):
        return _fallthrough(_xla.allgather_dev, comm, sendbuf)
    algo = _select("allgather", comm, sendbuf, None,
                   int(getattr(sendbuf, "size", 0)))
    if algo is None:
        return _fallthrough(_xla.allgather_dev, comm, sendbuf)
    _account("allgather", comm, sendbuf, algo)
    launcher = _allgather_prep(comm, sendbuf, algo)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "allgather", algo, comm, sendbuf)
    tok = fl.enter("allgather_dev", getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return _launch(launcher, "allgather", algo, comm, sendbuf)
    finally:
        fl.exit(tok)


def _reduce_scatter_prep(comm, sendbuf, opn, algo: str):
    from ompi_tpu.parallel import collectives as C

    ctx = _xla._ctx(comm)
    fnc = C.combine_fn(opn)
    interp = _interpret()

    def build():
        def body(a):
            x = a[0]
            if algo == "linear":
                return K.linear_reduce_scatter(x, _xla.AXIS, fnc,
                                               interpret=interp)
            if algo == "bidir":
                return K.bidir_reduce_scatter(x, _xla.AXIS, fnc,
                                              interpret=interp)
            return K.ring_reduce_scatter(x, _xla.AXIS, fnc,
                                         interpret=interp)

        return ctx.smap(body, out_varying=True)

    fn = ctx.compiled(_xla._key(sendbuf, "pallas_rsb", algo, opn.name,
                                interp), build)
    g = ctx.to_global(sendbuf)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def reduce_scatter_block_dev(comm, sendbuf, op=op_mod.SUM,
                             deterministic: Optional[str] = None):
    det = _det_ok(deterministic)
    opn = _opn(op)
    if opn is None or comm.size == 1:
        return _fallthrough(_xla.reduce_scatter_block_dev, comm,
                            sendbuf, op, deterministic)
    if getattr(sendbuf, "ndim", 0) < 1 \
            or sendbuf.shape[0] % comm.size:
        # same contract as coll/xla: an indivisible dim 0 is a caller
        # error, not a fallthrough case
        return _fallthrough(_xla.reduce_scatter_block_dev, comm,
                            sendbuf, op, deterministic)
    algo = _select("reduce_scatter_block", comm, sendbuf, det,
                   sendbuf.shape[0] // comm.size)
    if algo is None:
        return _fallthrough(_xla.reduce_scatter_block_dev, comm,
                            sendbuf, op, deterministic)
    _account("reduce_scatter_block", comm, sendbuf, algo)
    launcher = _reduce_scatter_prep(comm, sendbuf, opn, algo)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "reduce_scatter_block", algo, comm,
                       sendbuf)
    tok = fl.enter("reduce_scatter_block_dev",
                   getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return _launch(launcher, "reduce_scatter_block", algo, comm,
                       sendbuf)
    finally:
        fl.exit(tok)


# ---------------------------------------------------------------------------
# fused slots (pallas-only: no xla equivalent one level down)


def fused_rs_update_dev(comm, grads, pshards, mshards, *,
                        lr: float, mu: float = 0.0, avg: bool = True,
                        deterministic: Optional[str] = None):
    """ZeRO fused reduce_scatter + shard update over the gradient
    pytree: per ZeroPlan bucket, ONE kernel reduce_scatters the flat
    bucket and consumes the reduced chunk in-register with the
    average/momentum/SGD epilogue. Returns ``(new_pshards,
    new_mshards)`` ShardedStates, or **None** when any bucket is
    unsupported — the caller (ZeroOptimizer) then runs the unfused
    sequence, the same staged-fallthrough shape as the other slots.

    Numerics: under ``deterministic='linear'`` (the reproducibility
    mode) only the reduce_scatter runs in-kernel; the epilogue replays
    the exact unfused eager op sequence, so fused == unfused bit for
    bit by construction. The default/'ring' mode fuses the epilogue
    into the kernel — same dtype and op order, but the compiler may
    contract multiply-add inside the single program, so it is
    equivalent to within one rounding of the unfused result."""
    det = _det_ok(deterministic)
    if comm.size == 1:
        pvar.record("pallas_fallthrough")
        return None
    import jax

    from ompi_tpu.parallel import collectives as C
    from ompi_tpu.zero import layout as _zl

    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        pvar.record("pallas_fallthrough")
        return None
    ctx = _xla._ctx(comm)
    if ctx.mesh2d is not None:
        pvar.record("pallas_fallthrough")
        return None
    plan = pshards.plan
    metas = _xla._fuse_metas(leaves)
    if metas != tuple(pshards.metas) \
            or any(str(dt) not in _SUPPORTED_DTYPES
                   for dt in plan.dtypes):
        pvar.record("pallas_fallthrough")
        return None
    with_mom = mshards is not None
    inv = 1.0 / comm.size if avg else None
    fnc = C.combine_fn(op_mod.SUM)
    interp = _interpret()
    lrf, muf = float(lr), float(mu)

    launches = []
    for b, idxs in enumerate(plan.buckets):
        pad = plan.padded[b] - plan.elems[b]
        sig = tuple((metas[i][0], metas[i][1]) for i in idxs)

        if det == "linear":
            # Reproducibility mode: the kernel ONLY reduce_scatters
            # (rank-order fold, bitwise equal to the unfused bucket
            # RS); the update epilogue runs eagerly in run() with the
            # exact unfused op sequence. Fusing the epilogue into the
            # same program would let the compiler contract p - lr*g
            # into an FMA and break the bit-identity contract.
            def build(idxs=idxs, pad=pad):
                def body(args):
                    import jax.numpy as jnp

                    gs, = args
                    flat = (jnp.concatenate(
                        [g[0].reshape(-1) for g in gs])
                        if len(gs) > 1 else gs[0][0].reshape(-1))
                    if pad:
                        flat = jnp.pad(flat, (0, pad))
                    return K.linear_reduce_scatter(
                        flat, _xla.AXIS, fnc, interpret=interp)

                return ctx.smap(body, out_varying=True)

            fn = ctx.compiled(
                ("pallas_fused_rs_lin", sig, pad, interp), build)
            gs = tuple(ctx.to_global(leaves[i]) for i in idxs)
            launches.append((fn, (gs,), b))
            continue

        def build(idxs=idxs, pad=pad):
            def body(args):
                import jax.numpy as jnp

                gs, p, v = args
                flat = (jnp.concatenate(
                    [g[0].reshape(-1) for g in gs])
                    if len(gs) > 1 else gs[0][0].reshape(-1))
                if pad:
                    flat = jnp.pad(flat, (0, pad))
                vv = v[0] if v is not None else None
                return K.ring_reduce_scatter_update(
                    flat, _xla.AXIS, fnc, p[0], vv, lr=lrf, mu=muf,
                    inv=inv, interpret=interp)

            return ctx.smap(body, out_varying=True)

        fn = ctx.compiled(
            ("pallas_fused_rs", sig, pad, interp, lrf, muf, inv,
             with_mom), build)
        gs = tuple(ctx.to_global(leaves[i]) for i in idxs)
        pg = ctx.to_global(pshards.shards[b])
        vg = ctx.to_global(mshards.shards[b]) if with_mom else None
        launches.append((fn, (gs, pg, vg), b))

    nbytes = plan.nbytes
    pvar.record("pallas_launches")
    pvar.record(_BYTES_PVAR["linear" if det == "linear" else "ring"],
                int(nbytes))
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("reduce_scatter_multi", comm, nbytes,
                dtype=str(plan.dtypes[0]) if plan.dtypes else "",
                per_peer=_algo.pallas_per_peer(
                    "reduce_scatter_multi",
                    "linear" if det == "linear" else "ring",
                    comm.rank, comm.size, nbytes))

    import numpy as np

    def run():
        new_p, new_m = [], []
        for fn, args, b in launches:
            out = ctx.launch(fn, args)
            pvar.record("pallas_fused_launches")
            if det == "linear":
                # eager epilogue, op-for-op the unfused step: each op
                # dispatches as its own program, so rounding points
                # match the unfused cycle exactly
                g = ctx.my_shard(out)
                if avg:
                    g = g * np.asarray(inv, g.dtype)
                if with_mom:
                    v0 = mshards.shards[b]
                    g = np.asarray(muf, v0.dtype) * v0 + g
                    new_m.append(g)
                p0 = pshards.shards[b]
                new_p.append(p0 - np.asarray(lrf, p0.dtype) * g)
                continue
            pn = ctx.my_shard(out[0])
            new_p.append(pn)
            if with_mom:
                new_m.append(ctx.my_shard(out[1]))
        ps = _zl.ShardedState(plan, pshards.metas, pshards.treedef,
                              new_p, comm.rank, comm.size)
        ms = _zl.ShardedState(plan, pshards.metas, pshards.treedef,
                              new_m, comm.rank, comm.size) \
            if with_mom else None
        return ps, ms

    return _launch(run, "fused_rs_update", det or "ring", comm,
                   leaves[0], nbytes=plan.nbytes)


def _allgather_matmul_prep(comm, x, w):
    ctx = _xla._ctx(comm)
    interp = _interpret()

    def build():
        def body(args):
            return K.allgather_matmul(args[0][0], args[1][0],
                                      _xla.AXIS, interpret=interp)

        return ctx.smap(body, out_varying=False)

    fn = ctx.compiled(_xla._key(x, "pallas_agmm", tuple(w.shape),
                                str(w.dtype), interp), build)
    g = (ctx.to_global(x), ctx.to_global(w))
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def allgather_matmul_dev(comm, x, w):
    """Tensor-parallel fused allgather@matmul: x is this rank's
    (m, d) row block, w the replicated (d, f) weight; returns the
    full (n*m, f) product with each arriving block multiplied while
    the next ring hop is in flight. Unsupported cases compose the
    plain device allgather with a local matmul (same result, no
    overlap)."""
    import jax.numpy as jnp

    ok = (comm.size > 1
          and getattr(x, "ndim", 0) == 2
          and getattr(w, "ndim", 0) == 2
          and x.shape[1] == w.shape[0]
          and str(x.dtype) in _SUPPORTED_DTYPES
          and str(w.dtype) in _SUPPORTED_DTYPES
          and _xla._ctx(comm).mesh2d is None)
    if not ok:
        pvar.record("pallas_fallthrough")
        gathered = _xla.allgather_dev(comm, x)
        full = jnp.asarray(gathered).reshape(
            (comm.size * x.shape[0],) + tuple(x.shape[1:]))
        return jnp.dot(full, w)
    _account("allgather", comm, x, "ring")
    pvar.record("pallas_fused_launches")
    launcher = _allgather_matmul_prep(comm, x, w)
    fl = _flight.FLIGHT
    if fl is None:
        return _launch(launcher, "allgather_matmul", "ring", comm, x)
    tok = fl.enter("allgather_matmul_dev", getattr(comm, "cid", -1),
                   getattr(x, "nbytes", 0))
    try:
        return _launch(launcher, "allgather_matmul", "ring", comm, x)
    finally:
        fl.exit(tok)


def zero3_gather_matmul_dev(comm, state, rhs):
    """ZeRO stage-3 fused gather→use fast path: consume a sharded
    2-D weight W (a single-bucket single-leaf ShardedState) directly
    against ``rhs`` as ``allgather_matmul(shard_rows, rhs)`` — the
    gather of W overlaps the matmul, and the full W is NEVER
    materialized as a standalone array. Works because a contiguous
    1/n slice of a row-major (d, f) flatten with d % n == 0 and no
    pad IS rows [r*d/n, (r+1)*d/n): the flat shard reshapes to this
    rank's row block and the tensor-parallel kernel's rank-order
    concat equals the ZeroPlan pack order. Returns the (d, k) product
    or **None** for every other layout — the zero-3 engine then
    gathers through the persistent coll/xla allgather and matmuls
    locally (staged fallthrough)."""
    plan = getattr(state, "plan", None)
    shards = getattr(state, "shards", None)
    ok = (comm.size > 1
          and plan is not None and shards is not None
          and len(plan.buckets) == 1
          and len(plan.buckets[0]) == 1
          and plan.padded[0] == plan.elems[0]
          and getattr(rhs, "ndim", 0) == 2
          and str(getattr(rhs, "dtype", "")) in _SUPPORTED_DTYPES
          and str(plan.dtypes[0]) in _SUPPORTED_DTYPES)
    if ok:
        shape = state.metas[plan.buckets[0][0]][0]
        ok = (len(shape) == 2
              and int(shape[0]) % comm.size == 0
              and int(shape[1]) == int(rhs.shape[0]))
    if not ok:
        pvar.record("pallas_fallthrough")
        return None
    block = shards[0].reshape(int(shape[0]) // comm.size,
                              int(shape[1]))
    return allgather_matmul_dev(comm, block, rhs)


# ---------------------------------------------------------------------------


@framework.register
class CollPallas(CollModule):
    NAME = "pallas"
    PRIORITY = 60  # above xla(50): hand-rolled kernels override the
    # XLA lowering for the ops they implement; everything else keeps
    # resolving to xla's slots

    def query(self, comm) -> int:
        if _enable_var.get() != "on":
            return -1
        if comm.size == 1:
            return -1  # xla's trivial local path is already optimal
        from ompi_tpu.runtime import device_plane

        if not device_plane.active():
            return -1
        if any(device_plane.device_for_world_rank(w) is None
               for w in comm.group.ranks):
            return -1
        return self.PRIORITY

    def slots(self, comm):
        return {
            "allreduce_dev": allreduce_dev,
            "allgather_dev": allgather_dev,
            "reduce_scatter_block_dev": reduce_scatter_block_dev,
            # fused compute+comm kernels (pallas-only slots)
            "fused_rs_update_dev": fused_rs_update_dev,
            "allgather_matmul_dev": allgather_matmul_dev,
            "zero3_gather_matmul_dev": zero3_gather_matmul_dev,
        }
