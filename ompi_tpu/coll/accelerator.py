"""coll/accelerator — device-buffer interposition for the process plane.

Reference: ompi/mca/coll/accelerator (coll_accelerator_allreduce.c:32-115
— check_buf -> stage D2H -> underlying host collective -> copy back),
priority-stacked above tuned so device buffers are intercepted while host
buffers fall through untouched.

TPU-native division of labor (SURVEY.md §5 "Distributed communication
backend"): *within* an SPMD program, collectives on device shards are
XLA ops over ICI — that path is :mod:`ompi_tpu.parallel` and never
enters this component. This component serves the **multi-process MPI
plane**: ranks are OS processes, each holding jax Arrays; collective
movement rides the host transports (sm/tcp BTLs), with D2H/H2D staging
through the selected accelerator component — exactly the reference's
staging design.

Device slots return a *new* device array (jax Arrays are immutable;
in-place recv semantics are impossible on PJRT buffers — the API layer
documents this divergence).
"""

from __future__ import annotations

import numpy as np

from ompi_tpu import errors
from ompi_tpu import op as op_mod
from ompi_tpu.accelerator import current as acc_current
from ompi_tpu.coll import CollModule, framework
from ompi_tpu.core import pvar

def _stage_in(buf, writable: bool = False):
    """D2H: device array -> host numpy (reference: check_buf + memcpy).

    device_get may return a read-only view of the PJRT buffer; ask for
    ``writable=True`` only where the host collective mutates it in
    place (one copy, not two, on send-only paths)."""
    host = np.asarray(acc_current().to_host(buf))
    if writable and not host.flags.writeable:
        host = host.copy()
    return host


def _stage_out(host, like):
    """H2D: host numpy -> device array on like's device."""
    return acc_current().to_device(host, like=like)


def allreduce_dev(comm, sendbuf, op=op_mod.SUM, deterministic=None):
    # `deterministic` accepted for slot-signature parity with coll/xla;
    # the host path folds in whatever order the selected host algorithm
    # uses (basic's linear fold is already rank-ordered)
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    recv = np.empty_like(host)
    comm.coll.allreduce(comm, host, recv, recv.size, None, op)
    return _stage_out(recv, sendbuf)


def allreduce_multi_dev(comm, bufs, op=op_mod.SUM, deterministic=None):
    """Staged fallthrough for the fused (bucketed) allreduce: a
    per-buffer staged loop — device-side fusion buys nothing once the
    payload crosses the host transports, so the loop keeps semantics
    without pretending to coalesce."""
    import jax

    return jax.tree.map(
        lambda b: allreduce_dev(comm, b, op, deterministic), bufs)


def reduce_scatter_multi_dev(comm, bufs, op=op_mod.SUM,
                             deterministic=None):
    """Staged fallthrough for the zero/ bucketed reduce_scatter:
    D2H every leaf, run the host bucket cycle (one host allreduce per
    bucket + local slice), H2D the shards back next to the input
    leaves. Serves non-traceable ops and plane-off comms; the
    single-launch win is device-path only."""
    import jax

    from ompi_tpu.zero import layout as _zl

    pvar.record("coll_accelerator_staged")
    leaves = jax.tree.leaves(bufs)
    hosts = jax.tree.map(lambda b: _stage_in(b), bufs)
    st = _zl.host_reduce_scatter_multi(comm, hosts, op)
    if leaves and not isinstance(leaves[0], np.ndarray):
        st.shards = [_stage_out(s, leaves[0]) for s in st.shards]
    return st


def allgather_multi_dev(comm, state):
    """Staged fallthrough for the zero/ bucketed allgather: host
    object-channel allgather per bucket shard, reassemble, H2D the
    rebuilt leaves when the shards were device arrays."""
    from ompi_tpu.zero import layout as _zl

    pvar.record("coll_accelerator_staged")
    dev_template = None
    hosts = []
    for s in state.shards:
        if isinstance(s, np.ndarray):
            hosts.append(s)
        else:
            dev_template = s
            hosts.append(_stage_in(s))
    hstate = _zl.ShardedState(state.plan, state.metas, state.treedef,
                              hosts, state.rank, state.n)
    out = _zl.host_allgather_multi(comm, hstate)
    if dev_template is None:
        return out
    import jax

    return jax.tree.map(lambda h: _stage_out(h, dev_template), out)


def bcast_dev(comm, buf, root=0):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(buf, writable=True)
    comm.coll.bcast(comm, host, host.size, None, root)
    return _stage_out(host, buf)


def reduce_dev(comm, sendbuf, op=op_mod.SUM, root=0, deterministic=None):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    recv = np.empty_like(host)
    comm.coll.reduce(comm, host, recv, host.size, None, op, root)
    if comm.rank != root:
        return None
    return _stage_out(recv, sendbuf)


def allgather_dev(comm, sendbuf):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    recv = np.empty((comm.size,) + host.shape, host.dtype)
    comm.coll.allgather(comm, host, recv, host.size, None)
    return _stage_out(recv, sendbuf)


def alltoall_dev(comm, sendbuf):
    """Dim 0 of sendbuf (size n*k) is the destination split."""
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    if host.size % comm.size:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"alltoall: {host.size} elements not divisible by "
            f"comm size {comm.size}")
    recv = np.empty_like(host)
    comm.coll.alltoall(comm, host, recv, host.size // comm.size, None)
    return _stage_out(recv, sendbuf)


def reduce_scatter_block_dev(comm, sendbuf, op=op_mod.SUM,
                             deterministic=None):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    n = comm.size
    if host.shape[0] % n:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"reduce_scatter_block: dim0 {host.shape[0]} not "
            f"divisible by comm size {n}")
    recv = np.empty((host.shape[0] // n,) + host.shape[1:], host.dtype)
    comm.coll.reduce_scatter_block(comm, host, recv, recv.size, None, op)
    return _stage_out(recv, sendbuf)


def barrier_dev(comm):
    """No device payload to stage: the host barrier IS the semantics."""
    pvar.record("coll_accelerator_staged")
    comm.coll.barrier(comm)


def allgatherv_dev(comm, sendbuf, counts):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    total = int(sum(counts))
    recv = np.empty((total,) + host.shape[1:], host.dtype)
    displs = np.concatenate(
        [[0], np.cumsum(np.asarray(counts[:-1]))]).tolist()
    row = int(np.prod(host.shape[1:], dtype=np.int64)) or 1
    comm.coll.allgatherv(comm, host.reshape(-1),
                         recv.reshape(-1),
                         [int(c) * row for c in counts],
                         [int(d) * row for d in displs], None)
    return _stage_out(recv, sendbuf)


def gatherv_dev(comm, sendbuf, counts, root=0):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    row = int(np.prod(host.shape[1:], dtype=np.int64)) or 1
    recv = (np.empty((int(sum(counts)),) + host.shape[1:], host.dtype)
            if comm.rank == root else None)
    displs = np.concatenate(
        [[0], np.cumsum(np.asarray(counts[:-1]))]).tolist()
    comm.coll.gatherv(comm, host.reshape(-1),
                      None if recv is None else recv.reshape(-1),
                      [int(c) * row for c in counts],
                      [int(d) * row for d in displs], None, root)
    if comm.rank != root:
        return None
    return _stage_out(recv, sendbuf)


def alltoallv_dev(comm, sendbuf, scounts, rcounts, max_count=None):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    rest = host.shape[1:]
    row = int(np.prod(rest, dtype=np.int64)) or 1
    recv = np.empty((int(sum(rcounts)),) + rest, host.dtype)
    sdispls = np.concatenate(
        [[0], np.cumsum(np.asarray(scounts[:-1]))]).tolist()
    rdispls = np.concatenate(
        [[0], np.cumsum(np.asarray(rcounts[:-1]))]).tolist()
    comm.coll.alltoallv(comm, host.reshape(-1), recv.reshape(-1),
                        [int(c) * row for c in scounts],
                        [int(d) * row for d in sdispls],
                        [int(c) * row for c in rcounts],
                        [int(d) * row for d in rdispls], None)
    return _stage_out(recv, sendbuf)


def reduce_scatter_dev(comm, sendbuf, counts, op=op_mod.SUM,
                       deterministic=None):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    counts = [int(c) for c in counts]
    if sum(counts) != host.shape[0]:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"reduce_scatter: counts sum to {sum(counts)} but sendbuf "
            f"dim0 is {host.shape[0]}")
    recv = np.empty((counts[comm.rank],) + host.shape[1:], host.dtype)
    row = int(np.prod(host.shape[1:], dtype=np.int64)) or 1
    comm.coll.reduce_scatter(comm, host.reshape(-1), recv.reshape(-1),
                             [c * row for c in counts], None, op)
    return _stage_out(recv, sendbuf)


def scatterv_dev(comm, sendbuf, counts, root=0, like=None):
    """Same obj-channel design as scatter_dev: ragged chunks ride the
    object channel with their shapes, no metadata round."""
    pvar.record("coll_accelerator_staged")
    if comm.rank == root:
        host = _stage_in(sendbuf)
        chunks = []
        off = 0
        for c in counts:
            chunks.append(host[off:off + int(c)])
            off += int(c)
    else:
        chunks = None
    chunk = comm.coll.scatter_obj(comm, chunks, root)
    return _stage_out(np.asarray(chunk),
                      sendbuf if comm.rank == root else like)


def scatter_dev(comm, sendbuf, root=0, like=None):
    """One obj-channel collective (exactly one tag consumed on every
    rank) so the chunk shape/dtype ride along with the data — no
    separate metadata round that could desynchronize tag sequences."""
    pvar.record("coll_accelerator_staged")
    n = comm.size
    if comm.rank == root:
        host = _stage_in(sendbuf)
        if host.shape[0] % n:
            raise errors.MPIError(
                errors.ERR_COUNT,
                f"scatter: dim0 {host.shape[0]} not divisible "
                f"by comm size {n}")
        k = host.shape[0] // n
        chunks = [host[r * k:(r + 1) * k] for r in range(n)]
    else:
        chunks = None
    chunk = comm.coll.scatter_obj(comm, chunks, root)
    return _stage_out(np.asarray(chunk), sendbuf)


def gather_dev(comm, sendbuf, root=0):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    recv = np.empty((comm.size,) + host.shape, host.dtype) \
        if comm.rank == root else None
    comm.coll.gather(comm, host, recv, host.size, None, root)
    if comm.rank != root:
        return None
    return _stage_out(recv, sendbuf)


def scan_dev(comm, sendbuf, op=op_mod.SUM, deterministic=None):
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    recv = np.empty_like(host)
    comm.coll.scan(comm, host, recv, host.size, None, op)
    return _stage_out(recv, sendbuf)


def exscan_dev(comm, sendbuf, op=op_mod.SUM, deterministic=None):
    """MPI semantics: rank 0's result is undefined — this path pins it
    to zeros, matching coll/xla's traced exscan default so the two
    components agree."""
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    recv = np.empty_like(host)
    comm.coll.exscan(comm, host, recv, host.size, None, op)
    if comm.rank == 0:
        recv = np.zeros_like(host)
    return _stage_out(recv, sendbuf)


def neighbor_allgather_dev(comm, sendbuf):
    """Device-form contract (same as coll/xla_neighbor): EVERY rank
    passes a same-shaped sendbuf — a receive-only rank's buffer is a
    pure shape template (its data goes nowhere), so the per-edge
    count is the buffer size on every rank."""
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    ins = comm.topo.in_neighbors(comm.rank)
    recv = np.zeros((len(ins),) + host.shape, host.dtype)
    comm.coll.neighbor_allgather(comm, host, recv, host.size, None)
    return _stage_out(recv, sendbuf)


def neighbor_alltoall_dev(comm, sendbuf):
    """sendbuf rows are per-out-neighbor blocks (row j to out-neighbor
    j); result rows are per-in-neighbor (PROC_NULL rows zero).
    Zero-size blocks are a legal no-op exchange (count 0)."""
    pvar.record("coll_accelerator_staged")
    host = _stage_in(sendbuf)
    ins = comm.topo.in_neighbors(comm.rank)
    outs = comm.topo.out_neighbors(comm.rank)
    if host.shape[0] != len(outs):
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"neighbor_alltoall: sendbuf dim0 {host.shape[0]} != "
            f"out-degree {len(outs)}")
    recv = np.zeros((len(ins),) + host.shape[1:], host.dtype)
    count = int(np.prod(host.shape[1:], dtype=np.int64))
    comm.coll.neighbor_alltoall(comm, host, recv, count, None)
    return _stage_out(recv, sendbuf)


def _istaged(fn):
    """Staged i-variant: the host collective runs synchronously (the
    staging path has no async substrate), then the result is wrapped in
    the same request type the device path returns — honest completion,
    uniform caller contract."""
    def islot(*args, **kwargs):
        from ompi_tpu.coll.xla import DeviceRequest

        return DeviceRequest(fn(*args, **kwargs))
    islot.__name__ = "i" + fn.__name__
    return islot


def _pstaged(fn):
    """Persistent-init over the staged path: every start() re-runs
    the staged collective (coll/xla's request machinery drives the
    cycle — ONE construction helper, not a copy)."""
    from ompi_tpu.coll import xla as _xla

    return _xla._pinit(fn)


def ibarrier_dev(comm):
    from ompi_tpu.coll.xla import DeviceRequest

    barrier_dev(comm)
    return DeviceRequest(None)


def pallreduce_init_dev(comm, bufs, op=op_mod.SUM, deterministic=None):
    """Partitioned fused allreduce over the staged path: full MPI-4
    Pready/Parrived bookkeeping with the reduction deferred to wait()
    (no device-plane overlap — coll/xla owns that payoff)."""
    from ompi_tpu.coll import xla as _xla

    return _xla._TrivialPartitionedAllreduce(comm, bufs, op,
                                             deterministic)


def preduce_scatter_init_dev(comm, bufs, op=op_mod.SUM,
                             deterministic=None):
    """Partitioned zero/ reduce_scatter over the staged path — same
    deferred-to-wait design as pallreduce_init_dev."""
    from ompi_tpu.coll import xla as _xla

    return _xla._TrivialPartitionedReduceScatter(comm, bufs, op,
                                                 deterministic)


@framework.register
class CollAccelerator(CollModule):
    NAME = "accelerator"
    PRIORITY = 40  # above tuned(30): intercepts device buffers

    def query(self, comm) -> int:
        return self.PRIORITY

    def slots(self, comm):
        nbr = {} if getattr(comm, "topo", None) is None else {
            "neighbor_allgather_dev": neighbor_allgather_dev,
            "neighbor_alltoall_dev": neighbor_alltoall_dev,
        }
        return {
            **nbr,
            "allreduce_dev": allreduce_dev,
            "bcast_dev": bcast_dev,
            "reduce_dev": reduce_dev,
            "allgather_dev": allgather_dev,
            "alltoall_dev": alltoall_dev,
            "reduce_scatter_block_dev": reduce_scatter_block_dev,
            "scatter_dev": scatter_dev,
            "gather_dev": gather_dev,
            "scan_dev": scan_dev,
            "exscan_dev": exscan_dev,
            "barrier_dev": barrier_dev,
            "allgatherv_dev": allgatherv_dev,
            "gatherv_dev": gatherv_dev,
            "alltoallv_dev": alltoallv_dev,
            "scatterv_dev": scatterv_dev,
            "reduce_scatter_dev": reduce_scatter_dev,
            "reduce_scatter_multi_dev": reduce_scatter_multi_dev,
            "allgather_multi_dev": allgather_multi_dev,
            "ireduce_scatter_dev": _istaged(reduce_scatter_dev),
            "ibarrier_dev": ibarrier_dev,
            "iallreduce_dev": _istaged(allreduce_dev),
            "ibcast_dev": _istaged(bcast_dev),
            "ireduce_dev": _istaged(reduce_dev),
            "iallgather_dev": _istaged(allgather_dev),
            "igather_dev": _istaged(gather_dev),
            "ialltoall_dev": _istaged(alltoall_dev),
            "ireduce_scatter_block_dev":
                _istaged(reduce_scatter_block_dev),
            "iscatter_dev": _istaged(scatter_dev),
            "iscan_dev": _istaged(scan_dev),
            "iexscan_dev": _istaged(exscan_dev),
            "iallgatherv_dev": _istaged(allgatherv_dev),
            "igatherv_dev": _istaged(gatherv_dev),
            "ialltoallv_dev": _istaged(alltoallv_dev),
            "iscatterv_dev": _istaged(scatterv_dev),
            "allreduce_multi_dev": allreduce_multi_dev,
            "allreduce_multi_init_dev": _pstaged(allreduce_multi_dev),
            "pallreduce_init_dev": pallreduce_init_dev,
            "reduce_scatter_multi_init_dev":
                _pstaged(reduce_scatter_multi_dev),
            "allgather_multi_init_dev": _pstaged(allgather_multi_dev),
            "preduce_scatter_init_dev": preduce_scatter_init_dev,
            "allreduce_init_dev": _pstaged(allreduce_dev),
            "bcast_init_dev": _pstaged(bcast_dev),
            "allgather_init_dev": _pstaged(allgather_dev),
            "alltoall_init_dev": _pstaged(alltoall_dev),
            "reduce_scatter_block_init_dev":
                _pstaged(reduce_scatter_block_dev),
        }
