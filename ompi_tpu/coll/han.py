"""coll/han — hierarchical two-level collectives.

Reference: ompi/mca/coll/han/coll_han.h:22-33,62-63 — split each
communicator into an intra-node ``low_comm`` and an inter-node
``up_comm`` of node leaders, then compose per-level algorithms so
inter-node traffic is minimized (one message per node instead of one
per rank). The reference's default priority is 35, above tuned.

TPU mapping: "node" is the unit of cheap transport — sm rings
intra-host, tcp/DCN inter-host; once multi-host lands the same split is
the ICI-slice × DCN hierarchy (SURVEY §2.10 row "hierarchical").

Sub-communicators are built lazily on the first collective (the
reference does the same — han's comm_create on first use), which is
safe because every member reaches that first collective together.
Testing aid: cvar ``coll_han_split=modulo:K`` fakes K-node topology on
one host (the reference pins algorithms with forced cvars the same
way).
"""

from __future__ import annotations

import hashlib

import numpy as np

from ompi_tpu.core import cvar, pvar
from ompi_tpu.coll import CollModule, framework

_IN_PLACE = "MPI_IN_PLACE"  # sentinel shared with mpi.py / coll/basic

_split_var = cvar.register(
    "coll_han_split", "auto", str,
    help="Node-split strategy: 'auto' (by hostname), 'modulo:K' "
         "(fake K nodes for single-host testing), 'off'.", level=6)
_prio_var = cvar.register(
    "coll_han_priority", 35, int,
    help="coll/han selection priority (reference default 35, above "
         "tuned).", level=6)


def _node_color(comm) -> int:
    spec = _split_var.get()
    if spec.startswith("modulo:"):
        k = max(1, int(spec.split(":", 1)[1]))
        # contiguous blocks of ranks pretend to share a node
        per = -(-comm.size // k)
        return comm.rank // per
    from ompi_tpu.runtime import rte

    host = rte.hostname()
    return int.from_bytes(
        hashlib.sha1(host.encode()).digest()[:4], "little") & 0x7FFFFFFF


class _Levels:
    """low = my node's ranks; up = node leaders (or None if not one)."""

    def __init__(self, comm) -> None:
        from ompi_tpu.comm import UNDEFINED

        color = _node_color(comm)
        self.low = comm.split(color, key=comm.rank)
        is_leader = self.low.rank == 0
        self.up = comm.split(0 if is_leader else UNDEFINED,
                             key=comm.rank)
        # map: which comm-rank leads my node / each node's leader list
        self.leader_commrank = self._bcast_low_obj(
            comm.rank if is_leader else None)

    def _bcast_low_obj(self, obj):
        low = self.low
        if low.rank == 0:
            for r in range(1, low.size):
                low.send(obj, dest=r, tag=1)
            return obj
        return low.recv(source=0, tag=1)

    def release(self) -> None:
        """Free both sub-communicators — called from the parent
        Comm.free teardown; without it every han-served comm leaked
        its low/up splits (cids, coll tables, device ctxs) for the
        life of the job."""
        for sub in (self.low, self.up):
            if sub is not None and not getattr(sub, "_freed", False):
                sub.free()
        self.low = None
        self.up = None


def _levels(comm) -> _Levels:
    lv = getattr(comm, "_han_levels", None)
    if lv is None:
        lv = _Levels(comm)
        comm._han_levels = lv
    return lv


@framework.register
class CollHan(CollModule):
    NAME = "han"

    def query(self, comm) -> int:
        spec = _split_var.get()
        if spec == "off" or comm.size < 4:
            return -1
        if spec == "auto":
            # single-host job => every rank same node => hierarchy is
            # pure overhead; disqualify (reference han does the same
            # one-node check)
            return -1 if _single_node() else _prio_var.get()
        return _prio_var.get()

    def slots(self, comm):
        return {
            "barrier": barrier_han,
            "bcast": bcast_han,
            "reduce": reduce_han,
            "allreduce": allreduce_han,
            "allgather": allgather_han,
        }


def _single_node() -> bool:
    # all ranks of this job share local_size == size (launcher contract)
    from ompi_tpu.runtime import rte

    return rte.local_size >= rte.size


# -- composed algorithms (coll_han_*_intra two-level compositions) ---------

def allreduce_han(comm, sendbuf, recvbuf, count, dtype, op):
    """low reduce -> up allreduce among leaders -> low bcast
    (coll_han_allreduce.c's default composition)."""
    pvar.record("han_allreduce")
    lv = _levels(comm)
    if sendbuf is _IN_PLACE:
        # materialize: comm-level IN_PLACE would confuse the low
        # reduce when the comm root is not the low root
        sendbuf = np.array(recvbuf, copy=True)
    lv.low.coll.reduce(lv.low, sendbuf, recvbuf, count, dtype, op, 0)
    if lv.up is not None:
        tmp = np.array(recvbuf, copy=True)
        lv.up.coll.allreduce(lv.up, tmp, recvbuf, count, dtype, op)
    lv.low.coll.bcast(lv.low, recvbuf, count, dtype, 0)


def reduce_han(comm, sendbuf, recvbuf, count, dtype, op, root):
    """low reduce to node leaders -> up reduce to root's leader -> ship
    to root if the root is not a leader."""
    pvar.record("han_reduce")
    lv = _levels(comm)
    if sendbuf is _IN_PLACE:  # only legal at root, which has recvbuf
        sendbuf = np.array(recvbuf, copy=True)
    tmp = np.empty_like(np.asarray(sendbuf))
    lv.low.coll.reduce(lv.low, sendbuf, tmp, count, dtype, op, 0)
    root_leader = _leader_of(comm, root)
    if lv.up is not None:
        up_root = _up_rank_of(comm, lv, root_leader)
        lv.up.coll.reduce(lv.up, tmp, tmp, count, dtype, op, up_root)
    # root's node leader forwards to root (one hop, intra-node)
    if comm.rank == root_leader and root != root_leader:
        comm.Send(tmp, dest=root, tag=_han_tag(comm))
    if comm.rank == root:
        if root == root_leader:
            np.copyto(np.asarray(recvbuf), tmp)
        else:
            comm.Recv(recvbuf, source=root_leader, tag=_han_tag(comm))


def bcast_han(comm, buf, count, dtype, root):
    """root -> its leader -> up bcast -> low bcast."""
    pvar.record("han_bcast")
    lv = _levels(comm)
    root_leader = _leader_of(comm, root)
    if comm.rank == root and root != root_leader:
        comm.Send(buf, dest=root_leader, tag=_han_tag(comm))
    if comm.rank == root_leader and root != root_leader:
        comm.Recv(buf, source=root, tag=_han_tag(comm))
    if lv.up is not None:
        up_root = _up_rank_of(comm, lv, root_leader)
        lv.up.coll.bcast(lv.up, buf, count, dtype, up_root)
    lv.low.coll.bcast(lv.low, buf, count, dtype, 0)


def barrier_han(comm):
    pvar.record("han_barrier")
    lv = _levels(comm)
    # gather at leaders, leaders rendezvous, release
    lv.low.coll.barrier(lv.low)
    if lv.up is not None:
        lv.up.coll.barrier(lv.up)
    lv.low.coll.barrier(lv.low)


def allgather_han(comm, sendbuf, recvbuf, count, dtype):
    """low gather -> up allgather (node blocks) -> low bcast, then
    reorder node blocks into comm-rank order."""
    pvar.record("han_allgather")
    # han's allgather needs rank-reordering bookkeeping; the simple
    # correct composition: allreduce a one-hot assembled buffer would
    # waste bandwidth, so fall back to gather+bcast through leaders.
    lv = _levels(comm)
    if sendbuf is _IN_PLACE:  # my block already sits in recvbuf
        flat = np.asarray(recvbuf).reshape(comm.size, -1)
        sendbuf = np.array(flat[comm.rank], copy=True)
    send = np.asarray(sendbuf)
    n = send.size
    low_buf = (np.empty(n * lv.low.size, dtype=send.dtype)
               if lv.low.rank == 0 else None)
    lv.low.coll.gather(lv.low, send, low_buf, n, dtype, 0)
    full = np.asarray(recvbuf).reshape(-1)
    if lv.up is not None:
        # leaders exchange (node_ranks, block) and place by comm rank
        my_ranks = _low_commranks(comm, lv)
        pieces = lv.up.allgather((my_ranks, low_buf))
        for ranks, block in pieces:
            block = np.asarray(block).reshape(len(ranks), -1)
            for i, r in enumerate(ranks):
                full[r * n:(r + 1) * n] = block[i].view(send.dtype)
    lv.low.coll.bcast(lv.low, full, full.size, dtype, 0)
    np.asarray(recvbuf).reshape(-1)[:] = full


# -- helpers ---------------------------------------------------------------

def _han_tag(comm) -> int:
    return 78100


def _leader_of(comm, rank: int) -> int:
    """comm rank of `rank`'s node leader (deterministic: lowest comm
    rank with the same node color — recomputed, no exchange needed)."""
    colors = _color_table(comm)
    c = colors[rank]
    return min(i for i, col in enumerate(colors) if col == c)


def _up_rank_of(comm, lv, leader_commrank: int) -> int:
    """rank within up_comm of a leader, derived from color order."""
    colors = _color_table(comm)
    leaders = sorted(
        min(i for i, c in enumerate(colors) if c == col)
        for col in sorted(set(colors)))
    return leaders.index(leader_commrank)


def _color_table(comm):
    tbl = getattr(comm, "_han_colors", None)
    if tbl is None:
        spec = _split_var.get()
        if spec.startswith("modulo:"):
            k = max(1, int(spec.split(":", 1)[1]))
            per = -(-comm.size // k)
            tbl = [r // per for r in range(comm.size)]
        else:
            # single-color fallback; 'auto' multi-host exchanges
            # hostnames once via allgather
            tbl = comm.allgather(_node_color(comm))
        comm._han_colors = tbl
    return tbl


def _low_commranks(comm, lv):
    """comm ranks belonging to my node, in low-comm rank order."""
    colors = _color_table(comm)
    mine = colors[comm.rank]
    return [i for i, c in enumerate(colors) if c == mine]
