"""coll/sync — barrier-injection debug component (race smoker).

Reference: ompi/mca/coll/sync (925 LoC): when enabled, interposes on
collectives and injects an MPI_Barrier before every Nth operation, to
flush out applications relying on unsynchronized collective timing
(e.g. a bcast racing a later p2p). Priority puts it ABOVE every real
component; the installed slot wraps whatever was stacked underneath.

Enable: --mca coll_sync_barrier_before N  (0 = off, the default).
"""

from __future__ import annotations

from ompi_tpu.core import cvar, pvar
from ompi_tpu.coll import CollModule, SLOTS, framework

_before_var = cvar.register(
    "coll_sync_barrier_before", 0, int,
    help="Inject a barrier before every Nth collective (0=off). "
         "Debug aid for flushing collective/p2p races "
         "(reference: coll/sync).", level=7)

#: slots never wrapped: wrapping barrier with barrier is recursion,
#: and *_dev device slots take different signatures
_SKIP = {"barrier", "ibarrier"}


class _Wrapped:
    """One wrapped slot; counts calls per comm, barriers every Nth."""

    def __init__(self, inner, table) -> None:
        self._inner = inner
        self._table = table  # the table's real barrier (post-stack)

    def __call__(self, comm, *args, **kwargs):
        n = _before_var.get()
        if n > 0:
            self._table.calls += 1
            if self._table.calls % n == 0:
                pvar.record("sync_injected_barriers")
                self._table.fns["barrier"](comm)
        return self._inner(comm, *args, **kwargs)


@framework.register
class CollSync(CollModule):
    NAME = "sync"
    PRIORITY = 90  # above everything: interposition (reference: sync
    # must out-prioritize the components it wraps)
    INTER_OK = True

    def query(self, comm) -> int:
        return self.PRIORITY if _before_var.get() > 0 else -1

    def slots(self, comm):
        return {}  # interposition happens in post_stack, which sees
        # the fully-stacked table (slots() would see a partial one)

    def post_stack(self, comm, table) -> None:
        """Wrap every host collective slot already stacked."""
        table.calls = 0  # explicit: CollTable.__getattr__ raises for
        # unknown names, so getattr-with-default doesn't apply
        for name in list(table.fns):
            if name in _SKIP or name.endswith("_dev"):
                continue
            if name in SLOTS or name.startswith("i"):
                table.fns[name] = _Wrapped(table.fns[name], table)
                table.providers[name] = f"sync({table.providers[name]})"
