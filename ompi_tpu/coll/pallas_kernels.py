"""coll/pallas_kernels — hand-rolled ring collective kernels in Pallas.

The kernel library under :mod:`ompi_tpu.coll.pallas`: ring and
bidirectional-ring reduce_scatter / allgather / allreduce, the
rank-order "linear" fold, and the two fused compute+comm kernels
(reduce_scatter fused with the ZeRO shard update, matmul-overlapped
allgather). Every function runs inside ``shard_map`` tracing with the
comm's mesh axis bound, exactly like :mod:`ompi_tpu.parallel.ring` —
and follows the *same chunk schedule*, so 'ring' results are bitwise
equal to the ppermute rings and 'linear' results are bitwise equal to
``coll/xla``'s rank-order fold.

Transport gate (``interpret=``):

- **TPU** (``interpret=False``): one monolithic ``pl.pallas_call``
  per collective — double-buffered VMEM scratch, a DMA semaphore pair
  per buffer slot, and ``pltpu.make_async_remote_copy`` to the ring
  neighbor (the SNIPPETS exemplar pattern). A barrier-semaphore
  handshake with both neighbors opens the kernel so no rank DMAs into
  a peer that has not entered it. The fused kernels consume the final
  combined chunk in-register (update epilogue / per-hop matmul)
  instead of round-tripping HBM.
- **CPU / interpret** (``interpret=True``): no jax release can
  emulate inter-device DMA in the interpreter, so the *hop* is a
  ``lax.ppermute`` while every *combine / fold / matmul / update*
  runs as a ``pl.pallas_call(..., interpret=True)`` kernel. The
  accumulation order is identical to the DMA schedule, which is what
  lets tier-1 and the smoke lane prove ring correctness (and
  bit-identity vs ``coll/xla``) without hardware.

Real-TPU cycle numbers for the DMA path are a carry-over (ROADMAP);
the schedule, buffering and semaphore protocol are validated here in
interpret mode.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ompi_tpu.util import jaxcompat

#: barrier-semaphore collective ids for the monolithic DMA kernels
#: (concurrently-live kernels must not share one)
CID_RS, CID_AG, CID_FUSED, CID_MATMUL, CID_LINEAR = 1, 2, 3, 4, 5


def _pl():
    return jaxcompat.pallas()


def _pltpu():
    return jaxcompat.pallas_tpu()


def _compiler_params(pltpu, collective_id: int):
    """TPU compiler params across jax versions (CompilerParams vs the
    older TPUCompilerParams spelling); the barrier semaphore requires
    a collective_id and the remote DMAs must not be DCE'd."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    try:
        return cls(has_side_effects=True, collective_id=collective_id)
    except TypeError:
        return cls(collective_id=collective_id)


def _perm(n: int, d: int):
    return [(i, (i + d) % n) for i in range(n)]


def _hop(x, axis: str, n: int, d: int):
    """One ring hop toward the +d neighbor (interpret-mode transport)."""
    return lax.ppermute(x, axis, perm=_perm(n, d))


# ---------------------------------------------------------------------------
# kernel bodies — shared verbatim between the interpret path and the
# epilogues of the monolithic DMA kernels


def _combine_body(fn: Callable):
    def kernel(a_ref, b_ref, o_ref):
        o_ref[...] = fn(a_ref[...], b_ref[...])

    return kernel


def _fold_body(n: int, fn: Callable):
    """acc = g[0]; acc = fn(acc, g[i]) for i in 1..n-1 — the exact
    statically-unrolled rank-order fold of coll/xla's 'linear' mode."""

    def kernel(g_ref, o_ref):
        acc = g_ref[0]
        for i in range(1, n):
            acc = fn(acc, g_ref[i])
        o_ref[...] = acc

    return kernel


def _roll_body(x_ref, s_ref, o_ref):
    """Rotate hop-ordered blocks into rank order (the allgather
    reassembly step; shift comes in as a (1,) scalar operand)."""
    o_ref[...] = jnp.roll(x_ref[...], s_ref[0], axis=0)


def _matmul_body(out_dtype):
    def kernel(x_ref, w_ref, o_ref):
        o_ref[...] = jnp.dot(x_ref[...], w_ref[...],
                             preferred_element_type=out_dtype)

    return kernel


def _apply_update(g, p, v, lr: float, mu: float, inv: Optional[float]):
    """The ZeroOptimizer.step shard update, constants cast to the
    shard dtype exactly as the unfused path does. The unfused
    sequence dispatches each elementwise op as its OWN program, so
    every intermediate is correctly rounded; fused into one program
    the backend may still contract mul+add pairs into FMAs (LLVM
    contracts straight through optimization_barrier — the barriers
    only keep the op ORDER fixed), so the fused epilogue is
    equivalent to the unfused step to within one ulp, not bitwise.
    coll/pallas therefore runs this epilogue eagerly (outside the
    kernel) when ``deterministic='linear'`` demands bit-identity."""
    if inv is not None:
        g = lax.optimization_barrier(g * jnp.asarray(inv, g.dtype))
    vn = None
    if v is not None:
        t = lax.optimization_barrier(jnp.asarray(mu, v.dtype) * v)
        vn = lax.optimization_barrier(t + g)
        g = vn
    step = lax.optimization_barrier(jnp.asarray(lr, p.dtype) * g)
    pn = p - step
    return pn, vn


def _combine_update_body(fn, lr, mu, inv, with_mom: bool):
    """Final ring combine fused with the ZeRO shard update: the
    reduced chunk is consumed in-register by the optimizer epilogue."""

    if with_mom:
        def kernel(a_ref, b_ref, p_ref, v_ref, po_ref, vo_ref):
            g = fn(a_ref[...], b_ref[...])
            pn, vn = _apply_update(g, p_ref[...], v_ref[...],
                                   lr, mu, inv)
            po_ref[...] = pn
            vo_ref[...] = vn

        return kernel

    def kernel(a_ref, b_ref, p_ref, po_ref):
        g = fn(a_ref[...], b_ref[...])
        pn, _ = _apply_update(g, p_ref[...], None, lr, mu, inv)
        po_ref[...] = pn

    return kernel


def _fold_slice_body(n: int, k: int, fn):
    """Rank-order fold + own-chunk slice (linear reduce_scatter in one
    kernel — same fold-then-slice order as C.reduce_scatter 'linear')."""

    def kernel(g_ref, r_ref, o_ref):
        full = g_ref[0]
        for i in range(1, n):
            full = fn(full, g_ref[i])
        o_ref[...] = lax.dynamic_slice_in_dim(full, r_ref[0] * k, k,
                                              axis=0)

    return kernel


def _fold_slice_update_body(n: int, k: int, fn, lr, mu, inv,
                            with_mom: bool):
    """Linear fused kernel: rank-order fold, own-chunk slice, and the
    ZeRO update epilogue in one pallas_call."""

    if with_mom:
        def kernel(g_ref, r_ref, p_ref, v_ref, po_ref, vo_ref):
            full = g_ref[0]
            for i in range(1, n):
                full = fn(full, g_ref[i])
            g = lax.dynamic_slice_in_dim(full, r_ref[0] * k, k, axis=0)
            pn, vn = _apply_update(g, p_ref[...], v_ref[...],
                                   lr, mu, inv)
            po_ref[...] = pn
            vo_ref[...] = vn

        return kernel

    def kernel(g_ref, r_ref, p_ref, po_ref):
        full = g_ref[0]
        for i in range(1, n):
            full = fn(full, g_ref[i])
        g = lax.dynamic_slice_in_dim(full, r_ref[0] * k, k, axis=0)
        pn, _ = _apply_update(g, p_ref[...], None, lr, mu, inv)
        po_ref[...] = pn

    return kernel


def _call(body, out_shape, *args):
    """interpret-mode pallas_call over whole-array blocks."""
    pl = _pl()
    return pl.pallas_call(body, out_shape=out_shape, interpret=True)(
        *args)


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# reduce_scatter


def ring_reduce_scatter(x, axis: str, fn: Callable, *,
                        interpret: bool = True, direction: int = 1):
    """Ring reduce_scatter, chunk schedule identical to
    :func:`ompi_tpu.parallel.ring.ring_reduce_scatter` (carry starts
    at chunk r-d, step s folds ``fn(carry, own)`` with own chunk
    r-(s+2)d): dim 0 of x (size n*k) shrinks to k; rank r ends with
    chunk r reduced in ring-visit order. direction=-1 runs the
    mirror-image (counterclockwise) ring."""
    n = jaxcompat.axis_size(axis)
    if n == 1:
        return x
    assert x.shape[0] % n == 0, (
        f"ring_reduce_scatter: dim0 {x.shape[0]} not divisible by {n}")
    k = x.shape[0] // n
    if not interpret:
        return _dma_reduce_scatter(x, axis, n, k, fn, direction)
    chunks = x.reshape((n, k) + x.shape[1:])
    r = lax.axis_index(axis)
    carry = lax.dynamic_index_in_dim(chunks, (r - direction) % n,
                                     keepdims=False)
    for s in range(n - 1):
        carry = _hop(carry, axis, n, direction)
        own = lax.dynamic_index_in_dim(
            chunks, (r - (s + 2) * direction) % n, keepdims=False)
        carry = _call(_combine_body(fn),
                      _sds(carry.shape, carry.dtype), carry, own)
    return carry


def bidir_reduce_scatter(x, axis: str, fn: Callable, *,
                         interpret: bool = True):
    """Bidirectional ring reduce_scatter: the front half of every
    chunk's rows travels the clockwise ring, the back half the
    counterclockwise ring — both ICI link directions carry payload
    simultaneously. Deterministic (fixed schedule) but its fold order
    is its own; callers pick it only when no bit-identity mode was
    requested. Requires >= 2 rows per chunk (fall back to ring below
    that)."""
    n = jaxcompat.axis_size(axis)
    if n == 1:
        return x
    k = x.shape[0] // n
    h = k // 2
    assert h >= 1, "bidir_reduce_scatter: need >= 2 rows per chunk"
    rest = x.shape[1:]
    chunks = x.reshape((n, k) + rest)
    front = chunks[:, :h].reshape((n * h,) + rest)
    back = chunks[:, h:].reshape((n * (k - h),) + rest)
    cf = ring_reduce_scatter(front, axis, fn, interpret=interpret,
                             direction=1)
    cb = ring_reduce_scatter(back, axis, fn, interpret=interpret,
                             direction=-1)
    return jnp.concatenate([cf, cb], axis=0)


def linear_reduce_scatter(x, axis: str, fn: Callable, *,
                          interpret: bool = True):
    """'linear' reduce_scatter: gather every rank's contribution,
    fold in exact rank order, slice the own chunk — one pallas
    kernel, elementwise bit-identical to coll/xla's
    allreduce-linear + slice path."""
    n = jaxcompat.axis_size(axis)
    if n == 1:
        return x
    k = x.shape[0] // n
    g = _gather_stack(x, axis, n, interpret)
    r = lax.axis_index(axis).astype(jnp.int32)[None]
    body = _fold_slice_body(n, k, fn)
    out_shape = _sds((k,) + x.shape[1:], x.dtype)
    if interpret:
        return _call(body, out_shape, g, r)
    pl = _pl()
    return pl.pallas_call(body, out_shape=out_shape)(g, r)


# ---------------------------------------------------------------------------
# allgather


def ring_allgather(x, axis: str, *, interpret: bool = True,
                   direction: int = 1):
    """Ring allgather: local [k, ...] -> [n*k, ...] with rank i's
    block at chunk i (the parallel/ring.py placement). The interpret
    path collects blocks in hop order and rotates them into rank
    order with one pallas roll kernel."""
    n = jaxcompat.axis_size(axis)
    if n == 1:
        return x
    if not interpret:
        return _dma_allgather(x, axis, n, direction)
    r = lax.axis_index(axis)
    blocks = [x]
    blk = x
    for _ in range(n - 1):
        blk = _hop(blk, axis, n, direction)
        blocks.append(blk)
    # hop order: block j is rank (r - j*d)'s. Rotate into rank order:
    # d=+1 -> reverse then roll by r+1; d=-1 -> roll by r.
    if direction == 1:
        arr = jnp.stack(blocks[::-1])
        shift = (r + 1).astype(jnp.int32)[None]
    else:
        arr = jnp.stack(blocks)
        shift = r.astype(jnp.int32)[None]
    out = _call(_roll_body, _sds(arr.shape, arr.dtype), arr, shift)
    return out.reshape((n * x.shape[0],) + x.shape[1:])


def bidir_allgather(x, axis: str, *, interpret: bool = True):
    """Bidirectional ring allgather: front rows clockwise, back rows
    counterclockwise; each direction moves half the payload."""
    n = jaxcompat.axis_size(axis)
    if n == 1:
        return x
    k = x.shape[0]
    h = k // 2
    assert h >= 1, "bidir_allgather: need >= 2 rows per block"
    rest = x.shape[1:]
    gf = ring_allgather(x[:h], axis, interpret=interpret, direction=1)
    gb = ring_allgather(x[h:], axis, interpret=interpret, direction=-1)
    gf = gf.reshape((n, h) + rest)
    gb = gb.reshape((n, k - h) + rest)
    return jnp.concatenate([gf, gb], axis=1).reshape((n * k,) + rest)


def _gather_stack(x, axis: str, n: int, interpret: bool):
    """[n, *x.shape] stack of every rank's block (rank i at index i) —
    the 'linear' transport. Interpret mode uses lax.all_gather (the
    very op coll/xla's linear fold gathers with, so operands are
    bitwise identical); the DMA path rings the flat payload around."""
    if interpret:
        return lax.all_gather(x, axis)
    full = _dma_allgather(x.reshape((1,) + x.shape), axis, n, 1)
    return full.reshape((n,) + x.shape)


# ---------------------------------------------------------------------------
# allreduce


def ring_allreduce(x, axis: str, fn: Callable, *,
                   interpret: bool = True, bidir: bool = False):
    """Bandwidth-optimal allreduce = reduce_scatter + allgather over
    the flattened payload, zero-padded to a multiple of n — the exact
    pad/slice framing of parallel.ring.ring_allreduce, so the 'ring'
    result is bitwise equal to coll/xla's ring mode."""
    n = jaxcompat.axis_size(axis)
    if n == 1:
        return x
    shape = x.shape
    flat = x.reshape(-1)
    m = flat.shape[0]
    pad = (-m) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), x.dtype)])
    if bidir and flat.shape[0] // n >= 2:
        chunk = bidir_reduce_scatter(flat, axis, fn,
                                     interpret=interpret)
        full = bidir_allgather(chunk, axis, interpret=interpret)
    else:
        chunk = ring_reduce_scatter(flat, axis, fn,
                                    interpret=interpret)
        full = ring_allgather(chunk, axis, interpret=interpret)
    return full[:m].reshape(shape)


def linear_allreduce(x, axis: str, fn: Callable, *,
                     interpret: bool = True):
    """'linear' allreduce: gather all contributions, fold in exact
    rank order 0..n-1 inside one pallas kernel — bit-identical to
    coll/xla's ``_allreduce_linear`` (same gathered operands, same
    statically-unrolled fold)."""
    n = jaxcompat.axis_size(axis)
    if n == 1:
        return x
    g = _gather_stack(x, axis, n, interpret)
    body = _fold_body(n, fn)
    if interpret:
        return _call(body, _sds(x.shape, x.dtype), g)
    pl = _pl()
    return pl.pallas_call(body, out_shape=_sds(x.shape, x.dtype))(g)


# ---------------------------------------------------------------------------
# fused: reduce_scatter + ZeRO shard update


def ring_reduce_scatter_update(x, axis: str, fn: Callable, p, v, *,
                               lr: float, mu: float,
                               inv: Optional[float],
                               interpret: bool = True):
    """Ring reduce_scatter whose FINAL combine step is fused with the
    ZeRO stage-1/2 shard update: the reduced gradient chunk is
    consumed in-register by ``p -= lr * (mu*v + g*inv)`` instead of
    round-tripping HBM. x is the flat padded bucket (n*k,), p/v the
    (k,) param/momentum shards (v may be None). Returns (p', v')."""
    n = jaxcompat.axis_size(axis)
    k = x.shape[0] // n
    with_mom = v is not None
    if not interpret:
        return _dma_reduce_scatter_update(x, axis, n, k, fn, p, v,
                                          lr=lr, mu=mu, inv=inv)
    chunks = x.reshape((n, k))
    r = lax.axis_index(axis)
    carry = lax.dynamic_index_in_dim(chunks, (r - 1) % n,
                                     keepdims=False)
    for s in range(n - 2):
        carry = _hop(carry, axis, n, 1)
        own = lax.dynamic_index_in_dim(chunks, (r - 2 - s) % n,
                                       keepdims=False)
        carry = _call(_combine_body(fn),
                      _sds(carry.shape, carry.dtype), carry, own)
    # last hop: combine + update in ONE kernel
    carry = _hop(carry, axis, n, 1)
    own = lax.dynamic_index_in_dim(chunks, (r - n) % n, keepdims=False)
    body = _combine_update_body(fn, lr, mu, inv, with_mom)
    if with_mom:
        return _call(body, (_sds(p.shape, p.dtype),
                            _sds(v.shape, v.dtype)),
                     carry, own, p, v)
    pn, = _call(body, (_sds(p.shape, p.dtype),), carry, own, p)
    return pn, None


def linear_reduce_scatter_update(x, axis: str, fn: Callable, p, v, *,
                                 lr: float, mu: float,
                                 inv: Optional[float],
                                 interpret: bool = True):
    """'linear' fused variant: rank-order fold + own-chunk slice +
    update in one kernel — bit-identical to the unfused
    reduce_scatter('linear') -> average -> momentum -> SGD sequence."""
    n = jaxcompat.axis_size(axis)
    k = x.shape[0] // n
    with_mom = v is not None
    g = _gather_stack(x, axis, n, interpret)
    r = lax.axis_index(axis).astype(jnp.int32)[None]
    body = _fold_slice_update_body(n, k, fn, lr, mu, inv, with_mom)
    if with_mom:
        out_shape = (_sds(p.shape, p.dtype), _sds(v.shape, v.dtype))
        args = (g, r, p, v)
    else:
        out_shape = (_sds(p.shape, p.dtype),)
        args = (g, r, p)
    if interpret:
        outs = _call(body, out_shape, *args)
    else:
        pl = _pl()
        outs = pl.pallas_call(body, out_shape=out_shape)(*args)
    return (outs[0], outs[1]) if with_mom else (outs[0], None)


# ---------------------------------------------------------------------------
# fused: matmul-overlapped allgather (tensor parallelism)


def allgather_matmul(x, w, axis: str, *, interpret: bool = True):
    """allgather(x) @ w with the per-block matmul overlapping the
    next ring hop (the tensor-parallel row-gather fusion): x is the
    local (m, d) block of a row-sharded activation, w the local
    (d, f) weight; returns the full (n*m, f) product. Each arriving
    block is multiplied while the following block is in flight —
    never materializing the gathered (n*m, d) activation."""
    n = jaxcompat.axis_size(axis)
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    if n == 1:
        return _call(_matmul_body(out_dtype),
                     _sds((x.shape[0], w.shape[1]), out_dtype), x, w)
    if not interpret:
        return _dma_allgather_matmul(x, w, axis, n, out_dtype)
    m, f = x.shape[0], w.shape[1]
    r = lax.axis_index(axis)
    body = _matmul_body(out_dtype)
    prods = [_call(body, _sds((m, f), out_dtype), x, w)]
    blk = x
    for _ in range(n - 1):
        blk = _hop(blk, axis, n, 1)
        prods.append(_call(body, _sds((m, f), out_dtype), blk, w))
    arr = jnp.stack(prods[::-1])  # hop order -> rank order (cw ring)
    shift = (r + 1).astype(jnp.int32)[None]
    out = _call(_roll_body, _sds(arr.shape, arr.dtype), arr, shift)
    return out.reshape((n * m, f))


# ---------------------------------------------------------------------------
# monolithic DMA kernels (TPU path — interpret=False)
#
# Shared protocol: a barrier-semaphore handshake with both ring
# neighbors opens every kernel; payload then moves through a
# double-buffered VMEM scratch (2 slots, one DMA send/recv semaphore
# pair each) via make_async_remote_copy to the +d neighbor. Slot s%2
# alternation plus the blocking wait each step keeps reuse safe: a
# slot is rewritten two steps after the neighbor consumed it.


def _neighbor_handshake(pltpu, my, n: int, d: int):
    nxt = (my + d) % n
    prv = (my - d) % n
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, 1, device_id=(nxt,),
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_signal(barrier, 1, device_id=(prv,),
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)
    return nxt


def _dma_reduce_scatter(x, axis: str, n: int, k: int, fn: Callable,
                        d: int):
    pl, pltpu = _pl(), _pltpu()
    chunk_shape = (k,) + x.shape[1:]

    def kernel(x_ref, o_ref, comm_buf, send_sem, recv_sem):
        my = lax.axis_index(axis)
        nxt = _neighbor_handshake(pltpu, my, n, d)
        comm_buf[0] = x_ref[pl.ds(((my - d) % n) * k, k)]
        for s in range(n - 1):
            slot, nslot = s % 2, (s + 1) % 2
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[slot],
                dst_ref=comm_buf.at[nslot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nslot],
                device_id=(nxt,),
                device_id_type=pltpu.DeviceIdType.MESH)
            rdma.start()
            rdma.wait()
            own = x_ref[pl.ds(((my - (s + 2) * d) % n) * k, k)]
            comm_buf[nslot] = fn(comm_buf[nslot], own)
        o_ref[...] = comm_buf[(n - 1) % 2]

    return pl.pallas_call(
        kernel,
        out_shape=_sds(chunk_shape, x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2,) + chunk_shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_compiler_params(pltpu, CID_RS),
    )(x)


def _dma_allgather(x, axis: str, n: int, d: int):
    pl, pltpu = _pl(), _pltpu()
    k = x.shape[0]
    out_shape = (n * k,) + x.shape[1:]

    def kernel(x_ref, o_ref, comm_buf, send_sem, recv_sem):
        my = lax.axis_index(axis)
        nxt = _neighbor_handshake(pltpu, my, n, d)
        o_ref[pl.ds(my * k, k)] = x_ref[...]
        comm_buf[0] = x_ref[...]
        for s in range(n - 1):
            slot, nslot = s % 2, (s + 1) % 2
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[slot],
                dst_ref=comm_buf.at[nslot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nslot],
                device_id=(nxt,),
                device_id_type=pltpu.DeviceIdType.MESH)
            rdma.start()
            rdma.wait()
            src = (my - (s + 1) * d) % n
            o_ref[pl.ds(src * k, k)] = comm_buf[nslot]

    return pl.pallas_call(
        kernel,
        out_shape=_sds(out_shape, x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, k) + x.shape[1:], x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_compiler_params(pltpu, CID_AG),
    )(x)


def _dma_reduce_scatter_update(x, axis: str, n: int, k: int,
                               fn: Callable, p, v, *, lr, mu, inv):
    pl, pltpu = _pl(), _pltpu()
    with_mom = v is not None

    def body(x_ref, p_ref, v_ref, po_ref, vo_ref, comm_buf,
             send_sem, recv_sem):
        my = lax.axis_index(axis)
        nxt = _neighbor_handshake(pltpu, my, n, 1)
        comm_buf[0] = x_ref[pl.ds(((my - 1) % n) * k, k)]
        for s in range(n - 1):
            slot, nslot = s % 2, (s + 1) % 2
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[slot],
                dst_ref=comm_buf.at[nslot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nslot],
                device_id=(nxt,),
                device_id_type=pltpu.DeviceIdType.MESH)
            rdma.start()
            rdma.wait()
            own = x_ref[pl.ds(((my - 2 - s) % n) * k, k)]
            comm_buf[nslot] = fn(comm_buf[nslot], own)
        # fused epilogue: the reduced chunk never leaves VMEM
        g = comm_buf[(n - 1) % 2]
        pn, vn = _apply_update(g, p_ref[...],
                               v_ref[...] if with_mom else None,
                               lr, mu, inv)
        po_ref[...] = pn
        if with_mom:
            vo_ref[...] = vn

    if with_mom:
        def kernel(x_ref, p_ref, v_ref, po_ref, vo_ref, *scratch):
            body(x_ref, p_ref, v_ref, po_ref, vo_ref, *scratch)

        out_shape = (_sds(p.shape, p.dtype), _sds(v.shape, v.dtype))
        args = (x, p, v)
    else:
        def kernel(x_ref, p_ref, po_ref, *scratch):
            body(x_ref, p_ref, None, po_ref, None, *scratch)

        out_shape = (_sds(p.shape, p.dtype),)
        args = (x, p)

    outs = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((2, k), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_compiler_params(pltpu, CID_FUSED),
    )(*args)
    return (outs[0], outs[1]) if with_mom else (outs[0], None)


def _dma_allgather_matmul(x, w, axis: str, n: int, out_dtype):
    pl, pltpu = _pl(), _pltpu()
    m, f = x.shape[0], w.shape[1]

    def kernel(x_ref, w_ref, o_ref, comm_buf, send_sem, recv_sem):
        my = lax.axis_index(axis)
        nxt = _neighbor_handshake(pltpu, my, n, 1)
        comm_buf[0] = x_ref[...]
        for s in range(n - 1):
            slot, nslot = s % 2, (s + 1) % 2
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[slot],
                dst_ref=comm_buf.at[nslot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nslot],
                device_id=(nxt,),
                device_id_type=pltpu.DeviceIdType.MESH)
            rdma.start()
            # overlap: multiply the block that arrived last hop (own
            # block at s=0) while this hop's DMA is in flight
            src = (my - s) % n
            o_ref[pl.ds(src * m, m)] = jnp.dot(
                comm_buf[slot], w_ref[...],
                preferred_element_type=out_dtype)
            rdma.wait()
        last = (my - (n - 1)) % n
        o_ref[pl.ds(last * m, m)] = jnp.dot(
            comm_buf[(n - 1) % 2], w_ref[...],
            preferred_element_type=out_dtype)

    return pl.pallas_call(
        kernel,
        out_shape=_sds((n * m, f), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((2,) + x.shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_compiler_params(pltpu, CID_MATMUL),
    )(x, w)
