"""coll/xla — device-executed collectives on MPI communicators.

THE north-star component (SURVEY.md §2.3/§2.8, BASELINE.md config #1):
replaces the reference's coll/accelerator staging design
(ompi/mca/coll/accelerator/coll_accelerator_allreduce.c:32-115 — D2H,
host collective, H2D) with collectives that *never leave the device*.

How: the communicator's group maps onto the multi-controller device
plane (:mod:`ompi_tpu.runtime.device_plane` — one device per rank,
bootstrapped like the accelerator modex in
opal/mca/accelerator/accelerator.h:668-711). Per communicator we build a
1-D mesh over the member devices ordered by comm rank; each collective
compiles once per (kind, shape, dtype, op, mode) into an XLA program via
``shard_map`` — psum/all_gather/all_to_all lower to ICI transfers on TPU
and gloo on the CPU test backend. Compiled programs are cached on the
communicator exactly as the reference caches per-comm algorithm
schedules (coll_base_comm_select.c:236-330 stacking).

Determinism contract (BASELINE.md "bit-identical vs basic"):
``deterministic='linear'`` folds contributions in exact rank order —
bit-identical to coll/basic's linear reduce (coll_basic_reduce.c
semantics); ``deterministic='ring'`` fixes a ring chunk order that is
stable run-to-run. Default lets XLA schedule (fastest).

Fallback: any buffer/op the device path cannot express (e.g. MINLOC
struct dtypes) falls through to the coll/accelerator staging functions —
the same slot signature, one priority level down.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu import errors, op as op_mod
from ompi_tpu.coll import CollModule, accelerator as staging, framework
from ompi_tpu.core import cvar, output, pvar
from ompi_tpu.monitoring import matrix as _mon
from ompi_tpu.prof import ledger as _prof
from ompi_tpu.telemetry import flight as _flight
from ompi_tpu.trace import recorder as _trace
from ompi_tpu.tune import observe as _tobs

_out = output.stream("coll_xla")

AXIS = "mpi"  # the mesh axis name a communicator compiles to

_default_det = cvar.register(
    "coll_xla_deterministic", "", str,
    help="default determinism mode for device collectives: '' (XLA "
         "schedules, fastest), 'ring' (fixed ring chunk order), "
         "'linear' (exact rank-order fold, bit-identical to coll/basic)",
    choices=["", "ring", "linear"], level=4)

_scatter_cache_var = cvar.register(
    "coll_xla_scatter_meta_cache", 1, int,
    help="Cache the scatter/scatterv metadata host round per (comm, "
         "root) [1, default]. The cached contract requires a stable "
         "root buffer signature — a root-side change raises ON THE "
         "ROOT ONLY; non-root peers reuse the cached shape and enter "
         "the compiled collective, where they HANG uninterruptibly "
         "until the job is killed (they run no host round the root "
         "could poison). Set 0 to restore a per-call metadata round "
         "for shape-varying scatters without like= templates.",
    level=6)

_rooted_var = cvar.register(
    "coll_xla_rooted_threshold_bytes", 1 << 20, int,
    help="Rooted (reduce/gather) device collectives switch to a "
         "root-collecting schedule when the would-be-replicated "
         "result reaches this size: below it, every rank computes "
         "the full allreduce/allgather (one compiled program, free "
         "for small buffers); at/above it, reduce runs "
         "reduce_scatter + chunk-to-root rounds and gather runs "
         "per-source ppermute-to-root rounds, so non-roots "
         "materialize O(bytes), not O(n*bytes) "
         "(coll_base_reduce.c binomial-semantics analog). 0 forces "
         "rooted always; -1 disables it.", level=5)

_a2av_pad_var = cvar.register(
    "coll_xla_alltoallv_pad_factor", 4, int,
    help="alltoallv pads every cell to the GLOBAL max count; skewed "
         "counts (one hot expert) inflate that to n*max cells. When "
         "the padded volume exceeds this factor x the true payload, "
         "the call falls through to the staging path instead of "
         "allocating the blowup (only on the max_count=None path — "
         "an explicit max_count is the capacity-bounded MoE fast "
         "path and is never second-guessed). 0 disables the bound.",
    level=6)

_a2av_cache_var = cvar.register(
    "coll_xla_a2av_meta_cache", 0, int,
    help="Cache the alltoallv pad-metadata host round per comm while "
         "the caller's (scounts, rcounts) signature is unchanged — "
         "an iterative MoE loop then pays ONE host round total. "
         "OPT-IN [default 0]: enabling it is a PROMISE that count "
         "changes touch every rank's local signature (e.g. global "
         "capacity rebalancing); a change confined to a rank pair "
         "while other ranks' local counts stay identical makes "
         "cache-hit ranks skip the metadata collective that "
         "cache-miss ranks enter — a hang. Counts that never change "
         "should pass max_count= instead (host-free, always safe).",
    level=6)

_bucket_var = cvar.register(
    "coll_xla_bucket_bytes", 4 << 20, int,
    help="target flat-bucket size for the fused (bucketed) device "
         "collectives — allreduce_multi_dev / Allreduce_multi AND the "
         "zero/ scatter-gather pair (Reduce_scatter_multi / "
         "Allgather_multi, whose ZeroPlan pads each bucket to a "
         "multiple of the comm size): same-dtype buffers coalesce "
         "into flat buckets that close once they reach this many "
         "bytes, and each bucket runs ONE compiled program (the "
         "NCCL/Horovod/DDP gradient-bucketing analog). The "
         "close-at-threshold rule bounds compiled launches to "
         "ceil(total_bytes/bucket_bytes) + n_dtypes. 0 fuses each "
         "dtype into a single bucket regardless of size.", level=5)

_cache_max_var = cvar.register(
    "coll_xla_cache_max", 0, int,
    help="LRU bound on the per-comm compiled-program and bucket-plan "
         "caches (each of _Ctx.fns / _Ctx.plans independently): under "
         "shape churn these otherwise grow without bound "
         "(coll_xla_fns_size / coll_xla_plans_size pvars are the "
         "monitor). 0 [default] = unbounded. Eviction drops only the "
         "cache entry — handles that already hold the compiled "
         "launcher (persistent/partitioned inits, in-flight requests) "
         "keep working; the next cold call recompiles. Evictions "
         "count in the coll_xla_cache_evictions pvar.", level=6)

_hier_var = cvar.register(
    "coll_xla_hier", "auto", str,
    help="hierarchical ICI x DCN execution for comms spanning slices "
         "(coll/han's split-level algorithms on device, coll_han.h:"
         "62-63): 'auto' groups member devices by slice_index when "
         "comm ranks are slice-contiguous, 'off' always flat, an "
         "integer N forces N slices (testing on the virtual mesh). "
         "Deterministic modes always use the flat 1-D schedule — the "
         "split-level fold order differs from the rank-order "
         "contract.", level=5)

#: ops whose reduction is expressible as a traced elementwise fold
_TRACEABLE_OPS = {
    "MPI_SUM", "MPI_PROD", "MPI_MIN", "MPI_MAX", "MPI_LAND", "MPI_LOR",
    "MPI_LXOR", "MPI_BAND", "MPI_BOR", "MPI_BXOR",
}


def _det(deterministic: Optional[str]) -> Optional[str]:
    if deterministic is not None:
        return deterministic or None
    return _default_det.get() or None


def _observed(launcher, op: str, comm, nbytes, dtype: str,
              deterministic: Optional[str] = None):
    """tune-plane hook on the slot's prepared launcher: when the
    observatory is up, time this dispatch under provider 'xla' — the
    backend that actually served after hier/pallas fallthrough. One
    attribute load + one branch when off."""
    obs = _tobs.OBSERVER
    if obs is None:
        return launcher
    return obs.timed("xla", op, _det(deterministic) or "auto", comm,
                     int(nbytes), dtype, launcher)


class _Ctx:
    """Per-communicator compiled-collective state (the analog of the
    reference's per-comm coll module data)."""

    def __init__(self, comm) -> None:
        from ompi_tpu.runtime import device_plane

        devs = [device_plane.device_for_world_rank(w)
                for w in comm.group.ranks]
        self._setup(devs, device_plane.my_device())

    @classmethod
    def local(cls) -> "_Ctx":
        """A 1-device context over the local default device, no plane
        required — the bench/diagnostic lane: a psum over one device
        is an identity collective, so timing it isolates the pure
        host dispatch cost of the compiled-collective hot path."""
        import jax

        obj = cls.__new__(cls)
        dev = jax.devices()[0]
        obj._setup([dev], dev)
        return obj

    def _setup(self, devs, my) -> None:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self.jax = jax
        self.P = P
        self.mesh = Mesh(np.array(devs), (AXIS,))
        self.my = my
        self.n = len(devs)
        self.in_sharding = NamedSharding(self.mesh, P(AXIS))
        self.fns = {}  # (kind, shape, dtype, ...) -> compiled callable
        self.plans = {}  # fused-allreduce bucket plans per signature
        # hierarchical ICI x DCN mesh (rank-major rows = slices) when
        # the comm spans slices and ranks are slice-contiguous
        self.mesh2d = None
        n_slices = self._detect_slices(devs)
        if n_slices and 1 < n_slices < self.n:
            from ompi_tpu.parallel import hierarchical as H

            grid = np.array(devs).reshape(n_slices,
                                          self.n // n_slices)
            self.mesh2d = Mesh(grid, (H.DCN_AXIS, H.ICI_AXIS))
            self.in_sharding2d = NamedSharding(
                self.mesh2d, P((H.DCN_AXIS, H.ICI_AXIS)))

    @staticmethod
    def _detect_slices(devs) -> int:
        """Number of DCN groups (0 = stay flat). 'auto' requires comm
        rank order to be slice-contiguous with equal-size slices so
        mesh rows ARE physical slices (H.slice_split); anything else
        degrades to flat (correct, just not hierarchy-optimized)."""
        mode = _hier_var.get()
        if mode == "off":
            return 0
        if mode != "auto":
            try:
                n = int(mode)
            except ValueError:
                return 0
            return n if n > 1 and len(devs) % n == 0 else 0
        from ompi_tpu.parallel import hierarchical as H

        return H.slice_split(devs)

    def replica_groups(self):
        """Device-id groups this comm's collectives compile to
        (introspection parity with DeviceCommunicator.replica_groups)."""
        return [[d.id for d in self.mesh.devices.tolist()]]

    # -- plumbing ---------------------------------------------------------
    def to_global(self, x, sharding=None):
        """Local device array -> global array sharded (n, *shape) on
        the comm axis/axes (rank r's contribution at index r).

        Fast path: device_put is skipped when the buffer already
        lives on ``my`` — it runs on every collective call, and for
        resident arrays (the steady-state training case) it only adds
        a dispatch round."""
        jax = self.jax
        try:
            resident = x.device == self.my
        except (AttributeError, ValueError):
            resident = False  # numpy / multi-shard input: stage it
        if resident:
            pvar.record("coll_xla_device_put_skipped")
        elif _prof.PROFILER is None:
            x = jax.device_put(x, self.my)
        else:
            t0 = _prof.now()
            x = jax.device_put(x, self.my)
            x.block_until_ready()
            _prof.PROFILER.xfer("h2d", getattr(x, "nbytes", 0), t0,
                                _prof.now(), site="to_global")
        return jax.make_array_from_single_device_arrays(
            (self.n,) + x.shape, sharding or self.in_sharding,
            [x[None]])

    def my_shard(self, out):
        """This rank's shard of an AXIS-sharded result."""
        return out.addressable_data(0)

    def compiled(self, key, build):
        """Get-or-build a compiled program. Hit/miss/size pvars make
        cache churn (shape-varying workloads recompiling every call)
        visible via MPI_T instead of only via wall time. Bounded LRU
        when cvar coll_xla_cache_max > 0 (insertion order IS recency:
        hits reinsert)."""
        fn = self.fns.get(key)
        rec = _trace.RECORDER
        if fn is None:
            # cold path: always timed — prof_compile_ns is the
            # numerator of the attribution story and two clock reads
            # are noise against an XLA compile
            pvar.record("coll_xla_cache_misses")
            t0 = _trace.now()
            fn = self.fns[key] = build()
            t1 = _trace.now()
            if _prof.PROFILER is not None:
                pvar.record("prof_compile_misses")
                pvar.record("prof_compile_ns", t1 - t0)
            if rec is not None:
                rec.record("compile", "coll_xla", t0, t1,
                           {"cache": "miss", "key": repr(key)[:160]})
            pvar.record_hwm("coll_xla_fns_size", len(self.fns))
            self._evict(self.fns)
        else:
            pvar.record("coll_xla_cache_hits")
            if _prof.PROFILER is not None:
                pvar.record("prof_compile_hits")
            self.fns[key] = self.fns.pop(key)  # LRU touch
            if rec is not None:
                rec.instant("cache_hit", "coll_xla",
                            {"key": repr(key)[:160]})
        return fn

    def plan(self, key, build):
        """Get-or-build a fused-bucket plan (same contract as
        ``compiled`` — steady-state steps must pay zero re-planning)."""
        p = self.plans.get(key)
        rec = _trace.RECORDER
        if p is None:
            pvar.record("coll_xla_plan_cache_misses")
            t0 = _trace.now()
            p = self.plans[key] = build()
            t1 = _trace.now()
            if _prof.PROFILER is not None:
                pvar.record("prof_compile_misses")
                pvar.record("prof_compile_ns", t1 - t0)
            if rec is not None:
                rec.record("plan_build", "coll_xla", t0, t1,
                           {"cache": "miss", "key": repr(key)[:160]})
            pvar.record_hwm("coll_xla_plans_size", len(self.plans))
            self._evict(self.plans)
        else:
            pvar.record("coll_xla_plan_cache_hits")
            if _prof.PROFILER is not None:
                pvar.record("prof_compile_hits")
            self.plans[key] = self.plans.pop(key)  # LRU touch
            if rec is not None:
                rec.instant("plan_cache_hit", "coll_xla",
                            {"key": repr(key)[:160]})
        return p

    @staticmethod
    def _evict(cache) -> None:
        mx = int(_cache_max_var.get())
        while mx > 0 and len(cache) > mx:
            cache.pop(next(iter(cache)))  # oldest-touched first
            pvar.record("coll_xla_cache_evictions")

    def launch(self, fn, *args):
        """Dispatch one compiled collective program. Every device-path
        dispatch funnels through here so the launch counter is exact —
        the fusion regression tests assert on it. Tracing disabled
        costs exactly one extra branch here (no span construction);
        enabled, the span covers DISPATCH time only — PJRT execution
        is asynchronous."""
        pvar.record("coll_xla_launches")
        rec = _trace.RECORDER
        if rec is None:
            return fn(*args)
        t0 = _trace.now()
        out = fn(*args)
        rec.record("launch", "coll_xla", t0, _trace.now())
        return out

    def release(self) -> None:
        """Drop the compiled-program and plan caches (comm destructor
        path: long-lived jobs with shape churn must not grow these
        invisibly after the comm is freed)."""
        self.fns.clear()
        self.plans.clear()

    def smap(self, body, out_varying: bool, mesh=None, spec=None):
        """jit(shard_map(body)) over the comm mesh (or the 2-level
        ICI x DCN mesh when passed). Body sees the local (1, *shape)
        block; out_varying selects the sharded vs replicated spec."""
        from ompi_tpu.util import jaxcompat

        jax, P = self.jax, self.P
        spec = spec if spec is not None else P(AXIS)
        out_spec = spec if out_varying else P()
        return jax.jit(jaxcompat.shard_map(
            body, mesh=mesh if mesh is not None else self.mesh,
            in_specs=spec, out_specs=out_spec, check_vma=False))

    def to_global_hier(self, x):
        return self.to_global(x, self.in_sharding2d)

    def smap_hier(self, body, out_varying: bool):
        """Mesh rows are slices; row-major device order = comm rank."""
        from ompi_tpu.parallel import hierarchical as H

        return self.smap(body, out_varying, mesh=self.mesh2d,
                         spec=self.P((H.DCN_AXIS, H.ICI_AXIS)))


def _ctx(comm) -> _Ctx:
    ctx = getattr(comm, "_coll_xla_ctx", None)
    if ctx is None:
        ctx = comm._coll_xla_ctx = _Ctx(comm)
    return ctx


def _key(x, *extra):
    return (x.shape, str(x.dtype)) + extra


def _op_ok(op) -> bool:
    op = op_mod.BUILTIN.get(op) if not isinstance(op, op_mod.Op) else op
    if op is None:
        return False
    if op.name in _TRACEABLE_OPS:
        return True
    # user-defined ops run on device iff marked jax-traceable
    return bool(getattr(op, "traceable", False))


# ---------------------------------------------------------------------------
# slots — signatures match coll/accelerator's *_dev (the fallback)


def _allreduce_prep(comm, sendbuf, op=op_mod.SUM,
                    deterministic: Optional[str] = None):
    """Plan + compile + bind the allreduce NOW; returns a zero-arg
    launcher whose every call is one cached-executable dispatch. The
    blocking slot calls the launcher immediately; the MPI-4 persistent
    init holds it so Start()+Wait() pays zero re-planning (jax arrays
    are immutable, so the operand bound here never changes)."""
    det = _det(deterministic)
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    hier = det is None and ctx.mesh2d is not None

    def build():
        if hier:  # han split-level over ICI x DCN (deterministic
            # modes stay flat: the split fold order differs from the
            # rank-order bit-identical contract)
            from ompi_tpu.parallel import hierarchical as H

            return ctx.smap_hier(lambda a: H.allreduce(a[0], op=opn),
                                 out_varying=False)
        return ctx.smap(lambda a: C.allreduce(a[0], AXIS, opn, det),
                        out_varying=False)

    fn = ctx.compiled(_key(sendbuf, "allreduce", opn.name, det), build)
    to_g = ctx.to_global_hier if hier else ctx.to_global
    g = to_g(sendbuf)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def allreduce_dev(comm, sendbuf, op=op_mod.SUM,
                  deterministic: Optional[str] = None):
    if not _op_ok(op):
        return staging.allreduce_dev(comm, sendbuf, op)
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("allreduce", comm, getattr(sendbuf, "nbytes", 0),
                dtype=str(getattr(sendbuf, "dtype", "")))
    launcher = _observed(
        _allreduce_prep(comm, sendbuf, op, deterministic),
        "allreduce", comm, getattr(sendbuf, "nbytes", 0),
        str(getattr(sendbuf, "dtype", "")), deterministic)
    fl = _flight.FLIGHT
    if fl is None:
        return launcher()
    tok = fl.enter("allreduce_dev", getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return launcher()
    finally:
        fl.exit(tok)


#: test/diagnostic hook: the last rooted schedule's per-round,
#: per-rank output element count (proves non-roots moved O(bytes))
_last_rooted_plan: Optional[dict] = None


def _rooted(nbytes_result: int) -> bool:
    thr = _rooted_var.get()
    return thr >= 0 and nbytes_result >= thr


def _gather_rooted(ctx, comm, x, root: int):
    """Collect every rank's ``x`` on the root: one single-pair
    ppermute program per source (src -> root), each moving and
    allocating only ONE x-sized block per rank — non-roots never
    materialize the n-fold result (coll_base_gather.c linear
    semantics, on device). Root stacks the blocks locally (its own
    device, outside the collective programs). Returns (n, *x.shape)
    on root, None elsewhere."""
    global _last_rooted_plan
    import jax.numpy as jnp
    from jax import lax

    n, me = ctx.n, comm.rank
    _last_rooted_plan = {"kind": "gather_rooted", "rounds": n - 1,
                        "round_out_elems": int(x.size)}
    parts = [None] * n
    if me == root:
        parts[root] = x
    for src in range(n):
        if src == root:
            continue

        def build(src=src):
            return ctx.smap(
                lambda a: lax.ppermute(a[0], AXIS,
                                       perm=[(src, root)]),
                out_varying=True)

        fn = ctx.compiled(_key(x, "gather_rooted", src, root), build)
        got = ctx.my_shard(ctx.launch(fn, ctx.to_global(x)))
        if me == root:
            parts[src] = got
    if me != root:
        return None
    return jnp.stack(parts)


def _reduce_binomial(ctx, comm, x, opn, root: int):
    """Binomial ppermute reduction tree for commutative non-SUM ops
    above the rooted threshold (coll_base_reduce.c binomial, on
    device): ceil(log2 n) rounds of disjoint (src -> dst) single-pair
    ppermutes + a masked elementwise combine. Every rank sends its
    partial exactly once and every round's output stays x-sized —
    non-roots do O(bytes) traffic and never materialize the n-fold
    allreduce result (reduce_scatter has no native lowering for these
    ops, so the SUM path's psum_scatter program is unavailable)."""
    global _last_rooted_plan
    import jax.numpy as jnp
    from jax import lax

    from ompi_tpu.parallel.collectives import _JNP_FN

    n, me = ctx.n, comm.rank
    combine = _JNP_FN[opn.name]
    rounds = []
    mask = 1
    while mask < n:
        pairs = []
        for v in range(n):  # vrank space: v = (rank - root) mod n
            if v % (2 * mask) == mask:  # sender this round
                pairs.append((((v + root) % n),
                              ((v - mask + root) % n)))
        if pairs:
            rounds.append(tuple(pairs))
        mask <<= 1
    _last_rooted_plan = {"kind": "reduce_binomial",
                         "rounds": len(rounds),
                         "round_out_elems": int(x.size)}
    acc = x
    for rnd, pairs in enumerate(rounds):
        dsts = tuple(sorted({d for _, d in pairs}))

        def build(pairs=pairs, dsts=dsts):
            def body(a):
                cur = a[0]
                got = lax.ppermute(cur, AXIS, perm=list(pairs))
                idx = lax.axis_index(AXIS)
                recv = jnp.zeros((), bool)
                for d in dsts:
                    recv = recv | (idx == d)
                return jnp.where(recv, combine(cur, got), cur)

            return ctx.smap(body, out_varying=True)

        fn = ctx.compiled(_key(x, "reduce_binom", opn.name, rnd,
                               root, n), build)
        acc = ctx.my_shard(ctx.launch(fn, ctx.to_global(acc)))
    return acc if me == root else None


def reduce_dev(comm, sendbuf, op=op_mod.SUM, root: int = 0,
               deterministic: Optional[str] = None):
    if not _op_ok(op):
        return staging.reduce_dev(comm, sendbuf, op, root)
    det = _det(deterministic)
    n = comm.size
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    nbytes = int(sendbuf.size) * np.dtype(sendbuf.dtype).itemsize
    # small buffers / deterministic modes keep the one-program full
    # reduction (the rank-order contract needs the flat schedule
    # anyway, and it is free for small buffers).
    if n == 1 or det is not None or not _rooted(nbytes * n):
        out = allreduce_dev(comm, sendbuf, op, deterministic)
        return out if comm.rank == root else None
    if opn.name != "MPI_SUM":
        # non-SUM commutative: the binomial ppermute tree (O(bytes)
        # non-roots; the SUM psum_scatter route below has no lowering
        # for these ops)
        from ompi_tpu.parallel.collectives import _JNP_FN

        if opn.name not in _JNP_FN:
            out = allreduce_dev(comm, sendbuf, op, deterministic)
            return out if comm.rank == root else None
        pvar.record("coll_xla_device")
        tm = _mon.TRAFFIC
        if tm is not None:
            tm.coll("reduce", comm, nbytes, root=root,
                    dtype=str(getattr(sendbuf, "dtype", "")))
        return _reduce_binomial(_ctx(comm), comm, sendbuf, opn, root)
    # rooted schedule: reduce_scatter leaves each rank ONE 1/n chunk
    # (O(bytes/n) output), then the chunks ride single-pair ppermutes
    # to the root — non-roots do O(bytes) HBM/ICI total, never the
    # n-fold allreduce result (coll_base_reduce.c binomial role)
    pvar.record("coll_xla_device")
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("reduce", comm, nbytes, root=root,
                dtype=str(getattr(sendbuf, "dtype", "")))
    import jax.numpy as jnp

    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    flat = sendbuf.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))

    def build():
        return ctx.smap(
            lambda a: C.reduce_scatter(a[0], AXIS, opn,
                                       scatter_dim=0, tiled=True),
            out_varying=True)

    fn = ctx.compiled(_key(flat, "reduce_rooted_rs", opn.name), build)
    chunk = ctx.my_shard(ctx.launch(fn, ctx.to_global(flat)))
    stacked = _gather_rooted(ctx, comm, chunk, root)
    if comm.rank != root:
        return None
    return stacked.reshape(-1)[:sendbuf.size].reshape(sendbuf.shape)


def _bcast_prep(comm, buf, root: int = 0):
    ctx = _ctx(comm)
    hier = ctx.mesh2d is not None

    def build():
        if hier:
            from ompi_tpu.parallel import hierarchical as H

            ici = ctx.mesh2d.devices.shape[1]
            return ctx.smap_hier(
                lambda a: H.bcast(a[0], root_dcn=root // ici,
                                  root_ici=root % ici),
                out_varying=False)
        return ctx.smap(_bcast_body(root), out_varying=False)

    fn = ctx.compiled(_key(buf, "bcast", root), build)
    to_g = ctx.to_global_hier if hier else ctx.to_global
    g = to_g(buf)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def bcast_dev(comm, buf, root: int = 0):
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return buf
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("bcast", comm, getattr(buf, "nbytes", 0), root=root,
                dtype=str(getattr(buf, "dtype", "")))
    launcher = _observed(_bcast_prep(comm, buf, root), "bcast", comm,
                         getattr(buf, "nbytes", 0),
                         str(getattr(buf, "dtype", "")))
    fl = _flight.FLIGHT
    if fl is None:
        return launcher()
    tok = fl.enter("bcast_dev", getattr(comm, "cid", -1),
                   getattr(buf, "nbytes", 0))
    try:
        return launcher()
    finally:
        fl.exit(tok)


def _bcast_body(root: int):
    from ompi_tpu.parallel import collectives as C

    return lambda a: C.bcast(a[0], AXIS, root)


def _allgather_prep(comm, sendbuf):
    from jax import lax

    ctx = _ctx(comm)

    def build():
        return ctx.smap(lambda a: lax.all_gather(a[0], AXIS),
                        out_varying=False)

    fn = ctx.compiled(_key(sendbuf, "allgather"), build)
    g = ctx.to_global(sendbuf)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def allgather_dev(comm, sendbuf):
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf[None] if hasattr(sendbuf, "shape") else sendbuf
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("allgather", comm, getattr(sendbuf, "nbytes", 0),
                dtype=str(getattr(sendbuf, "dtype", "")))
    launcher = _observed(_allgather_prep(comm, sendbuf), "allgather",
                         comm, getattr(sendbuf, "nbytes", 0),
                         str(getattr(sendbuf, "dtype", "")))
    fl = _flight.FLIGHT
    if fl is None:
        return launcher()
    tok = fl.enter("allgather_dev", getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return launcher()
    finally:
        fl.exit(tok)


def gather_dev(comm, sendbuf, root: int = 0):
    n = comm.size
    nbytes = int(sendbuf.size) * np.dtype(sendbuf.dtype).itemsize
    if n == 1 or not _rooted(nbytes * n):
        out = allgather_dev(comm, sendbuf)
        return out if comm.rank == root else None
    # rooted: per-source ppermute-to-root rounds; non-roots allocate
    # one sendbuf-sized block per round, never the (n, ...) result
    pvar.record("coll_xla_device")
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("gather", comm, nbytes, root=root,
                dtype=str(getattr(sendbuf, "dtype", "")))
    return _gather_rooted(_ctx(comm), comm, sendbuf, root)


def _alltoall_prep(comm, sendbuf):
    if sendbuf.shape[0] % comm.size:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"alltoall: dim0 {sendbuf.shape[0]} not divisible by "
            f"comm size {comm.size}")
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    hier = ctx.mesh2d is not None

    def build():
        if hier:  # two-phase: every byte crosses DCN exactly once;
            # output is source-rank-major, the MPI alltoall order
            from ompi_tpu.parallel import hierarchical as H

            return ctx.smap_hier(lambda a: H.alltoall(a[0]),
                                 out_varying=True)
        return ctx.smap(lambda a: C.alltoall(a[0], AXIS, 0, 0),
                        out_varying=True)

    fn = ctx.compiled(_key(sendbuf, "alltoall"), build)
    to_g = ctx.to_global_hier if hier else ctx.to_global
    g = to_g(sendbuf)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def alltoall_dev(comm, sendbuf):
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("alltoall", comm, getattr(sendbuf, "nbytes", 0),
                dtype=str(getattr(sendbuf, "dtype", "")))
    launcher = _observed(_alltoall_prep(comm, sendbuf), "alltoall",
                         comm, getattr(sendbuf, "nbytes", 0),
                         str(getattr(sendbuf, "dtype", "")))
    fl = _flight.FLIGHT
    if fl is None:
        return launcher()
    tok = fl.enter("alltoall_dev", getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return launcher()
    finally:
        fl.exit(tok)


def _reduce_scatter_block_prep(comm, sendbuf, op=op_mod.SUM,
                               deterministic: Optional[str] = None):
    det = _det(deterministic)
    if sendbuf.shape[0] % comm.size:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"reduce_scatter_block: dim0 {sendbuf.shape[0]} not "
            f"divisible by comm size {comm.size}")
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]

    def build():
        return ctx.smap(
            lambda a: C.reduce_scatter(a[0], AXIS, opn, scatter_dim=0,
                                       tiled=True, deterministic=det),
            out_varying=True)

    fn = ctx.compiled(_key(sendbuf, "rsb", opn.name, det), build)
    g = ctx.to_global(sendbuf)
    return lambda: ctx.my_shard(ctx.launch(fn, g))


def reduce_scatter_block_dev(comm, sendbuf, op=op_mod.SUM,
                             deterministic: Optional[str] = None):
    if not _op_ok(op):
        return staging.reduce_scatter_block_dev(comm, sendbuf, op)
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("reduce_scatter_block", comm,
                getattr(sendbuf, "nbytes", 0),
                dtype=str(getattr(sendbuf, "dtype", "")))
    launcher = _observed(
        _reduce_scatter_block_prep(comm, sendbuf, op, deterministic),
        "reduce_scatter_block", comm, getattr(sendbuf, "nbytes", 0),
        str(getattr(sendbuf, "dtype", "")), deterministic)
    fl = _flight.FLIGHT
    if fl is None:
        return launcher()
    tok = fl.enter("reduce_scatter_block_dev", getattr(comm, "cid", -1),
                   getattr(sendbuf, "nbytes", 0))
    try:
        return launcher()
    finally:
        fl.exit(tok)


def _scatter_meta(comm, key, root: int, root_meta):
    """Per-(comm, kind, root) scatter metadata: the root passes its
    buffer signature; non-roots pass None and get the cached/broadcast
    value.

    The host metadata round runs ONCE per key and is cached like the
    compiled program (r2 VERDICT weak #4: it used to run per call).
    The cache is only valid while the root's signature is stable; a
    root that changes it raises instead of silently diverging from
    peers that would reuse stale metadata — pass ``like=`` (your
    recvbuf) on every rank for the zero-round dynamic path, or delete
    comm._coll_xla_scatter_meta on every rank."""
    if not _scatter_cache_var.get():  # per-call round (pre-cache
        # behavior): shape-varying scatters without like= templates
        if root_meta is not None:
            comm.coll.bcast_obj(comm, root_meta, root)
            return root_meta
        return comm.coll.bcast_obj(comm, None, root)
    cache = getattr(comm, "_coll_xla_scatter_meta", None)
    if cache is None:
        cache = comm._coll_xla_scatter_meta = {}
    if root_meta is not None:  # root side
        cached = cache.get(key)
        if cached is None:
            comm.coll.bcast_obj(comm, root_meta, root)
            cache[key] = root_meta
        elif cached != root_meta:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"{key}: buffer signature changed {cached} -> "
                f"{root_meta} after the metadata round was cached. "
                "Non-root peers reuse the cached shape and are "
                "entering (or already inside) the compiled "
                "collective, where they hang uninterruptibly — KILL "
                "THIS JOB externally, then either pass like= on "
                "every rank (zero-round dynamic path) or set "
                "--mca coll_xla_scatter_meta_cache 0 (per-call "
                "metadata round)")
        return root_meta
    cached = cache.get(key)
    if cached is None:
        cached = cache[key] = comm.coll.bcast_obj(comm, None, root)
    return cached


def scatter_dev(comm, sendbuf, root: int = 0, like=None):
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    # non-roots pass no data but SPMD needs same-shape operands on
    # every device. Shapes come from (in order): the caller's own
    # recvbuf template (``like`` — MPI semantics guarantee non-roots
    # know their chunk; zero host rounds), else one cached host
    # metadata round per (comm, root).
    import jax.numpy as jnp

    ctx0 = _ctx(comm)
    # ``like`` is a collective argument (like counts): either every
    # rank passes its recvbuf template (zero-round, shape-dynamic
    # path) or none does (cached metadata round). Mixing hangs, as
    # inconsistent collective arguments do in MPI.
    if comm.rank == root:
        if like is None:
            _scatter_meta(comm, ("scatter", root), root,
                          (tuple(sendbuf.shape), str(sendbuf.dtype)))
        x = sendbuf
    elif like is not None:
        x = ctx0.jax.device_put(
            jnp.zeros((comm.size * like.shape[0],) + tuple(
                like.shape[1:]), like.dtype), ctx0.my)
    else:
        shape, dtype = _scatter_meta(comm, ("scatter", root), root,
                                     None)
        x = ctx0.jax.device_put(jnp.zeros(shape, dtype), ctx0.my)
    if x.shape[0] % comm.size:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"scatter: dim0 {x.shape[0]} not divisible by comm size "
            f"{comm.size}")
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)

    def build():
        return ctx.smap(lambda a: C.scatter(a[0], AXIS, root, 0),
                        out_varying=True)

    fn = ctx.compiled(_key(x, "scatter", root), build)
    return ctx.my_shard(ctx.launch(fn, ctx.to_global(x)))


def barrier_dev(comm):
    """Device-plane barrier: a 1-element psum every member must enter
    before any member's program completes. Reference: coll/accelerator
    interposes every slot incl. barrier (ompi/mca/coll/accelerator/);
    here the rendezvous itself rides ICI instead of the host."""
    tm = _mon.TRAFFIC
    if tm is not None and comm.size > 1:
        tm.coll("barrier", comm, 0)
    fl = _flight.FLIGHT
    if fl is None:
        ibarrier_dev(comm).wait()
        return
    tok = fl.enter("barrier_dev", getattr(comm, "cid", -1), 0)
    try:
        ibarrier_dev(comm).wait()
    finally:
        fl.exit(tok)


def scatterv_dev(comm, sendbuf, counts, root: int = 0, like=None):
    """Ragged scatter on device: root pads each segment to max(counts),
    a compiled bcast-from-root + static slice hands rank r its
    counts[r] rows. counts is the full vector (every rank passes it —
    MPI_Scatterv semantics), so shapes agree with zero host rounds;
    non-roots derive trailing dims/dtype from ``like`` (their recvbuf)
    or from the root metadata cache (see scatter_dev)."""
    pvar.record("coll_xla_device")
    counts = tuple(int(c) for c in counts)
    if comm.size == 1:
        return sendbuf
    if len(counts) != comm.size:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"scatterv: {len(counts)} counts for {comm.size} ranks")
    tm = _mon.TRAFFIC
    if tm is not None and comm.rank == root:
        rowb = (sendbuf.nbytes / sendbuf.shape[0]
                if sendbuf.shape[0] else 0.0)
        tm.coll("scatterv", comm, getattr(sendbuf, "nbytes", 0),
                root=root, counts=counts, row_bytes=rowb,
                dtype=str(getattr(sendbuf, "dtype", "")))
    import jax.numpy as jnp
    from jax import lax

    ctx = _ctx(comm)
    m = max(counts)
    if comm.rank == root:
        rest, dtype = sendbuf.shape[1:], sendbuf.dtype
        if like is None:  # prime the shared metadata cache for
            # non-roots without a recvbuf template (same collective-
            # uniformity contract as scatter_dev)
            _scatter_meta(comm, ("scatterv", root), root,
                          (tuple(rest), str(dtype)))
        # pad segments to (n, m, *rest), segment r at row r
        rows = []
        off = 0
        for c in counts:
            seg = sendbuf[off:off + c]
            rows.append(jnp.pad(seg, ((0, m - c),)
                                + ((0, 0),) * len(rest)))
            off += c
        x = jnp.stack(rows)
    else:
        rest, dtype = _nonroot_meta(comm, root, like, counts)
        x = ctx.jax.device_put(
            jnp.zeros((comm.size, m) + rest, dtype), ctx.my)

    def build():
        def body(a):  # a: (1, n, m, *rest) -> my (m, *rest) segment
            from ompi_tpu.parallel import collectives as C

            full = C.bcast(a[0], AXIS, root)  # (n, m, *rest)
            me = lax.axis_index(AXIS)
            return lax.dynamic_index_in_dim(full, me, 0,
                                            keepdims=False)
        return ctx.smap(body, out_varying=True)

    fn = ctx.compiled(_key(x, "scatterv", counts, root), build)
    # ragged trim is per-rank-local (outside the collective program:
    # sharded outputs must be uniform across devices)
    return ctx.my_shard(
        ctx.launch(fn, ctx.to_global(x)))[:counts[comm.rank]]


def _nonroot_meta(comm, root, like, counts):
    """(trailing dims, dtype) for a non-root scatterv participant:
    from its own recvbuf template when given (zero host rounds — the
    MPI-idiomatic path), else from the metadata cache primed by one
    host bcast (see _scatter_meta)."""
    if like is not None:
        return tuple(like.shape[1:]), like.dtype
    rest, dtype = _scatter_meta(comm, ("scatterv", root), root, None)
    return tuple(rest), np.dtype(dtype)


def allgatherv_dev(comm, sendbuf, counts):
    """Ragged allgather on device: pad every block to max(counts),
    one compiled all_gather, then static slices reassemble the packed
    (sum(counts), ...) result — no host staging (the reference's
    accelerator path stages v-variants D2H; VERDICT r2 missing #4).
    counts is the full vector, identical on every rank, so the padded
    shapes agree with zero extra host rounds."""
    pvar.record("coll_xla_device")
    counts = tuple(int(c) for c in counts)
    if comm.size == 1:
        return sendbuf
    if len(counts) != comm.size:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"allgatherv: {len(counts)} counts for {comm.size} ranks")
    import jax.numpy as jnp
    from jax import lax

    ctx = _ctx(comm)
    m = max(counts)
    rest = sendbuf.shape[1:]
    x = jnp.pad(sendbuf, ((0, m - counts[comm.rank]),)
                + ((0, 0),) * len(rest))

    def build():
        def body(a):  # a: (1, m, *rest) -> packed (sum(counts), *rest)
            g = lax.all_gather(a[0], AXIS)  # (n, m, *rest)
            parts = [lax.slice_in_dim(g, r, r + 1)[0][:counts[r]]
                     for r in range(len(counts))]
            return jnp.concatenate(parts, axis=0)
        return ctx.smap(body, out_varying=False)

    fn = ctx.compiled(_key(x, "allgatherv", counts), build)
    return ctx.my_shard(ctx.launch(fn, ctx.to_global(x)))


def gatherv_dev(comm, sendbuf, counts, root: int = 0):
    out = allgatherv_dev(comm, sendbuf, counts)
    return out if comm.rank == root else None


def alltoallv_dev(comm, sendbuf, scounts, rcounts, max_count=None, *,
                  _expert_tokens: bool = True):
    """Ragged all-to-all on device: segments pad to a uniform cell
    size M, one compiled all_to_all, static slices repack. M must be
    the GLOBAL max cell (a rank's own rows/columns don't bound cells
    between other peers), so it costs one tiny host max-allreduce per
    call — unless the caller passes ``max_count`` (e.g. a fixed MoE
    expert capacity, the common TPU dispatch pattern), which makes the
    path entirely host-free and is the recommended usage.

    ``_expert_tokens=False`` keeps the call out of the per-expert
    routed-token stats: scounts here index RANKS, and only the EP
    dispatch pattern (destination shard == expert) may feed the
    expert-imbalance view — the serve plane's DCN overflow legs
    exchange by rank and must not skew it."""
    scounts = tuple(int(c) for c in scounts)
    rcounts = tuple(int(c) for c in rcounts)
    if comm.size == 1:
        return sendbuf
    import jax.numpy as jnp
    from jax import lax

    ctx = _ctx(comm)
    if max_count is None:
        # the one host metadata round carries (max cell, payload) —
        # the global max sizes the padding, the global total bounds
        # the blowup UNIFORMLY across ranks (a per-rank decision
        # would diverge into different collectives). An unchanged
        # (scounts, rcounts) signature reuses the cached outcome, so
        # an iterative MoE loop pays the round once (r4 weak #2).
        sig = (scounts, rcounts)
        cached = (getattr(comm, "_coll_xla_a2av_meta", None)
                  if _a2av_cache_var.get() else None)
        if cached is not None and cached[0] == sig:
            m, fell_back = cached[1]
            pvar.record("coll_xla_a2av_meta_cached")
        else:
            pairs = comm.coll.allgather_obj(
                comm, (max(max(scounts), max(rcounts)), sum(scounts)))
            m = max(p[0] for p in pairs)
            factor = _a2av_pad_var.get()
            padded_cells = comm.size * comm.size * m
            true_cells = max(sum(p[1] for p in pairs), 1)
            fell_back = (factor > 0
                         and padded_cells > factor * true_cells)
            if _a2av_cache_var.get():
                comm._coll_xla_a2av_meta = (sig, (m, fell_back))
        if fell_back:
            # pathological skew (one hot expert): the staged path
            # moves the ragged counts without padding
            pvar.record("coll_xla_alltoallv_fallback")
            return staging.alltoallv_dev(comm, sendbuf, scounts,
                                         rcounts)
    else:
        m = int(max_count)
        if max(max(scounts), max(rcounts)) > m:
            raise errors.MPIError(
                errors.ERR_COUNT,
                f"alltoallv: max_count {m} below local max "
                f"{max(max(scounts), max(rcounts))}")
    pvar.record("coll_xla_device")  # after the fallback decision, so
    # the device-path counter never counts host-staged calls
    tm = _mon.TRAFFIC
    if tm is not None:
        # actual splits, not the padded cells: bytes to peer r =
        # scounts[r] rows. This is also the EP dispatch site — each
        # destination shard is an expert, so scounts IS the per-expert
        # routed-token vector (ROADMAP item 5's imbalance feed).
        rowb = (sendbuf.nbytes / sendbuf.shape[0]
                if sendbuf.shape[0] else 0.0)
        tm.coll("alltoallv", comm, getattr(sendbuf, "nbytes", 0),
                dtype=str(getattr(sendbuf, "dtype", "")),
                counts=scounts, row_bytes=rowb)
        if _expert_tokens:
            tm.expert_tokens(scounts)
    rest = sendbuf.shape[1:]
    rows = []
    off = 0
    for c in scounts:
        rows.append(jnp.pad(sendbuf[off:off + c],
                            ((0, m - c),) + ((0, 0),) * len(rest)))
        off += c
    x = jnp.stack(rows)  # (n, m, *rest)

    def build():
        def body(a):  # (1, n, m, *rest) -> received cells (n, m, *rest)
            return lax.all_to_all(a, AXIS, split_axis=1, concat_axis=0,
                                  tiled=False)[:, 0]
        return ctx.smap(body, out_varying=True)

    fn = ctx.compiled(_key(x, "alltoallv", m), build)
    cells = ctx.my_shard(ctx.launch(fn, ctx.to_global(x)))  # (n, m, *rest)
    # ragged repack is per-rank-local (outside the collective program:
    # sharded outputs must be uniform across devices)
    return jnp.concatenate(
        [cells[r, :rcounts[r]] for r in range(comm.size)], axis=0)


def reduce_scatter_dev(comm, sendbuf, counts, op=op_mod.SUM,
                       deterministic: Optional[str] = None):
    """Ragged MPI_Reduce_scatter on device: full on-device reduction
    (shares allreduce's compiled program and cache entry), then each
    rank slices its counts[rank] rows locally — ragged outputs never
    enter the uniform-shape collective program."""
    if not _op_ok(op):
        return staging.reduce_scatter_dev(comm, sendbuf, counts, op)
    counts = [int(c) for c in counts]
    # erroneous calls raise MPIError so the comm's errhandler sees
    # them (the MPI-4 convention part/host.py documents — a bare
    # ValueError would bypass _with_errhandler dispatch)
    if len(counts) != comm.size:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"reduce_scatter: {len(counts)} counts for "
            f"{comm.size} ranks")
    if sum(counts) != sendbuf.shape[0]:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"reduce_scatter: counts sum to {sum(counts)} but sendbuf "
            f"dim0 is {sendbuf.shape[0]} (jax slicing would clamp "
            "silently)")
    full = allreduce_dev(comm, sendbuf, op, deterministic)
    off = sum(counts[:comm.rank])
    return full[off:off + counts[comm.rank]]


def scan_dev(comm, sendbuf, op=op_mod.SUM,
             deterministic: Optional[str] = None):
    """Inclusive prefix over comm ranks (lax.associative_scan under
    shard_map — log-depth on device)."""
    if not _op_ok(op):
        return staging.scan_dev(comm, sendbuf, op)
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("scan", comm, getattr(sendbuf, "nbytes", 0),
                dtype=str(getattr(sendbuf, "dtype", "")))
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]

    def build():
        return ctx.smap(lambda a: C.scan(a[0], AXIS, opn),
                        out_varying=True)

    fn = ctx.compiled(_key(sendbuf, "scan", opn.name), build)
    return ctx.my_shard(ctx.launch(fn, ctx.to_global(sendbuf)))


def exscan_dev(comm, sendbuf, op=op_mod.SUM,
               deterministic: Optional[str] = None):
    """Exclusive prefix; rank 0 gets zeros (MPI leaves it undefined)."""
    if not _op_ok(op):
        return staging.exscan_dev(comm, sendbuf, op)
    pvar.record("coll_xla_device")
    if comm.size == 1:
        import jax.numpy as jnp

        return jnp.zeros_like(sendbuf)
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]

    def build():
        return ctx.smap(lambda a: C.exscan(a[0], AXIS, opn),
                        out_varying=True)

    fn = ctx.compiled(_key(sendbuf, "exscan", opn.name), build)
    return ctx.my_shard(ctx.launch(fn, ctx.to_global(sendbuf)))


# ---------------------------------------------------------------------------
# fused (bucketed) allreduce — the gradient-bucketing engine


class _FusePlan:
    """dtype-segregated bucket layout for one leaf signature (the
    NCCL/Horovod/DDP gradient-bucket plan). ``buckets`` is a tuple of
    tuples of leaf indices; a bucket closes once its byte total
    reaches ``bucket_bytes`` (overflow allowed), which bounds compiled
    launches at ceil(total_bytes/bucket_bytes) + n_dtypes — the
    invariant the launch-count regression test asserts."""

    __slots__ = ("buckets", "nbytes")

    def __init__(self, metas, bucket_bytes: int) -> None:
        groups: dict = {}
        order = []
        for i, (_shape, dtype, nb) in enumerate(metas):
            if dtype not in groups:
                groups[dtype] = []
                order.append(dtype)
            groups[dtype].append((i, nb))
        buckets = []
        for dt in order:
            cur, cur_bytes = [], 0
            for i, nb in groups[dt]:
                cur.append(i)
                cur_bytes += nb
                if bucket_bytes > 0 and cur_bytes >= bucket_bytes:
                    buckets.append(tuple(cur))
                    cur, cur_bytes = [], 0
            if cur:
                buckets.append(tuple(cur))
        self.buckets = tuple(buckets)
        self.nbytes = sum(m[2] for m in metas)


def _fuse_metas(leaves):
    return tuple((tuple(l.shape), str(l.dtype),
                  int(l.size) * np.dtype(l.dtype).itemsize)
                 for l in leaves)


def _fuse_plan(ctx, metas, treedef, opn, det):
    bb = int(_bucket_var.get())
    return ctx.plan((metas, treedef, opn.name, det, bb),
                    lambda: _FusePlan(metas, bb))


def _bucket_fn(ctx, metas, idxs, opn, det: Optional[str], hier: bool):
    """ONE compiled concat+allreduce+split program for a bucket. The
    cache key depends only on (member signature, op, mode) — the
    all-at-Start fused path and the partitioned path resolve to the
    SAME executable, which is what makes Pallreduce_init bit-identical
    to Allreduce_multi by construction."""
    from ompi_tpu.parallel import collectives as C

    sig = tuple((metas[i][0], metas[i][1]) for i in idxs)

    def build():
        def body(args):
            import jax.numpy as jnp

            flat = (jnp.concatenate(
                [a[0].reshape(-1) for a in args])
                if len(args) > 1 else args[0][0].reshape(-1))
            if hier:
                from ompi_tpu.parallel import hierarchical as H

                red = H.allreduce(flat, op=opn)
            else:
                red = C.allreduce(flat, AXIS, opn, det)
            outs, off = [], 0
            for a in args:  # static split back to member shapes
                n = a[0].size
                outs.append(red[off:off + n].reshape(a.shape[1:]))
                off += n
            return tuple(outs)

        if hier:
            return ctx.smap_hier(body, out_varying=False)
        return ctx.smap(body, out_varying=False)

    return ctx.compiled(("fused_allreduce", sig, opn.name, det, hier),
                        build)


def _fuse_prep(ctx, comm, leaves, treedef, opn,
               det: Optional[str]):
    """Build (or reuse) the bucket plan and each bucket's ONE compiled
    concat+allreduce+split program, bind the operands, and return a
    zero-arg launcher producing the unflattened pytree.

    Bit-identity: under ``deterministic='linear'`` the fold is an
    elementwise rank-order reduction, and concatenation never changes
    an element's per-rank fold order — fused results are bitwise
    identical to the per-buffer loop (tested)."""
    import jax

    metas = _fuse_metas(leaves)
    plan = _fuse_plan(ctx, metas, treedef, opn, det)
    hier = det is None and ctx.mesh2d is not None
    to_g = ctx.to_global_hier if hier else ctx.to_global

    launches = []
    for idxs in plan.buckets:
        fn = _bucket_fn(ctx, metas, idxs, opn, det, hier)
        gs = tuple(to_g(leaves[i]) for i in idxs)
        launches.append((fn, gs, idxs))

    def launch():
        outs = [None] * len(leaves)
        for fn, gs, idxs in launches:
            res = ctx.launch(fn, gs)
            for j, i in enumerate(idxs):
                outs[i] = ctx.my_shard(res[j])
        pvar.record("coll_xla_fused_bytes", plan.nbytes)
        return jax.tree.unflatten(treedef, outs)

    return launch


def _allreduce_multi_prep(comm, bufs, op=op_mod.SUM,
                          deterministic: Optional[str] = None):
    import jax

    leaves, treedef = jax.tree.flatten(bufs)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    return _fuse_prep(_ctx(comm), comm, leaves, treedef, opn,
                      _det(deterministic))


def allreduce_multi_dev(comm, bufs, op=op_mod.SUM,
                        deterministic: Optional[str] = None):
    """Fused allreduce over a list/pytree of device buffers: flatten
    into dtype-segregated flat buckets (target size cvar
    ``coll_xla_bucket_bytes``), ONE compiled psum per bucket, split
    back — amortizing the per-buffer Python dispatch round that
    dominates many-small-gradient steps. Returns a new pytree with
    the input structure."""
    if not _op_ok(op):
        return staging.allreduce_multi_dev(comm, bufs, op,
                                           deterministic=deterministic)
    pvar.record("coll_xla_device")
    import jax

    if comm.size == 1 or not jax.tree.leaves(bufs):
        return bufs
    tm = _mon.TRAFFIC
    if tm is not None:
        leaves = jax.tree.leaves(bufs)
        tm.coll("allreduce_multi", comm,
                sum(getattr(b, "nbytes", 0) for b in leaves),
                dtype=str(getattr(leaves[0], "dtype", "")))
    leaves = jax.tree.leaves(bufs)
    nb = sum(getattr(b, "nbytes", 0) for b in leaves)
    launcher = _observed(
        _allreduce_multi_prep(comm, bufs, op, deterministic),
        "allreduce_multi", comm, nb,
        str(getattr(leaves[0], "dtype", "")), deterministic)
    fl = _flight.FLIGHT
    if fl is None:
        return launcher()
    tok = fl.enter("allreduce_multi_dev", getattr(comm, "cid", -1),
                   nb)
    try:
        return launcher()
    finally:
        fl.exit(tok)


# ---------------------------------------------------------------------------
# nonblocking device collectives — requests backed by PJRT readiness


class DeviceRequest:
    """MPI request over an asynchronously-dispatched device collective.

    PJRT dispatch is already asynchronous: the jitted program returns a
    jax.Array future immediately and the TPU runs in the background.
    This request EXPOSES that (r2 VERDICT missing #3) instead of hiding
    it — the analog of ob1's accelerator outstanding-copy event arrays
    (ompi/mca/pml/ob1/pml_ob1_accelerator.c:57-89), with the jax.Array
    itself as the completion event. ``.array`` is the result (None on
    non-root reduce/gather sides).

    Duck-types ompi_tpu.pml.request.Request (test/wait/cancel/free and
    the wait_all/test_all helpers hold on the shared contract:
    ``completed`` flag + non-blocking ``test()``).
    """

    def __init__(self, array) -> None:
        from ompi_tpu.pml import request as rq

        self.id = next(rq._req_ids)
        self.status = rq.Status()
        self.persistent = False
        self.array = array
        self._done = array is None

    @property
    def completed(self) -> bool:
        """Live readiness view. The plural helpers (rq.wait_all/
        test_all/...) poll ``.completed`` and spin the host progress
        engine, which never advances a device program — so this MUST
        probe the array, not cache a flag only test()/wait() flip."""
        if not self._done:
            import jax

            try:  # .array may be a pytree (fused allreduce results)
                if all(bool(a.is_ready())
                       for a in jax.tree.leaves(self.array)):
                    self._done = True
            except AttributeError:  # backend without is_ready:
                # readiness polling degrades to blocking (the same
                # guarantee the pre-property test() gave) — never
                # report completion that has not happened
                jax.block_until_ready(self.array)
                self._done = True
        return self._done

    def test(self) -> bool:
        return self.completed

    def wait(self, timeout=None):
        if not self._done:
            import jax

            jax.block_until_ready(self.array)
            self._done = True
        return self.status

    def cancel(self) -> None:  # dispatched programs are not cancelable
        pass

    def free(self) -> None:
        pass

    def retrieve_status(self):
        return self.status


def ibarrier_dev(comm):
    """Nonblocking device barrier: the 1-element psum is dispatched;
    the request completes when every plane member has entered."""
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return DeviceRequest(None)
    import jax.numpy as jnp

    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)

    def build():
        return ctx.smap(lambda a: C.allreduce(a[0], AXIS, op_mod.SUM),
                        out_varying=False)

    fn = ctx.compiled(("barrier",), build)
    token = ctx.jax.device_put(jnp.ones((1,), jnp.int32), ctx.my)
    return DeviceRequest(
        ctx.my_shard(ctx.launch(fn, ctx.to_global(token))))


class PersistentDeviceRequest:
    """MPI-4 persistent device collective (reference: the coll.h
    *_init slot table): init runs the FULL prep — plan, compile, and
    operand bind (jax arrays are immutable, so the bound operand never
    changes) — and every ``start()`` is one cached-executable launch
    of the zero-arg launcher, zero re-planning. jax arrays are
    immutable, so each cycle's result is a fresh array in ``.array``."""

    def __init__(self, launch) -> None:
        from ompi_tpu.pml import request as rq

        self.id = next(rq._req_ids)
        self.status = rq.Status()
        self.persistent = True
        self._launch = launch
        self._inner: Optional[DeviceRequest] = None

    def start(self) -> None:
        if self._launch is None:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                "start: persistent request already freed (MPI calls "
                "starting a freed request erroneous)")
        self._inner = DeviceRequest(self._launch())

    def rebind(self, *args, **kwargs) -> None:
        """Rebind the request's operands to fresh values of the SAME
        signature without re-planning or re-compiling — the zero-3
        parameter-stream hook (the optimizer replaces its shard
        arrays every step; the per-layer allgather keeps its cached
        executable and only swaps the bound inputs). Only preps that
        install a ``rebind`` hook support it; the trivial gated paths
        (size-1 comms, empty states) raise ERR_NOT_SUPPORTED and the
        caller re-inits instead (init is free there — there is no
        prep to redo)."""
        if self._launch is None:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                "rebind: persistent request already freed")
        if self.active:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                "rebind: cycle still active — wait() it to "
                "completion before swapping operands")
        rb = getattr(self._launch, "rebind", None)
        if rb is None:
            raise errors.MPIError(
                errors.ERR_NOT_SUPPORTED,
                "rebind: this persistent request binds per start "
                "(trivial/gated path) — free() and re-init instead")
        rb(*args, **kwargs)

    def discard(self) -> None:
        """Drop the completed cycle's result so its device arrays can
        be reclaimed — the zero-3 free-after-use hook (a gathered
        layer's full parameters would otherwise stay pinned by
        ``.array`` until the next start). The request stays usable."""
        self._inner = None

    @property
    def active(self) -> bool:
        """A started cycle whose result is not yet ready (start_all
        refuses to restart these — MPI calls it erroneous)."""
        return self._inner is not None and not self._inner.test()

    @property
    def completed(self) -> bool:
        """Live view over the in-flight cycle, so the plural wait/test
        helpers (which poll .completed) see device completion; an
        INACTIVE persistent request is complete with an empty status,
        per MPI — matching the host _PersistentRequest."""
        return True if self._inner is None else self._inner.test()

    @property
    def array(self):
        return None if self._inner is None else self._inner.array

    def test(self) -> bool:
        return self.completed

    def wait(self, timeout=None):
        if self._inner is None:
            return self.status  # inactive: immediately complete (MPI)
        return self._inner.wait(timeout)

    def retrieve_status(self):
        return self.status

    def cancel(self) -> None:
        pass

    def free(self) -> None:
        # release the launcher's bound operands (the param shards /
        # gathered results it pins) and the last cycle's arrays; a
        # start() after free raises ERR_REQUEST per MPI
        rel = getattr(self._launch, "release", None)
        if rel is not None:
            rel()
        self._launch = None
        self._inner = None


def _pinit(fn):
    """persistent-init variant of a slot WITHOUT a prep phase (the
    staged fallback path): bind the arguments now, re-run the whole
    slot at every start()."""
    def pslot(*args, **kwargs):
        return PersistentDeviceRequest(lambda: fn(*args, **kwargs))
    pslot.__name__ = fn.__name__ + "_init"
    return pslot


def _pprep(prep, blocking, name: str, gates=()):
    """persistent-init slot over a prep function: everything that can
    be hoisted out of the start/wait cycle — planning, compilation,
    sharding construction — runs at init; start() dispatches the
    cached executable. ``gates(comm, buf)`` returning True selects the
    trivial bind-now path (size-1 comms, non-traceable ops), which
    re-runs the blocking slot per start."""
    def pslot(comm, buf, *args, **kwargs):
        for gate in gates:
            if gate(comm, buf, *args, **kwargs):
                return PersistentDeviceRequest(
                    lambda: blocking(comm, buf, *args, **kwargs))
        return PersistentDeviceRequest(
            prep(comm, buf, *args, **kwargs))
    pslot.__name__ = name
    return pslot


def _gate_size1(comm, buf, *a, **k) -> bool:
    return comm.size == 1


def _gate_op(comm, buf, *args, **kwargs) -> bool:
    op = args[0] if args else kwargs.get("op", op_mod.SUM)
    return not _op_ok(op)


allreduce_init_dev = _pprep(
    _allreduce_prep, allreduce_dev, "allreduce_init_dev",
    gates=(_gate_op, _gate_size1))
bcast_init_dev = _pprep(
    _bcast_prep, bcast_dev, "bcast_init_dev", gates=(_gate_size1,))
allgather_init_dev = _pprep(
    _allgather_prep, allgather_dev, "allgather_init_dev",
    gates=(_gate_size1,))
alltoall_init_dev = _pprep(
    _alltoall_prep, alltoall_dev, "alltoall_init_dev",
    gates=(_gate_size1,))
reduce_scatter_block_init_dev = _pprep(
    _reduce_scatter_block_prep, reduce_scatter_block_dev,
    "reduce_scatter_block_init_dev", gates=(_gate_op, _gate_size1))


def _multi_empty(comm, bufs, *a, **k) -> bool:
    import jax

    return not jax.tree.leaves(bufs)


allreduce_multi_init_dev = _pprep(
    _allreduce_multi_prep, allreduce_multi_dev,
    "allreduce_multi_init_dev",
    gates=(_gate_op, _gate_size1, _multi_empty))


# ---------------------------------------------------------------------------
# fused (bucketed) reduce_scatter / allgather — the zero/ sharded
# data-parallel engine. Same _FusePlan dtype buckets, extended with
# pad-to-comm-size (zero.layout.ZeroPlan) so each bucket lowers to ONE
# tiled reduce_scatter/all_gather; plans + executables live in the
# same _Ctx LRU caches as the fused allreduce.


def _zero_plan(ctx, metas, treedef):
    """Pad-and-shard bucket plan, cached per (signature, bucket size,
    comm size). Op/determinism are NOT in the key: the layout is
    geometry only, so one plan serves the RS and AG directions."""
    from ompi_tpu.zero import layout as _zl

    bb = int(_bucket_var.get())
    return ctx.plan(("zero", metas, treedef, bb, ctx.n),
                    lambda: _zl.ZeroPlan(metas, bb, ctx.n))


def _zero_rs_fn(ctx, metas, idxs, pad: int, opn, det: Optional[str]):
    """ONE compiled concat+pad+reduce_scatter program for a bucket.
    Bit-identity: under 'linear' C.reduce_scatter folds in exact rank
    order then slices — elementwise identical to the per-buffer
    allreduce-linear path, and concatenation/zero-padding never
    change an element's fold order. Keyed like the fused allreduce so
    the partitioned path resolves to the SAME executable."""
    from ompi_tpu.parallel import collectives as C

    sig = tuple((metas[i][0], metas[i][1]) for i in idxs)

    def build():
        def body(args):
            import jax.numpy as jnp

            flat = (jnp.concatenate(
                [a[0].reshape(-1) for a in args])
                if len(args) > 1 else args[0][0].reshape(-1))
            if pad:
                flat = jnp.pad(flat, (0, pad))
            return C.reduce_scatter(flat, AXIS, opn, scatter_dim=0,
                                    tiled=True, deterministic=det)

        return ctx.smap(body, out_varying=True)

    return ctx.compiled(("zero_rs", sig, pad, opn.name, det), build)


def _zero_ag_fn(ctx, metas, idxs, elems: int, pad: int):
    """ONE compiled all_gather+split program for a bucket: the local
    shard gathers tiled in rank order (= the pack order), the pad
    tail drops, and the static split restores member leaf shapes."""
    from ompi_tpu.parallel import collectives as C

    sig = tuple((metas[i][0], metas[i][1]) for i in idxs)
    shapes = tuple(metas[i][0] for i in idxs)

    def build():
        def body(a):
            full = C.allgather(a[0], AXIS, tiled=True, gather_dim=0)
            outs, off = [], 0
            for shape in shapes:
                k = 1
                for s in shape:
                    k *= int(s)
                outs.append(full[off:off + k].reshape(shape))
                off += k
            return tuple(outs)

        return ctx.smap(body, out_varying=False)

    return ctx.compiled(("zero_ag", sig, elems, pad), build)


def _zero_empty_state(comm, treedef):
    from ompi_tpu.zero import layout as _zl

    plan = _zl.ZeroPlan((), int(_bucket_var.get()), comm.size)
    return _zl.ShardedState(plan, (), treedef, [], comm.rank,
                            comm.size)


def _reduce_scatter_multi_prep(comm, bufs, op=op_mod.SUM,
                               deterministic: Optional[str] = None):
    """Plan + compile + bind the bucketed reduce_scatter NOW; the
    returned zero-arg launcher runs one cached dispatch per bucket
    and yields the rank's ShardedState (the ZeRO gradient shards)."""
    import jax

    from ompi_tpu.zero import layout as _zl

    leaves, treedef = jax.tree.flatten(bufs)
    if not leaves:
        return lambda: _zero_empty_state(comm, treedef)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    det = _det(deterministic)
    ctx = _ctx(comm)
    metas = _fuse_metas(leaves)
    plan = _zero_plan(ctx, metas, treedef)
    launches = []
    for b, idxs in enumerate(plan.buckets):
        fn = _zero_rs_fn(ctx, metas, idxs,
                         plan.padded[b] - plan.elems[b], opn, det)
        gs = tuple(ctx.to_global(leaves[i]) for i in idxs)
        launches.append((fn, gs))

    def launch():
        shards = []
        for fn, gs in launches:
            shards.append(ctx.my_shard(ctx.launch(fn, gs)))
            pvar.record("zero_rs_launches")
        pvar.record("zero_fused_bytes", plan.nbytes)
        pvar.record("zero_pad_bytes", plan.pad_bytes)
        return _zl.ShardedState(plan, metas, treedef, shards,
                                comm.rank, ctx.n)

    return launch


def reduce_scatter_multi_dev(comm, bufs, op=op_mod.SUM,
                             deterministic: Optional[str] = None):
    """Bucketed reduce_scatter over a pytree of device buffers (the
    ZeRO gradient-sharding step): dtype-segregated flat buckets padded
    to a multiple of comm size (zero.layout.ZeroPlan), ONE compiled
    tiled reduce_scatter per bucket, returning this rank's
    ShardedState — full reduced gradients are never materialized.
    'linear' determinism is bit-identical to the per-buffer
    allreduce+slice path."""
    if not _op_ok(op):
        return staging.reduce_scatter_multi_dev(
            comm, bufs, op, deterministic=deterministic)
    pvar.record("coll_xla_device")
    import jax

    if comm.size == 1:
        # reducing over one rank is the identity: the shard is a
        # local pack+slice, no plane/collective needed (the same
        # trivial fast path the other size-1 device slots take)
        from ompi_tpu.zero import layout as _zl

        return _zl.ShardedState.from_full(comm, bufs)
    tm = _mon.TRAFFIC
    if tm is not None:
        leaves = jax.tree.leaves(bufs)
        tm.coll("reduce_scatter_multi", comm,
                sum(getattr(b, "nbytes", 0) for b in leaves),
                dtype=str(getattr(leaves[0], "dtype", ""))
                if leaves else "")
    fl = _flight.FLIGHT
    if fl is None:
        return _reduce_scatter_multi_prep(comm, bufs, op,
                                          deterministic)()
    tok = fl.enter("reduce_scatter_multi_dev",
                   getattr(comm, "cid", -1),
                   sum(getattr(b, "nbytes", 0)
                       for b in jax.tree.leaves(bufs)))
    try:
        return _reduce_scatter_multi_prep(comm, bufs, op,
                                          deterministic)()
    finally:
        fl.exit(tok)


def _zero_state_check(comm, state) -> None:
    """MPI erroneous-call validation for the allgather direction (the
    part/host.py MPIError convention, applied to the *_multi entry
    points from day one)."""
    from ompi_tpu.zero import layout as _zl

    if not isinstance(state, _zl.ShardedState):
        raise errors.MPIError(
            errors.ERR_ARG,
            f"Allgather_multi: operand is {type(state).__name__}, "
            "expected a ShardedState (the Reduce_scatter_multi / "
            "ShardedState.from_full result)")
    if state.n != comm.size:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"Allgather_multi: state sharded {state.n} ways on a "
            f"size-{comm.size} communicator")
    if len(state.shards) != len(state.plan.buckets):
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"Allgather_multi: {len(state.shards)} shards for "
            f"{len(state.plan.buckets)} plan buckets")
    for b, s in enumerate(state.shards):
        k = state.plan.shard_elems[b]
        if tuple(s.shape) != (k,) \
                or str(s.dtype) != state.plan.dtypes[b]:
            raise errors.MPIError(
                errors.ERR_COUNT,
                f"Allgather_multi: bucket {b} shard is "
                f"{tuple(s.shape)}/{s.dtype}, plan expects "
                f"({k},)/{state.plan.dtypes[b]} (shard-wise updates "
                "must preserve shape and dtype)")


def _allgather_multi_prep(comm, state):
    """Compile + bind the bucketed allgather NOW (operand = the
    state's current shards; like every persistent device collective
    the binding is per-init — jax arrays are immutable). The returned
    launcher carries two hooks the persistent form exposes for the
    zero-3 parameter stream: ``rebind(new_state)`` swaps the bound
    shard arrays for a same-plan state with NO re-planning or
    re-compiling (the optimizer replaces its shards every step), and
    ``release()`` drops the bound operands so nothing pins them."""
    ctx = _ctx(comm)
    _zero_state_check(comm, state)
    plan, metas = state.plan, state.metas
    launches = []
    for b, idxs in enumerate(plan.buckets):
        fn = _zero_ag_fn(ctx, metas, idxs, plan.elems[b],
                         plan.padded[b] - plan.elems[b])
        launches.append([fn, ctx.to_global(state.shards[b]), idxs])

    import jax

    n_leaves = sum(len(idxs) for idxs in plan.buckets)

    def launch():
        outs = [None] * n_leaves
        for fn, g, idxs in launches:
            if g is None:
                raise errors.MPIError(
                    errors.ERR_REQUEST,
                    "allgather_multi start: operands released — "
                    "rebind() a fresh state first")
            res = ctx.launch(fn, g)
            for j, i in enumerate(idxs):
                outs[i] = ctx.my_shard(res[j])
            pvar.record("zero_ag_launches")
        pvar.record("zero_fused_bytes", plan.nbytes)
        return jax.tree.unflatten(state.treedef, outs)

    def rebind(new_state) -> None:
        _zero_state_check(comm, new_state)
        if new_state.plan.buckets != plan.buckets:
            raise errors.MPIError(
                errors.ERR_ARG,
                "allgather_multi rebind: state packed by a different "
                "plan (the compiled programs are layout-specialized; "
                "re-init for a new bucket layout)")
        for b, entry in enumerate(launches):
            entry[1] = ctx.to_global(new_state.shards[b])

    def release() -> None:
        for entry in launches:
            entry[1] = None

    launch.rebind = rebind
    launch.release = release
    return launch


def allgather_multi_dev(comm, state):
    """Bucketed allgather of a ShardedState back to the full pytree
    (the ZeRO parameter-rebuild step): ONE compiled tiled all_gather
    per bucket, rank-order concat (= the pack order), pad tail
    dropped, leaf shapes restored."""
    pvar.record("coll_xla_device")
    _zero_state_check(comm, state)
    if not state.shards:
        import jax

        return jax.tree.unflatten(state.treedef, [])
    if comm.size == 1:
        # n=1 shards ARE the full padded buckets: unpack locally
        return state.unpack(state.shards)
    tm = _mon.TRAFFIC
    if tm is not None:
        tm.coll("allgather_multi", comm, state.plan.nbytes,
                dtype=state.plan.dtypes[0]
                if state.plan.dtypes else "")
    fl = _flight.FLIGHT
    if fl is None:
        return _allgather_multi_prep(comm, state)()
    tok = fl.enter("allgather_multi_dev", getattr(comm, "cid", -1),
                   state.plan.nbytes)
    try:
        return _allgather_multi_prep(comm, state)()
    finally:
        fl.exit(tok)


def allgather_multi_bucket_dev(comm, state, b: int):
    """Gather ONE bucket of a ShardedState: the member leaves (in
    ``plan.buckets[b]`` order) of the full tree, through the same
    cached per-bucket executable as allgather_multi_dev. The
    bucket-granular form the ZeroOptimizer dirty-skip path uses —
    buckets whose shards did not change this step reuse the previous
    cycle's gathered leaves instead of relaunching (the
    ``zero_ag_skipped`` accounting lives with the caller)."""
    pvar.record("coll_xla_device")
    _zero_state_check(comm, state)
    plan, metas = state.plan, state.metas
    if not 0 <= b < len(plan.buckets):
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"allgather_multi_bucket: bucket {b} out of range for a "
            f"{len(plan.buckets)}-bucket plan")
    idxs = plan.buckets[b]
    if comm.size == 1:
        # the n=1 shard IS the full padded bucket: unpack locally
        flat = state.shards[b]
        outs, off = [], 0
        for i in idxs:
            shape = metas[i][0]
            k = 1
            for s in shape:
                k *= int(s)
            outs.append(flat[off:off + k].reshape(shape))
            off += k
        return outs
    ctx = _ctx(comm)
    fn = _zero_ag_fn(ctx, metas, idxs, plan.elems[b],
                     plan.padded[b] - plan.elems[b])
    res = ctx.launch(fn, ctx.to_global(state.shards[b]))
    pvar.record("zero_ag_launches")
    return [ctx.my_shard(r) for r in res]


def _multi_state_empty(comm, state, *a, **k) -> bool:
    return not getattr(state, "shards", None)


reduce_scatter_multi_init_dev = _pprep(
    _reduce_scatter_multi_prep, reduce_scatter_multi_dev,
    "reduce_scatter_multi_init_dev",
    gates=(_gate_op, _gate_size1, _multi_empty))
allgather_multi_init_dev = _pprep(
    _allgather_multi_prep, allgather_multi_dev,
    "allgather_multi_init_dev",
    gates=(_gate_size1, _multi_state_empty))


# ---------------------------------------------------------------------------
# partitioned fused allreduce (MPI-4 part/ subsystem, device payoff)


class PartitionedAllreduceRequest:
    """MPI-4 partitioned fused allreduce handle (Pallreduce_init —
    the part/ subsystem's device-path payoff).

    Partitions are the leaves of the bound pytree in jax.tree.flatten
    order. Init does the full prep: the _FusePlan dtype-bucket layout
    and each bucket's ONE compiled concat+reduce+split program are
    resolved through the SAME _Ctx caches and keys as Allreduce_multi
    (shared executables -> bit-identical under 'linear', zero
    recompiles after init — pvar-verified). start() opens a cycle;
    Pready(i[, value]) marks leaf i ready — optionally rebinding this
    cycle's fresh value — and the moment a bucket's LAST member leaf
    is ready its compiled psum dispatches (PJRT-async), so early
    buckets' communication overlaps production of later gradients
    (the DDP/Horovod backward-hook overlap, through a standard MPI-4
    surface). wait() drains the tail and assembles ``.array``.

    Duck-types the request contract (completed/test/wait/free);
    inactive reads as complete, per MPI."""

    def __init__(self, ctx, leaves, treedef, opn,
                 det: Optional[str], comm=None) -> None:
        from ompi_tpu.pml import request as rq

        self.id = next(rq._req_ids)
        self.status = rq.Status()
        self.persistent = True
        self._ctx = ctx
        self._comm = comm  # traffic attribution (monitoring plane)
        self._treedef = treedef
        self._n = len(leaves)
        metas = _fuse_metas(leaves)
        plan = _fuse_plan(ctx, metas, treedef, opn, det)
        self.nbytes = plan.nbytes
        hier = det is None and ctx.mesh2d is not None
        self._to_g = ctx.to_global_hier if hier else ctx.to_global
        self._metas = metas
        self._buckets = tuple(
            (_bucket_fn(ctx, metas, idxs, opn, det, hier), idxs)
            for idxs in plan.buckets)
        self._leaf_bucket = {i: b
                             for b, (_fn, idxs)
                             in enumerate(self._buckets)
                             for i in idxs}
        # template operands bound now: a Pready without a fresh value
        # (static tensors, tests) reuses them — jax arrays are
        # immutable, so rebinding is per-cycle state, not mutation
        self._bound = [self._to_g(l) for l in leaves]
        self._ready = None  # None = inactive
        self._arr = None

    @property
    def active(self) -> bool:
        return self._ready is not None

    @property
    def array(self):
        """The synced pytree of the last completed cycle."""
        return self._arr

    def start(self) -> None:
        if self.active:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                "Pallreduce start: previous cycle still active — "
                "wait() it to completion first (starting an active "
                "request is erroneous)")
        self._ready = [False] * self._n
        self._n_ready = 0
        self._pending = [len(idxs) for _fn, idxs in self._buckets]
        self._results = [None] * len(self._buckets)
        fl = _flight.FLIGHT
        self._fl_tok = None if fl is None else fl.enter(
            "pallreduce_cycle", -1, self.nbytes)

    def Pready(self, idx: int, value=None) -> None:
        if self._ready is None:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Pready({idx}): request inactive — call start() "
                "before marking partitions ready")
        if self._ready[idx]:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"Pready({idx}): partition already marked ready "
                "this cycle (double-Pready is erroneous)")
        if value is not None:
            shape, dtype, _nb = self._metas[idx]
            if tuple(value.shape) != shape or str(value.dtype) != dtype:
                raise errors.MPIError(
                    errors.ERR_ARG,
                    f"Pready({idx}): value {tuple(value.shape)}/"
                    f"{value.dtype} does not match the bound template "
                    f"leaf {shape}/{dtype} (compiled programs are "
                    "shape-specialized; re-init for a new signature)")
            self._bound[idx] = self._to_g(value)
        self._ready[idx] = True
        self._n_ready += 1
        pvar.record("part_pready")
        rec = _trace.RECORDER
        if rec is not None:
            rec.instant("pready", "part", {"partition": idx})
        b = self._leaf_bucket[idx]
        self._pending[b] -= 1
        if self._pending[b] == 0:
            self._flush(b, idx)

    def Pready_range(self, lo: int, hi: int) -> None:
        for i in range(lo, hi + 1):
            self.Pready(i)

    def Pready_list(self, idxs) -> None:
        for i in idxs:
            self.Pready(i)

    def _flush(self, b: int, trigger: Optional[int] = None) -> None:
        fn, idxs = self._buckets[b]
        overlap = self._n_ready < self._n
        rec = _trace.RECORDER
        if rec is None:
            self._results[b] = self._ctx.launch(
                fn, tuple(self._bound[i] for i in idxs))
        else:
            # the flush span carries the Pready that triggered it, so
            # a timeline shows WHICH partition released each bucket
            # (the Pready -> flush causality the overlap design rests
            # on) and whether the dispatch overlapped the producer
            t0 = _trace.now()
            self._results[b] = self._ctx.launch(
                fn, tuple(self._bound[i] for i in idxs))
            t1 = _trace.now()
            nb = sum(self._metas[i][2] for i in idxs)
            rec.record("part_bucket_flush", "part", t0, t1,
                       {"bucket": b, "trigger_partition": trigger,
                        "overlap": overlap, "nbytes": nb})
            _trace.hist("part_bucket_flush", nb, t1 - t0)
        tm = _mon.TRAFFIC
        if tm is not None and self._comm is not None:
            # the bucket's psum IS an allreduce launch; attributed to
            # the part context so overlap traffic stays separable
            tm.coll("allreduce", self._comm,
                    sum(self._metas[i][2] for i in idxs),
                    dtype=self._metas[idxs[0]][1], ctx="part")
        pvar.record("part_bucket_flushes")
        if overlap:
            # dispatched while later partitions are still pending:
            # this bucket's wire time is hidden behind the producer
            pvar.record("part_overlap_flushes")

    @property
    def completed(self) -> bool:
        """Live view for the plural wait/test helpers; inactive is
        complete (MPI). An active cycle with unready partitions is
        incomplete — only wait() raises on it (a poll is not a
        completion demand)."""
        if self._ready is None:
            return True
        if self._n_ready < self._n:
            return False
        import jax

        try:
            return all(bool(a.is_ready())
                       for r in self._results
                       for a in jax.tree.leaves(r))
        except AttributeError:  # backend without is_ready
            jax.block_until_ready(self._results)
            return True

    def test(self) -> bool:
        return self.completed

    def _finalize(self) -> None:
        """Close the cycle: split the bucket results back into leaf
        shards, block, publish ``.array``, go inactive."""
        import jax

        outs = [None] * self._n
        for b, (_fn, idxs) in enumerate(self._buckets):
            res = self._results[b]
            for j, i in enumerate(idxs):
                outs[i] = self._ctx.my_shard(res[j])
        jax.block_until_ready(outs)
        pvar.record("coll_xla_fused_bytes", self.nbytes)
        self._arr = jax.tree.unflatten(self._treedef, outs)
        self._ready = None  # cycle closed: back to inactive
        tok, self._fl_tok = self._fl_tok, None
        if tok is not None:
            fl = _flight.FLIGHT
            if fl is not None:
                fl.exit(tok)

    def wait(self, timeout=None):
        if self._ready is None:
            return self.status  # inactive: immediately complete
        if self._n_ready < self._n:
            missing = [i for i, r in enumerate(self._ready) if not r]
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Pallreduce wait: partitions {missing} never marked "
                "ready — the bucket collective cannot launch and the "
                "wait would deadlock every rank")
        self._finalize()
        return self.status

    def retrieve_status(self):
        # the plural helpers (rq.wait_all/test_all) complete a request
        # via completed + retrieve_status, never wait(): a fully-ready
        # cycle must finalize here too or .array would stay stale
        if self._ready is not None and self._n_ready == self._n:
            self._finalize()
        return self.status

    def cancel(self) -> None:  # dispatched programs not cancelable
        pass

    def free(self) -> None:
        pass


class _TrivialPartitionedAllreduce:
    """Degenerate Pallreduce handle for the gated cases (size-1 comm,
    non-traceable op, empty pytree): full partitioned bookkeeping —
    identical Pready/start/wait semantics and errors — with the
    reduction itself deferred to wait() through the comm's
    allreduce_multi slot. Correct, no overlap."""

    def __init__(self, comm, bufs, op, deterministic) -> None:
        import jax

        from ompi_tpu.pml import request as rq

        self.id = next(rq._req_ids)
        self.status = rq.Status()
        self.persistent = True
        self._comm = comm
        self._op = op
        self._det = deterministic
        leaves, self._treedef = jax.tree.flatten(bufs)
        self._bound = list(leaves)
        self._n = len(leaves)
        self._ready = None
        self._arr = None

    @property
    def active(self) -> bool:
        return self._ready is not None

    @property
    def array(self):
        return self._arr

    @property
    def completed(self) -> bool:
        return self._ready is None or self._n_ready == self._n

    def start(self) -> None:
        if self.active:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                "Pallreduce start: previous cycle still active")
        self._ready = [False] * self._n
        self._n_ready = 0

    def Pready(self, idx: int, value=None) -> None:
        if self._ready is None:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Pready({idx}): request inactive — call start() "
                "before marking partitions ready")
        if self._ready[idx]:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"Pready({idx}): partition already marked ready "
                "this cycle (double-Pready is erroneous)")
        if value is not None:
            self._bound[idx] = value
        self._ready[idx] = True
        self._n_ready += 1
        pvar.record("part_pready")

    def Pready_range(self, lo: int, hi: int) -> None:
        for i in range(lo, hi + 1):
            self.Pready(i)

    def Pready_list(self, idxs) -> None:
        for i in idxs:
            self.Pready(i)

    def test(self) -> bool:
        return self.completed

    def _finalize(self) -> None:
        import jax

        tree = jax.tree.unflatten(self._treedef, self._bound)
        self._arr = self._comm.coll.allreduce_multi_dev(
            self._comm, tree, self._op, deterministic=self._det)
        self._ready = None

    def wait(self, timeout=None):
        if self._ready is None:
            return self.status
        if self._n_ready < self._n:
            missing = [i for i, r in enumerate(self._ready) if not r]
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Pallreduce wait: partitions {missing} never marked "
                "ready")
        self._finalize()
        return self.status

    def retrieve_status(self):
        if self._ready is not None and self._n_ready == self._n:
            self._finalize()
        return self.status

    def cancel(self) -> None:
        pass

    def free(self) -> None:
        pass


def pallreduce_init_dev(comm, bufs, op=op_mod.SUM,
                        deterministic: Optional[str] = None):
    """Partitioned fused allreduce init (MPI-4 part/ on the device
    plane): one partition per pytree leaf; each dtype bucket's single
    compiled psum launches the moment its last member leaf is
    Pready'd, overlapping early buckets' communication with late
    gradients' production. Shares plan + executable caches with
    allreduce_multi_dev."""
    import jax

    leaves, treedef = jax.tree.flatten(bufs)
    if not _op_ok(op) or comm.size == 1 or not leaves:
        return _TrivialPartitionedAllreduce(comm, bufs, op,
                                            deterministic)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    return PartitionedAllreduceRequest(_ctx(comm), leaves, treedef,
                                       opn, _det(deterministic),
                                       comm=comm)


class PartitionedReduceScatterRequest:
    """MPI-4 partitioned fused reduce_scatter (Preduce_scatter_init —
    the backward-overlap analog of Pallreduce_init for the ZeRO
    gradient-sharding step).

    Partitions are pytree leaves in flatten order. Init resolves the
    ZeroPlan and each bucket's ONE compiled concat+pad+reduce_scatter
    program through the SAME _Ctx caches and keys as
    Reduce_scatter_multi (shared executables -> bit-identical under
    'linear', zero recompiles after init). start() opens a cycle;
    Pready(i[, value]) marks leaf i ready, and the moment a bucket's
    LAST member is ready its reduce_scatter dispatches — early
    buckets' scatter traffic overlaps production of later gradients
    (``zero_overlap_flushes`` counts the buckets that beat the final
    Pready). wait() drains the tail; ``.array`` is the cycle's
    ShardedState."""

    def __init__(self, ctx, comm, leaves, treedef, opn,
                 det: Optional[str]) -> None:
        from ompi_tpu.pml import request as rq

        self.id = next(rq._req_ids)
        self.status = rq.Status()
        self.persistent = True
        self._ctx = ctx
        self._comm = comm
        self._treedef = treedef
        self._n = len(leaves)
        metas = _fuse_metas(leaves)
        plan = _zero_plan(ctx, metas, treedef)
        self._plan = plan
        self.nbytes = plan.nbytes
        self._metas = metas
        self._buckets = tuple(
            (_zero_rs_fn(ctx, metas, idxs,
                         plan.padded[b] - plan.elems[b], opn, det),
             idxs)
            for b, idxs in enumerate(plan.buckets))
        self._leaf_bucket = {i: b
                             for b, (_fn, idxs)
                             in enumerate(self._buckets)
                             for i in idxs}
        self._bound = [ctx.to_global(l) for l in leaves]
        self._ready = None  # None = inactive
        self._arr = None

    @property
    def active(self) -> bool:
        return self._ready is not None

    @property
    def array(self):
        """The ShardedState of the last completed cycle."""
        return self._arr

    def start(self) -> None:
        if self.active:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                "Preduce_scatter start: previous cycle still active — "
                "wait() it to completion first (starting an active "
                "request is erroneous)")
        self._ready = [False] * self._n
        self._n_ready = 0
        self._pending = [len(idxs) for _fn, idxs in self._buckets]
        self._results = [None] * len(self._buckets)
        fl = _flight.FLIGHT
        self._fl_tok = None if fl is None else fl.enter(
            "preduce_scatter_cycle", getattr(self._comm, "cid", -1),
            self.nbytes)

    def Pready(self, idx: int, value=None) -> None:
        if self._ready is None:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Pready({idx}): request inactive — call start() "
                "before marking partitions ready")
        if self._ready[idx]:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"Pready({idx}): partition already marked ready "
                "this cycle (double-Pready is erroneous)")
        if value is not None:
            shape, dtype, _nb = self._metas[idx]
            if tuple(value.shape) != shape or str(value.dtype) != dtype:
                raise errors.MPIError(
                    errors.ERR_COUNT,
                    f"Pready({idx}): value {tuple(value.shape)}/"
                    f"{value.dtype} does not match the bound template "
                    f"leaf {shape}/{dtype} (compiled programs are "
                    "shape-specialized; re-init for a new signature)")
            self._bound[idx] = self._ctx.to_global(value)
        self._ready[idx] = True
        self._n_ready += 1
        pvar.record("part_pready")
        rec = _trace.RECORDER
        if rec is not None:
            rec.instant("pready", "zero", {"partition": idx})
        b = self._leaf_bucket[idx]
        self._pending[b] -= 1
        if self._pending[b] == 0:
            self._flush(b, idx)

    def Pready_range(self, lo: int, hi: int) -> None:
        for i in range(lo, hi + 1):
            self.Pready(i)

    def Pready_list(self, idxs) -> None:
        for i in idxs:
            self.Pready(i)

    def _flush(self, b: int, trigger: Optional[int] = None) -> None:
        fn, idxs = self._buckets[b]
        overlap = self._n_ready < self._n
        rec = _trace.RECORDER
        if rec is None:
            self._results[b] = self._ctx.launch(
                fn, tuple(self._bound[i] for i in idxs))
        else:
            t0 = _trace.now()
            self._results[b] = self._ctx.launch(
                fn, tuple(self._bound[i] for i in idxs))
            t1 = _trace.now()
            nb = sum(self._metas[i][2] for i in idxs)
            rec.record("zero_bucket_flush", "zero", t0, t1,
                       {"bucket": b, "trigger_partition": trigger,
                        "overlap": overlap, "nbytes": nb})
            _trace.hist("zero_bucket_flush", nb, t1 - t0)
        tm = _mon.TRAFFIC
        if tm is not None:
            tm.coll("reduce_scatter", self._comm,
                    sum(self._metas[i][2] for i in idxs),
                    dtype=self._metas[idxs[0]][1], ctx="part")
        pvar.record("zero_rs_launches")
        if overlap:
            pvar.record("zero_overlap_flushes")

    @property
    def completed(self) -> bool:
        if self._ready is None:
            return True
        if self._n_ready < self._n:
            return False
        import jax

        try:
            return all(bool(a.is_ready())
                       for r in self._results
                       for a in jax.tree.leaves(r))
        except AttributeError:  # backend without is_ready
            jax.block_until_ready(self._results)
            return True

    def test(self) -> bool:
        return self.completed

    def _finalize(self) -> None:
        """Close the cycle: take this rank's shard of each bucket
        result, block, publish the ShardedState, go inactive."""
        import jax

        from ompi_tpu.zero import layout as _zl

        shards = [self._ctx.my_shard(self._results[b])
                  for b in range(len(self._buckets))]
        jax.block_until_ready(shards)
        pvar.record("zero_fused_bytes", self.nbytes)
        pvar.record("zero_pad_bytes", self._plan.pad_bytes)
        self._arr = _zl.ShardedState(
            self._plan, self._metas, self._treedef, shards,
            self._comm.rank, self._ctx.n)
        self._ready = None
        tok, self._fl_tok = self._fl_tok, None
        if tok is not None:
            fl = _flight.FLIGHT
            if fl is not None:
                fl.exit(tok)

    def wait(self, timeout=None):
        if self._ready is None:
            return self.status  # inactive: immediately complete
        if self._n_ready < self._n:
            missing = [i for i, r in enumerate(self._ready) if not r]
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Preduce_scatter wait: partitions {missing} never "
                "marked ready — the bucket collective cannot launch "
                "and the wait would deadlock every rank")
        self._finalize()
        return self.status

    def retrieve_status(self):
        if self._ready is not None and self._n_ready == self._n:
            self._finalize()
        return self.status

    def cancel(self) -> None:  # dispatched programs not cancelable
        pass

    def free(self) -> None:
        pass


class _TrivialPartitionedReduceScatter:
    """Degenerate Preduce_scatter handle for the gated cases
    (non-traceable op, empty pytree): identical Pready/start/wait
    bookkeeping and errors, the scatter itself deferred to wait()
    through the comm's reduce_scatter_multi slot. Correct, no
    overlap."""

    def __init__(self, comm, bufs, op, deterministic) -> None:
        import jax

        from ompi_tpu.pml import request as rq

        self.id = next(rq._req_ids)
        self.status = rq.Status()
        self.persistent = True
        self._comm = comm
        self._op = op
        self._det = deterministic
        leaves, self._treedef = jax.tree.flatten(bufs)
        self._bound = list(leaves)
        self._n = len(leaves)
        self._ready = None
        self._arr = None

    @property
    def active(self) -> bool:
        return self._ready is not None

    @property
    def array(self):
        return self._arr

    @property
    def completed(self) -> bool:
        return self._ready is None or self._n_ready == self._n

    def start(self) -> None:
        if self.active:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                "Preduce_scatter start: previous cycle still active")
        self._ready = [False] * self._n
        self._n_ready = 0

    def Pready(self, idx: int, value=None) -> None:
        if self._ready is None:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Pready({idx}): request inactive — call start() "
                "before marking partitions ready")
        if self._ready[idx]:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"Pready({idx}): partition already marked ready "
                "this cycle (double-Pready is erroneous)")
        if value is not None:
            self._bound[idx] = value
        self._ready[idx] = True
        self._n_ready += 1
        pvar.record("part_pready")

    def Pready_range(self, lo: int, hi: int) -> None:
        for i in range(lo, hi + 1):
            self.Pready(i)

    def Pready_list(self, idxs) -> None:
        for i in idxs:
            self.Pready(i)

    def test(self) -> bool:
        return self.completed

    def _finalize(self) -> None:
        import jax

        tree = jax.tree.unflatten(self._treedef, self._bound)
        self._arr = self._comm.coll.reduce_scatter_multi_dev(
            self._comm, tree, self._op, deterministic=self._det)
        self._ready = None

    def wait(self, timeout=None):
        if self._ready is None:
            return self.status
        if self._n_ready < self._n:
            missing = [i for i, r in enumerate(self._ready) if not r]
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Preduce_scatter wait: partitions {missing} never "
                "marked ready")
        self._finalize()
        return self.status

    def retrieve_status(self):
        if self._ready is not None and self._n_ready == self._n:
            self._finalize()
        return self.status

    def cancel(self) -> None:
        pass

    def free(self) -> None:
        pass


def preduce_scatter_init_dev(comm, bufs, op=op_mod.SUM,
                             deterministic: Optional[str] = None):
    """Partitioned fused reduce_scatter init (MPI-4 part/ on the
    device plane, ZeRO direction): one partition per pytree leaf;
    each bucket's single compiled reduce_scatter launches the moment
    its last member leaf is Pready'd, overlapping gradient sharding
    with the backward pass. Shares the ZeroPlan + executable caches
    with reduce_scatter_multi_dev; wait() publishes the cycle's
    ShardedState in ``.array``."""
    import jax

    leaves, treedef = jax.tree.flatten(bufs)
    if not _op_ok(op) or comm.size == 1 or not leaves:
        return _TrivialPartitionedReduceScatter(comm, bufs, op,
                                                deterministic)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    return PartitionedReduceScatterRequest(
        _ctx(comm), comm, leaves, treedef, opn, _det(deterministic))


def _irequest(fn):
    """i-variant of a device slot: same dispatch, no block — the
    blocking slots already return un-awaited futures, so the i-form
    simply wraps them in a readiness-backed request."""
    def islot(*args, **kwargs):
        return DeviceRequest(fn(*args, **kwargs))
    islot.__name__ = "i" + fn.__name__
    islot.__doc__ = (f"Nonblocking {fn.__name__}: PJRT-async dispatch "
                     "wrapped in a DeviceRequest.")
    return islot


iallreduce_dev = _irequest(allreduce_dev)
ibcast_dev = _irequest(bcast_dev)
ireduce_dev = _irequest(reduce_dev)
iallgather_dev = _irequest(allgather_dev)
igather_dev = _irequest(gather_dev)
ialltoall_dev = _irequest(alltoall_dev)
ireduce_scatter_block_dev = _irequest(reduce_scatter_block_dev)
iscatter_dev = _irequest(scatter_dev)
iscan_dev = _irequest(scan_dev)
iexscan_dev = _irequest(exscan_dev)
iallgatherv_dev = _irequest(allgatherv_dev)
igatherv_dev = _irequest(gatherv_dev)
ialltoallv_dev = _irequest(alltoallv_dev)
iscatterv_dev = _irequest(scatterv_dev)
ireduce_scatter_dev = _irequest(reduce_scatter_dev)


@framework.register
class CollXla(CollModule):
    NAME = "xla"
    PRIORITY = 50  # above accelerator(40): device buffers stay on device

    def query(self, comm) -> int:
        if comm.size == 1:
            return self.PRIORITY  # trivial local path, no plane needed
        from ompi_tpu.runtime import device_plane

        if not device_plane.active():
            return -1
        if any(device_plane.device_for_world_rank(w) is None
               for w in comm.group.ranks):
            return -1
        return self.PRIORITY

    def slots(self, comm):
        return {
            "allreduce_dev": allreduce_dev,
            # fused gradient-bucket allreduce (+ persistent form)
            "allreduce_multi_dev": allreduce_multi_dev,
            "allreduce_multi_init_dev": allreduce_multi_init_dev,
            # MPI-4 partitioned fused allreduce (part/ device payoff)
            "pallreduce_init_dev": pallreduce_init_dev,
            # zero/ sharded data parallel: bucketed reduce_scatter/
            # allgather (+ persistent forms + partitioned RS)
            "reduce_scatter_multi_dev": reduce_scatter_multi_dev,
            "reduce_scatter_multi_init_dev":
                reduce_scatter_multi_init_dev,
            "allgather_multi_dev": allgather_multi_dev,
            "allgather_multi_init_dev": allgather_multi_init_dev,
            "allgather_multi_bucket_dev": allgather_multi_bucket_dev,
            "preduce_scatter_init_dev": preduce_scatter_init_dev,
            "reduce_dev": reduce_dev,
            "bcast_dev": bcast_dev,
            "allgather_dev": allgather_dev,
            "gather_dev": gather_dev,
            "alltoall_dev": alltoall_dev,
            "reduce_scatter_block_dev": reduce_scatter_block_dev,
            "scatter_dev": scatter_dev,
            "scan_dev": scan_dev,
            "exscan_dev": exscan_dev,
            # v-variants + barrier on device (r2 VERDICT missing #4)
            "barrier_dev": barrier_dev,
            "allgatherv_dev": allgatherv_dev,
            "gatherv_dev": gatherv_dev,
            "alltoallv_dev": alltoallv_dev,
            "scatterv_dev": scatterv_dev,
            "reduce_scatter_dev": reduce_scatter_dev,
            # nonblocking device collectives (r2 VERDICT missing #3)
            "ibarrier_dev": ibarrier_dev,
            "iallreduce_dev": iallreduce_dev,
            "ibcast_dev": ibcast_dev,
            "ireduce_dev": ireduce_dev,
            "iallgather_dev": iallgather_dev,
            "igather_dev": igather_dev,
            "ialltoall_dev": ialltoall_dev,
            "ireduce_scatter_block_dev": ireduce_scatter_block_dev,
            "iscatter_dev": iscatter_dev,
            "iscan_dev": iscan_dev,
            "iexscan_dev": iexscan_dev,
            "iallgatherv_dev": iallgatherv_dev,
            "igatherv_dev": igatherv_dev,
            "ialltoallv_dev": ialltoallv_dev,
            "iscatterv_dev": iscatterv_dev,
            "ireduce_scatter_dev": ireduce_scatter_dev,
            # MPI-4 persistent device collectives (coll.h *_init):
            # prep-at-init — Start()+Wait() is one cached-executable
            # launch, zero re-planning (pvar-verified)
            "allreduce_init_dev": allreduce_init_dev,
            "bcast_init_dev": bcast_init_dev,
            "allgather_init_dev": allgather_init_dev,
            "alltoall_init_dev": alltoall_init_dev,
            "reduce_scatter_block_init_dev":
                reduce_scatter_block_init_dev,
            # neighborhood slots (topology comms only — coll.h:600-618)
            **_neighbor_slots(comm),
        }


def _neighbor_slots(comm):
    from ompi_tpu.coll import xla_neighbor

    return xla_neighbor.slots(comm)
