"""coll/xla — device-executed collectives on MPI communicators.

THE north-star component (SURVEY.md §2.3/§2.8, BASELINE.md config #1):
replaces the reference's coll/accelerator staging design
(ompi/mca/coll/accelerator/coll_accelerator_allreduce.c:32-115 — D2H,
host collective, H2D) with collectives that *never leave the device*.

How: the communicator's group maps onto the multi-controller device
plane (:mod:`ompi_tpu.runtime.device_plane` — one device per rank,
bootstrapped like the accelerator modex in
opal/mca/accelerator/accelerator.h:668-711). Per communicator we build a
1-D mesh over the member devices ordered by comm rank; each collective
compiles once per (kind, shape, dtype, op, mode) into an XLA program via
``shard_map`` — psum/all_gather/all_to_all lower to ICI transfers on TPU
and gloo on the CPU test backend. Compiled programs are cached on the
communicator exactly as the reference caches per-comm algorithm
schedules (coll_base_comm_select.c:236-330 stacking).

Determinism contract (BASELINE.md "bit-identical vs basic"):
``deterministic='linear'`` folds contributions in exact rank order —
bit-identical to coll/basic's linear reduce (coll_basic_reduce.c
semantics); ``deterministic='ring'`` fixes a ring chunk order that is
stable run-to-run. Default lets XLA schedule (fastest).

Fallback: any buffer/op the device path cannot express (e.g. MINLOC
struct dtypes) falls through to the coll/accelerator staging functions —
the same slot signature, one priority level down.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu import op as op_mod
from ompi_tpu.coll import CollModule, accelerator as staging, framework
from ompi_tpu.core import cvar, output, pvar

_out = output.stream("coll_xla")

AXIS = "mpi"  # the mesh axis name a communicator compiles to

_default_det = cvar.register(
    "coll_xla_deterministic", "", str,
    help="default determinism mode for device collectives: '' (XLA "
         "schedules, fastest), 'ring' (fixed ring chunk order), "
         "'linear' (exact rank-order fold, bit-identical to coll/basic)",
    choices=["", "ring", "linear"], level=4)

_hier_var = cvar.register(
    "coll_xla_hier", "auto", str,
    help="hierarchical ICI x DCN execution for comms spanning slices "
         "(coll/han's split-level algorithms on device, coll_han.h:"
         "62-63): 'auto' groups member devices by slice_index when "
         "comm ranks are slice-contiguous, 'off' always flat, an "
         "integer N forces N slices (testing on the virtual mesh). "
         "Deterministic modes always use the flat 1-D schedule — the "
         "split-level fold order differs from the rank-order "
         "contract.", level=5)

#: ops whose reduction is expressible as a traced elementwise fold
_TRACEABLE_OPS = {
    "MPI_SUM", "MPI_PROD", "MPI_MIN", "MPI_MAX", "MPI_LAND", "MPI_LOR",
    "MPI_LXOR", "MPI_BAND", "MPI_BOR", "MPI_BXOR",
}


def _det(deterministic: Optional[str]) -> Optional[str]:
    if deterministic is not None:
        return deterministic or None
    return _default_det.get() or None


class _Ctx:
    """Per-communicator compiled-collective state (the analog of the
    reference's per-comm coll module data)."""

    def __init__(self, comm) -> None:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ompi_tpu.runtime import device_plane

        self.jax = jax
        self.P = P
        devs = [device_plane.device_for_world_rank(w)
                for w in comm.group.ranks]
        self.mesh = Mesh(np.array(devs), (AXIS,))
        self.my = device_plane.my_device()
        self.n = len(devs)
        self.in_sharding = NamedSharding(self.mesh, P(AXIS))
        self.fns = {}  # (kind, shape, dtype, ...) -> compiled callable
        # hierarchical ICI x DCN mesh (rank-major rows = slices) when
        # the comm spans slices and ranks are slice-contiguous
        self.mesh2d = None
        n_slices = self._detect_slices(devs)
        if n_slices and 1 < n_slices < self.n:
            from ompi_tpu.parallel import hierarchical as H

            grid = np.array(devs).reshape(n_slices,
                                          self.n // n_slices)
            self.mesh2d = Mesh(grid, (H.DCN_AXIS, H.ICI_AXIS))
            self.in_sharding2d = NamedSharding(
                self.mesh2d, P((H.DCN_AXIS, H.ICI_AXIS)))

    @staticmethod
    def _detect_slices(devs) -> int:
        """Number of DCN groups (0 = stay flat). 'auto' requires comm
        rank order to be slice-contiguous with equal-size slices so
        mesh rows ARE physical slices; anything else degrades to flat
        (correct, just not hierarchy-optimized)."""
        mode = _hier_var.get()
        if mode == "off":
            return 0
        if mode != "auto":
            try:
                n = int(mode)
            except ValueError:
                return 0
            return n if n > 1 and len(devs) % n == 0 else 0
        slices = [getattr(d, "slice_index", None) for d in devs]
        if any(s is None for s in slices):
            return 0
        groups = []
        for s in slices:  # must be contiguous runs of equal length
            if not groups or groups[-1][0] != s:
                groups.append([s, 0])
            groups[-1][1] += 1
        ids = [g[0] for g in groups]
        if len(set(ids)) != len(ids):  # a slice appears twice: ranks
            return 0                   # interleave slices -> flat
        if len({g[1] for g in groups}) != 1:
            return 0  # ragged slices cannot form a mesh
        return len(groups) if len(groups) > 1 else 0

    def replica_groups(self):
        """Device-id groups this comm's collectives compile to
        (introspection parity with DeviceCommunicator.replica_groups)."""
        return [[d.id for d in self.mesh.devices.tolist()]]

    # -- plumbing ---------------------------------------------------------
    def to_global(self, x, sharding=None):
        """Local device array -> global array sharded (n, *shape) on
        the comm axis/axes (rank r's contribution at index r)."""
        jax = self.jax
        x = jax.device_put(x, self.my)
        return jax.make_array_from_single_device_arrays(
            (self.n,) + x.shape, sharding or self.in_sharding,
            [x[None]])

    def my_shard(self, out):
        """This rank's shard of an AXIS-sharded result."""
        return out.addressable_data(0)

    def compiled(self, key, build):
        fn = self.fns.get(key)
        if fn is None:
            fn = self.fns[key] = build()
        return fn

    def smap(self, body, out_varying: bool, mesh=None, spec=None):
        """jit(shard_map(body)) over the comm mesh (or the 2-level
        ICI x DCN mesh when passed). Body sees the local (1, *shape)
        block; out_varying selects the sharded vs replicated spec."""
        jax, P = self.jax, self.P
        spec = spec if spec is not None else P(AXIS)
        out_spec = spec if out_varying else P()
        return jax.jit(jax.shard_map(
            body, mesh=mesh if mesh is not None else self.mesh,
            in_specs=spec, out_specs=out_spec, check_vma=False))

    def to_global_hier(self, x):
        return self.to_global(x, self.in_sharding2d)

    def smap_hier(self, body, out_varying: bool):
        """Mesh rows are slices; row-major device order = comm rank."""
        from ompi_tpu.parallel import hierarchical as H

        return self.smap(body, out_varying, mesh=self.mesh2d,
                         spec=self.P((H.DCN_AXIS, H.ICI_AXIS)))


def _ctx(comm) -> _Ctx:
    ctx = getattr(comm, "_coll_xla_ctx", None)
    if ctx is None:
        ctx = comm._coll_xla_ctx = _Ctx(comm)
    return ctx


def _key(x, *extra):
    return (x.shape, str(x.dtype)) + extra


def _op_ok(op) -> bool:
    op = op_mod.BUILTIN.get(op) if not isinstance(op, op_mod.Op) else op
    if op is None:
        return False
    if op.name in _TRACEABLE_OPS:
        return True
    # user-defined ops run on device iff marked jax-traceable
    return bool(getattr(op, "traceable", False))


# ---------------------------------------------------------------------------
# slots — signatures match coll/accelerator's *_dev (the fallback)


def allreduce_dev(comm, sendbuf, op=op_mod.SUM,
                  deterministic: Optional[str] = None):
    det = _det(deterministic)
    if not _op_ok(op):
        return staging.allreduce_dev(comm, sendbuf, op)
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]
    hier = det is None and ctx.mesh2d is not None

    def build():
        if hier:  # han split-level over ICI x DCN (deterministic
            # modes stay flat: the split fold order differs from the
            # rank-order bit-identical contract)
            from ompi_tpu.parallel import hierarchical as H

            return ctx.smap_hier(lambda a: H.allreduce(a[0], op=opn),
                                 out_varying=False)
        return ctx.smap(lambda a: C.allreduce(a[0], AXIS, opn, det),
                        out_varying=False)

    fn = ctx.compiled(_key(sendbuf, "allreduce", opn.name, det), build)
    to_g = ctx.to_global_hier if hier else ctx.to_global
    return ctx.my_shard(fn(to_g(sendbuf)))


def reduce_dev(comm, sendbuf, op=op_mod.SUM, root: int = 0,
               deterministic: Optional[str] = None):
    if not _op_ok(op):
        return staging.reduce_dev(comm, sendbuf, op, root)
    # SPMD: every device computes the full reduction (free on-device;
    # avoids a divergent program) — shares allreduce's compiled program
    # and cache entry; only the root returns the result
    out = allreduce_dev(comm, sendbuf, op, deterministic)
    return out if comm.rank == root else None


def bcast_dev(comm, buf, root: int = 0):
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return buf
    ctx = _ctx(comm)
    hier = ctx.mesh2d is not None

    def build():
        if hier:
            from ompi_tpu.parallel import hierarchical as H

            ici = ctx.mesh2d.devices.shape[1]
            return ctx.smap_hier(
                lambda a: H.bcast(a[0], root_dcn=root // ici,
                                  root_ici=root % ici),
                out_varying=False)
        return ctx.smap(_bcast_body(root), out_varying=False)

    fn = ctx.compiled(_key(buf, "bcast", root), build)
    to_g = ctx.to_global_hier if hier else ctx.to_global
    return ctx.my_shard(fn(to_g(buf)))


def _bcast_body(root: int):
    from ompi_tpu.parallel import collectives as C

    return lambda a: C.bcast(a[0], AXIS, root)


def allgather_dev(comm, sendbuf):
    pvar.record("coll_xla_device")
    ctx_free = comm.size == 1
    if ctx_free:
        return sendbuf[None] if hasattr(sendbuf, "shape") else sendbuf
    from jax import lax

    ctx = _ctx(comm)

    def build():
        return ctx.smap(lambda a: lax.all_gather(a[0], AXIS),
                        out_varying=False)

    fn = ctx.compiled(_key(sendbuf, "allgather"), build)
    return ctx.my_shard(fn(ctx.to_global(sendbuf)))


def gather_dev(comm, sendbuf, root: int = 0):
    out = allgather_dev(comm, sendbuf)
    return out if comm.rank == root else None


def alltoall_dev(comm, sendbuf):
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    if sendbuf.shape[0] % comm.size:
        raise ValueError(
            f"alltoall: dim0 {sendbuf.shape[0]} not divisible by "
            f"comm size {comm.size}")
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    hier = ctx.mesh2d is not None

    def build():
        if hier:  # two-phase: every byte crosses DCN exactly once;
            # output is source-rank-major, the MPI alltoall order
            from ompi_tpu.parallel import hierarchical as H

            return ctx.smap_hier(lambda a: H.alltoall(a[0]),
                                 out_varying=True)
        return ctx.smap(lambda a: C.alltoall(a[0], AXIS, 0, 0),
                        out_varying=True)

    fn = ctx.compiled(_key(sendbuf, "alltoall"), build)
    to_g = ctx.to_global_hier if hier else ctx.to_global
    return ctx.my_shard(fn(to_g(sendbuf)))


def reduce_scatter_block_dev(comm, sendbuf, op=op_mod.SUM,
                             deterministic: Optional[str] = None):
    det = _det(deterministic)
    if not _op_ok(op):
        return staging.reduce_scatter_block_dev(comm, sendbuf, op)
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    if sendbuf.shape[0] % comm.size:
        raise ValueError(
            f"reduce_scatter_block: dim0 {sendbuf.shape[0]} not "
            f"divisible by comm size {comm.size}")
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]

    def build():
        return ctx.smap(
            lambda a: C.reduce_scatter(a[0], AXIS, opn, scatter_dim=0,
                                       tiled=True, deterministic=det),
            out_varying=True)

    fn = ctx.compiled(_key(sendbuf, "rsb", opn.name, det), build)
    return ctx.my_shard(fn(ctx.to_global(sendbuf)))


def scatter_dev(comm, sendbuf, root: int = 0):
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    # non-roots pass no buffer but SPMD needs same-shape operands on
    # every device: one host metadata round ships (shape, dtype), then
    # the data moves on-device (bcast-from-root + slice)
    if comm.rank == root:
        meta = (tuple(sendbuf.shape), str(sendbuf.dtype))
        comm.coll.bcast_obj(comm, meta, root)
        x = sendbuf
    else:
        shape, dtype = comm.coll.bcast_obj(comm, None, root)
        import jax.numpy as jnp

        ctx0 = _ctx(comm)
        x = ctx0.jax.device_put(jnp.zeros(shape, dtype), ctx0.my)
    if x.shape[0] % comm.size:
        raise ValueError(
            f"scatter: dim0 {x.shape[0]} not divisible by comm size "
            f"{comm.size}")
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)

    def build():
        return ctx.smap(lambda a: C.scatter(a[0], AXIS, root, 0),
                        out_varying=True)

    fn = ctx.compiled(_key(x, "scatter", root), build)
    return ctx.my_shard(fn(ctx.to_global(x)))


def scan_dev(comm, sendbuf, op=op_mod.SUM,
             deterministic: Optional[str] = None):
    """Inclusive prefix over comm ranks (lax.associative_scan under
    shard_map — log-depth on device)."""
    if not _op_ok(op):
        return staging.scan_dev(comm, sendbuf, op)
    pvar.record("coll_xla_device")
    if comm.size == 1:
        return sendbuf
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]

    def build():
        return ctx.smap(lambda a: C.scan(a[0], AXIS, opn),
                        out_varying=True)

    fn = ctx.compiled(_key(sendbuf, "scan", opn.name), build)
    return ctx.my_shard(fn(ctx.to_global(sendbuf)))


def exscan_dev(comm, sendbuf, op=op_mod.SUM,
               deterministic: Optional[str] = None):
    """Exclusive prefix; rank 0 gets zeros (MPI leaves it undefined)."""
    if not _op_ok(op):
        return staging.exscan_dev(comm, sendbuf, op)
    pvar.record("coll_xla_device")
    if comm.size == 1:
        import jax.numpy as jnp

        return jnp.zeros_like(sendbuf)
    from ompi_tpu.parallel import collectives as C

    ctx = _ctx(comm)
    opn = op if isinstance(op, op_mod.Op) else op_mod.BUILTIN[op]

    def build():
        return ctx.smap(lambda a: C.exscan(a[0], AXIS, opn),
                        out_varying=True)

    fn = ctx.compiled(_key(sendbuf, "exscan", opn.name), build)
    return ctx.my_shard(fn(ctx.to_global(sendbuf)))


@framework.register
class CollXla(CollModule):
    NAME = "xla"
    PRIORITY = 50  # above accelerator(40): device buffers stay on device

    def query(self, comm) -> int:
        if comm.size == 1:
            return self.PRIORITY  # trivial local path, no plane needed
        from ompi_tpu.runtime import device_plane

        if not device_plane.active():
            return -1
        if any(device_plane.device_for_world_rank(w) is None
               for w in comm.group.ranks):
            return -1
        return self.PRIORITY

    def slots(self, comm):
        return {
            "allreduce_dev": allreduce_dev,
            "reduce_dev": reduce_dev,
            "bcast_dev": bcast_dev,
            "allgather_dev": allgather_dev,
            "gather_dev": gather_dev,
            "alltoall_dev": alltoall_dev,
            "reduce_scatter_block_dev": reduce_scatter_block_dev,
            "scatter_dev": scatter_dev,
            "scan_dev": scan_dev,
            "exscan_dev": exscan_dev,
        }
