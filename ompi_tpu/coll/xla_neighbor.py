"""coll/xla neighborhood collectives — device-executed halo exchange.

Reference: the coll framework's neighborhood slots
(ompi/mca/coll/coll.h:600-618, implemented linearly in coll/basic over
p2p). Here a topology comm's adjacency compiles to a static schedule
of ``lax.ppermute`` rounds, so a cart/graph comm's neighbor exchange
on jax arrays runs entirely on the device plane (ICI on TPU) — the
last host-staging seam in the device path (r3 VERDICT missing #5).

Schedule construction (host side, once per (comm, shape)): the
directed edge set {(src, dst)} from the topology is greedily
edge-colored so every color class is a partial matching — unique
sources AND unique targets — which is exactly XLA CollectivePermute's
contract. One ppermute per color; a bounded-degree stencil needs
~degree rounds regardless of comm size (König: Δ colors suffice for
bipartite multigraphs; the greedy bound is < 2Δ).

Semantics on immutable arrays: results are NEW arrays with
(slot, *shape) leading-row layout matching the host recvbuf layout;
PROC_NULL slots (open cart boundaries) hold zeros (the host path
leaves those recv slots untouched — a template cannot be "untouched"
when the result is a fresh array). Ragged degrees (general graphs)
are padded to the max degree inside the compiled program and sliced
back per rank on exit.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ompi_tpu import errors
from ompi_tpu.core import pvar
from ompi_tpu.monitoring import matrix as _mon
from ompi_tpu.pml.request import PROC_NULL


class _GlobalAdj:
    """Global adjacency view for topologies that only know their own
    rank's lists (DistGraphTopo): one cached allgather round supplies
    every rank's (in, out) lists — the metadata analog of the modex
    (cached like _scatter_meta; dist-graph adjacency is immutable
    after creation, so the cache can never go stale)."""

    def __init__(self, ins, outs):
        self._ins, self._outs = ins, outs

    def in_neighbors(self, r):
        return self._ins[r]

    def out_neighbors(self, r):
        return self._outs[r]


def _global_topo(comm):
    topo = comm.topo
    if topo.kind != "dist_graph":
        return topo  # cart/graph topologies answer for any rank
    adj = getattr(comm, "_coll_xla_nbr_adj", None)
    if adj is None:
        gathered = comm.allgather(
            (list(topo.in_neighbors(comm.rank)),
             list(topo.out_neighbors(comm.rank))))
        adj = comm._coll_xla_nbr_adj = _GlobalAdj(
            [g[0] for g in gathered], [g[1] for g in gathered])
    return adj


def _edges_allgather(topo, n: int):
    """Directed edges (src, dst, dst_slot) — dst receives src's whole
    sendbuf into row dst_slot (its position in dst's in-neighbor
    list, PROC_NULL slots kept as holes)."""
    edges = []
    max_in = 0
    for d in range(n):
        nbrs = topo.in_neighbors(d)
        max_in = max(max_in, len(nbrs))
        for slot, s in enumerate(nbrs):
            if s != PROC_NULL:
                edges.append((s, d, slot))
    return edges, max_in


def _edges_alltoall(topo, n: int):
    """Directed edges (src, dst, src_slot, dst_slot): src sends row
    src_slot (its position of dst in src's out list) into dst's row
    dst_slot.

    Pairing: cartesian slots pair conjugate (in-slot j <-> the peer's
    out-slot j^1 — the (d,-1) in-edge IS the peer's (d,+1) out-edge;
    required for the periodic size-2 degenerate dim, same rule as
    basic's conjugate tags); graph/dist-graph multi-edges pair
    occurrence-by-occurrence (the standard's posted-order matching)."""
    is_cart = getattr(topo, "kind", None) == "cart"
    # per (s, d): FIFO of src slots where s lists d outbound
    out_slots = {}
    max_out = 0
    for s in range(n):
        outs = topo.out_neighbors(s)
        max_out = max(max_out, len(outs))
        for j, d in enumerate(outs):
            if d != PROC_NULL:
                out_slots.setdefault((s, d), []).append(j)
    edges = []
    max_in = 0
    for d in range(n):
        ins = topo.in_neighbors(d)
        max_in = max(max_in, len(ins))
        for slot, s in enumerate(ins):
            if s == PROC_NULL:
                continue
            if is_cart:
                edges.append((s, d, slot ^ 1, slot))
                continue
            q = out_slots.get((s, d))
            if not q:
                raise errors.MPIError(
                    errors.ERR_TOPOLOGY,
                    f"inconsistent topology: rank {d} lists {s} as an "
                    f"in-neighbor more times than {s} lists {d} "
                    "outbound")
            edges.append((s, d, q.pop(0), slot))
    return edges, max_in, max_out


def _color(edges) -> List[list]:
    """Greedy partition of directed edges into partial matchings
    (unique src + unique dst per round) — each round is one valid
    CollectivePermute."""
    remaining = list(edges)
    rounds = []
    while remaining:
        used_s, used_d, rnd, rest = set(), set(), [], []
        for e in remaining:
            if e[0] in used_s or e[1] in used_d:
                rest.append(e)
            else:
                used_s.add(e[0])
                used_d.add(e[1])
                rnd.append(e)
        rounds.append(rnd)
        remaining = rest
    return rounds


def _place(out, recvd, slot_np, tgt_np, ctx):
    """Place this round's received block into each target's slot row
    (non-targets keep `out`)."""
    import jax.numpy as jnp
    from jax import lax

    from ompi_tpu.coll.xla import AXIS

    me = lax.axis_index(AXIS)
    slot = jnp.asarray(slot_np)[me]
    is_tgt = jnp.asarray(tgt_np)[me]
    upd = lax.dynamic_update_slice_in_dim(out, recvd[None], slot,
                                          axis=0)
    return jnp.where(is_tgt, upd, out)


def neighbor_allgather_dev(comm, sendbuf):
    """Device MPI_Neighbor_allgather: returns (n_in, *sendbuf.shape)
    — row k is in-neighbor k's sendbuf (zeros for PROC_NULL slots)."""
    from jax import lax

    from ompi_tpu.coll import xla as X

    pvar.record("coll_xla_device")
    topo = _global_topo(comm)
    ctx = X._ctx(comm)
    n = ctx.n
    my_rows = len(topo.in_neighbors(comm.rank))
    tm = _mon.TRAFFIC
    if tm is not None:
        # graph edges, not an algo model: the full sendbuf goes to
        # every (non-PROC_NULL) out-neighbor
        nb = getattr(sendbuf, "nbytes", 0)
        per = {}
        for p in topo.out_neighbors(comm.rank):
            if p != PROC_NULL:
                per[p] = per.get(p, 0.0) + nb
        tm.coll("neighbor_allgather", comm, nb, per_peer=per,
                dtype=str(getattr(sendbuf, "dtype", "")))

    def build():
        import jax.numpy as jnp

        edges, max_in = _edges_allgather(topo, n)
        rounds = _color(edges)
        # per round: ppermute pairs + (slot, is-target) lookup tables
        plan = []
        for rnd in rounds:
            slot_np = np.zeros(n, np.int32)
            tgt_np = np.zeros(n, bool)
            for s, d, slot in rnd:
                slot_np[d] = slot
                tgt_np[d] = True
            plan.append(([(s, d) for s, d, _ in rnd], slot_np, tgt_np))

        def body(a):
            x = a[0]
            out = jnp.zeros((max_in,) + x.shape, x.dtype)
            for perm, slot_np, tgt_np in plan:
                recvd = lax.ppermute(x, X.AXIS, perm=perm)
                out = _place(out, recvd, slot_np, tgt_np, ctx)
            return out

        return ctx.smap(body, out_varying=True)

    fn = ctx.compiled(X._key(sendbuf, "neighbor_allgather"), build)
    out = ctx.my_shard(fn(ctx.to_global(sendbuf)))
    return out[:my_rows]


def neighbor_alltoall_dev(comm, sendbuf):
    """Device MPI_Neighbor_alltoall: ``sendbuf`` rows are per-out-
    neighbor blocks (row j to out-neighbor j); returns (n_in, *blk)
    with row k from in-neighbor k. PROC_NULL rows send nowhere /
    stay zero."""
    import jax.numpy as jnp
    from jax import lax

    from ompi_tpu.coll import xla as X

    pvar.record("coll_xla_device")
    topo = _global_topo(comm)
    ctx = X._ctx(comm)
    n = ctx.n
    my_out = len(topo.out_neighbors(comm.rank))
    my_in = len(topo.in_neighbors(comm.rank))
    if sendbuf.shape[0] != my_out:
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"neighbor_alltoall: sendbuf dim0 {sendbuf.shape[0]} != "
            f"out-degree {my_out}")
    tm = _mon.TRAFFIC
    if tm is not None:
        # one sendbuf row per out-neighbor (PROC_NULL rows go nowhere)
        rowb = (sendbuf.nbytes / sendbuf.shape[0]
                if sendbuf.shape[0] else 0.0)
        per = {}
        for p in topo.out_neighbors(comm.rank):
            if p != PROC_NULL:
                per[p] = per.get(p, 0.0) + rowb
        tm.coll("neighbor_alltoall", comm,
                getattr(sendbuf, "nbytes", 0), per_peer=per,
                dtype=str(getattr(sendbuf, "dtype", "")))
    edges, max_in, max_out = _edges_alltoall(topo, n)
    # SPMD needs uniform operand shapes: pad ragged out-degrees
    if sendbuf.shape[0] < max_out:
        pad = jnp.zeros((max_out - sendbuf.shape[0],)
                        + sendbuf.shape[1:], sendbuf.dtype)
        sendbuf = jnp.concatenate([sendbuf, pad]) if sendbuf.shape[0] \
            else jnp.zeros((max_out,) + sendbuf.shape[1:],
                           sendbuf.dtype)

    def build():
        rounds = _color(edges)
        plan = []
        for rnd in rounds:
            srow_np = np.zeros(n, np.int32)
            slot_np = np.zeros(n, np.int32)
            tgt_np = np.zeros(n, bool)
            for s, d, srow, slot in rnd:
                srow_np[s] = srow
                slot_np[d] = slot
                tgt_np[d] = True
            plan.append(([(s, d) for s, d, _, _ in rnd],
                         srow_np, slot_np, tgt_np))

        def body(a):
            x = a[0]  # (max_out, *blk)
            blk_shape = x.shape[1:]
            out = jnp.zeros((max_in,) + blk_shape, x.dtype)
            me = lax.axis_index(X.AXIS)
            for perm, srow_np, slot_np, tgt_np in plan:
                srow = jnp.asarray(srow_np)[me]
                blk = lax.dynamic_index_in_dim(x, srow, axis=0,
                                               keepdims=False)
                recvd = lax.ppermute(blk, X.AXIS, perm=perm)
                out = _place(out, recvd, slot_np, tgt_np, ctx)
            return out

        return ctx.smap(body, out_varying=True)

    fn = ctx.compiled(X._key(sendbuf, "neighbor_alltoall"), build)
    out = ctx.my_shard(fn(ctx.to_global(sendbuf)))
    return out[:my_in]


def slots(comm):
    """Neighborhood device slots — installed only on topology comms
    (the reference installs neighborhood functions at topo-comm
    creation, coll.h:600-618)."""
    if getattr(comm, "topo", None) is None:
        return {}
    return {
        "neighbor_allgather_dev": neighbor_allgather_dev,
        "neighbor_alltoall_dev": neighbor_alltoall_dev,
    }
