"""coll/tuned — the decision layer over the base algorithm library.

Reference: ompi/mca/coll/tuned — fixed decision rules keyed on communicator
size and total message bytes (coll_tuned_decision_fixed.c:55-160 for
allreduce), plus forced-algorithm MCA params
(``coll_tuned_allreduce_algorithm`` etc.) used for A/B validation.
Thresholds follow the reference's shape (small → recursive doubling /
binomial / bruck; large → ring / Rabenseifner / pairwise) with the actual
switchpoints as cvars so they can be re-tuned per fabric.
"""

from __future__ import annotations

import numpy as np

from ompi_tpu.coll import CollModule, framework
from ompi_tpu.coll import base_algos as A
from ompi_tpu.coll import basic as B
from ompi_tpu.core import cvar

_force_allreduce = cvar.register(
    "coll_tuned_allreduce_algorithm", "", str,
    help="Force: recursivedoubling|ring|rabenseifner|basic",
    choices=["", "recursivedoubling", "ring", "rabenseifner", "basic"])
_force_bcast = cvar.register(
    "coll_tuned_bcast_algorithm", "", str,
    help="Force: linear|binomial|pipeline",
    choices=["", "linear", "binomial", "pipeline"])
_force_allgather = cvar.register(
    "coll_tuned_allgather_algorithm", "", str,
    help="Force: ring|bruck|recursivedoubling|basic",
    choices=["", "ring", "bruck", "recursivedoubling", "basic"])
_force_alltoall = cvar.register(
    "coll_tuned_alltoall_algorithm", "", str,
    help="Force: pairwise|bruck|basic",
    choices=["", "pairwise", "bruck", "basic"])
_force_barrier = cvar.register(
    "coll_tuned_barrier_algorithm", "", str,
    help="Force: recursivedoubling|bruck|linear",
    choices=["", "recursivedoubling", "bruck", "linear"])

_small = cvar.register(
    "coll_tuned_small_msg", 16384, int,
    help="Bytes below which latency-optimal algorithms are used "
         "(reference switchpoint shape, decision_fixed.c)")
_pipeline_min = cvar.register(
    "coll_tuned_bcast_pipeline_min", 64 << 20, int,
    help="Bytes above which bcast switches to the segmented pipeline. "
         "High default: with smsc single-copy a binomial hop moves the "
         "whole payload in one copy (measured 1.26 GB/s vs pipeline's "
         "0.07 at 8MB/4 ranks), so segmentation only pays on streaming "
         "fabrics — lower this when smsc is off")
_bcast_segsize = cvar.register(
    "coll_tuned_bcast_segsize", 1 << 20, int,
    help="Pipeline bcast segment bytes (reference segsize params, "
         "coll_base_bcast.c). The Python per-segment cost is ~50x the "
         "reference's, so the default segment is 16x larger")
_ring_min = cvar.register(
    "coll_tuned_allreduce_ring_min", 2 << 20, int,
    help="Total bytes above which commutative allreduce uses the "
         "bandwidth-optimal ring (measured on sm+smsc: recursive "
         "doubling wins to ~1MB, ring from ~4MB; Rabenseifner trails "
         "both here and stays forced-only)")


def _bytes(count, dtype) -> int:
    return count * (dtype.size if dtype is not None else 1)


def allreduce_tuned(comm, sendbuf, recvbuf, count, dtype, op):
    forced = _force_allreduce.get()
    if forced == "basic":
        return B.allreduce_reduce_bcast(comm, sendbuf, recvbuf, count,
                                        dtype, op)
    if forced == "recursivedoubling":
        return A.allreduce_recursivedoubling(comm, sendbuf, recvbuf,
                                             count, dtype, op)
    if forced == "ring":
        return A.allreduce_ring(comm, sendbuf, recvbuf, count, dtype, op)
    if forced == "rabenseifner":
        return A.allreduce_rabenseifner(comm, sendbuf, recvbuf, count,
                                        dtype, op)
    total = _bytes(count, dtype)
    if (op.commute and comm.size > 2 and count >= comm.size
            and total >= _ring_min.get()):
        # bandwidth-bound (reference decision_fixed.c large branch):
        # ring measured fastest here at every size/rank combo tried
        return A.allreduce_ring(comm, sendbuf, recvbuf, count, dtype, op)
    return A.allreduce_recursivedoubling(comm, sendbuf, recvbuf, count,
                                         dtype, op)


def bcast_tuned(comm, buf, count, dtype, root):
    forced = _force_bcast.get()
    if forced == "linear":
        return B.bcast_linear(comm, buf, count, dtype, root)
    if forced == "binomial":
        return A.bcast_binomial(comm, buf, count, dtype, root)
    if forced == "pipeline":
        return A.bcast_pipeline(comm, buf, count, dtype, root,
                                segsize=_bcast_segsize.get())
    if _bytes(count, dtype) >= _pipeline_min.get() and comm.size > 2:
        return A.bcast_pipeline(comm, buf, count, dtype, root,
                                segsize=_bcast_segsize.get())
    return A.bcast_binomial(comm, buf, count, dtype, root)


def allgather_tuned(comm, sendbuf, recvbuf, count, dtype):
    forced = _force_allgather.get()
    if forced == "basic":
        return B.allgather_gather_bcast(comm, sendbuf, recvbuf, count,
                                        dtype)
    if forced == "ring":
        return A.allgather_ring(comm, sendbuf, recvbuf, count, dtype)
    if forced == "bruck":
        return A.allgather_bruck(comm, sendbuf, recvbuf, count, dtype)
    if forced == "recursivedoubling":
        return A.allgather_recursivedoubling(comm, sendbuf, recvbuf,
                                             count, dtype)
    if _bytes(count, dtype) <= _small.get():
        return A.allgather_bruck(comm, sendbuf, recvbuf, count, dtype)
    if comm.size & (comm.size - 1) == 0:
        # pow2: recursive doubling measured ~1.5x faster than ring at
        # every size tried (log p rounds vs p-1, same total bytes; the
        # per-round Python/handshake cost dominates on this plane)
        return A.allgather_recursivedoubling(comm, sendbuf, recvbuf,
                                             count, dtype)
    return A.allgather_ring(comm, sendbuf, recvbuf, count, dtype)


def alltoall_tuned(comm, sendbuf, recvbuf, count, dtype):
    forced = _force_alltoall.get()
    if forced == "basic":
        return B.alltoall_pairwise_isend(comm, sendbuf, recvbuf, count,
                                         dtype)
    if forced == "pairwise":
        return A.alltoall_pairwise(comm, sendbuf, recvbuf, count, dtype)
    if forced == "bruck":
        return A.alltoall_bruck(comm, sendbuf, recvbuf, count, dtype)
    if _bytes(count, dtype) <= 256 and comm.size >= 8:
        return A.alltoall_bruck(comm, sendbuf, recvbuf, count, dtype)
    return A.alltoall_pairwise(comm, sendbuf, recvbuf, count, dtype)


def barrier_tuned(comm):
    forced = _force_barrier.get()
    if forced == "linear":
        return B.barrier_linear(comm)
    if forced == "bruck":
        return A.barrier_bruck(comm)
    if forced == "recursivedoubling":
        return A.barrier_recursivedoubling(comm)
    return A.barrier_bruck(comm)


def reduce_tuned(comm, sendbuf, recvbuf, count, dtype, op, root):
    if not op.commute:
        return B.reduce_linear(comm, sendbuf, recvbuf, count, dtype, op,
                               root)
    return A.reduce_binomial(comm, sendbuf, recvbuf, count, dtype, op,
                             root)


def reduce_scatter_tuned(comm, sendbuf, recvbuf, counts, dtype, op):
    if op.commute and comm.size & (comm.size - 1) == 0:
        return A.reduce_scatter_recursivehalving(
            comm, sendbuf, recvbuf, counts, dtype, op)
    return B.reduce_scatter_basic(comm, sendbuf, recvbuf, counts, dtype,
                                  op)


def reduce_scatter_block_tuned(comm, sendbuf, recvbuf, count, dtype, op):
    if op.commute and comm.size > 2:
        return A.reduce_scatter_block_ring(comm, sendbuf, recvbuf,
                                           count, dtype, op)
    return B.reduce_scatter_block_basic(comm, sendbuf, recvbuf, count,
                                        dtype, op)


@framework.register
class CollTuned(CollModule):
    NAME = "tuned"
    PRIORITY = 30  # reference: tuned default priority 30

    def query(self, comm) -> int:
        if comm.size < 2:
            return -1  # COMM_SELF: let self/basic handle it
        return self.PRIORITY

    def slots(self, comm):
        return {
            "barrier": barrier_tuned,
            "bcast": bcast_tuned,
            "reduce": reduce_tuned,
            "allreduce": allreduce_tuned,
            "allgather": allgather_tuned,
            "alltoall": alltoall_tuned,
            "reduce_scatter": reduce_scatter_tuned,
            "reduce_scatter_block": reduce_scatter_block_tuned,
        }
