"""coll/basic — linear reference algorithms.

Reference: ompi/mca/coll/basic (4,882 LoC): naive linear/log
implementations every other component is validated against. These are the
correctness baseline: simple, deterministic operand order (rank order),
used by tests as the brute-force oracle.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ompi_tpu import op as op_mod
from ompi_tpu import pml
from ompi_tpu.coll import CollModule, framework
from ompi_tpu.core import pvar
from ompi_tpu.datatype.convertor import dtype_of

IN_PLACE = "MPI_IN_PLACE"


def _p(comm):
    return pml.current()


def _tag(comm) -> int:
    return comm.coll.next_tag()


@framework.register
class CollBasic(CollModule):
    NAME = "basic"
    PRIORITY = 10  # reference: basic priority 10

    def query(self, comm) -> int:
        return self.PRIORITY

    def slots(self, comm):
        slots = {
            "barrier": barrier_linear,
            "bcast": bcast_linear,
            "reduce": reduce_linear,
            "allreduce": allreduce_reduce_bcast,
            "gather": gather_linear,
            "gatherv": gatherv_linear,
            "scatter": scatter_linear,
            "scatterv": scatterv_linear,
            "allgather": allgather_gather_bcast,
            "allgatherv": allgatherv_linear,
            "alltoall": alltoall_pairwise_isend,
            "alltoallv": alltoallv_linear,
            "reduce_scatter": reduce_scatter_basic,
            "reduce_scatter_block": reduce_scatter_block_basic,
            "scan": scan_linear,
            "exscan": exscan_linear,
            "reduce_local": reduce_local,
            "bcast_obj": bcast_obj_linear,
            "gather_obj": gather_obj_linear,
            "scatter_obj": scatter_obj_linear,
            "allgather_obj": allgather_obj,
            "alltoall_obj": alltoall_obj,
            "allreduce_obj": allreduce_obj,
        }
        # neighborhood slots exist only on topology communicators
        # (reference: installed at topo comm creation,
        # ompi/mca/coll/coll.h:600-618); _attach re-selects the table
        # after setting comm.topo
        if getattr(comm, "topo", None) is not None:
            slots["neighbor_allgather"] = neighbor_allgather_linear
            slots["neighbor_alltoall"] = neighbor_alltoall_linear
            slots["neighbor_allgatherv"] = neighbor_allgatherv_linear
            slots["neighbor_alltoallv"] = neighbor_alltoallv_linear
        return slots


# -- p2p building blocks (always collective context) ----------------------

def _send(comm, buf, count, dtype, dst, tag):
    _p(comm).send(comm, buf, count, dtype, dst, tag, collective=True)


def _recv(comm, buf, count, dtype, src, tag):
    return _p(comm).recv(comm, buf, count, dtype, src, tag,
                         collective=True)


def _isend(comm, buf, count, dtype, dst, tag):
    return _p(comm).isend(comm, buf, count, dtype, dst, tag,
                          collective=True)


def _irecv(comm, buf, count, dtype, src, tag):
    return _p(comm).irecv(comm, buf, count, dtype, src, tag,
                          collective=True)


def _send_obj(comm, obj, dst, tag):
    _p(comm).send_obj(comm, obj, dst, tag, collective=True)


def _recv_obj(comm, src, tag):
    return _p(comm).recv_obj(comm, src, tag, collective=True)


# -- collectives ----------------------------------------------------------

def barrier_linear(comm) -> None:
    """Linear barrier: gather-to-0 then release (coll_basic_barrier.c)."""
    pvar.record("barrier")
    tag = _tag(comm)
    token = np.zeros(1, dtype=np.uint8)
    if comm.rank == 0:
        for r in range(1, comm.size):
            _recv(comm, token, 1, None, r, tag)
        for r in range(1, comm.size):
            _send(comm, token, 1, None, r, tag)
    elif comm.size > 1:
        _send(comm, token, 1, None, 0, tag)
        _recv(comm, token, 1, None, 0, tag)


def bcast_linear(comm, buf, count, dtype, root: int) -> None:
    pvar.record("bcast")
    tag = _tag(comm)
    if comm.rank == root:
        reqs = [_isend(comm, buf, count, dtype, r, tag)
                for r in range(comm.size) if r != root]
        for q in reqs:
            q.wait()
    else:
        _recv(comm, buf, count, dtype, root, tag)


def reduce_linear(comm, sendbuf, recvbuf, count, dtype, op, root: int):
    """Deterministic rank-order reduction (coll_basic_reduce.c)."""
    pvar.record("reduce")
    tag = _tag(comm)
    sb = np.asarray(sendbuf) if sendbuf is not IN_PLACE else \
        np.asarray(recvbuf)
    if comm.rank == root:
        # blocking recvs arrive in ascending rank order, so fold
        # incrementally — identical deterministic order, O(N) memory
        tmp = np.empty_like(sb)
        result = None
        for r in range(comm.size):
            if r == root:
                contrib = sb
            else:
                _recv(comm, tmp, count, dtype, r, tag)
                contrib = tmp
            result = contrib.copy() if result is None \
                else op.np_fn(result, contrib)
        np.copyto(np.asarray(recvbuf), result, casting="same_kind")
    else:
        _send(comm, sb, count, dtype, root, tag)


def allreduce_reduce_bcast(comm, sendbuf, recvbuf, count, dtype, op):
    pvar.record("allreduce")
    reduce_linear(comm, sendbuf, recvbuf, count, dtype, op, 0)
    bcast_linear(comm, recvbuf, count, dtype, 0)


def gather_linear(comm, sendbuf, recvbuf, count, dtype, root: int):
    """recvbuf at root: shaped (size * count) elements."""
    pvar.record("gather")
    tag = _tag(comm)
    sb = np.asarray(sendbuf)
    if comm.rank == root:
        rb = np.asarray(recvbuf).reshape(comm.size, -1)
        rb[root][:] = sb.reshape(-1)
        reqs = [(r, _irecv(comm, rb[r], count, dtype, r, tag))
                for r in range(comm.size) if r != root]
        for _, q in reqs:
            q.wait()
    else:
        _send(comm, sb, count, dtype, root, tag)


def gatherv_linear(comm, sendbuf, recvbuf, counts, displs, dtype,
                   root: int):
    pvar.record("gather")
    tag = _tag(comm)
    sb = np.asarray(sendbuf)
    if comm.rank == root:
        rb = np.asarray(recvbuf).reshape(-1)
        rb[displs[root]:displs[root] + counts[root]] = sb.reshape(-1)
        reqs = []
        for r in range(comm.size):
            if r == root:
                continue
            view = rb[displs[r]:displs[r] + counts[r]]
            reqs.append(_irecv(comm, view, counts[r], dtype, r, tag))
        for q in reqs:
            q.wait()
    else:
        _send(comm, sb, len(sb.reshape(-1)), dtype, root, tag)


def scatter_linear(comm, sendbuf, recvbuf, count, dtype, root: int):
    pvar.record("scatter")
    tag = _tag(comm)
    rb = np.asarray(recvbuf)
    if comm.rank == root:
        sb = np.asarray(sendbuf).reshape(comm.size, -1)
        reqs = [_isend(comm, sb[r], count, dtype, r, tag)
                for r in range(comm.size) if r != root]
        rb.reshape(-1)[:] = sb[root]
        for q in reqs:
            q.wait()
    else:
        _recv(comm, rb, count, dtype, root, tag)


def scatterv_linear(comm, sendbuf, recvbuf, counts, displs, dtype,
                    root: int):
    pvar.record("scatter")
    tag = _tag(comm)
    rb = np.asarray(recvbuf)
    if comm.rank == root:
        sb = np.asarray(sendbuf).reshape(-1)
        reqs = []
        for r in range(comm.size):
            view = sb[displs[r]:displs[r] + counts[r]]
            if r == root:
                rb.reshape(-1)[:counts[r]] = view
            else:
                reqs.append(_isend(comm, view.copy(), counts[r], dtype,
                                   r, tag))
        for q in reqs:
            q.wait()
    else:
        _recv(comm, rb, len(rb.reshape(-1)), dtype, root, tag)


def allgather_gather_bcast(comm, sendbuf, recvbuf, count, dtype):
    pvar.record("allgather")
    gather_linear(comm, sendbuf, recvbuf, count, dtype, 0)
    bcast_linear(comm, recvbuf, count * comm.size, dtype, 0)


def allgatherv_linear(comm, sendbuf, recvbuf, counts, displs, dtype):
    pvar.record("allgather")
    gatherv_linear(comm, sendbuf, recvbuf, counts, displs, dtype, 0)
    total = max(displs[r] + counts[r] for r in range(comm.size))
    bcast_linear(comm, np.asarray(recvbuf).reshape(-1)[:total], total,
                 dtype, 0)


def alltoall_pairwise_isend(comm, sendbuf, recvbuf, count, dtype):
    """All nonblocking at once (coll_basic_alltoall linear)."""
    pvar.record("alltoall")
    tag = _tag(comm)
    sb = np.asarray(sendbuf).reshape(comm.size, -1)
    rb = np.asarray(recvbuf).reshape(comm.size, -1)
    rb[comm.rank][:] = sb[comm.rank]
    rreqs = [(r, _irecv(comm, rb[r], count, dtype, r, tag))
             for r in range(comm.size) if r != comm.rank]
    sreqs = [_isend(comm, sb[r], count, dtype, r, tag)
             for r in range(comm.size) if r != comm.rank]
    for _, q in rreqs:
        q.wait()
    for q in sreqs:
        q.wait()


def alltoallv_linear(comm, sendbuf, recvbuf, scounts, sdispls,
                     rcounts, rdispls, dtype):
    pvar.record("alltoall")
    tag = _tag(comm)
    sb = np.asarray(sendbuf).reshape(-1)
    rb = np.asarray(recvbuf).reshape(-1)
    me = comm.rank
    rb[rdispls[me]:rdispls[me] + rcounts[me]] = \
        sb[sdispls[me]:sdispls[me] + scounts[me]]
    rreqs = []
    for r in range(comm.size):
        if r == me:
            continue
        view = rb[rdispls[r]:rdispls[r] + rcounts[r]]
        rreqs.append(_irecv(comm, view, rcounts[r], dtype, r, tag))
    sreqs = []
    for r in range(comm.size):
        if r == me:
            continue
        view = sb[sdispls[r]:sdispls[r] + scounts[r]].copy()
        sreqs.append(_isend(comm, view, scounts[r], dtype, r, tag))
    for q in rreqs:
        q.wait()
    for q in sreqs:
        q.wait()


def reduce_scatter_block_basic(comm, sendbuf, recvbuf, count, dtype, op):
    """reduce at 0 + scatter (coll_basic_reduce_scatter_block.c)."""
    pvar.record("reduce_scatter")
    sb = np.asarray(sendbuf)
    total = np.empty_like(sb) if comm.rank == 0 else sb
    reduce_linear(comm, sb, total, count * comm.size, dtype, op, 0)
    scatter_linear(comm, total if comm.rank == 0 else None, recvbuf,
                   count, dtype, 0)


def reduce_scatter_basic(comm, sendbuf, recvbuf, counts, dtype, op):
    """MPI_Reduce_scatter with per-rank counts: reduce + scatterv."""
    pvar.record("reduce_scatter")
    sb = np.asarray(sendbuf)
    total = np.empty_like(sb) if comm.rank == 0 else sb
    reduce_linear(comm, sb, total, int(sum(counts)), dtype, op, 0)
    displs = np.concatenate(
        [[0], np.cumsum(counts[:-1], dtype=np.intp)]).tolist()
    scatterv_linear(comm, total if comm.rank == 0 else None, recvbuf,
                    counts, displs, dtype, 0)


def scan_linear(comm, sendbuf, recvbuf, count, dtype, op):
    """MPI_Scan: inclusive prefix in rank order."""
    pvar.record("scan")
    tag = _tag(comm)
    sb = np.asarray(sendbuf)
    rb = np.asarray(recvbuf)
    if comm.rank == 0:
        np.copyto(rb, sb, casting="same_kind")
    else:
        prev = np.empty_like(rb)
        _recv(comm, prev, count, dtype, comm.rank - 1, tag)
        np.copyto(rb, op.np_fn(prev, sb), casting="same_kind")
    if comm.rank + 1 < comm.size:
        _send(comm, rb, count, dtype, comm.rank + 1, tag)


def exscan_linear(comm, sendbuf, recvbuf, count, dtype, op):
    pvar.record("exscan")
    tag = _tag(comm)
    sb = np.asarray(sendbuf)
    rb = np.asarray(recvbuf)
    if comm.rank > 0:
        _recv(comm, rb, count, dtype, comm.rank - 1, tag)
    if comm.rank + 1 < comm.size:
        nxt = sb if comm.rank == 0 else op.np_fn(rb, sb)
        _send(comm, np.ascontiguousarray(nxt), count, dtype,
              comm.rank + 1, tag)


def reduce_local(comm, inbuf, inoutbuf, count, dtype, op):
    op_mod.reduce_local(np.asarray(inbuf), np.asarray(inoutbuf), op)


# -- object variants ------------------------------------------------------

def bcast_obj_linear(comm, obj, root: int):
    tag = _tag(comm)
    if comm.rank == root:
        for r in range(comm.size):
            if r != root:
                _send_obj(comm, obj, r, tag)
        return obj
    return _recv_obj(comm, root, tag)


def gather_obj_linear(comm, obj, root: int) -> Optional[List[Any]]:
    tag = _tag(comm)
    if comm.rank == root:
        out: List[Any] = [None] * comm.size
        out[root] = obj
        for r in range(comm.size):
            if r != root:
                out[r] = _recv_obj(comm, r, tag)
        return out
    _send_obj(comm, obj, root, tag)
    return None


def scatter_obj_linear(comm, objs, root: int):
    tag = _tag(comm)
    if comm.rank == root:
        for r in range(comm.size):
            if r != root:
                _send_obj(comm, objs[r], r, tag)
        return objs[root]
    return _recv_obj(comm, root, tag)


def allgather_obj(comm, obj) -> List[Any]:
    got = gather_obj_linear(comm, obj, 0)
    return bcast_obj_linear(comm, got, 0)


def alltoall_obj(comm, objs) -> List[Any]:
    tag = _tag(comm)
    me = comm.rank
    out: List[Any] = [None] * comm.size
    out[me] = objs[me]
    sreqs = [_p(comm).isend_obj(comm, objs[r], r, tag, collective=True)
             for r in range(comm.size) if r != me]
    for r in range(comm.size):
        if r != me:
            out[r] = _recv_obj(comm, r, tag)
    for q in sreqs:
        q.wait()
    return out


def allreduce_obj(comm, obj, fn):
    """Generic python-object allreduce with a binary fn."""
    vals = allgather_obj(comm, obj)
    acc = vals[0]
    for v in vals[1:]:
        acc = fn(acc, v)
    return acc


# -- neighborhood collectives (topology comms only) -----------------------
#
# Reference: ompi/mca/coll/basic neighbor_allgather/alltoall — linear
# isend/irecv over the topology's neighbor lists in MPI-standard order.
# Cartesian degenerate case (periodic dim of size 2: both directions hit
# the same rank) is disambiguated with per-edge conjugate tags: the
# sender tags with its out-slot, the receiver matches its in-slot j
# against the sender's conjugate slot (j ^ 1 — the (d,-1) in-edge is the
# peer's (d,+1) out-edge).

def _nbr_tags(comm, topo):
    base = _tag(comm)
    if getattr(topo, "kind", None) == "cart":
        send_tag = lambda slot: (base + 1 + slot) & 0x3FFFFFFF
        recv_tag = lambda slot: (base + 1 + (slot ^ 1)) & 0x3FFFFFFF
    else:
        # graph/dist_graph: duplicate edges match in posted order
        # (FIFO per (ctx, src, tag) — the standard's behavior)
        send_tag = recv_tag = lambda slot: base
    return send_tag, recv_tag


def neighbor_allgather_reqs(comm, sendbuf, recvbuf, count, dtype):
    """Post the allgather's isend/irecv set (one linear round); the
    blocking form waits it, the ineighbor form yields it as a
    schedule round."""
    from ompi_tpu.pml.request import PROC_NULL

    pvar.record("neighbor_allgather")
    topo = comm.topo
    ins = topo.in_neighbors(comm.rank)
    outs = topo.out_neighbors(comm.rank)
    send_tag, recv_tag = _nbr_tags(comm, topo)
    sb = np.asarray(sendbuf)
    # zero-degree ranks are legal (receive-only/send-only dist graphs)
    rb = np.asarray(recvbuf).reshape(len(ins), -1) if ins else None
    rreqs = [q for q in (
        _irecv(comm, rb[i], count, dtype, src, recv_tag(i))
        for i, src in enumerate(ins) if src != PROC_NULL)]
    sreqs = [_isend(comm, sb, count, dtype, dst, send_tag(i))
             for i, dst in enumerate(outs) if dst != PROC_NULL]
    return rreqs + sreqs


def neighbor_alltoall_reqs(comm, sendbuf, recvbuf, count, dtype):
    from ompi_tpu.pml.request import PROC_NULL

    pvar.record("neighbor_alltoall")
    topo = comm.topo
    ins = topo.in_neighbors(comm.rank)
    outs = topo.out_neighbors(comm.rank)
    send_tag, recv_tag = _nbr_tags(comm, topo)
    # zero-degree ranks are legal (receive-only/send-only dist graphs)
    sb = np.asarray(sendbuf).reshape(len(outs), -1) if outs else None
    rb = np.asarray(recvbuf).reshape(len(ins), -1) if ins else None
    rreqs = [q for q in (
        _irecv(comm, rb[i], count, dtype, src, recv_tag(i))
        for i, src in enumerate(ins) if src != PROC_NULL)]
    sreqs = [_isend(comm, sb[i], count, dtype, dst, send_tag(i))
             for i, dst in enumerate(outs) if dst != PROC_NULL]
    return rreqs + sreqs


def neighbor_allgatherv_reqs(comm, sendbuf, recvbuf, count, dtype,
                             rcounts, rdispls):
    """MPI_Neighbor_allgatherv (ompi/mpi/c/neighbor_allgatherv.c):
    the same ``count``-element send goes to every out-neighbor;
    per-in-neighbor rcounts/rdispls (ELEMENT units) place the ragged
    blocks in recvbuf."""
    from ompi_tpu.pml.request import PROC_NULL

    pvar.record("neighbor_allgatherv")
    topo = comm.topo
    ins = topo.in_neighbors(comm.rank)
    outs = topo.out_neighbors(comm.rank)
    send_tag, recv_tag = _nbr_tags(comm, topo)
    sb = np.asarray(sendbuf)
    rb = np.asarray(recvbuf).reshape(-1)
    rreqs = [
        _irecv(comm, rb[rdispls[i]:rdispls[i] + rcounts[i]],
               rcounts[i], dtype, src, recv_tag(i))
        for i, src in enumerate(ins)
        if src != PROC_NULL and rcounts[i]]
    # zero-count skip must be SYMMETRIC with the recv side (peers
    # pass rcounts[i]==0 for our count==0): an unguarded send would
    # sit unmatched in their unexpected queues forever
    sreqs = [_isend(comm, sb, count, dtype, dst, send_tag(i))
             for i, dst in enumerate(outs)
             if dst != PROC_NULL and count]
    return rreqs + sreqs


def neighbor_alltoallv_reqs(comm, sendbuf, recvbuf, dtype, scounts,
                            sdispls, rcounts, rdispls):
    """MPI_Neighbor_alltoallv (ompi/mpi/c/neighbor_alltoallv.c):
    per-out-neighbor send segments and per-in-neighbor receive
    segments, both addressed by counts/displs in ELEMENT units."""
    from ompi_tpu.pml.request import PROC_NULL

    pvar.record("neighbor_alltoallv")
    topo = comm.topo
    ins = topo.in_neighbors(comm.rank)
    outs = topo.out_neighbors(comm.rank)
    send_tag, recv_tag = _nbr_tags(comm, topo)
    sb = np.asarray(sendbuf).reshape(-1)
    rb = np.asarray(recvbuf).reshape(-1)
    rreqs = [
        _irecv(comm, rb[rdispls[i]:rdispls[i] + rcounts[i]],
               rcounts[i], dtype, src, recv_tag(i))
        for i, src in enumerate(ins)
        if src != PROC_NULL and rcounts[i]]
    sreqs = [
        _isend(comm, sb[sdispls[i]:sdispls[i] + scounts[i]],
               scounts[i], dtype, dst, send_tag(i))
        for i, dst in enumerate(outs)
        if dst != PROC_NULL and scounts[i]]
    return rreqs + sreqs


def _wait_reqs(reqs) -> None:
    for q in reqs:
        q.wait()


def neighbor_allgather_linear(comm, sendbuf, recvbuf, count, dtype):
    _wait_reqs(neighbor_allgather_reqs(comm, sendbuf, recvbuf, count,
                                       dtype))


def neighbor_alltoall_linear(comm, sendbuf, recvbuf, count, dtype):
    _wait_reqs(neighbor_alltoall_reqs(comm, sendbuf, recvbuf, count,
                                      dtype))


def neighbor_allgatherv_linear(comm, sendbuf, recvbuf, count, dtype,
                               rcounts, rdispls):
    _wait_reqs(neighbor_allgatherv_reqs(comm, sendbuf, recvbuf, count,
                                        dtype, rcounts, rdispls))


def neighbor_alltoallv_linear(comm, sendbuf, recvbuf, dtype, scounts,
                              sdispls, rcounts, rdispls):
    _wait_reqs(neighbor_alltoallv_reqs(comm, sendbuf, recvbuf, dtype,
                                       scounts, sdispls, rcounts,
                                       rdispls))
