"""Collectives framework — per-communicator priority-stacked tables.

Reference: ompi/mca/coll/ — coll.h:532-649 (the per-comm function table),
coll_base_comm_select.c:236-330 (all enabled components stacked in
ascending priority, each overriding the slots it implements; disqualify on
priority<0). Components here: ``basic`` (linear reference algorithms),
``tuned`` (decision rules over the base algorithm library), ``libnbc``
(nonblocking schedules), ``accelerator`` (device-buffer staging
fallback), ``xla`` (device-executed collectives over the
multi-controller device plane — the north star), ``inter``
(group-vs-group algorithms for intercommunicators), ``han``
(hierarchical node×network compositions), ``adapt`` (event-driven
segmented ibcast/ireduce, opt-in), ``sync`` (barrier-injection
debug interposition). COMM_SELF/size-1 comms are served by basic's
linear paths and xla's local fast path (no separate ``self`` component
needed).

Collective p2p traffic runs in the communicator's collective context
(cid*2+1) with a per-comm monotonically increasing operation tag, so user
p2p can never interfere (reference uses the same split tag space).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ompi_tpu.core import output, registry

framework = registry.framework("coll")
_out = output.stream("coll_base")

#: every slot a component may install (blocking + object + nonblocking
#: variants are derived); mirrors coll.h's function-pointer members
SLOTS = (
    "barrier", "bcast", "reduce", "allreduce", "gather", "gatherv",
    "scatter", "scatterv", "allgather", "allgatherv", "alltoall",
    "alltoallv", "reduce_scatter", "reduce_scatter_block", "scan",
    "exscan", "reduce_local",
    # object (pickled) variants
    "bcast_obj", "gather_obj", "scatter_obj", "allgather_obj",
    "alltoall_obj", "allreduce_obj",
    # ULFM agreement
    "agree",
    # neighborhood (installed when a topology is attached)
    "neighbor_allgather", "neighbor_alltoall",
    "neighbor_allgatherv", "neighbor_alltoallv",
    # device-buffer variants (coll/accelerator staging; return new
    # device arrays — PJRT buffers are immutable)
    "allreduce_dev", "bcast_dev", "reduce_dev", "allgather_dev",
    "alltoall_dev", "reduce_scatter_block_dev", "scatter_dev",
    "gather_dev", "scan_dev", "exscan_dev",
    # fused (bucketed) device allreduce over a list/pytree of buffers
    # + its MPI-4 persistent form (gradient-bucketing hot path)
    "allreduce_multi_dev", "allreduce_multi_init_dev",
    # MPI-4 partitioned fused allreduce (part/ subsystem device
    # payoff): per-leaf Pready, bucket flushes on last-member ready
    "pallreduce_init_dev",
    # zero/ sharded data parallel: bucketed reduce_scatter returning
    # per-rank ShardedState shards, the allgather that rebuilds the
    # pytree, their persistent forms, and the partitioned RS
    "reduce_scatter_multi_dev", "reduce_scatter_multi_init_dev",
    "allgather_multi_dev", "allgather_multi_init_dev",
    "preduce_scatter_init_dev",
    # coll/pallas fused compute+comm kernels: reduce_scatter fused
    # with the ZeRO shard update, matmul-overlapped allgather (TP)
    "fused_rs_update_dev", "allgather_matmul_dev",
)


class CollModule(registry.Component):
    """A coll component instance; query() returns per-comm priority."""

    #: intra-group algorithms are wrong on intercommunicators — only
    #: components that implement group-vs-group semantics (coll/inter)
    #: opt in. Enforced centrally by comm_select, so components that
    #: override query() cannot forget the check (reference: the inter
    #: component's comm_query gate).
    INTER_OK = False

    def query(self, comm) -> int:
        """Return priority for this comm, or <0 to disqualify
        (reference: coll_base_comm_select.c:456-471)."""
        return self.PRIORITY

    def slots(self, comm) -> Dict[str, callable]:
        """The function slots this module installs for this comm."""
        return {}


class CollTable:
    """The stacked per-communicator table (comm.coll)."""

    def __init__(self) -> None:
        self.fns: Dict[str, callable] = {}
        self.providers: Dict[str, str] = {}
        self.seq = 0  # per-comm collective operation sequence -> tag

    def next_tag(self) -> int:
        self.seq += 1
        return self.seq & 0x3FFFFFFF

    def __getattr__(self, name):
        try:
            return self.fns[name]
        except KeyError:
            raise NotImplementedError(
                f"no coll component provides '{name}'") from None


def comm_select(comm) -> None:
    """Stack all qualifying components in ascending priority
    (higher priority installs last, overriding lower)."""
    table = CollTable()
    comps = framework.open_components()
    ranked = []
    is_inter = getattr(comm, "is_inter", False)
    for comp in comps:
        if not isinstance(comp, CollModule):
            continue
        if is_inter and not comp.INTER_OK:
            continue  # central gate: intra algorithms never stack on
            # an intercomm, regardless of the component's own query()
        try:
            pri = comp.query(comm)
        except Exception as exc:
            _out.verbose(1, "component %s query failed: %s",
                         comp.NAME, exc)
            continue
        if pri is None or pri < 0:
            continue
        ranked.append((pri, comp))
    ranked.sort(key=lambda t: t[0])  # ascending: high pri wins
    for pri, comp in ranked:
        for slot, fn in comp.slots(comm).items():
            table.fns[slot] = fn
            table.providers[slot] = comp.NAME
    # interposition hook: components like coll/sync wrap the finished
    # table rather than installing slots of their own
    for pri, comp in ranked:
        hook = getattr(comp, "post_stack", None)
        if hook is not None:
            hook(comm, table)
    comm.coll = table
    _out.verbose(5, "comm %s coll table: %s", getattr(comm, "name", "?"),
                 {s: table.providers.get(s) for s in table.fns})


def _register_builtin() -> None:
    from ompi_tpu.coll import (  # noqa: F401
        accelerator, adapt, basic, han, hier, inter, libnbc, pallas,
        sync, tuned, xla,
    )


_register_builtin()
