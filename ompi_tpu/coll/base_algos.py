"""Base collective algorithm library.

Reference: ompi/mca/coll/base/ (~70 ompi_coll_base_*_intra_* variants,
13,820 LoC): allreduce {recursivedoubling coll_base_allreduce.c:217, ring
:974, redscat_allgather (Rabenseifner) :1267}, bcast {binomial, pipeline,
scatter_allgather, coll_base_bcast.c:720-951}, allgather {ring,
recursivedoubling, bruck}, alltoall {bruck, pairwise,
coll_base_alltoall.c:180-616}, reduce_scatter {recursivehalving, ring},
barrier {recursivedoubling, bruck/dissemination, coll_base_barrier.c}.

All algorithms run over the PML in the communicator's collective context
and are validated against coll/basic in tests (the reference's own
A/B-testing strategy via forced-algorithm params).
"""

from __future__ import annotations

import numpy as np

from ompi_tpu import pml
from ompi_tpu.core import pvar

from ompi_tpu.coll.basic import (
    IN_PLACE, _irecv, _isend, _recv, _send, _tag,
)


def _sbuf(sendbuf, recvbuf):
    """Resolve MPI_IN_PLACE."""
    if sendbuf is IN_PLACE or sendbuf is None:
        return np.asarray(recvbuf)
    return np.asarray(sendbuf)


def _sendrecv(comm, sarr, dst, rarr, src, tag):
    rq = _irecv(comm, rarr, rarr.size, None, src, tag)
    sq = _isend(comm, sarr, sarr.size, None, dst, tag)
    rq.wait()
    sq.wait()


# ---------------------------------------------------------------------------
# barrier
# ---------------------------------------------------------------------------

def barrier_recursivedoubling(comm) -> None:
    """coll_base_barrier.c recursive doubling (power-of-2 w/ fold)."""
    pvar.record("barrier")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    tok = np.zeros(1, dtype=np.uint8)
    rtok = np.zeros(1, dtype=np.uint8)
    adjsize = 1
    while adjsize * 2 <= size:
        adjsize *= 2
    extra = size - adjsize
    if rank < 2 * extra:
        if rank % 2 == 1:  # odd of the folded pairs: passive
            _send(comm, tok, 1, None, rank - 1, tag)
            _recv(comm, rtok, 1, None, rank - 1, tag)
            return
        _recv(comm, rtok, 1, None, rank + 1, tag)
    new_rank = rank // 2 if rank < 2 * extra else rank - extra
    mask = 1
    while mask < adjsize:
        peer_new = new_rank ^ mask
        peer = peer_new * 2 if peer_new < extra else peer_new + extra
        _sendrecv(comm, tok, peer, rtok, peer, tag)
        mask <<= 1
    if rank < 2 * extra and rank % 2 == 0:
        _send(comm, tok, 1, None, rank + 1, tag)


def barrier_bruck(comm) -> None:
    """Dissemination barrier (coll_base_barrier.c bruck)."""
    pvar.record("barrier")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    tok = np.zeros(1, dtype=np.uint8)
    rtok = np.zeros(1, dtype=np.uint8)
    dist = 1
    while dist < size:
        to = (rank + dist) % size
        frm = (rank - dist + size) % size
        rq = _irecv(comm, rtok, 1, None, frm, tag)
        sq = _isend(comm, tok, 1, None, to, tag)
        rq.wait()
        sq.wait()
        dist <<= 1


# ---------------------------------------------------------------------------
# bcast
# ---------------------------------------------------------------------------

def bcast_binomial(comm, buf, count, dtype, root: int) -> None:
    """Binomial tree bcast (coll_base_bcast.c binomial)."""
    pvar.record("bcast")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    vrank = (rank - root + size) % size
    arr = np.asarray(buf)
    # receive from parent (the lowest set bit names it)
    if vrank != 0:
        mask = 1
        while not (vrank & mask):
            mask <<= 1
        parent = (vrank - mask + root) % size
        _recv(comm, arr, count, dtype, parent, tag)
    # forward to children vrank+m for every m below my lowest set bit
    reqs = []
    m = 1
    while m < size:
        if vrank & m:
            break
        if vrank + m < size:
            child = (vrank + m + root) % size
            reqs.append(_isend(comm, arr, count, dtype, child, tag))
        m <<= 1
    for q in reversed(reqs):
        q.wait()


def bcast_pipeline(comm, buf, count, dtype, root: int,
                   segsize: int = 65536) -> None:
    """Segmented chain pipeline (coll_base_bcast.c pipeline): rank i
    receives from i-1 and forwards to i+1 segment by segment — O(1/p)
    working set, the long-message schedule ring-attention reuses."""
    pvar.record("bcast")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    if size == 1:
        return
    vrank = (rank - root + size) % size
    prev = (rank - 1 + size) % size
    nxt = (rank + 1) % size
    flat = np.asarray(buf).reshape(-1)
    elem = flat.itemsize
    seg_elems = max(1, segsize // elem)
    nseg = (flat.size + seg_elems - 1) // seg_elems
    pending = None
    for s in range(nseg):
        lo, hi = s * seg_elems, min((s + 1) * seg_elems, flat.size)
        seg = flat[lo:hi]
        if vrank != 0:
            _recv(comm, seg, hi - lo, dtype, prev, tag)
        if vrank != size - 1:
            if pending is not None:
                pending.wait()
            pending = _isend(comm, seg, hi - lo, dtype, nxt, tag)
    if pending is not None:
        pending.wait()


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def allreduce_recursivedoubling(comm, sendbuf, recvbuf, count, dtype, op):
    """coll_base_allreduce.c:217 — log(p) exchange, good for small msgs."""
    pvar.record("allreduce")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf)
    sb = _sbuf(sendbuf, recvbuf)
    if rb is not sb:
        np.copyto(rb, sb, casting="same_kind")
    tmp = np.empty_like(rb)
    adjsize = 1
    while adjsize * 2 <= size:
        adjsize *= 2
    extra = size - adjsize
    if rank < 2 * extra:
        if rank % 2 == 1:
            _send(comm, rb, count, dtype, rank - 1, tag)
            _recv(comm, rb, count, dtype, rank - 1, tag)
            return
        _recv(comm, tmp, count, dtype, rank + 1, tag)
        # deterministic operand order: lower rank is left operand
        rb[...] = op.np_fn(rb, tmp)
    new_rank = rank // 2 if rank < 2 * extra else rank - extra
    mask = 1
    while mask < adjsize:
        peer_new = new_rank ^ mask
        peer = peer_new * 2 if peer_new < extra else peer_new + extra
        _sendrecv(comm, rb, peer, tmp, peer, tag)
        if peer_new < new_rank:
            rb[...] = op.np_fn(tmp, rb)
        else:
            rb[...] = op.np_fn(rb, tmp)
        mask <<= 1
    if rank < 2 * extra and rank % 2 == 0:
        _send(comm, rb, count, dtype, rank + 1, tag)


def allreduce_ring(comm, sendbuf, recvbuf, count, dtype, op):
    """coll_base_allreduce.c:974 — bandwidth-optimal reduce-scatter +
    allgather ring (the NCCL-style schedule)."""
    pvar.record("allreduce")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf).reshape(-1)
    sb = _sbuf(sendbuf, recvbuf).reshape(-1)
    if size == 1:
        if rb is not sb:
            rb[:] = sb
        return
    if rb is not sb:
        np.copyto(rb, sb, casting="same_kind")
    # chunk boundaries (count may not divide evenly)
    bounds = np.linspace(0, rb.size, size + 1).astype(np.int64)
    chunks = [(int(bounds[i]), int(bounds[i + 1])) for i in range(size)]
    nxt = (rank + 1) % size
    prv = (rank - 1 + size) % size
    maxchunk = max(hi - lo for lo, hi in chunks)
    tmp = np.empty(maxchunk, dtype=rb.dtype)
    # phase 1: reduce-scatter; after size-1 steps rank owns chunk
    # (rank+1)%size fully reduced
    for step in range(size - 1):
        send_idx = (rank - step + size) % size
        recv_idx = (rank - step - 1 + size) % size
        slo, shi = chunks[send_idx]
        rlo, rhi = chunks[recv_idx]
        view = tmp[:rhi - rlo]
        rq = _irecv(comm, view, rhi - rlo, dtype, prv, tag)
        sq = _isend(comm, rb[slo:shi].copy(), shi - slo, dtype, nxt, tag)
        rq.wait()
        sq.wait()
        rb[rlo:rhi] = op.np_fn(view, rb[rlo:rhi])
    # phase 2: allgather ring
    for step in range(size - 1):
        send_idx = (rank + 1 - step + size) % size
        recv_idx = (rank - step + size) % size
        slo, shi = chunks[send_idx]
        rlo, rhi = chunks[recv_idx]
        view = tmp[:rhi - rlo]
        rq = _irecv(comm, view, rhi - rlo, dtype, prv, tag)
        sq = _isend(comm, rb[slo:shi].copy(), shi - slo, dtype, nxt, tag)
        rq.wait()
        sq.wait()
        rb[rlo:rhi] = view


def allreduce_rabenseifner(comm, sendbuf, recvbuf, count, dtype, op):
    """coll_base_allreduce.c:1267 redscat_allgather — recursive halving
    reduce-scatter + recursive doubling allgather (power-of-2 folded)."""
    pvar.record("allreduce")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf).reshape(-1)
    sb = _sbuf(sendbuf, recvbuf).reshape(-1)
    if rb is not sb:
        np.copyto(rb, sb, casting="same_kind")
    if size == 1:
        return
    adjsize = 1
    while adjsize * 2 <= size:
        adjsize *= 2
    extra = size - adjsize
    tmp = np.empty_like(rb)
    # fold extras
    if rank < 2 * extra:
        if rank % 2 == 1:
            _send(comm, rb, count, dtype, rank - 1, tag)
            _recv(comm, rb, count, dtype, rank - 1, tag)
            return
        _recv(comm, tmp, count, dtype, rank + 1, tag)
        rb[...] = op.np_fn(rb, tmp)
    new_rank = rank // 2 if rank < 2 * extra else rank - extra

    def real(nr: int) -> int:
        return nr * 2 if nr < extra else nr + extra

    def segment(nr: int, down_to: int):
        """The data range rank ``nr`` is responsible for once the
        halving has descended to granularity ``down_to`` (handles
        counts not divisible by powers of two)."""
        s_lo, s_hi = 0, rb.size
        m = adjsize // 2
        while m >= down_to:
            s_mid = s_lo + (s_hi - s_lo) // 2
            if nr & m:
                s_lo = s_mid
            else:
                s_hi = s_mid
            m >>= 1
        return s_lo, s_hi

    # recursive halving reduce-scatter over adjsize ranks
    mask = adjsize // 2
    while mask >= 1:
        peer_new = new_rank ^ mask
        peer = real(peer_new)
        keep_lo, keep_hi = segment(new_rank, mask)
        give_lo, give_hi = segment(peer_new, mask)
        view = tmp[keep_lo:keep_hi]
        rq = _irecv(comm, view, keep_hi - keep_lo, dtype, peer, tag)
        sq = _isend(comm, rb[give_lo:give_hi].copy(),
                    give_hi - give_lo, dtype, peer, tag)
        rq.wait()
        sq.wait()
        if peer_new < new_rank:
            rb[keep_lo:keep_hi] = op.np_fn(view, rb[keep_lo:keep_hi])
        else:
            rb[keep_lo:keep_hi] = op.np_fn(rb[keep_lo:keep_hi], view)
        mask >>= 1
    # recursive doubling allgather (walk back up the same tree)
    mask = 1
    while mask < adjsize:
        peer_new = new_rank ^ mask
        peer = real(peer_new)
        my_lo, my_hi = segment(new_rank, mask)
        peer_lo, peer_hi = segment(peer_new, mask)
        rq = _irecv(comm, tmp[peer_lo:peer_hi], peer_hi - peer_lo,
                    dtype, peer, tag)
        sq = _isend(comm, rb[my_lo:my_hi].copy(), my_hi - my_lo,
                    dtype, peer, tag)
        rq.wait()
        sq.wait()
        rb[peer_lo:peer_hi] = tmp[peer_lo:peer_hi]
        mask <<= 1
    # unfold extras
    if rank < 2 * extra and rank % 2 == 0:
        _send(comm, rb, count, dtype, rank + 1, tag)


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------

def allgather_ring(comm, sendbuf, recvbuf, count, dtype):
    pvar.record("allgather")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf).reshape(size, -1)
    sb = _sbuf(sendbuf, recvbuf).reshape(-1)
    if sendbuf is not IN_PLACE:
        rb[rank][:] = sb
    nxt, prv = (rank + 1) % size, (rank - 1 + size) % size
    for step in range(size - 1):
        sidx = (rank - step + size) % size
        ridx = (rank - step - 1 + size) % size
        rq = _irecv(comm, rb[ridx], count, dtype, prv, tag)
        sq = _isend(comm, rb[sidx].copy(), count, dtype, nxt, tag)
        rq.wait()
        sq.wait()


def allgather_bruck(comm, sendbuf, recvbuf, count, dtype):
    """coll_base_allgather.c bruck: log(p) steps, then local rotate."""
    pvar.record("allgather")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf).reshape(size, -1)
    sb = _sbuf(sendbuf, recvbuf).reshape(-1)
    work = np.empty_like(rb)
    work[0][:] = sb if sendbuf is not IN_PLACE else rb[rank]
    have = 1
    dist = 1
    while dist < size:
        sendn = min(dist, size - have)
        to = (rank - dist + size) % size
        frm = (rank + dist) % size
        rq = _irecv(comm, work[have:have + sendn], sendn * work.shape[1],
                    dtype, frm, tag)
        sq = _isend(comm, work[:sendn].copy(), sendn * work.shape[1],
                    dtype, to, tag)
        rq.wait()
        sq.wait()
        have += sendn
        dist <<= 1
    # local inverse rotation: work[i] holds block (rank+i)%size
    for i in range(size):
        rb[(rank + i) % size][:] = work[i]


def allgather_recursivedoubling(comm, sendbuf, recvbuf, count, dtype):
    """Power-of-two only; falls back to ring otherwise."""
    rank, size = comm.rank, comm.size
    if size & (size - 1):
        return allgather_ring(comm, sendbuf, recvbuf, count, dtype)
    pvar.record("allgather")
    tag = _tag(comm)
    rb = np.asarray(recvbuf).reshape(size, -1)
    sb = _sbuf(sendbuf, recvbuf).reshape(-1)
    if sendbuf is not IN_PLACE:
        rb[rank][:] = sb
    mask = 1
    while mask < size:
        peer = rank ^ mask
        base = rank & ~(mask * 2 - 1)  # start of my current block pair
        mine_lo = rank & ~(mask - 1)
        peer_lo = peer & ~(mask - 1)
        rq = _irecv(comm, rb[peer_lo:peer_lo + mask],
                    mask * rb.shape[1], dtype, peer, tag)
        sq = _isend(comm, rb[mine_lo:mine_lo + mask].copy(),
                    mask * rb.shape[1], dtype, peer, tag)
        rq.wait()
        sq.wait()
        mask <<= 1


# ---------------------------------------------------------------------------
# alltoall
# ---------------------------------------------------------------------------

def alltoall_pairwise(comm, sendbuf, recvbuf, count, dtype):
    """coll_base_alltoall.c pairwise: size-1 rounds of sendrecv with
    rotating partners — bounded concurrency (vs basic's all-at-once)."""
    pvar.record("alltoall")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    sb = np.asarray(sendbuf).reshape(size, -1)
    rb = np.asarray(recvbuf).reshape(size, -1)
    rb[rank][:] = sb[rank]
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step + size) % size
        rq = _irecv(comm, rb[frm], count, dtype, frm, tag)
        sq = _isend(comm, sb[to], count, dtype, to, tag)
        rq.wait()
        sq.wait()


def alltoall_bruck(comm, sendbuf, recvbuf, count, dtype):
    """coll_base_alltoall.c:180 bruck — log(p) rounds of block batches;
    best for small messages at scale."""
    pvar.record("alltoall")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    sb = np.asarray(sendbuf).reshape(size, -1)
    rb = np.asarray(recvbuf).reshape(size, -1)
    blk = sb.shape[1]
    # phase 1: local rotation so block i is destined (rank+i)%size
    work = np.vstack([sb[(rank + i) % size] for i in range(size)])
    tmp = np.empty_like(work)
    dist = 1
    while dist < size:
        idx = [i for i in range(size) if i & dist]
        sendblocks = work[idx].copy()
        recvblocks = np.empty_like(sendblocks)
        to = (rank + dist) % size
        frm = (rank - dist + size) % size
        rq = _irecv(comm, recvblocks, len(idx) * blk, dtype, frm, tag)
        sq = _isend(comm, sendblocks, len(idx) * blk, dtype, to, tag)
        rq.wait()
        sq.wait()
        work[idx] = recvblocks
        dist <<= 1
    # phase 3: inverse rotation: final block for src s lands at
    # work[(s - rank + size) % size] reversed ordering
    for i in range(size):
        rb[(rank - i + size) % size][:] = work[i]


# ---------------------------------------------------------------------------
# reduce / reduce_scatter
# ---------------------------------------------------------------------------

def reduce_binomial(comm, sendbuf, recvbuf, count, dtype, op, root: int):
    """Binomial tree reduce (deterministic operand order per subtree)."""
    pvar.record("reduce")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    vrank = (rank - root + size) % size
    sb = _sbuf(sendbuf, recvbuf)
    acc = sb.copy()
    tmp = np.empty_like(acc)
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = (vrank - mask + root) % size
            _send(comm, acc, count, dtype, parent, tag)
            return
        child_v = vrank + mask
        if child_v < size:
            child = (child_v + root) % size
            _recv(comm, tmp, count, dtype, child, tag)
            # child covers higher v-ranks: child contributes on the right
            acc = op.np_fn(acc, tmp)
        mask <<= 1
    if recvbuf is not None:
        np.copyto(np.asarray(recvbuf), acc, casting="same_kind")


def reduce_scatter_recursivehalving(comm, sendbuf, recvbuf, counts,
                                    dtype, op):
    """coll_base_reduce_scatter.c recursive halving (pow2 only; ring
    fallback via basic otherwise)."""
    rank, size = comm.rank, comm.size
    if size & (size - 1):
        from ompi_tpu.coll.basic import reduce_scatter_basic

        return reduce_scatter_basic(comm, sendbuf, recvbuf, counts,
                                    dtype, op)
    pvar.record("reduce_scatter")
    tag = _tag(comm)
    sb = _sbuf(sendbuf, recvbuf).reshape(-1).copy()
    bounds = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    lo_r, hi_r = 0, size  # rank range whose chunks I still carry
    tmp = np.empty_like(sb)
    mask = size // 2
    while mask >= 1:
        mid = (lo_r + hi_r) // 2
        peer = rank ^ mask
        if (rank - lo_r) < (mid - lo_r):
            my_lo, my_hi = lo_r, mid
            give_lo, give_hi = mid, hi_r
        else:
            my_lo, my_hi = mid, hi_r
            give_lo, give_hi = lo_r, mid
        gl, gh = int(bounds[give_lo]), int(bounds[give_hi])
        ml, mh = int(bounds[my_lo]), int(bounds[my_hi])
        view = tmp[ml:mh]
        rq = _irecv(comm, view, mh - ml, dtype, peer, tag)
        sq = _isend(comm, sb[gl:gh].copy(), gh - gl, dtype, peer, tag)
        rq.wait()
        sq.wait()
        if peer < rank:
            sb[ml:mh] = op.np_fn(view, sb[ml:mh])
        else:
            sb[ml:mh] = op.np_fn(sb[ml:mh], view)
        lo_r, hi_r = my_lo, my_hi
        mask >>= 1
    rl, rh = int(bounds[rank]), int(bounds[rank + 1])
    np.asarray(recvbuf).reshape(-1)[:rh - rl] = sb[rl:rh]


def reduce_scatter_block_ring(comm, sendbuf, recvbuf, count, dtype, op):
    """Ring reduce-scatter phase only (phase 1 of allreduce_ring)."""
    pvar.record("reduce_scatter")
    tag = _tag(comm)
    rank, size = comm.rank, comm.size
    sb = _sbuf(sendbuf, recvbuf).reshape(-1)
    work = sb.copy()
    nxt, prv = (rank + 1) % size, (rank - 1 + size) % size
    tmp = np.empty(count, dtype=work.dtype)
    # schedule shifted by one vs allreduce_ring so the fully-reduced
    # chunk each rank ends with is its *own* chunk
    for step in range(size - 1):
        sidx = (rank - step - 1 + size) % size
        ridx = (rank - step - 2 + size) % size
        rq = _irecv(comm, tmp, count, dtype, prv, tag)
        sq = _isend(comm, work[sidx * count:(sidx + 1) * count].copy(),
                    count, dtype, nxt, tag)
        rq.wait()
        sq.wait()
        work[ridx * count:(ridx + 1) * count] = op.np_fn(
            tmp, work[ridx * count:(ridx + 1) * count])
    np.asarray(recvbuf).reshape(-1)[:count] = \
        work[rank * count:(rank + 1) * count]
