"""coll/inter — group-vs-group collectives for intercommunicators.

Reference: ompi/mca/coll/inter (leader-based algorithms: local phase on
c_local_comm, leader exchange across the bridge, local redistribution)
and coll/basic's inter variants. Root arguments follow the MPI inter
convention: the root group passes ``intercomm.ROOT`` at the root and
``PROC_NULL`` elsewhere; the other group passes the root's rank within
the remote group.

Only this component qualifies on intercomms; the intra components
(basic/tuned/libnbc/accelerator/xla) disqualify themselves — their
algorithms assume a single group.
"""

from __future__ import annotations

import numpy as np

from ompi_tpu import op as op_mod
from ompi_tpu.coll import CollModule, framework
from ompi_tpu.comm.intercomm import ROOT
from ompi_tpu.core import pvar
from ompi_tpu.pml.request import PROC_NULL


def _leader(comm) -> bool:
    return comm.rank == 0


def inter_barrier(comm) -> None:
    """Local barrier, leader token exchange, local barrier
    (coll_inter_barrier semantics)."""
    pvar.record("inter_barrier")
    comm.local_comm.Barrier()
    if _leader(comm):
        comm.sendrecv(None, dest=0, source=0, sendtag=-22, recvtag=-22)
    comm.local_comm.Barrier()


def inter_bcast_obj(comm, obj, root):
    pvar.record("inter_bcast")
    if root == PROC_NULL:
        return None  # non-root member of the root group
    if root == ROOT:
        comm.send(obj, dest=0, tag=-23)  # to remote leader
        return obj
    # receiving group: leader pulls from the remote root, local bcast
    if _leader(comm):
        obj = comm.recv(source=root, tag=-23)
    return comm.local_comm.bcast(obj, root=0)


def inter_bcast(comm, buf, count, dtype, root) -> None:
    if root == PROC_NULL:
        return
    if root == ROOT:
        comm.Send((buf, count, dtype), dest=0, tag=-23)
        return
    if _leader(comm):
        comm.Recv((buf, count, dtype), source=root, tag=-23)
    comm.local_comm.Bcast((buf, count, dtype), root=0)


def inter_allreduce(comm, sendbuf, recvbuf, count, dtype, op) -> None:
    """Each group receives the reduction of the OTHER group's vectors
    (MPI inter-allreduce): local reduce -> leader swap -> local bcast."""
    pvar.record("inter_allreduce")
    local = comm.local_comm
    sb = np.asarray(sendbuf)
    mine = np.empty_like(sb)
    local.Reduce(sb, mine, op=op, root=0)
    rb = np.asarray(recvbuf)
    if _leader(comm):
        rreq = comm.Irecv((rb, count, dtype), source=0, tag=-24)
        comm.Send((mine, count, dtype), dest=0, tag=-24)
        rreq.wait()
    local.Bcast((rb, count, dtype), root=0)


def inter_allgather(comm, sendbuf, recvbuf, count, dtype) -> None:
    """recvbuf receives the REMOTE group's contributions
    (remote_size * count elements)."""
    pvar.record("inter_allgather")
    local = comm.local_comm
    sb = np.asarray(sendbuf)
    gathered = np.empty((local.size,) + sb.shape, sb.dtype) \
        if _leader(comm) else None
    local.Gather(sb, gathered, root=0)
    rb = np.asarray(recvbuf)
    if _leader(comm):
        rreq = comm.Irecv((rb, rb.size, dtype), source=0, tag=-25)
        comm.Send((gathered, gathered.size, dtype), dest=0, tag=-25)
        rreq.wait()
    local.Bcast((rb, rb.size, dtype), root=0)


def inter_allgather_obj(comm, obj):
    pvar.record("inter_allgather")
    local = comm.local_comm
    mine = local.gather(obj, root=0)
    if _leader(comm):
        theirs = comm.sendrecv(mine, dest=0, source=0,
                               sendtag=-26, recvtag=-26)
    else:
        theirs = None
    return local.bcast(theirs, root=0)


@framework.register
class CollInter(CollModule):
    NAME = "inter"
    PRIORITY = 45
    INTER_OK = True  # the whole point: group-vs-group algorithms

    def query(self, comm) -> int:
        # the only component that serves intercomms; never intra
        return self.PRIORITY if getattr(comm, "is_inter", False) else -1

    def slots(self, comm):
        return {
            "barrier": inter_barrier,
            "bcast": inter_bcast,
            "bcast_obj": inter_bcast_obj,
            "allreduce": inter_allreduce,
            "allgather": inter_allgather,
            "allgather_obj": inter_allgather_obj,
        }
