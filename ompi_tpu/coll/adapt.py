"""coll/adapt — event-driven segmented ibcast/ireduce.

Reference: ompi/mca/coll/adapt (2,366 LoC): nonblocking bcast/reduce
that split the message into segments, each progressing independently
down a tree via completion-event callbacks — segments pipeline, so a
slow link stalls one segment instead of the whole operation. Opt-in
via priority (the reference ships it disabled by default).

Redesign over the libnbc schedule engine: one generator schedule PER
SEGMENT, with a bounded in-flight window (coll_adapt_max_inflight) —
the progress engine resumes whichever in-flight segment's round
completed (the event-driven part), and finished segments admit new
ones, so a gigabyte bcast never floods the match queues. A composite
request completes when every segment has.

Enable: --mca coll_adapt_priority N with N > 20 (it must out-rank
libnbc's nonblocking slots at priority 20 to take effect); segment
size via coll_adapt_segment_bytes. Buffers that cannot be viewed
flat (non-contiguous arrays, bytearrays) delegate to libnbc.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ompi_tpu.coll import CollModule, framework
from ompi_tpu.coll import libnbc
from ompi_tpu.coll.basic import _tag
from ompi_tpu.core import cvar, progress, pvar
from ompi_tpu.pml import request as rq

_prio_var = cvar.register(
    "coll_adapt_priority", -1, int,
    help="coll/adapt selection priority; <0 disables (the reference "
         "ships adapt opt-in the same way). Must EXCEED libnbc's 20 "
         "to actually take the ibcast/ireduce slots.", level=6)
_seg_var = cvar.register(
    "coll_adapt_segment_bytes", 1 << 16, int,
    help="Segment size for adapt's pipelined ibcast/ireduce "
         "(reference: adapt segment sizing).", level=6)
_window_var = cvar.register(
    "coll_adapt_max_inflight", 32, int,
    help="Max segment schedules in flight per adapt operation (the "
         "reference bounds outstanding segments the same way; without "
         "a cap a 1GB bcast would post tens of thousands of "
         "requests at once).", level=6)


class CompositeRequest(rq.Request):
    """Windowed per-segment schedules: finished segments admit new
    ones; completes when the last one has. Admission happens inside
    the ``completed`` poll, which every wait/test path drives via the
    progress engine."""

    def __init__(self, factories: List[Callable], window: int) -> None:
        super().__init__()
        self._factories = factories
        self._next = 0
        self._live: List[rq.Request] = []
        self._window = max(1, window)
        self._admit()

    def _admit(self) -> None:
        inflight = sum(1 for r in self._live if not r.completed)
        while (inflight < self._window
               and self._next < len(self._factories)):
            self._live.append(
                libnbc.NbcRequest(self._factories[self._next]()))
            self._next += 1
            inflight += 1

    @property
    def completed(self) -> bool:
        if self._next < len(self._factories):
            self._admit()
        return (self._next >= len(self._factories)
                and all(r.completed for r in self._live))

    @completed.setter
    def completed(self, v: bool) -> None:  # base __init__ writes here
        pass

    def test(self) -> bool:
        if not self.completed:
            progress.progress()
        return self.completed

    def wait(self, timeout=None):
        progress.wait_until(lambda: self.completed, timeout=timeout)
        if not self.completed:
            raise TimeoutError("adapt collective did not complete")
        return self.status


def _flat_view(buf, count: int) -> Optional[np.ndarray]:
    """A no-copy flat view of the first `count` elements, or None when
    the buffer cannot be viewed (delegate to libnbc then — receiving
    into a silent temporary would lose the data)."""
    if isinstance(buf, np.ndarray) and buf.flags["C_CONTIGUOUS"]:
        return buf.reshape(-1)[:count]
    return None


def _seg_spans(n: int, itemsize: int):
    per = max(1, _seg_var.get() // max(1, itemsize))
    return [(i, min(per, n - i)) for i in range(0, n, per)]


def ibcast_adapt(comm, buf, count, dtype, root):
    """Per-segment binomial trees under a bounded window (adapt
    ibcast)."""
    flat = _flat_view(buf, count)
    if flat is None:
        return libnbc.ibcast(comm, buf, count, dtype, root)
    pvar.record("adapt_ibcast")
    spans = _seg_spans(flat.size, flat.dtype.itemsize)
    # tags drawn NOW, at the collective call (every rank reaches it in
    # the same order): drawing lazily at admission would interleave
    # with other concurrent collectives' tag sequence per-rank
    tags = [_tag(comm) for _ in spans]
    factories = [
        (lambda off=off, n=n, tag=tag: libnbc._sched_bcast(
            comm, flat[off:off + n], n, dtype, root, tag))
        for (off, n), tag in zip(spans, tags)]
    return CompositeRequest(factories, _window_var.get())


def ireduce_adapt(comm, sendbuf, recvbuf, count, dtype, op, root):
    """Per-segment binomial reductions under a bounded window (adapt
    ireduce)."""
    from ompi_tpu.coll.basic import IN_PLACE

    src = recvbuf if sendbuf is IN_PLACE else sendbuf
    sflat = _flat_view(src, count)
    rflat = None if recvbuf is None else _flat_view(recvbuf, count)
    if sflat is None or (recvbuf is not None and rflat is None):
        return libnbc.ireduce(comm, sendbuf, recvbuf, count, dtype,
                              op, root)
    pvar.record("adapt_ireduce")
    spans = _seg_spans(sflat.size, sflat.dtype.itemsize)
    tags = [_tag(comm) for _ in spans]  # see ibcast_adapt
    factories = [
        (lambda off=off, n=n, tag=tag: libnbc._sched_reduce(
            comm, sflat[off:off + n],
            None if rflat is None else rflat[off:off + n],
            n, dtype, op, root, tag))
        for (off, n), tag in zip(spans, tags)]
    return CompositeRequest(factories, _window_var.get())


@framework.register
class CollAdapt(CollModule):
    NAME = "adapt"

    def query(self, comm) -> int:
        if comm.size < 2:
            return -1
        return _prio_var.get()  # <0 disables (default)

    def slots(self, comm):
        return {
            "ibcast": ibcast_adapt,
            "ireduce": ireduce_adapt,
        }
