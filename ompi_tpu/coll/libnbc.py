"""coll/libnbc — nonblocking collectives as progressed schedules.

Reference: ompi/mca/coll/libnbc (12,428 LoC): each i-collective compiles to
a schedule of send/recv/op/copy rounds advanced by the progress engine
(nbc_internal.h:156-165). Here a schedule is a Python generator that
yields lists of outstanding p2p requests; the NBC engine resumes it when
the current round completes — same round semantics, idiomatic coroutine
form.
"""

from __future__ import annotations

from typing import Generator, List, Optional

import numpy as np

from ompi_tpu.coll import CollModule, framework
from ompi_tpu.coll import basic as B
from ompi_tpu.coll.basic import _irecv, _isend, _tag
from ompi_tpu.core import progress
from ompi_tpu.pml import request as rq

_active: List["NbcRequest"] = []
_registered = False


def _nbc_progress() -> int:
    events = 0
    for req in list(_active):
        events += req._advance()
    return events


class NbcRequest(rq.Request):
    """A schedule being progressed (reference: NBC_Handle)."""

    def __init__(self, gen: Generator) -> None:
        super().__init__()
        self._gen = gen
        self._round: Optional[List[rq.Request]] = None
        global _registered
        if not _registered:
            progress.register(_nbc_progress)
            _registered = True
        _active.append(self)
        self._advance()

    def _advance(self) -> int:
        if self.completed:
            return 0
        if self._round is not None and \
                not all(r.completed for r in self._round):
            return 0
        events = 0
        try:
            while True:
                self._round = self._gen.send(None)
                events += 1
                if self._round and \
                        not all(r.completed for r in self._round):
                    return events
        except StopIteration:
            _active.remove(self)
            self.complete()
            return events + 1


# -- schedules ------------------------------------------------------------

def _sched_barrier(comm, tag):
    """Dissemination rounds (libnbc ibarrier)."""
    rank, size = comm.rank, comm.size
    tok = np.zeros(1, dtype=np.uint8)
    rtok = np.zeros(1, dtype=np.uint8)
    dist = 1
    while dist < size:
        to = (rank + dist) % size
        frm = (rank - dist + size) % size
        yield [_irecv(comm, rtok, 1, None, frm, tag),
               _isend(comm, tok, 1, None, to, tag)]
        dist <<= 1


def _sched_bcast(comm, buf, count, dtype, root, tag):
    """Binomial rounds."""
    rank, size = comm.rank, comm.size
    vrank = (rank - root + size) % size
    arr = np.asarray(buf)
    if vrank != 0:
        mask = 1
        while not (vrank & mask):
            mask <<= 1
        parent = (vrank - mask + root) % size
        yield [_irecv(comm, arr, count, dtype, parent, tag)]
    sends = []
    m = 1
    while m < size:
        if vrank & m:
            break
        if vrank + m < size:
            child = (vrank + m + root) % size
            sends.append(_isend(comm, arr, count, dtype, child, tag))
        m <<= 1
    if sends:
        yield sends


def _sched_allreduce(comm, sendbuf, recvbuf, count, dtype, op, tag):
    """Recursive-doubling rounds (libnbc iallreduce)."""
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf)
    sb = np.asarray(recvbuf) if sendbuf is B.IN_PLACE \
        else np.asarray(sendbuf)
    if rb is not sb:
        np.copyto(rb, sb, casting="same_kind")
    tmp = np.empty_like(rb)
    adjsize = 1
    while adjsize * 2 <= size:
        adjsize *= 2
    extra = size - adjsize
    if rank < 2 * extra:
        if rank % 2 == 1:
            yield [_isend(comm, rb, count, dtype, rank - 1, tag)]
            yield [_irecv(comm, rb, count, dtype, rank - 1, tag)]
            return
        yield [_irecv(comm, tmp, count, dtype, rank + 1, tag)]
        rb[...] = op.np_fn(rb, tmp)
    new_rank = rank // 2 if rank < 2 * extra else rank - extra
    mask = 1
    while mask < adjsize:
        peer_new = new_rank ^ mask
        peer = peer_new * 2 if peer_new < extra else peer_new + extra
        yield [_irecv(comm, tmp, count, dtype, peer, tag),
               _isend(comm, rb.copy(), count, dtype, peer, tag)]
        if peer_new < new_rank:
            rb[...] = op.np_fn(tmp, rb)
        else:
            rb[...] = op.np_fn(rb, tmp)
        mask <<= 1
    if rank < 2 * extra and rank % 2 == 0:
        yield [_isend(comm, rb, count, dtype, rank + 1, tag)]


def _sched_gather(comm, sendbuf, recvbuf, count, dtype, root, tag):
    rank, size = comm.rank, comm.size
    sb = np.asarray(sendbuf)
    if rank == root:
        rb = np.asarray(recvbuf).reshape(size, -1)
        rb[root][:] = sb.reshape(-1)
        yield [_irecv(comm, rb[r], count, dtype, r, tag)
               for r in range(size) if r != root]
    else:
        yield [_isend(comm, sb, count, dtype, root, tag)]


def _sched_scatter(comm, sendbuf, recvbuf, count, dtype, root, tag):
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf)
    if rank == root:
        sb = np.asarray(sendbuf).reshape(size, -1)
        rb.reshape(-1)[:] = sb[root]
        yield [_isend(comm, sb[r].copy(), count, dtype, r, tag)
               for r in range(size) if r != root]
    else:
        yield [_irecv(comm, rb, count, dtype, root, tag)]


def _sched_allgather(comm, sendbuf, recvbuf, count, dtype, tag):
    """Ring rounds."""
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf).reshape(size, -1)
    if sendbuf is not B.IN_PLACE:
        rb[rank][:] = np.asarray(sendbuf).reshape(-1)
    nxt, prv = (rank + 1) % size, (rank - 1 + size) % size
    for step in range(size - 1):
        sidx = (rank - step + size) % size
        ridx = (rank - step - 1 + size) % size
        yield [_irecv(comm, rb[ridx], count, dtype, prv, tag),
               _isend(comm, rb[sidx].copy(), count, dtype, nxt, tag)]


def _sched_alltoall(comm, sendbuf, recvbuf, count, dtype, tag):
    """Pairwise rounds."""
    rank, size = comm.rank, comm.size
    sb = np.asarray(sendbuf).reshape(size, -1)
    rb = np.asarray(recvbuf).reshape(size, -1)
    rb[rank][:] = sb[rank]
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step + size) % size
        yield [_irecv(comm, rb[frm], count, dtype, frm, tag),
               _isend(comm, sb[to], count, dtype, to, tag)]


def _sched_reduce(comm, sendbuf, recvbuf, count, dtype, op, root, tag):
    rank, size = comm.rank, comm.size
    vrank = (rank - root + size) % size
    sb = np.asarray(recvbuf) if sendbuf is B.IN_PLACE \
        else np.asarray(sendbuf)
    acc = sb.copy()
    tmp = np.empty_like(acc)
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = (vrank - mask + root) % size
            yield [_isend(comm, acc, count, dtype, parent, tag)]
            return
        child_v = vrank + mask
        if child_v < size:
            child = (child_v + root) % size
            yield [_irecv(comm, tmp, count, dtype, child, tag)]
            acc = op.np_fn(acc, tmp)
        mask <<= 1
    if recvbuf is not None:
        np.copyto(np.asarray(recvbuf), acc, casting="same_kind")


# -- component ------------------------------------------------------------

def ibarrier(comm):
    return NbcRequest(_sched_barrier(comm, _tag(comm)))


def ibcast(comm, buf, count, dtype, root):
    return NbcRequest(_sched_bcast(comm, buf, count, dtype, root,
                                   _tag(comm)))


def iallreduce(comm, sendbuf, recvbuf, count, dtype, op):
    return NbcRequest(_sched_allreduce(comm, sendbuf, recvbuf, count,
                                       dtype, op, _tag(comm)))


def ireduce(comm, sendbuf, recvbuf, count, dtype, op, root):
    return NbcRequest(_sched_reduce(comm, sendbuf, recvbuf, count,
                                    dtype, op, root, _tag(comm)))


def igather(comm, sendbuf, recvbuf, count, dtype, root):
    return NbcRequest(_sched_gather(comm, sendbuf, recvbuf, count,
                                    dtype, root, _tag(comm)))


def iscatter(comm, sendbuf, recvbuf, count, dtype, root):
    return NbcRequest(_sched_scatter(comm, sendbuf, recvbuf, count,
                                     dtype, root, _tag(comm)))


def iallgather(comm, sendbuf, recvbuf, count, dtype):
    return NbcRequest(_sched_allgather(comm, sendbuf, recvbuf, count,
                                       dtype, _tag(comm)))


def ialltoall(comm, sendbuf, recvbuf, count, dtype):
    return NbcRequest(_sched_alltoall(comm, sendbuf, recvbuf, count,
                                      dtype, _tag(comm)))


@framework.register
class CollLibnbc(CollModule):
    NAME = "libnbc"
    PRIORITY = 20

    def slots(self, comm):
        return {
            "ibarrier": ibarrier,
            "ibcast": ibcast,
            "iallreduce": iallreduce,
            "ireduce": ireduce,
            "igather": igather,
            "iscatter": iscatter,
            "iallgather": iallgather,
            "ialltoall": ialltoall,
        }
