"""coll/libnbc — nonblocking collectives as progressed schedules.

Reference: ompi/mca/coll/libnbc (12,428 LoC): each i-collective compiles to
a schedule of send/recv/op/copy rounds advanced by the progress engine
(nbc_internal.h:156-165). Here a schedule is a Python generator that
yields lists of outstanding p2p requests; the NBC engine resumes it when
the current round completes — same round semantics, idiomatic coroutine
form.
"""

from __future__ import annotations

from typing import Generator, List, Optional

import numpy as np

from ompi_tpu.coll import CollModule, framework
from ompi_tpu.coll import basic as B
from ompi_tpu.coll.basic import _irecv, _isend, _tag
from ompi_tpu.core import progress
from ompi_tpu.pml import request as rq

_active: List["NbcRequest"] = []
_registered = False


def _nbc_progress() -> int:
    events = 0
    for req in list(_active):
        events += req._advance()
    return events


class NbcRequest(rq.Request):
    """A schedule being progressed (reference: NBC_Handle)."""

    def __init__(self, gen: Generator) -> None:
        super().__init__()
        self._gen = gen
        self._round: Optional[List[rq.Request]] = None
        self._rounds_run = 0
        self._exc: Optional[BaseException] = None
        self._in_init = True
        self._advancing = False
        # MPI_T event metadata, harvested from the unstarted
        # generator's bound args (no call-site churn): the schedule
        # kind from its name, the comm from its locals
        self._kind = getattr(gen, "__name__", "?").replace("_sched_",
                                                           "")
        frame = getattr(gen, "gi_frame", None)
        c = None
        if frame is not None:  # module-level schedules bind `comm`;
            # bound-method schedules (Comm._sched_idup) bind `self`
            c = frame.f_locals.get("comm") or frame.f_locals.get("self")
        self._comm_cid = getattr(c, "cid", -1)
        global _registered
        if not _registered:
            progress.register(_nbc_progress)
            _registered = True
        _active.append(self)
        self._advance()
        self._in_init = False

    def _advance(self) -> int:
        if self.completed or self._advancing:
            # _advancing: a schedule body's send can spin the progress
            # engine when a transport is full (ob1._pump), re-entering
            # this sweep while the generator is executing — resuming
            # it again would raise "generator already executing" into
            # the error path below (a silent false completion)
            return 0
        if self._round is not None and \
                not all(r.completed for r in self._round):
            return 0
        events = 0
        self._advancing = True
        try:
            while True:
                self._round = self._gen.send(None)
                events += 1
                self._rounds_run += 1
                if self._round and \
                        not all(r.completed for r in self._round):
                    return events
        except StopIteration:
            _active.remove(self)
            from ompi_tpu.core import events as mpit_events

            if mpit_events.active("coll_schedule_complete"):
                mpit_events.emit("coll_schedule_complete",
                                 kind=self._kind,
                                 comm_cid=self._comm_cid,
                                 rounds=self._rounds_run)
            self.complete()
            return events + 1
        except Exception as exc:
            # A schedule body failed (e.g. an ERRORS_RETURN file
            # errhandler re-raised an IO error out of sched_write).
            # Letting it escape would surface it in whatever call
            # happened to be spinning progress.progress() — possibly
            # an unrelated request's wait. Complete THIS request with
            # the error instead; it re-raises at its own wait().
            # Exception: the prologue runs synchronously inside
            # __init__ — ARGUMENT errors (ValueError/TypeError/...)
            # there stay loud at the call site. MPI errors always
            # defer to the request's wait, even from __init__: a
            # communication failure (e.g. a recv from a known-dead
            # peer completing instantly) is a runtime outcome, not a
            # caller mistake.
            _active.remove(self)
            from ompi_tpu import errors as _errors

            if self._in_init and not isinstance(exc, _errors.MPIError):
                raise
            self._exc = exc
            code = exc.error_class if isinstance(exc, _errors.MPIError) \
                else _errors.ERR_OTHER
            self.complete(error=code)
            return events + 1
        finally:
            self._advancing = False

    def wait(self, timeout=None):
        progress.wait_until(lambda: self.completed, timeout=timeout)
        if not self.completed:
            raise TimeoutError(f"request {self.id} did not complete")
        if self._exc is not None:
            raise self._exc
        # completed: base wait returns immediately and runs the
        # plain-error dispatch path
        return super().wait(timeout)


# -- schedules ------------------------------------------------------------

def _sched_barrier(comm, tag):
    """Dissemination rounds (libnbc ibarrier)."""
    rank, size = comm.rank, comm.size
    tok = np.zeros(1, dtype=np.uint8)
    rtok = np.zeros(1, dtype=np.uint8)
    dist = 1
    while dist < size:
        to = (rank + dist) % size
        frm = (rank - dist + size) % size
        yield [_irecv(comm, rtok, 1, None, frm, tag),
               _isend(comm, tok, 1, None, to, tag)]
        dist <<= 1


def _sched_bcast(comm, buf, count, dtype, root, tag):
    """Binomial rounds."""
    rank, size = comm.rank, comm.size
    vrank = (rank - root + size) % size
    arr = np.asarray(buf)
    if vrank != 0:
        mask = 1
        while not (vrank & mask):
            mask <<= 1
        parent = (vrank - mask + root) % size
        yield [_irecv(comm, arr, count, dtype, parent, tag)]
    sends = []
    m = 1
    while m < size:
        if vrank & m:
            break
        if vrank + m < size:
            child = (vrank + m + root) % size
            sends.append(_isend(comm, arr, count, dtype, child, tag))
        m <<= 1
    if sends:
        yield sends


def _sched_allreduce(comm, sendbuf, recvbuf, count, dtype, op, tag):
    """Recursive-doubling rounds (libnbc iallreduce)."""
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf)
    sb = np.asarray(recvbuf) if sendbuf is B.IN_PLACE \
        else np.asarray(sendbuf)
    if rb is not sb:
        np.copyto(rb, sb, casting="same_kind")
    tmp = np.empty_like(rb)
    adjsize = 1
    while adjsize * 2 <= size:
        adjsize *= 2
    extra = size - adjsize
    if rank < 2 * extra:
        if rank % 2 == 1:
            yield [_isend(comm, rb, count, dtype, rank - 1, tag)]
            yield [_irecv(comm, rb, count, dtype, rank - 1, tag)]
            return
        yield [_irecv(comm, tmp, count, dtype, rank + 1, tag)]
        rb[...] = op.np_fn(rb, tmp)
    new_rank = rank // 2 if rank < 2 * extra else rank - extra
    mask = 1
    while mask < adjsize:
        peer_new = new_rank ^ mask
        peer = peer_new * 2 if peer_new < extra else peer_new + extra
        yield [_irecv(comm, tmp, count, dtype, peer, tag),
               _isend(comm, rb.copy(), count, dtype, peer, tag)]
        if peer_new < new_rank:
            rb[...] = op.np_fn(tmp, rb)
        else:
            rb[...] = op.np_fn(rb, tmp)
        mask <<= 1
    if rank < 2 * extra and rank % 2 == 0:
        yield [_isend(comm, rb, count, dtype, rank + 1, tag)]


def _sched_gather(comm, sendbuf, recvbuf, count, dtype, root, tag):
    rank, size = comm.rank, comm.size
    sb = np.asarray(sendbuf)
    if rank == root:
        rb = np.asarray(recvbuf).reshape(size, -1)
        rb[root][:] = sb.reshape(-1)
        yield [_irecv(comm, rb[r], count, dtype, r, tag)
               for r in range(size) if r != root]
    else:
        yield [_isend(comm, sb, count, dtype, root, tag)]


def _sched_scatter(comm, sendbuf, recvbuf, count, dtype, root, tag):
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf)
    if rank == root:
        sb = np.asarray(sendbuf).reshape(size, -1)
        rb.reshape(-1)[:] = sb[root]
        yield [_isend(comm, sb[r].copy(), count, dtype, r, tag)
               for r in range(size) if r != root]
    else:
        yield [_irecv(comm, rb, count, dtype, root, tag)]


def _sched_allgather(comm, sendbuf, recvbuf, count, dtype, tag):
    """Ring rounds."""
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf).reshape(size, -1)
    if sendbuf is not B.IN_PLACE:
        rb[rank][:] = np.asarray(sendbuf).reshape(-1)
    nxt, prv = (rank + 1) % size, (rank - 1 + size) % size
    for step in range(size - 1):
        sidx = (rank - step + size) % size
        ridx = (rank - step - 1 + size) % size
        yield [_irecv(comm, rb[ridx], count, dtype, prv, tag),
               _isend(comm, rb[sidx].copy(), count, dtype, nxt, tag)]


def _sched_alltoall(comm, sendbuf, recvbuf, count, dtype, tag):
    """Pairwise rounds."""
    rank, size = comm.rank, comm.size
    sb = np.asarray(sendbuf).reshape(size, -1)
    rb = np.asarray(recvbuf).reshape(size, -1)
    rb[rank][:] = sb[rank]
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step + size) % size
        yield [_irecv(comm, rb[frm], count, dtype, frm, tag),
               _isend(comm, sb[to], count, dtype, to, tag)]


def _sched_reduce(comm, sendbuf, recvbuf, count, dtype, op, root, tag):
    rank, size = comm.rank, comm.size
    vrank = (rank - root + size) % size
    sb = np.asarray(recvbuf) if sendbuf is B.IN_PLACE \
        else np.asarray(sendbuf)
    acc = sb.copy()
    tmp = np.empty_like(acc)
    mask = 1
    while mask < size:
        if vrank & mask:
            parent = (vrank - mask + root) % size
            yield [_isend(comm, acc, count, dtype, parent, tag)]
            return
        child_v = vrank + mask
        if child_v < size:
            child = (child_v + root) % size
            yield [_irecv(comm, tmp, count, dtype, child, tag)]
            acc = op.np_fn(acc, tmp)
        mask <<= 1
    if recvbuf is not None:
        np.copyto(np.asarray(recvbuf), acc, casting="same_kind")


def _sched_gatherv(comm, sendbuf, recvbuf, counts, displs, dtype,
                   root, tag):
    rank, size = comm.rank, comm.size
    sb = np.asarray(sendbuf)
    if rank == root:
        rb = np.asarray(recvbuf).reshape(-1)
        rb[displs[root]:displs[root] + counts[root]] = sb.reshape(-1)
        yield [_irecv(comm, rb[displs[r]:displs[r] + counts[r]],
                      counts[r], dtype, r, tag)
               for r in range(size) if r != root and counts[r]]
    elif counts[rank]:
        yield [_isend(comm, sb, counts[rank], dtype, root, tag)]


def _sched_scatterv(comm, sendbuf, recvbuf, counts, displs, dtype,
                    root, tag):
    rank, size = comm.rank, comm.size
    rb = np.asarray(recvbuf)
    if rank == root:
        sb = np.asarray(sendbuf).reshape(-1)
        rb.reshape(-1)[:counts[root]] = \
            sb[displs[root]:displs[root] + counts[root]]
        yield [_isend(comm, sb[displs[r]:displs[r] + counts[r]].copy(),
                      counts[r], dtype, r, tag)
               for r in range(size) if r != root and counts[r]]
    elif counts[rank]:
        yield [_irecv(comm, rb, counts[rank], dtype, root, tag)]


def _sched_allgatherv(comm, sendbuf, recvbuf, counts, displs, dtype,
                      tag):
    """gatherv at 0, then binomial bcast of the assembled buffer."""
    rank = comm.rank
    rb = np.asarray(recvbuf).reshape(-1)
    sb = rb[displs[rank]:displs[rank] + counts[rank]].copy() \
        if sendbuf is B.IN_PLACE else sendbuf
    yield from _sched_gatherv(comm, sb, recvbuf, counts, displs,
                              dtype, 0, tag)
    total = max(displs[r] + counts[r] for r in range(comm.size))
    yield from _sched_bcast(comm, rb[:total], total, dtype, 0, tag)


def _sched_alltoallv(comm, sendbuf, recvbuf, scounts, sdispls,
                     rcounts, rdispls, dtype, tag):
    """Pairwise rounds with per-peer counts (libnbc ialltoallv)."""
    rank, size = comm.rank, comm.size
    sb = np.asarray(sendbuf).reshape(-1)
    rb = np.asarray(recvbuf).reshape(-1)
    rb[rdispls[rank]:rdispls[rank] + rcounts[rank]] = \
        sb[sdispls[rank]:sdispls[rank] + scounts[rank]]
    for step in range(1, size):
        to = (rank + step) % size
        frm = (rank - step + size) % size
        ops = []
        if rcounts[frm]:
            ops.append(_irecv(
                comm, rb[rdispls[frm]:rdispls[frm] + rcounts[frm]],
                rcounts[frm], dtype, frm, tag))
        if scounts[to]:
            ops.append(_isend(
                comm, sb[sdispls[to]:sdispls[to] + scounts[to]].copy(),
                scounts[to], dtype, to, tag))
        if ops:
            yield ops


def _sched_scan(comm, sendbuf, recvbuf, count, dtype, op, tag,
                exclusive: bool):
    """Linear chain rounds (libnbc iscan/iexscan)."""
    rank, size = comm.rank, comm.size
    sb = np.asarray(recvbuf) if sendbuf is B.IN_PLACE \
        else np.asarray(sendbuf)
    rb = np.asarray(recvbuf)
    acc = sb.copy()  # inclusive prefix through this rank
    if rank > 0:
        tmp = np.empty_like(acc)
        yield [_irecv(comm, tmp, count, dtype, rank - 1, tag)]
        if exclusive:
            np.copyto(rb, tmp, casting="same_kind")
        acc = op.np_fn(tmp, acc)
    if not exclusive:
        np.copyto(rb, acc, casting="same_kind")
    if rank + 1 < size:
        yield [_isend(comm, acc, count, dtype, rank + 1, tag)]


def _flat(buf):
    """Flatten a user buffer for the 1-D staging compositions (other
    schedules reshape internally; _sched_reduce's final copyto needs
    matching shapes)."""
    return buf if buf is B.IN_PLACE else np.asarray(buf).reshape(-1)


def _sched_reduce_scatter_block(comm, sendbuf, recvbuf, count, dtype,
                                op, tag):
    """reduce at 0 + scatter rounds (compose: the schedule engine makes
    pipelined composition a yield-from)."""
    size = comm.size
    full = np.empty(size * count, dtype=np.asarray(recvbuf).dtype) \
        if comm.rank == 0 else None
    yield from _sched_reduce(comm, _flat(sendbuf), full, size * count,
                             dtype, op, 0, tag)
    yield from _sched_scatter(comm, full, recvbuf, count, dtype, 0, tag)


def _sched_reduce_scatter(comm, sendbuf, recvbuf, counts, dtype, op,
                          tag):
    total = sum(counts)
    displs = np.concatenate(
        ([0], np.cumsum(counts[:-1], dtype=np.intp))).tolist()
    full = np.empty(total, dtype=np.asarray(recvbuf).dtype) \
        if comm.rank == 0 else None
    yield from _sched_reduce(comm, _flat(sendbuf), full, total, dtype,
                             op, 0, tag)
    yield from _sched_scatterv(comm, full, recvbuf, counts, displs,
                               dtype, 0, tag)


# -- persistent collectives (MPI-4 *_init over the schedule engine) --------

class PersistentCollRequest(rq.Request):
    """MPI-4 persistent collective: start() re-launches the schedule;
    the request is reusable (reference: the 17 *_init slots of
    coll.h:532-649, implemented in libnbc).

    ``completed`` proxies the live schedule, so the plural waits
    (wait_all/wait_any/test_all) — which poll ``r.completed`` while
    spinning the progress engine — observe completion without needing
    a per-request test() call."""

    def __init__(self, factory) -> None:
        super().__init__()
        self.persistent = True
        self._factory = factory
        self._inner: Optional[NbcRequest] = None
        self._idle_done = True  # inactive counts as complete (MPI)

    @property
    def completed(self) -> bool:
        if self._inner is not None:
            return self._inner.completed
        return self._idle_done

    @completed.setter
    def completed(self, v: bool) -> None:  # base __init__ writes here
        self._idle_done = bool(v)

    def start(self) -> None:
        if self._inner is not None and not self._inner.completed:
            raise RuntimeError("persistent collective already active")
        self._inner = NbcRequest(self._factory())

    def test(self) -> bool:
        if not self.completed:
            progress.progress()
        return self.completed

    def wait(self, timeout=None):
        if self._inner is not None:
            return self._inner.wait(timeout)
        return self.status


# -- component ------------------------------------------------------------

# -- nonblocking neighborhood (ineighbor_allgather.c family): one
# linear round over the topology's neighbor lists, posted at start --

def _sched_neighbor(comm, reqs):
    yield reqs


def ineighbor_allgather(comm, sendbuf, recvbuf, count, dtype):
    return NbcRequest(_sched_neighbor(
        comm, B.neighbor_allgather_reqs(comm, sendbuf, recvbuf,
                                        count, dtype)))


def ineighbor_alltoall(comm, sendbuf, recvbuf, count, dtype):
    return NbcRequest(_sched_neighbor(
        comm, B.neighbor_alltoall_reqs(comm, sendbuf, recvbuf,
                                       count, dtype)))


def ineighbor_allgatherv(comm, sendbuf, recvbuf, count, dtype,
                         rcounts, rdispls):
    return NbcRequest(_sched_neighbor(
        comm, B.neighbor_allgatherv_reqs(comm, sendbuf, recvbuf,
                                         count, dtype, rcounts,
                                         rdispls)))


def ineighbor_alltoallv(comm, sendbuf, recvbuf, dtype, scounts,
                        sdispls, rcounts, rdispls):
    return NbcRequest(_sched_neighbor(
        comm, B.neighbor_alltoallv_reqs(comm, sendbuf, recvbuf,
                                        dtype, scounts, sdispls,
                                        rcounts, rdispls)))


def ibarrier(comm):
    return NbcRequest(_sched_barrier(comm, _tag(comm)))


def ibcast(comm, buf, count, dtype, root):
    return NbcRequest(_sched_bcast(comm, buf, count, dtype, root,
                                   _tag(comm)))


def iallreduce(comm, sendbuf, recvbuf, count, dtype, op):
    return NbcRequest(_sched_allreduce(comm, sendbuf, recvbuf, count,
                                       dtype, op, _tag(comm)))


def ireduce(comm, sendbuf, recvbuf, count, dtype, op, root):
    return NbcRequest(_sched_reduce(comm, sendbuf, recvbuf, count,
                                    dtype, op, root, _tag(comm)))


def igather(comm, sendbuf, recvbuf, count, dtype, root):
    return NbcRequest(_sched_gather(comm, sendbuf, recvbuf, count,
                                    dtype, root, _tag(comm)))


def iscatter(comm, sendbuf, recvbuf, count, dtype, root):
    return NbcRequest(_sched_scatter(comm, sendbuf, recvbuf, count,
                                     dtype, root, _tag(comm)))


def iallgather(comm, sendbuf, recvbuf, count, dtype):
    return NbcRequest(_sched_allgather(comm, sendbuf, recvbuf, count,
                                       dtype, _tag(comm)))


def ialltoall(comm, sendbuf, recvbuf, count, dtype):
    return NbcRequest(_sched_alltoall(comm, sendbuf, recvbuf, count,
                                      dtype, _tag(comm)))


def igatherv(comm, sendbuf, recvbuf, counts, displs, dtype, root):
    return NbcRequest(_sched_gatherv(comm, sendbuf, recvbuf, counts,
                                     displs, dtype, root, _tag(comm)))


def iscatterv(comm, sendbuf, recvbuf, counts, displs, dtype, root):
    return NbcRequest(_sched_scatterv(comm, sendbuf, recvbuf, counts,
                                      displs, dtype, root, _tag(comm)))


def iallgatherv(comm, sendbuf, recvbuf, counts, displs, dtype):
    return NbcRequest(_sched_allgatherv(comm, sendbuf, recvbuf, counts,
                                        displs, dtype, _tag(comm)))


def ialltoallv(comm, sendbuf, recvbuf, scounts, sdispls, rcounts,
               rdispls, dtype):
    return NbcRequest(_sched_alltoallv(
        comm, sendbuf, recvbuf, scounts, sdispls, rcounts, rdispls,
        dtype, _tag(comm)))


def iscan(comm, sendbuf, recvbuf, count, dtype, op):
    return NbcRequest(_sched_scan(comm, sendbuf, recvbuf, count, dtype,
                                  op, _tag(comm), exclusive=False))


def iexscan(comm, sendbuf, recvbuf, count, dtype, op):
    return NbcRequest(_sched_scan(comm, sendbuf, recvbuf, count, dtype,
                                  op, _tag(comm), exclusive=True))


def ireduce_scatter_block(comm, sendbuf, recvbuf, count, dtype, op):
    return NbcRequest(_sched_reduce_scatter_block(
        comm, sendbuf, recvbuf, count, dtype, op, _tag(comm)))


def ireduce_scatter(comm, sendbuf, recvbuf, counts, dtype, op):
    return NbcRequest(_sched_reduce_scatter(
        comm, sendbuf, recvbuf, counts, dtype, op, _tag(comm)))


def _persistent(sched, comm, *args):
    # one tag per start: each launch is a distinct operation on the
    # collective context
    return PersistentCollRequest(lambda: sched(comm, *args, _tag(comm)))


def barrier_init(comm):
    return _persistent(_sched_barrier, comm)


def bcast_init(comm, buf, count, dtype, root):
    return _persistent(_sched_bcast, comm, buf, count, dtype, root)


def allreduce_init(comm, sendbuf, recvbuf, count, dtype, op):
    return _persistent(_sched_allreduce, comm, sendbuf, recvbuf, count,
                       dtype, op)


def reduce_init(comm, sendbuf, recvbuf, count, dtype, op, root):
    return _persistent(_sched_reduce, comm, sendbuf, recvbuf, count,
                       dtype, op, root)


def gather_init(comm, sendbuf, recvbuf, count, dtype, root):
    return _persistent(_sched_gather, comm, sendbuf, recvbuf, count,
                       dtype, root)


def scatter_init(comm, sendbuf, recvbuf, count, dtype, root):
    return _persistent(_sched_scatter, comm, sendbuf, recvbuf, count,
                       dtype, root)


def allgather_init(comm, sendbuf, recvbuf, count, dtype):
    return _persistent(_sched_allgather, comm, sendbuf, recvbuf, count,
                       dtype)


def alltoall_init(comm, sendbuf, recvbuf, count, dtype):
    return _persistent(_sched_alltoall, comm, sendbuf, recvbuf, count,
                       dtype)


def reduce_scatter_block_init(comm, sendbuf, recvbuf, count, dtype,
                              op):
    # completes the host persistent table for the five collectives the
    # device path makes persistent (mpi.py used to raise TypeError on
    # the host form of Reduce_scatter_block_init)
    return _persistent(_sched_reduce_scatter_block, comm, sendbuf,
                       recvbuf, count, dtype, op)


@framework.register
class CollLibnbc(CollModule):
    NAME = "libnbc"
    PRIORITY = 20

    def slots(self, comm):
        return {
            "ibarrier": ibarrier,
            "ibcast": ibcast,
            "iallreduce": iallreduce,
            "ireduce": ireduce,
            "igather": igather,
            "iscatter": iscatter,
            "iallgather": iallgather,
            "ialltoall": ialltoall,
            "igatherv": igatherv,
            "iscatterv": iscatterv,
            "iallgatherv": iallgatherv,
            "ialltoallv": ialltoallv,
            "iscan": iscan,
            "iexscan": iexscan,
            "ireduce_scatter": ireduce_scatter,
            "ireduce_scatter_block": ireduce_scatter_block,
            "ineighbor_allgather": ineighbor_allgather,
            "ineighbor_alltoall": ineighbor_alltoall,
            "ineighbor_allgatherv": ineighbor_allgatherv,
            "ineighbor_alltoallv": ineighbor_alltoallv,
            # MPI-4 persistent collectives
            "barrier_init": barrier_init,
            "bcast_init": bcast_init,
            "allreduce_init": allreduce_init,
            "reduce_init": reduce_init,
            "gather_init": gather_init,
            "scatter_init": scatter_init,
            "allgather_init": allgather_init,
            "alltoall_init": alltoall_init,
            "reduce_scatter_block_init": reduce_scatter_block_init,
        }
