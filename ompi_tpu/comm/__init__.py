"""Groups and communicators.

Reference: ompi/group/ (set-algebra over proc lists) and ompi/communicator/
(CID allocation over PMIx groups, comm_cid.c:297-463; dup/split/create).
A communicator = (Group mapping comm rank -> world rank, cid, coll table,
errhandler, FT state). Context-id space: p2p uses tag context cid*2,
collectives cid*2+1 (the reference splits tag space the same way).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ompi_tpu import errors
from ompi_tpu.attr import AttrHost
from ompi_tpu.core import output
from ompi_tpu.runtime import rte

_out = output.stream("comm")

UNDEFINED = -32766


class Group:
    """MPI_Group: an ordered set of world ranks."""

    __slots__ = ("ranks", "_index")

    def __init__(self, ranks: Sequence[int]) -> None:
        self.ranks: Tuple[int, ...] = tuple(ranks)
        self._index = {r: i for i, r in enumerate(self.ranks)}

    @property
    def size(self) -> int:
        return len(self.ranks)

    @property
    def rank(self) -> int:
        """This process's rank in the group (UNDEFINED if absent)."""
        return self._index.get(rte.rank, UNDEFINED)

    def translate(self, rank: int, other: "Group") -> int:
        """MPI_Group_translate_ranks for one rank."""
        world = self.ranks[rank]
        return other._index.get(world, UNDEFINED)

    # -- set algebra (MPI_Group_union/intersection/difference) -----------
    def union(self, other: "Group") -> "Group":
        extra = [r for r in other.ranks if r not in self._index]
        return Group(list(self.ranks) + extra)

    def intersection(self, other: "Group") -> "Group":
        return Group([r for r in self.ranks if r in other._index])

    def difference(self, other: "Group") -> "Group":
        return Group([r for r in self.ranks if r not in other._index])

    def incl(self, ranks: Sequence[int]) -> "Group":
        return Group([self.ranks[r] for r in ranks])

    def excl(self, ranks: Sequence[int]) -> "Group":
        drop = set(ranks)
        return Group([r for i, r in enumerate(self.ranks)
                      if i not in drop])

    def range_incl(self, ranges) -> "Group":
        out = []
        for first, last, stride in ranges:
            out.extend(range(first, last + (1 if stride > 0 else -1),
                             stride))
        return self.incl(out)

    def compare(self, other: "Group") -> str:
        if self.ranks == other.ranks:
            return "ident"
        if set(self.ranks) == set(other.ranks):
            return "similar"
        return "unequal"

    def __repr__(self) -> str:
        return f"Group({list(self.ranks)})"


_comms: Dict[int, "Communicator"] = {}
_comms_lock = threading.Lock()


def lookup_cid(cid: int) -> Optional["Communicator"]:
    return _comms.get(cid)


class Communicator(AttrHost):
    """Base communicator: group + cid + per-comm collective table.

    P2P methods (send/recv families) and collective methods are attached
    by ompi_tpu.mpi (the API layer) and ompi_tpu.coll (table stacking) —
    this module owns identity, construction and destruction. Attribute
    caching (Set/Get/Delete_attr) comes from AttrHost.
    """

    _attr_kind = "comm"

    def __init__(self, group: Group, cid: int,
                 errhandler: str = errors.ERRORS_ARE_FATAL) -> None:
        self.group = group
        self.cid = cid
        self.errhandler = errhandler
        from ompi_tpu.info import Info

        self.attrs: Dict[object, object] = {}  # MPI_Comm_set_attr
        self.info = Info()  # MPI_Comm_set_info plane
        self.name = f"comm#{cid}"
        self.revoked = False  # ULFM state
        self.coll = None  # installed by coll.comm_select
        self.topo = None  # cart/graph attachment
        with _comms_lock:
            _comms[cid] = self
        from ompi_tpu.coll import comm_select

        comm_select(self)
        # replay any frames peers sent before we constructed this comm
        from ompi_tpu import pml as _pml

        if _pml._pml is not None:
            _pml.current().comm_registered(cid)

    # -- identity ---------------------------------------------------------
    @property
    def rank(self) -> int:
        return self.group.rank

    @property
    def size(self) -> int:
        return self.group.size

    def world_rank(self, rank: int) -> int:
        """comm rank -> world (job) rank."""
        if rank == self.rank:
            return rte.rank
        return self.group.ranks[rank]

    def comm_rank_of_world(self, world: int) -> int:
        return self.group._index.get(world, UNDEFINED)

    def Topo_test(self) -> str:
        """MPI_Topo_test: the topology kind attached to this comm —
        'cart' / 'graph' / 'dist_graph' / 'undefined'
        (ompi/mpi/c/topo_test.c)."""
        return getattr(self.topo, "kind", "undefined") \
            if self.topo is not None else "undefined"

    def Is_inter(self) -> bool:
        """MPI_Comm_test_inter."""
        return bool(getattr(self, "is_inter", False))

    def Get_group(self) -> Group:
        """MPI_Comm_group: a NEW group handle over this comm's
        membership (group handles are independent of the comm)."""
        return Group(self.group.ranks)

    def set_name(self, name: str) -> None:
        self.name = name

    def get_name(self) -> str:
        return self.name

    # -- construction (collective over self) ------------------------------
    def _materialize_dup(self, cid: int) -> "Communicator":
        """Construction tail shared by dup and Idup: errhandler, info
        hints (MPI-4 §7.4.1) and keyval copy callbacks
        (ompi_attr_copy_all) all propagate."""
        c = Communicator(Group(self.group.ranks), cid,
                         self.errhandler)
        c.info = self.info.dup()
        if self.attrs:
            from ompi_tpu import attr as _attr

            _attr.copy_attrs(self, c, "comm")
        return c

    def dup(self) -> "Communicator":
        """MPI_Comm_dup."""
        return self._materialize_dup(self._agree_cid(f"dup:{self.cid}"))

    def _sched_idup(self, out: dict):
        """Idup rounds: rank 0 allocates the cid and ships it over
        the object channel; construction + attribute copy callbacks
        run at completion (MPI-4: idup copies attrs like dup)."""
        from ompi_tpu import pml

        p = pml.current()
        tag = self.coll.next_tag()
        if self.rank == 0:
            cid = alloc_cid()
            yield [p.isend_obj(self, cid, d, tag, collective=True)
                   for d in range(1, self.size)]
        else:
            r = p.irecv_obj(self, 0, tag, collective=True)
            yield [r]
            if r.status.error:  # e.g. rank 0 died (ULFM recv sweep):
                # surface at the request's wait, never build a
                # cid=None communicator
                errors.raise_mpi_error(r.status.error,
                                       "idup cid recv failed")
            cid = r._obj
        out["comm"] = self._materialize_dup(cid)

    def Idup(self):
        """MPI_Comm_idup (ompi/mpi/c/comm_idup.c): nonblocking dup.
        The new communicator is ``req.result["comm"]`` after the
        request completes; overlap compute/p2p until then."""
        from ompi_tpu.coll import libnbc

        out: dict = {}
        req = libnbc.NbcRequest(self._sched_idup(out))
        req.result = out
        return req

    def create_group(self, group: Group,
                     tag: int = 0) -> "Communicator":
        """MPI_Comm_create_group (ompi/mpi/c/comm_create_group.c):
        collective over GROUP members ONLY — non-members do not call
        (unlike Comm_create, which is collective over the whole
        comm). Distinct concurrent creations disambiguate by tag."""
        c = comm_create_from_group(
            group, tag=f"ccg:{self.cid}:{int(tag)}")
        if c is not None:  # errhandler/info inherit from the parent
            c.errhandler = self.errhandler
            c.info = self.info.dup()
        return c

    def split(self, color: int, key: int = 0) -> Optional["Communicator"]:
        """MPI_Comm_split — gather (color,key) at root, compute groups,
        scatter results (reference does allgather + local compute;
        root-compute keeps the p2p bootstrap simple)."""
        from ompi_tpu import mpi

        me = (color, key, rte.rank)
        all_triples = self._gather_obj(me, root=0)
        if self.rank == 0:
            groups: Dict[int, List[Tuple]] = {}
            for t in all_triples:
                if t[0] != UNDEFINED:
                    groups.setdefault(t[0], []).append(t)
            plans = {}
            for col, members in groups.items():
                members.sort(key=lambda t: (t[1], t[2]))
                ranks = [t[2] for t in members]
                cid = alloc_cid()
                for t in members:
                    plans[t[2]] = (ranks, cid)
            results = [plans.get(t[2]) for t in all_triples]
        else:
            results = None
        mine = self._scatter_obj(results, root=0)
        if mine is None:
            return None
        ranks, cid = mine
        c = Communicator(Group(ranks), cid, self.errhandler)
        c.info = self.info.dup()  # hints propagate like errhandler
        return c

    def split_type(self, split_type: str = "shared",
                   key: int = 0) -> Optional["Communicator"]:
        """MPI_Comm_split_type(MPI_COMM_TYPE_SHARED): all our ranks are
        reachable by shared memory within a host; color by hostname."""
        import hashlib

        from ompi_tpu.runtime import rte

        host = rte.hostname()
        # stable digest: Python's hash() is salted per process
        color = int.from_bytes(
            hashlib.sha1(host.encode()).digest()[:4], "little") \
            & 0x7FFFFFFF
        return self.split(color, key)

    def create(self, group: Group) -> Optional["Communicator"]:
        """MPI_Comm_create."""
        color = 0 if group.rank != UNDEFINED else UNDEFINED
        sub = self.split(color, key=group.rank)
        if sub is None:
            return None
        return sub

    def free(self) -> None:
        if self.attrs:  # delete callbacks fire BEFORE destruction
            from ompi_tpu import attr as _attr

            _attr.delete_attrs(self, "comm")
        # release coll/xla per-comm state: the compiled-program and
        # fused-plan caches hold XLA executables + device operands —
        # long-lived jobs creating/freeing comms with shape churn must
        # not retain them past the comm's lifetime (attribute-based so
        # identity never imports the coll component)
        ctx = self.__dict__.pop("_coll_xla_ctx", None)
        if ctx is not None:
            ctx.release()
        self.__dict__.pop("_coll_xla_scatter_meta", None)
        self.__dict__.pop("_coll_xla_a2av_meta", None)
        # coll/hier grid plan (Mesh + sharding over this comm's
        # devices) dies with the comm
        self.__dict__.pop("_coll_hier_plan", None)
        # coll/han lazy sub-communicators: the low/up splits are full
        # Comms with their own cids and coll state — free them with
        # the parent instead of leaking them for the life of the job
        levels = self.__dict__.pop("_han_levels", None)
        if levels is not None:
            levels.release()
        self.__dict__.pop("_han_colors", None)
        # partitioned-p2p pairing epochs (part/host) die with the cid
        self.__dict__.pop("_part_epochs", None)
        # ULFM agreement/shrink epochs die with the cid too — a
        # reused cid must not alias a dead comm's epoch sequence
        from ompi_tpu.ft import release_comm as _ft_release

        _ft_release(self.cid)
        with _comms_lock:
            _comms.pop(self.cid, None)
        # the check-plane sanitizer flags any later call on this comm
        self._freed = True

    # -- ULFM (reference: ompi/communicator/ft) ---------------------------
    def revoke(self) -> None:
        from ompi_tpu.ft import revoke as _revoke

        _revoke(self)

    def is_revoked(self) -> bool:
        return self.revoked

    def check_revoked(self) -> None:
        if self.revoked:
            raise errors.RevokedError()

    def check_failed(self) -> None:
        """Collective-entry FT gate (see ft.check_comm_failed); p2p
        paths must NOT call this — sends/recvs among survivors stay
        legal after a failure."""
        from ompi_tpu.ft import check_comm_failed

        check_comm_failed(self)

    def shrink(self) -> "Communicator":
        """MPIX_Comm_shrink."""
        from ompi_tpu.ft import shrink as _shrink

        return _shrink(self)

    def iagree(self, flag: int):
        """MPIX_Comm_iagree -> request; after wait, .result is
        blocking agree's (value, failed) tuple."""
        from ompi_tpu.ft import iagree as _iagree

        return _iagree(self, flag)

    def agree(self, flag: int):
        """MPIX_Comm_agree -> (flag AND-combined over survivors,
        failed comm ranks)."""
        from ompi_tpu.ft import agree as _agree

        return _agree(self, flag)

    def get_failed(self):
        """MPIX_Comm_get_failed -> sorted failed comm ranks."""
        from ompi_tpu.ft import get_failed as _get_failed

        return _get_failed(self)

    def ack_failed(self) -> int:
        """MPIX_Comm_ack_failed -> number of failures acknowledged."""
        from ompi_tpu.ft import ack_failed as _ack_failed

        return _ack_failed(self)

    # -- internal p2p helpers used before coll exists ---------------------
    def _gather_obj(self, obj, root: int):
        from ompi_tpu import pml

        p = pml.current()
        if self.rank == root:
            out = [None] * self.size
            out[self.rank] = obj
            reqs = []
            for r in range(self.size):
                if r != self.rank:
                    reqs.append((r, p.irecv_obj(self, r, tag=-7)))
            for r, req in reqs:
                req.wait()
                out[r] = req._obj
            return out
        p.send_obj(self, obj, root, tag=-7)
        return None

    def _scatter_obj(self, objs, root: int):
        from ompi_tpu import pml

        p = pml.current()
        if self.rank == root:
            for r in range(self.size):
                if r != self.rank:
                    p.send_obj(self, objs[r], r, tag=-8)
            return objs[self.rank]
        req = p.irecv_obj(self, root, tag=-8)
        req.wait()
        return req._obj

    def _agree_cid(self, tag: str) -> int:
        """All members agree on a fresh cid: rank 0 allocates, others
        receive (reference: comm_cid.c PMIx-group allocation)."""
        if self.rank == 0:
            cid = alloc_cid()
            payload = [cid] * self.size
            self._scatter_obj(payload, root=0)
            return cid
        return self._scatter_obj(None, root=0)

    def __repr__(self) -> str:
        return (f"Communicator({self.name}, rank={self.rank}/"
                f"{self.size}, cid={self.cid})")


def alloc_cid() -> int:
    """Globally-unique communicator id (store-side atomic counter)."""
    return 1 + rte.next_id("cid")


_cfg_epochs: Dict[str, int] = {}


def comm_create_from_group(group: Group,
                           tag: str) -> Optional[Communicator]:
    """MPI_Comm_create_from_group (MPI-4 sessions path): agreement via
    the store keyed by the user-supplied tag, no parent needed. Members
    call in the same order per (tag, group), so a local epoch counter
    keeps repeated invocations distinct."""
    if group.rank == UNDEFINED:
        return None
    client = rte.client()
    base_key = f"cfg:{rte.jobid}:{tag}:{','.join(map(str, group.ranks))}"
    epoch = _cfg_epochs.get(base_key, 0)
    _cfg_epochs[base_key] = epoch + 1
    key = f"{base_key}:{epoch}"
    if group.rank == 0:
        cid = alloc_cid()
        client.put(key, cid)
    else:
        cid = client.get(key, wait=True)
    return Communicator(group, cid)


def build_world() -> Tuple[Communicator, Communicator]:
    """COMM_WORLD (cid 0) + COMM_SELF (cid 1). A spawned world's
    COMM_WORLD spans its own world-rank block (rte.world_ranks) —
    cross-world traffic only ever rides intercomm CIDs from the shared
    store counter, so the per-world cid 0/1 never collide on the
    wire."""
    rte.init()
    world = Communicator(Group(rte.world_ranks()), cid=0)
    world.set_name("MPI_COMM_WORLD")
    selfc = Communicator(Group([rte.rank]), cid=1)
    selfc.set_name("MPI_COMM_SELF")
    return world, selfc
