"""Intercommunicators + dynamic process connect/accept (dpm-lite).

Reference: ompi/communicator/comm.c (intercomm create/merge),
ompi/mca/coll/inter + coll/basic's inter algorithms (local reduce ->
leader exchange -> local bcast), ompi/dpm/dpm.c:386 (connect/accept
rendezvous through the naming service — here the kv store plays ompi's
PMIx publish/lookup role).

An intercommunicator binds a *local* group and a *remote* group under
one CID: p2p ranks address the remote group; collectives have
group-vs-group semantics (each side receives the other side's
contribution). A private local intracomm (built from the local group at
creation, as the reference's comm->c_local_comm) carries the
local phases of the inter algorithms.

Scope note: connect/accept pairs any two disjoint rank sets *within a
job's store* (the launcher can also share one store across jobs via
``tpurun --store``); MPI_Comm_spawn's process-starting side is the
launcher's domain, not the communicator layer's.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ompi_tpu import errors
from ompi_tpu.comm import (Communicator, Group, alloc_cid,
                           comm_create_from_group)
from ompi_tpu.runtime import rte

#: MPI_ROOT / MPI_PROC_NULL sentinels for inter-collective root args
ROOT = -4


class Intercommunicator(Communicator):
    """Communicator with distinct local and remote groups."""

    is_inter = True

    def __init__(self, local_group: Group, remote_group: Group,
                 cid: int, errhandler=None) -> None:
        if set(local_group.ranks) & set(remote_group.ranks):
            raise ValueError(
                "intercomm groups must be disjoint (MPI_ERR_COMM)")
        # remote_group must exist before Communicator.__init__ runs
        # comm_select (components may inspect it)
        self.remote_group = remote_group
        super().__init__(local_group, cid,
                         errhandler or errors.ERRORS_ARE_FATAL)
        self.name = f"intercomm#{cid}"
        # the local phases of inter collectives ride a private
        # intracomm over the local group (reference: c_local_comm)
        self.local_comm = comm_create_from_group(
            local_group, tag=f"icl:{cid}")

    # -- identity ---------------------------------------------------------
    @property
    def remote_size(self) -> int:
        return self.remote_group.size

    def world_rank(self, rank: int) -> int:
        """p2p destination ranks index the REMOTE group."""
        return self.remote_group.ranks[rank]

    # -- MPI_Intercomm_merge ---------------------------------------------
    def merge(self, high: bool = False) -> Communicator:
        """Union intracomm; the `low` side's ranks come first. Ties
        (both sides claim the same polarity) break by smallest world
        rank, as the reference does."""
        flags = self.local_comm.allgather(bool(high))
        my_high = flags[0]
        # exchange polarity with the remote side (leaders, then bcast)
        if self.rank == 0:
            their_high = self.sendrecv(my_high, dest=0, source=0,
                                       sendtag=-21, recvtag=-21)
        else:
            their_high = None
        their_high = self.local_comm.bcast(their_high, root=0)
        mine, theirs = list(self.group.ranks), list(self.remote_group.ranks)
        if my_high == their_high:
            first = mine if min(mine) < min(theirs) else theirs
        else:
            first = theirs if my_high else mine
        second = theirs if first is mine else mine
        merged = Group(first + second)
        return comm_create_from_group(merged, tag=f"imerge:{self.cid}")


def intercomm_create(local_comm: Communicator, local_leader: int,
                     peer_comm: Communicator, remote_leader: int,
                     tag: int = 0) -> Intercommunicator:
    """MPI_Intercomm_create: leaders exchange groups through peer_comm,
    agree a CID, then broadcast locally (comm.c:ompi_intercomm_create)."""
    me_leader = local_comm.rank == local_leader
    if me_leader:
        mine = list(local_comm.group.ranks)
        other = peer_comm.sendrecv(mine, dest=remote_leader,
                                   source=remote_leader,
                                   sendtag=tag, recvtag=tag)
        # disjoint groups guarantee distinct minima: smaller-min leader
        # allocates the shared CID
        if min(mine) < min(other):
            cid = alloc_cid()
            peer_comm.send(cid, remote_leader, tag)
        else:
            cid = peer_comm.recv(source=remote_leader, tag=tag)
        data = (other, cid)
    else:
        data = None
    other, cid = local_comm.bcast(data, root=local_leader)
    return Intercommunicator(Group(local_comm.group.ranks),
                             Group(other), cid)


# ---------------------------------------------------------------------------
# dpm-lite: Open_port / Comm_accept / Comm_connect over the store
# (reference: ompi/dpm/dpm.c:386 connect/accept; the store's atomic
# keyspace replaces PMIx publish/lookup)


def open_port(name: Optional[str] = None) -> str:
    """MPI_Open_port: a store-unique rendezvous name."""
    if name is None:
        name = f"port:{rte.jobid}:{rte.next_id('port')}"
    return name


def _port_rendezvous(port: str, comm: Communicator, root: int,
                     side: str) -> Intercommunicator:
    """Publish my group on my side's key, wait for the peer's, agree
    the CID through the store (accept side allocates)."""
    client = rte.client()
    me_root = comm.rank == root
    if me_root:
        client.put(f"{port}:{side}", list(comm.group.ranks))
        other_side = "connect" if side == "accept" else "accept"
        other = client.get(f"{port}:{other_side}", wait=True)
        if side == "accept":
            cid = alloc_cid()
            client.put(f"{port}:cid", cid)
        else:
            cid = client.get(f"{port}:cid", wait=True)
        data = (other, cid)
    else:
        data = None
    other, cid = comm.bcast(data, root=root)
    return Intercommunicator(Group(comm.group.ranks), Group(other), cid)


def comm_accept(port: str, comm: Communicator,
                root: int = 0) -> Intercommunicator:
    return _port_rendezvous(port, comm, root, "accept")


def comm_connect(port: str, comm: Communicator,
                 root: int = 0) -> Intercommunicator:
    return _port_rendezvous(port, comm, root, "connect")


def _attach() -> None:
    Communicator.is_inter = False
    Communicator.remote_group = None
    Communicator.Intercomm_merge = lambda self, high=False: \
        self.merge(high)


_attach()
