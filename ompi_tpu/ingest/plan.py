"""IngestPlan — deterministic partition of a pytree into upload units.

The ingest analog of the partitioned-send buffer split
(part/host.py): the param/data pytree is flattened, every leaf is cut
into contiguous flat element ranges of at most ``ingest_chunk_bytes``
each, and the resulting units are assigned round-robin to the upload
streams. Everything is a pure function of (leaf shapes/dtypes,
chunk_bytes, n_streams) — two ranks building the plan from the same
pytree agree on every unit boundary, which is what lets the gating
surface ("step 1 touches leaves 0 and 3") be stated in terms of plan
indices.

Units are the ``Parrived`` granularity: one unit == one staged
device_put == one completion event on the request.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ompi_tpu import errors


class Unit:
    """One upload unit: elements [lo, hi) of flat leaf ``leaf``."""

    __slots__ = ("idx", "leaf", "lo", "hi", "nbytes", "stream")

    def __init__(self, idx: int, leaf: int, lo: int, hi: int,
                 nbytes: int, stream: int) -> None:
        self.idx = idx
        self.leaf = leaf
        self.lo = lo
        self.hi = hi
        self.nbytes = nbytes
        self.stream = stream

    def key(self) -> Tuple[int, int, int, int, int, int]:
        return (self.idx, self.leaf, self.lo, self.hi, self.nbytes,
                self.stream)

    def __repr__(self) -> str:
        return (f"Unit(idx={self.idx}, leaf={self.leaf}, "
                f"[{self.lo},{self.hi}), {self.nbytes}B, "
                f"stream={self.stream})")


def _flatten(tree):
    """(leaves, treedef, keystrs) via jax when available; a bare
    list/tuple/dict of arrays degrades to a None treedef so the plan
    (and bit-identity tests) work without pulling jax in."""
    try:
        from jax import tree_util as jtu
    except Exception:  # pragma: no cover - jax is baked into the image
        if isinstance(tree, dict):
            keys = sorted(tree)
            return [tree[k] for k in keys], None, [f"['{k}']"
                                                   for k in keys]
        if isinstance(tree, (list, tuple)):
            return list(tree), None, [f"[{i}]"
                                      for i in range(len(tree))]
        return [tree], None, [""]
    flat, treedef = jtu.tree_flatten(tree)
    try:
        keystrs = [jtu.keystr(kp) for kp, _ in
                   jtu.tree_flatten_with_path(tree)[0]]
    except Exception:  # older jax without the keypath API
        keystrs = [f"[{i}]" for i in range(len(flat))]
    return flat, treedef, keystrs


class IngestPlan:
    """Deterministic unit decomposition of one pytree upload."""

    def __init__(self, leaves: Sequence[Any], chunk_bytes: int,
                 n_streams: int, treedef=None,
                 keystrs: Optional[List[str]] = None) -> None:
        if chunk_bytes < 1:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"ingest_chunk_bytes must be >= 1 (got {chunk_bytes})")
        if n_streams < 1:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"ingest_streams must be >= 1 (got {n_streams})")
        self.chunk_bytes = int(chunk_bytes)
        self.n_streams = int(n_streams)
        self.treedef = treedef
        self.keystrs = keystrs or [f"[{i}]"
                                   for i in range(len(leaves))]
        #: host-side leaves, contiguous (views where already so; note
        #: ascontiguousarray only on the copy path — it would promote
        #: 0-d scalars to 1-d and lose the shape)
        self.leaves: List[np.ndarray] = []
        for lf in leaves:
            arr = np.asarray(lf)
            if not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr).reshape(arr.shape)
            self.leaves.append(arr)
        self.units: List[Unit] = []
        #: leaf index -> this leaf's units, in flat order
        self.leaf_units: List[List[Unit]] = []
        idx = 0
        for li, arr in enumerate(self.leaves):
            mine: List[Unit] = []
            size = int(arr.size)
            itemsize = max(1, int(arr.itemsize))
            if size == 0:
                # zero-size leaves still get ONE unit so Parrived /
                # gating indices stay total over the tree
                u = Unit(idx, li, 0, 0, 0, idx % self.n_streams)
                self.units.append(u)
                mine.append(u)
                idx += 1
                self.leaf_units.append(mine)
                continue
            chunk_elems = max(1, self.chunk_bytes // itemsize)
            nch = -(-size // chunk_elems)  # ceil
            base, rem = divmod(size, nch)
            lo = 0
            for c in range(nch):
                hi = lo + base + (1 if c < rem else 0)
                u = Unit(idx, li, lo, hi, (hi - lo) * itemsize,
                         idx % self.n_streams)
                self.units.append(u)
                mine.append(u)
                idx += 1
                lo = hi
            self.leaf_units.append(mine)
        self.n_units = len(self.units)
        self.total_bytes = sum(u.nbytes for u in self.units)
        #: largest single unit — sizes the engine's staging buffers
        self.max_unit_bytes = max(
            (u.nbytes for u in self.units), default=0)
        self._key_index: Dict[str, int] = {
            k: i for i, k in enumerate(self.keystrs)}

    @classmethod
    def from_tree(cls, tree, chunk_bytes: int,
                  n_streams: int) -> "IngestPlan":
        leaves, treedef, keystrs = _flatten(tree)
        return cls(leaves, chunk_bytes, n_streams, treedef=treedef,
                   keystrs=keystrs)

    def leaf_index(self, key) -> int:
        """Resolve a leaf reference: an int index, an exact jax
        keystr (``"['w0']"``), or the bare dict-key/field shorthand
        (``"w0"``)."""
        if isinstance(key, int):
            if not 0 <= key < len(self.leaves):
                raise errors.MPIError(
                    errors.ERR_ARG,
                    f"leaf index {key} out of "
                    f"[0,{len(self.leaves)})")
            return key
        if key in self._key_index:
            return self._key_index[key]
        sugar = f"['{key}']"
        if sugar in self._key_index:
            return self._key_index[sugar]
        raise errors.MPIError(
            errors.ERR_ARG,
            f"unknown leaf {key!r} (known: {self.keystrs})")

    def units_for(self, keys) -> List[Unit]:
        """The units covering the given leaves (gating input)."""
        out: List[Unit] = []
        for key in keys:
            out.extend(self.leaf_units[self.leaf_index(key)])
        return out

    def stream_units(self, stream: int) -> List[Unit]:
        """This stream's units, in submission order."""
        return [u for u in self.units if u.stream == stream]

    def signature(self) -> Tuple:
        """Hashable identity: equal signatures <=> identical plans
        (the determinism contract the tests pin)."""
        return (self.chunk_bytes, self.n_streams,
                tuple(u.key() for u in self.units))
