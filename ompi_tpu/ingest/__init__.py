"""ompi_tpu.ingest — the streaming ingest plane (ninth subsystem).

Turns the serial ``device_put``-everything-then-compile cold start
(BENCH_r04/r05: 442–471s of a ~488s wall before step 1 — ROADMAP
item 1, THE production-latency item) into a pipeline:

- **chunked multi-stream upload** with double-buffered pinned staging
  rings (cvars ``ingest_streams`` / ``ingest_chunk_bytes`` /
  ``ingest_depth``) over the accelerator component's H2D stream pool;
- **compile/upload overlap** — ``_Ctx`` fn/plan compilation and the
  jax persistent-cache warm path run on a dedicated stream
  concurrently with the upload, proven by the prof ledger's
  ``prof_phase_overlap_ns`` accounting;
- **partial availability** — the MPI-4 ``Pready``/``Parrived`` model
  (shared with :mod:`ompi_tpu.part` via
  :class:`~ompi_tpu.part.partial.PartialAvailability`): an
  :class:`~ompi_tpu.ingest.plan.IngestPlan` partitions the pytree
  into upload units, the request exposes per-unit completion, and
  :meth:`~ompi_tpu.ingest.engine.IngestRequest.gate` starts step 1 on
  the units it actually touches while the tail uploads.

Enable with ``--mca ingest_enable 1`` (or ``OMPI_TPU_INGEST=1``); the
live engine is the one-branch guard global
``ompi_tpu.ingest.engine.INGEST``. Off by default; a standalone
:class:`~ompi_tpu.ingest.engine.IngestEngine` works without the plane
(bench/tests construct their own).
"""

from __future__ import annotations

from ompi_tpu.ingest.engine import (  # noqa: F401  (public re-exports)
    IngestEngine, IngestRequest, default_put, disable, enable,
    requested,
)
from ompi_tpu.ingest.plan import IngestPlan, Unit  # noqa: F401


def start(rank: int = 0) -> "IngestEngine":
    """Plane bring-up (runtime/state.init_instance)."""
    return enable(rank=rank)


def stop() -> None:
    """Plane teardown (runtime/state._release)."""
    disable()
