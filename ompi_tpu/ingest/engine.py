"""Streaming ingest engine — pipelined, partially-available H2D upload.

BENCH_r04/r05: 442–471s of a ~488s wall is serial
device_put-everything-then-compile before step 1. This engine turns
that cold start into a pipeline with three mechanisms:

1. **Multi-stream, double-buffered upload.** The
   :class:`~ompi_tpu.ingest.plan.IngestPlan` cuts the pytree into
   units of at most ``ingest_chunk_bytes``, assigned round-robin to
   ``ingest_streams`` ordered upload streams (the accelerator
   component's H2D stream pool). Each stream packs units into a ring
   of ``ingest_depth`` reusable pinned staging buffers
   (``host_register``-ed once, never reallocated per chunk) and
   dispatches the async ``device_put`` — a slot is reused only after
   the put that last borrowed it completed, so at most ``depth`` puts
   per stream are in flight against live host memory.

2. **Compile/upload overlap.** :meth:`IngestEngine.overlap_compile`
   runs the XLA compile (``_Ctx`` fn/plan builds, ``jax.jit`` lower/
   compile, the persistent-cache warm path — ``wire_compile_cache``
   is applied first) on a dedicated stream concurrently with the
   uploads, under the prof ledger's ``compile`` phase while the
   upload workers run under ``staging`` — the ledger's cross-thread
   overlap accounting (``prof_phase_overlap_ns``) then *proves* the
   two proceeded together.

3. **``Pready``-style partial availability.** The returned
   :class:`IngestRequest` implements the shared
   :class:`~ompi_tpu.part.partial.PartialAvailability` mixin:
   ``Parrived(i)`` probes one upload unit, ``gate(keys)`` blocks only
   on the leaves step 1 actually touches (recording
   ``ingest_early_starts`` when it releases while the tail is still
   uploading), and ``leaf()``/``tree()`` assemble device arrays
   bit-identical to the one-shot ``to_device`` path.

Guard discipline: the module global ``INGEST`` is the one-branch
disabled guard (lint ``GUARD_GLOBALS``), brought up by
``runtime.state.init_instance`` when ``ingest_enable`` /
``OMPI_TPU_INGEST`` asks for it and torn down (buffers unregistered,
streams drained) in ``_release``.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Any, Callable, Dict, List, Optional

from ompi_tpu import errors
from ompi_tpu.core import cvar, output, pvar
from ompi_tpu.ingest.plan import IngestPlan
from ompi_tpu.part import partial as _partial
from ompi_tpu.prof import ledger as _prof

_out = output.stream("ingest")

_enable_var = cvar.register(
    "ingest_enable", False, bool,
    help="Bring the streaming ingest plane up at instance init: "
         "multi-stream double-buffered H2D upload + compile overlap "
         "+ Parrived-gated first step (equivalently: any truthy "
         "OMPI_TPU_INGEST env value).",
    level=4)
_streams_var = cvar.register(
    "ingest_streams", 4, int,
    help="Concurrent H2D upload streams the ingest engine drives "
         "(the accelerator component's stream pool).", level=5)
_chunk_var = cvar.register(
    "ingest_chunk_bytes", 4 << 20, int,
    help="Upload unit ceiling: each pytree leaf is cut into units of "
         "at most this many bytes (the Parrived granularity).",
    level=5)
_depth_var = cvar.register(
    "ingest_depth", 2, int,
    help="Staging buffers per upload stream (2 = classic double "
         "buffering: pack unit k+1 while unit k's put is in flight).",
    level=7)

#: THE disabled guard (one-branch convention, lint GUARD_GLOBALS):
#: consumers do ``if engine.INGEST is not None: ...``.
INGEST: Optional["IngestEngine"] = None


def default_put(view, device=None):
    """One raw H2D put of a flat staging view — the accelerator
    component's ``put_chunk`` when it has one, plain
    ``jax.device_put`` otherwise. Module-level so tests and the smoke
    lane can wrap it with a deliberately slow simulated device."""
    from ompi_tpu import accelerator

    put = getattr(accelerator.current(), "put_chunk", None)
    if put is not None:
        return put(view, device)
    try:
        import jax
    except Exception as exc:
        raise errors.MPIError(
            errors.ERR_NOT_SUPPORTED,
            f"ingest upload needs an accelerator put path: {exc!r}")
    out = (jax.device_put(view, device) if device is not None
           else jax.device_put(view))
    # CPU-backend device_put may be ZERO-COPY, aliasing the staging
    # view the drain loop is about to repack — force a real copy so
    # block_until_ready == "slot reusable" holds on every backend
    try:
        alias = (out.unsafe_buffer_pointer()
                 == view.__array_interface__["data"][0])
    except Exception:  # noqa: BLE001 — backend-dependent API
        alias = False
    if alias:
        out = jax.numpy.array(out, copy=True)
    return out


class IngestRequest(_partial.PartialAvailability):
    """Handle on one streamed upload (the partitioned-recv analog:
    units arrive independently; probe with ``Parrived``, gate the
    first step with :meth:`gate`, assemble with :meth:`leaf` /
    :meth:`tree`, drain with :meth:`wait`)."""

    _PARRIVED_PVAR = "ingest_parrived"

    def __init__(self, engine: "IngestEngine", plan: IngestPlan,
                 device=None) -> None:
        self._engine = engine
        self.plan = plan
        self.device = device
        self.n_units = plan.n_units
        self._events = [threading.Event()
                        for _ in range(plan.n_units)]
        self._chunks: List[Any] = [None] * plan.n_units
        self._done_ns = [0] * plan.n_units
        self._dev_leaves: Dict[int, Any] = {}
        self._lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._started = False
        self._pending = plan.n_units
        self._all_done = threading.Event()
        self._streams_left = 0
        #: deepest per-stream put queue observed (tests pin <= depth)
        self.inflight_hwm = 0
        if plan.n_units == 0:
            self._all_done.set()

    # -- PartialAvailability hooks ----------------------------------------
    @property
    def completed(self) -> bool:
        """Every unit landed successfully (a cancelled or failed
        upload never reads complete — the error surfaces at the next
        probe/gate/wait instead)."""
        return (self._all_done.is_set() and self._error is None
                and not self._cancelled)

    def _partial_started(self) -> bool:
        return self._started

    def _partial_probe(self, idx: int) -> bool:
        if not 0 <= idx < self.n_units:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"Parrived({idx}): unit index out of "
                f"[0,{self.n_units})")
        if not self._events[idx].is_set():
            return False
        if self._chunks[idx] is None and self.plan.units[idx].nbytes:
            self._raise()
        return True

    # -- completion surface ------------------------------------------------
    def test(self) -> bool:
        """Nonblocking: all units resolved (success or not)."""
        return self._all_done.is_set()

    def wait(self, timeout: Optional[float] = None) -> "IngestRequest":
        """Drain the whole upload; raises the recorded MPIError on a
        failed or cancelled upload."""
        if not self._all_done.wait(timeout):
            raise errors.MPIError(
                errors.ERR_PENDING,
                f"ingest wait timed out after {timeout}s with "
                f"{self._pending}/{self.n_units} units outstanding")
        if self._error is not None or self._cancelled:
            self._raise()
        return self

    def gate(self, keys=None,
             timeout: Optional[float] = None) -> "IngestRequest":
        """Block until the leaves the first step touches are resident
        (all of them when ``keys`` is None). THE pipeline win: when
        the gate releases while the tail is still uploading, step 1
        starts early — counted in ``ingest_early_starts``."""
        t0 = _prof.now()
        units = (self.plan.units if keys is None
                 else self.plan.units_for(keys))
        for u in units:
            if not self._events[u.idx].wait(timeout):
                raise errors.MPIError(
                    errors.ERR_PENDING,
                    f"ingest gate timed out on unit {u.idx} "
                    f"(leaf {u.leaf})")
            if self._chunks[u.idx] is None and u.nbytes:
                self._raise()
        pvar.record("ingest_gate_ns", _prof.now() - t0)
        if not self._all_done.is_set():
            pvar.record("ingest_early_starts")
        return self

    def unit_done_ns(self, idx: int) -> int:
        """monotonic_ns timestamp unit ``idx`` landed (0: not yet)."""
        return self._done_ns[idx]

    def cancel(self) -> None:
        """Abandon the upload: workers stop at the next unit
        boundary, unfinished units resolve void, and every later
        probe/gate/wait raises MPIError (no buffer is left checked
        out — the staging rings stay with the engine)."""
        self._cancelled = True

    # -- assembly ----------------------------------------------------------
    def leaf(self, key):
        """The device array for one leaf (blocks on just that leaf's
        units). Reassembly is concatenate-of-flat-chunks + reshape —
        bit-identical to a one-shot ``to_device`` of the leaf."""
        li = self.plan.leaf_index(key)
        with self._lock:
            got = self._dev_leaves.get(li)
        if got is not None:
            return got
        units = self.plan.leaf_units[li]
        for u in units:
            self._events[u.idx].wait()
            if self._chunks[u.idx] is None and u.nbytes:
                self._raise()
        if self._cancelled or self._error is not None:
            self._raise()
        arr = self.plan.leaves[li]
        import jax.numpy as jnp

        chunks = [self._chunks[u.idx] for u in units]
        dev = (chunks[0] if len(chunks) == 1
               else jnp.concatenate(chunks)).reshape(arr.shape)
        with self._lock:
            return self._dev_leaves.setdefault(li, dev)

    def tree(self):
        """The whole pytree on device (blocks until fully uploaded);
        unflattened with the plan's treedef."""
        self.wait()
        leaves = [self.leaf(i) for i in range(len(self.plan.leaves))]
        td = self.plan.treedef
        return leaves if td is None else td.unflatten(leaves)

    # -- internals ---------------------------------------------------------
    def _raise(self):
        err = self._error
        if isinstance(err, errors.MPIError):
            raise err
        if err is not None:
            raise errors.MPIError(
                errors.ERR_INTERN, f"ingest upload failed: {err!r}")
        raise errors.MPIError(
            errors.ERR_REQUEST, "ingest upload cancelled")

    def _resolve(self, idx: int, chunk=None, t_ns: int = 0) -> None:
        with self._lock:
            if self._events[idx].is_set():
                return
            self._chunks[idx] = chunk
            self._done_ns[idx] = t_ns
            self._events[idx].set()
            self._pending -= 1
            if self._pending == 0:
                self._all_done.set()

    def _fail(self, exc: BaseException) -> None:
        with self._lock:
            if self._error is None:
                self._error = exc


class IngestEngine:
    """Process-wide upload pipeline: stream pool + staging rings +
    the dedicated compile-overlap stream. One engine serves many
    uploads; rings are engine-owned and reused (stream FIFO order
    serializes drains per stream, so ring sharing is safe)."""

    def __init__(self, rank: int = 0, streams: Optional[int] = None,
                 chunk_bytes: Optional[int] = None,
                 depth: Optional[int] = None,
                 put: Optional[Callable] = None) -> None:
        self.rank = rank
        self.n_streams = max(1, int(
            _streams_var.get() if streams is None else streams))
        self.chunk_bytes = max(1, int(
            _chunk_var.get() if chunk_bytes is None else chunk_bytes))
        self.depth = max(1, int(
            _depth_var.get() if depth is None else depth))
        #: injectable put (tests/smoke wrap default_put with a slow
        #: simulated device); None -> default_put
        self._put = put
        self._lock = threading.Lock()
        self._streams: Optional[list] = None
        self._own_streams = False
        self._compile_stream = None
        self._bufs: Optional[list] = None
        self._buf_bytes = 0
        self._buf_regs: List[int] = []
        self._active: List[IngestRequest] = []
        self._closed = False

    # -- upload ------------------------------------------------------------
    def upload(self, tree, device=None) -> IngestRequest:
        """Kick off the streamed upload of a pytree; returns the
        partially-available request immediately."""
        if self._closed:
            raise errors.MPIError(
                errors.ERR_OTHER,
                "ingest engine closed — no uploads after teardown")
        plan = IngestPlan.from_tree(tree, self.chunk_bytes,
                                    self.n_streams)
        req = IngestRequest(self, plan, device=device)
        req._started = True
        pvar.record("ingest_uploads")
        if plan.n_units == 0:
            return req
        streams = self._ensure_streams()
        bufs = self._ensure_bufs(plan.max_unit_bytes)
        per_stream = [plan.stream_units(s)
                      for s in range(self.n_streams)]
        req._streams_left = sum(1 for u in per_stream if u)
        with self._lock:
            self._active.append(req)
        for s, units in enumerate(per_stream):
            if units:
                streams[s].submit(
                    self._make_drain(req, s, units, bufs[s]))
        return req

    def upload_and_compile(self, tree, compile_fn: Callable,
                           device=None):
        """The pipelined cold start: kick the upload, then run
        ``compile_fn`` concurrently on the compile stream. Returns
        ``(request, compile_event)``."""
        req = self.upload(tree, device=device)
        ev = self.overlap_compile(compile_fn)
        return req, ev

    def overlap_compile(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` on the dedicated compile stream — concurrently
        with any in-flight uploads — under the ledger's ``compile``
        phase, with jax's persistent compilation cache wired first
        (the PR 6 warm path). Returns the stream Event."""
        if self._closed:
            raise errors.MPIError(
                errors.ERR_OTHER, "ingest engine closed")
        with self._lock:
            if self._compile_stream is None:
                from ompi_tpu.accelerator.stream import Stream

                self._compile_stream = Stream("ingest-compile")
            st = self._compile_stream

        def job():
            from ompi_tpu import prof as _prof_pkg

            _prof_pkg.wire_compile_cache()
            live_before = bool(self._live_uploads())
            with _prof.phase("compile"):
                out = fn(*args, **kwargs)
            if live_before and self._live_uploads():
                # the compile provably ran start-to-finish while an
                # upload was in flight — the overlap this plane buys
                pvar.record("ingest_compile_overlaps")
            return out

        return st.submit(job)

    def inflight(self) -> int:
        """Uploads with at least one stream still draining (0 after a
        clean teardown — the no-leak invariant tests pin)."""
        with self._lock:
            return len(self._active)

    def close(self) -> None:
        """Teardown: cancel live uploads, drain workers, destroy
        engine-owned streams, unregister every staging buffer."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            active = list(self._active)
        for r in active:
            r.cancel()
        for r in active:
            r._all_done.wait(30)
        if self._own_streams:
            for st in self._streams or []:
                st.destroy()
        if self._compile_stream is not None:
            self._compile_stream.destroy()
        from ompi_tpu import accelerator

        acc = accelerator.current()
        for h in self._buf_regs:
            acc.host_unregister(h)
        with self._lock:
            self._buf_regs = []
            self._bufs = None
            self._streams = None
            self._compile_stream = None
            self._active = []

    # -- internals ---------------------------------------------------------
    def _ensure_streams(self) -> list:
        with self._lock:
            if self._streams is None:
                from ompi_tpu import accelerator

                acc = accelerator.current()
                pool = getattr(acc, "h2d_streams", None)
                if pool is not None:
                    # accelerator-owned pool: shared across engines,
                    # lifecycle stays with the component
                    self._streams = pool(self.n_streams)
                else:
                    from ompi_tpu.accelerator.stream import Stream

                    self._streams = [Stream(f"ingest-h2d-{i}")
                                     for i in range(self.n_streams)]
                    self._own_streams = True
            return self._streams

    def _ensure_bufs(self, need_bytes: int) -> list:
        import numpy as np

        from ompi_tpu import accelerator

        with self._lock:
            need = max(int(need_bytes), 1)
            if self._bufs is not None and self._buf_bytes >= need:
                return self._bufs
            acc = accelerator.current()
            for h in self._buf_regs:
                acc.host_unregister(h)
            self._bufs = [[np.empty(need, dtype=np.uint8)
                           for _ in range(self.depth)]
                          for _ in range(self.n_streams)]
            self._buf_bytes = need
            self._buf_regs = [acc.host_register(b)
                              for ring in self._bufs for b in ring]
            return self._bufs

    def _live_uploads(self) -> List[IngestRequest]:
        with self._lock:
            return [r for r in self._active
                    if not r._all_done.is_set()]

    def _stream_idle(self, req: IngestRequest) -> None:
        with self._lock:
            req._streams_left -= 1
            if req._streams_left <= 0:
                try:
                    self._active.remove(req)
                except ValueError:
                    pass

    def _make_drain(self, req: IngestRequest, s: int, units: list,
                    ring: list) -> Callable[[], None]:
        import numpy as np

        def drain() -> None:
            put = self._put or default_put
            prof = _prof.PROFILER
            #: (unit, device chunk, ring slot, t0) — submission order
            inflight: collections.deque = collections.deque()

            def retire(entry) -> None:
                u, dev, _slot, t0 = entry
                bu = getattr(dev, "block_until_ready", None)
                if bu is not None:
                    bu()
                t1 = _prof.now()
                if prof is not None:
                    prof.xfer("h2d", u.nbytes, t0, t1, site="ingest",
                              stream=s, chunk=u.idx)
                req._resolve(u.idx, chunk=dev, t_ns=t1)
                pvar.record("ingest_units")
                pvar.record("ingest_bytes", u.nbytes)

            try:
                with _prof.phase("staging"):
                    for k, u in enumerate(units):
                        if req._cancelled or req._error is not None:
                            break
                        slot = k % self.depth
                        # double-buffer gate: a ring slot is reusable
                        # only once the put that last borrowed it has
                        # completed (and never more than depth puts
                        # outstanding on this stream)
                        while inflight and (
                                inflight[0][2] == slot
                                or len(inflight) >= self.depth):
                            retire(inflight.popleft())
                        buf = ring[slot]
                        flat = req.plan.leaves[u.leaf].reshape(-1)
                        n = u.hi - u.lo
                        view = buf[:u.nbytes].view(flat.dtype)[:n]
                        np.copyto(view, flat[u.lo:u.hi])
                        t0 = _prof.now()
                        dev = put(view, req.device)
                        inflight.append((u, dev, slot, t0))
                        if len(inflight) > req.inflight_hwm:
                            req.inflight_hwm = len(inflight)
                        pvar.record_hwm("ingest_inflight",
                                        len(inflight))
                    while inflight:
                        retire(inflight.popleft())
            except BaseException as exc:  # noqa: BLE001 — surfaced at wait/gate
                req._fail(exc)
                _out.verbose(1, "ingest stream %d failed: %r", s, exc)
            finally:
                voided = 0
                for u in units:
                    if not req._events[u.idx].is_set():
                        req._resolve(u.idx)
                        voided += 1
                if voided:
                    pvar.record("ingest_cancelled", voided)
                self._stream_idle(req)

        return drain


def upload_for_restore(tree, keys=None, engine=None):
    """Checkpoint-restore gating: stream a restored host pytree up
    through the ingest plane so step 1 gates on just its leaves
    (``gate(keys)``, default: the first leaf) instead of waiting for
    the whole state — the restore-side mirror of the cold-start
    pipeline. Returns the gated :class:`IngestRequest`; with no
    engine up this is the identity (the host tree is returned and
    the caller proceeds synchronously)."""
    eng = engine if engine is not None else INGEST
    if eng is None:
        return tree
    req = eng.upload(tree)
    if req.n_units:
        req.gate(keys=[0] if keys is None else keys)
    return req


# -- plane lifecycle (runtime/state wiring) -------------------------------

def requested() -> bool:
    """cvar ingest_enable (incl. OMPI_TPU_INGEST_ENABLE env) or the
    short-form OMPI_TPU_INGEST env knob."""
    if _enable_var.get():
        return True
    raw = os.environ.get("OMPI_TPU_INGEST", "").strip().lower()
    return raw not in ("", "0", "false", "no", "off")


def enable(rank: Optional[int] = None) -> IngestEngine:
    """Bring the plane up (idempotent)."""
    global INGEST
    if INGEST is None:
        INGEST = IngestEngine(rank=0 if rank is None else rank)
        _out.verbose(2, "ingest up: %d stream(s), %d B units, "
                     "depth %d", INGEST.n_streams,
                     INGEST.chunk_bytes, INGEST.depth)
    elif rank is not None:
        INGEST.rank = rank
    return INGEST


def disable() -> Optional[IngestEngine]:
    """Tear the plane down (buffers unregistered, streams drained)."""
    global INGEST
    eng, INGEST = INGEST, None
    if eng is not None:
        eng.close()
    return eng
