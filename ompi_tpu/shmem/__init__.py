"""OpenSHMEM facade — PGAS API over the osc window plane.

Reference: oshmem/ (52 KLoC): the shmem API (oshmem/shmem/c/, 69 files)
over spml (put/get transport, spml.h:1024-1082), sshmem (symmetric
segment), memheap (symmetric allocation + remote key exchange), scoll
(collectives, with an 'mpi' component delegating to ompi coll) and
atomic frameworks.

TPU-first redesign, one module per concern folded into this package:
  - symmetric heap  = one MPI-style window (osc) of heap_size bytes per
    PE with a passive lock_all epoch held open for the session — SHMEM's
    always-legal one-sided model; the reference's memheap mkey exchange
    is the window's own peer_info exchange.
  - allocation      = deterministic bump allocator: shmem_malloc is
    symmetric because every PE performs the same allocation sequence
    (the memheap contract), so offsets agree with no communication.
  - put/get/atomics = osc Put/Rput/Get/Fetch_and_op/Compare_and_swap at
    byte displacements (spml/ucx's RDMA mapped to the AM-emulation osc,
    which is the honest transport on a host plane with no NIC RDMA).
  - collectives     = delegate to the comm's coll table (exactly the
    reference's scoll/mpi component).
  - wait_until      = progress-engine spin on local heap memory (the
    window applies remote puts from the progress callback).
"""

from __future__ import annotations

import operator
from typing import Optional

import numpy as np

from ompi_tpu import errors, op as op_mod
from ompi_tpu.core import cvar, progress, pvar

_heap_var = cvar.register(
    "shmem_heap_size", 1 << 22, int,
    help="Symmetric heap bytes per PE (reference: SHMEM_SYMMETRIC_SIZE "
         "/ memheap size).", level=4)

_ALIGN = 16

_state: Optional["_Shmem"] = None

CMP_EQ, CMP_NE, CMP_GT, CMP_GE, CMP_LT, CMP_LE = (
    "eq", "ne", "gt", "ge", "lt", "le")
_CMPS = {CMP_EQ: operator.eq, CMP_NE: operator.ne, CMP_GT: operator.gt,
         CMP_GE: operator.ge, CMP_LT: operator.lt, CMP_LE: operator.le}


class SymArray:
    """A symmetric object: same shape/dtype/heap offset on every PE.
    ``.local`` is this PE's backing storage (a live view into the
    heap); remote access goes through put/get/atomics with the PE
    number."""

    def __init__(self, offset: int, shape, dtype) -> None:
        self.offset = offset
        self.shape = tuple(np.atleast_1d(np.empty(shape, dtype)).shape) \
            if shape != () else ()
        self.dtype = np.dtype(dtype)

    @property
    def local(self) -> np.ndarray:
        st = _require()
        nbytes = int(np.prod(self.shape or (1,))) * self.dtype.itemsize
        flat = st.heap[self.offset:self.offset + nbytes]
        return flat.view(self.dtype).reshape(self.shape)

    def byte_disp(self, index: int = 0) -> int:
        return self.offset + index * self.dtype.itemsize


class _Shmem:
    def __init__(self, heap_size: int) -> None:
        from ompi_tpu import mpi, osc

        self.comm = mpi.Init()
        self.heap_arr = np.zeros(heap_size, dtype=np.uint8)
        self.win = osc.win_create(self.comm, self.heap_arr, disp_unit=1)
        self.heap = self.heap_arr  # flat uint8 view
        self.brk = 0
        # session-long passive exposure: SHMEM one-sided is always legal
        self.win.Lock_all()


def _require() -> _Shmem:
    if _state is None:
        raise errors.MPIError(errors.ERR_OTHER,
                              "shmem.init() has not been called")
    return _state


# -- setup/query (shmem_init/my_pe/n_pes) ----------------------------------

def init(heap_size: Optional[int] = None) -> None:
    global _state
    if _state is None:
        _state = _Shmem(heap_size or _heap_var.get())


def finalize() -> None:
    global _state
    if _state is not None:
        st = _state
        _state = None
        try:
            st.win.Unlock_all()
            st.win.Free()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


def my_pe() -> int:
    return _require().comm.rank


def n_pes() -> int:
    return _require().comm.size


# -- symmetric allocation (shmem_malloc / memheap) -------------------------

def zeros(shape, dtype=np.float64) -> SymArray:
    """Symmetric allocation (collective by convention: every PE calls
    in the same order with the same arguments — the memheap contract;
    no communication needed)."""
    st = _require()
    sym = SymArray(st.brk, shape, dtype)
    nbytes = int(np.prod(sym.shape or (1,))) * sym.dtype.itemsize
    new_brk = (st.brk + nbytes + _ALIGN - 1) // _ALIGN * _ALIGN
    if new_brk > st.heap.size:
        raise errors.MPIError(
            errors.ERR_NO_MEM,
            f"symmetric heap exhausted ({st.heap.size} bytes; raise "
            f"--mca shmem_heap_size)")
    st.brk = new_brk
    pvar.record("shmem_alloc_bytes", nbytes)
    return sym


def free(sym: SymArray) -> None:
    """shmem_free: the bump allocator reclaims nothing (reference
    memheap/buddy does; acceptable for the facade — document it)."""


# -- RMA (shmem_put/get and friends over spml) -----------------------------

def put(dest: SymArray, value, pe: int, index: int = 0) -> None:
    """shmem_putmem: blocking-until-buffered put (delivery ordering to
    one PE preserved by the osc AM channel)."""
    st = _require()
    data = np.ascontiguousarray(value, dtype=dest.dtype)
    st.win.Put(data, pe, disp=dest.byte_disp(index))
    pvar.record("shmem_put")


def put_nbi(dest: SymArray, value, pe: int, index: int = 0):
    """shmem_put_nbi: returns a request; quiet() also completes it."""
    st = _require()
    data = np.ascontiguousarray(value, dtype=dest.dtype)
    req = st.win.Rput(data, pe, disp=dest.byte_disp(index))
    pvar.record("shmem_put")
    return req


def get(src: SymArray, pe: int, count: Optional[int] = None,
        index: int = 0) -> np.ndarray:
    """shmem_getmem: blocking get; returns a fresh array."""
    st = _require()
    n = count if count is not None else int(np.prod(src.shape or (1,)))
    out = np.empty(n, dtype=src.dtype)
    st.win.Get(out, pe, disp=src.byte_disp(index))
    pvar.record("shmem_get")
    return out.reshape(src.shape if count is None else (n,))


def p(dest: SymArray, value, pe: int, index: int = 0) -> None:
    """shmem_p — single element."""
    put(dest, np.asarray([value], dtype=dest.dtype), pe, index)


def g(src: SymArray, pe: int, index: int = 0):
    """shmem_g — single element."""
    return get(src, pe, count=1, index=index)[0]


# -- memory ordering (shmem_fence/quiet) -----------------------------------

def quiet() -> None:
    """shmem_quiet: all outstanding puts/atomics from this PE are
    complete at their targets (spml fence+quiet -> osc Flush_all)."""
    _require().win.Flush_all()


def fence() -> None:
    """shmem_fence: ordering only; the osc AM channel already delivers
    per-target in order, so fence is quiet's ordering half — a no-op
    beyond a progress poke."""
    progress.progress()


# -- point synchronization (shmem_wait_until) ------------------------------

def wait_until(sym: SymArray, cmp: str, value, index: int = 0) -> None:
    """Spin the progress engine until the LOCAL symmetric location
    satisfies cmp (remote puts land via the window's progress
    callback)."""
    fn = _CMPS[cmp]
    loc = sym.local.reshape(-1)
    progress.wait_until(lambda: bool(fn(loc[index], value)))


# -- atomics (shmem_atomic_* over osc accumulate) --------------------------

def atomic_fetch_add(dest: SymArray, value, pe: int, index: int = 0):
    st = _require()
    result = np.empty(1, dtype=dest.dtype)
    st.win.Fetch_and_op(np.asarray([value], dtype=dest.dtype), result,
                        pe, disp=dest.byte_disp(index), op=op_mod.SUM)
    pvar.record("shmem_atomic")
    return result[0]


def atomic_add(dest: SymArray, value, pe: int, index: int = 0) -> None:
    atomic_fetch_add(dest, value, pe, index)


def atomic_compare_swap(dest: SymArray, cond, value, pe: int,
                        index: int = 0):
    st = _require()
    result = np.empty(1, dtype=dest.dtype)
    st.win.Compare_and_swap(
        np.asarray([value], dtype=dest.dtype),
        np.asarray([cond], dtype=dest.dtype), result, pe,
        disp=dest.byte_disp(index))
    pvar.record("shmem_atomic")
    return result[0]


def atomic_swap(dest: SymArray, value, pe: int, index: int = 0):
    """shmem_atomic_swap: unconditional exchange (REPLACE fetch-op)."""
    st = _require()
    result = np.empty(1, dtype=dest.dtype)
    st.win.Fetch_and_op(np.asarray([value], dtype=dest.dtype), result,
                        pe, disp=dest.byte_disp(index),
                        op=op_mod.REPLACE)
    pvar.record("shmem_atomic")
    return result[0]


def atomic_fetch(src: SymArray, pe: int, index: int = 0):
    """shmem_atomic_fetch: atomic read (NO_OP fetch-op — ordered with
    other atomics at the target, unlike a plain g())."""
    st = _require()
    result = np.empty(1, dtype=src.dtype)
    st.win.Fetch_and_op(np.zeros(1, dtype=src.dtype), result, pe,
                        disp=src.byte_disp(index), op=op_mod.NO_OP)
    pvar.record("shmem_atomic")
    return result[0]


def atomic_set(dest: SymArray, value, pe: int, index: int = 0) -> None:
    """shmem_atomic_set: atomic write (REPLACE, result discarded)."""
    atomic_swap(dest, value, pe, index)


# -- distributed locks (shmem_set_lock / test_lock / clear_lock) -----------
# Reference: oshmem/shmem/c/shmem_lock.c — a symmetric long used as a
# lock word. Redesign: the lock word lives on PE 0 (every PE spins the
# same location, the simple-common-case of the reference's MCS-like
# queue) and acquisition is atomic compare-and-swap 0 -> my_pe+1.

def set_lock(lock: SymArray, index: int = 0) -> None:
    me = my_pe() + 1
    while True:
        prev = atomic_compare_swap(lock, 0, me, 0, index)
        if prev == 0:
            return
        progress.progress()


def test_lock(lock: SymArray, index: int = 0) -> bool:
    """True = lock acquired (returns immediately)."""
    return atomic_compare_swap(lock, 0, my_pe() + 1, 0, index) == 0


def clear_lock(lock: SymArray, index: int = 0) -> None:
    quiet()  # releases happen-after the critical section's puts
    atomic_set(lock, 0, 0, index)


# -- collectives (scoll/mpi: delegate to the comm's coll table) ------------

def barrier_all() -> None:
    """shmem_barrier_all = quiet + barrier."""
    st = _require()
    quiet()
    st.comm.Barrier()


def broadcast(dest: SymArray, source: SymArray, root: int) -> None:
    """shmem_broadcast across all PEs (scoll/mpi -> coll bcast)."""
    st = _require()
    if st.comm.rank == root:
        dest.local[...] = source.local
    st.comm.Bcast(dest.local, root=root)


def fcollect(dest: SymArray, source: SymArray) -> None:
    """shmem_fcollect: concatenate equal-size blocks from every PE."""
    st = _require()
    st.comm.Allgather(source.local, dest.local)


def sum_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.SUM)


def max_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.MAX)


def min_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.MIN)


def prod_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.PROD)


def and_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.BAND)


def or_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.BOR)


def xor_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.BXOR)


def alltoall(dest: SymArray, source: SymArray) -> None:
    """shmem_alltoall: PE i's block j lands in PE j's block i (equal
    block sizes; scoll/mpi -> coll alltoall)."""
    st = _require()
    n = st.comm.size
    flat = source.local.reshape(-1)
    if flat.size % n:
        raise errors.MPIError(
            errors.ERR_ARG,
            f"alltoall: {flat.size} elements not divisible by {n} PEs")
    st.comm.Alltoall(np.array(flat, copy=True),
                     dest.local.reshape(-1))


def collect(dest: SymArray, source: SymArray, nelems: int) -> None:
    """shmem_collect: concatenate variable-size contributions in PE
    order (Allgatherv over the delegated comm)."""
    st = _require()
    cbuf = np.zeros(st.comm.size, np.int64)
    st.comm.Allgather(np.asarray([nelems], np.int64), cbuf)
    st.comm.Allgatherv(np.array(source.local.reshape(-1)[:nelems],
                                copy=True),
                       dest.local.reshape(-1),
                       [int(c) for c in cbuf])


def _to_all(dest: SymArray, source: SymArray, op) -> None:
    st = _require()
    st.comm.Allreduce(np.array(source.local, copy=True), dest.local,
                      op=op)
