"""OpenSHMEM facade — PGAS API over the osc window plane.

Reference: oshmem/ (52 KLoC): the shmem API (oshmem/shmem/c/, 69 files)
over spml (put/get transport, spml.h:1024-1082), sshmem (symmetric
segment), memheap (symmetric allocation + remote key exchange), scoll
(collectives, with an 'mpi' component delegating to ompi coll) and
atomic frameworks.

TPU-first redesign, one module per concern folded into this package:
  - symmetric heap  = one MPI-style window (osc) of heap_size bytes per
    PE with a passive lock_all epoch held open for the session — SHMEM's
    always-legal one-sided model; the reference's memheap mkey exchange
    is the window's own peer_info exchange.
  - allocation      = deterministic bump allocator: shmem_malloc is
    symmetric because every PE performs the same allocation sequence
    (the memheap contract), so offsets agree with no communication.
  - put/get/atomics = osc Put/Rput/Get/Fetch_and_op/Compare_and_swap at
    byte displacements (spml/ucx's RDMA mapped to the AM-emulation osc,
    which is the honest transport on a host plane with no NIC RDMA).
  - collectives     = delegate to the comm's coll table (exactly the
    reference's scoll/mpi component).
  - wait_until      = progress-engine spin on local heap memory (the
    window applies remote puts from the progress callback).
"""

from __future__ import annotations

import operator
from typing import Optional

import numpy as np

from ompi_tpu import errors, op as op_mod
from ompi_tpu.core import cvar, progress, pvar

_heap_var = cvar.register(
    "shmem_heap_size", 1 << 22, int,
    help="Symmetric heap bytes per PE (reference: SHMEM_SYMMETRIC_SIZE "
         "/ memheap size).", level=4)

_ALIGN = 16

_state: Optional["_Shmem"] = None

CMP_EQ, CMP_NE, CMP_GT, CMP_GE, CMP_LT, CMP_LE = (
    "eq", "ne", "gt", "ge", "lt", "le")
_CMPS = {CMP_EQ: operator.eq, CMP_NE: operator.ne, CMP_GT: operator.gt,
         CMP_GE: operator.ge, CMP_LT: operator.lt, CMP_LE: operator.le}


class SymArray:
    """A symmetric object: same shape/dtype/heap offset on every PE.
    ``.local`` is this PE's backing storage (a live view into the
    heap); remote access goes through put/get/atomics with the PE
    number."""

    def __init__(self, offset: int, shape, dtype) -> None:
        self.offset = offset
        self.shape = tuple(np.atleast_1d(np.empty(shape, dtype)).shape) \
            if shape != () else ()
        self.dtype = np.dtype(dtype)

    @property
    def local(self) -> np.ndarray:
        st = _require()
        nbytes = int(np.prod(self.shape or (1,))) * self.dtype.itemsize
        flat = st.heap[self.offset:self.offset + nbytes]
        return flat.view(self.dtype).reshape(self.shape)

    def byte_disp(self, index: int = 0) -> int:
        return self.offset + index * self.dtype.itemsize


class _Shmem:
    def __init__(self, heap_size: int) -> None:
        import os

        from ompi_tpu import mpi, osc
        from ompi_tpu.runtime import rte

        self.comm = mpi.Init()
        # /dev/shm-backed heap (reference: sshmem/mmap symmetric
        # segments) so same-host peers can shmem_ptr-map it directly
        self._shm_dir = os.environ.get("OMPI_TPU_SHM_DIR", "/dev/shm")
        self._shm_path = None
        self.heap_arr = self._map_heap(rte.rank, heap_size,
                                       create=True)
        if self.heap_arr is None:  # no shm dir: private heap,
            self.heap_arr = np.zeros(heap_size, dtype=np.uint8)
            # shmem_ptr then degrades to None for every remote PE
        self.win = osc.win_create(self.comm, self.heap_arr, disp_unit=1)
        self.heap = self.heap_arr  # flat uint8 view
        self.brk = 0
        # shmem_ptr peer maps: world rank -> np view (or None)
        rte.modex_send("shmem_host", rte.hostname())
        self._peer_maps = {}
        # session-long passive exposure: SHMEM one-sided is always legal
        self.win.Lock_all()

    def _map_heap(self, world_rank: int, heap_size: int,
                  create: bool):
        import mmap
        import os

        from ompi_tpu.runtime import rte

        if not os.path.isdir(self._shm_dir):
            return None
        path = os.path.join(
            self._shm_dir, f"ompi_tpu_shmem_{rte.jobid}_{world_rank}")
        try:
            fd = os.open(path, os.O_RDWR | (os.O_CREAT if create
                                            else 0), 0o600)
        except OSError:
            return None
        try:
            if create:
                os.ftruncate(fd, heap_size)
                self._shm_path = path
            mm = mmap.mmap(fd, heap_size)
        finally:
            os.close(fd)
        return np.frombuffer(mm, dtype=np.uint8)


def _require() -> _Shmem:
    if _state is None:
        raise errors.MPIError(errors.ERR_OTHER,
                              "shmem.init() has not been called")
    return _state


# -- setup/query (shmem_init/my_pe/n_pes) ----------------------------------

def init(heap_size: Optional[int] = None) -> None:
    global _state
    if _state is None:
        _state = _Shmem(heap_size or _heap_var.get())


def finalize() -> None:
    global _state
    if _state is not None:
        import os

        st = _state
        _state = None
        try:
            st.win.Unlock_all()
            st.win.Free()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        if st._shm_path:
            try:
                os.unlink(st._shm_path)
            except OSError:
                pass


def my_pe() -> int:
    return _require().comm.rank


def n_pes() -> int:
    return _require().comm.size


# -- symmetric allocation (shmem_malloc / memheap) -------------------------

def zeros(shape, dtype=np.float64) -> SymArray:
    """Symmetric allocation (collective by convention: every PE calls
    in the same order with the same arguments — the memheap contract;
    no communication needed)."""
    st = _require()
    sym = SymArray(st.brk, shape, dtype)
    nbytes = int(np.prod(sym.shape or (1,))) * sym.dtype.itemsize
    new_brk = (st.brk + nbytes + _ALIGN - 1) // _ALIGN * _ALIGN
    if new_brk > st.heap.size:
        raise errors.MPIError(
            errors.ERR_NO_MEM,
            f"symmetric heap exhausted ({st.heap.size} bytes; raise "
            f"--mca shmem_heap_size)")
    st.brk = new_brk
    pvar.record("shmem_alloc_bytes", nbytes)
    return sym


def free(sym: SymArray) -> None:
    """shmem_free: the bump allocator reclaims nothing (reference
    memheap/buddy does; acceptable for the facade — document it)."""


# -- RMA (shmem_put/get and friends over spml) -----------------------------

def _win_put(win, dest: SymArray, value, pe: int, index: int) -> None:
    data = np.ascontiguousarray(value, dtype=dest.dtype)
    win.Put(data, pe, disp=dest.byte_disp(index))
    pvar.record("shmem_put")


def _win_get(win, src: SymArray, pe: int, count: Optional[int],
             index: int) -> np.ndarray:
    n = count if count is not None else int(np.prod(src.shape or (1,)))
    out = np.empty(n, dtype=src.dtype)
    win.Get(out, pe, disp=src.byte_disp(index))
    pvar.record("shmem_get")
    return out.reshape(src.shape if count is None else (n,))


def _win_fetch_add(win, dest: SymArray, value, pe: int, index: int):
    result = np.empty(1, dtype=dest.dtype)
    win.Fetch_and_op(np.asarray([value], dtype=dest.dtype), result,
                     pe, disp=dest.byte_disp(index), op=op_mod.SUM)
    pvar.record("shmem_atomic")
    return result[0]


def put(dest: SymArray, value, pe: int, index: int = 0) -> None:
    """shmem_putmem: blocking-until-buffered put (delivery ordering to
    one PE preserved by the osc AM channel)."""
    _win_put(_require().win, dest, value, pe, index)


def put_nbi(dest: SymArray, value, pe: int, index: int = 0):
    """shmem_put_nbi: returns a request; quiet() also completes it."""
    st = _require()
    data = np.ascontiguousarray(value, dtype=dest.dtype)
    req = st.win.Rput(data, pe, disp=dest.byte_disp(index))
    pvar.record("shmem_put")
    return req


def get(src: SymArray, pe: int, count: Optional[int] = None,
        index: int = 0) -> np.ndarray:
    """shmem_getmem: blocking get; returns a fresh array."""
    return _win_get(_require().win, src, pe, count, index)


def p(dest: SymArray, value, pe: int, index: int = 0) -> None:
    """shmem_p — single element."""
    put(dest, np.asarray([value], dtype=dest.dtype), pe, index)


def g(src: SymArray, pe: int, index: int = 0):
    """shmem_g — single element."""
    return get(src, pe, count=1, index=index)[0]


def iput(dest: SymArray, value, pe: int, tst: int = 1, sst: int = 1,
         nelems: Optional[int] = None, index: int = 0) -> None:
    """shmem_iput: strided put — element i of the (sst-strided) source
    lands at target offset index + i*tst. One AM message (an
    osc strided-put), not a per-element loop."""
    st = _require()
    src = np.ascontiguousarray(value, dtype=dest.dtype).reshape(-1)
    if nelems == 0 or src.size == 0:
        return  # SHMEM: zero elements moves nothing
    if nelems is not None:
        src = src[: (nelems - 1) * sst + 1]
    data = np.ascontiguousarray(src[::sst])
    st.win.Put_strided(data, pe, disp=dest.byte_disp(index),
                       stride=tst)
    pvar.record("shmem_put")


def iget(src: SymArray, pe: int, nelems: int, tst: int = 1,
         sst: int = 1, index: int = 0) -> np.ndarray:
    """shmem_iget: strided get — reads nelems elements at target
    stride sst starting at index; returns them packed at stride tst
    in a fresh array (tst > 1 interleaves zeros, matching the
    local-strided-destination semantics)."""
    st = _require()
    if nelems == 0:
        return np.empty(0, dtype=src.dtype)
    packed = np.empty(nelems, dtype=src.dtype)
    st.win.Get_strided(packed, pe, disp=src.byte_disp(index),
                       stride=sst)
    pvar.record("shmem_get")
    if tst == 1:
        return packed
    out = np.zeros((nelems - 1) * tst + 1, dtype=src.dtype)
    out[::tst] = packed
    return out


# -- contexts (shmem_ctx_create — independent completion streams) ----------

class Ctx:
    """A SHMEM context (reference: oshmem/shmem/c/shmem_ctx*.c,
    spml.h ctx entries): an independent ordering/completion stream.
    Redesign: each context owns its own osc window over the SAME
    symmetric heap — a private AM channel, so quiet() on one context
    never waits for another's traffic (the reference's per-ctx UCX
    worker, as an epoch scope).

    DIVERGENCE from the SHMEM spec, documented: ctx_create is
    COLLECTIVE here (window construction dups a communicator — every
    PE must call it, in the same order). Standard SHMEM contexts are
    local; a program creating contexts on a subset of PEs must use
    the default context on the others or restructure."""

    def __init__(self, comm=None) -> None:
        from ompi_tpu import osc

        st = _require()
        # a team-scoped context (shmem_team_create_ctx) windows over
        # the TEAM's comm: its ops address team-relative PE numbers
        self.win = osc.win_create(comm if comm is not None
                                  else st.comm,
                                  st.heap_arr, disp_unit=1)
        self.win.Lock_all()
        self._open = True

    def put(self, dest: SymArray, value, pe: int,
            index: int = 0) -> None:
        _win_put(self.win, dest, value, pe, index)

    def get(self, src: SymArray, pe: int, count: Optional[int] = None,
            index: int = 0) -> np.ndarray:
        return _win_get(self.win, src, pe, count, index)

    def atomic_fetch_add(self, dest: SymArray, value, pe: int,
                         index: int = 0):
        return _win_fetch_add(self.win, dest, value, pe, index)

    def quiet(self) -> None:
        """Completes THIS context's outstanding ops only."""
        self.win.Flush_all()

    def fence(self) -> None:
        progress.progress()

    def destroy(self) -> None:
        if self._open:
            self._open = False
            try:
                self.win.Unlock_all()
                self.win.Free()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def ctx_create(options: int = 0) -> Ctx:
    """shmem_ctx_create (options accepted for API parity; the private
    window already gives SERIALIZED/PRIVATE semantics). COLLECTIVE —
    every PE must call, in the same order (see Ctx docstring)."""
    return Ctx()


def ctx_destroy(ctx: Ctx) -> None:
    ctx.destroy()


# -- teams (SHMEM 1.5 shmem_team_* — sub-groups of PEs) --------------------

class Team:
    """A SHMEM team: an ordered subset of PEs with its own collectives
    (reference: oshmem teams over scoll; here the team IS a
    communicator, exactly the scoll/mpi delegation)."""

    def __init__(self, comm) -> None:
        self._comm = comm

    def my_pe(self) -> int:
        return self._comm.rank

    def n_pes(self) -> int:
        return self._comm.size

    def translate_pe(self, pe: int, dest: "Team") -> int:
        """shmem_team_translate_pe: -1 when absent (SHMEM convention)."""
        from ompi_tpu.comm import UNDEFINED

        out = self._comm.group.translate(pe, dest._comm.group)
        return -1 if out == UNDEFINED else out

    def world_pe(self, pe: int) -> int:
        """World PE number of team member ``pe`` (for put/get, which
        always address world PEs — SHMEM's TEAM_WORLD ranking)."""
        st = _require()
        return st.comm.group._index[self._comm.group.ranks[pe]]

    def sync(self) -> None:
        """shmem_team_sync = quiet + team barrier."""
        quiet()
        self._comm.Barrier()

    # -- team collectives (OpenSHMEM 1.5 team-based API) -----------------
    # Reference: scoll serves any active set/team
    # (oshmem/mca/scoll/scoll.h:158-159) and the reductions are
    # team-based in the API (oshmem/shmem/c/shmem_reduce.c:384-396,
    # shmem_*_reduce(shmem_team_t team, ...)). Every world collective
    # below delegates here with TEAM_WORLD.
    def broadcast(self, dest: SymArray, source: SymArray,
                  root: int) -> None:
        if self._comm.rank == root:
            dest.local[...] = source.local
        self._comm.Bcast(dest.local, root=root)

    def fcollect(self, dest: SymArray, source: SymArray) -> None:
        """shmem_fcollect: equal-size blocks concatenated in team PE
        order."""
        self._comm.Allgather(np.array(source.local, copy=True),
                             dest.local)

    def collect(self, dest: SymArray, source: SymArray,
                nelems: int) -> None:
        """shmem_collect: variable-size contributions in team PE
        order (Allgatherv over the delegated comm)."""
        cbuf = np.zeros(self._comm.size, np.int64)
        self._comm.Allgather(np.asarray([nelems], np.int64), cbuf)
        self._comm.Allgatherv(
            np.array(source.local.reshape(-1)[:nelems], copy=True),
            dest.local.reshape(-1), [int(c) for c in cbuf])

    def alltoall(self, dest: SymArray, source: SymArray) -> None:
        """shmem_alltoall: team PE i's block j lands in PE j's block
        i (equal block sizes)."""
        n = self._comm.size
        flat = source.local.reshape(-1)
        if flat.size % n:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"alltoall: {flat.size} elements not divisible by "
                f"{n} PEs")
        self._comm.Alltoall(np.array(flat, copy=True),
                            dest.local.reshape(-1))

    def reduce(self, dest: SymArray, source: SymArray, op) -> None:
        """shmem_*_reduce core (shmem_reduce.c:384-396 — reductions
        are team-scoped in the OpenSHMEM 1.5 API)."""
        self._comm.Allreduce(np.array(source.local, copy=True),
                             dest.local, op=op)

    def sum_reduce(self, dest: SymArray, source: SymArray) -> None:
        self.reduce(dest, source, op_mod.SUM)

    def prod_reduce(self, dest: SymArray, source: SymArray) -> None:
        self.reduce(dest, source, op_mod.PROD)

    def min_reduce(self, dest: SymArray, source: SymArray) -> None:
        self.reduce(dest, source, op_mod.MIN)

    def max_reduce(self, dest: SymArray, source: SymArray) -> None:
        self.reduce(dest, source, op_mod.MAX)

    def and_reduce(self, dest: SymArray, source: SymArray) -> None:
        self.reduce(dest, source, op_mod.BAND)

    def or_reduce(self, dest: SymArray, source: SymArray) -> None:
        self.reduce(dest, source, op_mod.BOR)

    def xor_reduce(self, dest: SymArray, source: SymArray) -> None:
        self.reduce(dest, source, op_mod.BXOR)

    # pre-1.5 naming kept for symmetry with the world forms
    def sum_to_all(self, dest: SymArray, source: SymArray) -> None:
        self.reduce(dest, source, op_mod.SUM)

    def create_ctx(self) -> "Ctx":
        """shmem_team_create_ctx (SHMEM 1.5; COLLECTIVE over the
        team, per this module's ctx divergence note): ops on the
        returned context address TEAM-relative PE numbers."""
        return Ctx(self._comm)

    def destroy(self) -> None:
        self._comm.free()


def team_world() -> Team:
    return Team(_require().comm)


def team_split_strided(parent: Team, start: int, stride: int,
                       size: int) -> Optional[Team]:
    """shmem_team_split_strided: members are parent PEs start,
    start+stride, ...; returns None on non-members (SHMEM returns
    SHMEM_TEAM_INVALID)."""
    members = [start + i * stride for i in range(size)]
    me = parent._comm.rank
    color = 0 if me in members else None
    from ompi_tpu.comm import UNDEFINED

    sub = parent._comm.split(
        color if color is not None else UNDEFINED,
        key=members.index(me) if me in members else 0)
    return Team(sub) if sub is not None else None


def team_split_2d(parent: Team, xrange: int):
    """shmem_team_split_2d: factor the parent into a 2-D grid, PE p
    at (x, y) = (p % xrange, p // xrange); returns (x_team, y_team) —
    the calling PE's row (shared y) and column (shared x) teams.
    Reference: oshmem/shmem/c/shmem_team_split_2d role."""
    if xrange < 1:
        raise errors.MPIError(errors.ERR_ARG,
                              f"team_split_2d: xrange {xrange} < 1")
    me = parent._comm.rank
    x, y = me % xrange, me // xrange
    xteam = parent._comm.split(y, key=x)
    yteam = parent._comm.split(x, key=y)
    return Team(xteam), Team(yteam)


# -- shmem_ptr (direct same-host load/store access) ------------------------

def ptr(sym: SymArray, pe: int) -> Optional[np.ndarray]:
    """shmem_ptr: a live numpy view of PE ``pe``'s symmetric object
    for direct load/store, or None when no such mapping exists
    (different host, or no /dev/shm backing) — the reference returns
    NULL exactly the same way. Same-host mapping attaches the peer's
    sshmem segment (reference: oshmem/mca/sshmem/mmap)."""
    st = _require()
    from ompi_tpu.runtime import rte

    world = st.comm.group.ranks[pe]
    if world == rte.rank:
        return sym.local
    if world not in st._peer_maps:
        heap = None
        if (st._shm_path is not None
                and rte.modex_recv("shmem_host", world)
                == rte.hostname()):
            heap = st._map_heap(world, st.heap.size, create=False)
        st._peer_maps[world] = heap
    heap = st._peer_maps[world]
    if heap is None:
        return None
    nbytes = int(np.prod(sym.shape or (1,))) * sym.dtype.itemsize
    flat = heap[sym.offset:sym.offset + nbytes]
    return flat.view(sym.dtype).reshape(sym.shape)


# -- memory ordering (shmem_fence/quiet) -----------------------------------

def quiet() -> None:
    """shmem_quiet: all outstanding puts/atomics from this PE are
    complete at their targets (spml fence+quiet -> osc Flush_all)."""
    _require().win.Flush_all()


def fence() -> None:
    """shmem_fence: ordering only; the osc AM channel already delivers
    per-target in order, so fence is quiet's ordering half — a no-op
    beyond a progress poke."""
    progress.progress()


# -- point synchronization (shmem_wait_until) ------------------------------

def wait_until(sym: SymArray, cmp: str, value, index: int = 0) -> None:
    """Spin the progress engine until the LOCAL symmetric location
    satisfies cmp (remote puts land via the window's progress
    callback)."""
    fn = _CMPS[cmp]
    loc = sym.local.reshape(-1)
    progress.wait_until(lambda: bool(fn(loc[index], value)))


def test(sym: SymArray, cmp: str, value, index: int = 0) -> bool:
    """shmem_test (oshmem/shmem/c/shmem_wait_ivars.c family): one
    progress sweep, then a nonblocking check of the local location."""
    progress.progress()
    fn = _CMPS[cmp]
    return bool(fn(sym.local.reshape(-1)[index], value))


def test_all(sym: SymArray, cmp: str, value,
             indices=None) -> bool:
    """shmem_test_all over a vector of symmetric locations."""
    progress.progress()
    fn = _CMPS[cmp]
    loc = sym.local.reshape(-1)
    idxs = range(loc.size) if indices is None else indices
    return all(bool(fn(loc[i], value)) for i in idxs)


def test_any(sym: SymArray, cmp: str, value, indices=None):
    """shmem_test_any: index of SOME satisfied location, else None."""
    progress.progress()
    fn = _CMPS[cmp]
    loc = sym.local.reshape(-1)
    idxs = range(loc.size) if indices is None else indices
    for i in idxs:
        if fn(loc[i], value):
            return i
    return None


def test_some(sym: SymArray, cmp: str, value, indices=None) -> list:
    """shmem_test_some: every currently-satisfied index."""
    progress.progress()
    fn = _CMPS[cmp]
    loc = sym.local.reshape(-1)
    idxs = range(loc.size) if indices is None else indices
    return [i for i in idxs if fn(loc[i], value)]


def wait_until_any(sym: SymArray, cmp: str, value, indices=None):
    """shmem_wait_until_any."""
    # materialize once: the polls re-iterate, so a one-shot iterable
    # (generator) would be exhausted after the first sweep
    indices = None if indices is None else list(indices)
    out: list = []

    def check() -> bool:
        got = test_any(sym, cmp, value, indices)
        if got is not None:
            out.append(got)
            return True
        return False

    progress.wait_until(check)
    return out[0]


def wait_until_all(sym: SymArray, cmp: str, value,
                   indices=None) -> None:
    """shmem_wait_until_all."""
    indices = None if indices is None else list(indices)
    progress.wait_until(lambda: test_all(sym, cmp, value, indices))


# -- signaled put (spml_put_signal, spml.h:280,1037;
#    oshmem/shmem/c/shmem_put_signal.c) ------------------------------------

SIGNAL_SET = "set"
SIGNAL_ADD = "add"


def _post_signal(st: "_Shmem", sig_addr: SymArray, signal, sig_op: str,
                 pe: int) -> None:
    op = op_mod.SUM if sig_op == SIGNAL_ADD else op_mod.REPLACE
    st.win.Accumulate(np.asarray([signal], dtype=sig_addr.dtype), pe,
                      disp=sig_addr.byte_disp(0), op=op)
    pvar.record("shmem_atomic")


def put_signal(dest: SymArray, value, sig_addr: SymArray, signal,
               sig_op: str = SIGNAL_SET, pe: int = 0,
               index: int = 0) -> None:
    """shmem_put_signal: data put + signal update as one ordered pair
    — the osc AM channel to one PE preserves delivery order, so the
    target's signal word updates only AFTER the data is visible (the
    consumer needs no barrier: signal_wait_until then read)."""
    st = _require()
    _win_put(st.win, dest, value, pe, index)
    _post_signal(st, sig_addr, signal, sig_op, pe)


def put_signal_nbi(dest: SymArray, value, sig_addr: SymArray, signal,
                   sig_op: str = SIGNAL_SET, pe: int = 0,
                   index: int = 0):
    """shmem_put_signal_nbi: nonblocking form; quiet() completes it.
    The data/signal pair still posts in order on the AM channel."""
    st = _require()
    data = np.ascontiguousarray(value, dtype=dest.dtype)
    req = st.win.Rput(data, pe, disp=dest.byte_disp(index))
    pvar.record("shmem_put")
    _post_signal(st, sig_addr, signal, sig_op, pe)
    return req


def signal_fetch(sig_addr: SymArray) -> int:
    """shmem_signal_fetch: read the LOCAL signal word."""
    progress.progress()
    return sig_addr.local.reshape(-1)[0]


def signal_wait_until(sig_addr: SymArray, cmp: str, value):
    """shmem_signal_wait_until: returns the satisfying signal value."""
    wait_until(sig_addr, cmp, value, index=0)
    return sig_addr.local.reshape(-1)[0]


# -- atomics (shmem_atomic_* over osc accumulate) --------------------------

def atomic_fetch_add(dest: SymArray, value, pe: int, index: int = 0):
    return _win_fetch_add(_require().win, dest, value, pe, index)


def atomic_add(dest: SymArray, value, pe: int, index: int = 0) -> None:
    atomic_fetch_add(dest, value, pe, index)


def atomic_compare_swap(dest: SymArray, cond, value, pe: int,
                        index: int = 0):
    st = _require()
    result = np.empty(1, dtype=dest.dtype)
    st.win.Compare_and_swap(
        np.asarray([value], dtype=dest.dtype),
        np.asarray([cond], dtype=dest.dtype), result, pe,
        disp=dest.byte_disp(index))
    pvar.record("shmem_atomic")
    return result[0]


def atomic_swap(dest: SymArray, value, pe: int, index: int = 0):
    """shmem_atomic_swap: unconditional exchange (REPLACE fetch-op)."""
    st = _require()
    result = np.empty(1, dtype=dest.dtype)
    st.win.Fetch_and_op(np.asarray([value], dtype=dest.dtype), result,
                        pe, disp=dest.byte_disp(index),
                        op=op_mod.REPLACE)
    pvar.record("shmem_atomic")
    return result[0]


def atomic_fetch(src: SymArray, pe: int, index: int = 0):
    """shmem_atomic_fetch: atomic read (NO_OP fetch-op — ordered with
    other atomics at the target, unlike a plain g())."""
    st = _require()
    result = np.empty(1, dtype=src.dtype)
    st.win.Fetch_and_op(np.zeros(1, dtype=src.dtype), result, pe,
                        disp=src.byte_disp(index), op=op_mod.NO_OP)
    pvar.record("shmem_atomic")
    return result[0]


def atomic_set(dest: SymArray, value, pe: int, index: int = 0) -> None:
    """shmem_atomic_set: atomic write (REPLACE, result discarded)."""
    atomic_swap(dest, value, pe, index)


# -- distributed locks (shmem_set_lock / test_lock / clear_lock) -----------
# Reference: oshmem/shmem/c/shmem_lock.c — a symmetric long used as a
# lock word. Redesign: the lock word lives on PE 0 (every PE spins the
# same location, the simple-common-case of the reference's MCS-like
# queue) and acquisition is atomic compare-and-swap 0 -> my_pe+1.

def set_lock(lock: SymArray, index: int = 0) -> None:
    me = my_pe() + 1
    while True:
        prev = atomic_compare_swap(lock, 0, me, 0, index)
        if prev == 0:
            return
        progress.progress()


def test_lock(lock: SymArray, index: int = 0) -> bool:
    """True = lock acquired (returns immediately)."""
    return atomic_compare_swap(lock, 0, my_pe() + 1, 0, index) == 0


def clear_lock(lock: SymArray, index: int = 0) -> None:
    quiet()  # releases happen-after the critical section's puts
    atomic_set(lock, 0, 0, index)


# -- collectives (scoll/mpi: delegate to the comm's coll table) ------------

def barrier_all() -> None:
    """shmem_barrier_all = quiet + barrier."""
    st = _require()
    quiet()
    st.comm.Barrier()


def broadcast(dest: SymArray, source: SymArray, root: int) -> None:
    """shmem_broadcast across all PEs (scoll/mpi -> coll bcast)."""
    team_world().broadcast(dest, source, root)


def fcollect(dest: SymArray, source: SymArray) -> None:
    """shmem_fcollect: concatenate equal-size blocks from every PE."""
    team_world().fcollect(dest, source)


def sum_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.SUM)


def max_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.MAX)


def min_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.MIN)


def prod_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.PROD)


def and_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.BAND)


def or_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.BOR)


def xor_to_all(dest: SymArray, source: SymArray) -> None:
    _to_all(dest, source, op_mod.BXOR)


def alltoall(dest: SymArray, source: SymArray) -> None:
    """shmem_alltoall: PE i's block j lands in PE j's block i (equal
    block sizes; scoll/mpi -> coll alltoall)."""
    team_world().alltoall(dest, source)


def collect(dest: SymArray, source: SymArray, nelems: int) -> None:
    """shmem_collect: concatenate variable-size contributions in PE
    order (Allgatherv over the delegated comm)."""
    team_world().collect(dest, source, nelems)


def _to_all(dest: SymArray, source: SymArray, op) -> None:
    team_world().reduce(dest, source, op)
