"""MPI attribute/keyval caching subsystem.

Reference: ompi/attribute/attribute.c (1,498 LoC) — unified keyval
space across comm/win/datatype with user copy/delete callbacks fired
on dup/free (ompi/mpi/c/comm_create_keyval.c:47-62), and
ompi/attribute/attribute_predefined.c:119-195 — ~20 predefined
attributes (MPI_TAG_UB, MPI_APPNUM, MPI_UNIVERSE_SIZE,
MPI_WTIME_IS_GLOBAL, window WIN_BASE/WIN_SIZE/DISP_UNIT, ...).

Design notes (vs the reference):
- One keyval namespace with a ``kind`` marker ("comm"/"win"/"type"),
  like the reference's unified attribute.c space; kind mismatches
  raise ERR_KEYVAL at set/get time.
- Callback convention is Pythonic, not pointer-based:
  ``copy_fn(obj, keyval, extra_state, value) -> new value`` — return
  the sentinel :data:`NO_COPY` to drop the attribute on dup (the
  MPI flag=0 outcome); ``delete_fn(obj, keyval, value, extra_state)``
  fires on Delete_attr, on overwrite by Set_attr (MPI-3.1 §6.7.2),
  and on object free.
- Predefined attributes are read-only resolver functions answered
  from the runtime/window, never stored — exactly the reference's
  attribute_predefined.c scheme of registering them against system
  state at init.
- Deletion order on object free is insertion order (MPI-4 leaves the
  order arbitrary; the reference iterates its hash).
- MPI_Comm_free_keyval semantics: the keyval is marked freed and
  becomes invalid for NEW set/get calls, but attributes already
  cached under it keep FUNCTIONING — copy callbacks still fire on
  dup and delete callbacks on free (MPI-4 §7.7.2: the keyval is only
  truly freed when the last attached attribute is deleted;
  attribute.c refcounts the keyval for this).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, Optional

from ompi_tpu import errors

KEYVAL_INVALID = -1

#: copy_fn return sentinel: do NOT propagate this attribute to the dup
NO_COPY = object()


class Keyval:
    __slots__ = ("id", "kind", "copy_fn", "delete_fn", "extra_state",
                 "freed")

    def __init__(self, kid: int, kind: str,
                 copy_fn: Optional[Callable],
                 delete_fn: Optional[Callable],
                 extra_state: Any) -> None:
        self.id = kid
        self.kind = kind
        self.copy_fn = copy_fn
        self.delete_fn = delete_fn
        self.extra_state = extra_state
        self.freed = False


# predefined ids live below 100; user keyvals above
_next_id = itertools.count(100)
_keyvals: Dict[int, Keyval] = {}
_lock = threading.Lock()

# -- predefined attribute ids (attribute_predefined.c:119-195) ------------
TAG_UB = 1
HOST = 2
IO = 3
WTIME_IS_GLOBAL = 4
APPNUM = 5
UNIVERSE_SIZE = 6
LASTUSEDCODE = 7
WIN_BASE = 20
WIN_SIZE = 21
WIN_DISP_UNIT = 22
WIN_CREATE_FLAVOR = 23
WIN_MODEL = 24

#: the framework's tag ceiling (pml tags are Python ints on the wire;
#: advertise the MPI minimum-guarantee-compatible 2^31-1)
MAX_TAG = (1 << 31) - 1

# window models (MPI-3 §11.4): the AM-backed windows are
# separate-memory-model; "unified" would claim public==private copy
WIN_SEPARATE = "separate"
WIN_FLAVOR_CREATE = "create"


def _predef_comm(kid: int):
    """Resolver for predefined COMM attributes (value, found)."""
    if kid == TAG_UB:
        return MAX_TAG, True
    if kid == WTIME_IS_GLOBAL:
        # Wtime is per-process perf_counter — never globally synced
        return False, True
    if kid == APPNUM:
        from ompi_tpu import dpm

        return dpm.appnum(), True
    if kid == UNIVERSE_SIZE:
        from ompi_tpu.runtime import rte

        return rte.size, True
    if kid == HOST:
        from ompi_tpu.runtime import rte

        return rte.hostname(), True
    if kid == IO:
        # any rank can perform IO (ompio equivalent is rank-agnostic)
        return True, True
    if kid == LASTUSEDCODE:
        return errors.last_used_code(), True
    return None, False


def _predef_win(win, kid: int):
    if kid == WIN_BASE:
        return win.base, True
    if kid == WIN_SIZE:
        return (0 if win.base is None else win.base.nbytes), True
    if kid == WIN_DISP_UNIT:
        return win.disp_unit, True
    if kid == WIN_CREATE_FLAVOR:
        return getattr(win, "flavor", WIN_FLAVOR_CREATE), True
    if kid == WIN_MODEL:
        return WIN_SEPARATE, True
    return None, False


_PREDEF_COMM_IDS = frozenset((TAG_UB, HOST, IO, WTIME_IS_GLOBAL,
                              APPNUM, UNIVERSE_SIZE, LASTUSEDCODE))
_PREDEF_WIN_IDS = frozenset((WIN_BASE, WIN_SIZE, WIN_DISP_UNIT,
                             WIN_CREATE_FLAVOR, WIN_MODEL))


# -- keyval lifecycle -----------------------------------------------------

def create_keyval(kind: str, copy_fn: Optional[Callable] = None,
                  delete_fn: Optional[Callable] = None,
                  extra_state: Any = None) -> int:
    """MPI_{Comm,Win,Type}_create_keyval. ``copy_fn=None`` is
    MPI_NULL_COPY_FN (attribute NOT propagated on dup); pass
    :func:`dup_fn` for MPI_COMM_DUP_FN (value copied by reference)."""
    if kind not in ("comm", "win", "type"):
        raise errors.MPIError(errors.ERR_ARG, f"bad keyval kind {kind}")
    with _lock:
        kid = next(_next_id)
        _keyvals[kid] = Keyval(kid, kind, copy_fn, delete_fn,
                               extra_state)
    return kid


def free_keyval(kid: int) -> int:
    """MPI_{Comm,Win,Type}_free_keyval: marks the keyval freed (new
    set/get raise); existing cached attributes still fire delete
    callbacks at their object's free. Returns KEYVAL_INVALID for the
    MPI 'handle set to invalid' convention."""
    kv = _keyvals.get(kid)
    if kv is None or kv.freed:
        raise errors.MPIError(errors.ERR_KEYVAL,
                              f"invalid keyval {kid}")
    kv.freed = True
    return KEYVAL_INVALID


def dup_fn(obj, keyval, extra_state, value):
    """MPI_COMM_DUP_FN / MPI_WIN_DUP_FN / MPI_TYPE_DUP_FN: copy the
    attribute value by reference."""
    return value


def null_copy_fn(obj, keyval, extra_state, value):
    """MPI_NULL_COPY_FN: never propagate."""
    return NO_COPY


def _get_kv(kid: int, kind: str, for_set: bool) -> Keyval:
    kv = _keyvals.get(kid)
    if kv is None or kv.freed:
        raise errors.MPIError(errors.ERR_KEYVAL,
                              f"invalid keyval {kid}")
    if kv.kind != kind:
        raise errors.MPIError(
            errors.ERR_KEYVAL,
            f"keyval {kid} is a {kv.kind} keyval, used on a {kind}")
    return kv


# -- attribute plane on a host object -------------------------------------
# Host objects expose a dict attribute ``attrs`` (keyval id -> value).
# The same dict may hold non-int internal keys (e.g. pml/part state);
# the keyval plane only ever touches int keys it registered.


class AttrHost:
    """Mixin giving a class the MPI attribute API over its ``attrs``
    dict. Subclasses set ``_attr_kind`` ("comm"/"win"/"type") and call
    :func:`copy_attrs` / :func:`delete_attrs` from their dup/free."""

    __slots__ = ()
    _attr_kind = "comm"

    def Set_attr(self, keyval: int, value) -> None:
        set_attr(self, self._attr_kind, keyval, value)

    def Get_attr(self, keyval: int):
        return get_attr(self, self._attr_kind, keyval)

    def Delete_attr(self, keyval: int) -> None:
        delete_attr(self, self._attr_kind, keyval)

def set_attr(obj, kind: str, kid: int, value: Any) -> None:
    """MPI_*_set_attr: overwriting an existing value fires the delete
    callback on the OLD value first (MPI-3.1 §6.7.2). Predefined
    attributes are read-only (the reference errors on user writes)."""
    if kid in (_PREDEF_COMM_IDS if kind == "comm" else
               _PREDEF_WIN_IDS if kind == "win" else ()):
        raise errors.MPIError(errors.ERR_KEYVAL,
                              f"predefined attribute {kid} is "
                              "read-only")
    kv = _get_kv(kid, kind, for_set=True)
    if kid in obj.attrs and kv.delete_fn is not None:
        kv.delete_fn(obj, kid, obj.attrs[kid], kv.extra_state)
    obj.attrs[kid] = value


def get_attr(obj, kind: str, kid: int):
    """MPI_*_get_attr: returns the value, or None when not set (the
    flag=false outcome). Predefined ids answer from system state."""
    if kind == "comm" and kid in _PREDEF_COMM_IDS:
        val, _ = _predef_comm(kid)
        return val
    if kind == "win" and kid in _PREDEF_WIN_IDS:
        val, _ = _predef_win(obj, kid)
        return val
    _get_kv(kid, kind, for_set=False)
    return obj.attrs.get(kid)


def delete_attr(obj, kind: str, kid: int) -> None:
    """MPI_*_delete_attr: fires the delete callback."""
    if kid in (_PREDEF_COMM_IDS if kind == "comm" else
               _PREDEF_WIN_IDS if kind == "win" else ()):
        raise errors.MPIError(errors.ERR_KEYVAL,
                              f"predefined attribute {kid} is "
                              "read-only")
    kv = _get_kv(kid, kind, for_set=True)
    if kid not in obj.attrs:
        raise errors.MPIError(errors.ERR_KEYVAL,
                              f"attribute {kid} not set")
    kv.delete_fn and kv.delete_fn(obj, kid, obj.attrs[kid],
                                  kv.extra_state)
    del obj.attrs[kid]


def copy_attrs(old, new, kind: str) -> None:
    """The dup hook (ompi_attr_copy_all): fire each cached keyval's
    copy callback; copy_fn=None (NULL_COPY_FN) and the NO_COPY
    sentinel both drop the attribute from the dup. Attrs attached
    before free_keyval still propagate (MPI-4 §7.7.2 — the
    PETSc-style create/set/free-immediately caching pattern)."""
    for kid in list(old.attrs):
        kv = _keyvals.get(kid) if isinstance(kid, int) else None
        if kv is None or kv.kind != kind:
            continue
        if kv.copy_fn is None:
            continue
        out = kv.copy_fn(old, kid, kv.extra_state, old.attrs[kid])
        if out is not NO_COPY:
            new.attrs[kid] = out


def delete_attrs(obj, kind: str) -> None:
    """The free hook (ompi_attr_delete_all): fire delete callbacks in
    insertion order, once, and clear."""
    for kid in list(obj.attrs):
        kv = _keyvals.get(kid) if isinstance(kid, int) else None
        if kv is None or kv.kind != kind:
            continue
        val = obj.attrs.pop(kid)
        if kv.delete_fn is not None:
            kv.delete_fn(obj, kid, val, kv.extra_state)
