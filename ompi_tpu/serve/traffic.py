"""Decode-shaped MoE traffic: seeded Zipf token->expert with a hotness dial.

Production MoE serving skew is Zipf-shaped — a handful of experts take
most tokens (GShard sec 3.2, Switch-Transformer appendix). The
generator draws expert ids from ``p(rank) ~ rank^-hotness`` over a
seeded random expert permutation, then synthesizes token embeddings
whose router argmax IS the drawn expert: the router matrix is a set of
orthonormal columns (QR of seeded gaussians) and a token for expert e
is ``scale * wg[:, e] + noise``, so ``x @ wg`` peaks at e by
construction. ``hotness=0`` is uniform; ``hotness~1.1`` gives the
classic 8x hot-expert skew the smoke lane asserts on.

Everything is driven by one ``numpy.random.default_rng(seed)`` — two
generators built with the same constructor args produce bitwise-equal
id streams and batches (the determinism test), and every rank of a
multi-controller job builds the same router weights for free.
"""

from __future__ import annotations

import numpy as np

from ompi_tpu import errors


class ZipfTraffic:
    """Seeded Zipf token->expert generator + matching router weights."""

    def __init__(self, n_experts: int, d_model: int, *,
                 hotness: float = 1.1, seed: int = 0,
                 scale: float = 4.0, noise: float = 0.05):
        if n_experts < 1 or d_model < n_experts:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"ZipfTraffic needs 1 <= n_experts <= d_model, got "
                f"n_experts={n_experts} d_model={d_model} (router columns "
                f"are orthonormal, so d_model must cover them)")
        if hotness < 0:
            raise errors.MPIError(
                errors.ERR_ARG, f"hotness must be >= 0, got {hotness}")
        self.n_experts = int(n_experts)
        self.d_model = int(d_model)
        self.hotness = float(hotness)
        self.scale = float(scale)
        self.noise = float(noise)
        rng = np.random.default_rng(seed)
        # which expert sits at each popularity rank (rank 0 = hottest)
        self.perm = rng.permutation(self.n_experts)
        ranks = np.arange(1, self.n_experts + 1, dtype=np.float64)
        w = ranks ** -self.hotness
        self.probs = w / w.sum()
        # orthonormal router columns: token built from column e argmaxes
        # to e under x @ wg (cross terms are exactly 0 pre-noise)
        q, _ = np.linalg.qr(rng.standard_normal((self.d_model,
                                                 self.n_experts)))
        self.wg = np.ascontiguousarray(q[:, :self.n_experts],
                                       dtype=np.float32)
        self._rng = rng

    @property
    def hot_expert(self) -> int:
        """The expert at popularity rank 0 (ground truth for tests)."""
        return int(self.perm[0])

    def expert_ids(self, n_tokens: int) -> np.ndarray:
        """Draw [n_tokens] expert ids from the Zipf distribution."""
        ranks = self._rng.choice(self.n_experts, size=int(n_tokens),
                                 p=self.probs)
        return self.perm[ranks]

    def batch(self, expert_ids: np.ndarray) -> np.ndarray:
        """Token embeddings [T, d_model] that route to ``expert_ids``."""
        ids = np.asarray(expert_ids, dtype=np.int64)
        x = self.wg[:, ids].T * self.scale
        x = x + self.noise * self._rng.standard_normal(x.shape)
        return np.ascontiguousarray(x, dtype=np.float32)

    def request(self, n_tokens: int) -> tuple[np.ndarray, np.ndarray]:
        """One decode request: (expert_ids [T], tokens [T, D])."""
        ids = self.expert_ids(n_tokens)
        return ids, self.batch(ids)
