"""Capacity-factor dispatch policies over the EP alltoall path.

The training router (:func:`ompi_tpu.ops.moe.top1_routing`) is
Switch-Transformer top-1 with static capacity: every token past an
expert's ``C`` slots is silently zeroed. Under serving skew that is a
*policy decision*, and this module makes it explicit — three policies,
each ONE compiled program per (policy, mesh, capacity) riding the
per-comm ``_Ctx`` caches of :mod:`ompi_tpu.coll.xla`:

``drop``
    Exactly the training path (bit-identical outputs — the program
    embeds the same ``top1_routing`` + ``ep_apply`` op sequence), but
    the overflow is METERED: the program returns a stats vector and
    the host leg feeds ``serve_dropped_tokens`` + the expert-load
    heatmap.

``reroute``
    Overflow tokens are re-dispatched to the least-loaded experts in
    the SAME slice (GShard's second-expert idea, restricted to free
    capacity): experts sort by primary load ascending, each overflow
    token takes the next free slot in that order, its combine weight
    is its gate for the expert it actually landed on. Token-conserving
    by construction — the j-th overflow token maps to the j-th free
    slot, and a token never holds two slots.

``dcn_overflow``
    Topology-aware: the primary program runs drop over the hier
    plane's ICI level only (slices are expert REPLICAS, so
    ``E_total = E_local * n_ici``); overflow tokens are then shipped
    to the neighbor slice over the DCN level via two
    ``alltoallv_dev`` legs (token rows forward, activations back),
    served from the replica's free capacity, and added back at their
    positions. The ``serve_dcn_budget_bytes`` cvar bounds the shipped
    bytes per dispatch — overflow past the budget drops, which is the
    link-cost-aware drop decision the flat policies cannot make.

An unknown policy name raises ``MPIError(ERR_ARG)`` at the FIRST
dispatch and is never cached (the coll/hier bad-split contract: a
config typo keeps surfacing instead of silently serving drop).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu import errors
from ompi_tpu.coll import hier as _hier, xla as _xla
from ompi_tpu.core import cvar, pvar
from ompi_tpu.monitoring import matrix as _mon
from ompi_tpu.ops import moe
from ompi_tpu.parallel import hierarchical as H
from ompi_tpu.util import jaxcompat

#: dispatch policy names, in documentation order
POLICIES = ("drop", "reroute", "dcn_overflow")

# registered WITHOUT choices= on purpose (the coll_hier_dcn_dtype
# precedent): serve policy/config errors surface at dispatch time via
# MPIError(ERR_ARG), not at mca-parse time
_budget_var = cvar.register(
    "serve_dcn_budget_bytes", 0, int,
    help="Per-dispatch byte budget for the dcn_overflow policy's "
         "remote leg (forward token rows + returned activations, "
         "f32 wire). Overflow tokens past the budget are dropped — "
         "the link-cost-aware drop decision. 0 [default] ships every "
         "overflow token.", level=5)


def _softmax(logits):
    """The exact gate formula of ``top1_routing`` (shared so the
    dcn_overflow program's remote combine weight is bit-consistent
    with the local one)."""
    import jax.numpy as jnp
    from jax import lax

    g = logits.astype(jnp.float32)
    g = jnp.exp(g - lax.stop_gradient(g.max(-1, keepdims=True)))
    return g / g.sum(-1, keepdims=True)


def reroute_routing(logits, capacity: int):
    """Top-1 routing with overflow re-dispatched to free capacity.

    Returns ``(MoEDispatch, rerouted)``. All shapes static: overflow
    tokens are ranked by arrival (j = their index among overflow),
    experts by primary load ascending (stable argsort), and the j-th
    overflow token takes the j-th free slot in that expert order —
    ``searchsorted`` over the cumulative free-slot counts finds the
    landing expert without any loop. Tokens past the total free
    capacity stay dropped (capacity rounding can make E*C < T)."""
    import jax.numpy as jnp

    t, e = logits.shape
    gates = _softmax(logits)
    expert = jnp.argmax(gates, axis=-1)                   # [T]
    onehot = jnp.eye(e, dtype=jnp.float32)[expert]        # [T,E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0       # [T,E]
    keep = (pos >= 0) & (pos < capacity)
    pos_c = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    dispatch = (jnp.eye(capacity, dtype=jnp.float32)[pos_c]
                * keep[..., None])                        # [T,E,C]
    gate1 = (gates * onehot).sum(-1)                      # [T]
    combine = dispatch * gate1[:, None, None]
    counts = onehot.sum(0).astype(jnp.int32)              # [E]

    # --- the reroute leg: j-th overflow token -> j-th free slot -----
    used = jnp.minimum(counts, capacity)                  # [E]
    free = capacity - used                                # [E]
    order = jnp.argsort(used)                             # least-loaded first
    cfree = jnp.cumsum(free[order])                       # [E]
    total_free = cfree[-1]
    over = 1 - (dispatch.sum((1, 2)) > 0.5).astype(jnp.int32)  # [T]
    j = jnp.cumsum(over) * over - 1                       # [T], -1 = kept
    valid = (over > 0) & (j >= 0) & (j < total_free)
    k = jnp.clip(jnp.searchsorted(cfree, j, side="right"), 0, e - 1)
    new_e = order[k]                                      # [T]
    offset = jnp.where(k > 0, cfree[jnp.maximum(k - 1, 0)], 0)
    slot = jnp.clip(used[new_e] + (j - offset),
                    0, capacity - 1).astype(jnp.int32)
    oh_new = (jnp.eye(e, dtype=jnp.float32)[new_e]
              * valid.astype(jnp.float32)[:, None])       # [T,E]
    disp_new = (jnp.eye(capacity, dtype=jnp.float32)[slot][:, None, :]
                * oh_new[..., None])                      # [T,E,C]
    gate_new = (gates * oh_new).sum(-1)                   # [T]
    dispatch = dispatch + disp_new
    combine = combine + disp_new * gate_new[:, None, None]
    rerouted = valid.sum().astype(jnp.int32)
    dropped = (over.sum() - rerouted).astype(jnp.int32)
    return moe.MoEDispatch(combine=combine, dispatch=dispatch,
                           counts=counts, dropped=dropped), rerouted


def routed_ffn(x, wg, w1, w2, axis: str, capacity_factor: float,
               policy: str):
    """The traced policy layer: ``moe_ffn`` with explicit overflow
    handling and a stats tail. Usable inside any shard_map (the bench
    drives it on an in-process mesh); :class:`Dispatcher` compiles it
    over a communicator's mesh. Returns ``(out [T,D], stats)`` where
    stats is ``int32 [4 + E]``: kept, rerouted, dropped,
    multi-assigned tokens (conservation probe, always 0), then the
    per-expert routed histogram (pre-capacity demand — what the
    hot-expert verdict reads)."""
    import jax.numpy as jnp

    if policy not in ("drop", "reroute"):
        raise errors.MPIError(
            errors.ERR_ARG,
            f"routed_ffn: policy {policy!r} not traceable here "
            "(expected 'drop' or 'reroute'; 'dcn_overflow' needs the "
            "Dispatcher's host legs)")
    n = jaxcompat.axis_size(axis)
    t = x.shape[0]
    e_total = w1.shape[0] * n
    cap = max(int(capacity_factor * t / e_total), 1)
    logits = x @ wg
    if policy == "drop":
        route = moe.top1_routing(logits, cap)
        rerouted = jnp.int32(0)
    else:
        route, rerouted = reroute_routing(logits, cap)
    out = moe.ep_apply(route, x, w1, w2, axis)
    multi = (route.dispatch.sum((1, 2)) > 1.5).sum().astype(jnp.int32)
    kept = (t - route.dropped - rerouted).astype(jnp.int32)
    stats = jnp.concatenate([
        jnp.stack([kept, rerouted, route.dropped, multi]), route.counts])
    return out, stats


class Dispatcher:
    """One serving MoE layer bound to a communicator.

    ``wg`` is the router ``[D, E_total]`` (replicated), ``w1``/``w2``
    this rank's experts ``[E_local, D, F]`` / ``[E_local, F, D]``.
    Under the flat policies ``E_total = E_local * comm.size``; under
    ``dcn_overflow`` the hier grid's slices are expert replicas, so
    ``E_total = E_local * n_ici`` and every slice passes the same
    logical weights. ``dispatch(x)`` returns ``(out, info)`` with
    info the host-readable stats dict; every dispatch feeds the
    ``serve_*`` pvars and the monitoring ``[serve]`` section."""

    def __init__(self, comm, wg, w1, w2, *,
                 capacity_factor: float = 1.25,
                 policy: str = "drop") -> None:
        self.comm = comm
        self.wg, self.w1, self.w2 = wg, w1, w2
        self.capacity_factor = float(capacity_factor)
        self.policy = policy
        self._staged: dict = {}

    # -- staged (device-resident, immutable) weight globals ----------
    def _weights(self, ctx, mode: str, sharding=None):
        st = self._staged.get(mode)
        if st is None:
            import jax.numpy as jnp

            st = self._staged[mode] = tuple(
                ctx.to_global(jnp.asarray(w, jnp.float32), sharding)
                for w in (self.wg, self.w1, self.w2))
        return st

    def dispatch(self, x):
        # policy validation BEFORE any cache/plan touch: a bad name
        # raises here on every call, never cached
        if self.policy not in POLICIES:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"serve: unknown dispatch policy {self.policy!r} "
                f"(expected one of {POLICIES})")
        import jax.numpy as jnp

        x_j = jnp.asarray(x, jnp.float32)
        ctx = _xla._ctx(self.comm)
        if self.policy == "dcn_overflow":
            return self._dispatch_dcn(ctx, x_j)
        return self._dispatch_flat(ctx, x_j)

    __call__ = dispatch

    def _check_router(self, groups: int, scope: str) -> None:
        # a mismatched router width would otherwise surface as an
        # opaque reshape error inside the traced alltoall
        e_total = int(self.wg.shape[1])
        e_local = int(self.w1.shape[0])
        if e_total != e_local * groups:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"serve: router wg has {e_total} experts but "
                f"{self.policy!r} dispatch expects e_local * {scope} "
                f"= {e_local} * {groups} = {e_local * groups}")

    # -- drop / reroute: one compiled program over the flat mesh ------
    def _dispatch_flat(self, ctx, x_j):
        self._check_router(self.comm.size, "comm.size")
        t = int(x_j.shape[0])
        cf, policy = self.capacity_factor, self.policy
        key = _xla._key(x_j, "serve_ffn", policy, cf,
                        int(self.w1.shape[0]))

        def build():
            def body(xb, wgb, w1b, w2b):
                return routed_ffn(xb[0], wgb[0], w1b[0], w2b[0],
                                  axis=_xla.AXIS, capacity_factor=cf,
                                  policy=policy)
            jax, P = ctx.jax, ctx.P
            return jax.jit(jaxcompat.shard_map(
                body, mesh=ctx.mesh, in_specs=P(_xla.AXIS),
                out_specs=(P(_xla.AXIS), P(_xla.AXIS)),
                check_vma=False))

        fn = ctx.compiled(key, build)
        gwg, gw1, gw2 = self._weights(ctx, "flat")
        out_g, stats_g = ctx.launch(fn, ctx.to_global(x_j),
                                    gwg, gw1, gw2)
        stats = np.array(ctx.my_shard(stats_g))
        return ctx.my_shard(out_g), self._meter(stats, t, 0, 0)

    # -- dcn_overflow: ICI-drop program + DCN host legs ---------------
    def _dispatch_dcn(self, ctx, x_j):
        plan = _hier._plan(self.comm)  # ERR_ARG on bad split, uncached
        if plan is None:
            raise errors.MPIError(
                errors.ERR_ARG,
                "serve: policy 'dcn_overflow' needs a hier grid for "
                "this comm — set coll_hier_split (e.g. '2x2') or run "
                "across slices")
        import jax.numpy as jnp

        t, d = (int(s) for s in x_j.shape)
        e_local = int(self.w1.shape[0])
        n_ici, n_dcn = plan.n_ici, plan.n_dcn
        self._check_router(n_ici, "n_ici (slices are replicas)")
        cap = max(int(self.capacity_factor * t / (e_local * n_ici)), 1)
        key = _xla._key(x_j, "serve_ffn_dcn", self.capacity_factor,
                        n_dcn, n_ici, e_local)

        def build():
            def body(xb, wgb, w1b, w2b):
                x_, wg_ = xb[0], wgb[0]
                logits = x_ @ wg_
                route = moe.top1_routing(logits, cap)
                out = moe.ep_apply(route, x_, w1b[0], w2b[0],
                                   H.ICI_AXIS)
                assigned = route.dispatch.sum((1, 2))
                kept_tok = (assigned > 0.5).astype(jnp.int32)   # [T]
                picked = jnp.argmax(logits, -1).astype(jnp.int32)
                gate1 = _softmax(logits).max(-1)                # [T]
                multi = (assigned > 1.5).sum().astype(jnp.int32)
                stats = jnp.concatenate([
                    jnp.stack([kept_tok.sum().astype(jnp.int32),
                               jnp.int32(0), route.dropped, multi]),
                    route.counts])
                return out, stats, kept_tok, picked, gate1
            jax, P = ctx.jax, ctx.P
            spec = P((H.DCN_AXIS, H.ICI_AXIS))
            return jax.jit(jaxcompat.shard_map(
                body, mesh=plan.mesh, in_specs=spec,
                out_specs=(spec,) * 5, check_vma=False))

        fn = ctx.compiled(key, build)
        gwg, gw1, gw2 = self._weights(ctx, "dcn", plan.sharding)
        out_g, stats_g, kept_g, picked_g, gate_g = ctx.launch(
            fn, ctx.to_global(x_j, plan.sharding), gwg, gw1, gw2)
        out = np.array(ctx.my_shard(out_g))
        stats = np.array(ctx.my_shard(stats_g))
        kept_tok = np.asarray(ctx.my_shard(kept_g))
        picked = np.asarray(ctx.my_shard(picked_g))
        gate1 = np.asarray(ctx.my_shard(gate_g))

        # --- DCN leg (host): ship overflow rows to the neighbor
        # slice's replica of the picked expert. Every rank runs the
        # SAME collective sequence (allgather_obj + 2 alltoallv) even
        # with zero overflow — these are collectives.
        me, size = self.comm.rank, self.comm.size
        d_me = me // n_ici
        over_idx = np.nonzero(kept_tok == 0)[0]
        row_elems = d + 2                      # x row, e_rel, gate
        cost = (row_elems + d) * 4             # fwd + return, f32
        budget = int(_budget_var.get())
        n_ship = len(over_idx)
        if budget > 0:
            n_ship = min(n_ship, budget // cost)
        shipped = over_idx[:n_ship]
        e_rel = picked[shipped] % e_local
        owner_ici = picked[shipped] // e_local
        dst = ((d_me + 1) % n_dcn) * n_ici + owner_ici
        order = np.argsort(dst, kind="stable")
        shipped, dst, e_rel = shipped[order], dst[order], e_rel[order]
        x_np = np.asarray(x_j)
        payload = np.zeros((len(shipped), row_elems), np.float32)
        payload[:, :d] = x_np[shipped]
        payload[:, d] = e_rel
        payload[:, d + 1] = gate1[shipped]
        scounts = tuple(
            int(c) for c in np.bincount(dst, minlength=size))
        mat = self.comm.coll.allgather_obj(self.comm, scounts)
        rcounts = tuple(int(mat[s][me]) for s in range(size))
        fwd = np.asarray(_xla.alltoallv_dev(
            self.comm, jnp.asarray(payload), scounts, rcounts,
            max_count=t, _expert_tokens=False))
        # serve the visitors from this rank's replica (eager — the
        # remote leg is the slow path by design; budget bounds it)
        xs, er = fwd[:, :d], fwd[:, d].astype(np.int64)
        w1l = np.asarray(self.w1, np.float32)
        w2l = np.asarray(self.w2, np.float32)
        h = np.maximum(np.einsum("kd,kdf->kf", xs, w1l[er]), 0.0)
        y = (np.einsum("kf,kfd->kd", h, w2l[er])
             * fwd[:, d + 1][:, None]).astype(np.float32)
        back = np.asarray(_xla.alltoallv_dev(
            self.comm, jnp.asarray(y), rcounts, scounts,
            max_count=t, _expert_tokens=False))
        # return rows arrive grouped by serving rank ascending ==
        # exactly my dst-sorted payload order
        if len(shipped):
            out[shipped] += back
        dcn_bytes = int(payload.nbytes) + len(shipped) * d * 4
        stats[2] -= len(shipped)  # DCN-served tokens are not dropped
        info = self._meter(stats, t, len(shipped), dcn_bytes)
        tm = _mon.TRAFFIC
        if tm is not None:
            tm.hier("serve_overflow", 0.0, float(dcn_bytes))
        return jnp.asarray(out), info

    # -- stats -> pvars / monitoring ----------------------------------
    def _meter(self, stats, tokens: int, dcn_tokens: int,
               dcn_bytes: int) -> dict:
        kept, rerouted, dropped, multi = (int(v) for v in stats[:4])
        counts = [int(c) for c in stats[4:]]
        pvar.record("serve_tokens", tokens)
        if dropped:
            pvar.record("serve_dropped_tokens", dropped)
        if rerouted:
            pvar.record("serve_rerouted_tokens", rerouted)
        if dcn_tokens:
            pvar.record("serve_dcn_overflow_tokens", dcn_tokens)
        if dcn_bytes:
            pvar.record("serve_dcn_overflow_bytes", dcn_bytes)
        from ompi_tpu import monitoring as _monitoring

        _monitoring.expert_load(counts)
        tm = _mon.TRAFFIC
        if tm is not None:
            tm.serve_event(self.policy, tokens=tokens, kept=kept,
                           rerouted=rerouted, dropped=dropped,
                           dcn_tokens=dcn_tokens, dcn_bytes=dcn_bytes)
        return {"policy": self.policy, "tokens": tokens, "kept": kept,
                "rerouted": rerouted, "dropped": dropped,
                "multi_assigned": multi, "dcn_tokens": dcn_tokens,
                "dcn_bytes": dcn_bytes, "counts": counts}
