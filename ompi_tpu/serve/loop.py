"""Decode-shaped serving loop: per-request wall timing -> tail latency.

Serving is measured by its TAIL — a training bench reports mean
throughput, but a decode plane answers for p99. The loop here is
deliberately many SMALL iterations (decode batches of tens of tokens,
not training's thousands): each request is one dispatch through a
:class:`~ompi_tpu.serve.dispatch.Dispatcher`, individually wall-timed
with the result forced (``block_until_ready``) so the measurement
covers dispatch + transfer + compute, and the percentile summary
(p50/p95/p99) is reported NEXT TO throughput, never instead of it.

Every request feeds ``serve_requests`` on the pvar plane and — when
tracing is on — a ``serve_decode`` log2 latency histogram on the
trace plane; per-dispatch token accounting (dropped/rerouted/DCN) is
the Dispatcher's job, so the two meters compose without double
counting.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ompi_tpu.core import pvar
from ompi_tpu.monitoring import matrix as _mon
from ompi_tpu.trace import recorder as _trace


def _percentile(sorted_ns, q: float) -> float:
    """Nearest-rank percentile in milliseconds over sorted ns."""
    if not len(sorted_ns):
        return 0.0
    i = min(len(sorted_ns) - 1,
            max(0, int(round(q / 100.0 * (len(sorted_ns) - 1)))))
    return float(sorted_ns[i]) / 1e6


def run_decode(dispatcher, traffic, *, n_requests: int = 32,
               tokens_per_request: int = 32, warmup: int = 2,
               on_request=None) -> dict:
    """Drive ``n_requests`` decode-shaped requests; return the tail
    summary. ``on_request(i, info, lat_ns)`` (optional) observes each
    timed request — the live-view hook the example uses."""
    lat_ns = []
    agg = {"tokens": 0, "kept": 0, "dropped": 0, "rerouted": 0,
           "dcn_tokens": 0, "dcn_bytes": 0}
    counts: Optional[np.ndarray] = None
    for i in range(warmup + n_requests):
        _ids, x = traffic.request(tokens_per_request)
        t0 = time.perf_counter_ns()
        out, info = dispatcher(x)
        try:
            out.block_until_ready()
        except AttributeError:
            np.asarray(out)
        dt = time.perf_counter_ns() - t0
        if i < warmup:
            continue
        lat_ns.append(dt)
        pvar.record("serve_requests")
        for k in agg:
            agg[k] += int(info.get(k, 0))
        c = np.asarray(info["counts"], dtype=np.int64)
        counts = c if counts is None else counts + c
        rec = _trace.RECORDER
        if rec is not None:
            _trace.hist("serve_decode", x.nbytes, dt)
        tm = _mon.TRAFFIC
        if tm is not None:
            tm.serve_event(info["policy"], requests=1, lat_ns=dt)
        if on_request is not None:
            on_request(i - warmup, info, dt)
    lat = np.sort(np.asarray(lat_ns, dtype=np.int64))
    total_s = float(lat.sum()) / 1e9 if len(lat) else 0.0
    counts = (counts if counts is not None
              else np.zeros(0, dtype=np.int64))
    hot = int(np.argmax(counts)) if counts.size else -1
    hot_share = (float(counts[hot]) / max(int(counts.sum()), 1)
                 if counts.size else 0.0)
    return {
        "policy": dispatcher.policy,
        "requests": int(len(lat)),
        "tokens": agg["tokens"],
        "kept": agg["kept"],
        "dropped": agg["dropped"],
        "rerouted": agg["rerouted"],
        "dcn_tokens": agg["dcn_tokens"],
        "dcn_bytes": agg["dcn_bytes"],
        "drop_rate": agg["dropped"] / max(agg["tokens"], 1),
        "p50_ms": _percentile(lat, 50.0),
        "p95_ms": _percentile(lat, 95.0),
        "p99_ms": _percentile(lat, 99.0),
        "tokens_per_s": (agg["tokens"] / total_s) if total_s else 0.0,
        "expert_counts": [int(c) for c in counts],
        "hot_expert": hot,
        "hot_share": hot_share,
    }
