"""serve/ — the production-skew MoE serving plane.

The training side of the framework is deep (ZeRO 1-3, pallas DMA
kernels, hierarchical ICI x DCN collectives, async checkpoints); this
package opens the **inference workload class**: latency-shaped decode
traffic where a Zipf-skewed token->expert distribution makes hot
experts overflow their capacity — the GShard / Switch-Transformer
capacity-factor dispatch problem, run over the EP alltoall path the
framework already lowers (:mod:`ompi_tpu.ops.moe`,
``coll/xla.alltoallv_dev``).

Three cooperating pieces:

- :mod:`dispatch` — capacity-factor dispatch policies as ONE compiled
  program per (policy, mesh, capacity), riding coll/xla's per-comm
  ``_Ctx`` caches: ``drop`` (the training default, bit-identical to
  ``moe_ffn`` — but metered), ``reroute`` (overflow re-dispatched to
  the least-loaded expert in the same slice, token-conserving), and
  ``dcn_overflow`` (topology-aware: overflow shipped to a
  remote-slice replica over the hier plane's DCN level via
  ``alltoallv_dev``, byte-metered and budget-bounded so the drop
  decision knows the link cost).
- :mod:`traffic` — a seeded Zipf token->expert generator with a
  hotness dial, producing decode-shaped request batches whose router
  argmax is the drawn expert.
- :mod:`loop` — the decode latency harness: many small iterations
  with per-request wall timing, p50/p95/p99 reported NEXT TO
  throughput (the serving metric no training bench measures), fed
  into ``serve_*`` pvars, the trace plane's latency histograms, and
  the monitoring report's ``[serve]`` section (per-expert load
  heatmap + hot-expert verdict).
"""

from ompi_tpu.serve.dispatch import POLICIES, Dispatcher, routed_ffn
from ompi_tpu.serve.loop import run_decode
from ompi_tpu.serve.traffic import ZipfTraffic

__all__ = ["POLICIES", "Dispatcher", "ZipfTraffic", "routed_ffn",
           "run_decode"]
