"""Utility layer (reference: opal/util/, 20,852 LoC of C).

Most of the reference's util directory is portability scaffolding that
Python's stdlib already provides (argv/cmdline -> argparse, opal_output
-> ompi_tpu.core.output, json/sha/crc -> stdlib+zlib, printf -> str
formatting). What remains genuinely needed is implemented here:

- :mod:`ompi_tpu.util.show_help` — tagged, de-duplicated, framed user
  diagnostics (opal/util/show_help.c + help-*.txt).
- :mod:`ompi_tpu.util.net` — interface enumeration + address scoring
  for the tcp BTL's modex (opal/util/net.c + mca/if + reachable).
"""

from ompi_tpu.util import net, show_help  # noqa: F401
