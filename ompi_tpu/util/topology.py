"""Host topology — the hwloc-glue analog for mapping/binding.

Reference: opal/mca/hwloc feeds PRRTE's ``--map-by``/``--bind-to``
policies (ranks round-robin over cores/packages/NUMA nodes; each
rank's CPU set is the object it mapped to). TPU-first redesign: the
topology reads straight from Linux sysfs (no external library), with
an injectable root so the policies are testable on any box —
including this 1-core one — against synthetic topologies.

Objects: *core* = set of SMT sibling CPUs sharing a physical core;
*package* (socket) = CPUs sharing physical_package_id; *numa* = CPUs
of /sys/devices/system/node/node*. Policies return, per rank, the
CPU LIST to bind (sched_setaffinity accepts sets, so a socket-bound
rank floats over the socket's CPUs — PRRTE's bind-to-socket
behavior).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

SYS_CPU = "/sys/devices/system/cpu"
SYS_NODE = "/sys/devices/system/node"


def _read(path: str) -> Optional[str]:
    try:
        with open(path) as fh:
            return fh.read().strip()
    except OSError:
        return None


def parse_cpulist(text: str) -> List[int]:
    """sysfs cpulist format: ``0-3,8,10-11``."""
    out: List[int] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out


class Topology:
    """Cores / packages / NUMA nodes of a host (or a synthetic
    sysfs tree via ``root``), restricted to the allowed CPU set."""

    def __init__(self, root: Optional[str] = None,
                 allowed: Optional[Sequence[int]] = None) -> None:
        self._cpu_root = os.path.join(root, "cpu") if root else SYS_CPU
        self._node_root = (os.path.join(root, "node") if root
                           else SYS_NODE)
        if allowed is None:
            try:
                allowed = sorted(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                allowed = list(range(os.cpu_count() or 1))
        self.cpus = sorted(allowed)
        self.cores = self._group_cores()
        self.packages = self._group_by(self._package_of)
        self.numa_nodes = self._group_numa() or [list(self.cpus)]

    # -- sysfs walks -------------------------------------------------------
    def _topo_attr(self, cpu: int, name: str) -> Optional[str]:
        return _read(os.path.join(self._cpu_root, f"cpu{cpu}",
                                  "topology", name))

    def _core_key(self, cpu: int):
        sib = self._topo_attr(cpu, "thread_siblings_list")
        if sib is not None:
            return tuple(c for c in parse_cpulist(sib)
                         if c in set(self.cpus))
        return (cpu,)  # no sysfs: every CPU its own core

    def _package_of(self, cpu: int):
        pkg = self._topo_attr(cpu, "physical_package_id")
        return pkg if pkg is not None else "0"

    def _group_cores(self) -> List[List[int]]:
        seen = {}
        for c in self.cpus:
            key = self._core_key(c)
            if key not in seen:
                seen[key] = [x for x in (key if key else (c,))]
        return [sorted(v) for v in seen.values()]

    def _group_by(self, key_fn) -> List[List[int]]:
        groups: Dict[object, List[int]] = {}
        for c in self.cpus:
            groups.setdefault(key_fn(c), []).append(c)

        def order(kv):  # numeric id order (string sort misorders >=10)
            k = kv[0]
            try:
                return (0, int(k))
            except (TypeError, ValueError):
                return (1, str(k))

        return [sorted(v) for _, v in sorted(groups.items(),
                                             key=order)]

    def _group_numa(self) -> List[List[int]]:
        out = []
        try:  # numeric order: node10 must follow node9, not node1
            nodes = sorted((d for d in os.listdir(self._node_root)
                            if d.startswith("node")
                            and d[4:].isdigit()),
                           key=lambda d: int(d[4:]))
        except OSError:
            return []
        allowed = set(self.cpus)
        for nd in nodes:
            text = _read(os.path.join(self._node_root, nd, "cpulist"))
            if text is None:
                continue
            cpus = [c for c in parse_cpulist(text) if c in allowed]
            if cpus:
                out.append(sorted(cpus))
        return out

    # -- mapping policies (PRRTE --map-by/--bind-to) ----------------------
    def cpuset_for(self, local_rank: int, policy: str) -> List[int]:
        """The CPU list rank ``local_rank`` binds under ``policy``
        (round-robin over the policy's objects — the rmaps
        round-robin mapper)."""
        if policy in ("none", ""):
            return list(self.cpus)
        objs = {"core": self.cores,
                "socket": self.packages,
                "package": self.packages,
                "numa": self.numa_nodes}.get(policy)
        if not objs:
            raise ValueError(f"unknown map/bind policy {policy!r} "
                             "(core|socket|numa|none)")
        return objs[local_rank % len(objs)]


def describe(topo: Topology) -> str:
    """One-line topology summary (hook for hook/comm_method-style
    dumps)."""
    return (f"{len(topo.cpus)} cpus / {len(topo.cores)} cores / "
            f"{len(topo.packages)} packages / "
            f"{len(topo.numa_nodes)} numa nodes")
