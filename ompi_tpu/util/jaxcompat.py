"""jax version compatibility shims.

The device plane targets the modern ``jax.shard_map`` surface
(``check_vma=`` keyword, top-level export, jax >= 0.6). Older jax
releases ship the same transform as ``jax.experimental.shard_map``
with the varying-axes check spelled ``check_rep=``. Everything in
ompi_tpu goes through :func:`shard_map` below so the rest of the tree
can use the modern spelling unconditionally.
"""

from __future__ import annotations


def axis_size(axis) -> int:
    """Static size of a named mesh axis inside an SPMD region.

    ``jax.lax.axis_size`` is a late addition; on older jax the psum of
    a Python literal constant-folds at trace time to the axis size, so
    the result is a plain int in both cases (safe in shape arithmetic).
    """
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def shard_map(fn, *, mesh, in_specs, out_specs, **kw):
    """``jax.shard_map`` with fallback to the pre-0.6 experimental API.

    Accepts the modern ``check_vma=`` keyword and translates it to
    ``check_rep=`` when only the experimental entry point exists.
    """
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def pallas():
    """The ``jax.experimental.pallas`` module across jax versions
    (newer releases promote it to ``jax.pallas``)."""
    try:
        import jax.pallas as pl  # promoted surface, jax >= 0.8
    except ImportError:
        from jax.experimental import pallas as pl
    return pl


def pallas_tpu():
    """The Pallas TPU extension module (``pltpu``: remote-DMA copies,
    DMA/barrier semaphores, TPU memory spaces) across jax versions."""
    try:
        import jax.pallas.tpu as pltpu  # promoted surface
    except ImportError:
        from jax.experimental.pallas import tpu as pltpu
    return pltpu


def pallas_remote_dma_ok() -> bool:
    """Whether this jax build can *execute* ``make_async_remote_copy``
    kernels on the current default backend. True only on real TPU —
    the CPU interpreter in every jax release to date cannot emulate
    inter-device DMA, which is why :mod:`ompi_tpu.coll.pallas_kernels`
    gates its transport (monolithic DMA kernel on TPU, per-step
    interpret kernels + ``ppermute`` hops elsewhere)."""
    import jax

    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False
