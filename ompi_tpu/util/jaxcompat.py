"""jax version compatibility shims.

The device plane targets the modern ``jax.shard_map`` surface
(``check_vma=`` keyword, top-level export, jax >= 0.6). Older jax
releases ship the same transform as ``jax.experimental.shard_map``
with the varying-axes check spelled ``check_rep=``. Everything in
ompi_tpu goes through :func:`shard_map` below so the rest of the tree
can use the modern spelling unconditionally.
"""

from __future__ import annotations


def axis_size(axis) -> int:
    """Static size of a named mesh axis inside an SPMD region.

    ``jax.lax.axis_size`` is a late addition; on older jax the psum of
    a Python literal constant-folds at trace time to the axis size, so
    the result is a plain int in both cases (safe in shape arithmetic).
    """
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def shard_map(fn, *, mesh, in_specs, out_specs, **kw):
    """``jax.shard_map`` with fallback to the pre-0.6 experimental API.

    Accepts the modern ``check_vma=`` keyword and translates it to
    ``check_rep=`` when only the experimental entry point exists.
    """
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def pallas():
    """The ``jax.experimental.pallas`` module across jax versions
    (newer releases promote it to ``jax.pallas``)."""
    try:
        import jax.pallas as pl  # promoted surface, jax >= 0.8
    except ImportError:
        from jax.experimental import pallas as pl
    return pl


def pallas_tpu():
    """The Pallas TPU extension module (``pltpu``: remote-DMA copies,
    DMA/barrier semaphores, TPU memory spaces) across jax versions."""
    try:
        import jax.pallas.tpu as pltpu  # promoted surface
    except ImportError:
        from jax.experimental.pallas import tpu as pltpu
    return pltpu


#: wire-format name -> (ml_dtypes attribute, bytes/element) for the
#: compressed-DCN transports. bf16 ships with every ml_dtypes (a jax
#: hard dep); the fp8 pair additionally needs this jax to cast through
#: it — probed once below, so call sites never version-check inline.
_WIRE_SPECS = (
    ("bf16", "bfloat16", 2),
    ("fp8_e4m3", "float8_e4m3fn", 1),
    ("fp8_e5m2", "float8_e5m2", 1),
)

_wire_cache: dict = {}


def _fp8_cast_ok(dt) -> bool:
    """Can this jax round-trip f32 -> dt -> f32? False on old releases
    whose XLA lacks the fp8 convert lowering — the degrade signal."""
    try:
        import jax.numpy as jnp

        x = jnp.asarray([1.0], jnp.float32).astype(dt)
        return bool(x.astype(jnp.float32)[0] == 1.0)
    except Exception:  # noqa: BLE001 — any failure means "unsupported"
        return False


def _wire_table() -> dict:
    """name -> numpy dtype of every wire format THIS stack supports,
    built once (ml_dtypes lookup + the jax cast probe)."""
    table = _wire_cache.get("table")
    if table is None:
        import ml_dtypes
        import numpy as np

        table = {}
        for name, attr, _isz in _WIRE_SPECS:
            dt = getattr(ml_dtypes, attr, None)
            if dt is None:
                continue
            if name.startswith("fp8") and not _fp8_cast_ok(dt):
                continue
            table[name] = np.dtype(dt)
        _wire_cache["table"] = table
    return table


def wire_dtype(name: str):
    """numpy dtype for a compressed-DCN wire-format name ('bf16',
    'fp8_e4m3', 'fp8_e5m2'), or None when this jax/ml_dtypes stack
    cannot represent it."""
    return _wire_table().get(name)


def wire_itemsize(name: str) -> int:
    """Bytes per element of a wire format (0 for unknown names) —
    static, no capability probe, safe for pure byte accounting."""
    for n, _attr, isz in _WIRE_SPECS:
        if n == name:
            return isz
    return 0


def wire_finfo_max(name: str) -> float:
    """Largest finite value of a wire format (the fp8 scale-factor
    denominator). ``ml_dtypes.finfo``, not ``np.finfo`` — numpy's
    rejects the extended dtypes it did not define."""
    import ml_dtypes

    return float(ml_dtypes.finfo(_wire_table()[name]).max)


def wire_degrade(name: str) -> str:
    """The requested wire format when this stack supports it, else
    'bf16' — old jax without fp8 lowerings degrades instead of raising
    at the call site (the ROADMAP no-inline-version-checks rule)."""
    return name if name in _wire_table() else "bf16"


def np_dtype(name: str):
    """``np.dtype`` over the ml_dtypes-extended namespace: 'bfloat16'
    and the float8 spellings resolve like builtins (importing
    ml_dtypes registers them with numpy)."""
    import ml_dtypes  # noqa: F401 — import registers extended dtypes
    import numpy as np

    return np.dtype(name)


def pallas_device_id_type(pltpu):
    """The mesh-logical ``DeviceIdType`` member for
    ``make_async_remote_copy``/``semaphore_signal`` across jax
    versions: newer releases spell the mesh-coordinate addressing mode
    ``MESH``, older ones only have ``LOGICAL`` (same semantics inside
    ``shard_map``). osc/pallas_kernels and any future DMA kernel go
    through here instead of version-checking at the call site."""
    dt = pltpu.DeviceIdType
    return getattr(dt, "MESH", None) or dt.LOGICAL


def pallas_remote_dma_ok() -> bool:
    """Whether this jax build can *execute* ``make_async_remote_copy``
    kernels on the current default backend. True only on real TPU —
    the CPU interpreter in every jax release to date cannot emulate
    inter-device DMA, which is why :mod:`ompi_tpu.coll.pallas_kernels`
    gates its transport (monolithic DMA kernel on TPU, per-step
    interpret kernels + ``ppermute`` hops elsewhere)."""
    import jax

    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False
