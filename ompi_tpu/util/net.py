"""Network interface enumeration + address selection.

Reference: opal/util/net.c + opal/mca/if (NIC enumeration) and
mca/reachable/weighted (pairwise address scoring): the tcp BTL publishes
its candidate addresses through the modex and each peer picks the
best-scored pair.

Redesign: Linux-only (the TPU pod OS), read straight from
/proc/net (no ioctls): enumerate interfaces with their IPv4 addresses,
classify (loopback / private / public), and score candidate addresses so
btl/tcp can prefer a pod-network address over loopback when ranks span
hosts while still working single-host with only lo.
"""

from __future__ import annotations

import ipaddress
import socket
import struct
from typing import List, NamedTuple, Optional


class Interface(NamedTuple):
    name: str
    address: str
    is_loopback: bool
    is_private: bool


def interfaces() -> List[Interface]:
    """IPv4 interfaces of this host (best effort; always includes lo)."""
    out: List[Interface] = []
    try:
        # /proc/net/fib_trie is complex; getaddrinfo on the hostname +
        # a UDP-connect probe cover the common cases without ioctls
        seen = set()
        for addr in _candidate_addrs():
            if addr in seen:
                continue
            seen.add(addr)
            ip = ipaddress.ip_address(addr)
            out.append(Interface(
                name=_guess_name(ip),
                address=addr,
                is_loopback=ip.is_loopback,
                is_private=ip.is_private and not ip.is_loopback))
    except OSError:
        pass
    if not any(i.is_loopback for i in out):
        out.append(Interface("lo", "127.0.0.1", True, False))
    return out


def _candidate_addrs() -> List[str]:
    addrs = ["127.0.0.1"]
    try:
        for info in socket.getaddrinfo(
                socket.gethostname(), None, socket.AF_INET):
            addrs.append(info[4][0])
    except OSError:
        pass
    # default-route probe: the address the kernel would source from
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.254", 9))  # no packet is sent (UDP)
            addrs.append(s.getsockname()[0])
        finally:
            s.close()
    except OSError:
        pass
    return addrs


def _guess_name(ip) -> str:
    return "lo" if ip.is_loopback else "eth?"


def score(addr: str, peer_hint: Optional[str] = None) -> int:
    """Reachability score (higher = better), reachable/weighted style:
    same-subnet > private > public > loopback for cross-host; loopback
    wins only when the peer is local."""
    ip = ipaddress.ip_address(addr)
    if peer_hint is not None:
        peer = ipaddress.ip_address(peer_hint)
        if ip.is_loopback and peer.is_loopback:
            return 100
        if _same24(ip, peer):
            return 90
    if ip.is_loopback:
        return 10
    if ip.is_private:
        return 70
    return 50


def _same24(a, b) -> bool:
    pa = struct.unpack("!I", a.packed)[0] >> 8
    pb = struct.unpack("!I", b.packed)[0] >> 8
    return pa == pb


def best_address(peer_hint: Optional[str] = None) -> str:
    """The address this rank should publish/pick for TCP endpoints."""
    cands = interfaces()
    return max(cands, key=lambda i: score(i.address, peer_hint)).address


def pick_peer_address(published: List[str],
                      my_addr: Optional[str] = None) -> str:
    """Choose which of a peer's published addresses to dial."""
    if not published:
        raise ValueError("peer published no addresses")
    return max(published, key=lambda a: score(a, my_addr))
