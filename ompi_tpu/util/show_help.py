"""show_help — tagged, templated user-facing diagnostics.

Reference: opal/util/show_help.c + the help-*.txt ini files: user-visible
errors are keyed (topic, tag), rendered from templates with %-style
substitution, de-duplicated so a 512-rank job prints one copy instead of
512, and framed so they stand out from debug noise.

Redesign: topics are Python dicts registered by the owning module (no
ini parsing), de-dup is per-process by (topic, tag) — the aggregation
the reference does in the runtime daemon is served by the launcher
only forwarding rank 0's stderr by default.
"""

from __future__ import annotations

import sys
import threading
from typing import Dict, Set, Tuple

_topics: Dict[str, Dict[str, str]] = {}
_seen: Set[Tuple[str, str]] = set()
_lock = threading.Lock()

_FRAME = "-" * 64


def add_topic(topic: str, messages: Dict[str, str]) -> None:
    """Register a topic's tagged message templates."""
    with _lock:
        _topics.setdefault(topic, {}).update(messages)


def render(topic: str, tag: str, **subst) -> str:
    tpl = _topics.get(topic, {}).get(tag)
    if tpl is None:
        return (f"[{topic}:{tag}] (no help text registered) "
                f"args={subst!r}")
    try:
        body = tpl % subst if subst else tpl
    except (KeyError, ValueError):
        body = f"{tpl}\n(help substitution failed: {subst!r})"
    return f"{_FRAME}\n{body.rstrip()}\n{_FRAME}"


def show(topic: str, tag: str, once: bool = True, **subst) -> None:
    """Print a framed help message to stderr; once=True de-duplicates
    repeats of the same (topic, tag) in this process."""
    with _lock:
        if once and (topic, tag) in _seen:
            return
        _seen.add((topic, tag))
    print(render(topic, tag, **subst), file=sys.stderr)


def reset_for_testing() -> None:
    with _lock:
        _seen.clear()


# built-in topics for the runtime plane
add_topic("launcher", {
    "rank-died": (
        "A rank exited abnormally and fault tolerance is not enabled,\n"
        "so tpurun is terminating the whole job (mpirun behavior).\n"
        "  rank:   %(rank)s\n"
        "  cause:  %(cause)s\n"
        "Enable ULFM-style survival with: tpurun --mca ft 1"),
    "store-unreachable": (
        "A rank could not reach the rendezvous store at %(addr)s.\n"
        "The job cannot bootstrap without it (it is the PMIx-server\n"
        "equivalent). Check that the launcher is still alive and that\n"
        "no firewall blocks loopback/job-private traffic."),
})
add_topic("ft", {
    "detector-dead": (
        "ULFM failure detector on rank %(rank)s stopped after repeated\n"
        "store RPC failures (%(error)s). This rank can no longer\n"
        "observe failures or revocations, and peers may soon declare\n"
        "it stale-dead. If the job is not shutting down, the\n"
        "rendezvous store is unhealthy."),
    "failure-detected": (
        "ULFM failure detector: rank(s) %(ranks)s declared failed\n"
        "(%(why)s). Surviving ranks keep running; use\n"
        "comm.shrink()/comm.agree() to recover, comm.revoke() to\n"
        "interrupt peers still blocked on the failed rank(s)."),
})
