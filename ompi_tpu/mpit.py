"""MPI_T — the tools information interface.

Reference: ompi/mpi/tool/ over mca_base_var / mca_base_pvar
(opal/mca/base/mca_base_pvar.h:20-64): indexed enumeration of control
variables with read/write, performance variables accessed through
sessions and bound handles with start/stop/read/reset semantics, and
the MPI-4 event interface (event_register_callback.c:22-24,
event_copy.c, event_read.c, event_set_dropped_handler.c) over typed
event sources.

Mapped onto the cvar/pvar/events planes: cvars enumerate in
sorted-name order (stable within a process lifetime, like the
reference's registration order); pvar handles bind a counter name
inside a session and report deltas from their start() point; event
handles bind a registered event type and either get synchronous
callbacks or drain a bounded buffer with drop accounting
(core/events.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ompi_tpu.core import cvar, events as _events, pvar

VERBOSITY_USER_BASIC, VERBOSITY_USER_DETAIL, VERBOSITY_USER_ALL = 1, 2, 3
VERBOSITY_TUNER_BASIC, VERBOSITY_TUNER_DETAIL, VERBOSITY_TUNER_ALL = 4, 5, 6
VERBOSITY_MPIDEV_BASIC, VERBOSITY_MPIDEV_DETAIL, VERBOSITY_MPIDEV_ALL = \
    7, 8, 9


def init_thread() -> None:
    """MPI_T_init_thread: the tool interface is usable before and
    after MPI init/finalize (nothing to bring up here — kept for API
    parity)."""


def finalize() -> None:
    """MPI_T_finalize."""


# -- control variables -----------------------------------------------------

#: enumeration order frozen at first sight: MPI_T indices must stay
#: stable for the process lifetime even though modules register cvars
#: lazily — new names APPEND, existing indices never shift
_cvar_order: List[str] = []
_cvar_seen: set = set()


def _cvar_names() -> List[str]:
    for name in sorted(cvar.all_vars()):
        if name not in _cvar_seen:
            _cvar_seen.add(name)
            _cvar_order.append(name)
    return _cvar_order


def cvar_get_num() -> int:
    return len(_cvar_names())


def cvar_get_info(index: int) -> Dict[str, Any]:
    """MPI_T_cvar_get_info: name/type/default/verbosity/description."""
    name = _cvar_names()[index]
    var = cvar.lookup(name)
    return {
        "name": name,
        "type": var.typ.__name__,
        "default": var.default,
        "verbosity": var.level,
        "desc": var.help,
        "choices": list(var.choices) if var.choices is not None else None,
    }


def cvar_index(name: str) -> int:
    """MPI_T_cvar_get_index."""
    return _cvar_names().index(name)


class CvarHandle:
    """MPI_T_cvar_handle: read/write one control variable."""

    def __init__(self, index: int) -> None:
        self._var = cvar.lookup(_cvar_names()[index])

    def read(self):
        return self._var.get()

    def write(self, value) -> None:
        self._var.set(value)


# -- performance variables -------------------------------------------------

def pvar_get_num() -> int:
    return len(pvar.snapshot())


def pvar_names() -> List[str]:
    return sorted(pvar.snapshot())


class PvarSession:
    """MPI_T_pvar_session: isolates handle lifetimes (reference:
    sessions scope bound handles so tools don't interfere)."""

    def __init__(self) -> None:
        self._handles: List["PvarHandle"] = []
        self._freed = False

    def handle_alloc(self, name: str) -> "PvarHandle":
        if self._freed:
            raise RuntimeError("session freed")
        h = PvarHandle(name)
        self._handles.append(h)
        return h

    def free(self) -> None:
        self._freed = True
        self._handles.clear()


class PvarHandle:
    """A counter bound in a session: start() marks the baseline,
    read() returns the delta since start, stop() freezes it."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._base: Optional[int] = None
        self._frozen: Optional[int] = None

    def start(self) -> None:
        self._base = pvar.read(self.name)
        self._frozen = None

    def stop(self) -> None:
        if self._base is not None:
            self._frozen = pvar.read(self.name) - self._base

    def read(self) -> int:
        if self._base is None:
            return pvar.read(self.name)  # unstarted: absolute value
        if self._frozen is not None:
            return self._frozen
        return pvar.read(self.name) - self._base

    def reset(self) -> None:
        self._base = pvar.read(self.name)
        self._frozen = None


def pvar_session_create() -> PvarSession:
    return PvarSession()


# -- events (MPI-4 MPI_T_event_*: r3 VERDICT missing #1) -------------------

def event_get_num() -> int:
    """MPI_T_event_get_num."""
    return _events.get_num()


def event_get_info(index: int) -> Dict[str, Any]:
    """MPI_T_event_get_info: name/desc/element fields/source."""
    return _events.get_info(index)


def event_index(name: str) -> int:
    """MPI_T_event_get_index."""
    return _events.index_of(name)


def event_handle_alloc(name_or_index, callback=None,
                       buffer_size: int = 256) -> "_events.EventHandle":
    """MPI_T_event_handle_alloc (+ register_callback when `callback`
    given). Without a callback the handle buffers up to `buffer_size`
    instances for :meth:`EventHandle.read`; overflow counts drops and
    fires the dropped handler."""
    return _events.handle_alloc(name_or_index, callback, buffer_size)


def source_get_num() -> int:
    """MPI_T_source_get_num."""
    return len(_events.SOURCES)


def source_get_info(index: int) -> Dict[str, Any]:
    """MPI_T_source_get_info."""
    return dict(_events.SOURCES[index])


def source_get_timestamp(index: int = 0) -> int:
    """MPI_T_source_get_timestamp."""
    return _events.source_timestamp()


# -- categories (MPI_T_category_*: one per framework) ----------------------

def category_get_num() -> int:
    return len(categories())


def categories() -> List[Tuple[str, List[str]]]:
    """Frameworks as categories, each listing its cvars by prefix."""
    from ompi_tpu.core import registry

    out = []
    names = _cvar_names()
    for fw in sorted(registry.all_frameworks()):
        out.append((fw, [n for n in names if n.startswith(fw)]))
    return out
