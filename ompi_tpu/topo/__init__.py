"""Process topologies — cartesian, graph, distributed graph.

Reference: ompi/mca/topo/ — topo_base_cart_create.c:1 (cart construction
+ optional reorder), topo_base_cart_sub.c (sub-grids), base graph/dist
graph bookkeeping, and the neighborhood collective slots they unlock
(ompi/mca/coll/coll.h:600-618, implemented linearly in coll/basic).

TPU-first bridge: a cartesian communicator is the host-plane face of a
device mesh — ``Cart_sub`` keeps a subset of dims exactly as
``DeviceCommunicator.sub`` keeps a subset of mesh axes
(parallel/device_comm.py). ``cart_of_mesh``/``replica_groups`` make the
correspondence testable: the groups Cart_sub produces equal the XLA
replica_groups of the matching mesh axes.

Neighbor ordering follows the MPI standard: cartesian neighbor lists are
(-1, +1) per dimension in dimension order; graph lists use the stored
adjacency order. PROC_NULL neighbors (open boundaries) contribute
nothing and their recv slots are left untouched.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ompi_tpu.comm import Communicator, UNDEFINED
from ompi_tpu.pml.request import PROC_NULL


def dims_create(nnodes: int, ndims: int,
                dims: Optional[Sequence[int]] = None) -> List[int]:
    """MPI_Dims_create: balanced factorization of nnodes over ndims
    (reference: ompi/mpi/c/dims_create.c). Nonzero entries in `dims`
    are fixed constraints."""
    out = list(dims) if dims is not None else [0] * ndims
    fixed = math.prod(d for d in out if d > 0) or 1
    if nnodes % fixed:
        raise ValueError(
            f"Dims_create: {nnodes} not divisible by fixed dims {out}")
    rem = nnodes // fixed
    free = [i for i, d in enumerate(out) if d == 0]
    # greedy balanced: repeatedly give the largest prime factor to the
    # currently-smallest free dim
    factors: List[int] = []
    n, p = rem, 2
    while p * p <= n:
        while n % p == 0:
            factors.append(p)
            n //= p
        p += 1
    if n > 1:
        factors.append(n)
    sizes = {i: 1 for i in free}
    for f in sorted(factors, reverse=True):
        tgt = min(free, key=lambda i: sizes[i]) if free else None
        if tgt is None:
            break
        sizes[tgt] *= f
    for i in free:
        out[i] = sizes[i]
    # MPI orders free dims non-increasing
    vals = sorted((out[i] for i in free), reverse=True)
    for i, v in zip(free, vals):
        out[i] = v
    return out


class CartTopo:
    """Cartesian topology attachment (comm.topo)."""

    kind = "cart"

    def __init__(self, dims: Sequence[int], periods: Sequence[bool]):
        self.dims = tuple(int(d) for d in dims)
        self.periods = tuple(bool(p) for p in periods)
        if len(self.dims) != len(self.periods):
            raise ValueError("dims/periods length mismatch")
        self.size = math.prod(self.dims) if self.dims else 1

    @property
    def ndims(self) -> int:
        return len(self.dims)

    def coords(self, rank: int) -> List[int]:
        """MPI_Cart_coords (row-major, like the reference)."""
        c = []
        for d in reversed(self.dims):
            c.append(rank % d)
            rank //= d
        return list(reversed(c))

    def rank_of(self, coords: Sequence[int]) -> int:
        """MPI_Cart_rank (periodic dims wrap; open dims out-of-range ->
        PROC_NULL)."""
        if len(coords) != self.ndims:
            raise ValueError(
                f"Cart_rank: {len(coords)} coords for {self.ndims} dims")
        r = 0
        for c, d, per in zip(coords, self.dims, self.periods):
            if not 0 <= c < d:
                if not per:
                    return PROC_NULL
                c %= d
            r = r * d + c
        return r

    def shift(self, rank: int, direction: int,
              disp: int = 1) -> Tuple[int, int]:
        """MPI_Cart_shift -> (source, dest)."""
        c = self.coords(rank)
        src = list(c)
        dst = list(c)
        src[direction] -= disp
        dst[direction] += disp
        return self.rank_of(src), self.rank_of(dst)

    def neighbors(self, rank: int) -> List[int]:
        """MPI-standard cart neighbor order: per dim, (-1, +1)."""
        out = []
        for d in range(self.ndims):
            src, dst = self.shift(rank, d, 1)
            out.extend((src, dst))
        return out

    in_neighbors = neighbors
    out_neighbors = neighbors

    def route(self, src: int, dst: int) -> List[Tuple[int, int, int, int]]:
        """Minimal-hop dimension-ordered route src -> dst on the grid:
        the hop list [(from_rank, to_rank, dim, step)] a message
        traverses, walking each dimension in turn by +/-1 steps and
        taking the wraparound direction on periodic dims when it is
        strictly shorter (ties -> positive direction, matching the ICI
        default route). This is the monitoring plane's link-attribution
        model — dimension-ordered routing on the torus."""
        hops: List[Tuple[int, int, int, int]] = []
        cur = list(self.coords(src))
        tgt = self.coords(dst)
        here = src
        for d, size in enumerate(self.dims):
            delta = tgt[d] - cur[d]
            if self.periods[d] and size > 1:
                # shortest signed distance on the ring; tie -> +1
                delta = (delta + size // 2 - (size % 2 == 0)) \
                    % size - size // 2 + (size % 2 == 0)
            step = 1 if delta > 0 else -1
            for _ in range(abs(delta)):
                cur[d] += step
                nxt = self.rank_of(cur)
                hops.append((here, nxt, d, step))
                here = nxt
        return hops


class GraphTopo:
    """MPI_Graph_create topology (index/edges arrays)."""

    kind = "graph"

    def __init__(self, index: Sequence[int], edges: Sequence[int]):
        self.index = tuple(index)
        self.edges = tuple(edges)
        self.size = len(self.index)

    def neighbors(self, rank: int) -> List[int]:
        lo = self.index[rank - 1] if rank > 0 else 0
        return list(self.edges[lo:self.index[rank]])

    in_neighbors = neighbors
    out_neighbors = neighbors


class DistGraphTopo:
    """MPI_Dist_graph_create_adjacent topology (directed, per-rank)."""

    kind = "dist_graph"

    def __init__(self, sources: Sequence[int],
                 destinations: Sequence[int]):
        self.sources = tuple(sources)
        self.destinations = tuple(destinations)

    def in_neighbors(self, rank: int) -> List[int]:
        return list(self.sources)

    def out_neighbors(self, rank: int) -> List[int]:
        return list(self.destinations)


# ---------------------------------------------------------------------------
# Communicator construction (attached as methods below)


def _attach(comm: Communicator, topo) -> Communicator:
    comm.topo = topo
    # re-stack the coll table: components may install neighborhood
    # slots only when a topology is present (reference re-selects at
    # topo comm creation, topo_base_cart_create.c end)
    from ompi_tpu.coll import comm_select

    comm_select(comm)
    return comm


def _Create_cart(self, dims: Sequence[int],
                 periods: Optional[Sequence[bool]] = None,
                 reorder: bool = False) -> Optional[Communicator]:
    """MPI_Cart_create. With ``reorder=True`` on the device plane, the
    stencil graph is placed onto the ranks' device-mesh coordinates so
    halo neighbors land on ICI neighbors (the treematch analog —
    ompi/mca/topo/treematch/; see topo.reorder). Off-plane the hint is
    identity, as in the reference when no topology is available."""
    dims = list(dims)
    periods = [False] * len(dims) if periods is None else list(periods)
    n = math.prod(dims) if dims else 1
    if n > self.size:
        raise ValueError(f"cart size {n} exceeds comm size {self.size}")
    key = self.rank
    if reorder and n > 1 and self.rank < n:
        from ompi_tpu.topo import reorder as reorder_mod

        perm = reorder_mod.permute_for(
            self, reorder_mod.cart_weights(dims, periods))
        if perm is not None:
            # perm[cart position] = old rank playing it; my new cart
            # rank is the position I was assigned
            key = perm.index(self.rank)
    color = 0 if self.rank < n else UNDEFINED
    sub = self.split(color, key=key)
    if sub is None:
        return None
    return _attach(sub, CartTopo(dims, periods))


def _Cart_sub(self, remain_dims: Sequence[bool]) -> Communicator:
    """MPI_Cart_sub: split into sub-grids keeping `remain_dims`.

    Device-plane analog: DeviceCommunicator.sub(axis_subset) — the
    retained dims are the mesh axes of the sub-communicator."""
    topo: CartTopo = self.topo
    if topo is None or topo.kind != "cart":
        raise ValueError("Cart_sub on a non-cartesian communicator")
    remain = [bool(r) for r in remain_dims]
    coords = topo.coords(self.rank)
    # color = coordinates of the dropped dims; key = row-major rank of
    # the kept dims (so sub-rank order matches the reference)
    color = 0
    for c, d, keep in zip(coords, topo.dims, remain):
        if not keep:
            color = color * d + c
    sub = self.split(color, key=self.rank)
    kept_dims = [d for d, keep in zip(topo.dims, remain) if keep]
    kept_per = [p for p, keep in zip(topo.periods, remain) if keep]
    return _attach(sub, CartTopo(kept_dims, kept_per))


def _Cart_coords(self, rank: Optional[int] = None) -> List[int]:
    return self.topo.coords(self.rank if rank is None else rank)


def _Cart_rank(self, coords: Sequence[int]) -> int:
    return self.topo.rank_of(coords)


def _Cart_shift(self, direction: int, disp: int = 1) -> Tuple[int, int]:
    return self.topo.shift(self.rank, direction, disp)


def _Cart_get(self):
    t: CartTopo = self.topo
    return list(t.dims), list(t.periods), t.coords(self.rank)


def _Create_graph(self, index: Sequence[int], edges: Sequence[int],
                  reorder: bool = False) -> Optional[Communicator]:
    """MPI_Graph_create (index/edges across all ranks, as the standard
    defines)."""
    n = len(index)
    if n > self.size:
        raise ValueError(f"graph size {n} exceeds comm size {self.size}")
    color = 0 if self.rank < n else UNDEFINED
    sub = self.split(color, key=self.rank)
    if sub is None:
        return None
    return _attach(sub, GraphTopo(index, edges))


def _Create_dist_graph(self, sources: Sequence[int],
                       degrees: Sequence[int],
                       destinations: Sequence[int],
                       reorder: bool = False) -> Communicator:
    """MPI_Dist_graph_create (the general form): every rank may
    contribute ARBITRARY edges — (sources[i], degrees[i]) says source
    vertex sources[i] owns the next degrees[i] entries of
    destinations. Contributions are gathered, redistributed into
    per-vertex adjacency, then placed like the adjacent form
    (reference: ompi/mca/topo/base/topo_base_dist_graph_create.c)."""
    contrib = self.allgather(
        (list(sources), list(degrees), list(destinations)))
    outs = {r: [] for r in range(self.size)}
    ins = {r: [] for r in range(self.size)}
    for srcs, degs, dsts in contrib:
        i = 0
        for s, d in zip(srcs, degs):
            for dst in dsts[i:i + d]:
                outs[s].append(dst)
                ins[dst].append(s)
            i += d
    key = self.rank
    if reorder and self.size > 1:
        from ompi_tpu.topo import reorder as reorder_mod

        w = np.zeros((self.size, self.size))
        for s in range(self.size):
            for d in outs[s]:
                w[s, d] += 1.0
        perm = reorder_mod.permute_for(self, w)
        if perm is not None:
            key = perm.index(self.rank)
    sub = self.split(0, key=key)
    return _attach(sub, DistGraphTopo(ins[key], outs[key]))


def _Create_dist_graph_adjacent(
        self, sources: Sequence[int], destinations: Sequence[int],
        reorder: bool = False) -> Communicator:
    """MPI_Dist_graph_create_adjacent: every rank supplies its own
    in/out neighbor lists. ``reorder=True`` places the (gathered)
    graph onto device-mesh coordinates: the edge lists describe the
    VIRTUAL topology by rank number, so a process reassigned to rank v
    adopts the adjacency originally specified for v (MPI reorder
    semantics; treematch analog — see topo.reorder)."""
    key = self.rank
    if reorder and self.size > 1:
        from ompi_tpu.topo import reorder as reorder_mod

        alladj = self.allgather((list(sources), list(destinations)))
        w = np.zeros((self.size, self.size))
        for r, (srcs, dsts) in enumerate(alladj):
            for s in srcs:
                w[s, r] += 1.0
            for d in dsts:
                w[r, d] += 1.0
        perm = reorder_mod.permute_for(self, w)
        if perm is not None:
            key = perm.index(self.rank)
            sources, destinations = alladj[key]
    sub = self.split(0, key=key)
    return _attach(sub, DistGraphTopo(sources, destinations))


def _Cart_map(self, dims: Sequence[int],
              periods: Optional[Sequence[bool]] = None) -> int:
    """MPI_Cart_map: the rank this process WOULD have in the cart
    (topo_base_cart_map.c). The host plane maps identity (reorder
    placement is a device-plane hint), so ranks beyond the grid get
    UNDEFINED."""
    n = math.prod(dims) if dims else 1
    if n > self.size:  # same contract as _Create_cart
        raise ValueError(
            f"cart size {n} exceeds comm size {self.size}")
    return self.rank if self.rank < n else UNDEFINED


def _Graph_map(self, index: Sequence[int],
               edges: Sequence[int]) -> int:
    """MPI_Graph_map (topo_base_graph_map.c role)."""
    if len(index) > self.size:  # same contract as _Create_graph
        raise ValueError(
            f"graph size {len(index)} exceeds comm size {self.size}")
    return self.rank if self.rank < len(index) else UNDEFINED


def _Graph_neighbors(self, rank: Optional[int] = None) -> List[int]:
    return self.topo.neighbors(self.rank if rank is None else rank)


def _Dist_graph_neighbors(self):
    t = self.topo
    return t.in_neighbors(self.rank), t.out_neighbors(self.rank)


# -- neighborhood collectives (dispatch into the coll table) --------------

def _nbr_allgather_args(self, sendbuf, recvbuf, what):
    from ompi_tpu.mpi import _parse_buf, _require_recvbuf

    _require_recvbuf(recvbuf, what)
    sarr, count, dt = _parse_buf(sendbuf)
    rarr, _, rdt = _parse_buf(recvbuf)
    # a receive-only rank's sendbuf is empty: take the per-edge count
    # from the recv side instead of posting count-0 (truncating) recvs
    n_in = len(self.topo.in_neighbors(self.rank))
    if count == 0 and n_in:
        count = np.asarray(rarr).size // n_in
        dt = rdt
    return sarr, rarr, count, dt


def _nbr_alltoall_args(self, sendbuf, recvbuf, what):
    from ompi_tpu.mpi import _parse_buf, _require_recvbuf

    _require_recvbuf(recvbuf, what)
    sarr, _, dt = _parse_buf(sendbuf)
    rarr = _parse_buf(recvbuf)[0]
    # per-edge count: derive from whichever side has edges (a
    # receive-only rank's sendbuf is empty and must not zero the count)
    n_out = len(self.topo.out_neighbors(self.rank))
    n_in = len(self.topo.in_neighbors(self.rank))
    if n_out:
        count = np.asarray(sarr).size // n_out
    elif n_in:
        count = np.asarray(rarr).size // n_in
    else:
        count = 0
    return sarr, rarr, count, dt


def _Neighbor_allgather(self, sendbuf, recvbuf=None):
    """Device path (jax sendbuf, recvbuf omitted): compiled ppermute
    schedule on the device plane, returns a NEW (n_in, *shape) array
    (coll/xla_neighbor; staging fallback when the plane is off)."""
    self.check_revoked()
    from ompi_tpu.mpi import _is_dev

    if _is_dev(sendbuf):
        return self.coll.neighbor_allgather_dev(self, sendbuf)
    sarr, rarr, count, dt = _nbr_allgather_args(
        self, sendbuf, recvbuf, "Neighbor_allgather")
    self.coll.neighbor_allgather(self, sarr, rarr, count, dt)


def _Ineighbor_allgather(self, sendbuf, recvbuf=None):
    """MPI_Ineighbor_allgather (ompi/mpi/c/ineighbor_allgather.c):
    nonblocking; recvbuf fills at completion."""
    self.check_revoked()
    sarr, rarr, count, dt = _nbr_allgather_args(
        self, sendbuf, recvbuf, "Ineighbor_allgather")
    return self.coll.ineighbor_allgather(self, sarr, rarr, count, dt)


def _Neighbor_alltoall(self, sendbuf, recvbuf=None):
    """Device path (jax sendbuf of shape (n_out, *blk), recvbuf
    omitted): returns a NEW (n_in, *blk) device array."""
    self.check_revoked()
    from ompi_tpu.mpi import _is_dev

    if _is_dev(sendbuf):
        return self.coll.neighbor_alltoall_dev(self, sendbuf)
    sarr, rarr, count, dt = _nbr_alltoall_args(
        self, sendbuf, recvbuf, "Neighbor_alltoall")
    self.coll.neighbor_alltoall(self, sarr, rarr, count, dt)


def _Ineighbor_alltoall(self, sendbuf, recvbuf=None):
    """MPI_Ineighbor_alltoall (ompi/mpi/c/ineighbor_alltoall.c)."""
    self.check_revoked()
    sarr, rarr, count, dt = _nbr_alltoall_args(
        self, sendbuf, recvbuf, "Ineighbor_alltoall")
    return self.coll.ineighbor_alltoall(self, sarr, rarr, count, dt)


def _nbr_v_common(sendbuf, recvbuf, what):
    from ompi_tpu.mpi import _is_dev, _parse_buf, _require_recvbuf

    if _is_dev(sendbuf):
        raise NotImplementedError(
            f"{what} has no device route; stage with np.asarray "
            "(the uniform neighborhood forms have one)")
    _require_recvbuf(recvbuf, what)
    sarr, count, dt = _parse_buf(sendbuf)
    rarr, _, rdt = _parse_buf(recvbuf)
    return sarr, rarr, count, dt or rdt


def _Neighbor_allgatherv(self, sendbuf, recvbuf, rcounts,
                         rdispls=None):
    """MPI_Neighbor_allgatherv: ragged per-in-neighbor receive blocks
    (counts/displs in element units; displs default to packed). Host
    buffers only — stage device arrays with np.asarray."""
    self.check_revoked()
    from ompi_tpu.mpi import _norm_cd

    sarr, rarr, count, dt = _nbr_v_common(sendbuf, recvbuf,
                                          "Neighbor_allgatherv")
    rcounts, rdispls = _norm_cd(rcounts, rdispls)
    self.coll.neighbor_allgatherv(self, sarr, rarr, count, dt,
                                  rcounts, rdispls)


def _Ineighbor_allgatherv(self, sendbuf, recvbuf, rcounts,
                          rdispls=None):
    """MPI_Ineighbor_allgatherv (nonblocking form)."""
    self.check_revoked()
    from ompi_tpu.mpi import _norm_cd

    sarr, rarr, count, dt = _nbr_v_common(sendbuf, recvbuf,
                                          "Ineighbor_allgatherv")
    rcounts, rdispls = _norm_cd(rcounts, rdispls)
    return self.coll.ineighbor_allgatherv(self, sarr, rarr, count,
                                          dt, rcounts, rdispls)


def _Neighbor_alltoallv(self, sendbuf, recvbuf, scounts, rcounts,
                        sdispls=None, rdispls=None):
    """MPI_Neighbor_alltoallv: ragged per-edge segments (element
    units; displs default to packed). Host buffers only."""
    self.check_revoked()
    from ompi_tpu.mpi import _norm_cd

    sarr, rarr, _, dt = _nbr_v_common(sendbuf, recvbuf,
                                      "Neighbor_alltoallv")
    scounts, sdispls = _norm_cd(scounts, sdispls)
    rcounts, rdispls = _norm_cd(rcounts, rdispls)
    self.coll.neighbor_alltoallv(self, sarr, rarr, dt,
                                 scounts, sdispls, rcounts, rdispls)


def _Ineighbor_alltoallv(self, sendbuf, recvbuf, scounts, rcounts,
                         sdispls=None, rdispls=None):
    """MPI_Ineighbor_alltoallv (nonblocking form)."""
    self.check_revoked()
    from ompi_tpu.mpi import _norm_cd

    sarr, rarr, _, dt = _nbr_v_common(sendbuf, recvbuf,
                                      "Ineighbor_alltoallv")
    scounts, sdispls = _norm_cd(scounts, sdispls)
    rcounts, rdispls = _norm_cd(rcounts, rdispls)
    return self.coll.ineighbor_alltoallv(self, sarr, rarr, dt,
                                         scounts, sdispls, rcounts,
                                         rdispls)


_API = {
    "Create_cart": _Create_cart,
    "Cart_sub": _Cart_sub,
    "Cart_coords": _Cart_coords,
    "Cart_rank": _Cart_rank,
    "Cart_shift": _Cart_shift,
    "Cart_get": _Cart_get,
    "Create_graph": _Create_graph,
    "Create_dist_graph": _Create_dist_graph,
    "Create_dist_graph_adjacent": _Create_dist_graph_adjacent,
    "Graph_neighbors": _Graph_neighbors,
    "Dist_graph_neighbors": _Dist_graph_neighbors,
    "Cart_map": _Cart_map,
    "Graph_map": _Graph_map,
    "Neighbor_allgather": _Neighbor_allgather,
    "Neighbor_alltoall": _Neighbor_alltoall,
    "Neighbor_allgatherv": _Neighbor_allgatherv,
    "Neighbor_alltoallv": _Neighbor_alltoallv,
    "Ineighbor_allgather": _Ineighbor_allgather,
    "Ineighbor_alltoall": _Ineighbor_alltoall,
    "Ineighbor_allgatherv": _Ineighbor_allgatherv,
    "Ineighbor_alltoallv": _Ineighbor_alltoallv,
}

for _name, _fn in _API.items():
    setattr(Communicator, _name, _fn)


def cart_of_mesh(mesh, axis_order: Optional[Sequence[str]] = None):
    """The (dims, axis_names) a device mesh corresponds to — for
    asserting Cart_sub <-> DeviceCommunicator.sub equivalence (the
    host-plane cart of an SPMD mesh has one dim per mesh axis, same
    order, no periodicity)."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    names = list(axis_order or mesh.axis_names)
    return [shape[n] for n in names], names
