"""Topology-aware rank reordering — the treematch analog, TPU-first.

Reference: ompi/mca/topo/treematch/ maps a communication graph onto
the hardware topology tree (vendored 3rd-party/treematch) when
MPI_Cart_create / MPI_Dist_graph_create get ``reorder=1``.

TPU redesign: the "hardware topology" is the device mesh — each
rank's device carries ICI coordinates
(accelerator.get_device_attr().coords, a 2/3-D torus position on real
TPUs). Reordering = placing the comm-graph vertices onto those
coordinates so heavy edges land on mesh neighbors, with a greedy
affinity placement (the same objective treematch optimizes; greedy
because comm sizes here are small and determinism matters more than
the last percent). Off the device plane (no coords) the permutation
is identity — reorder stays a hint, as in the reference.

All ranks compute the same placement from the same inputs, so no
extra agreement round is needed beyond the graph itself.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def rank_coords(comm) -> Optional[List[Tuple[int, ...]]]:
    """Device-mesh coordinates per comm rank, or None off-plane.

    Real TPUs expose `.coords` (ICI torus position); the virtual CPU
    plane has no coords, so device ids act as positions on a line —
    enough structure for placement to be meaningful and testable."""
    from ompi_tpu.runtime import device_plane

    if not device_plane.active():
        return None
    out = []
    for w in comm.group.ranks:
        d = device_plane.device_for_world_rank(w)
        if d is None:
            return None
        c = getattr(d, "coords", None)
        out.append(tuple(c) if c is not None else (int(d.id),))
    return out


def _dist(a: Tuple[int, ...], b: Tuple[int, ...]) -> int:
    return int(sum(abs(x - y) for x, y in zip(a, b)))


def place(weights: np.ndarray,
          coords: Sequence[Tuple[int, ...]]) -> List[int]:
    """Greedy affinity placement: perm[vertex] = slot index into
    ``coords`` (slot i is the process currently holding comm rank i).

    Objective: minimize sum over edges of weight * manhattan distance,
    the treematch objective on a mesh metric. Deterministic: ties
    break on lowest index."""
    n = len(coords)
    w = np.asarray(weights, dtype=np.float64)
    assert w.shape == (n, n)
    w = w + w.T  # symmetrize: cost counts both directions

    # slots sorted along the mesh (lexicographic = a space-filling walk
    # on lines and row-major tori); vertices ordered by a weighted
    # Cuthill-McKee BFS from a peripheral (lightest) vertex, so graph
    # neighborhoods become slot neighborhoods
    slot_order = sorted(range(n), key=lambda s: coords[s])
    deg = w.sum(axis=1)
    visited: List[int] = []
    remaining = set(range(n))
    while remaining:
        start = min(remaining, key=lambda v: (deg[v], v))
        remaining.discard(start)
        queue = [start]
        while queue:
            v = queue.pop(0)
            visited.append(v)
            nbrs = sorted((u for u in remaining if w[v, u] > 0),
                          key=lambda u: (-w[v, u], u))
            for u in nbrs:
                remaining.discard(u)
                queue.append(u)
    perm = [0] * n
    for v, s in zip(visited, slot_order):
        perm[v] = s
    return _refine(perm, w, coords)


def _refine(perm: List[int], w: np.ndarray,
            coords: Sequence[Tuple[int, ...]]) -> List[int]:
    """Pairwise-swap local search (the polish treematch's recursive
    bisection makes unnecessary at these comm sizes): swap two
    vertices' slots while total weighted distance drops."""
    n = len(perm)

    def vertex_cost(v: int, p: List[int]) -> float:
        cv = coords[p[v]]
        return sum(w[v, u] * _dist(cv, coords[p[u]])
                   for u in range(n) if u != v)

    improved = True
    while improved:
        improved = False
        for a in range(n):
            for b in range(a + 1, n):
                before = vertex_cost(a, perm) + vertex_cost(b, perm) \
                    - 2 * w[a, b] * _dist(coords[perm[a]],
                                          coords[perm[b]])
                perm[a], perm[b] = perm[b], perm[a]
                after = vertex_cost(a, perm) + vertex_cost(b, perm) \
                    - 2 * w[a, b] * _dist(coords[perm[a]],
                                          coords[perm[b]])
                if after < before - 1e-12:
                    improved = True
                else:
                    perm[a], perm[b] = perm[b], perm[a]
    return perm


def cart_weights(dims: Sequence[int],
                 periods: Sequence[bool]) -> np.ndarray:
    """Unit-weight stencil adjacency of a cartesian grid (each
    neighbor pair exchanges equally in a halo pattern)."""
    import math

    n = math.prod(dims) if dims else 1
    w = np.zeros((n, n))

    def coords_of(r):
        out = []
        for d in reversed(dims):
            out.append(r % d)
            r //= d
        return list(reversed(out))

    def rank_of(c):
        r = 0
        for x, d in zip(c, dims):
            r = r * d + x
        return r

    for r in range(n):
        c = coords_of(r)
        for dim, (d, per) in enumerate(zip(dims, periods)):
            for step in (-1, 1):
                c2 = list(c)
                c2[dim] += step
                if per:
                    c2[dim] %= d
                elif not (0 <= c2[dim] < d):
                    continue
                w[r, rank_of(c2)] = 1.0
    return w


def permute_for(comm, weights: np.ndarray) -> Optional[List[int]]:
    """perm[vertex] = current comm rank that should play that vertex,
    or None when the plane offers no coordinates (identity hint)."""
    coords = rank_coords(comm)
    if coords is None or len(coords) < weights.shape[0]:
        return None
    return place(weights, coords[:weights.shape[0]])
