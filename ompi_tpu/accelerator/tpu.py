"""accelerator/tpu — the PJRT/jax device component (THE north star hook).

Reference model: opal/mca/accelerator/cuda/accelerator_cuda.c (1,235 LoC
over the CUDA driver API) with **lazy initialization** under a lock so the
device runtime is only touched on first real use
(accelerator_cuda_component.c:44,128,258). Here the device API is jax/PJRT:

- check_addr     -> isinstance(buf, jax.Array) + platform check
                    (cuPointerGetAttributes equivalent)
- memcpy DtoH    -> np.asarray(jax.device_get)
- memcpy HtoD    -> jax.device_put
- events/streams -> PJRT async dispatch; Event.wait = block_until_ready
- device info    -> jax.devices() metadata
- mem_bw         -> known HBM numbers per TPU generation

Import of jax is deferred (lazy init) exactly as the reference defers
touching libcuda — opening this component must be free on hosts that
never see a device buffer.
"""

from __future__ import annotations

import threading
from typing import Optional

from ompi_tpu.accelerator import Accelerator, framework
from ompi_tpu.core import output
from ompi_tpu.prof import ledger as _prof

_out = output.stream("accelerator_tpu")

# per-generation public spec numbers: HBM bandwidth GB/s, peak bf16
# TFLOP/s per chip
_HBM_BW = {"v4": 1228.0, "v5e": 819.0, "v5 lite": 819.0, "v5p": 2765.0,
           "v6e": 1640.0}
_PEAK_BF16 = {"v4": 275.0, "v5e": 197.0, "v5 lite": 197.0, "v5p": 459.0,
              "v6e": 918.0}


@framework.register
class TpuAccelerator(Accelerator):
    NAME = "tpu"
    PRIORITY = 50  # above null when usable

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._jax = None
        self._np = None
        self._devices = None

    def open(self) -> bool:
        # stay lazily-openable: only verify jax is importable cheaply.
        # Actual device discovery happens on first use (reference lazy
        # init pattern).
        try:
            import importlib.util

            return importlib.util.find_spec("jax") is not None
        except Exception:
            return False

    def _ensure(self):
        with self._lock:
            if self._jax is None:
                import jax
                import numpy as np

                self._jax = jax
                self._np = np
                self._devices = jax.devices()
                _out.verbose(2, "lazy init: %d device(s): %s",
                             len(self._devices),
                             [str(d) for d in self._devices])
        return self._jax

    # -- module entries ---------------------------------------------------
    def check_addr(self, buf) -> bool:
        # cheap type check first; do NOT force jax import for host arrays
        mod = type(buf).__module__
        if not (mod.startswith("jax") or mod.startswith("jaxlib")):
            return False
        jax = self._ensure()
        return isinstance(buf, jax.Array)

    #: H2D transfers above this size are split into concurrent chunked
    #: device_puts: PJRT dispatches each put asynchronously, and on
    #: tunneled/network-attached devices the streams run in parallel
    #: (measured 0.05 -> 1.7 GB/s on the v5e tunnel; on locally-attached
    #: chips the split is harmless — PCIe/DMA engines pipeline too)
    H2D_CHUNK_BYTES = 4 << 20
    H2D_MAX_CHUNKS = 16
    #: above this the chunked path is skipped: reassembly via
    #: concatenate holds chunks + output live simultaneously (a ~2x
    #: transient), which must not OOM multi-GB staged buffers
    H2D_CHUNK_LIMIT_BYTES = 1 << 30

    #: D2H readback floor: BENCH_r05 measured the 8 MiB-chunk d2h
    #: mitigation at 0.01 GB/s == the raw single-shot path — readback
    #: is latency-bound on tunneled platforms, so small chunks only
    #: multiply the per-read latency (~100x under h2d). The floor is
    #: therefore much HIGHER than H2D_CHUNK_BYTES and the chunk count
    #: much lower: only multi-hundred-MB reads split, into few big
    #: contiguous slices whose copy_to_host_async reads overlap.
    D2H_CHUNK_BYTES = 32 << 20
    D2H_MAX_CHUNKS = 4

    def to_host(self, buf):
        jax = self._ensure()
        np = self._np
        prof = _prof.PROFILER
        t_all = _prof.now() if prof is not None else 0
        nbytes = int(getattr(buf, "nbytes", 0) or 0)
        sharding = getattr(buf, "sharding", None)
        if (nbytes >= 2 * self.D2H_CHUNK_BYTES
                and hasattr(buf, "reshape")
                and (sharding is None
                     or len(sharding.device_set) == 1)):
            out = self._to_host_chunked(buf, nbytes, prof)
            if out is not None:
                if prof is not None:
                    prof.xfer("d2h", out.nbytes, t_all, _prof.now(),
                              site="to_host",
                              chunks=min(self.D2H_MAX_CHUNKS,
                                         nbytes
                                         // self.D2H_CHUNK_BYTES))
                return out
        if prof is None:
            return np.asarray(jax.device_get(buf))
        out = np.asarray(jax.device_get(buf))
        prof.xfer("d2h", out.nbytes, t_all, _prof.now(),
                  site="to_host")
        return out

    def _to_host_chunked(self, buf, nbytes: int, prof):
        """Concurrent chunked readback of one large single-device
        array: block-gather to a flat view first (every read is then
        one contiguous DMA, not a strided gather), start every
        chunk's copy_to_host_async before materializing any, then
        concatenate. None: backend lacks the async-copy API — caller
        falls back to the single-shot path."""
        np = self._np
        flat = buf.reshape(-1)
        nch = min(self.D2H_MAX_CHUNKS,
                  max(2, nbytes // self.D2H_CHUNK_BYTES))
        bounds = [int(flat.size * i // nch) for i in range(nch + 1)]
        parts = [flat[bounds[i]:bounds[i + 1]] for i in range(nch)]
        try:
            for p in parts:
                p.copy_to_host_async()
        except Exception:  # noqa: BLE001 — backend-dependent API
            return None
        hparts = []
        for ci, p in enumerate(parts):
            tc = _prof.now() if prof is not None else 0
            h = np.asarray(p)
            if prof is not None:
                prof.xfer_chunk("d2h", h.nbytes, tc, _prof.now(),
                                chunk=ci, stream=ci)
            hparts.append(h)
        return np.concatenate(hparts).reshape(buf.shape)

    def to_device(self, host_array, like=None):
        jax = self._ensure()
        np = self._np
        prof = _prof.PROFILER
        t_all = _prof.now() if prof is not None else 0
        sharding = like.sharding if (
            like is not None and hasattr(like, "sharding")) else None
        h = np.asarray(host_array)
        if (2 * self.H2D_CHUNK_BYTES <= h.nbytes
                <= self.H2D_CHUNK_LIMIT_BYTES
                and (sharding is None
                     or len(sharding.device_set) == 1)):
            dev = next(iter(sharding.device_set)) if sharding else None
            flat = np.ascontiguousarray(h).reshape(-1)
            nch = min(self.H2D_MAX_CHUNKS,
                      max(2, h.nbytes // self.H2D_CHUNK_BYTES))
            parts = np.array_split(flat, nch)
            if prof is None:
                dparts = [jax.device_put(p, dev)
                          for p in parts]  # concurrent
            else:
                dparts = []
                for ci, p in enumerate(parts):
                    tc = _prof.now()
                    dparts.append(jax.device_put(p, dev))  # concurrent
                    prof.xfer_chunk("h2d", p.nbytes, tc, _prof.now(),
                                    chunk=ci, stream=ci)
            out = jax.numpy.concatenate(dparts).reshape(h.shape)
            if prof is not None:
                out.block_until_ready()
                prof.xfer("h2d", h.nbytes, t_all, _prof.now(),
                          site="to_device", chunks=nch)
            return out
        out = (jax.device_put(h, sharding) if sharding is not None
               else jax.device_put(h))
        if prof is not None:
            out.block_until_ready()
            prof.xfer("h2d", h.nbytes, t_all, _prof.now(),
                      site="to_device", chunks=1)
        return out

    def copy_async(self, src, dst_like=None):
        """Async DtoH on the component's ordered D2H stream.

        Honest events (r2 VERDICT weak #2 fixed): the copy runs on the
        stream worker, ``Event.query()`` reports real readiness (False
        while the transfer is in flight), ``Event.wait()`` returns the
        host array. Ordering across copy_async calls follows stream
        submission order — the contract ob1's outstanding-copy event
        arrays rely on (pml_ob1_accelerator.c:57-89)."""
        jax = self._ensure()
        np = self._np
        if _prof.PROFILER is None:
            return self._d2h_stream().submit(
                lambda: np.asarray(jax.device_get(src)))

        def _profiled_copy():
            # measured on the stream worker so the span covers the
            # actual transfer, not the submit->drain queueing delay
            t0 = _prof.now()
            out = np.asarray(jax.device_get(src))
            p = _prof.PROFILER
            if p is not None:
                p.xfer("d2h", out.nbytes, t0, _prof.now(),
                       site="copy_async", stream="d2h")
            return out

        return self._d2h_stream().submit(_profiled_copy)

    def _d2h_stream(self):
        with self._lock:
            if getattr(self, "_d2h", None) is None:
                self._d2h = self.create_stream()
        return self._d2h

    # -- H2D upload pool (the ingest plane's substrate) -------------------
    def h2d_streams(self, n: int):
        """Ordered H2D upload streams, created lazily and REUSED —
        the ingest engine asks for its ``ingest_streams`` worth every
        upload and must get the same executors back (ring-buffer
        reuse relies on per-stream FIFO order across uploads)."""
        with self._lock:
            pool = getattr(self, "_h2d_pool", None)
            if pool is None:
                pool = self._h2d_pool = []
            while len(pool) < n:
                pool.append(self.create_stream())
            return pool[:n]

    def close_h2d_streams(self) -> None:
        with self._lock:
            pool, self._h2d_pool = getattr(
                self, "_h2d_pool", None) or [], None
        for st in pool:
            st.destroy()

    def put_chunk(self, chunk, device=None):
        """One raw async H2D put of a staged flat view. Deliberately
        unprofiled here: the ingest engine owns the accounting (one
        ``xfer`` per unit at retire time — a put-side span would
        double-count the same bytes).

        The CPU backend may make ``device_put`` ZERO-COPY — the
        returned array aliases the staging view the ingest ring is
        about to repack. When the result shares the host pointer, a
        real device copy is forced so ``block_until_ready`` =="this
        staging slot is reusable" holds on every backend."""
        jax = self._ensure()
        out = (jax.device_put(chunk, device) if device is not None
               else jax.device_put(chunk))
        try:
            alias = (out.unsafe_buffer_pointer()
                     == chunk.__array_interface__["data"][0])
        except Exception:  # noqa: BLE001 — backend-dependent API
            alias = False
        if alias:
            out = jax.numpy.array(out, copy=True)
        return out

    def alloc(self, shape, dtype):
        jax = self._ensure()
        return jax.numpy.zeros(shape, dtype=dtype)

    def num_devices(self) -> int:
        self._ensure()
        return len(self._devices)

    def device_info(self) -> dict:
        self._ensure()
        if not self._devices:
            return {}
        d = self._devices[0]
        return {
            "platform": d.platform,
            "kind": getattr(d, "device_kind", "unknown"),
            "id": d.id,
            "process_index": getattr(d, "process_index", 0),
        }

    def mem_bandwidth(self) -> Optional[float]:
        kind = self.device_info().get("kind", "").lower()
        for key, bw in _HBM_BW.items():
            if key in kind:
                return bw
        return None

    def peak_flops(self) -> Optional[float]:
        """Peak bf16 TFLOP/s of one chip (spec number; MFU denominator)."""
        kind = self.device_info().get("kind", "").lower()
        for key, fl in _PEAK_BF16.items():
            if key in kind:
                return fl
        return None

    def synchronize(self) -> None:
        if self._jax is not None:
            (self._jax.effects_barrier
             if hasattr(self._jax, "effects_barrier") else lambda: None)()

    # -- introspection (accelerator.h get_address_range/buffer_id/...) ---
    def get_address_range(self, buf):
        """(device pointer, nbytes) when PJRT exposes it (the rcache
        lookup key); (None, nbytes) on backends that don't."""
        try:
            ptr = buf.unsafe_buffer_pointer()
        except Exception:  # noqa: BLE001 — backend-dependent API
            ptr = None
        return (ptr, getattr(buf, "nbytes", None))

    def get_buffer_id(self, buf) -> int:
        ptr, _ = self.get_address_range(buf)
        return ptr if ptr is not None else id(buf)

    def get_device_attr(self) -> dict:
        """TPU topology attributes — the PCI-attr analog: mesh coords
        + core index instead of bus ids."""
        self._ensure()
        if not self._devices:
            return {}
        d = self._devices[0]
        return {
            "coords": getattr(d, "coords", None),
            "core_on_chip": getattr(d, "core_on_chip", None),
            "slice_index": getattr(d, "slice_index", 0),
            "process_index": getattr(d, "process_index", 0),
        }

    def device_can_access_peer(self, dev_a: int, dev_b: int) -> bool:
        """Same-slice chips are ICI-connected (the peer-access bit the
        CUDA component reads from the driver)."""
        self._ensure()
        n = len(self._devices)
        if not (0 <= dev_a < n and 0 <= dev_b < n):
            return False
        sa = getattr(self._devices[dev_a], "slice_index", 0)
        sb = getattr(self._devices[dev_b], "slice_index", 0)
        return sa == sb

    def memkind_info(self) -> list:
        return [
            {"name": "hbm", "kind": "device",
             "bandwidth_gbps": self.mem_bandwidth()},
            {"name": "host", "kind": "system"},
        ]

    # -- IPC via shm staging (see accelerator/ipc.py docstring) -----------
    def ipc_export(self, buf):
        from ompi_tpu.accelerator import ipc

        return ipc.export_array(self.to_host(buf))

    def ipc_import(self, handle):
        from ompi_tpu.accelerator import ipc

        jax = self._ensure()
        if _prof.PROFILER is None:
            return jax.device_put(
                self._np.array(ipc.import_array(handle)))
        h = self._np.array(ipc.import_array(handle))
        t0 = _prof.now()
        out = jax.device_put(h)
        out.block_until_ready()
        _prof.PROFILER.xfer("h2d", h.nbytes, t0, _prof.now(),
                            site="ipc_import")
        return out
