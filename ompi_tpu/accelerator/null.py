"""accelerator/null — host-only fallback.

Reference: opal/mca/accelerator/null/accelerator_null_component.c:138 —
check_addr always says "host", memcpys are host memcpy. Always available;
keeps every accelerator-consuming path exercised on CPU-only machines.
"""

from __future__ import annotations

import numpy as np

from ompi_tpu.accelerator import Accelerator, framework


@framework.register
class NullAccelerator(Accelerator):
    NAME = "null"
    PRIORITY = 1  # the fallthrough

    def check_addr(self, buf) -> bool:
        return False

    def to_host(self, buf):
        return np.asarray(buf)

    def to_device(self, host_array, like=None):
        return np.asarray(host_array)

    def alloc(self, shape, dtype):
        return np.empty(shape, dtype=dtype)

    def num_devices(self) -> int:
        return 0

    def get_address_range(self, buf):
        arr = np.asarray(buf)
        base = arr.ctypes.data if arr.flags["C_CONTIGUOUS"] else None
        return (base, arr.nbytes)

    def get_buffer_id(self, buf) -> int:
        base, _ = self.get_address_range(buf)
        return base if base is not None else id(buf)

    # host-plane IPC is genuinely zero-copy on import (shm mapping)
    def ipc_export(self, buf):
        from ompi_tpu.accelerator import ipc

        return ipc.export_array(np.asarray(buf))

    def ipc_import(self, handle):
        from ompi_tpu.accelerator import ipc

        return ipc.import_array(handle)
