"""IPC memory handles — same-host cross-process buffer export.

Reference: accelerator.h's get_ipc_handle/open_ipc_handle (CUDA:
cuIpcGetMemHandle — a device-memory handle another process maps
directly) and the smsc/accelerator single-copy component built on it.

PJRT exposes no device-memory IPC, so the honest equivalent stages
through POSIX shared memory: export snapshots the buffer's bytes into
a /dev/shm segment (one D2H), import maps and uploads (one H2D). Two
copies instead of zero, but the *surface* consumers program against is
identical, and on the host plane (null component) it IS zero-copy on
import when the consumer accepts a read-only view.
"""

from __future__ import annotations

import mmap
import os
import uuid
from dataclasses import dataclass
from typing import Tuple

import numpy as np


@dataclass(frozen=True)
class IpcHandle:
    """Picklable handle a peer process can open (modex-transportable,
    like the reference's 64-byte CUipcMemHandle)."""

    path: str
    shape: Tuple[int, ...]
    dtype: str


def export_array(host: np.ndarray,
                 shm_dir: str = "/dev/shm") -> IpcHandle:
    path = os.path.join(
        shm_dir, f"ompi_tpu_ipc_{os.getpid()}_{uuid.uuid4().hex[:8]}")
    with open(path, "wb") as fh:
        fh.write(np.ascontiguousarray(host).tobytes())
    return IpcHandle(path, tuple(host.shape), str(host.dtype))


def import_array(handle: IpcHandle, writable: bool = False) -> np.ndarray:
    fd = os.open(handle.path, os.O_RDWR if writable else os.O_RDONLY)
    try:
        size = os.fstat(fd).st_size
        mm = mmap.mmap(fd, size,
                       prot=(mmap.PROT_READ | mmap.PROT_WRITE)
                       if writable else mmap.PROT_READ)
    finally:
        os.close(fd)
    arr = np.frombuffer(mm, dtype=np.dtype(handle.dtype))
    return arr.reshape(handle.shape)


def release(handle: IpcHandle) -> None:
    """Exporter-side cleanup (reference: handles are freed when the
    owning allocation is)."""
    try:
        os.unlink(handle.path)
    except OSError:
        pass
