"""Streams and events — ordered async op queues for the accelerator.

Reference: opal/mca/accelerator/accelerator.h:668-711 — create_stream/
sync_stream, create_event/record_event/query_event/sync_event, and the
*_async memcpy/alloc entries that take a stream. The CUDA component
maps these 1:1 onto CUstream/CUevent.

TPU/PJRT redesign: PJRT dispatch is already asynchronous (every jax op
returns immediately; readiness is exposed per-buffer), so a "stream"
here is a host-side ordered executor — a worker thread draining a FIFO
of submitted host↔device ops — which is exactly the ordering contract
CUDA streams give the reference's consumers (pml_ob1_accelerator.c's
outstanding-copy event arrays). Events mark points in that order.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional


class Event:
    """Completion marker (reference: create_event/record/query/sync)."""

    def __init__(self) -> None:
        self._done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None

    def _fire(self, result=None, error=None) -> None:
        self.result = result
        self.error = error
        self._done.set()

    def query(self) -> bool:
        """Nonblocking readiness probe (query_event)."""
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None):
        """Block until recorded work completes (sync_event)."""
        if not self._done.wait(timeout):
            raise TimeoutError("event did not complete")
        if self.error is not None:
            raise self.error
        return self.result


def completed_event(result=None) -> Event:
    ev = Event()
    ev._fire(result)
    return ev


class Stream:
    """Ordered async executor (reference: create_stream/sync_stream)."""

    def __init__(self, name: str = "accel-stream") -> None:
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True)
        self._alive = True
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, ev = item
            try:
                ev._fire(fn())
            except BaseException as exc:  # noqa: BLE001 — surfaced at wait
                ev._fire(error=exc)

    def submit(self, fn: Callable[[], Any]) -> Event:
        """Enqueue fn; returns the Event completing when it ran (the
        *_async entries build on this)."""
        if not self._alive:
            raise RuntimeError("stream destroyed")
        ev = Event()
        self._q.put((fn, ev))
        return ev

    def record_event(self) -> Event:
        """Marker event: fires when everything submitted before it has
        executed (record_event semantics)."""
        return self.submit(lambda: None)

    def synchronize(self) -> None:
        """Drain: block until all prior submissions ran (sync_stream)."""
        self.record_event().wait()

    def destroy(self) -> None:
        if self._alive:
            self._alive = False
            self._q.put(None)
            self._thread.join(timeout=10)
