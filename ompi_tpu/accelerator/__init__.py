"""Accelerator framework — device buffer integration.

Reference: opal/mca/accelerator/ (accelerator.h:668-711, the 30-entry
module: check_addr, streams/events, memcpy sync+async, alloc/free, IPC,
device info...). Exactly one active component + null fallback
(accelerator.h:24-27); selected during core init (opal_init.c:202-206).

TPU-native redesign: PJRT (via jax) is the device runtime. check_addr
classifies jax.Array vs host memory; memcpy maps to device_put /
device_get; "streams" map to the PJRT async dispatch + block_until_ready
events; IPC handles are out of scope for single-controller TPU (the device
plane shares buffers through the mesh instead — see ompi_tpu.parallel).
"""

from __future__ import annotations

from typing import Optional

from ompi_tpu.core import registry

framework = registry.framework("accelerator")

_current = None


class Accelerator(registry.Component):
    """The module interface (subset of the reference's 30 entries that
    has meaning on this runtime; the rest raise NotImplementedError to
    make capability probing explicit)."""

    def check_addr(self, buf) -> bool:
        """True if buf is device-resident (reference: check_addr)."""
        return False

    def to_host(self, buf):
        """Device -> host numpy copy (memcpy DtoH)."""
        raise NotImplementedError

    def to_device(self, host_array, like=None):
        """Host -> device copy (memcpy HtoD)."""
        raise NotImplementedError

    def copy_async(self, src, dst_like=None):
        """Async DtoH: returns an Event completing when readable."""
        raise NotImplementedError

    def alloc(self, shape, dtype):
        raise NotImplementedError

    def num_devices(self) -> int:
        return 0

    def device_info(self) -> dict:
        return {}

    def mem_bandwidth(self) -> Optional[float]:
        """Device memory bandwidth GB/s if known (reference: mem_bw)."""
        return None

    def synchronize(self) -> None:
        pass


def current() -> Accelerator:
    """The selected accelerator component (null always qualifies)."""
    global _current
    if _current is None:
        from ompi_tpu.accelerator import null, tpu  # register components

        _current = framework.select_one()
    return _current


def reset_for_testing() -> None:
    global _current
    _current = None
    framework.close_components()
