"""Accelerator framework — device buffer integration.

Reference: opal/mca/accelerator/ (accelerator.h:668-711, the 30-entry
module: check_addr, streams/events, memcpy sync+async, alloc/free, IPC,
device info...). Exactly one active component + null fallback
(accelerator.h:24-27); selected during core init (opal_init.c:202-206).

TPU-native redesign: PJRT (via jax) is the device runtime. check_addr
classifies jax.Array vs host memory; memcpy maps to device_put /
device_get; "streams" map to the PJRT async dispatch + block_until_ready
events; IPC handles are out of scope for single-controller TPU (the device
plane shares buffers through the mesh instead — see ompi_tpu.parallel).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.core import registry

framework = registry.framework("accelerator")

_current = None


class Accelerator(registry.Component):
    """The module interface — the reference's 30 entries
    (accelerator.h:668-711) mapped onto a PJRT-shaped runtime. Entries
    without a device-native mechanism are implemented with their
    honest host-plane equivalent (IPC = shm staging, host_register =
    bookkeeping) rather than left unimplemented, so every consumer
    path stays exercised.

    Entry map (reference name -> method):
      check_addr                  -> check_addr
      create/sync stream          -> create_stream / Stream.synchronize
      create/record/query/sync ev -> Stream.record_event / Event.*
      memcpy, memmove             -> memcpy / memmove (kind-aware)
      memcpy_async                -> memcpy_async (stream-ordered)
      mem_alloc/release (+stream) -> mem_alloc / mem_release
      get_address_range           -> get_address_range
      IPC mem handles             -> ipc_export / ipc_import
      host_register/unregister    -> host_register / host_unregister
      get_device / PCI attr       -> device_info / get_device_attr
      device_can_access_peer      -> device_can_access_peer
      get_buffer_id               -> get_buffer_id
      num_devices / mem_bw        -> num_devices / mem_bandwidth
      get_memkind                 -> memkind_info
    """

    def check_addr(self, buf) -> bool:
        """True if buf is device-resident (reference: check_addr)."""
        return False

    # module-level helper lives below (is_device_buffer) so every
    # device-dispatch layer shares ONE predicate

    def to_host(self, buf):
        """Device -> host numpy copy (memcpy DtoH)."""
        raise NotImplementedError

    def to_device(self, host_array, like=None):
        """Host -> device copy (memcpy HtoD)."""
        raise NotImplementedError

    def copy_async(self, src, dst_like=None):
        """Async DtoH: returns an Event completing when readable.
        Default: the synchronous memcpy wrapped in a completed event;
        device components override with genuinely-async dispatch."""
        from ompi_tpu.accelerator.stream import completed_event

        return completed_event(self.memcpy(src, "dtoh"))

    def alloc(self, shape, dtype):
        raise NotImplementedError

    def num_devices(self) -> int:
        return 0

    def device_info(self) -> dict:
        return {}

    def mem_bandwidth(self) -> Optional[float]:
        """Device memory bandwidth GB/s if known (reference: mem_bw)."""
        return None

    def synchronize(self) -> None:
        pass

    # -- streams / events (reference: stream+event entries) --------------
    def create_stream(self):
        from ompi_tpu.accelerator.stream import Stream

        return Stream(f"accel-{self.NAME}-stream")

    # -- kind-aware copies -----------------------------------------------
    def memcpy(self, src, direction: str = "auto"):
        """Synchronous copy; direction 'dtoh'|'htod'|'auto'."""
        if direction == "dtoh" or (direction == "auto"
                                   and self.check_addr(src)):
            return self.to_host(src)
        return self.to_device(src)

    def memmove(self, src, direction: str = "auto"):
        """The reference's memmove entry: same data movement — device
        buffers never alias host buffers here, so move == copy."""
        return self.memcpy(src, direction)

    def memcpy_async(self, src, stream=None, direction: str = "auto"):
        """Stream-ordered copy; returns an Event with the result."""
        from ompi_tpu.accelerator import stream as stream_mod

        if stream is None:
            return stream_mod.completed_event(
                self.memcpy(src, direction))
        return stream.submit(lambda: self.memcpy(src, direction))

    # -- allocation -------------------------------------------------------
    def mem_alloc(self, shape, dtype, stream=None):
        """(Optionally stream-ordered) allocation."""
        if stream is None:
            return self.alloc(shape, dtype)
        return stream.submit(lambda: self.alloc(shape, dtype))

    def mem_release(self, buf, stream=None) -> None:
        """Release a device allocation (stream-ordered when given)."""
        def rel():
            delete = getattr(buf, "delete", None)
            if delete is not None:
                try:
                    delete()
                except Exception:  # noqa: BLE001 — already deleted
                    pass
        if stream is None:
            rel()
        else:
            stream.submit(rel)

    # -- introspection -----------------------------------------------------
    def get_address_range(self, buf):
        """(base_address_or_None, nbytes) of the allocation backing
        buf (reference: get_address_range for rcache lookups)."""
        nbytes = getattr(buf, "nbytes", None)
        return (None, nbytes)

    def get_buffer_id(self, buf) -> int:
        """Stable id for registration caching (reference:
        get_buffer_id; CUDA uses the allocation's unique id)."""
        return id(buf)

    def get_device_attr(self) -> dict:
        """Topology attributes — the PCI-attr analog (TPUs expose mesh
        coordinates instead of PCI addresses)."""
        return {}

    def device_can_access_peer(self, dev_a: int, dev_b: int) -> bool:
        return False

    def memkind_info(self) -> list:
        """Memory kinds this component serves (reference: memkind info
        keys, ompi/info/info_memkind.*)."""
        return [{"name": "host", "kind": "system"}]

    def memkinds(self) -> list:
        """MPI-4.1 ``mpi_memory_alloc_kinds`` strings this component
        contributes (info_memkind.c): the component name as the kind
        plus one ``name:region`` restrictor per device memkind row —
        the cuda/cuda:device pattern; tpu yields ['tpu', 'tpu:hbm']."""
        out = []
        for row in self.memkind_info():
            if row.get("kind") == "device":
                if self.NAME not in out:
                    out.append(self.NAME)
                out.append(f"{self.NAME}:{row['name']}")
        return out

    # -- host registration (reference: host_register/unregister) ---------
    def host_register(self, arr) -> int:
        """Record a host region as transfer-hot. PJRT manages pinning
        internally; the bookkeeping keeps the consumer surface (and
        lets a future backend act on it). Returns a monotonic handle;
        the registry holds the array itself so the region stays alive
        (and handles can never alias a freed registration)."""
        regs = getattr(self, "_host_regs", None)
        if regs is None:
            regs = self._host_regs = {}
            self._host_reg_seq = 0
        self._host_reg_seq += 1
        handle = self._host_reg_seq
        regs[handle] = arr
        return handle

    def host_unregister(self, handle: int) -> None:
        getattr(self, "_host_regs", {}).pop(handle, None)

    # -- IPC (reference: get/open ipc mem handles) ------------------------
    def ipc_export(self, buf):
        """Export a buffer for a same-host peer process. PJRT has no
        device-memory IPC, so the handle stages through /dev/shm (the
        role smsc/accelerator plays with CUDA IPC in the reference);
        the device plane shares buffers through the mesh instead."""
        raise NotImplementedError

    def ipc_import(self, handle):
        raise NotImplementedError


def current() -> Accelerator:
    """The selected accelerator component (null always qualifies)."""
    global _current
    if _current is None:
        from ompi_tpu.accelerator import null, tpu  # register components

        _current = framework.select_one()
    return _current


def reset_for_testing() -> None:
    global _current
    _current = None
    framework.close_components()


def is_device_buffer(buf) -> bool:
    """THE device-buffer predicate every dispatch layer shares
    (reference: accelerator check_addr on each API entry,
    coll_accelerator_allreduce.c check_buf). Cheap host-type
    early-outs keep the hot host path free of accelerator calls."""
    if buf is None or isinstance(
            buf, (np.ndarray, bytes, bytearray, memoryview, tuple,
                  str)):
        return False
    return current().check_addr(buf)
