"""Backward-overlap gradient sync — the DDP/Horovod hook pattern over
MPI-4 partitioned collectives.

PyTorch DDP and Horovod register per-parameter backward hooks that
feed gradients into buckets and launch a bucket's allreduce the
moment it fills, overlapping communication with the rest of the
backward pass. :class:`GradientSync` is that pattern expressed through
the standard MPI-4 partitioned API instead of ad-hoc hooks: the
gradient pytree is bound once to a ``Comm.Pallreduce_init`` request
(one partition per leaf), each training step opens a cycle with
``start()``, the backward pushes leaves in ANY order via ``push``,
and every dtype bucket's single compiled psum dispatches as soon as
its last member leaf arrives; ``finish()`` drains the tail and
returns the synced pytree.

Leaves are addressed either by flatten index or by the jax key-path
string of the template (``keystr`` form, e.g. ``"['layers'][0]['w']"``)
— the string form is what a per-parameter hook naturally has in hand.
"""

from __future__ import annotations

from ompi_tpu import op as op_mod


class GradientSync:
    """Bind a gradient-pytree template once; per step: ``start()``,
    ``push(key, grad)`` per leaf as the backward produces it,
    ``finish()`` -> synced pytree. Push order is free — buckets flush
    themselves (pvar ``part_overlap_flushes`` counts flushes that
    beat the final push)."""

    def __init__(self, comm, template, op=op_mod.SUM,
                 deterministic=None) -> None:
        import jax

        paths, _ = jax.tree_util.tree_flatten_with_path(template)
        self._index = {jax.tree_util.keystr(p): i
                       for i, (p, _leaf) in enumerate(paths)}
        self.n_leaves = len(paths)
        self._req = comm.Pallreduce_init(template, op,
                                         deterministic=deterministic)

    def index_of(self, key) -> int:
        """Flatten index for a key-path string (or pass-through int)."""
        return key if isinstance(key, int) else self._index[key]

    def start(self) -> None:
        """Open a sync cycle (call once per training step, before the
        backward starts producing gradients)."""
        self._req.start()

    def push(self, key, grad=None) -> None:
        """Mark leaf ``key`` ready, optionally rebinding this step's
        fresh gradient value (same shape/dtype as the template leaf).
        The leaf's bucket dispatches when its last member arrives."""
        self._req.Pready(self.index_of(key), grad)

    def finish(self):
        """Drain remaining buckets and return the synced pytree."""
        self._req.wait()
        return self._req.array

    @property
    def request(self):
        """The underlying partitioned request (for Startall mixing)."""
        return self._req

    def free(self) -> None:
        self._req.free()


class ZeroGradientSync(GradientSync):
    """The same push-as-produced surface over the zero/ sharded cycle:
    bound to ``Comm.Preduce_scatter_init`` instead of
    ``Pallreduce_init``, so ``finish()`` returns a
    :class:`~ompi_tpu.zero.layout.ShardedState` — this rank's 1/n
    gradient shards, ready for a sharded optimizer update (feed to
    ``Comm.Allgather_multi`` after the update to rebuild params).
    Buckets that dispatch before the final push count in the
    ``zero_overlap_flushes`` pvar."""

    def __init__(self, comm, template, op=op_mod.SUM,
                 deterministic=None) -> None:
        import jax

        paths, _ = jax.tree_util.tree_flatten_with_path(template)
        self._index = {jax.tree_util.keystr(p): i
                       for i, (p, _leaf) in enumerate(paths)}
        self.n_leaves = len(paths)
        self._req = comm.Preduce_scatter_init(
            template, op, deterministic=deterministic)
