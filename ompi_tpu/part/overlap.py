"""Backward-overlap gradient sync — the DDP/Horovod hook pattern over
MPI-4 partitioned collectives.

PyTorch DDP and Horovod register per-parameter backward hooks that
feed gradients into buckets and launch a bucket's allreduce the
moment it fills, overlapping communication with the rest of the
backward pass. :class:`GradientSync` is that pattern expressed through
the standard MPI-4 partitioned API instead of ad-hoc hooks: the
gradient pytree is bound once to a ``Comm.Pallreduce_init`` request
(one partition per leaf), each training step opens a cycle with
``start()``, the backward pushes leaves in ANY order via ``push``,
and every dtype bucket's single compiled psum dispatches as soon as
its last member leaf arrives; ``finish()`` drains the tail and
returns the synced pytree.

Leaves are addressed either by flatten index or by the jax key-path
string of the template (``keystr`` form, e.g. ``"['layers'][0]['w']"``)
— the string form is what a per-parameter hook naturally has in hand.
"""

from __future__ import annotations

from ompi_tpu import errors
from ompi_tpu import op as op_mod


class GradientSync:
    """Bind a gradient-pytree template once; per step: ``start()``,
    ``push(key, grad)`` per leaf as the backward produces it,
    ``finish()`` -> synced pytree. Push order is free — buckets flush
    themselves (pvar ``part_overlap_flushes`` counts flushes that
    beat the final push)."""

    def __init__(self, comm, template, op=op_mod.SUM,
                 deterministic=None) -> None:
        import jax

        paths, _ = jax.tree_util.tree_flatten_with_path(template)
        self._index = {jax.tree_util.keystr(p): i
                       for i, (p, _leaf) in enumerate(paths)}
        self.n_leaves = len(paths)
        self._req = comm.Pallreduce_init(template, op,
                                         deterministic=deterministic)

    def index_of(self, key) -> int:
        """Flatten index for a key-path string (or pass-through int)."""
        return key if isinstance(key, int) else self._index[key]

    def start(self) -> None:
        """Open a sync cycle (call once per training step, before the
        backward starts producing gradients)."""
        self._req.start()

    def push(self, key, grad=None) -> None:
        """Mark leaf ``key`` ready, optionally rebinding this step's
        fresh gradient value (same shape/dtype as the template leaf).
        The leaf's bucket dispatches when its last member arrives."""
        self._req.Pready(self.index_of(key), grad)

    def finish(self):
        """Drain remaining buckets and return the synced pytree."""
        self._req.wait()
        return self._req.array

    @property
    def request(self):
        """The underlying partitioned request (for Startall mixing)."""
        return self._req

    def free(self) -> None:
        self._req.free()


class LayerPrefetcher:
    """Run-ahead scheduler for per-layer gathers — the ZeRO stage-3
    parameter stream's timing brain.

    The zero-3 engine gathers one layer's parameters at a time and
    frees them after use; hiding the gather latency requires the NEXT
    layer's gather to already be in flight when the consumer arrives
    (the FSDP prefetch rule, expressed over this repo's persistent
    ``Allgather_multi_init`` requests: ``start()`` here plays the role
    ``Pready`` plays on the send side — it fires the layer-boundary
    event that releases the next gather). This class only decides
    WHEN: the ``start(layer)`` callback owns the how.

    A pass opens with :meth:`begin` (fires the first ``depth``
    gathers); every consumer arrival calls :meth:`advance`, which
    tops the in-flight window back up to ``depth`` layers beyond the
    consumer's position. Layers may be visited in any order of the
    declared pass order — the window is positional, so a reversed
    order models the backward pass. Hit/miss accounting (did the
    scheduler beat the consumer?) stays with the caller, which is the
    only side that knows whether a gather had actually completed."""

    def __init__(self, start, depth: int = 1) -> None:
        if depth < 0:
            raise errors.MPIError(
                errors.ERR_ARG, f"LayerPrefetcher: depth {depth} < 0")
        self._start = start
        self._depth = int(depth)
        self._order = []
        self._pos = {}
        self._next = 0

    def begin(self, order) -> None:
        """Open a pass over ``order`` (layer ids, consumer order);
        fires the first ``depth`` gathers immediately so layer 0 is
        already in flight before the consumer reaches it."""
        self._order = list(order)
        self._pos = {g: i for i, g in enumerate(self._order)}
        self._next = 0
        self._fill(self._depth - 1)

    def advance(self, layer) -> None:
        """Consumer reached ``layer``: extend the in-flight window to
        ``depth`` layers past it. Unknown layers (fetched outside the
        declared order) are the caller's miss to account — no-op
        here."""
        pos = self._pos.get(layer)
        if pos is not None:
            self._fill(pos + self._depth)

    def _fill(self, upto: int) -> None:
        while self._next <= upto and self._next < len(self._order):
            g = self._order[self._next]
            self._next += 1
            self._start(g)

    @property
    def issued(self) -> int:
        """Gathers fired so far this pass."""
        return self._next

    def reset(self) -> None:
        """Abandon the pass (no further starts until begin())."""
        self._order = []
        self._pos = {}
        self._next = 0


class ZeroGradientSync(GradientSync):
    """The same push-as-produced surface over the zero/ sharded cycle:
    bound to ``Comm.Preduce_scatter_init`` instead of
    ``Pallreduce_init``, so ``finish()`` returns a
    :class:`~ompi_tpu.zero.layout.ShardedState` — this rank's 1/n
    gradient shards, ready for a sharded optimizer update (feed to
    ``Comm.Allgather_multi`` after the update to rebuild params).
    Buckets that dispatch before the final push count in the
    ``zero_overlap_flushes`` pvar."""

    def __init__(self, comm, template, op=op_mod.SUM,
                 deterministic=None) -> None:
        import jax

        paths, _ = jax.tree_util.tree_flatten_with_path(template)
        self._index = {jax.tree_util.keystr(p): i
                       for i, (p, _leaf) in enumerate(paths)}
        self.n_leaves = len(paths)
        self._req = comm.Preduce_scatter_init(
            template, op, deterministic=deterministic)
