"""Host-path partitioned point-to-point (MPI-4 Psend/Precv).

Reference: ompi/mca/part/part.h:124-185 + part/persist (2,261 LoC): a
partitioned send is a persistent request whose buffer is split into P
partitions the application marks ready one by one (``Pready``); each
ready partition moves independently, so fine-grained producers (e.g.
per-microbatch pipeline stages — SURVEY.md §2.10 maps this machinery to
pipeline parallelism; models/pipeline.py exposes the stage-handoff
helpers) overlap communication with computation.

Transport: each partition rides the regular PML as an independent
message on a framework-internal (negative) tag that encodes
(user tag, pairing epoch, partition index). Pairing follows MPI
matching rules: Psend_init/Precv_init calls on the same (comm, peer,
tag) pair up in call order (the per-(peer,tag) epoch counter on both
sides — ``comm._part_epochs``, dropped in ``Communicator.free`` —
tracks this without any wire traffic).

Erroneous-call policy (MPI 4.0 §4.2): ``Pready`` on an inactive
request or an already-ready partition, ``Parrived`` on a never-started
request, and ``start()`` while the previous epoch is still in flight
all raise :class:`~ompi_tpu.errors.MPIError` — silently re-starting
would orphan the in-flight partitions' wire tags and desync the
pairing epochs on the two sides.

Limits (documented, checked): partitions <= 4096, user tag < 1024,
256 in-flight pairings per (peer, tag) — sized so every encoded tag
fits the int32 wire field (|PART_BASE| + (1023<<8|255)*4096 + 4095
< 2^31).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ompi_tpu import errors, pml
from ompi_tpu.core import pvar
from ompi_tpu.part import partial as _partial
from ompi_tpu.pml import request as rq
from ompi_tpu.telemetry import flight as _flight
from ompi_tpu.trace import recorder as _trace

_PART_BASE = -(1 << 24)  # below any other framework-internal tag
MAX_PARTITIONS = 4096
MAX_TAG = 1024  # keeps the encoded tag within int32 (see module doc)


def _part_tag(user_tag: int, epoch: int, idx: int) -> int:
    if not 0 <= user_tag < MAX_TAG:
        raise errors.MPIError(
            errors.ERR_TAG,
            f"partitioned tag must be in [0,{MAX_TAG})")
    return _PART_BASE - (((user_tag << 8) | (epoch & 0xFF))
                         * MAX_PARTITIONS + idx)


def _epoch(comm, peer: int, tag: int, side: str) -> int:
    # dedicated dict (not comm.attrs): epochs are transport pairing
    # state, not user attributes — attribute copy callbacks on dup
    # must never clone them onto a comm with a fresh cid
    table = getattr(comm, "_part_epochs", None)
    if table is None:
        table = comm._part_epochs = {}
    key = (side, peer, tag)
    n = table.get(key, 0)
    table[key] = n + 1
    return n


class _PartitionedBase(rq.Request):
    def __init__(self, comm, buf, partitions: int, peer: int,
                 tag: int) -> None:
        super().__init__()
        if partitions < 1 or partitions > MAX_PARTITIONS:
            raise errors.MPIError(
                errors.ERR_COUNT,
                f"partitions must be in [1,{MAX_PARTITIONS}]")
        arr = np.asarray(buf)
        if not arr.flags.c_contiguous:
            # reshape(-1) would copy: partition views must alias the
            # user's buffer (recv data lands in them; send reads them
            # at Pready time) — same contract the Convertor enforces
            raise errors.MPIError(
                errors.ERR_BUFFER,
                "partitioned buffers must be C-contiguous")
        flat = arr.reshape(-1)
        if flat.size % partitions:
            raise errors.MPIError(
                errors.ERR_COUNT,
                f"buffer of {flat.size} elements not divisible into "
                f"{partitions} partitions")
        self.persistent = True
        self.comm = comm
        self.peer = peer
        self.tag = tag
        self.partitions = partitions
        self._chunks = np.split(flat, partitions)  # views
        self._started = False  # ever started (Parrived precondition)
        self._fl_tok = None  # flight-recorder token of the open epoch
        self.completed = True  # inactive until start()

    @property
    def completed(self) -> bool:
        """Live completion view: the plural helpers (rq.wait_all/
        test_any/...) poll ``.completed`` while spinning progress, so
        it must evaluate the epoch, not echo a flag only test()
        flips — same contract as _PersistentRequest/DeviceRequest."""
        if not self._done:
            self._done = self._epoch_done()
            if self._done and self._fl_tok is not None:
                tok, self._fl_tok = self._fl_tok, None
                fl = _flight.FLIGHT
                if fl is not None:
                    fl.exit(tok)
        return self._done

    @completed.setter
    def completed(self, v: bool) -> None:  # base __init__ writes here
        self._done = bool(v)

    @property
    def active(self) -> bool:
        """An epoch is open and not yet known complete. Inactive
        requests read as complete (MPI), so active == not completed;
        ``start_all`` uses this to reject erroneous re-starts."""
        return not self.completed

    def _check_start(self) -> None:
        if self._started and not self.completed:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                "partitioned start: previous epoch still active — "
                "wait/test the request to completion before "
                "restarting (MPI 4.0 §4.2: starting an active "
                "request is erroneous)")

    def _chunk_reqs(self) -> List[Optional[rq.Request]]:
        return [None] * self.partitions


class PartitionedSendRequest(_PartitionedBase):
    """MPI_Psend_init handle: Start() activates an epoch, Pready(i)
    launches partition i, completion = every partition sent."""

    def start(self) -> None:
        self._check_start()
        self._ep = _epoch(self.comm, self.peer, self.tag, "send")
        self._reqs = self._chunk_reqs()
        self._ready = [False] * self.partitions
        self._started = True
        self.completed = False
        pvar.record("part_send_start")
        fl = _flight.FLIGHT
        if fl is not None:
            self._fl_tok = fl.enter(
                "psend_epoch", getattr(self.comm, "cid", -1),
                sum(int(c.nbytes) for c in self._chunks))

    def Pready(self, idx: int) -> None:
        if self.completed:
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Pready({idx}): request inactive — call start() "
                "before marking partitions ready")
        if self._ready[idx]:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"Pready({idx}): partition already marked ready "
                "this epoch (double-Pready is erroneous)")
        self._ready[idx] = True
        pvar.record("part_pready")
        chunk = self._chunks[idx]
        rec = _trace.RECORDER
        if rec is None:
            self._reqs[idx] = pml.current().isend(
                self.comm, chunk, chunk.size, None, self.peer,
                _part_tag(self.tag, self._ep, idx))
            return
        t0 = _trace.now()
        self._reqs[idx] = pml.current().isend(
            self.comm, chunk, chunk.size, None, self.peer,
            _part_tag(self.tag, self._ep, idx))
        rec.record("psend_pready", "part", t0, _trace.now(),
                   {"partition": idx, "peer": self.peer,
                    "tag": self.tag, "nbytes": int(chunk.nbytes)})

    def Pready_range(self, lo: int, hi: int) -> None:
        for i in range(lo, hi + 1):
            self.Pready(i)

    def Pready_list(self, idxs) -> None:
        for i in idxs:
            self.Pready(i)

    def _epoch_done(self) -> bool:
        return all(self._ready) and all(r.test() for r in self._reqs)

    def test(self) -> bool:
        return self.completed

    def wait(self, timeout=None):
        from ompi_tpu.core import progress

        progress.wait_until(self.test)
        return self.status


class PartitionedRecvRequest(_PartitionedBase,
                             _partial.PartialAvailability):
    """MPI_Precv_init handle: Start() posts all partition receives,
    Parrived(i) / Parrived_range / Parrived_list poll (the probe
    family is the shared :class:`~ompi_tpu.part.partial.
    PartialAvailability` surface the ingest plane reuses), completion
    = all arrived."""

    _PARRIVED_PVAR = "part_parrived"

    def start(self) -> None:
        self._check_start()
        ep = _epoch(self.comm, self.peer, self.tag, "recv")
        p = pml.current()
        rec = _trace.RECORDER
        t0 = _trace.now() if rec is not None else 0
        self._reqs = [
            p.irecv(self.comm, self._chunks[i], self._chunks[i].size,
                    None, self.peer, _part_tag(self.tag, ep, i))
            for i in range(self.partitions)]
        if rec is not None:
            rec.record("precv_start", "part", t0, _trace.now(),
                       {"partitions": self.partitions,
                        "peer": self.peer, "tag": self.tag})
        self._started = True
        self.completed = False
        pvar.record("part_recv_start")
        fl = _flight.FLIGHT
        if fl is not None:
            self._fl_tok = fl.enter(
                "precv_epoch", getattr(self.comm, "cid", -1),
                sum(int(c.nbytes) for c in self._chunks))

    def _partial_started(self) -> bool:
        return self._started

    def _partial_probe(self, idx: int) -> bool:
        if not 0 <= idx < self.partitions:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"Parrived({idx}): partition index out of "
                f"[0,{self.partitions})")
        return self._reqs[idx].test()

    def _epoch_done(self) -> bool:
        return all(r.test() for r in self._reqs)

    def test(self) -> bool:
        return self.completed

    def wait(self, timeout=None):
        from ompi_tpu.core import progress

        progress.wait_until(self.test)
        return self.status


def _Psend_init(self, buf, partitions: int, dest: int,
                tag: int = 0) -> PartitionedSendRequest:
    return PartitionedSendRequest(self, buf, partitions, dest, tag)


def _Precv_init(self, buf, partitions: int, source: int,
                tag: int = 0) -> PartitionedRecvRequest:
    return PartitionedRecvRequest(self, buf, partitions, source, tag)


def attach() -> None:
    from ompi_tpu.comm import Communicator

    Communicator.Psend_init = _Psend_init
    Communicator.Precv_init = _Precv_init


attach()
