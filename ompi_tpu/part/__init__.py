"""ompi_tpu/part — the MPI-4 partitioned communication subsystem.

Reference: ompi/mca/part (part.h:124-185) and the part/persist
component: partitioned operations are persistent requests whose
payload is split into partitions the application hands over one by
one, so communication of the early pieces overlaps production of the
late ones. Three layers live under this name:

- :mod:`ompi_tpu.part.host` — partitioned point-to-point
  (``Comm.Psend_init`` / ``Precv_init`` returning requests with
  ``Pready`` / ``Pready_range`` / ``Pready_list`` / ``Parrived``),
  riding the regular PML one message per partition. Attaches the
  Communicator methods at import.
- the device-path payoff, ``Comm.Pallreduce_init`` (coll/xla's
  ``PartitionedAllreduceRequest``): a partitioned FUSED allreduce
  whose partitions are gradient-pytree leaves — each dtype bucket's
  single compiled psum launches the moment its last member leaf is
  marked ready, overlapping bucket communication with backward-pass
  gradient production (bound in :mod:`ompi_tpu.mpi`).
- :mod:`ompi_tpu.part.overlap` — :class:`GradientSync`, the
  DDP/Horovod backward-hook-style wrapper over ``Pallreduce_init``
  for training loops, and :class:`ZeroGradientSync`, the same surface
  over ``Preduce_scatter_init`` yielding sharded gradients for the
  zero/ optimizer cycle.
- :mod:`ompi_tpu.part.partial` — :class:`PartialAvailability`, the
  shared ``Parrived``/``Parrived_range``/``Parrived_list`` probe
  mixin (MPI 4.0 §4.2 erroneous-call policy included). The recv
  request implements it for wire partitions; the streaming ingest
  plane (:mod:`ompi_tpu.ingest`) implements it for host->device
  upload units, so "start on the first ready shards" reads the same
  both places.

``ompi_tpu.pml.part`` remains as a compat shim over ``part.host``.
"""

from ompi_tpu.part import host  # noqa: F401  (attaches at import)
from ompi_tpu.part.host import (  # noqa: F401
    MAX_PARTITIONS, MAX_TAG, PartitionedRecvRequest,
    PartitionedSendRequest,
)
from ompi_tpu.part.overlap import (  # noqa: F401
    GradientSync, LayerPrefetcher, ZeroGradientSync,
)
from ompi_tpu.part.partial import PartialAvailability  # noqa: F401
