"""Shared partial-availability semantics (the MPI-4 Parrived family).

Two subsystems expose "the payload arrives in independently-completing
pieces": the partitioned-recv request (:mod:`ompi_tpu.part.host`,
partitions arriving off the wire) and the streaming-ingest upload
request (:mod:`ompi_tpu.ingest.engine`, pytree units landing on the
device). Both offer the same MPI-4 probe surface, so it lives here
once:

- ``Parrived(i)`` — nonblocking: has piece ``i`` completed?
- ``Parrived_range(lo, hi)`` / ``Parrived_list(idxs)`` — grouped
  probes, mirroring ``Pready_range`` / ``Pready_list`` on the send
  side (MPI 4.0 §4.2.4).
- Probing a request that was never started is erroneous and raises
  :class:`~ompi_tpu.errors.MPIError` (MPI 4.0 §4.2: ``MPI_Parrived``
  on an inactive never-started request).

Concrete classes implement three hooks — ``_partial_started()``
(ever activated?), ``_partial_probe(idx)`` (one nonblocking
completion poll; index validation lives here too), and the class
attribute ``_PARRIVED_PVAR`` naming the counter a successful probe
records (``part_parrived`` on the wire path, ``ingest_parrived`` on
the upload path) — plus the live ``completed`` property every request
class in this codebase already carries.
"""

from __future__ import annotations

from typing import Iterable, Optional

from ompi_tpu import errors
from ompi_tpu.core import pvar


class PartialAvailability:
    """Mixin: the MPI-4 ``Parrived`` probe family over pluggable
    completion hooks."""

    #: counter recorded on each successful probe (None: record nothing)
    _PARRIVED_PVAR: Optional[str] = None

    # -- hooks the concrete request implements ---------------------------
    def _partial_started(self) -> bool:
        raise NotImplementedError

    def _partial_probe(self, idx: int) -> bool:
        raise NotImplementedError

    # -- the shared MPI-4 surface -----------------------------------------
    def Parrived(self, idx: int) -> bool:
        if not self._partial_started():
            raise errors.MPIError(
                errors.ERR_REQUEST,
                f"Parrived({idx}): request never started — nothing "
                "is in flight to probe (MPI 4.0 §4.2)")
        # no completed-request fast path: an out-of-range index is
        # erroneous even after everything arrived, and the probe
        # counter must reflect every answered probe
        ok = self._partial_probe(idx)
        name = self._PARRIVED_PVAR
        if ok and name is not None:
            pvar.record(name)
        return ok

    def Parrived_range(self, lo: int, hi: int) -> bool:
        """True when every piece in [lo, hi] (inclusive, like
        ``Pready_range``) has completed."""
        return all(self.Parrived(i) for i in range(lo, hi + 1))

    def Parrived_list(self, idxs: Iterable[int]) -> bool:
        return all(self.Parrived(i) for i in idxs)
