"""zero/optimizer — sharded-state optimizer over the zero collectives.

The ZeRO training cycle (Rajbhandari et al., SC'20; stage numbers are
theirs):

- **stage 1** (P\\ :sub:`os`): optimizer state (momentum here) is
  sharded 1/n per rank; gradients are still fully allreduced, each
  rank updates only its parameter shard, and an allgather rebuilds
  the replicated parameters.
- **stage 2** (P\\ :sub:`os+g`): gradients are *reduce_scattered* —
  a rank never materializes the full reduced gradient, only its
  shard — then the same shard-update + allgather-params tail.

:class:`ZeroOptimizer` is SGD(+momentum) over that cycle, built
entirely on the comm's fused zero collectives
(``Reduce_scatter_multi`` / ``Allgather_multi`` — one compiled launch
per dtype bucket) with an optional backward-overlap mode that feeds
gradients leaf-by-leaf through ``Preduce_scatter_init`` (a bucket's
reduce_scatter dispatches the moment its last leaf is pushed;
``zero_overlap_flushes`` counts the buckets that beat the final
push). Bit-identity: under ``deterministic='linear'`` the whole cycle
reproduces the per-buffer allreduce + local SGD step bitwise.
"""

from __future__ import annotations

from typing import Optional

from ompi_tpu import errors, op as op_mod
from ompi_tpu.core import pvar
from ompi_tpu.zero import layout as _layout


class ZeroShardedState:
    """The per-rank optimizer state bundle: the parameter shard plus
    named optimizer slots (each a :class:`~ompi_tpu.zero.layout.
    ShardedState` over the same plan). ``shard_bytes`` vs
    ``replicated_bytes`` is the O(1/n) memory claim the smoke lane
    asserts."""

    __slots__ = ("params", "slots")

    def __init__(self, params: _layout.ShardedState, slots=None) -> None:
        self.params = params
        self.slots = dict(slots or {})

    @property
    def shard_bytes(self) -> int:
        """Bytes this rank holds (param shard + every slot shard)."""
        return self.params.shard_bytes + sum(
            s.shard_bytes for s in self.slots.values())

    @property
    def replicated_bytes(self) -> int:
        """Bytes a replicated (non-ZeRO) optimizer would hold."""
        return self.params.total_bytes + sum(
            s.total_bytes for s in self.slots.values())


class ZeroOptimizer:
    """SGD(+momentum) with ZeRO-sharded state over an MPI comm.

    ``step(grads)`` runs one shard-grad -> local-update ->
    allgather-params cycle and returns the new replicated parameter
    pytree (grads must match the template's structure/shapes).

    - ``stage=2`` (default): gradients arrive as shards via
      ``Reduce_scatter_multi`` (or the partitioned overlap request).
    - ``stage=1``: gradients are fully allreduced
      (``Allreduce_multi``), then the shard is sliced locally —
      optimizer state is still 1/n.
    - ``overlap=True`` (stage 2 only): binds a ``Preduce_scatter_init``
      request at construction; each step pushes gradient leaves
      individually so early buckets' reduce_scatter overlaps the
      production of later gradients.
    - ``grad_average=True`` divides the reduced gradient shard by the
      comm size (data-parallel mean); False keeps the MPI SUM.
    - ``fused=True`` (stage 2, no overlap): routes the whole
      shard-grad + update through the comm's ``fused_rs_update_dev``
      slot when a component provides it (coll/pallas: ONE kernel per
      bucket reduce_scatters the gradients and consumes the reduced
      chunk in-register with the average/momentum/SGD epilogue). The
      slot returns None for unsupported cases, in which case — or
      when no component installs the slot at all — the step falls
      back to the unfused sequence below, the same staged-fallthrough
      shape the device collectives use. Bit-identical to unfused
      under ``deterministic='linear'``.
    - ``error_feedback`` (optional wire-format name: ``'bf16'``,
      ``'fp8_e4m3'``, ``'fp8_e5m2'``): quantize each step's gradients
      to the wire format at the source with a carried per-bucket
      residual (:class:`~ompi_tpu.zero.layout.ErrorFeedback` — the
      1-bit-SGD/DGC compensation scheme), the training-side companion
      of ``coll_hier_dcn_dtype``. Mutually exclusive with ``fused``
      (the fused kernel consumes raw gradients in-register; there is
      no host point to carry the residual at).
    - ``frozen`` (optional pytree of bools matching ``params``): True
      marks a non-trainable leaf. Buckets whose members are ALL
      frozen are excluded from the shard update (their
      ``ShardedState.versions`` counter never bumps), and the
      allgather tail skips re-gathering them — the previous cycle's
      gathered leaves are reused, with ``zero_ag_skipped`` counting
      the skipped launches. Mutually exclusive with ``fused`` (the
      fused kernel updates whole buckets unconditionally and rebuilds
      states with reset version counters).
    """

    def __init__(self, comm, params, lr: float = 1e-3,
                 momentum: float = 0.0, stage: int = 2,
                 deterministic: Optional[str] = None,
                 overlap: bool = False,
                 grad_average: bool = True,
                 fused: bool = False,
                 error_feedback: Optional[str] = None,
                 frozen=None) -> None:
        if stage not in (1, 2):
            raise errors.MPIError(
                errors.ERR_ARG,
                f"ZeroOptimizer: stage={stage} (ZeRO stages 1 and 2 "
                "shard state/gradients; stage 3 parameter sharding "
                "lives in ompi_tpu.zero.zero3.Zero3Optimizer — the "
                "streaming surface differs, it is not a flag here)")
        if overlap and stage != 2:
            raise errors.MPIError(
                errors.ERR_ARG,
                "ZeroOptimizer: overlap rides the partitioned "
                "reduce_scatter — stage 2 only (stage 1 allreduces "
                "full gradients)")
        if fused and (stage != 2 or overlap):
            raise errors.MPIError(
                errors.ERR_ARG,
                "ZeroOptimizer: fused consumes the reduce_scattered "
                "gradient in-kernel — stage 2 only, and mutually "
                "exclusive with overlap (the partitioned request "
                "already owns the reduce_scatter)")
        if fused and error_feedback is not None:
            raise errors.MPIError(
                errors.ERR_ARG,
                "ZeroOptimizer: error_feedback quantizes gradients "
                "before the collective and carries the residual on "
                "the host — the fused in-kernel path has no such "
                "point; pick one")
        if fused and frozen is not None:
            raise errors.MPIError(
                errors.ERR_ARG,
                "ZeroOptimizer: frozen leaves require the unfused "
                "step (the fused kernel updates whole buckets "
                "unconditionally, losing the version counters the "
                "allgather skip is proven by)")
        self._comm = comm
        self._lr = float(lr)
        self._mu = float(momentum)
        self._stage = stage
        self._det = deterministic
        self._avg = bool(grad_average)
        self._fused = bool(fused)
        # ctor-time validation (MPIError(ERR_ARG) on unknown names),
        # step-time application: EF state binds lazily to the grads'
        # own ZeroPlan at the first step
        self._ef = _layout.ErrorFeedback(error_feedback) \
            if error_feedback is not None else None
        # every rank holds the full initial params: the shard is a
        # local slice, no collective
        self._pshards = _layout.ShardedState.from_full(comm, params)
        slots = {}
        if self._mu:
            slots["momentum"] = self._pshards.zeros_like()
        self.state = ZeroShardedState(self._pshards, slots)
        self._req = None
        if overlap:
            self._req = comm.Preduce_scatter_init(
                params, op_mod.SUM, deterministic=deterministic)
        import jax

        self._n_leaves = len(jax.tree.leaves(params))
        #: per-bucket "has a trainable member" mask (None: everything
        #: trains); all-frozen buckets skip the update AND the
        #: re-gather (their versions prove they did not change)
        self._bucket_live = None
        self._frozen_leaves = None
        self._ag_versions = None
        self._ag_leaves: dict = {}
        if frozen is not None:
            fl = jax.tree.leaves(frozen)
            if len(fl) != self._n_leaves:
                raise errors.MPIError(
                    errors.ERR_COUNT,
                    f"ZeroOptimizer: {len(fl)} frozen flags for a "
                    f"{self._n_leaves}-leaf parameter pytree")
            self._frozen_leaves = [bool(f) for f in fl]
            self._bucket_live = [
                any(not fl[i] for i in idxs)
                for idxs in self._pshards.plan.buckets]

    # -- one training step -------------------------------------------------
    def _grad_shards(self, grads) -> _layout.ShardedState:
        if self._stage == 1:
            full = self._comm.Allreduce_multi(
                grads, op_mod.SUM, deterministic=self._det)
            return _layout.ShardedState.from_full(
                self._comm, full, plan=self._pshards.plan)
        if self._req is not None:
            import jax

            leaves = jax.tree.leaves(grads)
            if len(leaves) != self._n_leaves:
                raise errors.MPIError(
                    errors.ERR_COUNT,
                    f"ZeroOptimizer.step: {len(leaves)} gradient "
                    f"leaves for a {self._n_leaves}-leaf template")
            self._req.start()
            for i, g in enumerate(leaves):
                self._req.Pready(i, g)
            self._req.wait()
            return self._req.array
        return self._comm.Reduce_scatter_multi(
            grads, op_mod.SUM, deterministic=self._det)

    def step(self, grads):
        """shard-grad -> local shard update -> allgather-params;
        returns the new replicated parameter pytree."""
        import numpy as np

        if self._fused and "fused_rs_update_dev" in self._comm.coll.fns:
            mom = self.state.slots.get("momentum")
            fused = self._comm.coll.fused_rs_update_dev(
                self._comm, grads, self._pshards, mom,
                lr=self._lr, mu=self._mu, avg=self._avg,
                deterministic=self._det)
            if fused is not None:  # None = unsupported case: run the
                # unfused sequence below (staged fallthrough)
                self._pshards, new_mom = fused
                self.state.params = self._pshards
                if new_mom is not None:
                    self.state.slots["momentum"] = new_mom
                return self._comm.Allgather_multi(self._pshards)
        # constants cast to the shard dtype: a bare python float would
        # upcast numpy f32 shards to f64 (dtype drift across the
        # host/device paths would break the bit-identity contract)
        g = self._mask_frozen(grads)
        if self._ef is not None:
            # quantize-at-source AFTER the frozen mask (a frozen
            # leaf's zeros quantize to zeros, residual stays zero) and
            # BEFORE the collective, so any transport reduces exactly
            # what the residual accounts for
            g = self._ef.apply(g, self._comm.size)
        g = self._grad_shards(g)
        if self._avg:
            inv = 1.0 / self._comm.size
            g = g.map(lambda s: s * np.asarray(inv, s.dtype))
        mom = self.state.slots.get("momentum")
        if mom is not None:
            mom = mom.map(
                lambda v, gs: np.asarray(self._mu, v.dtype) * v + gs,
                g, where=self._bucket_live)
            self.state.slots["momentum"] = mom
            g = mom
        self._pshards = self._pshards.map(
            lambda p, gs: p - np.asarray(self._lr, p.dtype) * gs, g,
            where=self._bucket_live)
        self.state.params = self._pshards
        return self._gather_params()

    def _mask_frozen(self, grads):
        """Zero the gradients of frozen leaves, so a frozen leaf that
        shares a bucket with trainable ones stays exactly put when the
        bucket updates (p - lr*0 == p bitwise; its zero momentum
        contribution stays zero). All-frozen buckets additionally skip
        the update entirely via the ``where`` mask below."""
        if self._frozen_leaves is None:
            return grads
        import jax

        leaves, treedef = jax.tree.flatten(grads)
        leaves = [_layout._xp([g]).zeros_like(g) if fr else g
                  for g, fr in zip(leaves, self._frozen_leaves)]
        return jax.tree.unflatten(treedef, leaves)

    def _gather_params(self):
        """The allgather tail. With frozen leaves, bucket-granular:
        buckets whose shard versions did not move since the last
        gather reuse the cached gathered leaves (``zero_ag_skipped``
        counts them); only dirty buckets relaunch."""
        st = self._pshards
        if self._bucket_live is None or all(self._bucket_live):
            return self._comm.Allgather_multi(st)
        import jax
        import numpy as np

        host = bool(st.shards) and isinstance(st.shards[0],
                                              np.ndarray)
        bucket_dev = None if host else \
            self._comm.coll.fns.get("allgather_multi_bucket_dev")
        if not host and bucket_dev is None:
            return self._comm.Allgather_multi(st)
        outs = [None] * self._n_leaves
        skipped = 0
        for b, idxs in enumerate(st.plan.buckets):
            cached = self._ag_leaves.get(b)
            if (cached is not None and self._ag_versions is not None
                    and self._ag_versions[b] == st.versions[b]):
                lb = cached
                skipped += 1
            elif host:
                lb = _layout.host_allgather_bucket(self._comm, st, b)
            else:
                lb = bucket_dev(self._comm, st, b)
            if not self._bucket_live[b]:
                # only all-frozen buckets can ever be clean again —
                # caching live buckets would just pin a stale copy
                self._ag_leaves[b] = lb
            for j, i in enumerate(idxs):
                outs[i] = lb[j]
        if skipped:
            pvar.record("zero_ag_skipped", skipped)
        self._ag_versions = list(st.versions)
        return jax.tree.unflatten(st.treedef, outs)

    def params(self):
        """Replicated parameters rebuilt from the current shards (one
        allgather cycle — what ``step`` already returns)."""
        return self._gather_params()

    def free(self) -> None:
        if self._req is not None:
            self._req.free()
            self._req = None
