"""zero/layout — pad-and-shard bucket layout for sharded data parallel.

The ZeRO family (Rajbhandari et al., SC'20) and FSDP (Zhao et al.,
VLDB'23) replace the replicated allreduce-everything step with a
reduce_scatter(grads) -> local shard update -> all_gather(params)
cycle, so every rank materializes O(1/n) optimizer state. The layout
problem is the same one the fused allreduce already solved with
:class:`~ompi_tpu.coll.xla._FusePlan` — dtype-segregated flat buckets
that close at the ``coll_xla_bucket_bytes`` threshold — plus ONE new
constraint: a bucket's flat element count must divide evenly by the
comm size so the whole bucket lowers to a single tiled
``reduce_scatter``/``all_gather``. :class:`ZeroPlan` extends the fuse
plan with exactly that: per-bucket zero padding up to the next
multiple of n (``zero_pad_bytes`` pvar counts the waste).

:class:`ShardedState` is the per-rank view a `Reduce_scatter_multi`
returns and an `Allgather_multi` consumes: one 1-D shard array per
bucket (length ``padded/n``) plus the metadata to reassemble the
original pytree. Packing order is jax.tree.flatten leaf order — the
same order the fused allreduce concatenates, which is what keeps the
``deterministic='linear'`` fold bit-identical to the per-buffer path.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ompi_tpu import errors, op as op_mod
from ompi_tpu.coll.xla import _FusePlan, _bucket_var, _fuse_metas
from ompi_tpu.core import pvar


class ZeroPlan(_FusePlan):
    """_FusePlan + per-bucket pad-to-comm-size layout.

    Inherits the dtype-segregated ``buckets`` (tuples of leaf indices;
    close-at-threshold rule, launch bound ceil(total/bucket_bytes) +
    n_dtypes) and adds, per bucket: flat element count, padded count
    (next multiple of ``n``), per-rank shard length, and dtype.
    Construction is deterministic in (metas, bucket_bytes, n) — two
    independent builders (the collective path and a local
    :meth:`ShardedState.from_full` pack) always agree on the layout.
    """

    __slots__ = ("n", "elems", "padded", "shard_elems", "dtypes",
                 "pad_bytes")

    def __init__(self, metas, bucket_bytes: int, n: int) -> None:
        super().__init__(metas, bucket_bytes)
        self.n = int(n)
        elems, padded, shard, dtypes = [], [], [], []
        pad_bytes = 0
        for idxs in self.buckets:
            dt = metas[idxs[0]][1]
            e = sum(_elems_of(metas[i][0]) for i in idxs)
            p = -(-e // self.n) * self.n  # ceil to multiple of n
            elems.append(e)
            padded.append(p)
            shard.append(p // self.n)
            dtypes.append(dt)
            pad_bytes += (p - e) * np.dtype(dt).itemsize
        self.elems = tuple(elems)
        self.padded = tuple(padded)
        self.shard_elems = tuple(shard)
        self.dtypes = tuple(dtypes)
        self.pad_bytes = pad_bytes


def _elems_of(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def plan_for(leaves, n: int, bucket_bytes: Optional[int] = None
             ) -> ZeroPlan:
    """The bucket/pad layout the zero collectives will use for these
    leaves on a size-``n`` comm (default bucket size: the
    ``coll_xla_bucket_bytes`` cvar). Local, deterministic — safe to
    call on any rank without agreement."""
    bb = int(_bucket_var.get()) if bucket_bytes is None \
        else int(bucket_bytes)
    return ZeroPlan(_fuse_metas(leaves), bb, n)


def layer_groups(template) -> Tuple[Tuple[str, Tuple[int, ...]], ...]:
    """Ordered (name, leaf_indices) layer grouping of a pytree — the
    unit of ZeRO stage-3 parameter streaming (gather a layer, use it,
    free it). Leaves group by the TOP component of their jax key path;
    when that component holds a sequence the second component joins
    the key, so ``layers[0]``, ``layers[1]``, … are separate layers
    (the transformer-block shape) while ``{"embed": …}`` stays one.
    Groups are ordered by first appearance in flatten order — the
    forward-pass order a prefetch scheduler runs ahead of.
    Deterministic in the treedef: every rank derives the same grouping
    locally, no agreement needed."""
    import jax

    paths, _ = jax.tree_util.tree_flatten_with_path(template)
    groups: dict = {}
    for i, (path, _leaf) in enumerate(paths):
        depth = 2 if (len(path) > 1 and isinstance(
            path[1], jax.tree_util.SequenceKey)) else 1
        key = jax.tree_util.keystr(path[:depth]) if path else ""
        groups.setdefault(key, []).append(i)
    return tuple((k, tuple(v)) for k, v in groups.items())


def _xp(arrs):
    """jnp for jax arrays, numpy otherwise (one code path packs both
    the device and host layouts)."""
    try:
        import jax

        if any(isinstance(a, jax.Array) for a in arrs):
            import jax.numpy as jnp

            return jnp
    except ImportError:  # pragma: no cover - jax is a hard dep today
        pass
    return np


class ShardedState:
    """This rank's 1/n of a pytree packed by a :class:`ZeroPlan`.

    ``shards[b]`` is a 1-D array of ``plan.shard_elems[b]`` elements of
    ``plan.dtypes[b]`` — rank r's contiguous chunk of bucket b's padded
    flat concat. Produced by ``Comm.Reduce_scatter_multi`` (the
    reduced gradient shards) or :meth:`from_full` (a local slice of
    replicated values, e.g. the initial parameters); consumed by
    ``Comm.Allgather_multi`` which reassembles the full pytree."""

    __slots__ = ("plan", "metas", "treedef", "shards", "rank", "n",
                 "versions")

    def __init__(self, plan: ZeroPlan, metas, treedef, shards,
                 rank: int, n: int, versions=None) -> None:
        self.plan = plan
        self.metas = metas
        self.treedef = treedef
        self.shards = list(shards)
        self.rank = int(rank)
        self.n = int(n)
        #: per-bucket mutation counters (changed-bucket dirty
        #: tracking): every :meth:`map` bumps them, so the async
        #: checkpoint plane's incremental mode can tell which buckets
        #: MAY have changed since the last snapshot without touching
        #: the data (digest-diff stays the source of truth — versions
        #: are the cheap over-approximation)
        self.versions = list(versions) if versions is not None \
            else [0] * len(self.shards)

    # -- sizing (the O(1/n) story the smoke lane asserts) -----------------
    @property
    def shard_bytes(self) -> int:
        """Bytes this rank actually holds."""
        return sum(int(plan_sh) * np.dtype(dt).itemsize
                   for plan_sh, dt in zip(self.plan.shard_elems,
                                          self.plan.dtypes))

    @property
    def total_bytes(self) -> int:
        """Bytes of the full (replicated) pytree this shards."""
        return self.plan.nbytes

    @property
    def nbytes(self) -> int:
        """Alias of :attr:`total_bytes` — generic byte-counting hooks
        (the telemetry flight PMPI interposer reads ``args[0].nbytes``)
        see the full cycle payload."""
        return self.plan.nbytes

    # -- local elementwise math (the optimizer update) --------------------
    def map(self, fn, *others: "ShardedState", where=None
            ) -> "ShardedState":
        """New state with ``fn(self.shards[b], *others.shards[b])`` per
        bucket — the local-shard update step (runs on whatever array
        type the shards are; no collective). ``where`` (optional
        per-bucket bool mask) limits the update to selected buckets:
        unselected buckets keep their shard AND their version counter,
        which is what lets a downstream allgather prove "this bucket
        did not change" (the frozen-leaf skip path)."""
        for o in others:
            if o.plan.buckets != self.plan.buckets \
                    or o.plan.n != self.plan.n:
                raise errors.MPIError(
                    errors.ERR_ARG,
                    "ShardedState.map: operand packed by a different "
                    "plan (shard-wise math requires identical bucket "
                    "layouts)")
        if where is not None and len(where) != len(self.shards):
            raise errors.MPIError(
                errors.ERR_COUNT,
                f"ShardedState.map: where mask has {len(where)} "
                f"entries for {len(self.shards)} buckets")
        shards = [fn(s, *(o.shards[b] for o in others))
                  if where is None or where[b] else s
                  for b, s in enumerate(self.shards)]
        return ShardedState(self.plan, self.metas, self.treedef,
                            shards, self.rank, self.n,
                            versions=[v + 1 if where is None or where[b]
                                      else v
                                      for b, v in
                                      enumerate(self.versions)])

    def zeros_like(self) -> "ShardedState":
        xp = _xp(self.shards)
        shards = [xp.zeros((k,), dtype=dt)
                  for k, dt in zip(self.plan.shard_elems,
                                   self.plan.dtypes)]
        return ShardedState(self.plan, self.metas, self.treedef,
                            shards, self.rank, self.n)

    # -- pack / unpack -----------------------------------------------------
    @classmethod
    def from_full(cls, comm, tree, plan: Optional[ZeroPlan] = None
                  ) -> "ShardedState":
        """Slice this rank's shard out of a REPLICATED pytree (no
        collective — every rank already holds the full values; used to
        seed the optimizer's param/momentum shards). The layout is the
        same ZeroPlan the collectives use, so shards line up with
        ``Reduce_scatter_multi`` gradients element-for-element."""
        import jax

        leaves, treedef = jax.tree.flatten(tree)
        metas = _fuse_metas(leaves)
        if plan is None:
            plan = ZeroPlan(metas, int(_bucket_var.get()), comm.size)
        xp = _xp(leaves)
        rank = comm.rank
        shards = []
        for b, idxs in enumerate(plan.buckets):
            flat = xp.concatenate([xp.reshape(leaves[i], (-1,))
                                   for i in idxs]) \
                if len(idxs) > 1 else xp.reshape(leaves[idxs[0]], (-1,))
            pad = plan.padded[b] - plan.elems[b]
            if pad:
                flat = xp.pad(flat, (0, pad))
            k = plan.shard_elems[b]
            shards.append(flat[rank * k:(rank + 1) * k])
        return cls(plan, metas, treedef, shards, rank, comm.size)

    def unpack(self, fulls) -> object:
        """Full padded flat bucket arrays -> the original pytree
        (drops the pad tail, restores leaf shapes; the inverse of the
        bucket concat)."""
        import jax

        xp = _xp(fulls)
        outs: List[object] = [None] * sum(
            len(idxs) for idxs in self.plan.buckets)
        for b, idxs in enumerate(self.plan.buckets):
            off = 0
            for i in idxs:
                shape = self.metas[i][0]
                k = _elems_of(shape)
                outs[i] = xp.reshape(fulls[b][off:off + k], shape)
                off += k
        return jax.tree.unflatten(self.treedef, outs)


class ErrorFeedback:
    """Per-bucket compression-residual carry for ZeRO gradient cycles
    (Seide et al. 2014 1-bit SGD; Lin et al. 2018 DGC): each step
    transmits Q(g + e) and keeps e' = (g + e) - Q(g + e) locally, so
    quantization error is re-injected next step instead of lost and
    SGD tracks the exact-gradient trajectory. Quantization happens at
    the SOURCE — elementwise, deterministic, before the exact reduce —
    which makes the scheme self-consistent no matter which collective
    transport (flat, hier, compressed-DCN) carries the payload.

    Layout-matched to the same deterministic :class:`ZeroPlan` the
    zero collectives derive, so the fp8 scale is per BUCKET (the
    compressed-DCN granularity) and the residual is one unpadded flat
    array per compressible bucket. Buckets whose dtype the wire format
    cannot narrow (ints, dtypes <= the wire width) pass through
    untouched and carry no residual."""

    __slots__ = ("wire", "plan", "residuals", "_active")

    def __init__(self, wire: str) -> None:
        from ompi_tpu.util import jaxcompat as _jc

        if _jc.wire_dtype(wire) is None:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"error_feedback={wire!r}: expected 'bf16', "
                "'fp8_e4m3' or 'fp8_e5m2'")
        self.wire = _jc.wire_degrade(wire)
        self.plan: Optional[ZeroPlan] = None
        self.residuals: List[object] = []
        self._active: Tuple[bool, ...] = ()

    def _bind(self, plan: ZeroPlan) -> None:
        """(Re)bind to a bucket layout; a layout change resets the
        carried residuals (they index a different packing)."""
        from ompi_tpu.util import jaxcompat as _jc

        self.plan = plan
        wsz = _jc.wire_itemsize(self.wire)
        active = []
        for dt in plan.dtypes:
            try:
                ndt = _jc.np_dtype(dt)
            except TypeError:
                active.append(False)
                continue
            active.append(ndt.kind == "f" and wsz < ndt.itemsize)
        self._active = tuple(active)
        self.residuals = [None] * len(plan.buckets)

    def apply(self, tree, n: int):
        """Same-treedef pytree with every compressible bucket replaced
        by Q(bucket + residual), the new residual carried for the next
        step. ``n`` is the comm size (the plan's pad modulus), so the
        packing here is element-for-element the one the zero
        collectives will transmit."""
        import jax

        from ompi_tpu.parallel import hierarchical as H
        from ompi_tpu.util import jaxcompat as _jc

        leaves, treedef = jax.tree.flatten(tree)
        metas = _fuse_metas(leaves)
        plan = ZeroPlan(metas, int(_bucket_var.get()), int(n))
        if self.plan is None or plan.buckets != self.plan.buckets \
                or plan.dtypes != self.plan.dtypes:
            self._bind(plan)
        xp = _xp(leaves)
        outs = list(leaves)
        wsz = _jc.wire_itemsize(self.wire)
        ef_bytes = 0
        for b, idxs in enumerate(plan.buckets):
            if not self._active[b]:
                continue
            flat = xp.concatenate(
                [xp.reshape(leaves[i], (-1,)) for i in idxs]) \
                if len(idxs) > 1 else xp.reshape(leaves[idxs[0]], (-1,))
            r = self.residuals[b]
            if r is not None:
                flat = flat + r
            q = H.wire_quantize(flat, self.wire)
            self.residuals[b] = flat - q
            off = 0
            for i in idxs:
                shape = metas[i][0]
                k = _elems_of(shape)
                outs[i] = xp.reshape(q[off:off + k], shape)
                off += k
            ef_bytes += plan.elems[b] * wsz
        pvar.record("zero_ef_steps")
        pvar.record("zero_ef_bytes", ef_bytes)
        return jax.tree.unflatten(treedef, outs)


# ---------------------------------------------------------------------------
# host-buffer fallback cycle (numpy leaves, no device plane required):
# the same ZeroPlan layout over the stacked host collectives — one
# allreduce/allgather per bucket, shard sliced locally. Correct and
# O(1/n)-sharded; the overlap + single-launch wins are device-path.


def host_reduce_scatter_multi(comm, bufs, op=op_mod.SUM
                              ) -> ShardedState:
    """Bucketed reduce_scatter of numpy leaves: per bucket ONE host
    allreduce of the padded flat concat, then slice this rank's
    chunk. Same ZeroPlan layout (and leaf order) as the device path."""
    import jax

    from ompi_tpu.datatype.convertor import dtype_of

    leaves, treedef = jax.tree.flatten(bufs)
    metas = _fuse_metas(leaves)
    plan = ZeroPlan(metas, int(_bucket_var.get()), comm.size)
    rank, k_shards = comm.rank, []
    for b, idxs in enumerate(plan.buckets):
        flat = np.concatenate(
            [np.ascontiguousarray(leaves[i]).reshape(-1)
             for i in idxs])
        pad = plan.padded[b] - plan.elems[b]
        if pad:
            flat = np.pad(flat, (0, pad))
        out = np.empty_like(flat)
        comm.coll.allreduce(comm, flat, out, out.size, dtype_of(out),
                            op)
        k = plan.shard_elems[b]
        k_shards.append(out[rank * k:(rank + 1) * k].copy())
        pvar.record("zero_rs_launches")
    pvar.record("zero_fused_bytes", plan.nbytes)
    pvar.record("zero_pad_bytes", plan.pad_bytes)
    return ShardedState(plan, metas, treedef, k_shards, rank,
                        comm.size)


def host_allgather_bucket(comm, state: ShardedState, b: int):
    """Gather ONE bucket of a numpy ShardedState: the member leaves
    (in ``plan.buckets[b]`` order) reshaped to their original shapes.
    The bucket-granular form the optimizer's dirty-skip path uses —
    unchanged buckets reuse the previous cycle's gathered leaves
    instead of relaunching."""
    plan = state.plan
    if not 0 <= b < len(plan.buckets):
        raise errors.MPIError(
            errors.ERR_COUNT,
            f"host_allgather_bucket: bucket {b} out of range for a "
            f"{len(plan.buckets)}-bucket plan")
    parts = comm.coll.allgather_obj(
        comm, np.ascontiguousarray(state.shards[b]))
    full = np.concatenate(parts)
    pvar.record("zero_ag_launches")
    outs, off = [], 0
    for i in plan.buckets[b]:
        shape = state.metas[i][0]
        k = _elems_of(shape)
        outs.append(full[off:off + k].reshape(shape))
        off += k
    return outs


def host_allgather_multi(comm, state: ShardedState):
    """Bucketed allgather of numpy shards back to the full pytree:
    per bucket ONE host allgather of the shard, concat in rank order
    (= the pack order), unpack."""
    fulls = []
    for b, shard in enumerate(state.shards):
        parts = comm.coll.allgather_obj(comm, np.ascontiguousarray(
            shard))
        fulls.append(np.concatenate(parts))
        pvar.record("zero_ag_launches")
    pvar.record("zero_fused_bytes", state.plan.nbytes)
    return state.unpack(fulls)
