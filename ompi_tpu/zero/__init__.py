"""ompi_tpu.zero — ZeRO-style sharded data parallel.

Peer of :mod:`ompi_tpu.part` (MPI-4 partitioned) and
:mod:`ompi_tpu.parallel` (in-program SPMD collectives): the subsystem
that turns the fused gradient-bucket machinery into a memory-scaling
story. A :class:`~ompi_tpu.zero.layout.ZeroPlan` pads each dtype
bucket to a multiple of the comm size so it lowers to ONE
``reduce_scatter``/``all_gather``; ``Comm.Reduce_scatter_multi`` /
``Comm.Allgather_multi`` (coll/xla) run the cycle on device;
:class:`~ompi_tpu.zero.optimizer.ZeroOptimizer` wraps it into the
shard-grad -> local-update -> allgather-params training step with
O(1/n) optimizer state per rank (ZeRO stages 1/2).
:class:`~ompi_tpu.zero.zero3.Zero3Optimizer` extends the cycle to
stage 3 — parameters themselves sharded, streamed layer by layer
through per-layer persistent allgathers prefetched one layer ahead
and freed after use (O(1/n) + two-layer residency).
"""

from ompi_tpu.zero.layout import (  # noqa: F401
    ShardedState, ZeroPlan, layer_groups, plan_for,
)
from ompi_tpu.zero.optimizer import (  # noqa: F401
    ZeroOptimizer, ZeroShardedState,
)
from ompi_tpu.zero.zero3 import (  # noqa: F401
    Zero3Optimizer, Zero3Plan, prefetch_info,
)
