"""zero/zero3 — ZeRO stage-3 parameter sharding with layer-ahead
prefetch.

Stage 3 (P\\ :sub:`os+g+p`, Rajbhandari et al. SC'20; FSDP is the
same idea) shards the PARAMETERS themselves: each rank keeps only its
1/n flat shard resident and materializes a layer's full weights just
in time for use, freeing them immediately after. The streaming cycle
is built entirely out of landed subsystems:

- **layout**: :func:`~ompi_tpu.zero.layout.layer_groups` splits the
  parameter pytree into layers (the streaming unit); each layer's
  leaves pack into their own :class:`~ompi_tpu.zero.layout.ZeroPlan`
  buckets, so a layer gather is the same cached per-bucket tiled
  all_gather the stage-1/2 cycle uses.
- **persistent collectives**: one ``Comm.Allgather_multi_init``
  request per layer, prepped ONCE — plans and compiled programs live
  in the ``_Ctx`` LRU caches, so steady-state steps never replan or
  recompile; after the optimizer refreshes a layer's shards the
  request ``rebind()``\\ s the fresh arrays into the same executable.
- **the partitioned plane's timing discipline**:
  :class:`~ompi_tpu.part.overlap.LayerPrefetcher` fires each layer's
  ``start()`` on the PREVIOUS layer's consumption event (the
  ``Pready``-on-layer-boundary shape), so the gather for layer k+1
  is in flight while layer k computes; :meth:`Zero3Optimizer.fetch`
  is the ``Parrived``-style consumption gate that blocks only when
  the prefetch lost the race (``zero_prefetch_late_ns`` + the
  ``prefetch`` trace lane + ``prof.phase('prefetch')`` account the
  loss; the watchdog names it via :func:`prefetch_info` instead of
  reporting a false hang).
- **free-after-use**: :meth:`Zero3Optimizer.release` drops the
  gathered arrays and ``discard()``\\ s the request's cycle result,
  so steady-state residency is the O(1/n) shard plus the in-flight
  prefetch window (``zero3_resident_bytes`` is the high-water proof).
- **fused fast path**: when coll_pallas is on,
  :meth:`Zero3Optimizer.matmul` consumes a single-leaf 2-D layer
  through the ``zero3_gather_matmul_dev`` slot — the tensor-parallel
  allgather@matmul kernel eats the SHARD directly and the full weight
  is never materialized; every other layout falls through to the
  persistent coll/xla gather (staged fallthrough).

Bit-identity: the update math is op-for-op the stage-1/2
:class:`~ompi_tpu.zero.optimizer.ZeroOptimizer` sequence (same
dtype-cast constants, same fold order), and 'linear' reduce_scatter /
all_gather are elementwise identical regardless of how leaves are
grouped into buckets — so a stage-3 trajectory under
``deterministic='linear'`` reproduces stage 1 bitwise, momentum
included (proven in tests/test_zero3.py).
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import numpy as np

from ompi_tpu import errors, op as op_mod, prof as _prof
from ompi_tpu.core import pvar
from ompi_tpu.part.overlap import LayerPrefetcher
from ompi_tpu.trace import recorder as _trace
from ompi_tpu.zero import layout as _layout

#: last blocked prefetch, for the watchdog hang dump (a rank stuck in
#: a gather wait is a LATE PREFETCH, not a lost peer — naming it
#: turns a false hang report into an actionable one)
_PREFETCH_INFO: Optional[dict] = None


def prefetch_info() -> Optional[dict]:
    """The most recent blocked-prefetch record ({layer, pos, step,
    late_ns}) or None if every fetch so far was already complete —
    read by telemetry.watchdog's hang dump."""
    return _PREFETCH_INFO


class Zero3Plan:
    """Layer-grouped extension of the ZeroPlan bucket/pad layout.

    :func:`~ompi_tpu.zero.layout.layer_groups` fixes the streaming
    order; each layer's leaves get their own
    :class:`~ompi_tpu.zero.layout.ZeroPlan` (same
    ``coll_xla_bucket_bytes`` close rule, same pad-to-n), so the
    per-layer gather is the cached per-bucket executable the stage-1/2
    cycle already compiled. Deterministic in (template treedef/shapes,
    bucket_bytes, n) — every rank derives the identical plan locally,
    no agreement needed."""

    __slots__ = ("groups", "plans", "n", "treedef", "n_leaves")

    def __init__(self, template, n: int,
                 bucket_bytes: Optional[int] = None) -> None:
        import jax

        leaves, self.treedef = jax.tree.flatten(template)
        if not leaves:
            raise errors.MPIError(
                errors.ERR_ARG,
                "Zero3Plan: empty parameter pytree (nothing to shard)")
        self.n = int(n)
        self.n_leaves = len(leaves)
        self.groups = _layout.layer_groups(template)
        self.plans = tuple(
            _layout.plan_for([leaves[i] for i in idxs], self.n,
                             bucket_bytes)
            for _name, idxs in self.groups)

    @property
    def n_layers(self) -> int:
        return len(self.groups)

    @property
    def total_bytes(self) -> int:
        """Bytes of the full replicated parameters."""
        return sum(p.nbytes for p in self.plans)

    @property
    def layer_bytes(self):
        """Full (gathered) bytes per layer, in streaming order."""
        return tuple(p.nbytes for p in self.plans)

    def name_of(self, g: int) -> str:
        return self.groups[g][0]


class Zero3Optimizer:
    """SGD(+momentum) with fully sharded parameters (ZeRO stage 3).

    Unlike stages 1/2 there is no replicated parameter pytree: the
    training loop streams layers through the optimizer —

    >>> opt.start_pass()                    # forward: prefetch ahead
    >>> for g in range(opt.plan.n_layers):
    ...     with opt.layer(g) as ws:        # fetch -> use -> release
    ...         acts = forward_layer(ws, acts)
    >>> opt.step(grads)                     # reduce_scatter + update

    - :meth:`start_pass` opens a forward (or ``reverse=True``
      backward) pass: the prefetcher fires the first ``depth`` layer
      gathers immediately and keeps the window topped up as layers
      are consumed.
    - :meth:`fetch` returns layer ``g``'s full leaves, blocking only
      if the prefetched gather has not finished (hit/miss/late pvars;
      a fetch outside the prefetch window counts a miss and gathers
      on the spot).
    - :meth:`release` frees the gathered arrays (and the persistent
      request's held cycle) — O(1/n) + window residency.
    - :meth:`step` reduce_scatters the gradients per layer, runs the
      exact stage-1/2 shard-update math, and rebinds each layer's
      persistent allgather to the fresh shards (``rebind``; gated
      trivial requests re-init, same cost).
    - :meth:`matmul` is the fused gather→use fast path (coll_pallas
      ``zero3_gather_matmul_dev``), falling through to fetch + dot.
    - ``error_feedback`` (optional ``'bf16'``/``'fp8_e4m3'``/
      ``'fp8_e5m2'``): quantize each layer's gradients at the source
      with a per-layer carried residual
      (:class:`~ompi_tpu.zero.layout.ErrorFeedback`) before the
      reduce_scatter — the stage-3 shape of the stage-1/2 option.

    Host (numpy) parameters run the same cycle over the stacked host
    collectives — prefetch degrades to eager blocking gathers (every
    prefetched fetch is a hit; there is just no overlap to win).
    """

    def __init__(self, comm, params, lr: float = 1e-3,
                 momentum: float = 0.0,
                 deterministic: Optional[str] = None,
                 grad_average: bool = True,
                 error_feedback: Optional[str] = None,
                 prefetch_depth: int = 1) -> None:
        import jax

        self._comm = comm
        self._lr = float(lr)
        self._mu = float(momentum)
        self._det = deterministic
        self._avg = bool(grad_average)
        self.plan = Zero3Plan(params, comm.size)
        # one residual carry per LAYER: stage-3 reduces gradients a
        # layer at a time, and each layer's leaves pack their own
        # ZeroPlan — the per-bucket residual layout follows it
        self._efs: Optional[List[_layout.ErrorFeedback]] = (
            [_layout.ErrorFeedback(error_feedback)
             for _ in range(self.plan.n_layers)]
            if error_feedback is not None else None)
        leaves = jax.tree.leaves(params)
        from ompi_tpu import accelerator

        self._dev = accelerator.is_device_buffer(leaves[0])
        # every rank holds the full initial params: each layer's shard
        # is a local slice (no collective), packed by the layer plan
        # the collectives will reuse
        self._pstates: List[_layout.ShardedState] = [
            _layout.ShardedState.from_full(
                comm, [leaves[i] for i in idxs], plan=lplan)
            for (_n, idxs), lplan in zip(self.plan.groups,
                                         self.plan.plans)]
        self._mstates: Optional[List[_layout.ShardedState]] = (
            [s.zeros_like() for s in self._pstates]
            if self._mu else None)
        # one persistent allgather per layer (device path): prepped
        # once, rebound after every step — zero replans across steps
        self._reqs = [comm.Allgather_multi_init(s)
                      for s in self._pstates] if self._dev else None
        self._prefetcher = LayerPrefetcher(self._start_gather,
                                           depth=prefetch_depth)
        self._gathered: Dict[int, list] = {}
        self._started: set = set()
        self._step_no = 0
        pvar.record_hwm("zero3_shard_bytes", self.shard_bytes)
        pvar.record_hwm("zero3_layer_bytes",
                        max(self.plan.layer_bytes))
        pvar.record_hwm("zero3_resident_bytes", self.resident_bytes)

    # -- sizing (the O(1/n)+window story the smoke lane asserts) ----------
    @property
    def shard_bytes(self) -> int:
        """Parameter bytes this rank holds permanently (the shards)."""
        return sum(s.shard_bytes for s in self._pstates)

    @property
    def replicated_bytes(self) -> int:
        """Bytes a replicated (non-stage-3) copy of the params needs."""
        return self.plan.total_bytes

    @property
    def resident_bytes(self) -> int:
        """Parameter bytes resident right now: the shards plus every
        currently gathered layer (``zero3_resident_bytes`` tracks the
        high-water mark of this)."""
        return self.shard_bytes + sum(
            self._pstates[g].total_bytes for g in self._gathered)

    # -- the prefetch/fetch/release stream --------------------------------
    def _start_gather(self, g: int) -> None:
        if g in self._started or g in self._gathered:
            return
        pvar.record("zero3_gathers")
        if not self._dev:
            # host path: no async request to arm — gather eagerly so
            # a later fetch of a prefetched layer is a hit
            self._gathered[g] = self._comm.Allgather_multi(
                self._pstates[g])
            pvar.record_hwm("zero3_resident_bytes",
                            self.resident_bytes)
            return
        self._reqs[g].start()
        self._started.add(g)
        rec = _trace.RECORDER
        if rec is not None:
            rec.instant("prefetch_start", "prefetch",
                        {"layer": self.plan.name_of(g), "pos": g})

    def start_pass(self, reverse: bool = False) -> None:
        """Open a pass: drop any state left from a previous pass and
        fire the first ``depth`` gathers of the (possibly reversed —
        the backward) streaming order."""
        self._drain()
        order = range(self.plan.n_layers)
        self._prefetcher.begin(reversed(order) if reverse else order)

    def fetch(self, g: int) -> list:
        """Layer ``g``'s full parameter leaves (the layer's flatten
        order). A prefetched-and-complete gather is a hit; a fetch the
        prefetcher never issued is a miss (gathered on the spot); a
        prefetched-but-unfinished gather blocks — the wait is the
        ``prefetch`` trace span, ``prof.phase('prefetch')`` time and
        the ``zero_prefetch_late_ns`` pvar."""
        global _PREFETCH_INFO

        if not 0 <= g < self.plan.n_layers:
            raise errors.MPIError(
                errors.ERR_COUNT,
                f"zero3 fetch: layer {g} out of range for a "
                f"{self.plan.n_layers}-layer plan")
        if g in self._gathered:
            if not self._dev:
                pvar.record("zero_prefetch_hits")
            self._prefetcher.advance(g)
            return self._gathered[g]
        if not self._dev:
            pvar.record("zero_prefetch_misses")
            self._gathered[g] = self._comm.Allgather_multi(
                self._pstates[g])
            pvar.record_hwm("zero3_resident_bytes",
                            self.resident_bytes)
            self._prefetcher.advance(g)
            return self._gathered[g]
        if g in self._started:
            pvar.record("zero_prefetch_hits")
        else:
            pvar.record("zero_prefetch_misses")
            self._reqs[g].start()
            self._started.add(g)
        req = self._reqs[g]
        if not req.completed:
            # the prefetch lost the race to the consumer: account the
            # blocked wait so a long stall reads as "late prefetch of
            # layer X", not as a hang or unattributed train time
            t0 = _trace.now()
            with _prof.phase("prefetch"):
                req.wait()
            late = _trace.now() - t0
            pvar.record("zero_prefetch_late_ns", int(late))
            _PREFETCH_INFO = {"layer": self.plan.name_of(g),
                              "pos": g, "step": self._step_no,
                              "late_ns": int(late)}
            rec = _trace.RECORDER
            if rec is not None:
                rec.record("prefetch_wait", "prefetch", t0,
                           _trace.now(),
                           {"layer": self.plan.name_of(g), "pos": g})
        else:
            req.wait()
        self._gathered[g] = req.array
        # the request's cycle handle would pin the gathered arrays
        # past release(); drop it now — our dict is the only owner
        req.discard()
        self._started.discard(g)
        pvar.record_hwm("zero3_resident_bytes", self.resident_bytes)
        self._prefetcher.advance(g)
        return self._gathered[g]

    def release(self, g: int) -> None:
        """Free layer ``g``'s gathered parameters (free-after-use —
        THE stage-3 residency lever). No-op if not gathered."""
        if self._gathered.pop(g, None) is not None:
            pvar.record("zero3_releases")

    @contextlib.contextmanager
    def layer(self, g: int):
        """``with opt.layer(g) as ws:`` — fetch on entry, release on
        exit (the use-and-free discipline as a scope)."""
        try:
            yield self.fetch(g)
        finally:
            self.release(g)

    def matmul(self, g: int, rhs):
        """Layer ``g``'s (single 2-D leaf) weight @ ``rhs`` — through
        the fused allgather-matmul kernel when a component provides
        ``zero3_gather_matmul_dev`` and the layout qualifies (the full
        weight is never materialized); otherwise fetch + local dot
        (same result, staged fallthrough)."""
        fn = self._comm.coll.fns.get("zero3_gather_matmul_dev") \
            if self._dev else None
        if fn is not None:
            out = fn(self._comm, self._pstates[g], rhs)
            if out is not None:
                pvar.record("zero3_fused_matmuls")
                self._prefetcher.advance(g)
                return out
        ws = self.fetch(g)
        if len(ws) != 1:
            raise errors.MPIError(
                errors.ERR_ARG,
                f"zero3 matmul: layer {g} has {len(ws)} leaves — the "
                "gather→matmul path consumes single-weight layers")
        return ws[0] @ rhs

    def _drain(self) -> None:
        """Quiesce the stream: wait out in-flight gathers (their
        results are dropped) and free everything gathered."""
        for g in list(self._started):
            self._reqs[g].wait()
            self._reqs[g].discard()
        self._started.clear()
        for g in list(self._gathered):
            self.release(g)
        self._prefetcher.reset()

    # -- one training step -------------------------------------------------
    def step(self, grads) -> None:
        """Per layer (backward order): reduce_scatter the gradient
        leaves, run the exact stage-1/2 shard-update math
        (average -> momentum -> SGD, constants cast to the shard
        dtype), then rebind the layer's persistent allgather to the
        fresh shards. No replicated parameters are ever built."""
        import jax

        self._drain()
        glaves = jax.tree.leaves(grads)
        if len(glaves) != self.plan.n_leaves:
            raise errors.MPIError(
                errors.ERR_COUNT,
                f"zero3 step: {len(glaves)} gradient leaves for a "
                f"{self.plan.n_leaves}-leaf template")
        for g in reversed(range(self.plan.n_layers)):
            idxs = self.plan.groups[g][1]
            layer_grads = [glaves[i] for i in idxs]
            if self._efs is not None:
                layer_grads = self._efs[g].apply(layer_grads,
                                                 self._comm.size)
            gs = self._comm.Reduce_scatter_multi(
                layer_grads, op_mod.SUM,
                deterministic=self._det)
            if self._avg:
                inv = 1.0 / self._comm.size
                gs = gs.map(lambda s: s * np.asarray(inv, s.dtype))
            if self._mstates is not None:
                mom = self._mstates[g].map(
                    lambda v, sh: np.asarray(self._mu, v.dtype) * v
                    + sh, gs)
                self._mstates[g] = mom
                gs = mom
            new = self._pstates[g].map(
                lambda p, sh: p - np.asarray(self._lr, p.dtype) * sh,
                gs)
            self._pstates[g] = new
            self._refresh_req(g, new)
        self._step_no += 1

    def _refresh_req(self, g: int, state) -> None:
        if self._reqs is None:
            return
        try:
            self._reqs[g].rebind(state)
        except errors.MPIError as e:
            if e.error_class != errors.ERR_NOT_SUPPORTED:
                raise
            # gated trivial request (size-1 comm): binds per start —
            # re-init costs nothing there
            self._reqs[g].free()
            self._reqs[g] = self._comm.Allgather_multi_init(state)

    # -- whole-tree views (tests / checkpointing — NOT the hot path) ------
    def gathered_params(self):
        """The full parameter pytree, assembled layer by layer (each
        layer gathered then kept — this materializes O(P); tests and
        export only)."""
        return self._gather_tree(self._pstates)

    def gathered_momentum(self):
        """The full momentum pytree (None without momentum) — the
        trajectory-comparison hook for the bit-identity tests."""
        if self._mstates is None:
            return None
        return self._gather_tree(self._mstates)

    def _gather_tree(self, states):
        import jax

        outs = [None] * self.plan.n_leaves
        for (g, (_name, idxs)) in enumerate(self.plan.groups):
            fulls = self._comm.Allgather_multi(states[g])
            for j, i in enumerate(idxs):
                outs[i] = fulls[j]
        return jax.tree.unflatten(self.plan.treedef, outs)

    def free(self) -> None:
        """Release the per-layer persistent requests and every
        gathered layer."""
        self._drain()
        if self._reqs is not None:
            for r in self._reqs:
                r.free()
            self._reqs = None
