"""CLI: render the collective performance observatory report.

    python -m ompi_tpu.tune report tune_r0.json tune_r1.json
    python -m ompi_tpu.tune report --db tune_perfdb_cpu_n2.json \
        --tables cand --json merged.json tune_r*.json

Inputs are per-rank Finalize dumps (``--mca tune_dump
'/tmp/tune_r{rank}.json'``) and/or a persistent PerfDB file — all
the same schema ``ompi_tpu.tune.perfdb/1`` — merged associatively.
``--db`` names the BASELINE to diff against for regression verdicts;
``--tables PREFIX`` writes the candidate switchpoint suggestions
(``PREFIX_pallas.json`` / ``PREFIX_hier.json``) in the exact shapes
the ``coll_*_switchpoints`` readers consume. Missing or corrupt
input: one line on stderr, exit 1 — the monitoring CLI contract.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ompi_tpu.tune import perfdb, report


def _cmd_report(args) -> int:
    docs = []
    try:
        for path in args.inputs:
            with open(path) as fh:
                docs.append(json.load(fh))
        merged = perfdb.merge(docs)
        stats = perfdb.stats_of(merged["entries"])
        baseline = None
        if args.db:
            with open(args.db) as fh:
                bdoc = json.load(fh)
            if bdoc.get("schema") != perfdb.SCHEMA:
                raise ValueError(
                    f"baseline {args.db}: schema "
                    f"{bdoc.get('schema')!r}, want {perfdb.SCHEMA!r}")
            baseline = perfdb.stats_of(bdoc.get("entries", []))
    except OSError as exc:
        print(f"tune report: {exc}", file=sys.stderr)
        return 1
    except (json.JSONDecodeError, KeyError, TypeError,
            ValueError) as exc:
        print("tune report: corrupt perfdb input: "
              f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return 1
    print(report.render(stats, baseline=baseline,
                        threshold=args.threshold, top=args.top))
    try:
        if args.json:
            with open(args.json, "w") as fh:
                json.dump(merged, fh, indent=1)
            print(f"merged perfdb written: {args.json}")
        if args.tables:
            tables = report.candidate_tables(stats)
            for kind in ("pallas", "hier"):
                path = f"{args.tables}_{kind}.json"
                with open(path, "w") as fh:
                    json.dump(tables[kind], fh, indent=1)
                print(f"candidate {kind} switchpoints (suggestions, "
                      f"{len(tables[kind])} entries): {path}")
    except OSError as exc:
        print(f"tune report: {exc}", file=sys.stderr)
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tune",
        description="collective performance observatory reports")
    sub = ap.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser(
        "report", help="measured crossovers, candidate switchpoint "
                       "tables, and regression verdicts from perfdb "
                       "dumps")
    r.add_argument("inputs", nargs="+",
                   help="per-rank tune_dump / perfdb JSON files")
    r.add_argument("--db", default="",
                   help="baseline PerfDB to diff for regression "
                        "verdicts")
    r.add_argument("--json", default="",
                   help="also write the merged perfdb JSON artifact")
    r.add_argument("--tables", default="",
                   help="write candidate switchpoint tables as "
                        "PREFIX_pallas.json / PREFIX_hier.json")
    r.add_argument("--threshold", type=float, default=1.5,
                   help="regression verdict bar (default 1.5x p50)")
    r.add_argument("--top", type=int, default=20,
                   help="observed keys to print (default 20)")
    r.set_defaults(fn=_cmd_report)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
