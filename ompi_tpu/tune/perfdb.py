"""PerfDB — the persistent collective performance database.

The coll/tuned analogue of a measured dynamic-rules file: observer
stats (:class:`ompi_tpu.tune.observe.Observer` snapshots) serialize
to a JSON doc keyed ``(op, dtype, log2-size, mesh, provider,
algorithm)`` with the associative record ``[count, sum_ns, min_ns,
max_ns, {log2-latency-bin: n}]``, and because every component merges
associatively — counts/sums add, min/max fold, histograms add —
docs combine across ranks (kvstore exchange, the
``monitoring/merge.py`` publish/collect shape) and across **runs**
(rank 0 folds the fresh merge into the on-disk DB at Finalize), so
measurements accumulate instead of dying with the process.

The DB lives alongside the compile cache (``tune_db_dir``, default
``compile_cache_dir``), one file per ``(device_kind, world size)``:
``tune_perfdb_<device_kind>_n<nranks>.json``. Loading is failure-
proof by contract: a corrupt/alien file degrades to an empty DB with
``tune_db_errors`` bumped — never an exception at init.

Schema ``ompi_tpu.tune.perfdb/1``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from ompi_tpu.core import output, pvar

_out = output.stream("tune")

SCHEMA = "ompi_tpu.tune.perfdb/1"

#: in-memory stats key (the observe.Observer key)
Key = Tuple[str, str, int, Tuple[int, ...], str, str]


def entries_of(stats: Dict[Key, list]) -> List[Dict[str, object]]:
    """Stats table -> sorted JSON-able entry list."""
    return [
        {"op": op, "dtype": dt, "log2": lg, "mesh": list(mesh),
         "provider": prov, "algorithm": algo,
         "count": rec[0], "sum_ns": rec[1],
         "min_ns": rec[2], "max_ns": rec[3],
         "hist": {str(b): c for b, c in sorted(rec[4].items())}}
        for (op, dt, lg, mesh, prov, algo), rec in
        sorted(stats.items())]


def stats_of(entries: List[Dict[str, object]]) -> Dict[Key, list]:
    """Entry list -> stats table (inverse of :func:`entries_of`)."""
    stats: Dict[Key, list] = {}
    for e in entries:
        key = (str(e["op"]), str(e["dtype"]), int(e["log2"]),
               tuple(int(d) for d in e["mesh"]),
               str(e["provider"]), str(e["algorithm"]))
        rec = stats.get(key)
        if rec is None:
            rec = stats[key] = [0, 0, None, 0, {}]
        rec[0] += int(e["count"])
        rec[1] += int(e["sum_ns"])
        mn = int(e["min_ns"])
        rec[2] = mn if rec[2] is None else min(rec[2], mn)
        rec[3] = max(rec[3], int(e["max_ns"]))
        for b, c in dict(e.get("hist", {})).items():
            rec[4][int(b)] = rec[4].get(int(b), 0) + int(c)
    for rec in stats.values():
        if rec[2] is None:
            rec[2] = 0
    return stats


def doc_of(stats: Dict[Key, list], device_kind: str = "",
           nranks: int = 0, runs: int = 1) -> Dict[str, object]:
    return {
        "schema": SCHEMA,
        "device_kind": device_kind,
        "nranks": int(nranks),
        "runs": int(runs),
        "entries": entries_of(stats),
    }


def db_path(dirpath: str, device_kind: str, nranks: int) -> str:
    kind = "".join(c if (c.isalnum() or c in "-_") else "_"
                   for c in (device_kind or "unknown"))
    return os.path.join(dirpath, f"tune_perfdb_{kind}_n{nranks}.json")


def load(path: str) -> Dict[str, object]:
    """Load a PerfDB doc; NEVER raises — a missing file is an empty
    DB, a corrupt/alien one degrades to empty with ``tune_db_errors``
    bumped (init must not die on a stale cache dir)."""
    if not path or not os.path.exists(path):
        return doc_of({}, runs=0)
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("schema") != SCHEMA:
            raise ValueError(f"schema {doc.get('schema')!r}, "
                             f"want {SCHEMA!r}")
        stats_of(doc.get("entries", []))  # validate entry shapes
    except (OSError, ValueError, KeyError, TypeError) as exc:
        pvar.record("tune_db_errors")
        _out.verbose(0, "WARNING: perfdb %s unreadable (%s) — "
                        "starting from an empty database", path, exc)
        return doc_of({}, runs=0)
    pvar.record("tune_db_loads")
    return doc


def save(path: str, doc: Dict[str, object]) -> bool:
    """Atomic write (tmp + rename); False on OSError, never raises."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError as exc:
        pvar.record("tune_db_errors")
        _out.verbose(0, "WARNING: perfdb save to %s failed: %s",
                     path, exc)
        return False
    pvar.record("tune_db_saves")
    return True


def merge(docs: List[Dict[str, object]]) -> Dict[str, object]:
    """Fold PerfDB docs into one — associative and commutative in
    every component, so rank order and run order don't matter."""
    for doc in docs:
        if doc.get("schema") != SCHEMA:
            raise ValueError(
                f"not a tune perfdb doc (schema="
                f"{doc.get('schema')!r}, want {SCHEMA!r})")
    stats: Dict[Key, list] = {}
    for doc in docs:
        for key, rec in stats_of(doc.get("entries", [])).items():
            got = stats.get(key)
            if got is None:
                stats[key] = [rec[0], rec[1], rec[2], rec[3],
                              dict(rec[4])]
                continue
            got[0] += rec[0]
            got[1] += rec[1]
            got[2] = min(got[2], rec[2])
            got[3] = max(got[3], rec[3])
            for b, c in rec[4].items():
                got[4][b] = got[4].get(b, 0) + c
    device_kind = next((d["device_kind"] for d in docs
                        if d.get("device_kind")), "")
    nranks = max([int(d.get("nranks", 0)) for d in docs] + [0])
    runs = sum(int(d.get("runs", 1)) for d in docs)
    return doc_of(stats, device_kind=device_kind, nranks=nranks,
                  runs=runs)


# -- cross-rank kvstore exchange (the monitoring/merge.py shape) ----------

def _key(jobid: str, rank: int) -> str:
    return f"tune:db:{jobid}:{rank}"


def publish(client, jobid: str, rank: int,
            doc: Dict[str, object]) -> None:
    client.put(_key(jobid, rank), json.dumps(doc))


def collect(client, jobid: str, nranks: int,
            timeout: float = 10.0) -> List[Dict[str, object]]:
    """Gather every rank's published doc (blocking get per rank,
    kvstore-side wait)."""
    docs = []
    for r in range(nranks):
        raw = client.get(_key(jobid, r), wait=timeout)
        docs.append(json.loads(raw))
    return docs


def exchange(doc: Dict[str, object], client, jobid: str, rank: int,
             nranks: int,
             timeout: float = 10.0) -> Optional[Dict[str, object]]:
    """All ranks publish; rank 0 collects and merges (the telemetry
    rollup shape). Non-zero ranks return None."""
    publish(client, jobid, rank, doc)
    if rank != 0:
        return None
    return merge(collect(client, jobid, nranks, timeout))
