"""In-band collective observation — the OBSERVER guard + aggregator.

The measurement half of the coll/tuned story (reference:
ompi/mca/coll/tuned's measured dynamic-rules files): every device
collective dispatch site in coll/xla, coll/pallas and coll/hier wraps
its zero-arg launcher behind the process-wide :data:`OBSERVER` guard —
the ``FLIGHT``/``TRAFFIC`` one-branch discipline, enforced by the lint
engine's ``GUARD_GLOBALS`` — and, when the plane is up, times the
dispatch and folds the sample into an associative per-key table.

Keys are exactly what every switchpoint table already selects on —
``(op, dtype, log2-size-bucket, mesh-shape, provider, algorithm)`` —
and the provider is the backend that ACTUALLY served the call after
staged fallthrough (only the serving backend's launch funnel fires),
so the table answers "which algorithm ran, on what, how fast" without
replaying traces. Per-key stats are count/sum/min/max plus a log2
latency histogram (the serve-plane ``lat_ns`` shape): every component
merges associatively, which is what lets :mod:`ompi_tpu.tune.perfdb`
accumulate across ranks and across runs.

Sampling cost when enabled: two ``perf_counter_ns`` reads + one dict
update under the lock + two pvar bumps. Disabled: one module-attribute
load and one ``is None`` branch per dispatch site — the level-0
contract ``bench.py --tune`` bounds against the 256 KiB payload floor.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from ompi_tpu.core import cvar, events, output, pvar

_out = output.stream("tune")

_max_keys_var = cvar.register(
    "tune_max_keys", 4096, int,
    help="Cap on distinct (op, dtype, log2-size, mesh, provider, "
         "algorithm) keys the observer aggregates; samples for new "
         "keys past the cap are counted in tune_dropped instead of "
         "growing the table without bound (shape-churn jobs).",
    level=7)

#: providers the observe hooks name — the report and the OpenMetrics
#: ``tune_obs_<op>_<provider>`` decode both key off this set
PROVIDERS = ("xla", "pallas", "hier")

TUNE_TABLE_ERROR = events.register_type(
    "tune_table_error",
    "a switchpoint-table cvar points at a malformed/unreadable file",
    ("cvar", "path", "error"))

#: stats record layout: [count, sum_ns, min_ns, max_ns, {log2bin: n}]
Key = Tuple[str, str, int, Tuple[int, ...], str, str]


def log2_bucket(nbytes: int) -> int:
    """The monitoring.algo.log2_bucket size key (duplicated here so
    the hot sample path needs no cross-plane import)."""
    b = 0
    n = int(nbytes)
    while n > 1:
        n >>= 1
        b += 1
    return b


def _mesh_of(comm) -> Tuple[int, ...]:
    """The comm's device-mesh shape, from the coll/xla ctx the slot
    already built (cached on the comm); degrades to (size,)."""
    if comm is None:
        return ()
    ctx = getattr(comm, "_coll_xla_ctx", None)
    if ctx is not None:
        try:
            return tuple(int(d) for d in ctx.mesh.devices.shape)
        except Exception:  # noqa: BLE001 — observation never raises
            pass
    return (int(getattr(comm, "size", 0)),)


class Observer:
    """Per-rank sample aggregator behind the OBSERVER guard."""

    def __init__(self, rank: int = 0) -> None:
        self.rank = rank
        self.lock = threading.Lock()
        self.stats: Dict[Key, list] = {}
        self.max_keys = int(_max_keys_var.get())

    # -- the dispatch-site hook -------------------------------------------
    def timed(self, provider: str, op: str, algorithm: str, comm,
              nbytes: int, dtype: str, launcher,
              mesh: Optional[Tuple[int, ...]] = None):
        """Wrap a zero-arg launcher: time the dispatch, fold the
        sample. Mesh resolves ONCE here (wrap time), not per call."""
        mesh = _mesh_of(comm) if mesh is None else tuple(
            int(d) for d in mesh)
        lg = log2_bucket(nbytes)

        def run():
            t0 = time.perf_counter_ns()
            out = launcher()
            self.sample(op, dtype, lg, mesh, provider, algorithm,
                        time.perf_counter_ns() - t0)
            return out

        return run

    def sample(self, op: str, dtype: str, lg: int,
               mesh: Tuple[int, ...], provider: str, algorithm: str,
               dur_ns: int) -> None:
        key = (op, dtype, lg, mesh, provider, algorithm)
        dur_ns = int(dur_ns)
        with self.lock:
            rec = self.stats.get(key)
            if rec is None:
                if len(self.stats) >= self.max_keys:
                    pvar.record("tune_dropped")
                    return
                rec = self.stats[key] = [0, 0, dur_ns, dur_ns, {}]
            rec[0] += 1
            rec[1] += dur_ns
            if dur_ns < rec[2]:
                rec[2] = dur_ns
            if dur_ns > rec[3]:
                rec[3] = dur_ns
            b = dur_ns.bit_length()
            rec[4][b] = rec[4].get(b, 0) + 1
        pvar.record("tune_samples")
        # per-(op, provider) counter family for OpenMetrics
        # (dynamically named, decoded by telemetry.openmetrics)
        pvar.record("tune_obs_%s_%s" % (op, provider))

    def snapshot(self) -> Dict[Key, list]:
        """Copy of the stats table (histograms copied too)."""
        with self.lock:
            return {k: [v[0], v[1], v[2], v[3], dict(v[4])]
                    for k, v in self.stats.items()}


#: process-wide guard — None = off, every hook pays ONE branch
OBSERVER: Optional[Observer] = None


def enable(rank: int = 0) -> Observer:
    global OBSERVER
    if OBSERVER is None:
        OBSERVER = Observer(rank=rank)
    return OBSERVER


def disable() -> Optional[Observer]:
    """Drop the guard; returns the observer so Finalize can persist
    its samples after the hooks went quiet."""
    global OBSERVER
    obs, OBSERVER = OBSERVER, None
    return obs


# -- switchpoint-table error surfacing ------------------------------------
# (satellite of the same PR: a fat-fingered coll_*_switchpoints path
# used to emit one verbose(1) line and silently revert to defaults)

_warned_tables: set = set()


def table_error(var_name: str, path: str, exc: BaseException) -> None:
    """A switchpoint-table file failed to load: count it
    (``tune_table_errors``), warn once per path at verbose 0, and
    emit the ``tune_table_error`` MPI_T event for listening tools."""
    pvar.record("tune_table_errors")
    if path not in _warned_tables:
        _warned_tables.add(path)
        _out.verbose(0, "WARNING: %s %s unreadable (%s) — falling "
                        "back to built-in thresholds; fix the path "
                        "or the JSON (tune_table_errors counts every "
                        "load attempt)", var_name, path, exc)
    if events.active("tune_table_error"):
        events.emit("tune_table_error", cvar=var_name, path=path,
                    error=repr(exc))
