"""tune/ — the in-band collective performance observatory.

The measurement half of ROADMAP item 3 (the coll/tuned measured
dynamic-rules story, PAPER.md: coll/tuned): the three decision
tables (``coll_pallas_switchpoints``, ``coll_hier_switchpoints``,
``coll_xla_bucket_bytes``) were fed by a human running ``bench.py``
offline; this plane measures real collectives **in-band** instead.

Four cooperating pieces, all opt-in via ``tune_observe`` (or the
short ``OMPI_TPU_TUNE`` env knob):

- :mod:`observe` — the ``OBSERVER`` guard (one attribute load + one
  ``is None`` branch per dispatch site when off — the ``FLIGHT``/
  ``TRAFFIC`` discipline) timing every served device-collective
  launch in coll/xla, coll/pallas, and coll/hier, keyed ``(op,
  dtype, log2-size, mesh-shape, provider, algorithm)`` — the
  provider being whichever backend actually served after staged
  fallthrough.
- :mod:`perfdb` — the persistent PerfDB: associative per-key
  count/sum/min/max + log2 latency histograms, merged across ranks
  through the kvstore (the ``monitoring/merge`` publish/collect
  shape) and folded across **runs** into a per-``(device_kind,
  world size)`` JSON alongside the compile cache
  (``tune_db_dir``, default ``compile_cache_dir``).
- :mod:`report` + ``python -m ompi_tpu.tune report`` — measured
  pallas-vs-xla and hier-vs-flat crossovers, candidate switchpoint
  tables in the exact reader JSON shapes (suggestions only — the
  observatory never self-applies), and run-over-run regression
  verdicts against the stored baseline, folded into the watchdog
  hang-dump context and the OpenMetrics ``tune_*`` family.
- the satellite: malformed switchpoint-table files now surface as a
  once-per-path warning + ``tune_table_errors`` pvar +
  ``tune_table_error`` event instead of a verbose(1) whisper.

Lifecycle: ``start(rank)`` at init loads the baseline DB and raises
the guard; ``stop()`` at Finalize computes regression verdicts,
dumps the per-rank doc (``tune_dump``), exchanges through the
kvstore, and rank 0 folds the merged run into the on-disk DB.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ompi_tpu.core import cvar, output, pvar

_out = output.stream("tune")

_observe_var = cvar.register(
    "tune_observe", 0, int,
    help="Collective performance observatory: 0 off (every dispatch "
         "site pays one attribute load + one branch — the OBSERVER "
         "guard), 1 records per-launch samples keyed (op, dtype, "
         "log2-size, mesh, provider, algorithm) into the persistent "
         "PerfDB. Equivalently: OMPI_TPU_TUNE=1.", level=5)

_db_dir_var = cvar.register(
    "tune_db_dir", "", str,
    help="Directory holding the persistent PerfDB "
         "(tune_perfdb_<device_kind>_n<nranks>.json). Empty: "
         "compile_cache_dir when set, else no cross-run "
         "persistence (in-run merge + dump still work).", level=6)

_dump_var = cvar.register(
    "tune_dump", "", str,
    help="Finalize-time per-rank PerfDB doc dump path; '{rank}' "
         "expands to the world rank (e.g. /tmp/tune_r{rank}.json). "
         "Feed the files to `python -m ompi_tpu.tune report`.",
    level=6)

_regress_var = cvar.register(
    "tune_regress_threshold", 1.5, float,
    help="Run-over-run regression bar: a key whose p50 is this many "
         "times slower than the PerfDB baseline gets a named "
         "regression verdict (report, watchdog hang-dump context, "
         "tune_regressions pvar).", level=7)

#: baseline stats loaded at start() — what regressions compare against
_BASELINE: Optional[Dict] = None
_baseline_runs = 0


def requested() -> bool:
    """Cvar or the short OMPI_TPU_TUNE env knob (monitoring-style
    truthy parse)."""
    if int(_observe_var.get()) > 0:
        return True
    raw = os.environ.get("OMPI_TPU_TUNE", "").strip().lower()
    return bool(raw and raw not in ("0", "false", "no", "off"))


def device_kind() -> str:
    """The accelerator kind the DB is keyed by (cpu/TPU v4/...)."""
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001 — naming must not sink init
        return "unknown"


def db_dir() -> str:
    d = _db_dir_var.get().strip()
    if d:
        return d
    from ompi_tpu import prof as _prof

    return _prof._cache_dir_var.get().strip()


def _db_path(nranks: int) -> str:
    from ompi_tpu.tune import perfdb as _perfdb

    d = db_dir()
    if not d:
        return ""
    return _perfdb.db_path(d, device_kind(), nranks)


def start(rank: int = 0, nranks: int = 0) -> None:
    """Bring the observatory up (idempotent): load the baseline DB
    for this (device_kind, world size) and raise the OBSERVER guard
    before any traffic flows."""
    global _BASELINE, _baseline_runs
    if not requested():
        return
    from ompi_tpu.tune import observe as _observe
    from ompi_tpu.tune import perfdb as _perfdb

    if nranks <= 0:
        from ompi_tpu.runtime import rte

        nranks = rte.size
    path = _db_path(nranks)
    if path:
        doc = _perfdb.load(path)
        _BASELINE = _perfdb.stats_of(doc.get("entries", []))
        _baseline_runs = int(doc.get("runs", 0))
        if _BASELINE:
            _out.verbose(1, "perfdb baseline: %d keys over %d runs "
                            "(%s)", len(_BASELINE), _baseline_runs,
                         path)
    else:
        _BASELINE = None
        _baseline_runs = 0
    _observe.enable(rank=rank)


def stop() -> None:
    """Finalize: regression verdicts vs the baseline, per-rank doc
    dump, cross-rank kvstore merge, and (rank 0) fold the run into
    the on-disk DB. Every step is failure-proof — teardown must not
    sink Finalize."""
    global _BASELINE
    from ompi_tpu.tune import observe as _observe

    obs = _observe.disable()
    if obs is None:
        return
    from ompi_tpu.tune import perfdb as _perfdb
    from ompi_tpu.tune import report as _report

    stats = obs.snapshot()

    # 1. run-over-run regression verdicts (pvar + named lines)
    if _BASELINE:
        try:
            regs = _report.regressions(stats, _BASELINE,
                                       float(_regress_var.get()))
            for r in regs:
                pvar.record("tune_regressions")
                _out.verbose(0, "REGRESSION: %s", r["verdict"])
        except Exception as exc:  # noqa: BLE001
            _out.verbose(0, "tune regression check failed: %r", exc)

    from ompi_tpu.runtime import rte

    doc = _perfdb.doc_of(stats, device_kind=device_kind(),
                         nranks=rte.size)

    # 2. per-rank artifact dump ({rank} expansion, atomic write)
    path = _dump_var.get()
    if path:
        try:
            _perfdb.save(path.replace("{rank}", str(obs.rank)), doc)
        except Exception as exc:  # noqa: BLE001
            _out.verbose(0, "tune dump failed: %r", exc)

    # 3. cross-rank merge + cross-run fold into the on-disk DB
    merged = doc
    if rte.size > 1:
        try:
            got = _perfdb.exchange(doc, rte.client(), rte.jobid,
                                   obs.rank, rte.size)
            if got is not None:
                merged = got
            elif obs.rank != 0:
                merged = None  # non-zero ranks don't write the DB
        except Exception as exc:  # noqa: BLE001
            _out.verbose(0, "tune kvstore exchange failed "
                            "(keeping local doc): %r", exc)
    if merged is not None and obs.rank == 0:
        dbp = _db_path(rte.size)
        if dbp:
            try:
                prior = _perfdb.load(dbp)
                _perfdb.save(dbp, _perfdb.merge([prior, merged]))
            except Exception as exc:  # noqa: BLE001
                _out.verbose(0, "perfdb update failed: %r", exc)
    _BASELINE = None


def regression_info() -> Optional[List[str]]:
    """Live regression verdicts for the watchdog hang-dump context
    (None when the plane is off or nothing regressed) — a hang that
    follows a 10x collective slowdown should say so in the dump."""
    from ompi_tpu.tune import observe as _observe

    obs = _observe.OBSERVER
    if obs is None or not _BASELINE:
        return None
    try:
        from ompi_tpu.tune import report as _report

        regs = _report.regressions(obs.snapshot(), _BASELINE,
                                   float(_regress_var.get()))
    except Exception:  # noqa: BLE001 — dump context must not sink
        return None
    return [r["verdict"] for r in regs[:8]] or None
