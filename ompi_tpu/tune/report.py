"""Crossover + regression analysis over PerfDB stats.

Three consumers of the same aggregated table:

- :func:`crossovers` — measured pallas-vs-xla and hier-vs-flat
  comparisons per ``(op, dtype, mesh, log2-size)``: which provider/
  algorithm actually won, by how much (p50 ratio), only where BOTH
  arms were observed (no extrapolation).
- :func:`candidate_tables` — ready-to-ingest switchpoint suggestions
  in the exact JSON entry shapes ``coll/pallas._switchpoint`` and
  ``coll/hier._switchpoint`` parse (``{op, dtype, mesh, log2,
  algorithm}``; largest log2 <= the payload's bucket wins). These are
  SUGGESTIONS — the observatory reports, it never self-applies; a
  human (or a later explore/exploit PR) points the ``coll_*_
  switchpoints`` cvars at them.
- :func:`regressions` — current run vs the stored baseline DB, named
  verdicts ("allreduce float32 2^24 on 2x2 [hier/hier]: p50 1.8x
  slower than PerfDB baseline") for keys whose p50 degraded past
  ``tune_regress_threshold``.

Quantiles come from the log2 latency histograms (bin midpoints, the
OpenMetrics exposition's ``_bin_mid`` convention) — approximate by
design, stable under the associative merge.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

Key = Tuple[str, str, int, Tuple[int, ...], str, str]


def _bin_mid(b: int) -> float:
    """Representative value for log2 bin b (midpoint of
    [2^(b-1), 2^b); b=0 holds exact zeros)."""
    if b <= 0:
        return 0.0
    if b == 1:
        return 1.0
    return 3.0 * 2.0 ** (b - 2)


def quantile(hist: Dict[int, int], q: float) -> float:
    """Approximate q-quantile of a log2 histogram."""
    total = sum(hist.values())
    if total <= 0:
        return 0.0
    want = q * total
    cum = 0
    for b in sorted(hist):
        cum += hist[b]
        if cum >= want:
            return _bin_mid(b)
    return _bin_mid(max(hist))


def summarize(rec: list) -> Dict[str, float]:
    """count/mean/p50/p99 (+ min/max) for one stats record."""
    count = int(rec[0])
    return {
        "count": count,
        "mean_ns": rec[1] / count if count else 0.0,
        "min_ns": int(rec[2]),
        "max_ns": int(rec[3]),
        "p50_ns": quantile(rec[4], 0.50),
        "p99_ns": quantile(rec[4], 0.99),
    }


def _size_of(mesh) -> int:
    size = 1
    for d in mesh:
        size *= int(d)
    return size


def _arms(stats: Dict[Key, list]):
    """Group stats by (op, dtype, mesh-device-product, log2); each
    group holds the (provider, algorithm, mesh, summary) arms that
    served that shape. Product-of-mesh matching is what lets the flat
    1-D arm (mesh ``(n,)``) line up against the hier 2-D arm (mesh
    ``(n_dcn, n_ici)``) on the same communicator size."""
    groups: Dict[Tuple[str, str, int, int], list] = {}
    for (op, dt, lg, mesh, prov, algo), rec in stats.items():
        size = _size_of(mesh)
        groups.setdefault((op, dt, size, lg), []).append(
            (prov, algo, mesh, summarize(rec)))
    return groups


#: the two measured comparisons, keyed by the slower-arm's name shape
_PAIRS = (("pallas-vs-xla", "pallas", "xla"),
          ("hier-vs-flat", "hier", "xla"))


def crossovers(stats: Dict[Key, list]) -> List[Dict[str, object]]:
    """Per-key measured winners where both arms of a pair ran."""
    rows: List[Dict[str, object]] = []
    for (op, dt, size, lg), arms in sorted(_arms(stats).items()):
        by_prov: Dict[str, Tuple[str, Tuple[int, ...], dict]] = {}
        for prov, algo, mesh, summ in arms:
            best = by_prov.get(prov)
            if best is None or summ["p50_ns"] < best[2]["p50_ns"]:
                by_prov[prov] = (algo, mesh, summ)
        for pair, a, b in _PAIRS:
            if a not in by_prov or b not in by_prov:
                continue
            (algo_a, mesh_a, sa) = by_prov[a]
            (algo_b, mesh_b, sb) = by_prov[b]
            a_wins = sa["p50_ns"] <= sb["p50_ns"]
            win, lose = ((a, algo_a, mesh_a, sa),
                         (b, algo_b, mesh_b, sb))
            if not a_wins:
                win, lose = lose, win
            slow = max(lose[3]["p50_ns"], 1e-9)
            fast = max(win[3]["p50_ns"], 1e-9)
            rows.append({
                "pair": pair, "op": op, "dtype": dt,
                "size": size, "log2": lg,
                "winner": win[0], "winner_algorithm": win[1],
                "winner_mesh": list(win[2]),
                "winner_p50_ns": win[3]["p50_ns"],
                "loser": lose[0], "loser_algorithm": lose[1],
                "loser_p50_ns": lose[3]["p50_ns"],
                "speedup": slow / fast,
            })
    return rows


def candidate_tables(
        stats: Dict[Key, list]) -> Dict[str, List[Dict[str, object]]]:
    """Suggested switchpoint tables from the measured winners, in the
    exact entry shapes the ``_switchpoint`` readers consume."""
    pallas: List[Dict[str, object]] = []
    hier: List[Dict[str, object]] = []
    for row in crossovers(stats):
        if row["pair"] == "pallas-vs-xla":
            # the pallas reader keys on the flat device-mesh shape;
            # algorithm 'xla' means "fall through"
            mesh = (row["winner_mesh"] if row["winner"] == "pallas"
                    else [row["size"]])
            algo = (row["winner_algorithm"]
                    if row["winner"] == "pallas" else "xla")
            pallas.append({"op": row["op"], "dtype": row["dtype"],
                           "mesh": list(mesh), "log2": row["log2"],
                           "algorithm": algo})
        else:  # hier-vs-flat: reader keys on (n_dcn, n_ici)
            if row["winner"] == "hier":
                hmesh, algo = row["winner_mesh"], "hier"
            else:
                # the hier arm lost; its 2-D mesh is on the loser side
                hmesh = next(
                    (list(m) for (op, dt, lg, m, prov, _a) in stats
                     if prov == "hier" and op == row["op"]
                     and dt == row["dtype"] and lg == row["log2"]
                     and _size_of(m) == row["size"]),
                    None)
                algo = "flat"
            if hmesh is not None:
                hier.append({"op": row["op"], "dtype": row["dtype"],
                             "mesh": list(hmesh), "log2": row["log2"],
                             "algorithm": algo})
    return {"pallas": pallas, "hier": hier}


def regressions(stats: Dict[Key, list], baseline: Dict[Key, list],
                threshold: float = 1.5,
                min_count: int = 1) -> List[Dict[str, object]]:
    """Current-run keys whose p50 degraded past ``threshold`` x the
    baseline DB's p50, worst first, each with a named verdict."""
    out: List[Dict[str, object]] = []
    for key, rec in stats.items():
        base = baseline.get(key)
        if base is None or rec[0] < min_count or base[0] < min_count:
            continue
        cur = quantile(rec[4], 0.50)
        ref = quantile(base[4], 0.50)
        if ref <= 0:
            continue
        ratio = cur / ref
        if ratio < threshold:
            continue
        op, dt, lg, mesh, prov, algo = key
        out.append({
            "op": op, "dtype": dt, "log2": lg, "mesh": list(mesh),
            "provider": prov, "algorithm": algo,
            "p50_ns": cur, "baseline_p50_ns": ref, "ratio": ratio,
            "verdict": (
                "%s %s 2^%d on %s [%s/%s]: p50 %.1fx slower than "
                "PerfDB baseline (%.0f ns vs %.0f ns)" % (
                    op, dt, lg, "x".join(str(d) for d in mesh),
                    prov, algo, ratio, cur, ref)),
        })
    out.sort(key=lambda r: -r["ratio"])
    return out


def render(stats: Dict[Key, list],
           baseline: Optional[Dict[Key, list]] = None,
           threshold: float = 1.5, top: int = 20) -> str:
    """Human-readable observatory report."""
    lines = ["== tune: collective performance observatory =="]
    total = sum(rec[0] for rec in stats.values())
    lines.append("keys=%d samples=%d" % (len(stats), total))

    lines.append("")
    lines.append("-- observed (top %d keys by samples) --" % top)
    ranked = sorted(stats.items(), key=lambda kv: -kv[1][0])[:top]
    for (op, dt, lg, mesh, prov, algo), rec in ranked:
        s = summarize(rec)
        lines.append(
            "  %-18s %-9s 2^%-2d %-7s %s/%s: n=%d mean=%.0fns "
            "p50=%.0fns p99=%.0fns" % (
                op, dt, lg, "x".join(str(d) for d in mesh),
                prov, algo, s["count"], s["mean_ns"], s["p50_ns"],
                s["p99_ns"]))

    rows = crossovers(stats)
    lines.append("")
    lines.append("-- measured crossovers (%d) --" % len(rows))
    for row in rows:
        lines.append(
            "  [%s] %s %s 2^%d on %d devices: %s(%s) wins %.2fx "
            "over %s (p50 %.0fns vs %.0fns)" % (
                row["pair"], row["op"], row["dtype"], row["log2"],
                row["size"], row["winner"],
                row["winner_algorithm"], row["speedup"],
                row["loser"], row["winner_p50_ns"],
                row["loser_p50_ns"]))
    if not rows:
        lines.append("  (none — need both arms of a pair observed "
                     "on the same op/dtype/size/bucket)")

    tables = candidate_tables(stats)
    lines.append("")
    lines.append("-- candidate switchpoint tables (suggestions; "
                 "point coll_*_switchpoints at the emitted JSON) --")
    lines.append("  pallas entries: %d   hier entries: %d" % (
        len(tables["pallas"]), len(tables["hier"])))

    if baseline is not None:
        regs = regressions(stats, baseline, threshold)
        lines.append("")
        lines.append("-- regression verdicts vs PerfDB baseline "
                     "(threshold %.2fx): %d --" % (threshold,
                                                   len(regs)))
        for r in regs:
            lines.append("  REGRESSION: " + r["verdict"])
        if not regs:
            lines.append("  (none — every shared key within "
                         "%.2fx of baseline p50)" % threshold)
    return "\n".join(lines) + "\n"
